"""Per-phase prefill profiler: localize where the prefill phase's MXU
time goes on the bench geometry (llama-3b, bf16), the prefill analogue
of bench_decode_phases.py.

Round-5 verdict: prefill MFU is 0.098 and p50 TTFT flat at ~2.9s —
prefill ran as one jitted program per padded-length bucket per sequence,
mostly padding and serial dispatch.  This script times each phase of the
chunked-prefill pipeline separately on the real chip:

  packed      ONE packed program: S prompts' chunks concatenated into a
              padding-free stream with segment ids (the serving path,
              ops/packed_prefill.py).  `--impl` selects the attention
              implementation inside it — the masked XLA reference
              (S-fold attention FLOPs) or the Pallas tile-skip kernel
              (ops/pallas_packed_prefill.py) — and `--impl ab` runs
              BOTH and prints one JSON line with each variant's
              hand-counted est_mfu AND the measured-program MFU from
              the roofline plane (obs/compile_watch.xla_costs), so the
              S-fold overhead elimination is visible as a FLOP-count
              drop rather than just a wall-clock win.
  batched     the legacy padded multi-row program (every row padded to
              the packed total — what packing replaces)
  single      S serial B=1 bucket programs (the pre-round-6 path)
  attn        the packed causal-within-segment attention op alone
  kv_write    the packed K/V scatter alone
  weights     projection/MLP matmuls only (attention stubbed) — the
              MXU-streaming bound for the packed stream

and prints tokens/s plus achieved model FLOPs utilisation (MFU) per
phase against the v5e bf16 pin.

Run on the chip:  python benchmarks/bench_prefill_phases.py
CPU smoke:        python benchmarks/bench_prefill_phases.py --model tiny \
                      --tokens 64 --seqs 2 --ctx-blocks 4
"""

import argparse
import dataclasses
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dynamo_tpu.models import llama            # noqa: E402
from dynamo_tpu.obs.compile_watch import xla_costs  # noqa: E402
from dynamo_tpu.ops import packed_prefill as pp  # noqa: E402

PEAK_TFLOPS = 197.0  # v5e dense bf16


def _sync(r):
    """Close timing with a device FETCH (see bench_decode_phases)."""
    leaf = jax.tree_util.tree_leaves(r)[0]
    np.asarray(jax.device_get(leaf.ravel()[0]))


def timeit(fn, n=4, warm=1):
    for _ in range(warm):
        r = fn()
    _sync(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    _sync(r)
    return (time.perf_counter() - t0) / n


def main():
    p = argparse.ArgumentParser(
        description="per-phase prefill profiler (see module docstring)")
    p.add_argument("phases", nargs="*",
                   help="phase tags: packed batched single attn kv_write "
                        "weights (default: all)")
    p.add_argument("--model", default="llama-3b")
    p.add_argument("--tokens", type=int, default=2048,
                   help="packed chunk budget (total stream tokens)")
    p.add_argument("--seqs", type=int, default=4,
                   help="co-scheduled prompts packed per dispatch")
    p.add_argument("--ctx-blocks", type=int, default=16,
                   help="block-table width per sequence")
    p.add_argument("--block", type=int, default=128)
    p.add_argument("--impl", default="xla",
                   choices=["xla", "pallas", "pallas_interpret", "ab"],
                   help="packed-attention impl for the `packed` phase; "
                        "`ab` runs the XLA reference AND the Pallas "
                        "tile-skip kernel (interpret mode off-TPU) and "
                        "prints both variants' MFU in one JSON line")
    args = p.parse_args()
    if args.seqs > args.tokens:
        p.error(f"--seqs ({args.seqs}) must be <= --tokens "
                f"({args.tokens})")
    if args.tokens % args.seqs:
        rounded = args.tokens - args.tokens % args.seqs
        print(f"note: rounding --tokens {args.tokens} -> {rounded} "
              f"(whole {rounded // args.seqs}-token chunks per sequence)")
        args.tokens = rounded
    cap = args.ctx_blocks * args.block
    if args.tokens // args.seqs > cap:
        # JAX clamps out-of-bounds table indices, so overflowing the
        # per-sequence KV capacity would silently time the wrong
        # computation instead of erroring
        p.error(f"per-sequence chunk ({args.tokens // args.seqs} tokens) "
                f"exceeds KV capacity --ctx-blocks*--block = {cap}")
    sel = set(args.phases)

    def want(tag):
        return not sel or tag in sel

    cfg = llama.PRESETS[args.model]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    # exclude the embedding lookup and an untied lm_head (logits run on
    # last-token rows only) — the engine's _flops_per_token convention,
    # so bench MFU and the FPM-stream MFU are comparable
    skip = sum(params[k].size for k in ("embedding", "lm_head")
               if k in params)
    flops_per_tok = 2 * (n_params - skip)

    S, T, BLOCK, MB = args.seqs, args.tokens, args.block, args.ctx_blocks
    chunk = T // S
    num_blocks = 1 + S * MB
    kv = tuple(
        jnp.zeros((cfg.n_layers, cfg.n_kv_heads, num_blocks,
                   cfg.head_dim, BLOCK), cfg.dtype)
        for _ in range(2)
    )
    rng = np.random.default_rng(0)
    toks = rng.integers(3, cfg.vocab_size, T).astype(np.int32)
    seg_ids = np.repeat(np.arange(S, dtype=np.int32), chunk)
    positions = np.tile(np.arange(chunk, dtype=np.int32), S)
    valid = np.ones(T, bool)
    tables = np.zeros((S, MB), np.int32)
    for s in range(S):
        tables[s] = 1 + s * MB + np.arange(MB)
    last_idx = (np.arange(S, dtype=np.int32) + 1) * chunk - 1

    gf = flops_per_tok * T / 1e9
    print(f"{args.model}: {S} x {chunk}-token prompts packed to T={T}; "
          f"~{gf:.1f} GF matmul per dispatch")
    dev = {k: jnp.asarray(v) for k, v in dict(
        toks=toks, seg_ids=seg_ids, positions=positions, valid=valid,
        tables=tables, last_idx=last_idx).items()}

    def report(name, t, tokens, flops):
        mfu = flops / t / (PEAK_TFLOPS * 1e12)
        print(f"  {name:10s} {t*1e3:8.2f} ms   {tokens/t/1e3:8.1f} ktok/s"
              f"   MFU {mfu:5.3f}")

    state = {"kv": kv}

    # --- packed: the serving path --------------------------------------
    if want("packed"):
        if args.impl == "ab":
            on_tpu = any(d.platform == "tpu" for d in jax.devices())
            impls = ["xla", "pallas" if on_tpu else "pallas_interpret"]
        else:
            impls = [args.impl]
        # analytic attention FLOPs per layer: score + pv matmuls over
        # each token's segment context window (mb blocks wide).  The
        # XLA reference runs one masked pass PER SEGMENT over the WHOLE
        # stream — S-fold; the Pallas kernel's tile-skip visits only a
        # token's own segment — 1x (upper bound: tile-granular causal
        # frontier skips more).
        attn_base = 4.0 * cfg.n_layers * cfg.n_heads * cfg.head_dim \
            * T * MB * BLOCK
        variants = {}
        for impl in impls:
            cfg_i = dataclasses.replace(cfg, packed_attn_impl=impl)

            @jax.jit
            def packed(params, kv, toks, positions, seg_ids, tables,
                       last_idx, valid, cfg_i=cfg_i):
                lg, kv = llama.prefill_packed(
                    params, cfg_i, kv, toks, positions, seg_ids, tables,
                    last_idx, valid)
                return lg, kv

            def run_packed(packed=packed):
                lg, state["kv"] = packed(
                    params, state["kv"], dev["toks"], dev["positions"],
                    dev["seg_ids"], dev["tables"], dev["last_idx"],
                    dev["valid"])
                return lg

            t = timeit(run_packed)
            est_flops = flops_per_tok * T
            est_mfu = est_flops / t / (PEAK_TFLOPS * 1e12)
            # measured-program FLOPs from the roofline plane: XLA's own
            # HLO cost analysis of the compiled program (for the Pallas
            # variant the kernel's CostEstimate feeds this) — the
            # number the S-fold elimination shows up in
            costs = xla_costs(packed, (
                params, state["kv"], dev["toks"], dev["positions"],
                dev["seg_ids"], dev["tables"], dev["last_idx"],
                dev["valid"]))
            row = {
                "ms": round(t * 1e3, 3),
                "tok_per_s": round(T / t, 1),
                "est_flops": est_flops,
                "est_mfu": round(est_mfu, 4),
                "attn_flops_analytic": attn_base
                * (S if impl == "xla" else 1),
            }
            if costs is not None:
                row["xla_flops"] = costs["flops"]
                row["xla_bytes"] = costs["bytes"]
                row["xla_mfu"] = round(
                    costs["flops"] / t / (PEAK_TFLOPS * 1e12), 4)
            variants[impl] = row
            report(f"packed/{impl}", t, T, flops_per_tok * T)
        print(json.dumps({
            "bench": "prefill_phases",
            "mode": ("tpu" if any(d.platform == "tpu"
                                  for d in jax.devices()) else "smoke"),
            "model": args.model, "seqs": S,
            "tokens": T, "ctx_blocks": MB, "block": BLOCK,
            "peak_tflops": PEAK_TFLOPS, "target_mfu": 0.4,
            "impls": variants,
        }))

    # --- batched: every row padded to the packed total -----------------
    if want("batched"):
        btoks = np.zeros((S, T), np.int32)
        bpos = np.zeros((S, T), np.int32)
        for s in range(S):
            btoks[s, :chunk] = toks[s * chunk:(s + 1) * chunk]
            bpos[s] = np.arange(T)
        true_lens = np.full(S, chunk, np.int32)

        @jax.jit
        def batched(params, kv, toks, pos, tables, ctx, tl):
            return llama.prefill_batched(params, cfg, kv, toks, pos,
                                         tables, ctx, tl)

        dd = (jnp.asarray(btoks), jnp.asarray(bpos), dev["tables"],
              jnp.zeros(S, jnp.int32), jnp.asarray(true_lens))

        def run_batched():
            lg, state["kv"] = batched(params, state["kv"], *dd)
            return lg
        # padded program computes S*T token rows for T real tokens
        report("batched", timeit(run_batched), T, flops_per_tok * T)

    # --- single: serial B=1 dispatches ---------------------------------
    if want("single"):
        @jax.jit
        def single(params, kv, toks, pos, table):
            return llama.prefill(params, cfg, kv, toks, pos, table,
                                 jnp.int32(0), jnp.int32(chunk))

        sd = [(jnp.asarray(toks[s * chunk:(s + 1) * chunk]),
               jnp.asarray(np.arange(chunk, dtype=np.int32)),
               jnp.asarray(tables[s])) for s in range(S)]

        def run_single():
            lg = None
            for s in range(S):
                lg, state["kv"] = single(params, state["kv"], *sd[s])
            return lg
        report("single", timeit(run_single), T, flops_per_tok * T)

    # --- packed attention op alone -------------------------------------
    if want("attn"):
        q0 = jnp.asarray(
            rng.standard_normal((T, cfg.n_heads, cfg.head_dim)), cfg.dtype)

        @jax.jit
        def attn(q, kc, vc, tables, seg_ids, positions, valid):
            for li in range(cfg.n_layers):
                o = pp.packed_prefill_attention(
                    q, kc, vc, li, tables, seg_ids, positions, valid)
                q = (o.astype(jnp.float32) * 0.999).astype(q.dtype)
            return q
        # attention flops: per token ~ 2 matmuls over its own context
        afl = 4 * cfg.n_layers * cfg.n_heads * cfg.head_dim \
            * float(np.sum(positions + 1))
        report("attn", timeit(lambda: attn(
            q0, state["kv"][0], state["kv"][1], dev["tables"],
            dev["seg_ids"], dev["positions"], dev["valid"])), T, afl)

    # --- packed kv scatter alone ---------------------------------------
    if want("kv_write"):
        kvec = jnp.asarray(
            rng.standard_normal((T, cfg.n_kv_heads, cfg.head_dim)),
            cfg.dtype)

        @jax.jit
        def wr(kv, kvec, tables, seg_ids, positions, valid):
            kc, vc = kv
            for li in range(cfg.n_layers):
                kc, vc = pp.write_packed_kv(kc, vc, li, kvec, kvec,
                                            tables, seg_ids, positions,
                                            valid)
            return kc, vc

        def run_wr():
            state["kv"] = wr(state["kv"], kvec, dev["tables"],
                             dev["seg_ids"], dev["positions"],
                             dev["valid"])
            return state["kv"][0]
        wfl = 2 * cfg.n_layers * T * cfg.n_kv_heads * cfg.head_dim * 2
        report("kv_write", timeit(run_wr), T, wfl)

    # --- weights only (attention stubbed) ------------------------------
    if want("weights"):
        @jax.jit
        def wonly(params, toks, positions):
            x = params["embedding"][toks].astype(cfg.dtype)
            for layer in params["layers"]:
                h = llama.rms_norm(x, layer["attn_norm"]["norm"],
                                   cfg.rms_eps)
                q, k, v = llama._qkv(layer, cfg, h, positions)
                a = q + k.repeat(cfg.n_heads // cfg.n_kv_heads, 1)
                x = x + llama._attn_out(layer, a.reshape(T, cfg.q_dim))
                h = llama.rms_norm(x, layer["mlp_norm"]["norm"],
                                   cfg.rms_eps)
                x = x + llama._mlp(layer, h)
            return llama._logits(params, cfg, x[-1])
        report("weights",
               timeit(lambda: wonly(params, dev["toks"],
                                    dev["positions"])),
               T, flops_per_tok * T)


if __name__ == "__main__":
    main()
