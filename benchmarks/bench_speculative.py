"""Speculative decoding: accepted tokens/s vs plain decode.

Serves identical greedy workloads through two JaxEngines — spec_decode
off and on — and reports tokens/s, the acceptance rate, and the speedup,
across acceptance regimes:

  repeat   repetition-friendly prompts (greedy streams cycle; n-gram
           drafts from the sequence's own tail get accepted).  The
           acceptance target is >= 1.3x accepted tokens/s over plain
           decode here.
  random   adversarial prompts with non-repeating continuations: the
           per-sequence acceptance EMA must collapse draft length to 0
           (plain pipelined decode) and hold the regression under 2%.

Greedy speculative output is token-identical to plain decode by
construction (engine/sampler.py spec_accept_tokens), and this bench
asserts it on every run — a speedup that changes tokens is a bug, not
a result.

CPU smoke:  python benchmarks/bench_speculative.py --model tiny --tokens 96
On a chip:  python benchmarks/bench_speculative.py --model llama-3b
"""

import argparse
import asyncio
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dynamo_tpu.engine import EngineConfig, JaxEngine  # noqa: E402
from dynamo_tpu.protocols import (  # noqa: E402
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)


def build_engine(args, spec: bool) -> JaxEngine:
    cfg = EngineConfig(
        model=args.model,
        block_size=args.block_size,
        num_blocks=args.num_blocks,
        max_blocks_per_seq=args.max_blocks_per_seq,
        max_num_seqs=args.seqs,
        decode_fused_steps=args.fused,
        spec_decode=args.proposer if spec else "off",
        spec_k=args.k,
        # --draft-model defaults to self-drafting (same preset): an
        # upper-bound acceptance measurement, not a deployment config
        spec_draft_model=(args.draft_model or args.model)
        if spec and args.proposer == "draft" else "",
        seed=3,
    )
    return JaxEngine(cfg)


def make_prompts(args, regime: str):
    rng = np.random.default_rng(17)
    prompts = []
    for i in range(args.seqs):
        if regime == "repeat":
            phrase = list(map(int, rng.integers(5, 99, 4 + i)))
            reps = -(-args.prompt_len // len(phrase))
            prompts.append((phrase * reps)[: args.prompt_len])
        else:
            prompts.append(
                list(map(int, rng.integers(1, 30000, args.prompt_len))))
    return prompts


async def serve(eng: JaxEngine, prompts, max_tokens: int):
    async def one(i, p):
        req = PreprocessedRequest(
            token_ids=p, request_id=f"r{i}",
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        )
        toks = []
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
        return toks

    t0 = time.perf_counter()
    outs = await asyncio.gather(*[one(i, p) for i, p in enumerate(prompts)])
    dt = time.perf_counter() - t0
    return outs, sum(len(t) for t in outs) / dt


async def run_regime(args, regime: str):
    prompts = make_prompts(args, regime)
    base = build_engine(args, spec=False)
    base_out, base_tps = await serve(base, prompts, args.tokens)
    await base.close()

    spec = build_engine(args, spec=True)
    spec_out, spec_tps = await serve(spec, prompts, args.tokens)
    m = spec.metrics
    proposed = m.get("spec_proposed", 0)
    accepted = m.get("spec_accepted", 0)
    await spec.close()

    assert spec_out == base_out, (
        f"{regime}: speculative greedy output diverged from baseline")
    acc = accepted / proposed if proposed else 0.0
    speedup = spec_tps / base_tps if base_tps else 0.0
    print(f"{regime:8s} plain {base_tps:9.1f} tok/s | spec "
          f"{spec_tps:9.1f} tok/s | speedup {speedup:5.2f}x | "
          f"acceptance {acc:5.2f} ({accepted}/{proposed}) | "
          f"verify dispatches {m.get('spec_steps', 0)}")
    return {"regime": regime, "plain_tps": base_tps, "spec_tps": spec_tps,
            "speedup": speedup, "acceptance": acc}


async def amain(args):
    print(f"model={args.model} proposer={args.proposer} k={args.k} "
          f"seqs={args.seqs} prompt={args.prompt_len} "
          f"tokens={args.tokens} fused={args.fused}")
    results = [await run_regime(args, r) for r in args.regimes]
    rep = next((r for r in results if r["regime"] == "repeat"), None)
    rnd = next((r for r in results if r["regime"] == "random"), None)
    if rep is not None:
        print(f"repeat-regime speedup {rep['speedup']:.2f}x "
              f"(target >= 1.30x)")
    if rnd is not None:
        reg = 1.0 - rnd["speedup"]
        print(f"random-regime regression {reg * 100:+.1f}% "
              f"(target < 2%: adaptive k collapses to plain decode)")


def main():
    ap = argparse.ArgumentParser(
        description="speculative decoding: accepted tokens/s vs plain "
                    "decode across acceptance regimes")
    ap.add_argument("--model", default="tiny",
                    help="model preset (tiny for CPU smoke, llama-3b on "
                         "a chip)")
    ap.add_argument("--proposer", default="ngram",
                    choices=["ngram", "draft"])
    ap.add_argument("--draft-model", default="",
                    help="draft preset for --proposer draft (default: "
                         "the target preset, i.e. self-drafting)")
    ap.add_argument("--k", type=int, default=4, help="max draft tokens")
    ap.add_argument("--seqs", type=int, default=4,
                    help="concurrent sequences")
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--tokens", type=int, default=256,
                    help="decode tokens per sequence")
    ap.add_argument("--fused", type=int, default=8,
                    help="decode_fused_steps for the plain-decode burst")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=2048)
    ap.add_argument("--max-blocks-per-seq", type=int, default=64)
    ap.add_argument("--regimes", nargs="+", default=["repeat", "random"],
                    choices=["repeat", "random"])
    args = ap.parse_args()
    asyncio.run(amain(args))


if __name__ == "__main__":
    main()
