"""KV-cache quantization bench: bf16 vs int8 end to end.

Three measurements, each against the acceptance bar of the int8 KV
subsystem (quant/kv.py):

  capacity  bytes/block and bytes/token at bf16 vs int8 for the chosen
            model geometry, and the block count a fixed HBM budget
            (--hbm-gb) holds at each — asserts the int8 pool is >= 1.8x
            the bf16 pool (the per-position fp32 scales cost
            4/head_dim of the win; 1.94x at head_dim 128).
  parity    greedy decode through two real engines (same weights, same
            prompts) with kv_cache_dtype bf16 vs int8 — asserts the
            matching-token fraction >= --parity-min (measured 1.0 on
            the CPU test geometry: per-token scales bound the error at
            absmax/254 per element, far under the argmax margins).
  decode    fused decode_multi tok/s at each (dtype, attention impl) on
            the bench geometry — rows for the XLA gather path AND the
            Pallas kernel (ops/pallas_paged_attention.py), whose int8
            row exercises the in-kernel dequant: int8 blocks + fp32
            scale rows DMA'd to VMEM, scale multiply fused into the
            chunk consume.  On HBM-bound hardware the int8 read's
            halved KV traffic is the headline and the bench ASSERTS
            int8-Pallas decode tok/s >= bf16-Pallas (the compounding
            the kernel unification exists for; target MFU >= 0.4 for
            the next TPU bench round).  Off-TPU the kernel runs in
            interpret mode as a smoke — numbers are not meaningful and
            the assert is skipped.

CPU-runnable by default (tiny geometry); pass --model llama-3b
--ctx 2048 --block 128 on a chip for the roofline-relevant numbers.
"""

import argparse
import asyncio
import dataclasses
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models import llama
from dynamo_tpu.quant.kv import kv_cache_bytes_per_block


def capacity_report(cfg, block_size: int, hbm_gb: float,
                    min_ratio: float) -> float:
    budget = int(hbm_gb * 1e9)
    rows = {}
    for dt in ("bf16", "int8"):
        per_block = kv_cache_bytes_per_block(llama, cfg, block_size, dt)
        rows[dt] = (per_block, per_block / block_size, budget // per_block)
    ratio = rows["int8"][2] / max(1, rows["bf16"][2])
    print(f"capacity @ {cfg.name} block_size={block_size} "
          f"budget={hbm_gb:g} GB")
    for dt, (pb, pt, nb) in rows.items():
        print(f"  {dt:5s} {pb:>10d} B/block  {pt:>8.1f} B/token  "
              f"{nb:>8d} blocks")
    print(f"  int8/bf16 blocks ratio: {ratio:.2f}x")
    assert ratio >= min_ratio, (
        f"int8 capacity ratio {ratio:.2f} < required {min_ratio}")
    assert rows["int8"][1] < rows["bf16"][1], "int8 must cut bytes/token"
    return ratio


async def _greedy(engine_cfg, prompts, n_out):
    from dynamo_tpu.engine import JaxEngine
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    eng = JaxEngine(engine_cfg)
    outs = []
    for i, prompt in enumerate(prompts):
        toks = []
        async for out in eng.generate(PreprocessedRequest(
                token_ids=prompt, request_id=f"q{i}",
                sampling=SamplingOptions(temperature=0.0, seed=0),
                stop=StopConditions(max_tokens=n_out, ignore_eos=True))):
            toks.extend(out.token_ids)
        outs.append(toks)
    await eng.close()
    return outs


def parity_report(args) -> float:
    from dynamo_tpu.engine import EngineConfig

    cfg = llama.LlamaConfig(
        name="quant-parity", vocab_size=512, d_model=64, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
        dtype=jnp.float32)
    rng = np.random.default_rng(0)
    prompts = [list(map(int, rng.integers(3, 500, 24)))
               for _ in range(args.parity_seqs)]

    def ecfg(dt):
        return EngineConfig(
            model_config=cfg, block_size=8, num_blocks=128,
            max_blocks_per_seq=16, max_num_seqs=4,
            prefill_buckets=(8, 16, 32), seed=3, kv_cache_dtype=dt)

    ref = asyncio.run(_greedy(ecfg("bf16"), prompts, args.parity_tokens))
    q = asyncio.run(_greedy(ecfg("int8"), prompts, args.parity_tokens))
    total = sum(len(t) for t in ref)
    match = sum(a == b for r, s in zip(ref, q) for a, b in zip(r, s))
    frac = match / max(1, total)
    print(f"greedy parity: {match}/{total} tokens match "
          f"({frac * 100:.1f}%)")
    assert frac >= args.parity_min, (
        f"greedy parity {frac:.3f} < required {args.parity_min}")
    return frac


def decode_report(args) -> dict:
    cfg = llama.PRESETS[args.model]
    B, ctx, bs, K = args.batch, args.ctx, args.block, args.steps
    max_blocks = ctx // bs + 2
    num_blocks = B * max_blocks + 1
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tables = np.zeros((B, max_blocks), np.int32)
    for b in range(B):
        tables[b] = 1 + b * max_blocks + np.arange(max_blocks)
    tables = jnp.asarray(tables)
    lens = jnp.full((B,), ctx, jnp.int32)
    tok0 = jnp.asarray(
        np.random.default_rng(0).integers(3, cfg.vocab_size, B, np.int32))

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    pallas_impl = "pallas" if on_tpu else "pallas_interpret"
    rows = [("bf16", "auto"), ("int8", "auto"),
            ("bf16", pallas_impl), ("int8", pallas_impl)]
    tok_s = {}
    for dt, impl in rows:
        quant = dt == "int8"
        cfg_i = dataclasses.replace(cfg, attn_impl=impl)
        kv = [jnp.zeros((cfg.n_layers, cfg.n_kv_heads, num_blocks,
                         cfg.head_dim, bs),
                        jnp.int8 if quant else cfg.dtype)
              for _ in range(2)]
        if quant:
            kv += [jnp.zeros((cfg.n_layers, cfg.n_kv_heads, num_blocks,
                              bs), jnp.float32) for _ in range(2)]
        kv = tuple(kv)

        def burst(params, kv, tokens, positions, tables, ctx_lens,
                  cfg_i=cfg_i):
            toks, kv = llama.decode_multi(
                params, cfg_i, kv, tokens, positions, tables, ctx_lens, K)
            return toks[-1], kv

        step = jax.jit(burst, donate_argnums=(1,))
        state = {"kv": kv, "tok": tok0}

        def run(step=step):
            state["tok"], state["kv"] = step(
                params, state["kv"], state["tok"], lens, tables, lens)
            return state["tok"]

        for _ in range(args.warmup):
            r = run()
        np.asarray(jax.device_get(r.ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(args.iters):
            r = run()
        np.asarray(jax.device_get(r.ravel()[0]))
        dt_s = (time.perf_counter() - t0) / args.iters / K
        per_head = (cfg.head_dim + 4) if quant else 2 * cfg.head_dim
        kv_bytes = 2 * cfg.n_layers * ctx * cfg.n_kv_heads * per_head * B
        tok_s[(dt, impl)] = B / dt_s
        print(f"  {dt:5s} {impl:17s} {dt_s * 1e3:8.2f} ms/step  "
              f"{B / dt_s:8.1f} tok/s  "
              f"kv read {kv_bytes / 1e9:6.3f} GB/step")
    if on_tpu:
        # the compounding bar: in-kernel dequant must let int8's halved
        # HBM traffic SHOW UP through the fast path.  TPU-gated — the
        # interpret-mode rows are a CPU smoke, not a measurement.
        assert tok_s[("int8", pallas_impl)] >= tok_s[("bf16",
                                                      pallas_impl)], (
            f"int8-Pallas decode "
            f"({tok_s[('int8', pallas_impl)]:.1f} tok/s) slower than "
            f"bf16-Pallas ({tok_s[('bf16', pallas_impl)]:.1f} tok/s)")
        print("  int8-Pallas >= bf16-Pallas: OK")
    else:
        print("  (interpret-mode Pallas rows are a CPU smoke; the "
              "int8>=bf16 assert is TPU-gated)")
    return {"on_tpu": on_tpu, "pallas_impl": pallas_impl,
            "rows": [{"kv_dtype": dt, "attn_impl": impl,
                      "tok_s": round(v, 1)}
                     for (dt, impl), v in tok_s.items()]}


def main() -> None:
    p = argparse.ArgumentParser(
        description="bf16 vs int8 KV-cache quantization bench "
                    "(see module docstring)")
    p.add_argument("--model", default="tiny", choices=sorted(llama.PRESETS),
                   help="preset for the capacity + decode phases")
    p.add_argument("--hbm-gb", type=float, default=16.0,
                   help="HBM budget for the blocks-per-budget report")
    p.add_argument("--min-ratio", type=float, default=1.8,
                   help="required int8/bf16 block-capacity ratio")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--ctx", type=int, default=256)
    p.add_argument("--block", type=int, default=16)
    p.add_argument("--steps", type=int, default=16,
                   help="fused decode steps per dispatch")
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--parity-seqs", type=int, default=2)
    p.add_argument("--parity-tokens", type=int, default=16)
    p.add_argument("--parity-min", type=float, default=0.9,
                   help="required matching-token fraction bf16 vs int8")
    p.add_argument("--skip-decode", action="store_true",
                   help="capacity + parity only (fast CPU smoke)")
    args = p.parse_args()

    ratio = capacity_report(llama.PRESETS[args.model], args.block,
                            args.hbm_gb, args.min_ratio)
    # the headline config too: the 2x-blocks claim is about serving
    # geometry (head_dim 128, block 128), not the CPU test shapes
    if args.model != "llama-3b":
        capacity_report(llama.PRESETS["llama-3b"], 128, args.hbm_gb,
                        args.min_ratio)
    frac = parity_report(args)
    decode = None
    if not args.skip_decode:
        print(f"decode tok/s @ {args.model} B={args.batch} "
              f"ctx={args.ctx} K={args.steps}  "
              f"(next TPU round targets: int8-Pallas >= bf16-Pallas "
              f"tok/s here, prefill MFU >= 0.4 in "
              f"bench_prefill_phases --impl ab)")
        decode = decode_report(args)
    # one BENCH-style JSON line (the run_round.py contract): the
    # (dtype x impl) decode rows plus the pass/fail state of every
    # assert that already fired above; mode labels interpret-mode rows
    # as a smoke so a scoreboard never mistakes them for chip numbers
    on_tpu = bool(decode and decode["on_tpu"])
    print(json.dumps({
        "bench": "kv_quant", "mode": "tpu" if on_tpu else "smoke",
        "model": args.model, "block_size": args.block,
        "capacity": {"int8_bf16_blocks_ratio": round(ratio, 3),
                     "min_ratio": args.min_ratio},
        "parity": {"match_frac": round(frac, 4),
                   "min": args.parity_min},
        **({"decode": decode} if decode else {}),
    }))


if __name__ == "__main__":
    main()
