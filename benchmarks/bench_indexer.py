"""KV indexer microbenchmark: python vs native, with parity assert.

Reference claim to compare against: >10M events+requests/s, p99 <10µs
(lib/kv-router/src/indexer/README.md:5, on its CPU).  Benches every
built indexer implementation (PyKvIndexer always; NativeKvIndexer when
`make -C native` has produced the shared library), asserts the two
agree on a randomized store/remove/query trace first — a fast wrong
indexer routes every request to the wrong worker — and emits one
r06-convention gated JSON summary line:

    {"bench": "indexer", "round": "r06", "mode": ..., "gates": [...],
     "result": {"impls": {...}, "parity": ...}}

The events/s + p99 gate is enforced in tpu mode (the round's quoted
numbers come from the serving host's CPU) and reported skipped_smoke
elsewhere, matching benchmarks/run_round.py which wires this in.
"""

import argparse
import json
import random
import statistics
import time

from dynamo_tpu.router.indexer import PyKvIndexer, make_indexer

TARGET_EVENTS_PER_S = 10e6
TARGET_P99_US = 10.0


def parity_check(n_ops: int = 2000, seed: int = 11) -> dict:
    """Randomized Py-vs-native equivalence on one interleaved trace of
    stores, removals, worker drops and queries.  Returns the rollup;
    raises AssertionError on the first divergence."""
    try:
        from dynamo_tpu.router.native_indexer import NativeKvIndexer
    except (ImportError, OSError):
        return {"checked": False, "reason": "native indexer not built"}
    rng = random.Random(seed)
    py, cc = PyKvIndexer(), NativeKvIndexer()
    universe = [(i << 70) | (i * 2654435761 + 17) for i in range(4096)]
    queries = 0
    for _ in range(n_ops):
        op = rng.random()
        w = rng.randrange(8)
        start = rng.randrange(len(universe) - 64)
        chunk = universe[start:start + rng.randrange(1, 64)]
        if op < 0.55:
            py.apply_stored(w, chunk)
            cc.apply_stored(w, chunk)
        elif op < 0.75:
            py.apply_removed(w, chunk)
            cc.apply_removed(w, chunk)
        elif op < 0.80:
            py.remove_worker(w)
            cc.remove_worker(w)
        else:
            qp, qc = py.find_matches(chunk), cc.find_matches(chunk)
            assert qp == qc, (
                f"indexer parity divergence on query {chunk[:4]}...: "
                f"py={qp} native={qc}")
            queries += 1
    assert py.num_blocks == cc.num_blocks, (
        f"block-count divergence: py={py.num_blocks} "
        f"native={cc.num_blocks}")
    return {"checked": True, "ops": n_ops, "queries": queries}


def bench(ix, n_workers=16, n_events=20000, blocks_per_event=16,
          n_queries=20000, query_len=64):
    rng = random.Random(7)
    universe = [(i << 70) | (i * 2654435761 + 17) for i in range(50000)]

    batches = []
    for _ in range(n_events):
        start = rng.randrange(0, len(universe) - blocks_per_event)
        batches.append((rng.randrange(n_workers),
                        universe[start:start + blocks_per_event]))
    t0 = time.perf_counter()
    for w, chunk in batches:
        ix.apply_stored(w, chunk)
    ev_dt = time.perf_counter() - t0
    events_per_s = n_events * blocks_per_event / ev_dt

    queries = []
    for _ in range(n_queries):
        start = rng.randrange(0, len(universe) - query_len)
        queries.append(universe[start:start + query_len])
    lat = []
    t0 = time.perf_counter()
    for q in queries:
        t1 = time.perf_counter()
        ix.find_matches(q)
        lat.append(time.perf_counter() - t1)
    q_dt = time.perf_counter() - t0
    queries_per_s = n_queries / q_dt
    p50 = statistics.median(lat) * 1e6
    p99 = statistics.quantiles(lat, n=100)[98] * 1e6
    return {"events_per_s": round(events_per_s, 1),
            "queries_per_s": round(queries_per_s, 1),
            "p50_us": round(p50, 2), "p99_us": round(p99, 2)}


def main() -> int:
    p = argparse.ArgumentParser(
        description="KV indexer microbenchmark (python vs native, with "
                    "parity assert; see module docstring)")
    p.add_argument("--mode", default="smoke", choices=["smoke", "tpu"],
                   help="tpu enforces the reference's 10M events/s @ "
                        "p99 <10µs claim; smoke reports skipped_smoke")
    p.add_argument("--events", type=int, default=20000)
    p.add_argument("--queries", type=int, default=20000)
    p.add_argument("--parity-ops", type=int, default=2000)
    args = p.parse_args()
    enforced = args.mode == "tpu"

    parity = parity_check(args.parity_ops)
    impls = {"py": make_indexer("py")}
    try:
        impls["native"] = make_indexer("native")
    except (ImportError, OSError):
        pass
    results = {name: bench(ix, n_events=args.events,
                           n_queries=args.queries)
               for name, ix in impls.items()}
    # the claim row is scored on the promoted default (native when
    # built), because that is what serves production routing
    head = results.get("native") or results["py"]
    ev, p99 = head["events_per_s"], head["p99_us"]
    gates = [
        {"name": "indexer_events_per_s",
         "target": f">= {TARGET_EVENTS_PER_S:.0f}", "value": ev,
         "status": ("pass" if ev >= TARGET_EVENTS_PER_S else "fail")
         if enforced else "skipped_smoke"},
        {"name": "indexer_query_p99_us",
         "target": f"< {TARGET_P99_US}", "value": p99,
         "status": ("pass" if p99 < TARGET_P99_US else "fail")
         if enforced else "skipped_smoke"},
        {"name": "indexer_parity",
         "target": "py == native",
         "value": parity.get("checked"),
         # parity is enforced in EVERY mode a native lib exists: it is
         # a correctness bar, not a perf bar
         "status": "pass" if parity.get("checked") else "skipped_smoke"},
    ]
    print(json.dumps({
        "bench": "indexer", "round": "r06", "mode": args.mode,
        "gates": gates,
        "result": {"impls": results, "parity": parity,
                   "default_impl": ("native" if "native" in impls
                                    else "py")},
    }), flush=True)
    return 1 if any(g["status"] == "fail" for g in gates) else 0


if __name__ == "__main__":
    raise SystemExit(main())
