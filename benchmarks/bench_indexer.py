"""KV indexer microbenchmark.

Reference claim to compare against: >10M events+requests/s, p99 <10µs
(lib/kv-router/src/indexer/README.md:5, on its CPU).  Prints events/s,
matches/s and p99 latency for the Python and C++ indexers.
"""

import random
import statistics
import sys
import time

sys.path.insert(0, ".")

from dynamo_tpu.router.indexer import PyKvIndexer  # noqa: E402


def bench(ix, n_workers=16, n_events=20000, blocks_per_event=16,
          n_queries=20000, query_len=64):
    rng = random.Random(7)
    universe = [(i << 70) | (i * 2654435761 + 17) for i in range(50000)]

    batches = []
    for _ in range(n_events):
        start = rng.randrange(0, len(universe) - blocks_per_event)
        batches.append((rng.randrange(n_workers),
                        universe[start:start + blocks_per_event]))
    t0 = time.perf_counter()
    for w, chunk in batches:
        ix.apply_stored(w, chunk)
    ev_dt = time.perf_counter() - t0
    events_per_s = n_events * blocks_per_event / ev_dt

    queries = []
    for _ in range(n_queries):
        start = rng.randrange(0, len(universe) - query_len)
        queries.append(universe[start:start + query_len])
    lat = []
    t0 = time.perf_counter()
    for q in queries:
        t1 = time.perf_counter()
        ix.find_matches(q)
        lat.append(time.perf_counter() - t1)
    q_dt = time.perf_counter() - t0
    queries_per_s = n_queries / q_dt
    p50 = statistics.median(lat) * 1e6
    p99 = statistics.quantiles(lat, n=100)[98] * 1e6
    return events_per_s, queries_per_s, p50, p99


def main():
    import argparse

    argparse.ArgumentParser(
        description="KV indexer microbenchmark (no options; compares the "
                    "python and native indexers)").parse_args()
    rows = [("python", PyKvIndexer())]
    try:
        from dynamo_tpu.router.native_indexer import NativeKvIndexer

        rows.append(("c++", NativeKvIndexer()))
    except ImportError:
        print("(native indexer not built: make -C native)")
    for name, ix in rows:
        ev, q, p50, p99 = bench(ix)
        print(f"{name:7s} events: {ev/1e6:7.2f}M blocks/s   "
              f"queries: {q/1e3:7.1f}k/s   p50 {p50:6.1f}µs  p99 {p99:6.1f}µs")


if __name__ == "__main__":
    main()
