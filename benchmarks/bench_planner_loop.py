"""Closed-loop autoscaling benchmark: a diurnal load swing over a mocker
fleet, planner in the loop (ROADMAP item 4's acceptance bench).

CPU-only: the mocker's timing model simulates engine step latency, so
this measures CONTROL quality — how well the planner's
OBSERVE→PREDICT→PROPOSE→RECONCILE→EXECUTE loop provisions a swinging
load — not kernel speed.  A synthesized diurnal trace (default 10×
trough→peak→trough swing, loadgen.synthesize_diurnal) replays through
the real frontend migration path against workers spawned/drained by a
CallbackConnector, under two policies:

  * closed — the planner scales [min, max] live: load-proposed
    replicas, fast-burn forced scale-up (the frontend-analogue SloPlane
    feeds slo_metrics exactly like a real frontend), drain-gated
    scale-down (victims' streams finish or migrate via token replay).
  * static — max_replicas workers for the whole run: the provisioning
    a fleet without a planner must pay for the same peak.

One JSON line per policy; `--policy ab` adds a summary line comparing
them: the closed loop must hold the p90 TTFT/ITL targets (p90, not
p95 — smoke-scale runs replay tens of requests, where a p95 gate is a
single-sample coin flip) while spending FEWER worker-seconds than
static max-provisioning (`"ok": true`).

    python benchmarks/bench_planner_loop.py --duration-s 30 \
        --rate-low 0.4 --rate-high 4.0 --max-replicas 4
"""

import argparse
import asyncio
import json
import sys
import time
import uuid
from types import SimpleNamespace

sys.path.insert(0, ".")

from dynamo_tpu.frontend import ModelManager, ModelWatcher  # noqa: E402
from dynamo_tpu.loadgen import replay, synthesize_diurnal  # noqa: E402
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker  # noqa: E402
from dynamo_tpu.obs.slo import SloConfig, SloPlane  # noqa: E402
from dynamo_tpu.planner import (  # noqa: E402
    CallbackConnector,
    Planner,
    PlannerConfig,
)
from dynamo_tpu.protocols import PreprocessedRequest  # noqa: E402
from dynamo_tpu.runtime import (  # noqa: E402
    DistributedRuntime,
    RuntimeConfig,
)

BLOCK = 16
MODEL = "bench"


def fresh_runtime():
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


def engine_args(args):
    return MockEngineArgs(
        model_name=MODEL, block_size=BLOCK, num_blocks=4096,
        base_step_s=args.base_step_ms / 1e3,
        prefill_s_per_token=args.prefill_us_per_token / 1e6,
        decode_s_per_seq=args.decode_us_per_seq / 1e6,
        max_num_seqs=args.max_num_seqs)


async def sample_worker_seconds(conn, stop: asyncio.Event, out: dict):
    """∫ replicas dt while the replay runs — the provisioning cost the
    closed loop is judged on — plus the replica-count envelope."""
    last = time.monotonic()
    while not stop.is_set():
        now = time.monotonic()
        n = len(conn.handles)
        out["worker_seconds"] = out.get("worker_seconds", 0.0) \
            + n * (now - last)
        out["replicas_min"] = min(out.get("replicas_min", n), n)
        out["replicas_max"] = max(out.get("replicas_max", n), n)
        last = now
        try:
            await asyncio.wait_for(stop.wait(), 0.05)
        except asyncio.TimeoutError:
            pass


def planner_action_counts(planner) -> dict:
    counts: dict = {}
    for d in planner.decisions:
        kind = ("scale_up" if d["applied"] > d["current"] else "scale_down")
        counts[kind] = counts.get(kind, 0) + 1
        if "burn_actuation" in d:
            counts["burn_up"] = counts.get("burn_up", 0) + 1
    return counts


async def run_policy(policy: str, rows, args) -> dict:
    rt = await fresh_runtime().start()
    eargs = engine_args(args)
    try:
        conn = CallbackConnector(
            spawn=lambda: MockerWorker(
                rt, eargs, component="backend", migration_limit=4).start(),
            stop=lambda w: w.close(),
            drain=lambda w, deadline: w.drain(deadline_s=deadline),
            drain_deadline_s=args.drain_deadline_s)
        await conn.scale(args.max_replicas if policy == "static"
                         else args.min_replicas)

        manager = ModelManager()
        watcher = await ModelWatcher(rt, manager).start()
        for _ in range(400):
            if manager.get(MODEL):
                break
            await asyncio.sleep(0.01)
        pipeline = manager.get(MODEL)
        assert pipeline is not None, "mocker fleet never registered"
        await pipeline.client.wait_for_instances()

        # frontend-analogue SLO plane: per-request outcomes feed rolling
        # burn the exact way a real frontend does, published on
        # slo_metrics.{ns} for the planner's burn actuation
        slo_plane = SloPlane(
            rt.metrics.scoped(component="frontend"),
            SloConfig(ttft_ms=args.slo_ttft_ms, itl_ms=args.slo_itl_ms,
                      windows_s=(5.0, 30.0, 120.0)))
        shim = SimpleNamespace(model=MODEL)

        async def publish_slo():
            while True:
                await asyncio.sleep(0.25)
                await slo_plane.publish(rt, ["dynamo"])

        pub_task = asyncio.create_task(publish_slo())

        planner = None
        if policy == "closed":
            planner = Planner(
                rt, "dynamo", "backend", conn,
                config=PlannerConfig(
                    interval_s=args.tick_s,
                    min_replicas=args.min_replicas,
                    max_replicas=args.max_replicas,
                    target_active_per_replica=args.target_active,
                    cooldown_s=args.cooldown_s,
                    max_step=2, down_stable_ticks=4,
                    burn_up_threshold=args.burn_up_threshold,
                    predictor="ema"))
            await planner.start()

        async def client_fn(req_dict):
            req = PreprocessedRequest.from_dict(req_dict)
            t0 = time.perf_counter()
            first_t = last_t = None
            ntok = 0
            outcome = None
            try:
                async for out in pipeline.migration.generate(req):
                    now = time.perf_counter()
                    n = len(out.token_ids or ())
                    if n:
                        if first_t is None:
                            first_t = now
                        last_t = now
                        ntok += n
                    yield out.to_dict()
            except Exception:
                # an errored request burns SLO budget like a real
                # frontend's outcome=error — without this the burn
                # actuation is blind to exactly the failure mode it
                # should scale against
                outcome = "error"
                raise
            finally:
                end = time.perf_counter()
                itl_ms = None
                if ntok > 1 and first_t is not None and last_t > first_t:
                    itl_ms = (last_t - first_t) / (ntok - 1) * 1e3
                if outcome is None:
                    outcome = ("ok" if first_t is not None
                               else "no_first_token")
                slo_plane.observe_finish(shim, {"request": {
                    "outcome": outcome,
                    "total_time_ms": (end - t0) * 1e3,
                    "ttft_ms": ((first_t - t0) * 1e3
                                if first_t is not None else None),
                    "avg_itl_ms": itl_ms,
                }})

        stop, cost = asyncio.Event(), {}
        sampler = asyncio.create_task(
            sample_worker_seconds(conn, stop, cost))
        try:
            report = await replay(client_fn, rows, block_size=BLOCK,
                                  speedup=args.speedup)
        finally:
            stop.set()
            await sampler
            pub_task.cancel()
            await asyncio.gather(pub_task, return_exceptions=True)

        summary = report.summary(
            slo_ttft_s=args.slo_ttft_ms / 1e3 if args.slo_ttft_ms else None,
            slo_itl_s=args.slo_itl_ms / 1e3 if args.slo_itl_ms else None)
        line = {
            "config": "planner_loop",
            "policy": policy,
            "swing": round(args.rate_high / max(args.rate_low, 1e-9), 2),
            "requests": summary["requests"],
            "completed": summary["completed"],
            "errors": summary["errors"],
            "wall_s": summary["wall_s"],
            "ttft_s": summary["ttft_s"],
            "itl_s": summary["itl_s"],
            "worker_seconds": round(cost.get("worker_seconds", 0.0), 2),
            "replicas": {"min": cost.get("replicas_min"),
                         "max": cost.get("replicas_max")},
            "slo": {"ttft_ms": args.slo_ttft_ms,
                    "itl_ms": args.slo_itl_ms},
        }
        if planner is not None:
            line["actions"] = planner_action_counts(planner)
            line["drain_escalations"] = conn.drain_escalations
            line["last_diag"] = {
                k: v for k, v in planner.last_diag.items()
                if k.startswith(("slo_", "spawn"))}
            await planner.close()
        await watcher.close()
        await conn.close()
        return line
    finally:
        await rt.shutdown()


def verdict(closed: dict, static: dict, args) -> dict:
    """The acceptance comparison: closed must hold the latency targets
    AND spend fewer worker-seconds than static max-provisioning."""
    ttft_ok = closed["ttft_s"]["p90"] <= args.slo_ttft_ms / 1e3
    itl_ok = (args.slo_itl_ms is None
              or closed["itl_s"]["p90"] <= args.slo_itl_ms / 1e3)
    cheaper = closed["worker_seconds"] < static["worker_seconds"]
    return {
        "config": "planner_loop_ab",
        "p90_ttft_ok": ttft_ok,
        "p90_itl_ok": itl_ok,
        "closed_worker_seconds": closed["worker_seconds"],
        "static_worker_seconds": static["worker_seconds"],
        "saving_frac": round(
            1.0 - closed["worker_seconds"]
            / max(static["worker_seconds"], 1e-9), 4),
        "errors": closed["errors"] + static["errors"],
        "ok": bool(ttft_ok and itl_ok and cheaper
                   and closed["errors"] == 0),
    }


async def main():
    p = argparse.ArgumentParser(
        description="closed-loop planner benchmark over a diurnal swing")
    p.add_argument("--policy", default="ab",
                   choices=["closed", "static", "ab"])
    p.add_argument("--duration-s", type=float, default=30.0,
                   help="trace duration (one full diurnal cycle)")
    p.add_argument("--rate-low", type=float, default=0.4,
                   help="trough arrival rate, req/s")
    p.add_argument("--rate-high", type=float, default=4.0,
                   help="peak arrival rate, req/s (default = 10x trough)")
    p.add_argument("--input-len", type=int, default=64)
    p.add_argument("--output-len", type=int, default=128)
    p.add_argument("--speedup", type=float, default=1.0)
    p.add_argument("--seed", type=int, default=0)
    # fleet bounds + control knobs
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=4)
    p.add_argument("--target-active", type=float, default=2.0)
    p.add_argument("--tick-s", type=float, default=0.25)
    p.add_argument("--cooldown-s", type=float, default=0.5)
    p.add_argument("--burn-up-threshold", type=float, default=2.0)
    p.add_argument("--drain-deadline-s", type=float, default=2.0)
    # SLO targets the loop must hold
    p.add_argument("--slo-ttft-ms", type=float, default=1000.0)
    p.add_argument("--slo-itl-ms", type=float, default=100.0)
    # mocker timing model
    p.add_argument("--base-step-ms", type=float, default=12.0)
    p.add_argument("--prefill-us-per-token", type=float, default=20.0)
    p.add_argument("--decode-us-per-seq", type=float, default=3000.0)
    p.add_argument("--max-num-seqs", type=int, default=8)
    args = p.parse_args()

    rows = synthesize_diurnal(
        args.duration_s, rate_low_rps=args.rate_low,
        rate_high_rps=args.rate_high, input_len=args.input_len,
        output_len=args.output_len, seed=args.seed)
    print(json.dumps({"config": "trace", "requests": len(rows),
                      "duration_s": args.duration_s,
                      "swing": round(args.rate_high
                                     / max(args.rate_low, 1e-9), 2)}),
          flush=True)

    results = {}
    for policy in (("closed", "static") if args.policy == "ab"
                   else (args.policy,)):
        results[policy] = await run_policy(policy, rows, args)
        print(json.dumps(results[policy]), flush=True)
    if args.policy == "ab":
        v = verdict(results["closed"], results["static"], args)
        print(json.dumps(v), flush=True)
        if not v["ok"]:
            sys.exit(1)


if __name__ == "__main__":
    asyncio.run(main())
