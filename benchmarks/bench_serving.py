"""Serving latency benchmark: trace replay against in-proc mocker clusters.

CPU-only (no accelerator): the mocker's timing model simulates engine step
latency, so this measures ORCHESTRATION quality — routing, admission,
disagg hand-off — as TTFT/ITL percentiles and goodput, the same metric set
as the reference's router benchmarks (benchmarks/router/README.md:4-46).

Runs two topologies over the same synthesized trace and prints one JSON
report line per config:

  * agg     — N aggregated mocker workers, round-robin routing
  * disagg  — prefill fleet + decode fleet behind the PrefillOrchestrator

    python benchmarks/bench_serving.py [--requests 200] [--rate 16]
"""

import argparse
import asyncio
import json
import sys
import uuid

sys.path.insert(0, ".")

from dynamo_tpu.disagg.prefill_router import (  # noqa: E402
    ConditionalDisaggConfig,
    PrefillOrchestrator,
)
from dynamo_tpu.loadgen import replay, synthesize  # noqa: E402
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker  # noqa: E402
from dynamo_tpu.protocols import PreprocessedRequest  # noqa: E402
from dynamo_tpu.runtime import (  # noqa: E402
    DistributedRuntime,
    RuntimeConfig,
)

BLOCK = 16


def fresh_runtime():
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


# simulated accelerator peaks: nonzero so the mocker's roofline gauges
# (dynamo_engine_mfu/mbu) light up and land in the bench JSON
SIM_PEAK_TFLOPS = 50.0
SIM_PEAK_HBM_GBPS = 100.0


def engine_args(role="both", overlap=True, fused=8, ledger=None):
    return MockEngineArgs(model_name="bench", block_size=BLOCK,
                          num_blocks=8192, speedup_ratio=1.0, role=role,
                          peak_tflops=SIM_PEAK_TFLOPS,
                          peak_hbm_gbps=SIM_PEAK_HBM_GBPS,
                          overlap_scheduling=overlap,
                          decode_fused_steps=fused,
                          kv_ledger=ledger)


class RunTrace:
    """Per-topology span recording: each bench run gets its own Tracer
    (service tagged with the config label, so merged dumps keep their
    tracks distinct) and reduces its own timeline to the obs.report gap
    block — sched_overhead/device_wait/idle/enqueue_ahead fractions and
    cont_burst_frac land in the run's JSON line next to the latency
    numbers they explain."""

    def __init__(self, label: str, out_path: str = ""):
        import os

        from dynamo_tpu import obs

        path = ""
        if out_path:
            # split on the BASENAME only: a dotted directory component
            # (/runs/2026.08/trace) must not become the split point
            root, ext = os.path.splitext(out_path)
            path = f"{root}.{label}{ext or '.json'}"
        self.tracer = obs.Tracer(service=f"bench-{label}",
                                 ring=8 * obs.DEFAULT_RING,
                                 out_path=path or None)
        self.path = path

    def __enter__(self):
        self.tracer.install()
        return self

    def __exit__(self, *exc):
        self.tracer.uninstall()
        return False

    def gap(self):
        from dynamo_tpu.obs.report import events_of_doc, report

        if self.path:
            self.path = self.tracer.dump() or ""
        return report(events_of_doc(self.tracer.chrome_trace()))["gap"]


class ForensicCapture:
    """Frontend-analogue forensics over the worker-contract stream: a
    RequestTracker per replayed request records the hop timeline
    (dispatched → first_token → decode_stall → finish) and the worker's
    forensic stamps, feeding a ForensicsPlane — so the bench exercises
    the always-on plane end to end and its JSON line carries the `tail`
    block.  Token streams are captured in BOTH modes (identical capture
    cost on either side of the A/B), so `--forensics ab` can assert the
    plane changes nothing about what clients see."""

    def __init__(self, enabled: bool, metrics=None):
        from dynamo_tpu.obs.forensics import ForensicsPlane

        self.enabled = enabled
        self.plane = ForensicsPlane(metrics) if enabled else None
        self.streams: dict = {}  # request_id -> [token ids]

    def wrap(self, client_fn, pass_tracker=False):
        from dynamo_tpu.frontend.request_trace import RequestTracker

        async def wrapped(req_dict):
            rid = req_dict.get("request_id", "")
            toks = self.streams.setdefault(rid, [])
            tracker = None
            if self.enabled:
                tracker = RequestTracker(
                    request_id=rid, model="bench", forensics=self.plane,
                    input_tokens=len(req_dict.get("token_ids") or ()))
                tracker.on_dispatch(None)
            finish = None
            # pass_tracker: a composite client (disagg orchestration)
            # records its own prefill_open/prefill_done hops, exactly
            # like the real frontend pipeline brackets maybe_prefill
            stream = (client_fn(req_dict, tracker=tracker) if pass_tracker
                      else client_fn(req_dict))
            async for item in stream:
                ids = item.get("token_ids") or ()
                toks.extend(ids)
                if tracker is not None:
                    stamp = (item.get("metrics") or {}).get("forensic")
                    if stamp is not None:
                        tracker.on_worker_stamp(stamp)
                    tracker.on_tokens(len(ids))
                    finish = item.get("finish_reason") or finish
                yield item
            if tracker is not None:
                tracker.finish(finish_reason=finish)

        return wrapped

    def tail_block(self, rt):
        """The bench JSON `tail` block: realized-overlap rate read back
        off the run's own metrics registry with the real parser (the
        fleet/roofline-block idiom), plus the worst retained exemplar's
        exact phase partition — the reservoir IS the tail, so its worst
        entry is the p99+ autopsy."""
        if self.plane is None:
            return None
        from prometheus_client.parser import text_string_to_metric_families

        out = dict(self.plane.counts())
        for fam in text_string_to_metric_families(
                rt.metrics.render().decode()):
            if fam.name == "dynamo_frontend_realized_overlap_ratio":
                out["realized_overlap_ratio"] = round(
                    fam.samples[0].value, 4)
        worst = self.plane.worst("ttft")
        if worst is not None:
            out["p99_ttft_ms"] = round(worst.ttft_ms or 0.0, 3)
            out["p99_partition"] = {p: round(v, 3) for p, v in
                                    worst.partition.items()}
        return out


async def sample_fleet_peaks(workers, stop: asyncio.Event, peaks: dict):
    """Track the fleet-plane headline AT PEAK while the replay runs:
    worst load imbalance, worst straggler count, minimum KV headroom —
    sampled from the same per-worker debug states obs.fleet scrapes,
    reduced by the same summarize_states."""
    from dynamo_tpu.obs.fleet import summarize_states

    while not stop.is_set():
        s = summarize_states([w.debug_state() for w in workers])
        peaks["imbalance"] = max(peaks.get("imbalance", 1.0),
                                 s["imbalance"])
        peaks["stragglers"] = max(peaks.get("stragglers", 0),
                                  s["straggler_count"])
        peaks["kv_headroom_min"] = min(peaks.get("kv_headroom_min", 1.0),
                                       s["kv_headroom_min"])
        peaks["_last"] = s
        try:
            await asyncio.wait_for(stop.wait(), 0.05)
        except asyncio.TimeoutError:
            pass


async def collect_fleet(rt, workers, peaks: dict):
    """`fleet` block for the bench JSON: export the peak-annotated
    summary through the fleet gauge surface (obs/fleet.py), then read
    the numbers back off the run's own registry with the prometheus
    parser — the same families a production scrape of a fleet exporter
    would see."""
    import time

    from prometheus_client.parser import text_string_to_metric_families

    from dynamo_tpu.obs.fleet import FleetSnapshot, export_fleet_gauges, \
        summarize_states

    summary = peaks.get("_last") or summarize_states(
        [w.debug_state() for w in workers])
    summary["imbalance"] = peaks.get("imbalance", summary["imbalance"])
    summary["straggler_count"] = peaks.get("stragglers",
                                           summary["straggler_count"])
    summary["kv_headroom_min"] = peaks.get("kv_headroom_min",
                                           summary["kv_headroom_min"])
    export_fleet_gauges(
        rt.metrics.scoped(component="fleet"),
        FleetSnapshot(ts_unix=time.time(), workers=[], frontends=[],
                      summary=summary))
    out = {}
    for fam in text_string_to_metric_families(rt.metrics.render().decode()):
        if fam.name == "dynamo_fleet_load_imbalance":
            out["imbalance"] = round(fam.samples[0].value, 4)
        elif fam.name == "dynamo_fleet_straggler_workers":
            out["stragglers"] = int(fam.samples[0].value)
        elif fam.name == "dynamo_fleet_kv_headroom_min":
            out["kv_headroom_min"] = round(fam.samples[0].value, 4)
    return out


def collect_kv_ledger(workers):
    """`kv_ledger` entry for the bench JSON `fleet` block: run each
    worker's ON-DEMAND ledger audit (the /debug/kv path) after the
    replay and reduce with the fleet's own rollup — a clean bench run
    must reconcile exactly (violations_total == 0), which is the
    acceptance gate --kv-ledger ab asserts."""
    from dynamo_tpu.obs.fleet import reduce_kv_ledgers

    rollup = reduce_kv_ledgers([w.kv_debug() for w in workers])
    if rollup is None:
        return {}
    return {"kv_ledger": {
        "violations_total": rollup["violations_total"],
        "violations": rollup["violations"],
        "occupancy": rollup["occupancy"],
    }}


async def collect_roofline(rt):
    """Scrape the run's worker gauges (one load-loop tick after the
    replay) into the bench JSON's roofline block: per-phase MFU/MBU and
    compile counts per program family — the same names a production
    Prometheus would scrape, parsed with the same parser."""
    from prometheus_client.parser import text_string_to_metric_families

    await asyncio.sleep(0.4)  # let the workers' 0.25s load loops tick
    out = {"mfu": {}, "mbu": {}, "compiles": {}, "serving_compiles": {}}
    for fam in text_string_to_metric_families(
            rt.metrics.render().decode()):
        if fam.name == "dynamo_engine_mfu":
            for s in fam.samples:
                out["mfu"][s.labels.get("phase", "")] = round(s.value, 4)
        elif fam.name == "dynamo_engine_mbu":
            for s in fam.samples:
                out["mbu"][s.labels.get("phase", "")] = round(s.value, 4)
        elif fam.name in ("dynamo_engine_compiles",
                          "dynamo_engine_serving_compiles"):
            # serving_compiles = compiles that landed with requests in
            # flight (obs/compile_watch.py): each one is a serving
            # stall, and the bench round's zero-mid-serving gate reads
            # this block
            key_out = ("compiles" if fam.name == "dynamo_engine_compiles"
                       else "serving_compiles")
            for s in fam.samples:
                if not s.name.endswith("_total"):
                    continue
                key = s.labels.get("family", "")
                out[key_out][key] = out[key_out].get(key, 0) + int(s.value)
    return out


async def bench_agg(rows, n_workers, args, overlap=True, label="agg",
                    forensics=True, ledger=None):
    rt = await fresh_runtime().start()
    workers = [
        await MockerWorker(rt, engine_args(overlap=overlap,
                                           ledger=ledger),
                           component="backend").start()
        for _ in range(n_workers)
    ]
    client = await (rt.namespace("dynamo").component("backend")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    cap = ForensicCapture(forensics,
                          rt.metrics.scoped(component="frontend"))
    stop, peaks = asyncio.Event(), {}
    sampler = asyncio.create_task(sample_fleet_peaks(workers, stop, peaks))
    with RunTrace(label, args.trace_out) as rtrace:
        try:
            report = await replay(cap.wrap(client.generate), rows,
                                  block_size=BLOCK, speedup=args.speedup)
        finally:
            stop.set()
            await sampler
        roofline = await collect_roofline(rt)
    gap = rtrace.gap()
    fleet = await collect_fleet(rt, workers, peaks)
    fleet.update(collect_kv_ledger(workers))
    tail = cap.tail_block(rt)
    await client.close()
    for w in workers:
        await w.close()
    await rt.shutdown()
    return report, roofline, fleet, gap, rtrace.path, tail, cap


async def bench_disagg(rows, n_prefill, n_decode, args, overlap=True,
                       label="disagg", forensics=True, ledger=None):
    rt = await fresh_runtime().start()
    prefills = [
        await MockerWorker(rt, engine_args("prefill", overlap=overlap,
                                           ledger=ledger),
                           component="prefill").start()
        for _ in range(n_prefill)
    ]
    decodes = [
        await MockerWorker(rt, engine_args("decode", overlap=overlap,
                                           ledger=ledger),
                           component="backend").start()
        for _ in range(n_decode)
    ]
    pclient = await (rt.namespace("dynamo").component("prefill")
                     .endpoint("generate").client()).start()
    dclient = await (rt.namespace("dynamo").component("backend")
                     .endpoint("generate").client()).start()
    await pclient.wait_for_instances()
    await dclient.wait_for_instances()
    orch = PrefillOrchestrator(
        pclient, ConditionalDisaggConfig(always_remote=True))

    async def client_fn(req_dict, tracker=None):
        import time as _time

        t_hop = _time.monotonic()
        routed = await orch.maybe_prefill(
            PreprocessedRequest.from_dict(req_dict))
        if tracker is not None and routed.disaggregated_params:
            # same bracketing as the frontend pipeline: the remote
            # prefill IS the first dispatch, and first_token after the
            # decode dispatch partitions as `transfer`
            tracker.hop("prefill_open", at=t_hop)
            tracker.hop("prefill_done")
            tracker.mark_dispatching(at=t_hop)
        async for item in dclient.generate(routed.to_dict()):
            yield item

    cap = ForensicCapture(forensics,
                          rt.metrics.scoped(component="frontend"))
    stop, peaks = asyncio.Event(), {}
    sampler = asyncio.create_task(
        sample_fleet_peaks(prefills + decodes, stop, peaks))
    with RunTrace(label, args.trace_out) as rtrace:
        try:
            report = await replay(cap.wrap(client_fn, pass_tracker=True),
                                  rows,
                                  block_size=BLOCK, speedup=args.speedup)
        finally:
            stop.set()
            await sampler
        roofline = await collect_roofline(rt)
    gap = rtrace.gap()
    fleet = await collect_fleet(rt, prefills + decodes, peaks)
    fleet.update(collect_kv_ledger(prefills + decodes))
    tail = cap.tail_block(rt)
    await orch.close()
    await pclient.close()
    await dclient.close()
    for w in prefills + decodes:
        await w.close()
    await rt.shutdown()
    return report, roofline, fleet, gap, rtrace.path, tail, cap


async def main():
    p = argparse.ArgumentParser()
    p.add_argument("--requests", type=int, default=100)
    p.add_argument("--rate", type=float, default=16.0)
    p.add_argument("--input-len", type=int, default=384)
    p.add_argument("--output-len", type=int, default=24)
    p.add_argument("--prefix-groups", type=int, default=8)
    p.add_argument("--speedup", type=float, default=1.0)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--slo-ttft", type=float, default=2.0)
    p.add_argument("--slo-itl", type=float, default=0.025)
    # ms-denominated aliases matching the frontend's --slo-* flags
    # (obs/slo.py); when given they override the seconds-based knobs
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="TTFT SLO target in ms (overrides --slo-ttft; "
                        "same convention as the frontend's flag)")
    p.add_argument("--slo-itl-ms", type=float, default=None,
                   help="mean-ITL SLO target in ms (overrides "
                        "--slo-itl)")
    p.add_argument("--trace-out", default="",
                   help="dump each topology's Perfetto-loadable Chrome "
                        "trace to PATH with the config label inserted "
                        "before the extension, and print a merged "
                        "obs.report gap-attribution line (the per-run "
                        "gap fracs are in every JSON line regardless)")
    p.add_argument("--overlap", choices=["on", "off", "ab"], default="on",
                   help="scheduler mode for the mocker engines: "
                        "overlapped (default), lockstep sync, or 'ab' — "
                        "run every topology in BOTH modes so the "
                        "overlapped scheduler's win is measurable in "
                        "one invocation")
    p.add_argument("--forensics", choices=["on", "off", "ab"],
                   default="on",
                   help="per-request forensics plane "
                        "(obs/forensics.py): on (default — every JSON "
                        "line carries a `tail` block), off, or 'ab' — "
                        "run the agg topology with the plane off then "
                        "on over the SAME trace, assert byte-identical "
                        "token streams, and print a forensics_ab line "
                        "with the measured throughput overhead "
                        "(target <1%%)")
    p.add_argument("--kv-ledger", choices=["on", "off", "ab"],
                   default="on",
                   help="KV block-lifecycle ledger + auditor "
                        "(obs/kv_ledger.py): on (default — every JSON "
                        "line's `fleet` block carries the post-run "
                        "audit rollup, which must reconcile clean), "
                        "off, or 'ab' — run the agg topology with the "
                        "plane off then on over the SAME trace, assert "
                        "byte-identical token streams AND a clean "
                        "audit, and print a kv_ledger_ab line with the "
                        "measured throughput overhead (target <1%%)")
    # kernel-impl bookkeeping for round scoreboards: the mocker's timing
    # model dispatches no real kernels, so these flags only STAMP the
    # settings a paired on-chip run used into every JSON line (the
    # `impls` block), keeping r06 rows self-describing next to rows from
    # the real engine.  Choices mirror ops/paged_attention.DECODE_IMPLS,
    # ops/packed_prefill.PACKED_IMPLS and ops/fused_sampling
    # .EPILOGUE_MODES as literals — importing those modules would pull
    # jax into this deliberately jax-free bench (tests pin the parity).
    p.add_argument("--attn-impl", default="auto",
                   choices=["auto", "pallas", "pallas_interpret", "jnp",
                            "jnp_bf16"],
                   help="decode attention impl stamped into the JSON "
                        "`impls` block")
    p.add_argument("--packed-attn-impl", default="auto",
                   choices=["auto", "xla", "pallas", "pallas_interpret"],
                   help="packed-prefill impl stamped into the JSON "
                        "`impls` block")
    p.add_argument("--sampling-epilogue", default="off",
                   choices=["off", "fused"],
                   help="sampling epilogue mode stamped into the JSON "
                        "`impls` block")
    args = p.parse_args()

    rows = synthesize(args.requests, rate_rps=args.rate,
                      input_len=args.input_len, output_len=args.output_len,
                      block_size=BLOCK, prefix_groups=args.prefix_groups,
                      seed=11)
    slo_ttft_s = (args.slo_ttft_ms / 1000.0
                  if args.slo_ttft_ms is not None else args.slo_ttft)
    slo_itl_s = (args.slo_itl_ms / 1000.0
                 if args.slo_itl_ms is not None else args.slo_itl)

    # the headline gap-report fracs every JSON line carries (the
    # item-3 scoreboard: sched_overhead -> ~0 and cont_burst -> 1 is
    # what the overlapped scheduler is FOR; the rest partitions where
    # the remaining wall time goes)
    GAP_KEYS = ("sched_overhead_frac", "enqueue_ahead_frac",
                "device_wait_frac", "idle_frac", "cont_burst_frac")

    def line(config, summary, roofline, fleet, gap, tail=None):
        # stable bench JSON schema: the `slo` block mirrors the
        # frontend SLO plane's vocabulary (targets + goodput fraction),
        # `roofline` the worker gauges, `fleet` the obs.fleet headline
        # at peak (imbalance, straggler count, min KV headroom), and
        # `gap` the obs.report wall partition of this run's own engine
        # tracks — a scoreboard diff across rounds reads the same
        # numbers a live scrape/trace would
        gp = summary.get("goodput", {})
        total = summary.get("requests", 0)
        return json.dumps({
            "config": config, **summary,
            # effective kernel/epilogue settings for this row (mocker =
            # simulated step timing; the settings describe the paired
            # on-chip configuration a round scoreboard lines this row
            # up against)
            "impls": {
                "engine": "mocker",
                "attn_impl": args.attn_impl,
                "packed_attn_impl": args.packed_attn_impl,
                "sampling_epilogue": args.sampling_epilogue,
            },
            "slo": {
                "ttft_s": slo_ttft_s, "itl_s": slo_itl_s,
                "goodput": (round(gp.get("good_requests", 0) / total, 4)
                            if total else None),
                "good_rps": gp.get("good_rps"),
            },
            "roofline": roofline,
            "fleet": fleet,
            "gap": {k: gap[k] for k in GAP_KEYS if k in gap},
            # tail-forensics block (obs/forensics.py via the replay's
            # per-request trackers): worst retained exemplar's exact
            # phase partition + the realized-overlap rate, read back
            # off the run's own registry
            **({"tail": tail} if tail is not None else {}),
        })

    if args.kv_ledger == "ab":
        # A/B smoke: the SAME trace against the agg topology with the
        # ledger off then on.  The ledger is pure accounting — the
        # token streams must be byte-identical (hard assert), the ON
        # run's post-run audit must reconcile exactly (0 violations),
        # and the throughput delta is the always-on overhead (target
        # <1%; open-loop arrivals keep the rate comparison stable)
        await bench_agg(rows[: min(len(rows), 8)], args.workers, args,
                        label="agg-kvledger-warmup", ledger=True)
        off, *_rest_off, cap_off = await bench_agg(
            rows, args.workers, args, label="agg-kvledger-off",
            ledger=False)
        on, _roof, fleet_on, _gap, _path, _tail, cap_on = await bench_agg(
            rows, args.workers, args, label="agg-kvledger-on",
            ledger=True)
        s_off = off.summary(slo_ttft_s, slo_itl_s)
        s_on = on.summary(slo_ttft_s, slo_itl_s)
        tps_off = s_off["output_tokens_per_s"]
        tps_on = s_on["output_tokens_per_s"]
        overhead = (1.0 - tps_on / tps_off) if tps_off else 0.0
        identical = cap_off.streams == cap_on.streams
        kvl = fleet_on.get("kv_ledger") or {}
        print(json.dumps({
            "config": "kv_ledger_ab",
            "streams_identical": identical,
            "tok_s_off": tps_off, "tok_s_on": tps_on,
            "overhead_frac": round(overhead, 4),
            "overhead_target_frac": 0.01,
            "overhead_ok": overhead < 0.01,
            "violations_total": kvl.get("violations_total"),
            "kv_ledger": kvl,
        }))
        if not identical:
            raise SystemExit(
                "kv ledger changed the token streams — it must be pure "
                "accounting")
        if kvl.get("violations_total", 0) != 0:
            raise SystemExit(
                f"kv ledger audit did not reconcile clean: "
                f"{kvl.get('violations')}")
        return

    if args.forensics == "ab":
        # A/B smoke: the SAME trace against the agg topology with the
        # plane off then on.  The plane is pure observation — the token
        # streams must be byte-identical (hard assert), and the
        # throughput delta is the always-on overhead (target <1%; the
        # open-loop arrival schedule makes the rate comparison stable)
        # throwaway warmup so the first measured run doesn't eat the
        # process's import/infra cold start and bias the comparison
        await bench_agg(rows[: min(len(rows), 8)], args.workers, args,
                        label="agg-forensics-warmup", forensics=True)
        off, *_rest_off, cap_off = await bench_agg(
            rows, args.workers, args, label="agg-forensics-off",
            forensics=False)
        on, _roof, _fleet, _gap, _path, tail, cap_on = await bench_agg(
            rows, args.workers, args, label="agg-forensics-on",
            forensics=True)
        s_off = off.summary(slo_ttft_s, slo_itl_s)
        s_on = on.summary(slo_ttft_s, slo_itl_s)
        tps_off = s_off["output_tokens_per_s"]
        tps_on = s_on["output_tokens_per_s"]
        overhead = (1.0 - tps_on / tps_off) if tps_off else 0.0
        identical = cap_off.streams == cap_on.streams
        print(json.dumps({
            "config": "forensics_ab",
            "streams_identical": identical,
            "tok_s_off": tps_off, "tok_s_on": tps_on,
            "overhead_frac": round(overhead, 4),
            "overhead_target_frac": 0.01,
            "overhead_ok": overhead < 0.01,
            "tail": tail,
        }))
        if not identical:
            raise SystemExit(
                "forensics plane changed the token streams — it must be "
                "pure observation")
        return

    modes = {"on": [(True, "overlap")], "off": [(False, "sync")],
             "ab": [(False, "sync"), (True, "overlap")]}[args.overlap]
    forensics_on = args.forensics == "on"
    # on = follow DYN_KV_LEDGER (default-on); off pins the plane off
    ledger = None if args.kv_ledger == "on" else False
    np_, nd = max(1, args.workers // 2), max(1, args.workers // 2)
    trace_paths = []
    for ov, tag in modes:
        suffix = f"-{tag}" if args.overlap == "ab" else ""
        label = f"agg-{args.workers}w{suffix}"
        agg, roof, fleet, gap, path, tail, _cap = await bench_agg(
            rows, args.workers, args, overlap=ov, label=label,
            forensics=forensics_on, ledger=ledger)
        trace_paths.append(path)
        print(line(label, agg.summary(slo_ttft_s, slo_itl_s), roof,
                   fleet, gap, tail))
        label = f"disagg-{np_}p{nd}d{suffix}"
        dis, roof, fleet, gap, path, tail, _cap = await bench_disagg(
            rows, np_, nd, args, overlap=ov, label=label,
            forensics=forensics_on, ledger=ledger)
        trace_paths.append(path)
        print(line(label, dis.summary(slo_ttft_s, slo_itl_s), roof,
                   fleet, gap, tail))

    if args.trace_out:
        from dynamo_tpu.obs.report import report_paths

        paths = [p for p in trace_paths if p]
        if not paths:
            print(json.dumps({"config": "trace",
                              "error": f"trace dump to "
                                       f"{args.trace_out!r} failed"}))
        else:
            print(json.dumps({"config": "trace", "trace_out": paths,
                              **report_paths(paths)["gap"]}))


if __name__ == "__main__":
    asyncio.run(main())
