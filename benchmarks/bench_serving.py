"""Serving latency benchmark: trace replay against in-proc mocker clusters.

CPU-only (no accelerator): the mocker's timing model simulates engine step
latency, so this measures ORCHESTRATION quality — routing, admission,
disagg hand-off — as TTFT/ITL percentiles and goodput, the same metric set
as the reference's router benchmarks (benchmarks/router/README.md:4-46).

Runs two topologies over the same synthesized trace and prints one JSON
report line per config:

  * agg     — N aggregated mocker workers, round-robin routing
  * disagg  — prefill fleet + decode fleet behind the PrefillOrchestrator

    python benchmarks/bench_serving.py [--requests 200] [--rate 16]
"""

import argparse
import asyncio
import json
import sys
import uuid

sys.path.insert(0, ".")

from dynamo_tpu.disagg.prefill_router import (  # noqa: E402
    ConditionalDisaggConfig,
    PrefillOrchestrator,
)
from dynamo_tpu.loadgen import replay, synthesize  # noqa: E402
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker  # noqa: E402
from dynamo_tpu.protocols import PreprocessedRequest  # noqa: E402
from dynamo_tpu.runtime import (  # noqa: E402
    DistributedRuntime,
    RuntimeConfig,
)

BLOCK = 16


def fresh_runtime():
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


# simulated accelerator peaks: nonzero so the mocker's roofline gauges
# (dynamo_engine_mfu/mbu) light up and land in the bench JSON
SIM_PEAK_TFLOPS = 50.0
SIM_PEAK_HBM_GBPS = 100.0


def engine_args(role="both"):
    return MockEngineArgs(model_name="bench", block_size=BLOCK,
                          num_blocks=8192, speedup_ratio=1.0, role=role,
                          peak_tflops=SIM_PEAK_TFLOPS,
                          peak_hbm_gbps=SIM_PEAK_HBM_GBPS)


async def sample_fleet_peaks(workers, stop: asyncio.Event, peaks: dict):
    """Track the fleet-plane headline AT PEAK while the replay runs:
    worst load imbalance, worst straggler count, minimum KV headroom —
    sampled from the same per-worker debug states obs.fleet scrapes,
    reduced by the same summarize_states."""
    from dynamo_tpu.obs.fleet import summarize_states

    while not stop.is_set():
        s = summarize_states([w.debug_state() for w in workers])
        peaks["imbalance"] = max(peaks.get("imbalance", 1.0),
                                 s["imbalance"])
        peaks["stragglers"] = max(peaks.get("stragglers", 0),
                                  s["straggler_count"])
        peaks["kv_headroom_min"] = min(peaks.get("kv_headroom_min", 1.0),
                                       s["kv_headroom_min"])
        peaks["_last"] = s
        try:
            await asyncio.wait_for(stop.wait(), 0.05)
        except asyncio.TimeoutError:
            pass


async def collect_fleet(rt, workers, peaks: dict):
    """`fleet` block for the bench JSON: export the peak-annotated
    summary through the fleet gauge surface (obs/fleet.py), then read
    the numbers back off the run's own registry with the prometheus
    parser — the same families a production scrape of a fleet exporter
    would see."""
    import time

    from prometheus_client.parser import text_string_to_metric_families

    from dynamo_tpu.obs.fleet import FleetSnapshot, export_fleet_gauges, \
        summarize_states

    summary = peaks.get("_last") or summarize_states(
        [w.debug_state() for w in workers])
    summary["imbalance"] = peaks.get("imbalance", summary["imbalance"])
    summary["straggler_count"] = peaks.get("stragglers",
                                           summary["straggler_count"])
    summary["kv_headroom_min"] = peaks.get("kv_headroom_min",
                                           summary["kv_headroom_min"])
    export_fleet_gauges(
        rt.metrics.scoped(component="fleet"),
        FleetSnapshot(ts_unix=time.time(), workers=[], frontends=[],
                      summary=summary))
    out = {}
    for fam in text_string_to_metric_families(rt.metrics.render().decode()):
        if fam.name == "dynamo_fleet_load_imbalance":
            out["imbalance"] = round(fam.samples[0].value, 4)
        elif fam.name == "dynamo_fleet_straggler_workers":
            out["stragglers"] = int(fam.samples[0].value)
        elif fam.name == "dynamo_fleet_kv_headroom_min":
            out["kv_headroom_min"] = round(fam.samples[0].value, 4)
    return out


async def collect_roofline(rt):
    """Scrape the run's worker gauges (one load-loop tick after the
    replay) into the bench JSON's roofline block: per-phase MFU/MBU and
    compile counts per program family — the same names a production
    Prometheus would scrape, parsed with the same parser."""
    from prometheus_client.parser import text_string_to_metric_families

    await asyncio.sleep(0.4)  # let the workers' 0.25s load loops tick
    out = {"mfu": {}, "mbu": {}, "compiles": {}}
    for fam in text_string_to_metric_families(
            rt.metrics.render().decode()):
        if fam.name == "dynamo_engine_mfu":
            for s in fam.samples:
                out["mfu"][s.labels.get("phase", "")] = round(s.value, 4)
        elif fam.name == "dynamo_engine_mbu":
            for s in fam.samples:
                out["mbu"][s.labels.get("phase", "")] = round(s.value, 4)
        elif fam.name == "dynamo_engine_compiles":
            for s in fam.samples:
                if not s.name.endswith("_total"):
                    continue
                key = s.labels.get("family", "")
                out["compiles"][key] = \
                    out["compiles"].get(key, 0) + int(s.value)
    return out


async def bench_agg(rows, n_workers, args):
    rt = await fresh_runtime().start()
    workers = [
        await MockerWorker(rt, engine_args(), component="backend").start()
        for _ in range(n_workers)
    ]
    client = await (rt.namespace("dynamo").component("backend")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    stop, peaks = asyncio.Event(), {}
    sampler = asyncio.create_task(sample_fleet_peaks(workers, stop, peaks))
    try:
        report = await replay(client.generate, rows, block_size=BLOCK,
                              speedup=args.speedup)
    finally:
        stop.set()
        await sampler
    roofline = await collect_roofline(rt)
    fleet = await collect_fleet(rt, workers, peaks)
    await client.close()
    for w in workers:
        await w.close()
    await rt.shutdown()
    return report, roofline, fleet


async def bench_disagg(rows, n_prefill, n_decode, args):
    rt = await fresh_runtime().start()
    prefills = [
        await MockerWorker(rt, engine_args("prefill"),
                           component="prefill").start()
        for _ in range(n_prefill)
    ]
    decodes = [
        await MockerWorker(rt, engine_args("decode"),
                           component="backend").start()
        for _ in range(n_decode)
    ]
    pclient = await (rt.namespace("dynamo").component("prefill")
                     .endpoint("generate").client()).start()
    dclient = await (rt.namespace("dynamo").component("backend")
                     .endpoint("generate").client()).start()
    await pclient.wait_for_instances()
    await dclient.wait_for_instances()
    orch = PrefillOrchestrator(
        pclient, ConditionalDisaggConfig(always_remote=True))

    async def client_fn(req_dict):
        routed = await orch.maybe_prefill(
            PreprocessedRequest.from_dict(req_dict))
        async for item in dclient.generate(routed.to_dict()):
            yield item

    stop, peaks = asyncio.Event(), {}
    sampler = asyncio.create_task(
        sample_fleet_peaks(prefills + decodes, stop, peaks))
    try:
        report = await replay(client_fn, rows, block_size=BLOCK,
                              speedup=args.speedup)
    finally:
        stop.set()
        await sampler
    roofline = await collect_roofline(rt)
    fleet = await collect_fleet(rt, prefills + decodes, peaks)
    await orch.close()
    await pclient.close()
    await dclient.close()
    for w in prefills + decodes:
        await w.close()
    await rt.shutdown()
    return report, roofline, fleet


async def main():
    p = argparse.ArgumentParser()
    p.add_argument("--requests", type=int, default=100)
    p.add_argument("--rate", type=float, default=16.0)
    p.add_argument("--input-len", type=int, default=384)
    p.add_argument("--output-len", type=int, default=24)
    p.add_argument("--prefix-groups", type=int, default=8)
    p.add_argument("--speedup", type=float, default=1.0)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--slo-ttft", type=float, default=2.0)
    p.add_argument("--slo-itl", type=float, default=0.025)
    # ms-denominated aliases matching the frontend's --slo-* flags
    # (obs/slo.py); when given they override the seconds-based knobs
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="TTFT SLO target in ms (overrides --slo-ttft; "
                        "same convention as the frontend's flag)")
    p.add_argument("--slo-itl-ms", type=float, default=None,
                   help="mean-ITL SLO target in ms (overrides "
                        "--slo-itl)")
    p.add_argument("--trace-out", default="",
                   help="record the run's timeline spans (obs/) and dump "
                        "a Perfetto-loadable Chrome trace here; also "
                        "prints the obs.report gap-attribution line")
    args = p.parse_args()

    tracer = None
    if args.trace_out:
        from dynamo_tpu import obs

        tracer = obs.Tracer(service="bench_serving",
                            ring=4 * obs.DEFAULT_RING,
                            out_path=args.trace_out).install()

    rows = synthesize(args.requests, rate_rps=args.rate,
                      input_len=args.input_len, output_len=args.output_len,
                      block_size=BLOCK, prefix_groups=args.prefix_groups,
                      seed=11)
    slo_ttft_s = (args.slo_ttft_ms / 1000.0
                  if args.slo_ttft_ms is not None else args.slo_ttft)
    slo_itl_s = (args.slo_itl_ms / 1000.0
                 if args.slo_itl_ms is not None else args.slo_itl)

    def line(config, summary, roofline, fleet):
        # stable bench JSON schema: the `slo` block mirrors the
        # frontend SLO plane's vocabulary (targets + goodput fraction),
        # `roofline` the worker gauges, `fleet` the obs.fleet headline
        # at peak (imbalance, straggler count, min KV headroom), so a
        # scoreboard diff across rounds reads the same numbers a live
        # scrape would
        gp = summary.get("goodput", {})
        total = summary.get("requests", 0)
        return json.dumps({
            "config": config, **summary,
            "slo": {
                "ttft_s": slo_ttft_s, "itl_s": slo_itl_s,
                "goodput": (round(gp.get("good_requests", 0) / total, 4)
                            if total else None),
                "good_rps": gp.get("good_rps"),
            },
            "roofline": roofline,
            "fleet": fleet,
        })

    agg, agg_roof, agg_fleet = await bench_agg(rows, args.workers, args)
    print(line(f"agg-{args.workers}w",
               agg.summary(slo_ttft_s, slo_itl_s), agg_roof, agg_fleet))
    dis, dis_roof, dis_fleet = await bench_disagg(
        rows, max(1, args.workers // 2), max(1, args.workers // 2), args)
    print(line(f"disagg-{max(1, args.workers // 2)}p"
               f"{max(1, args.workers // 2)}d",
               dis.summary(slo_ttft_s, slo_itl_s), dis_roof, dis_fleet))

    if tracer is not None:
        from dynamo_tpu.obs.report import report_paths

        path = tracer.dump()
        tracer.uninstall()
        if path is None:
            print(json.dumps({"config": "trace",
                              "error": f"trace dump to "
                                       f"{args.trace_out!r} failed"}))
        else:
            print(json.dumps({"config": "trace", "trace_out": path,
                              **report_paths([path])["gap"]}))


if __name__ == "__main__":
    asyncio.run(main())
