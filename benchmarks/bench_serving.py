"""Serving latency benchmark: trace replay against in-proc mocker clusters.

CPU-only (no accelerator): the mocker's timing model simulates engine step
latency, so this measures ORCHESTRATION quality — routing, admission,
disagg hand-off — as TTFT/ITL percentiles and goodput, the same metric set
as the reference's router benchmarks (benchmarks/router/README.md:4-46).

Runs two topologies over the same synthesized trace and prints one JSON
report line per config:

  * agg     — N aggregated mocker workers, round-robin routing
  * disagg  — prefill fleet + decode fleet behind the PrefillOrchestrator

    python benchmarks/bench_serving.py [--requests 200] [--rate 16]
"""

import argparse
import asyncio
import json
import sys
import uuid

sys.path.insert(0, ".")

from dynamo_tpu.disagg.prefill_router import (  # noqa: E402
    ConditionalDisaggConfig,
    PrefillOrchestrator,
)
from dynamo_tpu.loadgen import replay, synthesize  # noqa: E402
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker  # noqa: E402
from dynamo_tpu.protocols import PreprocessedRequest  # noqa: E402
from dynamo_tpu.runtime import (  # noqa: E402
    DistributedRuntime,
    RuntimeConfig,
)

BLOCK = 16


def fresh_runtime():
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


def engine_args(role="both"):
    return MockEngineArgs(model_name="bench", block_size=BLOCK,
                          num_blocks=8192, speedup_ratio=1.0, role=role)


async def bench_agg(rows, n_workers, args):
    rt = await fresh_runtime().start()
    workers = [
        await MockerWorker(rt, engine_args(), component="backend").start()
        for _ in range(n_workers)
    ]
    client = await (rt.namespace("dynamo").component("backend")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    report = await replay(client.generate, rows, block_size=BLOCK,
                          speedup=args.speedup)
    await client.close()
    for w in workers:
        await w.close()
    await rt.shutdown()
    return report


async def bench_disagg(rows, n_prefill, n_decode, args):
    rt = await fresh_runtime().start()
    prefills = [
        await MockerWorker(rt, engine_args("prefill"),
                           component="prefill").start()
        for _ in range(n_prefill)
    ]
    decodes = [
        await MockerWorker(rt, engine_args("decode"),
                           component="backend").start()
        for _ in range(n_decode)
    ]
    pclient = await (rt.namespace("dynamo").component("prefill")
                     .endpoint("generate").client()).start()
    dclient = await (rt.namespace("dynamo").component("backend")
                     .endpoint("generate").client()).start()
    await pclient.wait_for_instances()
    await dclient.wait_for_instances()
    orch = PrefillOrchestrator(
        pclient, ConditionalDisaggConfig(always_remote=True))

    async def client_fn(req_dict):
        routed = await orch.maybe_prefill(
            PreprocessedRequest.from_dict(req_dict))
        async for item in dclient.generate(routed.to_dict()):
            yield item

    report = await replay(client_fn, rows, block_size=BLOCK,
                          speedup=args.speedup)
    await orch.close()
    await pclient.close()
    await dclient.close()
    for w in prefills + decodes:
        await w.close()
    await rt.shutdown()
    return report


async def main():
    p = argparse.ArgumentParser()
    p.add_argument("--requests", type=int, default=100)
    p.add_argument("--rate", type=float, default=16.0)
    p.add_argument("--input-len", type=int, default=384)
    p.add_argument("--output-len", type=int, default=24)
    p.add_argument("--prefix-groups", type=int, default=8)
    p.add_argument("--speedup", type=float, default=1.0)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--slo-ttft", type=float, default=2.0)
    p.add_argument("--slo-itl", type=float, default=0.025)
    p.add_argument("--trace-out", default="",
                   help="record the run's timeline spans (obs/) and dump "
                        "a Perfetto-loadable Chrome trace here; also "
                        "prints the obs.report gap-attribution line")
    args = p.parse_args()

    tracer = None
    if args.trace_out:
        from dynamo_tpu import obs

        tracer = obs.Tracer(service="bench_serving",
                            ring=4 * obs.DEFAULT_RING,
                            out_path=args.trace_out).install()

    rows = synthesize(args.requests, rate_rps=args.rate,
                      input_len=args.input_len, output_len=args.output_len,
                      block_size=BLOCK, prefix_groups=args.prefix_groups,
                      seed=11)

    agg = await bench_agg(rows, args.workers, args)
    print(json.dumps({"config": f"agg-{args.workers}w",
                      **agg.summary(args.slo_ttft, args.slo_itl)}))
    dis = await bench_disagg(rows, max(1, args.workers // 2),
                             max(1, args.workers // 2), args)
    print(json.dumps({
        "config": f"disagg-{max(1, args.workers // 2)}p"
                  f"{max(1, args.workers // 2)}d",
        **dis.summary(args.slo_ttft, args.slo_itl),
    }))

    if tracer is not None:
        from dynamo_tpu.obs.report import report_paths

        path = tracer.dump()
        tracer.uninstall()
        if path is None:
            print(json.dumps({"config": "trace",
                              "error": f"trace dump to "
                                       f"{args.trace_out!r} failed"}))
        else:
            print(json.dumps({"config": "trace", "trace_out": path,
                              **report_paths([path])["gap"]}))


if __name__ == "__main__":
    asyncio.run(main())
