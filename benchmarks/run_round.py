"""Bench round driver: one command cashes in a whole round.

Round r07 hardens the cache fabric r06 built: every persisted/
transferred KV block now carries a crc32 footer, checksum failures
quarantine the blob and fall back to recompute, and per-tier circuit
breakers bound how much a failing shared mount can cost.  The kernel/
serving benches carry over from r06:

  prefill   bench_prefill_phases.py --impl ab packed
            gate[tpu]: packed-Pallas est MFU >= 0.4
  kv_quant  bench_kv_quant.py (dtype x impl decode rows)
            gate[tpu]: int8-Pallas decode tok/s >= bf16-Pallas
  serving   bench_serving.py --overlap ab
            gate[tpu]: zero mid-serving compiles
            (dynamo_engine_serving_compiles_total stays 0)

plus the benches that emit their own gated line, adopted verbatim
(indexer, global_router, prefix_fleet, and — new this round —
chaos_cache, the KV-integrity A/B: byte-identical serving under
injected G4 corruption + stalls, every corruption attributed in the
ledger, breaker tripped, p90 TTFT bounded by recompute).

Each bench contributes ONE summary JSON line to stdout:

  {"bench": ..., "round": "r07", "mode": "smoke"|"tpu",
   "gates": [{"name", "target", "value", "status"}...], "result": {...}}

Off-TPU every bench still runs end to end at smoke scale (tiny model,
interpret-mode kernels, mocker serving) so the driver is tier-1
testable — rows are labeled mode=smoke and every gate reports
status=skipped_smoke instead of pass/fail.  On a chip (--mode tpu or
auto-detected) the gates are enforced: any fail exits nonzero.

    python benchmarks/run_round.py [--mode auto|smoke|tpu] [--only ...]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_DIR = os.path.join(REPO, "benchmarks")

ROUND = "r07"
TARGET_PREFILL_MFU = 0.4

# per-bench argv at each scale: smoke keeps every bench CPU-runnable
# in seconds (tiny geometry, interpret kernels, short mocker trace);
# tpu is the serving geometry the round's numbers are quoted at
BENCH_ARGS = {
    "prefill": {
        "script": "bench_prefill_phases.py",
        "smoke": ["packed", "--impl", "ab", "--model", "tiny",
                  "--tokens", "64", "--seqs", "2", "--ctx-blocks", "4",
                  "--block", "16"],
        "tpu": ["packed", "--impl", "ab"],
    },
    "kv_quant": {
        "script": "bench_kv_quant.py",
        "smoke": ["--batch", "2", "--ctx", "64", "--steps", "4",
                  "--iters", "1", "--parity-seqs", "1"],
        "tpu": ["--model", "llama-3b", "--ctx", "2048", "--block", "128",
                "--batch", "8", "--steps", "32"],
    },
    "serving": {
        "script": "bench_serving.py",
        "smoke": ["--overlap", "ab", "--requests", "16", "--rate", "32",
                  "--speedup", "4"],
        "tpu": ["--overlap", "ab"],
    },
    "indexer": {
        "script": "bench_indexer.py",
        "smoke": ["--mode", "smoke", "--events", "4000",
                  "--queries", "4000", "--parity-ops", "500"],
        "tpu": ["--mode", "tpu"],
    },
    "global_router": {
        "script": "bench_global_router.py",
        "smoke": ["--mode", "smoke"],
        "tpu": ["--mode", "tpu"],
    },
    "prefix_fleet": {
        "script": "bench_prefix_fleet.py",
        "smoke": ["--mode", "smoke"],
        "tpu": ["--mode", "tpu"],
    },
    "chaos_cache": {
        "script": "bench_chaos_cache.py",
        "smoke": ["--mode", "smoke"],
        "tpu": ["--mode", "tpu"],
    },
}


def detect_mode() -> str:
    try:
        import jax

        return ("tpu" if any(d.platform == "tpu" for d in jax.devices())
                else "smoke")
    except Exception:
        return "smoke"


def run_bench(name: str, argv, timeout_s: float):
    """Subprocess one bench and parse its stdout JSON lines."""
    proc = subprocess.run(
        [sys.executable, os.path.join(BENCH_DIR, name), *argv],
        capture_output=True, text=True, timeout=timeout_s,
        env={**os.environ, "PYTHONPATH": REPO})
    lines = []
    for ln in proc.stdout.splitlines():
        if ln.startswith("{"):
            try:
                lines.append(json.loads(ln))
            except ValueError:
                pass
    return proc, lines


def gate(name: str, target: str, value, ok, enforced: bool) -> dict:
    """One acceptance-gate row: in tpu mode pass/fail (fail flunks the
    round), in smoke mode the gate is still PRESENT in the JSON but
    labeled skipped — interpret-mode/mocker numbers must never
    satisfy (or flunk) a chip bar."""
    if not enforced:
        status = "skipped_smoke"
    elif value is None:
        status = "fail_missing"
    else:
        status = "pass" if ok else "fail"
    return {"name": name, "target": target, "value": value,
            "status": status}


def eval_prefill(lines, enforced):
    row = next((l for l in lines if l.get("bench") == "prefill_phases"),
               None)
    impls = (row or {}).get("impls", {})
    pal = impls.get("pallas") or impls.get("pallas_interpret") or {}
    mfu = pal.get("est_mfu")
    gates = [gate("prefill_pallas_mfu", f">= {TARGET_PREFILL_MFU}", mfu,
                  mfu is not None and mfu >= TARGET_PREFILL_MFU,
                  enforced)]
    return gates, row


def eval_kv_quant(lines, enforced):
    row = next((l for l in lines if l.get("bench") == "kv_quant"), None)
    tok = {}
    for r in (row or {}).get("decode", {}).get("rows", []):
        tok[(r["kv_dtype"], r["attn_impl"])] = r["tok_s"]
    pallas = (row or {}).get("decode", {}).get("pallas_impl", "pallas")
    i8, b16 = tok.get(("int8", pallas)), tok.get(("bf16", pallas))
    val = (None if i8 is None or b16 is None
           else round(i8 / max(b16, 1e-9), 3))
    gates = [gate("int8_pallas_ge_bf16", "tok/s ratio >= 1.0", val,
                  val is not None and val >= 1.0, enforced)]
    return gates, row


def eval_serving(lines, enforced):
    # one driver line summarizes BOTH overlap modes: keep the overlap
    # row (the serving configuration) as the headline result and gate
    # on mid-serving compiles across every topology row
    rows = [l for l in lines if "roofline" in l]
    compiles = sum(sum(l["roofline"].get("serving_compiles", {}).values())
                   for l in rows)
    gates = [gate("zero_mid_serving_compiles", "== 0",
                  compiles if rows else None,
                  bool(rows) and compiles == 0, enforced)]
    head = next((l for l in reversed(rows)
                 if "overlap" in l.get("config", "")), None)
    return gates, head or (rows[-1] if rows else None)


def eval_gated_line(bench_name):
    """Benches that emit their own gated line (indexer, global_router,
    prefix_fleet, chaos_cache): adopt their gates verbatim —
    enforcement already followed the --mode flag the driver passed
    down."""
    def _eval(lines, enforced):
        row = next((l for l in lines if l.get("bench") == bench_name),
                   None)
        if row is None:
            return [gate(f"{bench_name}_summary_line", "present", None,
                         False, True)], None
        return row.get("gates", []), row.get("result")
    return _eval


EVALS = {"prefill": eval_prefill, "kv_quant": eval_kv_quant,
         "serving": eval_serving,
         "indexer": eval_gated_line("indexer"),
         "global_router": eval_gated_line("global_router"),
         "prefix_fleet": eval_gated_line("prefix_fleet"),
         "chaos_cache": eval_gated_line("chaos_cache")}


def main() -> int:
    p = argparse.ArgumentParser(
        description="one-shot bench round driver (see module docstring)")
    p.add_argument("--mode", default="auto",
                   choices=["auto", "smoke", "tpu"],
                   help="auto = tpu when a TPU backend is attached, "
                        "else smoke (tiny geometry, gates skipped)")
    p.add_argument("--only", nargs="*", choices=sorted(BENCH_ARGS),
                   default=None,
                   help="run a subset of the round's benches")
    p.add_argument("--timeout-s", type=float, default=1800.0,
                   help="per-bench subprocess timeout")
    args = p.parse_args()

    mode = detect_mode() if args.mode == "auto" else args.mode
    enforced = mode == "tpu"
    failed = []
    for bench in (args.only or sorted(BENCH_ARGS)):
        spec = BENCH_ARGS[bench]
        proc, lines = run_bench(spec["script"], spec[mode],
                                args.timeout_s)
        gates, result = EVALS[bench](lines, enforced)
        if proc.returncode != 0:
            # the bench's own in-process asserts (parity, capacity,
            # int8>=bf16) count as round gates too
            gates.append({"name": "bench_exit", "target": "rc == 0",
                          "value": proc.returncode, "status": "fail"})
            sys.stderr.write(proc.stdout[-2000:] +
                             proc.stderr[-2000:] + "\n")
        print(json.dumps({
            "bench": bench, "round": ROUND, "mode": mode,
            "gates": gates,
            **({"result": result} if result is not None else {}),
        }), flush=True)
        failed += [g["name"] for g in gates if g["status"].
                   startswith("fail")]
    if failed:
        sys.stderr.write(f"round {ROUND} gate failures: "
                         f"{', '.join(failed)}\n")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
