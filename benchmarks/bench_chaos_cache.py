"""KV integrity closed loop: serving under G4 corruption and stalls.

The ISSUE-20 acceptance scenario, end to end in one process and two
arms.  Each arm builds a warm mocker fleet behind a KV-routed frontend
sharing one in-process `SimObjectStore` with NO host tier, so G1
evictions spill straight to G4 and the measure wave onboards from the
shared store — the exact path the chaos arm then attacks:

  1. *populate* — every tenant's prefix lands in some worker's G1,
  2. *churn* — unique junk prompts flood G1 so the LRU spills the
     tenant prefixes into the shared object store,
  3. *measure* — the same tenants return.  The control arm serves them
     off a healthy store; the chaos arm runs the identical trace with a
     `kvbm.object_io` chaos plane installed: the first lookups return
     tampered payloads (byte flips the crc32 verdict must catch), then
     a stall burst sized past the per-worker breaker threshold hangs
     past the I/O deadline and trips a G4 circuit breaker.

A corrupted lookup must quarantine the blob fleet-wide, publish
removed(g4), attribute the event in the KV ledger as corrupt{g4}, and
fall back to prefill recompute; a stalled lookup must cost at most the
I/O deadline and feed the breaker.  Neither may ever reach a token
stream.

Gates (per r07 JSON line):

  * byte identity: the measure wave's token streams must match across
    arms exactly — integrity degradation may add zero token-level
    noise (enforced in every mode)
  * mechanism (enforced in every mode): store populated by churn;
    control arm onboarded > 0 blocks from G4 (the attacked path is
    real); > 0 stall injections with matching timeout counters and a
    tripped breaker; every materialized corruption attributed — ledger
    corrupt{g4} count == engine quarantine count > 0; every worker's
    ledger audit clean in BOTH arms (corruption records must not
    unbalance the books)
  * timing (chip bar, skipped at smoke scale): chaos-arm p90 TTFT
    <= 2x the control arm — degraded mode must stay bounded by
    recompute, never wedge behind the broken tier

Smoke scale: 2 workers x 4 tenants, seconds on CPU.  TPU/full scale:
4 workers x 8 tenants at real-time step pacing.
"""

import argparse
import asyncio
import json
import random
import time
import uuid
import zlib

import aiohttp

from dynamo_tpu import chaos
from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.mocker.kv_cache_sim import SimObjectStore
from dynamo_tpu.router.kv_router import make_kv_route_factory
from dynamo_tpu.runtime import DistributedRuntime, RouterMode, RuntimeConfig

MODEL = "bench-model"
BLOCK = 16
PREFIX_BLOCKS = 12          # shared prefix: 192 byte-tokens
SUFFIX_CHARS = 2 * BLOCK    # per-stream divergence: 2 blocks
JUNK_CHARS = 16 * BLOCK     # each junk stream burns 16 unique blocks

# timing model (seconds): recompute is 3.2 ms per block, onboarding
# from the store 2 ms — a corrupted/stalled lookup falls back to the
# 1.6x recompute price, which is what keeps the degraded arm inside
# the p90 <= 2x bound the gate asserts (the tier still wins when
# healthy; when poisoned, falling back must stay bounded by recompute)
PREFILL_S_PER_TOKEN = 0.0002
G4_ONBOARD_S_PER_BLOCK = 0.002
G4_DEADLINE_S = 0.01        # simulated per-lookup deadline (stall cost)
BREAKER_THRESHOLD = 3

# chaos schedule for the measure wave, fully count-based so smoke runs
# are deterministic: the first CORRUPT_N object-store lookups return
# tampered payloads (the very first is a just-churned tenant block, so
# at least one quarantine always materializes), then a stall burst —
# every subsequent lookup stalls until the burst drains, so SOME
# worker's breaker must see `threshold` consecutive failures and trip
# (the burst is sized at 2x threshold-per-worker because the router
# spreads the wave across the fleet's independent breakers)
CORRUPT_N = 6

SCALES = {
    "smoke": dict(workers=2, tenants=4, warm_streams=24,
                  junk_streams=32, measure_streams=24, concurrency=8,
                  max_tokens=8, num_blocks=96, speedup=4.0),
    "tpu": dict(workers=4, tenants=8, warm_streams=96,
                junk_streams=128, measure_streams=96, concurrency=32,
                max_tokens=16, num_blocks=256, speedup=1.0),
}


def tenant_prefixes(scale: dict) -> list:
    rng = random.Random(7)
    alphabet = "abcdefghijklmnopqrstuvwxyz "
    return ["".join(rng.choice(alphabet)
                    for _ in range(PREFIX_BLOCKS * BLOCK))
            for _ in range(scale["tenants"])]


def wave(prefixes: list, streams: int, tag: str, scale: dict) -> list:
    rng = random.Random(zlib.crc32(tag.encode()))
    alphabet = "abcdefghijklmnopqrstuvwxyz "
    reqs = []
    for s in range(streams):
        t = s % len(prefixes)
        suffix = "".join(rng.choice(alphabet)
                         for _ in range(SUFFIX_CHARS))
        key = f"{tag}-t{t}s{s}"
        reqs.append({
            "key": key,
            "body": {
                "model": MODEL,
                "prompt": prefixes[t] + suffix,
                "max_tokens": scale["max_tokens"],
                "stream": True,
                "seed": zlib.crc32(key.encode()) & 0x7FFFFFFF,
            },
        })
    return reqs


def junk_wave(scale: dict) -> list:
    rng = random.Random(13)
    alphabet = "abcdefghijklmnopqrstuvwxyz "
    reqs = []
    for s in range(scale["junk_streams"]):
        key = f"junk-{s}"
        reqs.append({
            "key": key,
            "body": {
                "model": MODEL,
                "prompt": "".join(rng.choice(alphabet)
                                  for _ in range(JUNK_CHARS)),
                "max_tokens": 4,
                "stream": True,
                "seed": zlib.crc32(key.encode()) & 0x7FFFFFFF,
            },
        })
    return reqs


async def start_fleet(cluster: str, n_workers: int, engine_kwargs: dict):
    ns = "fleet"
    wrt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc", namespace=ns),
        cluster_id=cluster).start()
    workers = []
    for _ in range(n_workers):
        workers.append(await MockerWorker(
            wrt, MockEngineArgs(**engine_kwargs), namespace=ns).start())
    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc", namespace=ns),
        cluster_id=cluster).start()
    manager = ModelManager()
    watcher = await ModelWatcher(
        rt, manager, router_mode=RouterMode.KV,
        make_route=make_kv_route_factory(
            rt, overlap_score_weight=1.0, temperature=0.0),
        namespaces={ns}).start()
    svc = await HttpService(rt, manager, host="127.0.0.1", port=0,
                            advertise=True).start()
    for _ in range(200):
        if manager.get(MODEL):
            break
        await asyncio.sleep(0.02)
    assert manager.get(MODEL), f"frontend never saw {MODEL}"
    return {"wrt": wrt, "workers": workers, "rt": rt,
            "manager": manager, "watcher": watcher, "svc": svc,
            "port": svc._runner.addresses[0][1]}


async def stop_fleet(pool: dict) -> None:
    await pool["svc"].close()
    await pool["watcher"].close()
    await pool["rt"].shutdown()
    for w in pool["workers"]:
        await w.close()
    await pool["wrt"].shutdown()


async def drive(url: str, reqs: list, concurrency: int) -> dict:
    sem = asyncio.Semaphore(concurrency)
    out = {}

    async def one(session, req):
        async with sem:
            t0 = time.monotonic()
            ttft = None
            text = []
            async with session.post(f"{url}/v1/completions",
                                    json=req["body"]) as r:
                assert r.status == 200, (r.status, await r.text())
                async for raw in r.content:
                    line = raw.decode().strip()
                    if not line.startswith("data:"):
                        continue
                    data = line[5:].strip()
                    if data == "[DONE]":
                        break
                    if ttft is None:
                        ttft = time.monotonic() - t0
                    obj = json.loads(data)
                    for ch in obj.get("choices", ()):
                        if ch.get("text"):
                            text.append(ch["text"])
            out[req["key"]] = {"text": "".join(text), "ttft_s": ttft}

    conn = aiohttp.TCPConnector(limit=concurrency + 8)
    async with aiohttp.ClientSession(connector=conn) as session:
        await asyncio.gather(*(one(session, r) for r in reqs))
    return out


def quantile(vals, p):
    vals = sorted(vals)
    if not vals:
        return None
    return vals[min(int(p * len(vals)), len(vals) - 1)]


def fleet_integrity(pool: dict) -> dict:
    """Quarantine/timeout counters, ledger corrupt attribution, breaker
    trips and audit cleanliness across every engine of every worker."""
    quarantined = timeouts = errors = trips = 0
    ledger_corrupt = 0
    audits_total = audits_clean = 0
    onboard_g4 = 0
    for w in pool["workers"]:
        for e in getattr(w, "engines", []):
            onboard_g4 += e.metrics.get("kv_onboard_g4", 0)
            for (tier, action), n in e.kv_integrity_counters().items():
                if action == "quarantine":
                    quarantined += n
                elif action == "timeout":
                    timeouts += n
                else:
                    errors += n
            if e.kv_breaker is not None:
                trips += e.kv_breaker.trips("g4")
            if e.kv_ledger is not None:
                by_kind = e.kv_ledger.violations_by_kind()
                ledger_corrupt += by_kind.get("corrupt", {}).get("g4", 0)
                audits_total += 1
                if e.audit_kv(where="bench").get("clean"):
                    audits_clean += 1
    return {"quarantined": quarantined, "timeouts": timeouts,
            "errors": errors, "breaker_trips": trips,
            "ledger_corrupt_g4": ledger_corrupt,
            "onboard_g4": onboard_g4,
            "audits": {"workers": audits_total, "clean": audits_clean}}


async def run_arm(mode: str, with_chaos: bool) -> dict:
    scale = SCALES[mode]
    cluster = uuid.uuid4().hex
    store = SimObjectStore()
    common = dict(model_name=MODEL, block_size=BLOCK,
                  num_blocks=scale["num_blocks"],
                  base_step_s=0.0005,
                  prefill_s_per_token=PREFILL_S_PER_TOKEN,
                  decode_s_per_seq=0.0,
                  speedup_ratio=scale["speedup"],
                  kv_ledger=True,
                  host_blocks=0,  # G1 evictions spill straight to G4
                  object_store=store,
                  g4_onboard_s_per_block=G4_ONBOARD_S_PER_BLOCK,
                  g4_deadline_s=G4_DEADLINE_S,
                  kv_breaker_threshold=BREAKER_THRESHOLD,
                  kv_breaker_cooldown_s=0.5)
    fleet = await start_fleet(cluster, scale["workers"], common)
    try:
        prefixes = tenant_prefixes(scale)
        url = f"http://127.0.0.1:{fleet['port']}"
        await drive(url, wave(prefixes, scale["warm_streams"],
                              "populate", scale), scale["concurrency"])
        await drive(url, junk_wave(scale), scale["concurrency"])
        store_blobs = len(store)

        plane = None
        if with_chaos:
            stall_burst = 2 * BREAKER_THRESHOLD * scale["workers"]
            plane = chaos.ChaosPlane(seed=11)
            plane.rule("kvbm.object_io", "corrupt", times=CORRUPT_N,
                       match="get:")
            plane.rule("kvbm.object_io", "stall", times=stall_burst,
                       match="get:")
            plane.install()
        try:
            measured = await drive(
                url, wave(prefixes, scale["measure_streams"],
                          "measure", scale), scale["concurrency"])
        finally:
            if plane is not None:
                plane.uninstall()

        ttfts = [v["ttft_s"] for v in measured.values()
                 if v["ttft_s"] is not None]
        return {
            "arm": "chaos" if with_chaos else "control",
            "store_blobs": store_blobs,
            "ttft_ms": {
                "p50": round((quantile(ttfts, 0.5) or 0) * 1e3, 2),
                "p90": round((quantile(ttfts, 0.9) or 0) * 1e3, 2),
            },
            "p90_ttft_s": quantile(ttfts, 0.9),
            "integrity": fleet_integrity(fleet),
            "injections": {
                "stall": sum(1 for i in plane.injections
                             if i.action == "stall"),
                "corrupt": sum(1 for i in plane.injections
                               if i.action == "corrupt"),
            } if plane is not None else {},
            "texts": {k: v["text"] for k, v in measured.items()},
            "empty_streams": sum(1 for v in measured.values()
                                 if not v["text"]),
        }
    finally:
        await stop_fleet(fleet)


async def run(mode: str) -> dict:
    ctl = await run_arm(mode, with_chaos=False)
    cha = await run_arm(mode, with_chaos=True)
    identical = (ctl.pop("texts") == cha.pop("texts")
                 and ctl["empty_streams"] == 0
                 and cha["empty_streams"] == 0)
    ratio = None
    if ctl["p90_ttft_s"] and cha["p90_ttft_s"]:
        ratio = round(cha["p90_ttft_s"] / ctl["p90_ttft_s"], 3)
    return {"mode": mode, "scale": SCALES[mode],
            "byte_identical": identical, "p90_ttft_ratio": ratio,
            "control": ctl, "chaos": cha}


def main() -> int:
    p = argparse.ArgumentParser(
        description="KV integrity closed loop: serving under G4 "
                    "corruption + stalls (see module docstring)")
    p.add_argument("--mode", default="smoke", choices=["smoke", "tpu"])
    args = p.parse_args()
    enforced = args.mode == "tpu"
    result = asyncio.run(run(args.mode))

    def g(name, target, value, ok, always=False):
        status = (("pass" if ok else "fail")
                  if (enforced or always) else "skipped_smoke")
        if value is None:
            status = "fail_missing" if (enforced or always) else \
                "skipped_smoke"
        return {"name": name, "target": target, "value": value,
                "status": status}

    ctl, cha = result["control"], result["chaos"]
    ci, hi = ctl["integrity"], cha["integrity"]
    gates = [
        # mechanism gates hold in every mode: degraded serving must add
        # zero token-level noise, the attacked path must be real, every
        # materialized corruption must be quarantined AND attributed,
        # and the books must stay balanced through all of it
        g("chaos_cache_byte_identity",
          "measure-wave bytes identical across arms",
          result["byte_identical"], result["byte_identical"],
          always=True),
        g("chaos_cache_store_populated", "> 0 blobs after churn",
          cha["store_blobs"], cha["store_blobs"] > 0, always=True),
        g("chaos_cache_control_onboard_g4", "> 0 blocks from G4",
          ci["onboard_g4"], ci["onboard_g4"] > 0, always=True),
        g("chaos_cache_stall_observed",
          "stalls injected, timeouts counted, breaker tripped",
          {"injected": cha["injections"].get("stall", 0),
           "timeouts": hi["timeouts"], "trips": hi["breaker_trips"]},
          (cha["injections"].get("stall", 0) > 0
           and hi["timeouts"] > 0 and hi["breaker_trips"] > 0),
          always=True),
        g("chaos_cache_corrupt_attributed",
          "ledger corrupt{g4} == quarantines > 0",
          {"quarantined": hi["quarantined"],
           "ledger_corrupt_g4": hi["ledger_corrupt_g4"]},
          (hi["quarantined"] > 0
           and hi["ledger_corrupt_g4"] == hi["quarantined"]),
          always=True),
        g("chaos_cache_ledger_audit", "every worker audit clean",
          ci["audits"]["clean"] + hi["audits"]["clean"],
          (ci["audits"]["clean"] == ci["audits"]["workers"]
           and hi["audits"]["clean"] == hi["audits"]["workers"]),
          always=True),
        # chip bar: degraded mode stays bounded by recompute — the
        # chaos arm may cost at most 2x the healthy arm at p90
        g("chaos_cache_p90_ttft_ratio", "<= 2.0",
          result["p90_ttft_ratio"],
          result["p90_ttft_ratio"] is not None
          and result["p90_ttft_ratio"] <= 2.0),
    ]
    print(json.dumps({
        "bench": "chaos_cache", "round": "r07", "mode": args.mode,
        "gates": gates, "result": result,
    }), flush=True)
    return 1 if any(x["status"] == "fail" for x in gates) else 0


if __name__ == "__main__":
    raise SystemExit(main())
