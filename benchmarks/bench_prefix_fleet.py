"""Fleet-wide prefix cache closed loop: cold-worker onboarding A/B.

The ISSUE-19 acceptance scenario, end to end in one process and two
arms.  Each arm builds a warm mocker fleet behind a KV-routed frontend
and drives three waves of multi-tenant shared-prefix traffic:

  1. *populate* — every tenant's prefix lands in some worker's G1,
  2. *churn* — unique junk prompts flood G1 so the LRU demotes the
     tenant prefixes down the tier ladder (G1 -> G2 host LRU -> G4
     shared object store; the arm under test shares one in-process
     `SimObjectStore` across the whole fleet),
  3. *measure* — the same tenants return and the steady-state warm
     TTFT p50 is taken client-side.

Then a COLD worker starts in a separate namespace behind its own
frontend — the planner-scale-up stand-in: empty G1/G2, but (in the G4
arm) the same shared store — and the cold wave measures the FIRST
request per tenant, i.e. the cold-start TTFT before any G1 reuse
exists.  The control arm runs the identical trace with the tier ladder
disabled, so the same first requests pay full prefill recompute.

The cold-start penalty is self-controlled: first-per-tenant TTFT p50
over the NON-first p50 of the same wave on the same worker (its own
steady state, identical concurrency and queue) — immune to the
warm-fleet/cold-worker load asymmetry and to the KV router's overlap
concentration, which both skew a cross-fleet ratio.

Gates (per r06 JSON line):

  * byte identity: the cold wave's token streams must match across
    arms exactly — onboarding may add zero token-level noise
    (enforced in every mode, like the grouter bench)
  * mechanism: store populated by churn; cold worker onboarded >0
    blocks from G4; the warm frontend's tiered index saw G4 blocks
    (the routing-visible half of the subsystem); every worker's
    ledger audit clean (enforced in every mode)
  * timing (chip bars, skipped at smoke scale): cold-start penalty
    <= 1.5x in the G4 arm (onboarding ~= already-warm) and > 3x in
    the control arm — the TTFT gap the tier exists to close

Smoke scale: 3 warm workers x 4 tenants, seconds on CPU.  TPU/full
scale: 8 workers x 8 tenants at real-time step pacing.
"""

import argparse
import asyncio
import json
import random
import time
import uuid
import zlib

import aiohttp

from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.mocker.kv_cache_sim import SimObjectStore
from dynamo_tpu.router.kv_router import make_kv_route_factory
from dynamo_tpu.runtime import DistributedRuntime, RouterMode, RuntimeConfig

MODEL = "bench-model"
BLOCK = 16
PREFIX_BLOCKS = 12          # shared prefix: 192 byte-tokens
SUFFIX_CHARS = 2 * BLOCK    # per-stream divergence: 2 blocks
JUNK_CHARS = 16 * BLOCK     # each junk stream burns 16 unique blocks

# timing model (seconds).  Recompute is block_size * prefill_s = 16 ms
# per block; onboarding a block from the shared store costs 0.5 ms —
# the 32x gap the cold-start ratio gate cashes in.
PREFILL_S_PER_TOKEN = 0.001
G2_ONBOARD_S_PER_BLOCK = 0.0002
G4_ONBOARD_S_PER_BLOCK = 0.0005

SCALES = {
    "smoke": dict(warm_workers=3, tenants=4, warm_streams=24,
                  measure_streams=24, cold_streams=24, junk_streams=48,
                  concurrency=12, max_tokens=8, num_blocks=160,
                  host_blocks=16, speedup=4.0),
    "tpu": dict(warm_workers=8, tenants=8, warm_streams=128,
                measure_streams=128, cold_streams=128, junk_streams=320,
                concurrency=64, max_tokens=16, num_blocks=512,
                host_blocks=48, speedup=1.0),
}


def tenant_prefixes(scale: dict) -> list:
    rng = random.Random(7)
    alphabet = "abcdefghijklmnopqrstuvwxyz "
    return ["".join(rng.choice(alphabet)
                    for _ in range(PREFIX_BLOCKS * BLOCK))
            for _ in range(scale["tenants"])]


def wave(prefixes: list, streams: int, tag: str, scale: dict) -> list:
    """One wave of shared-prefix traffic, round-robin over tenants so
    the first len(prefixes) entries are exactly one request per tenant
    — the cold wave's `first` markers (cold-START TTFT, before any G1
    reuse exists on the new worker)."""
    rng = random.Random(zlib.crc32(tag.encode()))
    alphabet = "abcdefghijklmnopqrstuvwxyz "
    reqs = []
    for s in range(streams):
        t = s % len(prefixes)
        suffix = "".join(rng.choice(alphabet)
                         for _ in range(SUFFIX_CHARS))
        key = f"{tag}-t{t}s{s}"
        reqs.append({
            "key": key, "tenant": t, "first": s < len(prefixes),
            "body": {
                "model": MODEL,
                "prompt": prefixes[t] + suffix,
                "max_tokens": scale["max_tokens"],
                "stream": True,
                "seed": zlib.crc32(key.encode()) & 0x7FFFFFFF,
            },
        })
    return reqs


def junk_wave(scale: dict) -> list:
    """Unique single-use prompts that overflow every warm worker's G1 +
    G2 capacity, forcing the tenant prefixes down the demotion chain."""
    rng = random.Random(13)
    alphabet = "abcdefghijklmnopqrstuvwxyz "
    reqs = []
    for s in range(scale["junk_streams"]):
        key = f"junk-{s}"
        reqs.append({
            "key": key, "tenant": -1, "first": False,
            "body": {
                "model": MODEL,
                "prompt": "".join(rng.choice(alphabet)
                                  for _ in range(JUNK_CHARS)),
                "max_tokens": 4,
                "stream": True,
                "seed": zlib.crc32(key.encode()) & 0x7FFFFFFF,
            },
        })
    return reqs


async def start_ns(cluster: str, ns: str, n_workers: int,
                   engine_kwargs: dict):
    """One namespace: worker runtime + one KV-routed frontend.  The
    cold namespace gets its own so the warm router never places traffic
    on the joining worker — the cold TTFT measurement stays clean."""
    wrt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc", namespace=ns),
        cluster_id=cluster).start()
    workers = []
    for _ in range(n_workers):
        workers.append(await MockerWorker(
            wrt, MockEngineArgs(**engine_kwargs), namespace=ns).start())
    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc", namespace=ns),
        cluster_id=cluster).start()
    manager = ModelManager()
    watcher = await ModelWatcher(
        rt, manager, router_mode=RouterMode.KV,
        make_route=make_kv_route_factory(
            rt, overlap_score_weight=1.0, temperature=0.0),
        namespaces={ns}).start()
    svc = await HttpService(rt, manager, host="127.0.0.1", port=0,
                            advertise=True).start()
    for _ in range(200):
        if manager.get(MODEL):
            break
        await asyncio.sleep(0.02)
    assert manager.get(MODEL), f"frontend in {ns} never saw {MODEL}"
    return {"ns": ns, "wrt": wrt, "workers": workers, "rt": rt,
            "manager": manager, "watcher": watcher, "svc": svc,
            "port": svc._runner.addresses[0][1]}


async def stop_ns(pool: dict) -> None:
    await pool["svc"].close()
    await pool["watcher"].close()
    await pool["rt"].shutdown()
    for w in pool["workers"]:
        await w.close()
    await pool["wrt"].shutdown()


async def drive(url: str, reqs: list, concurrency: int) -> dict:
    sem = asyncio.Semaphore(concurrency)
    out = {}

    async def one(session, req):
        async with sem:
            t0 = time.monotonic()
            ttft = None
            text = []
            async with session.post(f"{url}/v1/completions",
                                    json=req["body"]) as r:
                assert r.status == 200, (r.status, await r.text())
                async for raw in r.content:
                    line = raw.decode().strip()
                    if not line.startswith("data:"):
                        continue
                    data = line[5:].strip()
                    if data == "[DONE]":
                        break
                    if ttft is None:
                        ttft = time.monotonic() - t0
                    obj = json.loads(data)
                    for ch in obj.get("choices", ()):
                        if ch.get("text"):
                            text.append(ch["text"])
            out[req["key"]] = {
                "text": "".join(text),
                "ttft_s": ttft,
                "first": req["first"],
            }

    conn = aiohttp.TCPConnector(limit=concurrency + 8)
    async with aiohttp.ClientSession(connector=conn) as session:
        await asyncio.gather(*(one(session, r) for r in reqs))
    return out


def quantile(vals, p):
    vals = sorted(vals)
    if not vals:
        return None
    return vals[min(int(p * len(vals)), len(vals) - 1)]


def worker_onboards(workers: list) -> dict:
    out = {"g2": 0, "g4": 0}
    for w in workers:
        for e in getattr(w, "engines", []):
            out["g2"] += e.metrics.get("kv_onboard_g2", 0)
            out["g4"] += e.metrics.get("kv_onboard_g4", 0)
    return out


def audits_clean(pools: list) -> dict:
    """Fresh on-demand ledger audit across every worker of every
    namespace — the 0-violation acceptance bar."""
    total, clean = 0, 0
    for pool in pools:
        for w in pool["workers"]:
            dbg = w.kv_debug()
            if not dbg.get("enabled", True):
                continue
            total += 1
            audits = [dbg.get("audit", {})] + [
                r["audit"] for r in dbg.get("ranks", [])]
            if all(a.get("clean") for a in audits if a):
                clean += 1
    return {"workers": total, "clean": clean}


async def run_arm(mode: str, g4: bool) -> dict:
    scale = SCALES[mode]
    cluster = uuid.uuid4().hex
    store = SimObjectStore() if g4 else None
    common = dict(model_name=MODEL, block_size=BLOCK,
                  num_blocks=scale["num_blocks"],
                  base_step_s=0.0005,
                  prefill_s_per_token=PREFILL_S_PER_TOKEN,
                  decode_s_per_seq=0.0,
                  speedup_ratio=scale["speedup"],
                  kv_ledger=True,
                  host_blocks=scale["host_blocks"] if g4 else 0,
                  object_store=store,
                  g2_onboard_s_per_block=G2_ONBOARD_S_PER_BLOCK,
                  g4_onboard_s_per_block=G4_ONBOARD_S_PER_BLOCK)
    warm = await start_ns(cluster, "warm", scale["warm_workers"], common)
    cold = None
    try:
        prefixes = tenant_prefixes(scale)
        url = f"http://127.0.0.1:{warm['port']}"
        await drive(url, wave(prefixes, scale["warm_streams"],
                              "populate", scale), scale["concurrency"])
        await drive(url, junk_wave(scale), scale["concurrency"])
        measured = await drive(
            url, wave(prefixes, scale["measure_streams"], "steady",
                      scale), scale["concurrency"])
        # one event-plane beat so the churn's stored(g4) batches land
        # in the frontend's tiered index before it is inspected
        await asyncio.sleep(0.3)
        store_blobs = len(store) if store is not None else 0

        # the planner-scaled joiner: empty G1/G2, shared G4 (g4 arm)
        cold = await start_ns(cluster, "cold", 1, common)
        cold_conc = max(2, scale["concurrency"]
                        // scale["warm_workers"])
        cold_out = await drive(
            f"http://127.0.0.1:{cold['port']}",
            wave(prefixes, scale["cold_streams"], "cold", scale),
            cold_conc)

        warm_ttfts = [v["ttft_s"] for v in measured.values()
                      if v["ttft_s"] is not None]
        cold_firsts = [v["ttft_s"] for v in cold_out.values()
                       if v["first"] and v["ttft_s"] is not None]
        cold_steady = [v["ttft_s"] for v in cold_out.values()
                       if not v["first"] and v["ttft_s"] is not None]
        first_p50 = quantile(cold_firsts, 0.5)
        steady_p50 = quantile(cold_steady, 0.5)
        router = (warm["svc"].debug_state().get("router") or {}).get(
            MODEL, {})
        g4_sample = None
        if store is not None:
            dbg = cold["workers"][0].kv_debug()
            g4_sample = dbg.get("g4")
        return {
            "arm": "g4" if g4 else "control",
            "warm_ttft_ms": {
                "p50": round((quantile(warm_ttfts, 0.5) or 0)
                             * 1e3, 2),
                "p99": round((quantile(warm_ttfts, 0.99) or 0)
                             * 1e3, 2),
            },
            "cold_first_ttft_ms_p50": round((first_p50 or 0) * 1e3, 2),
            "cold_steady_ttft_ms_p50": round(
                (steady_p50 or 0) * 1e3, 2),
            "cold_start_penalty": (round(first_p50 / steady_p50, 3)
                                   if steady_p50 and first_p50
                                   else None),
            "store_blobs": store_blobs,
            "router_g4_blocks": router.get("g4_blocks", 0),
            "warm_onboards": worker_onboards(warm["workers"]),
            "cold_onboards": worker_onboards(cold["workers"]),
            "audits": audits_clean([warm, cold]),
            **({"cold_g4_residency": g4_sample} if g4_sample else {}),
            "cold_texts": {k: v["text"] for k, v in cold_out.items()},
            "empty_streams": sum(1 for v in cold_out.values()
                                 if not v["text"]),
        }
    finally:
        if cold is not None:
            await stop_ns(cold)
        await stop_ns(warm)


async def run(mode: str) -> dict:
    arm_g4 = await run_arm(mode, g4=True)
    arm_ctl = await run_arm(mode, g4=False)
    identical = (arm_g4.pop("cold_texts") == arm_ctl.pop("cold_texts")
                 and arm_g4["empty_streams"] == 0
                 and arm_ctl["empty_streams"] == 0)
    return {"mode": mode, "scale": SCALES[mode],
            "byte_identical": identical, "g4": arm_g4,
            "control": arm_ctl}


def main() -> int:
    p = argparse.ArgumentParser(
        description="fleet-wide prefix cache cold-start closed loop "
                    "(see module docstring)")
    p.add_argument("--mode", default="smoke", choices=["smoke", "tpu"])
    args = p.parse_args()
    enforced = args.mode == "tpu"
    result = asyncio.run(run(args.mode))

    def g(name, target, value, ok, always=False):
        status = (("pass" if ok else "fail")
                  if (enforced or always) else "skipped_smoke")
        if value is None:
            status = "fail_missing" if (enforced or always) else \
                "skipped_smoke"
        return {"name": name, "target": target, "value": value,
                "status": status}

    g4, ctl = result["g4"], result["control"]
    gates = [
        # mechanism gates hold in every mode: the onboarding path must
        # add zero token-level noise and actually exercise the tier
        # ladder end to end (store <- churn, cold worker <- store,
        # router index <- stored(g4) events, ledger books balanced)
        g("prefix_fleet_byte_identity",
          "cold-wave bytes identical across arms",
          result["byte_identical"], result["byte_identical"],
          always=True),
        g("prefix_fleet_store_populated", "> 0 blobs after churn",
          g4["store_blobs"], g4["store_blobs"] > 0, always=True),
        g("prefix_fleet_cold_onboard_g4", "> 0 blocks from G4",
          g4["cold_onboards"]["g4"], g4["cold_onboards"]["g4"] > 0,
          always=True),
        g("prefix_fleet_router_g4_visible",
          "> 0 G4 blocks in warm frontend index",
          g4["router_g4_blocks"], g4["router_g4_blocks"] > 0,
          always=True),
        g("prefix_fleet_ledger_audit", "every worker audit clean",
          g4["audits"]["clean"] + ctl["audits"]["clean"],
          (g4["audits"]["clean"] == g4["audits"]["workers"]
           and ctl["audits"]["clean"] == ctl["audits"]["workers"]),
          always=True),
        # chip bars: the cold-start penalty the subsystem closes —
        # first-per-tenant TTFT over the same worker's own steady
        # state (see module docstring for why it is self-controlled)
        g("prefix_fleet_cold_start_penalty", "<= 1.5",
          g4["cold_start_penalty"],
          g4["cold_start_penalty"] is not None
          and g4["cold_start_penalty"] <= 1.5),
        g("prefix_fleet_control_cold_penalty", "> 3.0",
          ctl["cold_start_penalty"],
          ctl["cold_start_penalty"] is not None
          and ctl["cold_start_penalty"] > 3.0),
    ]
    print(json.dumps({
        "bench": "prefix_fleet", "round": "r06", "mode": args.mode,
        "gates": gates, "result": result,
    }), flush=True)
    return 1 if any(x["status"] == "fail" for x in gates) else 0


if __name__ == "__main__":
    raise SystemExit(main())
