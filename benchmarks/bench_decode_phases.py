"""Per-phase decode profiler: localize where the decode step's HBM
bandwidth goes on the bench geometry (llama-3b, B=8, ctx=2048, bf16).

Round-4 verdict: the raw decode loop reaches only 0.55 of the HBM
roofline and nothing localizes the loss.  This script times each phase
of one fused decode burst separately on the real chip:

  full        decode_multi burst (the bench.py raw loop, per-step)
  weights     transformer matmuls only (attention stubbed out) — the
              weight-streaming bound
  attn[...]   the Pallas paged-attention op alone, 28 layers x K steps,
              for several blocks_per_chunk settings
  attn_jnp    the jnp (XLA gather) attention path for comparison
  kv_write    write_token_kv scatter alone, 28 layers x K steps
  sample      argmax over [B, vocab]

and prints a table with achieved GB/s per phase vs the v5e 819 GB/s pin.

`--epilogue on|off|ab` additionally serves greedy requests through a
real JaxEngine with the fused sampling epilogue (ops/fused_sampling.py)
on/off and reports decode MBU from the same dynamo_engine_mbu{phase}
gauge the worker exports — the HBM-bound hypothesis is checked in the
same run that measures the fix, against the gauge the fleet watches.

Run on the chip:  python benchmarks/bench_decode_phases.py
"""

import argparse
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# phase selection: e.g. `python bench_decode_phases.py attn kv_write`
# (populated from argv by the __main__ block; empty = all phases)
_SEL = set()
# fused-sampling A/B: None = skip; "on"/"off"/"ab" = which engine
# epilogue modes to serve (populated from --epilogue by __main__)
EPILOGUE = None


def want(tag: str) -> bool:
    return not _SEL or tag in _SEL

from dynamo_tpu.models import llama
from dynamo_tpu.ops import paged_attention as pa
from dynamo_tpu.ops.pallas_paged_attention import paged_attention_decode_pallas

MODEL = "llama-3b"
# K=64 fused steps per dispatch: the tunneled chip charges a VARIABLE
# ~15-30ms per dispatch (measured via /tmp probes, round 5) — per-step
# numbers are mush unless each call carries ~1s of on-chip work
B, CTX, BLOCK, K = 8, 2048, 128, 64
HBM_GBPS = 819.0
# KV storage dtype (--kv-dtype): "int8" stores quantized K/V + fp32
# scale planes (quant/kv.py) — half the KV bytes the decode read streams
KV_DTYPE = "bf16"


def _sync(r):
    """Force completion with a device FETCH: on the tunneled axon backend
    block_until_ready can return before execution finishes, so timing
    must close with an actual value read (one ~35ms RTT, amortized over
    the measured calls)."""
    leaf = jax.tree_util.tree_leaves(r)[0]
    np.asarray(jax.device_get(leaf.ravel()[0]))


def timeit(fn, n=8, warm=2):
    for _ in range(warm):
        r = fn()
    _sync(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    _sync(r)
    return (time.perf_counter() - t0) / n


def epilogue_report(modes):
    """Engine-level fused-sampling A/B (--epilogue): serve B greedy
    requests through a real JaxEngine per mode and report decode MBU
    from the dynamo_engine_mbu{phase="decode"} gauge the worker itself
    exports (planner/metrics.py export_engine_gauges), not a
    bench-local byte model.  Greedy token streams must match between
    modes — the epilogue's byte-identity contract, re-proven here on
    the bench geometry."""
    import asyncio

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.planner.metrics import FpmWindow, export_engine_gauges
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    class _Gauges:
        def __init__(self):
            self.vals = {}

        def set(self, name, value, doc="", **labels):
            self.vals[(name, tuple(sorted(labels.items())))] = value

    max_blocks = CTX // BLOCK + 2

    async def run_mode(mode):
        eng = JaxEngine(EngineConfig(
            model=MODEL, block_size=BLOCK, num_blocks=B * max_blocks + 1,
            max_blocks_per_seq=max_blocks, max_num_seqs=B,
            kv_cache_dtype=KV_DTYPE, sampling_epilogue=mode,
            peak_hbm_gbps=HBM_GBPS, seed=0))
        eng.warmup_decode()
        rng = np.random.default_rng(0)
        prompt = [int(t) for t in rng.integers(3, 255, 64)]

        async def one(i):
            req = PreprocessedRequest(
                token_ids=prompt, request_id=f"ep-{mode}-{i}",
                sampling=SamplingOptions(temperature=0.0, seed=i),
                stop=StopConditions(max_tokens=K, ignore_eos=True))
            toks = []
            async for out in eng.generate(req):
                toks.extend(out.token_ids)
            return toks

        t0 = time.perf_counter()
        outs = await asyncio.gather(*(one(i) for i in range(B)))
        dt = time.perf_counter() - t0
        # post-hoc gauge replay: _phase_rates works from each record's
        # own gap_s/xla_flops/xla_bytes fields, so draining eng.fpm
        # into a wide-open window reproduces the worker's export
        fw = FpmWindow(window_s=3600.0)
        while eng.fpm:
            fw.add(0, eng.fpm.popleft())
        g = _Gauges()
        export_engine_gauges(g, fw, peak_hbm_gbps=HBM_GBPS)
        mbu = g.vals.get(("dynamo_engine_mbu", (("phase", "decode"),)), 0.0)
        await eng.close()
        return outs, sum(len(t) for t in outs) / dt, mbu

    print(f"epilogue A/B: {MODEL}, B={B}, {K} tokens/req, kv {KV_DTYPE}")
    results = {}
    for mode in modes:
        outs, tok_s, mbu = asyncio.run(run_mode(mode))
        results[mode] = (outs, tok_s, mbu)
        print(f"  epilogue[{mode:5s}] {tok_s:9.1f} tok/s   decode MBU "
              f"{mbu:5.3f}  (dynamo_engine_mbu{{phase=decode}} vs "
              f"{HBM_GBPS:.0f} GB/s pin)")
    if "off" in results and "fused" in results:
        assert results["off"][0] == results["fused"][0], \
            "greedy token streams diverged between epilogue modes"
        ratio = results["fused"][1] / max(results["off"][1], 1e-9)
        print(f"  epilogue A/B: greedy streams identical; fused/off "
              f"tok/s ratio {ratio:.2f}")


def main():
    cfg = llama.PRESETS[MODEL]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    emb = params["embedding"].size

    max_blocks = CTX // BLOCK + 2
    num_blocks = B * max_blocks + 1
    quant = KV_DTYPE == "int8"
    kv = [
        jnp.zeros((cfg.n_layers, cfg.n_kv_heads, num_blocks,
                   cfg.head_dim, BLOCK),
                  jnp.int8 if quant else cfg.dtype)
        for _ in range(2)
    ]
    if quant:
        kv += [jnp.zeros((cfg.n_layers, cfg.n_kv_heads, num_blocks,
                          BLOCK), jnp.float32) for _ in range(2)]
    kv = tuple(kv)
    tables = np.zeros((B, max_blocks), np.int32)
    for b in range(B):
        tables[b] = 1 + b * max_blocks + np.arange(max_blocks)
    tables = jnp.asarray(tables)
    lens = jnp.full((B,), CTX, jnp.int32)
    rng = np.random.default_rng(0)
    tok0 = jnp.asarray(rng.integers(3, cfg.vocab_size, B, np.int32))
    q0 = jnp.asarray(
        rng.standard_normal((B, cfg.n_heads, cfg.head_dim)), cfg.dtype)

    L = cfg.n_layers
    # bytes/token/layer/head: 2*hd at bf16; hd int8 + 4B fp32 scale at int8
    per_head = (cfg.head_dim + 4) if quant else 2 * cfg.head_dim
    kv_gb = 2 * L * CTX * cfg.n_kv_heads * per_head * B / 1e9
    w_gb = (n_params - emb) * 2 / 1e9
    print(f"per-step traffic: weights {w_gb:.2f} GB + KV {kv_gb:.2f} GB"
          f" (kv dtype {KV_DTYPE})")
    rows = []

    def report(name, t_burst, gb_per_step):
        t = t_burst / K
        rows.append((name, t * 1e3, gb_per_step / t))
        print(f"  {name:16s} {t*1e3:7.2f} ms/step   "
              f"{gb_per_step / t:6.1f} GB/s  "
              f"({gb_per_step / t / HBM_GBPS * 100:4.1f}% of pin)")

    # --- full burst (the raw loop) -------------------------------------
    def burst(params, kv, tokens, positions, tables, ctx_lens):
        toks, kv = llama.decode_multi(params, cfg, kv, tokens, positions,
                                      tables, ctx_lens, K)
        return toks[-1], kv
    step = jax.jit(burst, donate_argnums=(1,))
    state = {"kv": kv, "tok": tok0}

    if want("full"):
        def run_full():
            state["tok"], state["kv"] = step(
                params, state["kv"], state["tok"], lens, tables, lens)
            return state["tok"]
        report("full", timeit(run_full), w_gb + kv_gb)
        kv = state["kv"]  # the full burst DONATED the original buffers

    if want("full_jnp"):
        import dataclasses

        cfg_jnp = dataclasses.replace(cfg, attn_impl="jnp")

        def burst_jnp(params, kv, tokens, positions, tables, ctx_lens):
            toks, kv = llama.decode_multi(params, cfg_jnp, kv, tokens,
                                          positions, tables, ctx_lens, K)
            return toks[-1], kv
        stepj = jax.jit(burst_jnp, donate_argnums=(1,))

        def run_jnp():
            state["tok"], state["kv"] = stepj(
                params, state["kv"], state["tok"], lens, tables, lens)
            return state["tok"]
        report("full_jnp", timeit(run_jnp), w_gb + kv_gb)
        kv = state["kv"]

    # --- weights only (attention stubbed) ------------------------------
    def decode_noattn(params, tokens, positions):
        x = params["embedding"][tokens].astype(cfg.dtype)
        pos1 = positions[:, None]
        for layer in params["layers"]:
            h = llama.rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
            q, k, v = llama._qkv(layer, cfg, h[:, None, :], pos1)
            attn = q[:, 0] + k[:, 0].repeat(cfg.n_heads // cfg.n_kv_heads, 1)
            x = x + llama._attn_out(layer, attn.reshape(B, cfg.q_dim))
            h = llama.rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
            x = x + llama._mlp(layer, h)
        return llama._logits(params, cfg, x)

    if want("weights"):
        @jax.jit
        def wburst(params, tok, positions):
            def body(t, _):
                lg = decode_noattn(params, t, positions)
                return jnp.argmax(lg, -1).astype(jnp.int32), None
            t, _ = jax.lax.scan(body, tok, None, length=K)
            return t
        report("weights", timeit(lambda: wburst(params, tok0, lens)), w_gb)

    # --- attention only: pallas bpc sweep + debug splits + jnp ---------
    def attn_burst_fn(impl_bpc, debug=""):
        scales = kv[2:] if quant else (None, None)

        def one_step(q, kc, vc):
            for li in range(L):
                if impl_bpc == "jnp":
                    o = pa.paged_attention_decode_jnp(
                        q, kc, vc, li, tables, lens,
                        k_scale=scales[0], v_scale=scales[1])
                else:
                    o = paged_attention_decode_pallas(
                        q, kc, vc, li, tables, lens,
                        blocks_per_chunk=impl_bpc, debug_mode=debug)
                q = (o.astype(jnp.float32) * 0.999).astype(q.dtype)
            return q

        @jax.jit
        def aburst(q, kc, vc):
            def body(q, _):
                return one_step(q, kc, vc), None
            q, _ = jax.lax.scan(body, q, None, length=K)
            return q
        return aburst

    if want("attn") and quant:
        # the Pallas kernel has no int8 lane layout (see
        # ops/paged_attention.py): the quantized cache serves via the
        # jnp gather path — measure attn_jnp instead
        print("  attn_pallas      skipped: int8 cache has no pallas path")
    if want("attn") and not quant:
        for bpc in (4, 8):
            f = attn_burst_fn(bpc)
            report(f"attn_pallas[{bpc}]",
                   timeit(lambda: f(q0, kv[0], kv[1])), kv_gb)
        # NB: "compute_only" exists too but has crashed the tunneled TPU
        # worker (kernel fault reading never-DMA'd VMEM); run it only by
        # explicit selection
        for debug in (("dma_only", "compute_only") if "attn_debug" in _SEL
                      else ("dma_only",)):
            f = attn_burst_fn(4, debug)
            report(f"attn[{debug}]",
                   timeit(lambda: f(q0, kv[0], kv[1])), kv_gb)
    if want("attn_jnp"):
        fj = attn_burst_fn("jnp")
        report("attn_jnp", timeit(lambda: fj(q0, kv[0], kv[1])), kv_gb)

    # --- official jax pallas paged attention, if importable ------------
    try:
        if not want("attn_jaxlib"):
            raise ImportError("skipped")
        from jax.experimental.pallas.ops.tpu.paged_attention import (
            paged_attention as jax_paged,
        )

        # library layout: pages [nkv, total_pages, page, hd]
        kp = jnp.zeros((cfg.n_kv_heads, num_blocks, BLOCK, cfg.head_dim),
                       cfg.dtype)
        vp = jnp.zeros_like(kp)

        @jax.jit
        def jburst(q, kp, vp):
            def body(q, _):
                for _li in range(L):
                    o = jax_paged(q, kp, vp, lens, tables,
                                  pages_per_compute_block=4)
                    q = (o.astype(jnp.float32) * 0.999).astype(q.dtype)
                return q, None
            q, _ = jax.lax.scan(body, q, None, length=K)
            return q
        # one cache serves all layers here, so traffic per step is still
        # 28 gathers of the same pages = kv_gb equivalent
        report("attn_jaxlib", timeit(lambda: jburst(q0, kp, vp)), kv_gb)
        del kp, vp
    except Exception as e:  # pragma: no cover - probe
        print(f"  attn_jaxlib      unavailable: {type(e).__name__}: {e}")

    # --- kv write scatter only -----------------------------------------
    if want("kv_write"):
        kvec = jnp.asarray(
            rng.standard_normal((B, cfg.n_kv_heads, cfg.head_dim)),
            cfg.dtype)

        @partial(jax.jit, donate_argnums=(0,))
        def wr_burst(kv, kvec):
            def body(carry, _):
                for li in range(L):
                    if len(carry) == 4:
                        kc, vc, ks, vs = carry
                        carry = pa.write_token_kv(
                            kc, vc, li, kvec, kvec, tables, lens,
                            k_scale=ks, v_scale=vs)
                    else:
                        kc, vc = carry
                        carry = pa.write_token_kv(kc, vc, li, kvec, kvec,
                                                  tables, lens)
                return carry, None

            out, _ = jax.lax.scan(body, kv, None, length=K)
            return out
        wr_gb = 2 * L * B * cfg.n_kv_heads * per_head / 1e9
        state2 = {"kv": kv}

        def run_wr():
            state2["kv"] = wr_burst(state2["kv"], kvec)
            return state2["kv"][0]
        report("kv_write", timeit(run_wr), wr_gb)

    # --- sampling -------------------------------------------------------
    if want("sample"):
        logits = jnp.asarray(
            rng.standard_normal((B, cfg.vocab_size)), jnp.float32)

        @jax.jit
        def samp(lg):
            def body(c, _):
                return (jnp.argmax(lg + c[:, None], -1).astype(jnp.int32),
                        None)
            t, _ = jax.lax.scan(body, tok0, None, length=K)
            return t
        report("sample", timeit(lambda: samp(logits)),
               B * cfg.vocab_size * 4 / 1e9)


if __name__ == "__main__":
    p = argparse.ArgumentParser(
        description="per-phase decode profiler (see module docstring)")
    p.add_argument("phases", nargs="*",
                   help="phase tags to run: full full_jnp weights attn "
                        "attn_debug attn_jnp attn_jaxlib kv_write sample "
                        "(default: all)")
    p.add_argument("--kv-dtype", default="bf16", choices=["bf16", "int8"],
                   help="KV storage dtype: int8 streams half the KV "
                        "bytes per decode step (quant/kv.py); the pallas "
                        "attn phases are skipped (no int8 kernel)")
    p.add_argument("--epilogue", default="", choices=["", "on", "off", "ab"],
                   help="fused sampling epilogue A/B through a real "
                        "JaxEngine: on = fused only, off = reference "
                        "only, ab = both + greedy byte-identity check; "
                        "reports decode MBU from the worker's "
                        "dynamo_engine_mbu{phase} gauge")
    p.add_argument("--model", default=MODEL,
                   help="model preset for all phases (default llama-3b; "
                        "use tiny for a CPU smoke of --epilogue)")
    args = p.parse_args()
    _SEL = set(args.phases)
    KV_DTYPE = args.kv_dtype
    MODEL = args.model
    # `epilogue` as a bare phase tag defaults to the full A/B; when the
    # epilogue is the only selection, the classic phases are skipped
    EPILOGUE = args.epilogue or ("ab" if "epilogue" in _SEL else None)
    _SEL.discard("epilogue")
    if not EPILOGUE or _SEL:
        main()
    if EPILOGUE:
        epilogue_report(
            {"on": ("fused",), "off": ("off",), "ab": ("off", "fused")}
            [EPILOGUE])
