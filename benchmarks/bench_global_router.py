"""Mega-fleet closed-loop bench: global router over pool namespaces.

Builds the whole PR-18 request plane in one process — P pools (one agg,
one disagg with a mocker prefill tier) × F replica-sync'd frontends per
pool × mocker workers — then drives T tenants of shared-prefix streams
through `GlobalRouterService` and re-drives the SAME trace through a
single frontend directly, asserting the token streams are
byte-identical (MockEngine streams are position-addressed by request
seed, so ANY placement must produce the same bytes — the proxy layer
may add zero token-level noise).

Reported per r06 JSON line:

  * p99 route latency (receive -> forward-started inside the grouter)
  * per-replica `dynamo_router_overlap_staleness_ratio` and its spread
    within each pool (the replica-sync convergence signal)
  * per-frontend routed-decision counts + goodput spread (how evenly
    the replica tier shares the load)
  * per-pool routed counts by classification reason (both classes must
    see traffic: the short-prompt tenants land agg, the long-prompt
    tenants clear the conditional-disagg thresholds)

Smoke scale (tier-1, seconds on CPU): 2 pools x 3 frontends x ~3
workers, ~60 streams at concurrency ~20.  TPU/full scale: 1k+
concurrent streams across dozens of workers; gates enforced.
"""

import argparse
import asyncio
import json
import random
import time
import uuid
import zlib

import aiohttp

from dynamo_tpu.disagg.prefill_router import ConditionalDisaggConfig
from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.global_router import GlobalRouterConfig, GlobalRouterService
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.router.kv_router import make_kv_route_factory
from dynamo_tpu.runtime import DistributedRuntime, RouterMode, RuntimeConfig

MODEL = "bench-model"
BLOCK = 16
# classification geometry, scaled down from the reference thresholds so
# the smoke run stays in CPU-seconds: the grouter estimates ~4 chars per
# token, the byte tokenizer counts 1 per char, so the frontend-side
# threshold is 4x the grouter-side one for the same prompt
GROUTER_MIN_ISL = 256
FRONTEND_MIN_ISL = 1024
LONG_PROMPT_CHARS = 1600
SHORT_PROMPT_CHARS = 180
SHARED_PREFIX_FRAC = 0.6

SCALES = {
    "smoke": dict(pools=2, frontends=3, decode_workers=2,
                  prefill_workers=1, streams=60, concurrency=20,
                  tenants=4, max_tokens=16),
    "tpu": dict(pools=2, frontends=3, decode_workers=12,
                prefill_workers=6, streams=1500, concurrency=1024,
                tenants=16, max_tokens=32),
}


def build_trace(scale: dict) -> list:
    """Multi-tenant shared-prefix request trace: half the tenants speak
    short prompts (agg class), half long ones (disagg class); within a
    tenant every stream shares a prefix and diverges in the suffix."""
    rng = random.Random(42)
    alphabet = "abcdefghijklmnopqrstuvwxyz "
    reqs = []
    for t in range(scale["tenants"]):
        long_class = t % 2 == 1
        chars = LONG_PROMPT_CHARS if long_class else SHORT_PROMPT_CHARS
        prefix = "".join(rng.choice(alphabet)
                         for _ in range(int(chars * SHARED_PREFIX_FRAC)))
        for s in range(scale["streams"] // scale["tenants"]):
            suffix = "".join(rng.choice(alphabet)
                             for _ in range(chars - len(prefix)))
            key = f"t{t}s{s}"
            reqs.append({
                "key": key, "tenant": t, "long": long_class,
                "body": {
                    "model": MODEL,
                    "prompt": prefix + suffix,
                    "max_tokens": scale["max_tokens"],
                    "stream": True,
                    "seed": zlib.crc32(key.encode()) & 0x7FFFFFFF,
                },
            })
    return reqs


async def start_pool(cluster: str, ns: str, disagg: bool, scale: dict):
    """One pool namespace: worker runtime + per-frontend runtimes (a
    runtime per replica gives each its own metrics registry, so the
    per-replica staleness gauges are genuine)."""
    wrt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc", namespace=ns),
        cluster_id=cluster).start()
    common = dict(model_name=MODEL, block_size=BLOCK,
                  base_step_s=0.0005, prefill_s_per_token=0.0,
                  decode_s_per_seq=0.0)
    workers = []
    for _ in range(scale["decode_workers"]):
        workers.append(await MockerWorker(
            wrt, MockEngineArgs(**common), namespace=ns).start())
    if disagg:
        for _ in range(scale["prefill_workers"]):
            workers.append(await MockerWorker(
                wrt, MockEngineArgs(role="prefill", **common),
                namespace=ns, component="prefill").start())
    frontends = []
    for _ in range(scale["frontends"]):
        rt = await DistributedRuntime(
            config=RuntimeConfig(discovery_backend="mem",
                                 event_plane="inproc", namespace=ns),
            cluster_id=cluster).start()
        manager = ModelManager()
        watcher = await ModelWatcher(
            rt, manager, router_mode=RouterMode.KV,
            make_route=make_kv_route_factory(
                rt, overlap_score_weight=1.0, temperature=0.0),
            disagg_config=ConditionalDisaggConfig(
                min_effective_isl=FRONTEND_MIN_ISL,
                min_effective_ratio=0.7),
            namespaces={ns}).start()
        svc = await HttpService(rt, manager, host="127.0.0.1", port=0,
                                advertise=True).start()
        frontends.append({"rt": rt, "manager": manager,
                          "watcher": watcher, "svc": svc,
                          "port": svc._runner.addresses[0][1]})
    return {"ns": ns, "wrt": wrt, "workers": workers,
            "frontends": frontends}


async def stop_pool(pool: dict) -> None:
    for fe in pool["frontends"]:
        await fe["svc"].close()
        await fe["watcher"].close()
        await fe["rt"].shutdown()
    for w in pool["workers"]:
        await w.close()
    await pool["wrt"].shutdown()


async def wait_ready(pools: list) -> None:
    for pool in pools:
        for fe in pool["frontends"]:
            for _ in range(200):
                if fe["manager"].get(MODEL):
                    break
                await asyncio.sleep(0.02)
            assert fe["manager"].get(MODEL), (
                f"frontend in {pool['ns']} never saw {MODEL}")


async def drive(url: str, reqs: list, concurrency: int) -> dict:
    """Fire the trace at `url` and collect per-request concatenated
    delta text + client-side latencies."""
    sem = asyncio.Semaphore(concurrency)
    out = {}

    async def one(session, req):
        async with sem:
            t0 = time.monotonic()
            ttft = None
            text = []
            async with session.post(f"{url}/v1/completions",
                                    json=req["body"]) as r:
                assert r.status == 200, (r.status, await r.text())
                async for raw in r.content:
                    line = raw.decode().strip()
                    if not line.startswith("data:"):
                        continue
                    data = line[5:].strip()
                    if data == "[DONE]":
                        break
                    if ttft is None:
                        ttft = time.monotonic() - t0
                    obj = json.loads(data)
                    for ch in obj.get("choices", ()):
                        if ch.get("text"):
                            text.append(ch["text"])
            out[req["key"]] = {
                "text": "".join(text),
                "ttft_s": ttft,
                "total_s": time.monotonic() - t0,
                "long": req["long"],
            }

    conn = aiohttp.TCPConnector(limit=concurrency + 8)
    async with aiohttp.ClientSession(connector=conn) as session:
        await asyncio.gather(*(one(session, r) for r in reqs))
    return out


def quantile(vals, p):
    vals = sorted(vals)
    if not vals:
        return None
    return vals[min(int(p * len(vals)), len(vals) - 1)]


def staleness_rollup(pools: list) -> dict:
    """Per-replica staleness straight from each frontend's KvRouter
    (the same numbers the grouter scrapes over /metrics)."""
    per_pool = {}
    for pool in pools:
        replicas = {}
        for i, fe in enumerate(pool["frontends"]):
            router = (fe["svc"].debug_state().get("router") or {}).get(
                MODEL, {})
            replicas[f"fe{i}"] = {
                "staleness_ratio": router.get("staleness_ratio"),
                "decisions": router.get("decisions", 0),
            }
        vals = [r["staleness_ratio"] for r in replicas.values()
                if r["staleness_ratio"] is not None]
        decs = [r["decisions"] for r in replicas.values()]
        mean_d = sum(decs) / max(len(decs), 1)
        per_pool[pool["ns"]] = {
            "replicas": replicas,
            "staleness_spread": (round(max(vals) - min(vals), 4)
                                 if len(vals) > 1 else None),
            # goodput spread: how evenly the replica tier shared the
            # pool's load (0 = perfectly even)
            "goodput_spread": (round((max(decs) - min(decs))
                                     / max(mean_d, 1e-9), 4)
                               if decs else None),
        }
    return per_pool


async def run(mode: str) -> dict:
    scale = SCALES[mode]
    cluster = uuid.uuid4().hex
    pools = []
    for p in range(scale["pools"]):
        pools.append(await start_pool(cluster, f"pool{p}",
                                      disagg=(p % 2 == 1), scale=scale))
    grt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc", namespace="global"),
        cluster_id=cluster).start()
    grouter = await GlobalRouterService(
        grt, host="127.0.0.1", port=0,
        config=GlobalRouterConfig(disagg_min_isl=GROUTER_MIN_ISL,
                                  disagg_ratio=0.7),
        staleness_scrape_s=0.5).start()
    try:
        await wait_ready(pools)
        # pool discovery: both pools with all frontends
        for _ in range(200):
            ps = grouter.directory.pools_for_model(MODEL)
            if (len(ps) >= scale["pools"]
                    and all(len(p.frontends) >= scale["frontends"]
                            for p in ps)):
                break
            await asyncio.sleep(0.02)
        reqs = build_trace(scale)
        t0 = time.monotonic()
        routed = await drive(f"http://127.0.0.1:{grouter.port}", reqs,
                             scale["concurrency"])
        routed_dt = time.monotonic() - t0
        await asyncio.sleep(0.6)  # let the staleness scrape fire once
        grouter_state = grouter.debug_state()
        staleness = staleness_rollup(pools)

        # single-frontend baseline: same trace, straight at one pool-0
        # replica (token streams are position-addressed by seed, so the
        # bytes must match no matter who served them)
        base_url = f"http://127.0.0.1:{pools[0]['frontends'][0]['port']}"
        baseline = await drive(base_url, reqs, scale["concurrency"])
        mismatches = [k for k in routed
                      if routed[k]["text"] != baseline[k]["text"]]
        empty = [k for k, v in routed.items() if not v["text"]]

        ttfts = [v["ttft_s"] for v in routed.values()
                 if v["ttft_s"] is not None]
        pools_hit = {k.split("/", 1)[0]
                     for k in grouter_state["routed"]}
        return {
            "mode": mode, "scale": scale,
            "streams": len(reqs),
            "wall_s": round(routed_dt, 3),
            "streams_per_s": round(len(reqs) / routed_dt, 1),
            "byte_identical": not mismatches,
            "mismatches": len(mismatches),
            "empty_streams": len(empty),
            "route_latency": grouter_state["route_latency"],
            "routed": grouter_state["routed"],
            "pools_hit": sorted(pools_hit),
            "client_ttft_ms": {
                "p50": round((quantile(ttfts, 0.5) or 0) * 1e3, 2),
                "p99": round((quantile(ttfts, 0.99) or 0) * 1e3, 2),
            },
            "staleness": staleness,
            "grouter_staleness_scrape": grouter_state["staleness"],
        }
    finally:
        await grouter.close()
        await grt.shutdown()
        for pool in pools:
            await stop_pool(pool)


def main() -> int:
    p = argparse.ArgumentParser(
        description="mega-fleet global-router closed loop "
                    "(see module docstring)")
    p.add_argument("--mode", default="smoke", choices=["smoke", "tpu"])
    args = p.parse_args()
    enforced = args.mode == "tpu"
    result = asyncio.run(run(args.mode))

    def g(name, target, value, ok, always=False):
        status = (("pass" if ok else "fail")
                  if (enforced or always) else "skipped_smoke")
        if value is None:
            status = "fail_missing" if (enforced or always) else \
                "skipped_smoke"
        return {"name": name, "target": target, "value": value,
                "status": status}

    p99 = result["route_latency"].get("p99_ms")
    spreads = [s["staleness_spread"]
               for s in result["staleness"].values()
               if s["staleness_spread"] is not None]
    max_spread = max(spreads) if spreads else None
    gates = [
        # correctness gates hold in every mode: the proxy layer must
        # add zero token-level noise and both classes must route
        g("grouter_byte_identity", "routed == single-frontend bytes",
          result["byte_identical"], result["byte_identical"],
          always=True),
        g("grouter_pools_routed", ">= 2 pools",
          len(result["pools_hit"]), len(result["pools_hit"]) >= 2,
          always=True),
        g("grouter_route_p99_ms", "< 5.0", p99,
          p99 is not None and p99 < 5.0),
        g("grouter_staleness_spread", "< 0.25", max_spread,
          max_spread is not None and max_spread < 0.25),
    ]
    print(json.dumps({
        "bench": "global_router", "round": "r06", "mode": args.mode,
        "gates": gates, "result": result,
    }), flush=True)
    return 1 if any(x["status"] == "fail" for x in gates) else 0


if __name__ == "__main__":
    raise SystemExit(main())
