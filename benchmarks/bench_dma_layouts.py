"""Raw gather-DMA microbench: what bandwidth can a Pallas kernel actually
pull from HBM for paged-KV gathers, per cache layout?

Decides the round-5 layout question: the decode kernel's DMA leg measures
~190 GB/s on the head-major layout ([nkv, nb, hd, bs] — a block's planes
are 8 strided 32KB runs), far under the 819 GB/s pin.  Candidates:

  strided     current: one descriptor per block, [nkv, hd, bs] with a
              ~4.6 MB stride between 32KB head planes
  contig      block-major layout ([nb, nkv, hd, bs]): one contiguous
              256KB descriptor per block
  seq         sequential whole-slab read via BlockSpec pipelining
              (no gather at all — upper bound)

Prints GB/s for each.  Run: python benchmarks/bench_dma_layouts.py
"""

import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dynamo_tpu.ops.pallas_paged_attention import (  # noqa: E402
    tpu_compiler_params,
)

NKV, HD, BS = 8, 128, 128
NB = 1024            # pool blocks (256 MB slab at bf16)
NREAD = 512          # blocks gathered per kernel call (128 MB)
BPC = 8              # blocks per chunk
HBM_GBPS = 819.0


def _sync(r):
    np.asarray(jax.device_get(r.ravel()[0]))


def timeit(fn, n=6, warm=2):
    for _ in range(warm):
        r = fn()
    _sync(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    _sync(r)
    return (time.perf_counter() - t0) / n


REPS = 8  # in-kernel repeats: amortize the tunnel's fixed dispatch cost


def gather_kernel(tables_ref, hbm, o_ref, buf, sem, *, mode, nread):
    n_chunks = nread // BPC

    def start(c, slot):
        for i in range(BPC):
            pid = tables_ref[c * BPC + i]
            if mode == "strided":
                cp = pltpu.make_async_copy(
                    hbm.at[:, pid], buf.at[slot, i], sem.at[slot])
            else:
                cp = pltpu.make_async_copy(
                    hbm.at[pid], buf.at[slot, i], sem.at[slot])
            cp.start()

    def wait(c, slot):
        for i in range(BPC):
            pid = tables_ref[c * BPC + i]
            if mode == "strided":
                cp = pltpu.make_async_copy(
                    hbm.at[:, pid], buf.at[slot, i], sem.at[slot])
            else:
                cp = pltpu.make_async_copy(
                    hbm.at[pid], buf.at[slot, i], sem.at[slot])
            cp.wait()

    start(0, 0)
    acc0 = jnp.zeros((8, 128), jnp.float32)

    def body(t, acc):
        c = jax.lax.rem(t, n_chunks)
        slot = jax.lax.rem(t, 2)

        @pl.when(t + 1 < REPS * n_chunks)
        def _():
            start(jax.lax.rem(t + 1, n_chunks), jax.lax.rem(t + 1, 2))
        wait(c, slot)
        return acc + buf[slot, 0, 0, :8, :].astype(jnp.float32)

    acc = jax.lax.fori_loop(0, REPS * n_chunks, body, acc0)
    o_ref[...] = acc


def make_gather(mode):
    buf = pltpu.VMEM((2, BPC, NKV, HD, BS), jnp.bfloat16)
    fn = pl.pallas_call(
        functools.partial(gather_kernel, mode=mode, nread=NREAD),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((8, 128), lambda i, *r: (0, 0)),
            scratch_shapes=[buf, pltpu.SemaphoreType.DMA((2,))],
        ),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
    )
    return jax.jit(fn)


def main():
    rng = np.random.default_rng(0)
    tables = jnp.asarray(rng.permutation(NB)[:NREAD].astype(np.int32))
    nbytes = NREAD * NKV * HD * BS * 2 * REPS
    print(f"gather payload: {nbytes/1e6:.0f} MB "
          f"({REPS}x{NREAD} blocks) per call")

    hbm_hm = jnp.zeros((NKV, NB, HD, BS), jnp.bfloat16)   # head-major
    g = make_gather("strided")
    t = timeit(lambda: g(tables, hbm_hm))
    print(f"  strided (head-major):  {nbytes/t/1e9:6.1f} GB/s "
          f"({nbytes/t/1e9/HBM_GBPS*100:4.1f}% of pin)")
    del hbm_hm

    hbm_bm = jnp.zeros((NB, NKV, HD, BS), jnp.bfloat16)   # block-major
    g = make_gather("contig")
    t = timeit(lambda: g(tables, hbm_bm))
    print(f"  contig (block-major):  {nbytes/t/1e9:6.1f} GB/s "
          f"({nbytes/t/1e9/HBM_GBPS*100:4.1f}% of pin)")

    # sequential upper bound: stream the whole slab through BlockSpec
    # pipelining and reduce it
    def seq_kernel(x_ref, o_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            o_ref[...] = jnp.zeros_like(o_ref)
        o_ref[...] += x_ref[0, 0].astype(jnp.float32)

    seq = pl.pallas_call(
        seq_kernel,
        grid=(REPS * NB // BPC,),
        in_specs=[pl.BlockSpec(
            (BPC, NKV, HD, BS),
            lambda i: (jax.lax.rem(i, NB // BPC), 0, 0, 0))],
        out_specs=pl.BlockSpec((HD, BS), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((HD, BS), jnp.float32),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
    )
    seq = jax.jit(seq)
    seq_bytes = REPS * NB * NKV * HD * BS * 2
    t = timeit(lambda: seq(hbm_bm))
    print(f"  sequential pipeline:   {seq_bytes/t/1e9:6.1f} GB/s "
          f"({seq_bytes/t/1e9/HBM_GBPS*100:4.1f}% of pin)")


if __name__ == "__main__":
    import argparse

    argparse.ArgumentParser(
        description="raw gather-DMA layout microbench (no options; "
                    "requires a TPU)").parse_args()
    main()
