"""Canary health checks: wedged-worker detection, lease withdraw/restore
(ref: lib/runtime/src/health_check.rs)."""

import asyncio
import uuid

from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig
from dynamo_tpu.runtime.health_check import HealthCheckConfig


def fresh_runtime(**health_kw) -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    rt = DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)
    if health_kw:
        rt.system_health.config = HealthCheckConfig(**health_kw)
    return rt


async def test_canary_passes_on_healthy_worker():
    rt = await fresh_runtime(canary_wait_s=0.1, request_timeout_s=2.0).start()
    try:
        args = MockEngineArgs(model_name="m", block_size=4,
                              base_step_s=0.0005)
        w = await MockerWorker(rt, args).start()
        assert rt.system_health.healthy
        # let at least one canary fire (no organic traffic)
        target = next(iter(rt.system_health.targets.values()))
        for _ in range(100):
            if target.last_result_t:
                break
            await asyncio.sleep(0.05)
        assert target.last_result_t > 0, "canary never fired"
        assert rt.system_health.healthy
        statuses = rt.system_health.statuses()
        assert all(v == "ready" for v in statuses.values())
        await w.close()
        # closing deregisters the canary
        assert not any("generate" in s for s in rt.system_health.targets)
    finally:
        await rt.shutdown()


async def test_wedged_worker_withdraws_lease_and_recovers():
    """Fault injection: the engine hangs -> canary times out -> instance
    vanishes from discovery; engine unwedges -> canary passes -> instance
    returns."""
    rt = await fresh_runtime(canary_wait_s=0.1,
                             request_timeout_s=0.3).start()
    try:
        args = MockEngineArgs(model_name="m", block_size=4,
                              base_step_s=0.0005)
        w = await MockerWorker(rt, args).start()
        key = w.served.instance.key()
        assert key in await rt.discovery.get_prefix("v1/instances")

        # wedge: replace the handler's engine.generate with one that
        # never yields (simulates a stuck device loop)
        real_generate = w.engine.generate
        wedged = asyncio.Event()

        async def hung_generate(request, token=None):
            wedged.set()
            await asyncio.sleep(3600)
            yield  # pragma: no cover

        w.engine.generate = hung_generate
        for _ in range(200):
            if not rt.system_health.healthy:
                break
            await asyncio.sleep(0.05)
        assert not rt.system_health.healthy, "canary never tripped"
        # lease withdrawn: instance gone from discovery
        for _ in range(100):
            if key not in await rt.discovery.get_prefix("v1/instances"):
                break
            await asyncio.sleep(0.05)
        assert key not in await rt.discovery.get_prefix("v1/instances")

        # recovery
        w.engine.generate = real_generate
        for _ in range(200):
            if rt.system_health.healthy:
                break
            await asyncio.sleep(0.05)
        assert rt.system_health.healthy, "canary never recovered"
        for _ in range(100):
            if key in await rt.discovery.get_prefix("v1/instances"):
                break
            await asyncio.sleep(0.05)
        assert key in await rt.discovery.get_prefix("v1/instances")
        await w.close()
    finally:
        await rt.shutdown()


async def test_activity_resets_canary_timer():
    """Organic traffic keeps the canary quiet (ref health_check.rs
    notifier path): with steady requests, no canary fires."""
    rt = await fresh_runtime(canary_wait_s=0.4,
                             request_timeout_s=2.0).start()
    try:
        args = MockEngineArgs(model_name="m", block_size=4,
                              base_step_s=0.0005, prefill_s_per_token=0.0,
                              decode_s_per_seq=0.0)
        w = await MockerWorker(rt, args).start()
        client = await (rt.namespace("dynamo").component("mocker")
                        .endpoint("generate").client()).start()
        await client.wait_for_instances()
        target = next(t for t in rt.system_health.targets.values()
                      if t.path.endswith("generate"))
        # steady traffic for ~1.2s (3x the canary wait)
        for i in range(8):
            async for _ in client.generate(
                    {"token_ids": [1, 2, 3], "request_id": f"r{i}",
                     "stop": {"max_tokens": 2, "ignore_eos": True}}):
                pass
            await asyncio.sleep(0.15)
        assert target.last_result_t == 0.0, "canary fired despite traffic"
        assert rt.system_health.healthy
        await client.close()
        await w.close()
    finally:
        await rt.shutdown()


async def test_system_status_health_reflects_canaries():
    import socket

    import aiohttp

    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        free_port = sock.getsockname()[1]
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc",
                        system_port=free_port)
    rt = DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)
    rt.system_health.config = HealthCheckConfig(canary_wait_s=0.1,
                                                request_timeout_s=0.3)
    await rt.start()
    try:
        port = free_port
        args = MockEngineArgs(model_name="m", block_size=4,
                              base_step_s=0.0005)
        w = await MockerWorker(rt, args).start()
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/health") as r:
                assert r.status == 200
                body = await r.json()
                assert body["status"] == "healthy"
                assert any(k.endswith(str(w.served.instance_id))
                           for k in body["endpoints"])

            async def hung(request, token=None):
                await asyncio.sleep(3600)
                yield  # pragma: no cover

            w.engine.generate = hung
            for _ in range(200):
                if not rt.system_health.healthy:
                    break
                await asyncio.sleep(0.05)
            async with s.get(f"http://127.0.0.1:{port}/health") as r:
                assert r.status == 503
                assert (await r.json())["status"] == "unhealthy"
        await w.close()
    finally:
        await rt.shutdown()


async def test_canary_recovery_retries_failed_lease_restore():
    """Satellite (ISSUE 5): a transient discovery outage during
    _reconcile_lease must be retried by the next probe and end with the
    lease restored.  The restore's put fails once (injected); the stash
    must survive the failed attempt (discovery.py restore_lease) and the
    next canary's _maybe_reconcile must finish the job."""
    from dynamo_tpu import chaos

    rt = await fresh_runtime(canary_wait_s=0.1,
                             request_timeout_s=0.3).start()
    try:
        args = MockEngineArgs(model_name="m", block_size=4,
                              base_step_s=0.0005)
        w = await MockerWorker(rt, args).start()
        key = w.served.instance.key()

        # wedge -> canary trips -> lease withdrawn
        real_generate = w.engine.generate

        async def hung_generate(request, token=None):
            await asyncio.sleep(3600)
            yield  # pragma: no cover

        w.engine.generate = hung_generate
        for _ in range(200):
            if key not in await rt.discovery.get_prefix("v1/instances"):
                break
            await asyncio.sleep(0.05)
        assert key not in await rt.discovery.get_prefix("v1/instances")

        # recover the engine, but fail the FIRST restore put (transient
        # discovery outage exactly during _reconcile_lease)
        plane = chaos.ChaosPlane(seed=41).rule(
            "discovery.op", "fail", match="put:", times=1,
            error="injected discovery outage during restore")
        w.engine.generate = real_generate
        with plane:
            for _ in range(300):
                if (rt.system_health.healthy
                        and key in await rt.discovery.get_prefix(
                            "v1/instances")):
                    break
                await asyncio.sleep(0.05)
        assert plane.fired() >= 1, "restore was never attempted"
        assert rt.system_health.healthy
        assert key in await rt.discovery.get_prefix("v1/instances"), \
            "lease not restored after the transient outage"
        await w.close()
    finally:
        await rt.shutdown()
