"""Timeline tracing plane (dynamo_tpu/obs): zero-cost-off span tracer,
Chrome trace export, flight recorder, cross-process trace stitching,
and the gap-attribution report."""

import asyncio
import json
import os
import subprocess
import sys
import threading
import time
import uuid

import aiohttp
import pytest

from dynamo_tpu import chaos, obs
from dynamo_tpu.mocker import MockEngine, MockEngineArgs, MockerWorker
from dynamo_tpu.obs.report import report_paths
from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fresh_runtime() -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


@pytest.fixture(autouse=True)
def _no_tracer_leak():
    """A test that installs a tracer must not leak it into the next."""
    yield
    tr = obs.tracer()
    if tr is not None:
        tr.uninstall()
    assert obs.tracer() is None


# --------------------- zero-cost-off (the chaos-style None check) ----------


def test_disabled_helpers_are_noops():
    assert obs.tracer() is None and not obs.enabled()
    # begin() returns the shared 0.0 constant — no float allocated per
    # call on the hot loop (same zero-cost-off bar as chaos.hit's one
    # global None check)
    assert obs.begin() == 0.0
    assert obs.begin() is obs.begin()
    # end() with a disabled-start handle is a no-op even if a tracer
    # appears mid-span
    obs.end("step", 0.0, anything=1)
    with obs.Tracer() as tr:
        obs.end("step", 0.0, anything=1)  # began disabled: still dropped
        assert len(tr.spans) == 0
    # span() hands back one process-wide no-op context manager
    # dynlint: disable=DYN006 synthetic kinds: this tests tracer mechanics, not the span taxonomy
    assert obs.span("a") is obs.span("b")
    # dynlint: disable=DYN006 synthetic kinds: this tests tracer mechanics, not the span taxonomy
    with obs.span("a"):
        pass
    assert obs.flight_dump("nope") is None


def test_mock_engine_bit_identical_with_tracing_on():
    """The spans-disabled path must not change behavior — and enabling
    it must not either: same seed, same tokens, traced or not."""

    async def run_once(traced: bool):
        eng = MockEngine(MockEngineArgs(
            model_name="m", block_size=4, base_step_s=0.0,
            prefill_s_per_token=0.0, decode_s_per_seq=0.0))
        req = PreprocessedRequest(
            token_ids=list(range(40)), request_id="same-rid",
            stop=StopConditions(max_tokens=32, ignore_eos=True))
        toks = []
        tr = obs.Tracer().install() if traced else None
        try:
            async for out in eng.generate(req):
                toks.extend(out.token_ids)
        finally:
            if tr is not None:
                tr.uninstall()
            await eng.close()
        return toks, (set(s[0] for s in tr.spans) if tr else set())

    async def main():
        plain, _ = await run_once(False)
        traced, kinds = await run_once(True)
        assert plain == traced and len(plain) == 32
        # the mocker emits the engine taxonomy so the timeline plane is
        # exercised CPU-only
        assert {"step", "sched", "device_wait",
                "decode_dispatch", "prefill_dispatch"} <= kinds

    asyncio.run(main())


# --------------------- chrome trace export ---------------------------------


def test_chrome_trace_roundtrips_with_monotonic_ts_per_track():
    tr = obs.Tracer(service="t", ring=256)
    with tr:
        with obs.span("step", track="sched:x", active=2):
            with obs.span("sched", track="sched:x"):
                time.sleep(0.002)
            time.sleep(0.001)

        def other_thread():
            t0 = obs.begin()
            time.sleep(0.001)
            obs.end("detok", t0, tokens=3)

        th = threading.Thread(target=other_thread, name="loop-thread")
        th.start()
        th.join()
    doc = json.loads(json.dumps(tr.chrome_trace()))  # round-trip
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = {e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {"sched:x", "loop-thread"} <= set(names.values())
    by_tid = {}
    for e in xs:
        by_tid.setdefault(e["tid"], []).append(e["ts"])
    for tss in by_tid.values():
        assert tss == sorted(tss)  # monotonic start ts per track
    # nesting survived: the step span covers its sched child
    step = next(e for e in xs if e["name"] == "step")
    sched = next(e for e in xs if e["name"] == "sched")
    assert step["ts"] <= sched["ts"]
    assert step["ts"] + step["dur"] >= sched["ts"] + sched["dur"]
    assert step["args"]["active"] == 2
    assert next(e for e in xs if e["name"] == "detok")["args"]["tokens"] == 3


def test_ring_bounds_the_recorder():
    tr = obs.Tracer(ring=32)
    now = time.monotonic()
    for i in range(100):
        tr.record("k", now, now + 1e-6, {"i": i})
    assert len(tr.spans) == 32
    assert tr.spans[0][4]["i"] == 68  # oldest spans fell off


def test_span_histogram_on_metrics_hierarchy():
    from dynamo_tpu.runtime.metrics import MetricsHierarchy

    m = MetricsHierarchy(component="backend")
    tr = obs.Tracer().bind_metrics(m)
    with tr:
        t0 = obs.begin()
        obs.end("decode_dispatch", t0)
    text = m.render().decode()
    assert 'dynamo_trace_span_seconds_count{' in text
    assert 'kind="decode_dispatch"' in text


# --------------------- flight recorder -------------------------------------


def test_flight_recorder_fires_on_engine_step_chaos(tmp_path):
    """An injected engine.step fault must leave a valid Chrome-trace
    flight dump of the spans that led up to it (PR 4's fault plane tied
    to a post-mortem timeline)."""

    async def main():
        eng = MockEngine(MockEngineArgs(
            model_name="m", block_size=4, base_step_s=0.0))
        req = PreprocessedRequest(
            token_ids=list(range(12)), request_id="r1",
            stop=StopConditions(max_tokens=64, ignore_eos=True))
        plane = chaos.ChaosPlane(seed=3)
        plane.rule("engine.step", "fail", after=3, times=1)
        errored = False
        with plane:
            async for out in eng.generate(req):
                if out.finish_reason == "error":
                    errored = True
        await eng.close()
        assert errored and plane.fired("engine.step") == 1

    tr = obs.Tracer(out_path=str(tmp_path / "trace.json")).install()
    try:
        asyncio.run(main())
        assert tr.flight_dumps, "flight recorder did not fire"
        path = tr.flight_dumps[0]
        assert os.path.basename(path).startswith(
            "dynflight-chaos.engine.step-")
        doc = json.load(open(path))
        kinds = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
        assert "step" in kinds  # the pre-fault timeline is in the dump
    finally:
        tr.uninstall()


def test_flight_recorder_rate_limited(tmp_path):
    tr = obs.Tracer(out_path=str(tmp_path / "t.json"))
    with tr:
        now = time.monotonic()
        tr.record("step", now, now)
        assert tr.flight_dump("storm") is not None
        assert tr.flight_dump("storm") is None  # within cooldown
        assert tr.flight_dump("other") is not None  # distinct reason


# --------------------- report: gap attribution ------------------------------


def _synthetic_engine_trace(tmp_path):
    """10 steps of 10ms: 2ms sched, 3ms decode_dispatch wrapping 2ms
    device_wait, 1ms sample; 4ms of the step unattributed; 2ms idle
    between steps.  Wall = 118ms (last idle gap not included)."""
    tr = obs.Tracer(service="synth", out_path=str(tmp_path / "synth.json"))
    base = time.monotonic()
    for i in range(10):
        t0 = base + i * 0.012
        tr.record("sched", t0, t0 + 0.002, None, None, "sched:eng")
        tr.record("device_wait", t0 + 0.003, t0 + 0.005, None, None,
                  "sched:eng")
        tr.record("decode_dispatch", t0 + 0.002, t0 + 0.005,
                  {"cont": i % 2 == 0, "k": 4, "lanes": 2}, None,
                  "sched:eng")
        tr.record("sample", t0 + 0.005, t0 + 0.006, None, None, "sched:eng")
        tr.record("step", t0, t0 + 0.010, None, None, "sched:eng")
    return tr.dump()


def test_report_partition_sums_to_wall(tmp_path):
    path = _synthetic_engine_trace(tmp_path)
    rep = report_paths([path])
    gap = rep["gap"]
    # the named phases + idle partition the engine wall time (±1% — the
    # acceptance bar; here it is exact by construction)
    assert abs(sum(gap["wall_fractions"].values()) - 1.0) < 0.01
    assert gap["engine_wall_s"] == pytest.approx(0.118, rel=0.01)
    assert gap["cont_burst_frac"] == 0.5
    # per-phase self time: decode_dispatch is 3ms with 2ms of
    # device_wait nested inside -> 1ms self per step
    assert gap["wall_fractions"]["device_wait"] == pytest.approx(
        0.020 / 0.118, abs=0.01)
    assert gap["wall_fractions"]["decode_dispatch"] == pytest.approx(
        0.010 / 0.118, abs=0.01)
    assert gap["wall_fractions"]["step_other"] == pytest.approx(
        0.040 / 0.118, abs=0.01)
    assert gap["wall_fractions"]["idle"] == pytest.approx(
        0.018 / 0.118, abs=0.02)
    assert gap["sched_overhead_frac"] == pytest.approx(
        0.060 / 0.118, abs=0.02)
    assert rep["kinds"]["decode_dispatch"]["count"] == 10
    assert rep["kinds"]["step"]["p95_ms"] == pytest.approx(10.0, rel=0.01)


def test_report_zero_duration_span_does_not_swallow_track(tmp_path):
    """A zero-width span (coarse clock) must not become a ghost entry
    in the self-time sweep that eats the track's unattributed time."""
    tr = obs.Tracer(service="z", out_path=str(tmp_path / "z.json"))
    base = time.monotonic()
    tr.record("step", base, base + 0.100, None, None, "sched:eng")
    tr.record("sched", base, base, None, None, "sched:eng")  # dur 0
    tr.record("decode_dispatch", base + 0.010, base + 0.030, None, None,
              "sched:eng")
    gap = report_paths([tr.dump()])["gap"]
    assert gap["wall_fractions"].get("sched", 0.0) == 0.0
    assert gap["wall_fractions"]["step_other"] == pytest.approx(0.8,
                                                                abs=0.01)
    assert abs(sum(gap["wall_fractions"].values()) - 1.0) < 0.01


def test_report_cli_runs_on_fixture(tmp_path):
    path = _synthetic_engine_trace(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.obs.report", path,
         "--indent", "0"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rep = json.loads(r.stdout)
    assert abs(sum(rep["gap"]["wall_fractions"].values()) - 1.0) < 0.01


def test_report_on_live_mocker_run(tmp_path):
    """End to end on a real (simulated) serving run: spans recorded by
    the mocker engine reduce to a partition that covers ≥95% of wall."""

    async def main():
        eng = MockEngine(MockEngineArgs(
            model_name="m", block_size=4, base_step_s=0.002))
        reqs = [PreprocessedRequest(
            token_ids=list(range(30 + i)), request_id=f"r{i}",
            stop=StopConditions(max_tokens=20, ignore_eos=True))
            for i in range(3)]

        async def drive(req):
            async for _ in eng.generate(req):
                pass

        await asyncio.gather(*(drive(r) for r in reqs))
        await eng.close()

    tr = obs.Tracer(out_path=str(tmp_path / "live.json")).install()
    try:
        asyncio.run(main())
        path = tr.dump()
    finally:
        tr.uninstall()
    gap = report_paths([path])["gap"]
    named = sum(v for k, v in gap["wall_fractions"].items() if k != "idle")
    assert named >= 0.95  # phases explain ≥95% of engine wall time
    assert abs(sum(gap["wall_fractions"].values()) - 1.0) < 0.01


# --------------------- cross-process trace stitching ------------------------


async def test_frontend_worker_trace_id_stitching(tmp_path, monkeypatch):
    """With tracing enabled and NO inbound traceparent, the frontend
    mints a trace_id; the request_end record, the frontend `request`
    span, and the worker's `worker_request` span all share it."""
    from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher

    trace_file = tmp_path / "rt.jsonl"
    monkeypatch.setenv("DYN_REQUEST_TRACE", "1")
    monkeypatch.setenv("DYN_REQUEST_TRACE_FILE_PATH", str(trace_file))
    tr = obs.Tracer().install()
    rt = await fresh_runtime().start()
    worker = await MockerWorker(rt, MockEngineArgs(
        model_name="stitch-model", block_size=4, base_step_s=0.0005,
        prefill_s_per_token=0.0, decode_s_per_seq=0.0)).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get("stitch-model"):
            break
        await asyncio.sleep(0.02)
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "stitch-model",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4, "ignore_eos": True}
            async with s.post(f"http://127.0.0.1:{port}/v1/chat/completions",
                              json=body) as r:
                assert r.status == 200
        rec = json.loads(trace_file.read_text().strip().splitlines()[-1])
        tid = rec["trace"]["trace_id"]
        assert tid and len(tid) == 32
        spans = list(tr.spans)
        req_span = next(s for s in spans if s[0] == "request")
        wrk_span = next(s for s in spans if s[0] == "worker_request")
        assert req_span[5] == tid
        assert wrk_span[5] == tid  # worker joined via the annotation
        assert wrk_span[4]["tokens"] == 4
        # the MDC advertises the capability while tracing is on
        assert worker.card.runtime_config.get("tracing") is True
    finally:
        tr.uninstall()
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()


# --------------------- request_end on error paths ---------------------------


async def test_request_end_emitted_on_drain_abort(tmp_path, monkeypatch):
    """A drain-abort with no migration budget must still emit the
    request_end record, error field populated with the drain marker."""
    from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher

    trace_file = tmp_path / "rt.jsonl"
    monkeypatch.setenv("DYN_REQUEST_TRACE", "1")
    monkeypatch.setenv("DYN_REQUEST_TRACE_FILE_PATH", str(trace_file))
    rt = await fresh_runtime().start()
    worker = await MockerWorker(rt, MockEngineArgs(
        model_name="drain-model", block_size=4, base_step_s=0.01)).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get("drain-model"):
            break
        await asyncio.sleep(0.02)
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "drain-model",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 500, "ignore_eos": True, "stream": True}

            async def request_task():
                async with s.post(
                    f"http://127.0.0.1:{port}/v1/chat/completions",
                    json=body,
                ) as r:
                    assert r.status == 200
                    return await r.read()

            task = asyncio.create_task(request_task())
            await asyncio.sleep(0.15)  # stream under way
            await worker.drain(deadline_s=0.05)
            await task
        recs = [json.loads(x) for x in
                trace_file.read_text().strip().splitlines()]
        assert len(recs) == 1  # finish() is idempotent: exactly one
        assert "worker draining" in recs[0]["request"]["error"]
    finally:
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()


async def test_request_end_emitted_on_worker_death(tmp_path, monkeypatch):
    """Migration budget exhausted (limit 0, worker dies mid-decode):
    request_end carries the death marker instead of vanishing."""
    from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher

    trace_file = tmp_path / "rt.jsonl"
    monkeypatch.setenv("DYN_REQUEST_TRACE", "1")
    monkeypatch.setenv("DYN_REQUEST_TRACE_FILE_PATH", str(trace_file))
    rt = await fresh_runtime().start()
    worker = await MockerWorker(rt, MockEngineArgs(
        model_name="dead-model", block_size=4, base_step_s=0.0005,
        fail_after_tokens=3)).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get("dead-model"):
            break
        await asyncio.sleep(0.02)
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "dead-model",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 64, "ignore_eos": True}
            async with s.post(f"http://127.0.0.1:{port}/v1/chat/completions",
                              json=body) as r:
                assert r.status == 500
        recs = [json.loads(x) for x in
                trace_file.read_text().strip().splitlines()]
        assert len(recs) == 1
        assert "connection lost" in recs[0]["request"]["error"]
    finally:
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()


def test_on_dispatch_counts_same_instance_redispatch():
    """A token-replay that lands back on the SAME instance (avoid set
    relaxed) is still a migration the record must count."""
    from dynamo_tpu.frontend.request_trace import RequestTracker

    tr = RequestTracker(request_id="r", model="m")
    tr.on_dispatch(7)
    tr.on_dispatch(7)  # re-dispatch to the same worker
    tr.on_dispatch(7)
    rec = tr.finish(error="died twice, same worker revived")
    assert rec["request"]["migrations"] == 2
    assert rec["request"]["worker"]["decode_worker_id"] == 7


def test_finish_is_idempotent():
    from dynamo_tpu.frontend.request_trace import (
        RequestTracker, TraceConfig, TraceSink)

    class CountingSink(TraceSink):
        def __init__(self):
            super().__init__(TraceConfig(enabled=True, sinks=()))
            self.n = 0

        def emit(self, record):
            self.n += 1

    sink = CountingSink()
    tr = RequestTracker(request_id="r", model="m", sink=sink)
    first = tr.finish(finish_reason="stop")
    second = tr.finish(error="late teardown exception")
    assert first is second and sink.n == 1
    assert "error" not in first["request"]  # the clean record won


# --------------------- FPM aggregates on /metrics ---------------------------


def test_fpm_window_decode_tokens_per_s():
    from dynamo_tpu.planner.metrics import FpmWindow

    fw = FpmWindow()
    for _ in range(10):
        # 4 tokens x 2 lanes per 10ms gap -> 800 tok/s
        fw.add(1, {"kind": "decode", "k": 4, "lanes": 2, "gap_s": 0.01})
    fw.add(1, {"kind": "decode", "k": 4, "lanes": 2, "gap_s": 0.0})  # idle
    assert fw.decode_tokens_per_s() == pytest.approx(800.0)
    assert fw.decode_itl_s() == pytest.approx(0.01 / 4)


async def test_worker_exports_fpm_gauges_on_metrics():
    """The mocker worker (same path as the JAX worker) surfaces FPM
    aggregates as gauges: a spec-decoding run leaves
    dynamo_engine_spec_acceptance on /metrics."""
    rt = await fresh_runtime().start()
    worker = await MockerWorker(rt, MockEngineArgs(
        model_name="fpm-model", block_size=4, base_step_s=0.0005,
        speculative={"k": 4, "acceptance": 0.7})).start()
    client = await (rt.namespace("dynamo").component("mocker")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    req = PreprocessedRequest(
        token_ids=list(range(16)), request_id="r1",
        stop=StopConditions(max_tokens=24, ignore_eos=True))
    async for _ in client.generate(req.to_dict()):
        pass
    text = ""
    for _ in range(40):  # wait out a load-loop tick
        await asyncio.sleep(0.1)
        text = rt.metrics.render().decode()
        if "dynamo_engine_spec_acceptance" in text:
            break
    assert "dynamo_engine_spec_acceptance" in text
    await client.close()
    await worker.close()
    await rt.shutdown()


def test_trace_id_from_annotations():
    tid = "0af7651916cd43dd8448eb211c80319c"
    assert obs.trace_id_from_annotations(
        [f"traceparent:00-{tid}-b7ad6b7169203331-01"]) == tid
    assert obs.trace_id_from_annotations(["traceparent:junk"]) is None
    assert obs.trace_id_from_annotations([]) is None
    assert obs.trace_id_from_annotations(None) is None
