"""SLA planner: profiler sweep, perf-model interpolation/inversion, and
the PROPOSE loop holding latency targets (ref planner-design.md
"Throughput-Based Scaling": predict traffic -> invert perf model under
TTFT/ITL SLAs -> replica targets)."""

import pytest
import asyncio
import math
import uuid

from dynamo_tpu.mocker import MockEngine, MockEngineArgs
from dynamo_tpu.planner import PerfModel, Planner, PlannerConfig, make_predictor
from dynamo_tpu.planner.metrics import AggregateLoad, LoadObserver
from dynamo_tpu.profiler import PerfPoint, PerfProfile, profile_engine
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig


def synthetic_profile(base=0.002, per_seq=0.001, prefill_per_tok=0.00002):
    """Profile of a linear-timing engine (the mocker's model): ITL grows
    with concurrency, TTFT with ISL and queueing."""
    prof = PerfProfile(model_name="synth")
    for isl in (128, 512):
        for c in (1, 2, 4, 8, 16):
            itl = base + per_seq * c
            ttft = (base + prefill_per_tok * isl) * (1 + 0.3 * (c - 1))
            prof.points.append(PerfPoint(
                isl=isl, osl=32, concurrency=c,
                ttft_p50_s=ttft * 0.9, ttft_p95_s=ttft,
                itl_mean_s=itl * 0.95, itl_p95_s=itl,
                req_per_s=c / (ttft + 32 * itl),
                output_tok_per_s=32 * c / (ttft + 32 * itl),
            ))
    return prof


# ----------------------------- profiler ----------------------------------


# profiler sweep: CPU-bound host math runs in the test coroutine —
# borderline against the loop gate under suite load (harness cost,
# not a serving path)
@pytest.mark.allow_slow_callbacks
async def test_profile_mock_engine_latency_surface():
    """The sweep recovers the mocker's polynomial timing model: ITL rises
    with concurrency, TTFT rises with ISL."""
    engine = MockEngine(MockEngineArgs(
        base_step_s=0.001, prefill_s_per_token=0.00002,
        decode_s_per_seq=0.0005, max_batch_tokens=512,
    ))
    try:
        prof = await profile_engine(
            engine, model_name="mock", isls=(32, 256), osl=8,
            concurrencies=(1, 8), rounds=2,
        )
    finally:
        await engine.close()
    assert len(prof.points) == 4
    by = {(p.isl, p.concurrency): p for p in prof.points}
    # ITL at c=8 must exceed c=1 (decode_s_per_seq dominates)
    assert by[(32, 8)].itl_mean_s > by[(32, 1)].itl_mean_s
    # TTFT at isl=256 must exceed isl=32 at the same concurrency
    assert by[(256, 1)].ttft_p95_s > by[(32, 1)].ttft_p95_s
    # round-trip through JSON preserves the surface
    prof2 = PerfProfile.from_json(prof.to_json())
    assert prof2.points[0].itl_mean_s == prof.points[0].itl_mean_s


# ---------------------------- perf model ----------------------------------


def test_perf_model_interpolation_and_inversion():
    pm = PerfModel(synthetic_profile())
    # interpolation between grid points: itl(6) between itl(4) and itl(8)
    assert pm.itl(4) < pm.itl(6) < pm.itl(8)
    # inversion: target 0.007 = base+per_seq*5 -> capacity ~5 seqs
    cap = pm.max_active_for_itl(0.007)
    assert 4.0 <= cap <= 6.0, cap
    # extrapolation past the grid: target beyond c=16 still inverts
    assert pm.max_active_for_itl(0.030) > 16.0
    # unattainable ITL floors at 0.5 (over-provision, never div-zero)
    assert pm.max_active_for_itl(0.0001) == 0.5
    # TTFT rate capacity: looser target admits more throughput
    tight = pm.max_rps_for_ttft(128, 0.003)
    loose = pm.max_rps_for_ttft(128, 0.02)
    assert loose >= tight > 0
    # ISL interpolation: TTFT at 300 sits between the 128 and 512 curves
    assert pm.ttft(128, 1) < pm.ttft(300, 1) < pm.ttft(512, 1)


def test_perf_model_conservative_on_noisy_profile():
    """A p95 outlier mid-grid (1-core measurement noise) must not let
    linear extrapolation invent infinite capacity past the grid — found
    live: planner refused to scale because itl(32) extrapolated negative."""
    prof = PerfProfile(model_name="noisy")
    for c, itl in ((1, 0.0034), (4, 0.1249), (8, 0.0062)):
        prof.points.append(PerfPoint(isl=64, osl=8, concurrency=c,
                                     ttft_p95_s=0.01, itl_p95_s=itl,
                                     itl_mean_s=itl, req_per_s=c * 10.0))
    pm = PerfModel(prof)
    # beyond the grid the estimate never drops below the last sample
    assert pm.itl(32) >= 0.0062
    # capacity under a 4ms target stops at the first violation (~1)
    assert pm.max_active_for_itl(0.004) < 1.5


def test_perf_model_online_correction():
    pm = PerfModel(synthetic_profile())
    base_est = pm.itl(4)
    # hardware consistently 2x slower than the stale profile
    for _ in range(50):
        pm.observe_itl(4, base_est * 2.0)
    assert 1.7 <= pm.itl_correction <= 2.1
    # corrected estimate halves the capacity at the same target
    assert pm.max_active_for_itl(0.007) < 4.0
    # correction is clamped against pathological samples
    for _ in range(100):
        pm.observe_itl(4, 100.0)
    assert pm.itl_correction <= 4.0


# ----------------------------- planner -----------------------------------


class _FakeConnector:
    def __init__(self, replicas=1):
        self.replicas = replicas
        self.calls = []

    async def current_replicas(self):
        return self.replicas

    async def scale(self, n):
        self.calls.append(n)
        self.replicas = n
        return n


class _FakeObserver:
    def __init__(self):
        self.load = None

    async def start(self):
        return self

    async def close(self):
        pass

    def aggregate(self):
        return self.load


def _sla_planner(cfg, conn, pm):
    p = Planner.__new__(Planner)
    p.config = cfg
    p.connector = conn
    p.observer = _FakeObserver()
    p.fpm = None
    p.slo = None
    p._storm_warned = 0
    p.predictor = make_predictor("constant")
    p.rate_predictor = make_predictor("constant")
    p.perf_model = pm
    p._task = None
    p._last_action_t = 0.0
    p._low_ticks = 0
    p.decisions = []
    return p


async def test_sla_planner_holds_itl_slo_on_ramp():
    """Ramping active sequences: replicas grow so per-replica concurrency
    stays within the perf model's ITL capacity."""
    pm = PerfModel(synthetic_profile())
    cfg = PlannerConfig(mode="sla", itl_target_s=0.007, cooldown_s=0.0,
                        min_replicas=1, max_replicas=8, max_step=8,
                        down_stable_ticks=1)
    conn = _FakeConnector(replicas=1)
    p = _sla_planner(cfg, conn, pm)
    cap = pm.max_active_for_itl(0.007)

    for active in (4, 10, 22, 38):
        p.observer.load = AggregateLoad(workers=conn.replicas,
                                        active_seqs=active,
                                        mean_kv_usage=0.2, mean_isl=128)
        p.predictor = make_predictor("constant")
        await p.tick()
        want = math.ceil(active / cap)
        assert conn.replicas == min(want, 8), (active, conn.replicas)
        # the SLO holds at the applied fleet size
        assert pm.itl(active / conn.replicas) <= 0.007 * 1.05

    # drain scales back down to min
    p.observer.load = AggregateLoad(workers=conn.replicas, active_seqs=0,
                                    mean_kv_usage=0.0)
    p.predictor = make_predictor("constant")
    p.rate_predictor = make_predictor("constant")
    for _ in range(8):
        await p.tick()
    assert conn.replicas == 1


async def test_sla_planner_ttft_bound_scales_on_arrival_rate():
    """Low active count but high arrival rate: the TTFT/rate bound must
    drive scaling even when the ITL bound is satisfied."""
    pm = PerfModel(synthetic_profile())
    cfg = PlannerConfig(mode="sla", itl_target_s=0.02,
                        ttft_target_s=0.004, cooldown_s=0.0,
                        min_replicas=1, max_replicas=16, max_step=16)
    conn = _FakeConnector(replicas=1)
    p = _sla_planner(cfg, conn, pm)
    rps_cap = pm.max_rps_for_ttft(128, 0.004)
    p.observer.load = AggregateLoad(workers=1, active_seqs=2,
                                    mean_kv_usage=0.1, req_per_s=rps_cap * 5,
                                    mean_isl=128)
    applied = await p.tick()
    assert applied == math.ceil(5.0), applied  # 5x one replica's capacity


def test_sla_mode_requires_perf_model():
    try:
        Planner(None, "ns", "c", _FakeConnector(),
                PlannerConfig(mode="sla", itl_target_s=0.01))
        raise AssertionError("sla mode without perf model must raise")
    except ValueError:
        pass


# ----------------------------- observer -----------------------------------


async def test_observer_differentiates_counters_into_rates():
    """Cumulative requests/prompt-token counters become windowed arrival
    rate and mean ISL; counter resets (worker restart) are discarded."""
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    rt = await DistributedRuntime(
        config=cfg, cluster_id=uuid.uuid4().hex).start()
    obs = await LoadObserver(rt, "dynamo", "backend",
                             rate_window_s=30.0).start()
    subj = "load_metrics.dynamo.backend"
    # 20 requests of 256 tokens over the sample stream
    for i in range(5):
        await rt.event_plane.publish(subj, {
            "worker_id": 1, "active_seqs": 4, "kv_usage": 0.3,
            "requests_total": i * 5, "prompt_tokens_total": i * 5 * 256,
            "itl_ema_s": 0.004,
        })
        await asyncio.sleep(0.05)
    agg = obs.aggregate()
    assert agg.req_per_s > 0
    assert abs(agg.mean_isl - 256) < 1e-6
    assert abs(agg.mean_itl_s - 0.004) < 1e-9

    # reset: counters go backwards -> window discarded, no negative rates
    await rt.event_plane.publish(subj, {
        "worker_id": 1, "active_seqs": 0, "kv_usage": 0.0,
        "requests_total": 2, "prompt_tokens_total": 512,
    })
    await asyncio.sleep(0.05)
    assert obs.aggregate().req_per_s >= 0.0
    await obs.close()
    await rt.shutdown()


# ------------------------------- e2e --------------------------------------


async def test_sla_planner_e2e_profile_then_plan_mocker():
    """The full bootstrap chain on CPU: profile the mocker, build the perf
    model, and verify the SLA proposer sizes a fleet for a load the
    load-mode constant would get wrong."""
    engine = MockEngine(MockEngineArgs(
        base_step_s=0.001, prefill_s_per_token=0.00001,
        decode_s_per_seq=0.0005,
    ))
    try:
        prof = await profile_engine(engine, isls=(64,), osl=8,
                                    concurrencies=(1, 4, 16), rounds=2)
    finally:
        await engine.close()
    pm = PerfModel(prof)

    # target just above the c=4 ITL: capacity lands in [4, 16)
    target = pm.itl(4) * 1.2
    cap = pm.max_active_for_itl(target)
    assert 4.0 <= cap <= 16.0, (target, cap)

    cfg = PlannerConfig(mode="sla", itl_target_s=target, cooldown_s=0.0,
                        min_replicas=1, max_replicas=8, max_step=8)
    conn = _FakeConnector(replicas=1)
    p = _sla_planner(cfg, conn, pm)
    p.observer.load = AggregateLoad(workers=1, active_seqs=32,
                                    mean_kv_usage=0.2, mean_isl=64)
    applied = await p.tick()
    assert applied == min(8, math.ceil(32 / cap))


# ------------------------------- FPM --------------------------------------


async def test_fpm_observer_derives_itl_and_prefill_rate():
    """The FpmObserver turns per-program dispatch records into a fleet
    decode ITL (gap per fused step) and a prefill token rate."""
    from dynamo_tpu.planner.metrics import FpmObserver

    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    rt = await DistributedRuntime(
        config=cfg, cluster_id=uuid.uuid4().hex).start()
    obs = await FpmObserver(rt, "dynamo", "backend").start()
    await asyncio.sleep(0.05)  # let the subscription attach
    subj = "fpm.dynamo.backend"
    # 16-step bursts dispatched every 64ms -> 4ms per token-step
    await rt.event_plane.publish(subj, {"worker_id": 1, "steps": [
        {"t": i * 0.064, "kind": "decode", "k": 16, "lanes": 8,
         "gap_s": 0.064} for i in range(10)
    ]})
    # two prefill programs ~0.1s apart totalling 4096 tokens
    await rt.event_plane.publish(subj, {"worker_id": 1, "steps": [
        {"t": 0.0, "kind": "prefill", "rows": 2, "tokens": 2048},
    ]})
    await asyncio.sleep(0.1)
    await rt.event_plane.publish(subj, {"worker_id": 1, "steps": [
        {"t": 0.1, "kind": "prefill", "rows": 2, "tokens": 2048},
    ]})
    await asyncio.sleep(0.05)
    assert abs(obs.decode_itl_s() - 0.004) < 1e-6
    rate = obs.prefill_tokens_per_s()
    assert rate > 0  # window spans the two publishes
    await obs.close()
    await rt.shutdown()


# real JAX engine in an async body: -O0 compiles dwarf the 200ms
# loop gate (see conftest); mocker-based tests here stay gated
@pytest.mark.allow_slow_callbacks
async def test_fpm_prefill_mfu_queue_depth_and_single_record_rate():
    """The chunked-prefill FPM fields flow end-to-end: records produced
    by the ENGINE's own _fpm_prefill (gap/flops/mfu/queue_depth) publish
    onto the event plane and aggregate through the FpmObserver into
    prefill-phase MFU and chunk-queue depth; and a window holding a
    SINGLE prefill record reports a nonzero token rate (tokens/window_s
    floor) instead of 0.0."""
    import time as _time

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.planner.metrics import FpmObserver

    import jax.numpy as jnp
    tiny = LlamaConfig(name="tiny32", vocab_size=64, d_model=16,
                       n_layers=1, n_heads=2, n_kv_heads=1, head_dim=8,
                       ffn_dim=32, dtype=jnp.float32)
    eng = JaxEngine(EngineConfig(model_config=tiny, block_size=4,
                                 num_blocks=8, max_blocks_per_seq=4,
                                 max_num_seqs=2, prefill_buckets=(8,),
                                 peak_tflops=1e-6))
    # two dispatch records in quick succession: the second carries a real
    # gap, a FLOPs estimate, and (peak_tflops pinned + a device sync
    # inside the gap) the MFU itself
    eng._fpm_prefill(rows=1, tokens=8, bucket=8, packed=True)
    _time.sleep(0.01)
    eng._fpm_sync_t = _time.monotonic()  # blocking fetch inside the gap
    eng._fpm_prefill(rows=2, tokens=16, bucket=16, packed=True)
    recs = [r for r in eng.fpm if r["kind"] == "prefill"]
    await eng.close()
    assert recs[-1]["gap_s"] > 0.0 and recs[-1]["flops"] > 0
    assert recs[-1]["mfu"] > 0.0
    assert "queue_depth" in recs[-1]

    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    rt = await DistributedRuntime(
        config=cfg, cluster_id=uuid.uuid4().hex).start()
    obs = await FpmObserver(rt, "dynamo", "backend",
                            window_s=20.0).start()
    await asyncio.sleep(0.05)
    subj = "fpm.dynamo.backend"
    await rt.event_plane.publish(subj, {"worker_id": 1, "steps": recs})
    # a second worker that does NOT know its peak publishes flops+gap
    # plus a single-record window for the rate fallback
    await rt.event_plane.publish(subj, {"worker_id": 2, "steps": [
        {"t": 5.0, "kind": "prefill", "rows": 1, "tokens": 4096,
         "gap_s": 0.5, "flops": 1e9, "queue_depth": 3},
    ]})
    await asyncio.sleep(0.05)
    assert obs.prefill_mfu() > 0.0          # from worker 1's mfu records
    # worker 2's single record: rate floors at tokens/window_s, not 0.0
    assert obs.prefill_tokens_per_s() > 4096 / 20.0 - 1e-6
    # fleet chunk-queue depth sums each worker's latest record
    depth = obs.prefill_queue_depth()
    assert depth == recs[-1]["queue_depth"] + 3
    await obs.close()
    await rt.shutdown()


async def test_sla_planner_consumes_live_fpm_stream():
    """End-to-end: FPM records published on the event plane reach the SLA
    planner's perf-model regression (the correction moves toward the
    measured ITL, and the tick diagnostics carry fpm_itl_s)."""
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    rt = await DistributedRuntime(
        config=cfg, cluster_id=uuid.uuid4().hex).start()
    pm = PerfModel(synthetic_profile())
    pcfg = PlannerConfig(mode="sla", itl_target_s=0.007, cooldown_s=0.0,
                         min_replicas=1, max_replicas=8, max_step=8,
                         consume_fpm=True)
    conn = _FakeConnector(replicas=1)
    p = Planner(rt, "dynamo", "backend", conn, config=pcfg, perf_model=pm)
    await p.start()
    await asyncio.sleep(0.05)  # let the subscriptions attach
    try:
        # the model predicts ~6ms at c=4; the live fleet measures 12ms
        await rt.event_plane.publish("fpm.dynamo.backend", {
            "worker_id": 7, "steps": [
                {"t": i * 0.2, "kind": "decode", "k": 16, "lanes": 4,
                 "gap_s": 0.192} for i in range(8)
            ]})
        await rt.event_plane.publish(
            "load_metrics.dynamo.backend",
            {"worker_id": 7, "active_seqs": 4, "kv_usage": 0.2,
             "requests_total": 10, "prompt_tokens_total": 1280,
             "itl_ema_s": 0.001})  # the coarse EMA disagrees; FPM wins
        await asyncio.sleep(0.1)
        before = pm.itl_correction
        await p.tick()
        assert pm.itl_correction > before  # corrected UP toward 12ms
        assert p.fpm is not None
        assert abs(p.fpm.decode_itl_s() - 0.012) < 1e-6
    finally:
        await p.close()
        await rt.shutdown()
