"""Overlapped-scheduler composition suite (ROADMAP item 3 / PR 11).

The contract under test: `overlap_scheduling=True` (pipelined decode,
deferred prefill first-token readback, adaptive decode fusion,
enqueue-ahead spans) is **greedy byte-identical** to the lockstep sync
mode across the composition matrix — mixed prefill/decode arrivals,
mid-stream cancellation, drain_abort, chaos-seeded step delays — plus
the scheduler-policy properties themselves: adaptive fusion ramps up a
decode-only stretch and de-fuses within one step of a new arrival,
serving steady state triggers ZERO recompiles (the packed-prefill
committed-KV executable fork regression), and SLA-aware admission
shrinks prefill chunks under SLO burn.

Everything here runs CPU-only (JAX_PLATFORMS=cpu) in tier-1 — the
`overlap` marker exists so the mode's smoke can be selected explicitly.
"""

import asyncio

import jax.numpy as jnp
import pytest

# real-JAX-engine tests: XLA compiles and device work run inside the
# async bodies; the conftest slow-callback gate cannot hold here (same
# opt-out as tests/test_engine.py)
pytestmark = [pytest.mark.overlap, pytest.mark.allow_slow_callbacks]

from dynamo_tpu import chaos
from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.protocols import (
    DRAIN_ABORT,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

FP32 = LlamaConfig(name="tiny32", vocab_size=256, d_model=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
                   dtype=jnp.float32)


def engine(**kw):
    defaults = dict(model_config=FP32, block_size=4, num_blocks=128,
                    max_blocks_per_seq=16, max_num_seqs=4,
                    prefill_buckets=(8, 16, 32, 64), seed=7)
    defaults.update(kw)
    return JaxEngine(EngineConfig(**defaults))


def greedy_req(tokens, n, rid, seed=0):
    return PreprocessedRequest(
        token_ids=tokens, request_id=rid,
        sampling=SamplingOptions(temperature=0.0, seed=seed),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )


async def collect(eng, req, token=None):
    toks = []
    async for out in eng.generate(req, token=token):
        if out.finish_reason == "error":
            raise RuntimeError(out.error)
        toks.extend(out.token_ids)
    return toks


PROMPTS = [
    list(range(7, 20)),            # 13 tokens
    list(range(40, 49)),           # 9 tokens
    list(range(7, 15)),            # shares a 2-block prefix with [0]
]


async def _staggered_run(overlap: bool, tag: str, stagger_s=0.2,
                         n_tokens=14, **cfg):
    """Three requests arriving mid-each-other's decode: the mixed
    prefill/decode regime the overlapped scheduler reorders most."""
    eng = engine(overlap_scheduling=overlap, **cfg)

    async def one(i, delay):
        await asyncio.sleep(delay)
        return await collect(
            eng, greedy_req(PROMPTS[i], n_tokens, f"{tag}-r{i}"))

    outs = await asyncio.gather(*[
        one(i, i * stagger_s) for i in range(len(PROMPTS))])
    metrics = dict(eng.metrics)
    await eng.close()
    return outs, metrics


async def test_greedy_byte_identity_mixed_arrivals():
    """The headline contract: overlapped scheduling is greedy
    byte-identical to lockstep sync under staggered mixed
    prefill/decode arrivals (deferred first tokens, pipelined bursts,
    adaptive fusion and all)."""
    sync_outs, _ = await _staggered_run(False, "sync")
    over_outs, m = await _staggered_run(True, "over")
    assert over_outs == sync_outs
    # (whether a pure continuation burst engaged is timing-dependent on
    # a -O0 CPU; test_engine's continuation test pins that path — here
    # the contract is the byte identity above)
    assert m["decode_tokens"] > 0


async def test_byte_identity_mid_stream_cancellation():
    """Cancelling one stream mid-decode (token-level teardown racing
    in-flight bursts AND a possibly-deferred first token) must not
    perturb the surviving streams in either mode."""
    from dynamo_tpu.runtime import CancellationToken

    async def run(overlap: bool, tag: str):
        eng = engine(overlap_scheduling=overlap)
        token = CancellationToken()
        victim = greedy_req(list(range(20, 32)), 10_000, f"{tag}-victim")
        got = []

        async def consume():
            async for out in eng.generate(victim, token=token):
                got.append(out)

        vtask = asyncio.create_task(consume())

        async def survivor():
            await asyncio.sleep(0.15)
            return await collect(
                eng, greedy_req(PROMPTS[0], 16, f"{tag}-live"))

        stask = asyncio.create_task(survivor())
        await asyncio.sleep(0.6)
        token.stop()
        await asyncio.wait_for(vtask, timeout=30)
        toks = await asyncio.wait_for(stask, timeout=60)
        assert got[-1].finish_reason == "cancelled"
        # the cancelled slot's teardown frees its blocks on a later step
        for _ in range(600):
            if all(s is None for s in eng._slots) and not eng.waiting:
                break
            await asyncio.sleep(0.05)
        assert all(s is None for s in eng._slots)
        await eng.close()
        return toks

    sync_toks = await run(False, "sync")
    over_toks = await run(True, "over")
    assert over_toks == sync_toks


async def test_drain_abort_mid_overlap():
    """drain_abort with unread bursts + deferred first tokens in flight:
    every stream errors with the migratable DRAIN_ABORT marker, emitted
    tokens are a prefix of the fault-free stream, nothing hangs or
    leaks."""
    # fault-free reference
    ref, _ = await _staggered_run(True, "ref", stagger_s=0.05,
                                  n_tokens=64)

    eng = engine(overlap_scheduling=True)
    streams = {i: [] for i in range(len(PROMPTS))}
    errors = {}

    async def one(i):
        await asyncio.sleep(i * 0.05)
        async for out in eng.generate(
                greedy_req(PROMPTS[i], 64, f"drain-r{i}")):
            if out.finish_reason == "error":
                errors[i] = out.error
                return
            streams[i].extend(out.token_ids)

    tasks = [asyncio.create_task(one(i)) for i in range(len(PROMPTS))]
    await asyncio.sleep(0.8)
    eng.drain_abort()
    await asyncio.wait_for(asyncio.gather(*tasks), timeout=30)
    assert errors, "drain_abort aborted nothing in flight"
    for i, err in errors.items():
        assert DRAIN_ABORT in err
    for i, toks in streams.items():
        assert toks == ref[i][:len(toks)], \
            f"stream {i} diverged from the fault-free prefix"
    await eng.close()


async def test_byte_identity_under_chaos_step_delays():
    """Seeded chaos delays on the engine.step seam jitter the arrival/
    step phase alignment (different fusion ramps, different pipeline
    occupancy) — output must not care, in either mode."""
    plane = chaos.ChaosPlane(seed=23).rule(
        "engine.step", "delay", delay_s=0.02, p=0.25)
    with plane:
        chaos_outs, _ = await _staggered_run(True, "chaos")
    plain_outs, _ = await _staggered_run(True, "plain")
    sync_outs, _ = await _staggered_run(False, "syncref")
    assert chaos_outs == plain_outs == sync_outs


async def test_adaptive_fusion_ramps_and_defuses_on_arrival():
    """A decode-only stretch must ramp the burst size to the full
    decode_fused_steps; a new arrival must de-fuse the NEXT dispatched
    burst to the interleave size (within one step), then re-ramp."""
    eng = engine(overlap_scheduling=True, decode_fused_steps=8,
                 max_num_seqs=2, block_size=16, prefill_buckets=(16, 32))
    r1 = greedy_req(list(range(7, 20)), 80, "ramp-r1")

    async def second():
        await asyncio.sleep(1.0)  # land mid r1's decode-only stretch
        mark = len(eng.fpm)
        toks = await collect(eng, greedy_req(list(range(40, 49)), 8,
                                             "ramp-r2"))
        return mark, toks

    t2 = asyncio.create_task(second())
    toks1 = await collect(eng, r1)
    mark, toks2 = await t2
    assert len(toks1) == 80 and len(toks2) == 8
    recs = list(eng.fpm)
    decode_ks = [r["k"] for r in recs if r["kind"] == "decode"]
    assert max(decode_ks) == 8, "ramp never reached full fusion"
    assert 4 in decode_ks, "interleave rung never dispatched"
    # de-fuse within one step: find r2's prefill dispatch; the decode
    # burst dispatched in that same step (right after it) must be short
    pre_idx = [i for i, r in enumerate(recs)
               if r["kind"] == "prefill" and i >= mark]
    assert pre_idx, "second request's prefill not recorded"
    after = [r["k"] for r in recs[pre_idx[0]:] if r["kind"] == "decode"]
    assert after and after[0] <= JaxEngine.INTERLEAVE_BURST, \
        f"burst after arrival was k={after[0] if after else None}"
    await eng.close()


async def test_serving_steady_state_zero_recompiles():
    """The compile-watchdog acceptance gate: once warmup + the first
    request have compiled every shape serving reaches, further traffic
    of the same shape triggers ZERO compiles — in particular
    prefill_packed compiles exactly once per bucket (the
    committed-vs-uncommitted KV executable fork regression: without
    pinned kv out_shardings, the SECOND packed dispatch after any
    decode recompiled the same bucket)."""
    eng = engine(overlap_scheduling=True)
    await asyncio.to_thread(eng.warmup_decode)
    await collect(eng, greedy_req([5, 9, 13, 2, 7, 11, 3, 1, 8, 20],
                                  24, "warm-r0"))
    counts_after_first = dict(eng.compile_watch.counts)
    assert counts_after_first.get("prefill_packed", 0) == 1
    # same prompt length, different tokens (no prefix hit: differs at 0)
    await collect(eng, greedy_req([6, 10, 14, 3, 8, 12, 4, 2, 9, 21],
                                  24, "warm-r1"))
    await collect(eng, greedy_req([9, 13, 17, 6, 11, 15, 7, 5, 12, 24],
                                  24, "warm-r2"))
    assert dict(eng.compile_watch.counts) == counts_after_first, \
        "steady-state serving recompiled an already-served shape"
    await eng.close()


async def test_slo_yield_shrinks_prefill_chunks_under_burn():
    """SLA-aware admission: with the SLO plane reporting a burn above
    threshold while decodes are live, prefill dispatches yield chunk
    budget (smaller tokens-per-dispatch) and the yield is counted."""

    async def run(burn):
        eng = engine(overlap_scheduling=True, slo_yield_burn=1.0,
                     max_num_seqs=2, num_blocks=256,
                     max_blocks_per_seq=32, block_size=4,
                     prefill_buckets=(8, 16),
                     prefill_chunk_tokens=64)
        if burn:
            eng.set_slo_burn(burn)

        async def long_prompt():
            await asyncio.sleep(0.4)  # arrive while r1 decodes
            return await collect(
                eng, greedy_req(list(range(1, 81)), 2, "slo-long"))

        t2 = asyncio.create_task(long_prompt())
        toks1 = await collect(eng, greedy_req(PROMPTS[0], 48, "slo-r1"))
        toks2 = await t2
        chunks = [r["tokens"] for r in eng.fpm
                  if r["kind"] == "prefill" and r["rows"] == 1
                  and r["tokens"] > 1]
        yields = eng.metrics.get("slo_yield_steps", 0)
        await eng.close()
        return toks1, toks2, max(chunks, default=0), yields

    toks1a, toks2a, max_free, y0 = await run(0.0)
    toks1b, toks2b, max_burn, y1 = await run(8.0)
    assert y0 == 0 and y1 > 0
    # burn=8 vs threshold 1.0 scales the ~62-token budget by 1/8 ->
    # floored near the smallest bucket; the free run keeps big chunks
    assert max_burn < max_free, (max_burn, max_free)
    # and yielding never changes WHAT is generated, only when
    assert (toks1a, toks2a) == (toks1b, toks2b)


async def test_spec_decode_byte_identity_across_modes():
    """Speculative decoding composed with the overlapped scheduler:
    token streams stay byte-identical to sync mode (spec engagement
    cadence may differ — the pipeline coarsens collapsed-slot probes —
    but rejection sampling preserves the greedy stream regardless)."""
    repeat = [5, 9, 13, 2] * 6

    async def run(overlap):
        eng = engine(overlap_scheduling=overlap, spec_decode="ngram",
                     spec_k=4, max_blocks_per_seq=32)
        toks = await collect(eng, greedy_req(repeat, 48, "spec-ov"))
        await eng.close()
        return toks

    assert await run(True) == await run(False)


async def test_guided_disagg_parks_cleanly_under_overlap():
    """A guided + disagg-prefill request defers its first-token readback
    like any other completing prefill; the guided step must NOT touch
    the slot during that one deferred step (a constrained decode there
    would write KV past the prompt and corrupt the parked prompt_len
    the decode side pulls — the review-pass finding)."""
    from dynamo_tpu.protocols.llm import DISAGG_ANNOTATION

    schema = {"type": "object",
              "properties": {"city": {"type": "string"}}}
    prompt = list(range(7, 19))

    async def run(overlap):
        eng = engine(overlap_scheduling=overlap)
        req = PreprocessedRequest(
            token_ids=prompt, request_id=f"gd-{overlap}",
            sampling=SamplingOptions(temperature=0.0,
                                     guided_json=schema),
            stop=StopConditions(max_tokens=32, ignore_eos=True),
            annotations=[DISAGG_ANNOTATION],
        )
        outs = []
        async for out in eng.generate(req):
            outs.append(out)
        parked = dict(eng._parked)
        await eng.close()
        return outs, parked

    for overlap in (False, True):
        outs, parked = await run(overlap)
        # exactly one output: the park finish with transfer params
        assert len(outs) == 1 and outs[0].finish_reason == "stop"
        params = outs[0].kv_transfer_params
        assert params is not None
        assert params["prompt_len"] == len(prompt), \
            f"overlap={overlap}: parked prompt_len corrupted"
        (rid, p), = parked.items()
        assert p.prompt_len == len(prompt)


async def test_mocker_overlap_byte_identity_and_cont_bursts():
    """The mocker's overlap sim: identical token streams either mode,
    and the overlapped run emits fused continuation decode dispatches
    (the bench gap line's cont_burst_frac source)."""
    from dynamo_tpu import obs
    from dynamo_tpu.mocker import MockEngineArgs
    from dynamo_tpu.mocker.engine import MockEngine

    async def run(overlap):
        eng = MockEngine(MockEngineArgs(
            model_name="m", block_size=4, base_step_s=0.0,
            prefill_s_per_token=0.0, decode_s_per_seq=0.0,
            overlap_scheduling=overlap, decode_fused_steps=8))
        req = PreprocessedRequest(
            token_ids=list(range(40)), request_id="same-rid",
            stop=StopConditions(max_tokens=48, ignore_eos=True))
        toks = []
        tr = obs.Tracer().install()
        try:
            async for out in eng.generate(req):
                toks.extend(out.token_ids)
        finally:
            tr.uninstall()
            await eng.close()
        decodes = [s for s in tr.spans if s[0] == "decode_dispatch"]
        return toks, decodes

    sync_toks, sync_d = await run(False)
    over_toks, over_d = await run(True)
    assert over_toks == sync_toks and len(over_toks) == 48
    assert all((s[4] or {}).get("k", 1) == 1 for s in sync_d)
    over_ks = [(s[4] or {}).get("k", 1) for s in over_d]
    over_cont = [(s[4] or {}).get("cont") for s in over_d]
    assert max(over_ks) == 8, "overlap sim never fused"
    assert any(over_cont), "overlap sim never marked a continuation"
    # fused bursts amortize dispatches: strictly fewer of them
    assert len(over_d) < len(sync_d)
