"""Int8 KV-cache quantization subsystem (quant/kv.py + engine
kv_cache_dtype="int8"): primitive error bounds, end-to-end greedy
parity vs bf16, exact scale round-trips through the KVBM tiers and the
disagg wire, capacity sizing, multihost bit-identity, and the
mocker/planner satellites."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks


from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.models import llama
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.ops import paged_attention as pa
from dynamo_tpu.protocols import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.quant.kv import (
    blocks_for_hbm_budget,
    dequantize,
    kv_cache_bytes_per_block,
    quantize_tokens,
)

FP32 = LlamaConfig(name="tiny32", vocab_size=256, d_model=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
                   dtype=jnp.float32)


def engine(**kw):
    defaults = dict(model_config=FP32, block_size=4, num_blocks=128,
                    max_blocks_per_seq=16, max_num_seqs=4,
                    prefill_buckets=(8, 16, 32, 64), seed=7)
    defaults.update(kw)
    return JaxEngine(EngineConfig(**defaults))


def greedy_req(tokens, n, rid, seed=0):
    return PreprocessedRequest(
        token_ids=tokens, request_id=rid,
        sampling=SamplingOptions(temperature=0.0, seed=seed),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )


async def collect(eng, req):
    toks = []
    async for out in eng.generate(req):
        toks.extend(out.token_ids)
    return toks


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 3.0, (64, 4, 32)).astype(np.float32))
    q, scale = quantize_tokens(x)
    assert q.dtype == jnp.int8
    assert scale.shape == (64, 4)
    deq = dequantize(q, scale)
    # symmetric per-token quantization: error <= scale/2 == absmax/254
    # (small fp32 slack: the q*scale product rounds once more)
    err = np.abs(np.asarray(deq - x))
    bound = np.asarray(scale)[..., None] * (0.5 + 1e-5) + 1e-6
    np.testing.assert_array_less(err, np.broadcast_to(bound, err.shape))


def test_quantize_zero_rows_and_extremes():
    x = jnp.zeros((3, 2, 8), jnp.float32)
    q, scale = quantize_tokens(x)
    assert np.all(np.asarray(q) == 0) and np.all(np.asarray(scale) == 0.0)
    np.testing.assert_array_equal(np.asarray(dequantize(q, scale)), 0.0)
    # the absmax element must round-trip to itself exactly
    y = jnp.asarray([[[-5.0, 2.0, 5.0, 0.0]]])
    qy, sy = quantize_tokens(y)
    deq = np.asarray(dequantize(qy, sy))
    assert deq[0, 0, 0] == -5.0 and deq[0, 0, 2] == 5.0


def test_write_sites_quantize_and_gather_dequantizes():
    """Every write op scatters int8 + scales with the same index math;
    _gather_ctx returns the dequantized context within the bound."""
    L, nkv, nb, hd, bs = 2, 2, 9, 8, 4
    rng = np.random.default_rng(1)
    kc = jnp.zeros((L, nkv, nb, hd, bs), jnp.int8)
    vc = jnp.zeros_like(kc)
    ks = jnp.zeros((L, nkv, nb, bs), jnp.float32)
    vs = jnp.zeros_like(ks)
    T = 10
    k = jnp.asarray(rng.normal(0, 2, (T, nkv, hd)).astype(np.float32))
    v = jnp.asarray(rng.normal(0, 2, (T, nkv, hd)).astype(np.float32))
    table = jnp.asarray([3, 5, 7, 0, 0, 0, 0, 0], jnp.int32)
    kc, vc, ks, vs = pa.write_prompt_kv(
        kc, vc, 0, k, v, table, jnp.int32(0), jnp.int32(T),
        k_scale=ks, v_scale=vs)
    got = np.asarray(pa._gather_ctx(kc, 0, table, ks))  # [nkv, S, hd]
    want = np.asarray(k).transpose(1, 0, 2)             # [nkv, T, hd]
    # gathered scale per (head, stream position), same layout as `got`
    scale = np.asarray(ks)[0][:, np.asarray(table)].reshape(nkv, -1)
    err = np.abs(got[:, :T] - want)
    bound = scale[:, :T, None] * (0.5 + 1e-5) + 1e-6
    np.testing.assert_array_less(err, np.broadcast_to(bound, err.shape))
    # decode append into the next free position (block 7, offset T % bs)
    tok_k = jnp.asarray(rng.normal(0, 2, (1, nkv, hd)).astype(np.float32))
    kc, vc, ks, vs = pa.write_token_kv(
        kc, vc, 0, tok_k, tok_k, table[None], jnp.asarray([T], jnp.int32),
        k_scale=ks, v_scale=vs)
    got = np.asarray(pa._gather_ctx(kc, 0, table, ks))
    err = np.abs(got[:, T] - np.asarray(tok_k)[0])
    s = np.asarray(ks)[0, :, 7, T % bs]
    bound = s[:, None] * (0.5 + 1e-5) + 1e-6
    np.testing.assert_array_less(err, np.broadcast_to(bound, err.shape))


def test_bf16_write_path_unchanged():
    """Without scales the write ops return 2-tuples (the pre-quantization
    contract, byte-identical behavior) and the engine default cache stays
    a 2-tuple of the model dtype."""
    kc = jnp.zeros((1, 1, 4, 4, 4), jnp.float32)
    out = pa.write_token_kv(kc, kc, 0, jnp.ones((1, 1, 4)),
                            jnp.ones((1, 1, 4)),
                            jnp.zeros((1, 4), jnp.int32),
                            jnp.zeros((1,), jnp.int32))
    assert len(out) == 2
    eng = engine()
    assert len(eng.kv) == 2 and eng.kv[0].dtype == jnp.float32
    assert eng.kv_dtype == "bf16"


# ---------------------------------------------------------------------------
# engine end-to-end
# ---------------------------------------------------------------------------


async def test_greedy_parity_bf16_vs_int8():
    """Greedy decode with an int8 cache matches bf16 token-for-token on
    the test geometry (per-token scales bound elementwise error at
    absmax/254, far below the argmax margins).  Covers packed chunked
    prefill (long prompt), prefix-cache reuse, and fused decode."""
    e_ref = engine()
    e_q = engine(kv_cache_dtype="int8")
    assert e_q.kv_dtype == "int8" and len(e_q.kv) == 4
    assert e_q.kv[0].dtype == jnp.int8
    assert e_q.kv[2].dtype == jnp.float32
    prompts = [list(range(3, 25)),            # multi-block
               [5, 9] * 40]                   # > largest bucket: chunked
    for i, p in enumerate(prompts):
        ref = await collect(e_ref, greedy_req(p, 8, f"r{i}"))
        got = await collect(e_q, greedy_req(p, 8, f"q{i}"))
        assert got == ref, f"prompt {i}: {got} != {ref}"
    # prefix-cache hit on the quantized cache must preserve output too
    again = await collect(e_q, greedy_req(prompts[0], 8, "q-again"))
    ref = await collect(e_ref, greedy_req(prompts[0], 8, "r-again"))
    assert again == ref
    await e_ref.close()
    await e_q.close()


async def test_speculative_decoding_on_int8_cache():
    """The ngram spec path (packed verify + draft-position KV writes)
    serves the int8 cache: greedy output token-identical to the plain
    int8 engine."""
    kw = dict(kv_cache_dtype="int8", decode_fused_steps=2,
              decode_pipeline_depth=2)
    plain = engine(**kw)
    spec = engine(spec_decode="ngram", spec_k=3, **kw)
    assert spec.spec_enabled
    prompt = [7, 8, 9, 10] * 6  # repetitive: the ngram proposer engages
    want = await collect(plain, greedy_req(prompt, 16, "p"))
    got = await collect(spec, greedy_req(prompt, 16, "s"))
    assert got == want
    # the finish token is emitted INSIDE _spec_step's accept loop and
    # spec_steps increments a few statements later on the scheduler
    # thread — the consumer can observe the finish first, so give the
    # counter a beat before asserting (a loaded suite widens the race)
    for _ in range(100):
        if spec.metrics.get("spec_steps", 0):
            break
        await asyncio.sleep(0.01)
    assert spec.metrics.get("spec_steps", 0) > 0
    await plain.close()
    await spec.close()


def test_mla_family_falls_back_to_bf16():
    from dynamo_tpu.models.deepseek import DeepseekConfig

    mla = DeepseekConfig(
        name="mla-q", vocab_size=256, d_model=64, n_layers=2,
        n_heads=4, q_lora_rank=24, kv_lora_rank=32, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, ffn_dim=128, dtype=jnp.float32)
    eng = JaxEngine(EngineConfig(
        model_config=mla, block_size=4, num_blocks=32,
        max_blocks_per_seq=8, max_num_seqs=2, prefill_buckets=(8, 16),
        kv_cache_dtype="int8"))
    assert eng.kv_dtype == "bf16"
    assert len(eng.kv) == 2 and eng.kv[0].dtype == jnp.float32


def test_invalid_kv_dtype_rejected():
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        engine(kv_cache_dtype="fp8")


# ---------------------------------------------------------------------------
# capacity sizing
# ---------------------------------------------------------------------------


def test_capacity_doubles_within_hbm_budget():
    cfg = llama.PRESETS["llama-3b"]
    b_bf = blocks_for_hbm_budget(llama, cfg, 128, "bf16", 16 * 10**9)
    b_q = blocks_for_hbm_budget(llama, cfg, 128, "int8", 16 * 10**9)
    assert b_q / b_bf >= 1.8, (b_bf, b_q)
    assert kv_cache_bytes_per_block(llama, cfg, 128, "int8") \
        < kv_cache_bytes_per_block(llama, cfg, 128, "bf16")


def test_engine_kv_hbm_budget_sizes_block_pool():
    budget_gb = 0.002  # 2 MB: tiny32 fp32 blocks are 4 KiB
    e_bf = engine(kv_hbm_gb=budget_gb)
    e_q = engine(kv_hbm_gb=budget_gb, kv_cache_dtype="int8")
    nb_bf = e_bf.config.num_blocks
    nb_q = e_q.config.num_blocks
    assert nb_q / nb_bf >= 1.8, (nb_bf, nb_q)
    # the allocator and the device arrays agree with the derived count
    assert e_q.allocator.num_blocks == nb_q
    assert e_q.kv[0].shape[2] == nb_q


# ---------------------------------------------------------------------------
# KVBM tiers: scales round-trip bit-exactly
# ---------------------------------------------------------------------------


def _rand_block(rng, quant):
    k = rng.normal(size=(2, 4, 2, 8)).astype(np.float32)
    v = rng.normal(size=(2, 4, 2, 8)).astype(np.float32)
    if not quant:
        return (k, v)
    ks = rng.random((2, 4, 2)).astype(np.float32)
    vs = rng.random((2, 4, 2)).astype(np.float32)
    return (k.astype(np.int8), v.astype(np.int8), ks, vs)


def test_kvbm_tiers_roundtrip_quantized_blocks(tmp_path):
    """G2 -> G3 demotion -> fetch promotion must return all four payload
    arrays BIT-exact (scales included) — a perturbed scale rescales every
    element of the block."""
    from dynamo_tpu.kvbm import TieredKvManager

    mgr = TieredKvManager(2, disk_dir=str(tmp_path / "g3"), disk_blocks=8,
                          object_dir=str(tmp_path / "g4"))
    rng = np.random.default_rng(3)
    blocks = {h: _rand_block(rng, quant=True) for h in (11, 12, 13)}
    for h, blk in blocks.items():
        mgr.offload(h, *blk)  # capacity 2: 11 demotes to G3
    assert 11 in mgr.g3 and 11 not in mgr.g2
    for h, want in blocks.items():
        got, _events, _src = mgr.fetch(h)
        assert got is not None and len(got) == 4
        for a, b in zip(got, want):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


def test_object_store_roundtrips_quantized_blocks(tmp_path):
    from dynamo_tpu.kvbm.object_store import ObjectStorePool

    pool = ObjectStorePool(str(tmp_path))
    rng = np.random.default_rng(4)
    blk = _rand_block(rng, quant=True)
    assert pool.put(99, *blk)
    got = pool.get(99)
    assert len(got) == 4
    for a, b in zip(got, blk):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)


def test_kvbm_remote_wire_roundtrips_scales():
    from dynamo_tpu.kvbm.remote import decode_block, encode_block

    rng = np.random.default_rng(5)
    blk = _rand_block(rng, quant=True)
    h, *arrays = decode_block(encode_block(42, *blk))
    assert h == 42 and len(arrays) == 4
    for a, b in zip(arrays, blk):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)
    # bf16-era 2-array frames still decode (mixed fleets)
    h, *arrays = decode_block(encode_block(7, *blk[:2]))
    assert len(arrays) == 2


async def test_engine_offload_onboard_int8_preserves_output():
    """Engine-level G2 round trip at int8: prompt A's quantized blocks
    offload under churn, onboard on resubmission (no recompute), and the
    greedy output is unchanged."""
    eng = engine(kv_cache_dtype="int8", num_blocks=16,
                 max_blocks_per_seq=8, host_cache_blocks=64,
                 offload_watermark_blocks=16, prefill_buckets=(8, 16, 32))
    prompt_a = list(range(1, 13))
    out1 = await collect(eng, greedy_req(prompt_a, 4, "a1"))
    for i in range(6):
        p = [50 + 7 * i + j for j in range(12)]
        await collect(eng, greedy_req(p, 2, f"churn{i}"))
    assert eng.kvbm.stats["offloaded"] > 0
    out2 = await collect(eng, greedy_req(prompt_a, 4, "a2"))
    assert out2 == out1
    assert eng.metrics.get("onboarded_tokens", 0) > 0, \
        "workload failed to exercise the onboard (inject) path"
    await eng.close()


# ---------------------------------------------------------------------------
# disagg wire
# ---------------------------------------------------------------------------


def test_chunk_frame_roundtrips_scales_bitexact():
    from dynamo_tpu.disagg.transfer import (
        KvLayout,
        decode_chunk_frame,
        encode_chunk_frame,
    )

    rng = np.random.default_rng(6)
    kb = rng.integers(-127, 128, (2, 3, 4, 2, 8)).astype(np.int8)
    vb = rng.integers(-127, 128, (2, 3, 4, 2, 8)).astype(np.int8)
    ksb = rng.random((2, 3, 4, 2)).astype(np.float32)
    vsb = rng.random((2, 3, 4, 2)).astype(np.float32)
    layout = KvLayout.of(kb, scales=True)
    assert layout.dtype == "int8" and layout.scales
    # scale bytes are priced into the chunk bound
    assert layout.block_bytes() == 2 * (2 * 4 * 2 * 8) + 2 * 4 * 2 * 2 * 4
    b0, n, k2, v2, ks2, vs2 = decode_chunk_frame(
        encode_chunk_frame(0, kb, vb, ksb, vsb), layout)
    assert (b0, n) == (0, 3)
    for a, b in ((k2, kb), (v2, vb), (ks2, ksb), (vs2, vsb)):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)
    # a quantized layout REQUIRES the scale planes
    with pytest.raises(ValueError, match="scale"):
        decode_chunk_frame(encode_chunk_frame(0, kb, vb), layout)
    # wire round trip of the layout keeps the scales flag
    assert KvLayout.from_dict(layout.to_dict()).scales


def test_layout_rejects_mixed_dtype_pairs():
    from dynamo_tpu.disagg.transfer import KvLayout

    rng = np.random.default_rng(7)
    q = KvLayout.of(rng.integers(0, 5, (2, 3, 4, 2, 8)).astype(np.int8),
                    scales=True)
    bf = KvLayout.of(rng.random((2, 3, 4, 2, 8)).astype(np.float32))
    with pytest.raises(ValueError, match="dtype"):
        q.check_compatible(bf)


async def test_disagg_transfer_int8_end_to_end():
    """KV prefilled on an int8 prefill worker continues identically on an
    int8 decode worker — the quantized payload + scales ride the wire and
    the output matches an aggregated int8 engine."""
    import uuid as _uuid

    from dynamo_tpu.disagg.prefill_router import (
        ConditionalDisaggConfig,
        PrefillOrchestrator,
    )
    from dynamo_tpu.engine.worker import JaxEngineWorker
    from dynamo_tpu.protocols import LLMEngineOutput
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem", event_plane="inproc"),
        cluster_id=_uuid.uuid4().hex).start()
    ecfg = dict(model_config=FP32, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, max_num_seqs=2,
                prefill_buckets=(8, 16, 32), seed=7,
                kv_cache_dtype="int8", transfer_chunk_bytes=2048)
    prefill_worker = await JaxEngineWorker(
        rt, EngineConfig(role="prefill", **ecfg), component="prefill",
    ).start()
    decode_worker = await JaxEngineWorker(
        rt, EngineConfig(role="decode", **ecfg), component="backend",
    ).start()
    agg = JaxEngine(EngineConfig(**ecfg))

    prompt = list(range(30, 52))
    expect = await collect(agg, greedy_req(prompt, 6, "agg"))

    pclient = await (rt.namespace("dynamo").component("prefill")
                     .endpoint("generate").client()).start()
    dclient = await (rt.namespace("dynamo").component("backend")
                     .endpoint("generate").client()).start()
    orch = PrefillOrchestrator(
        pclient, ConditionalDisaggConfig(always_remote=True))
    routed = await orch.maybe_prefill(greedy_req(prompt, 6, "int8d"))
    assert routed.disaggregated_params is not None
    tokens = []
    async for item in dclient.generate(routed.to_dict()):
        tokens.extend(LLMEngineOutput.from_dict(item).token_ids)
    assert tokens == expect, "int8 disagg continuation diverged"
    assert decode_worker.engine.metrics["prefill_tokens"] == 0
    assert decode_worker.engine.metrics.get("pull_blocks", 0) > 0

    await orch.close()
    await pclient.close()
    await dclient.close()
    await agg.close()
    await prefill_worker.close()
    await decode_worker.close()
    await rt.shutdown()


# ---------------------------------------------------------------------------
# multihost replay
# ---------------------------------------------------------------------------


async def test_multihost_follower_bit_identical_at_int8():
    """A follower replaying the leader's step stream ends with ALL FOUR
    cache components bit-identical (int8 data and fp32 scales)."""
    import uuid as _uuid

    from dynamo_tpu.engine.worker import JaxEngineWorker
    from dynamo_tpu.parallel.multihost import MultihostContext
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem", event_plane="inproc"),
        cluster_id=_uuid.uuid4().hex).start()
    ecfg = dict(model_config=FP32, block_size=4, num_blocks=32,
                max_blocks_per_seq=8, max_num_seqs=2,
                prefill_buckets=(8, 16), seed=5, kv_cache_dtype="int8")
    follower = await JaxEngineWorker(
        rt, EngineConfig(**ecfg), mh=MultihostContext(rank=1, world=2),
    ).start()
    leader = await JaxEngineWorker(
        rt, EngineConfig(**ecfg), mh=MultihostContext(rank=0, world=2),
    ).start()
    assert len(leader.engine.kv) == 4
    assert len(follower.engine.kv) == 4

    toks = await collect(leader.engine,
                         greedy_req(list(range(3, 17)), 6, "mhq"))
    assert len(toks) == 6
    for _ in range(300):
        await asyncio.sleep(0.02)
        if all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(leader.engine.kv, follower.engine.kv)):
            break
    for i, (a, b) in enumerate(zip(leader.engine.kv, follower.engine.kv)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"cache component {i} diverged")
    await leader.close()
    await follower.close()
    await rt.shutdown()


# ---------------------------------------------------------------------------
# satellites: mocker + planner
# ---------------------------------------------------------------------------


def test_mocker_simulates_capacity_doubling_and_advertises():
    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
    from dynamo_tpu.mocker.engine import MockEngine
    from dynamo_tpu.mocker.kv_cache_sim import kv_dtype_capacity_blocks

    assert kv_dtype_capacity_blocks(1000, "bf16") == 1000
    assert kv_dtype_capacity_blocks(1000, "int8") == 1939  # 2*128/132
    args = MockEngineArgs(num_blocks=1000, kv_cache_dtype="int8")
    eng = MockEngine(args)
    assert eng.cache.num_blocks == 1939
    card = MockerWorker(None, args).card
    rc = card.runtime_config
    assert rc["kv_cache_dtype"] == "int8"
    assert rc["total_kv_blocks"] == 1939


def test_mocker_cli_flag_parses():
    from dynamo_tpu.mocker.__main__ import build_args

    a = build_args().parse_args(["--kv-cache-dtype", "int8"])
    assert a.kv_cache_dtype == "int8"


def test_perf_model_warns_on_kv_dtype_mismatch(caplog):
    from dynamo_tpu.planner.perf_model import PerfModel
    from dynamo_tpu.profiler import PerfProfile
    from dynamo_tpu.profiler.profile import PerfPoint

    prof = PerfProfile(points=[
        PerfPoint(isl=128, osl=32, concurrency=c, itl_mean_s=0.01 * c,
                  ttft_p95_s=0.1, req_per_s=1.0) for c in (1, 2, 4)],
        meta={"kv_cache_dtype": "bf16"})
    pm = PerfModel(prof)
    assert pm.kv_cache_dtype == "bf16"
    assert pm.check_kv_dtype(("bf16",)) == []
    with caplog.at_level("WARNING"):
        assert pm.check_kv_dtype(("int8",)) == ["int8"]
    assert any("kv_cache_dtype" in r.message for r in caplog.records)
    # warns once per dtype; untagged workers never mismatch
    caplog.clear()
    with caplog.at_level("WARNING"):
        assert pm.check_kv_dtype(("int8", "")) == ["int8"]
    assert not caplog.records
    # untagged PROFILE never mismatches either
    pm2 = PerfModel(PerfProfile(points=prof.points))
    assert pm2.check_kv_dtype(("int8",)) == []


def test_load_observer_aggregates_kv_dtypes():
    from dynamo_tpu.planner.metrics import LoadObserver

    obs = LoadObserver.__new__(LoadObserver)
    obs.stale_after_s = 60.0
    obs.rate_window_s = 10.0
    obs.samples = {}
    obs._cum = {}
    from dynamo_tpu.planner.metrics import WorkerSample

    obs.samples[1] = WorkerSample(active_seqs=1, kv_cache_dtype="int8")
    obs.samples[2] = WorkerSample(active_seqs=1, kv_cache_dtype="bf16")
    agg = obs.aggregate()
    assert agg.kv_dtypes == ("bf16", "int8")
