"""MoE model family: routing math, capacity overflow, EP sharding parity,
and engine e2e on the tiny-moe preset.

The EP check is the load-bearing one: expert weights shard over the tp mesh
axis (parallel/mesh.py moe_w_* rules) and the GShard dispatch einsums must
produce identical outputs sharded vs unsharded."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks


from dynamo_tpu.models import llama
from dynamo_tpu.models.llama import (
    LlamaConfig,
    PRESETS,
    _moe_mlp,
    _moe_mlp_dense,
)


def moe_cfg(**kw):
    base = dict(name="m", vocab_size=64, d_model=32, n_layers=1, n_heads=2,
                n_kv_heads=2, head_dim=16, ffn_dim=48, n_experts=4,
                experts_per_token=2, dtype=jnp.float32)
    base.update(kw)
    return LlamaConfig(**base)


def expert_ffn(layer, e, x):
    """Reference per-expert FFN for one token."""
    g = jax.nn.silu(x @ layer["moe_w_gate"][e]) * (x @ layer["moe_w_up"][e])
    return g @ layer["moe_w_down"][e]


@pytest.mark.parametrize("impl", [_moe_mlp_dense, _moe_mlp])
def test_moe_routes_to_topk_experts(impl):
    """Both dispatch modes: output must equal the softmax-weighted sum of
    the top-k experts' FFN outputs, computed independently per token."""
    cfg = moe_cfg()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    layer = params["layers"][0]
    x = jax.random.normal(jax.random.PRNGKey(1), (5, cfg.d_model),
                          jnp.float32)
    out = impl(layer, cfg, x)

    router = x @ layer["moe_gate"]
    for t in range(x.shape[0]):
        top_w, top_e = jax.lax.top_k(router[t], cfg.experts_per_token)
        w = jax.nn.softmax(top_w)
        expect = sum(
            w[j] * expert_ffn(layer, int(top_e[j]), x[t])
            for j in range(cfg.experts_per_token)
        )
        np.testing.assert_allclose(np.asarray(out[t]), np.asarray(expect),
                                   rtol=2e-4, atol=2e-4)


def test_moe_capacity_overflow_drops_tokens():
    """Capacity mode: with 1 slot per expert and every token routed to the
    same expert, only the first token gets expert compute; the rest
    contribute 0 (residual passthrough happens in the transformer block)."""
    cfg = moe_cfg(experts_per_token=1, moe_dispatch="capacity",
                  moe_capacity_factor=0.25)  # C=1 for T=4
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    layer = dict(params["layers"][0])
    # force all tokens to expert 2
    gate = np.zeros((cfg.d_model, cfg.n_experts), np.float32)
    gate[:, 2] = 1.0
    layer["moe_gate"] = jnp.asarray(gate)
    x = jnp.ones((4, cfg.d_model), jnp.float32)
    out = _moe_mlp(layer, cfg, x)
    expect0 = expert_ffn(layer, 2, x[0])
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(expect0),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(out[1:]), 0.0, atol=1e-6)


@pytest.mark.parametrize("impl", [_moe_mlp_dense, _moe_mlp])
def test_moe_ep_sharding_parity(impl):
    """Expert-parallel (experts sharded over tp) output == unsharded, for
    both dispatch modes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh, shard_params

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg = moe_cfg()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    layer = params["layers"][0]
    x = jax.random.normal(jax.random.PRNGKey(2), (8, cfg.d_model),
                          jnp.float32)
    ref = impl(layer, cfg, x)

    mesh = make_mesh(MeshConfig(dp=1, tp=4))
    sharded = shard_params(params, mesh)["layers"][0]
    assert sharded["moe_w_gate"].sharding.spec == P("tp", None, None)
    with mesh:
        out = jax.jit(lambda l, x: impl(l, cfg, x))(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


async def test_moe_prefix_cache_rerun_deterministic():
    """Regression (caught live): a rerun of the same prompt takes the
    cached-prefix + short-tail-prefill path, whose different chunk size
    changed capacity-mode drops and produced DIFFERENT greedy output.  The
    default dense dispatch must be batch-invariant: identical tokens out,
    whatever the chunking."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    prompt = [3 + ord(c) for c in "hello mixture of experts"]
    for seed in (0, 7):
        cfg = EngineConfig(model="tiny-moe", block_size=4, num_blocks=64,
                           max_blocks_per_seq=16, max_num_seqs=2, seed=seed)
        eng = JaxEngine(cfg)

        async def run(rid):
            req = PreprocessedRequest(
                token_ids=list(prompt), request_id=rid,
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=8, ignore_eos=True),
            )
            toks = []
            async for o in eng.generate(req):
                toks.extend(o.token_ids)
            return toks

        first = await run("a")
        second = await run("b")
        assert second == first, f"seed {seed}: cache-path divergence"
        assert eng.metrics["cache_hit_tokens"] > 0
        await eng.close()


async def test_engine_serves_moe_preset():
    """tiny-moe end to end through the engine: deterministic greedy decode
    with prefill + fused decode, twice (prefix-cache second pass)."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    cfg = EngineConfig(model="tiny-moe", block_size=4, num_blocks=32,
                       max_blocks_per_seq=8, max_num_seqs=2,
                       prefill_buckets=(8, 16), seed=3)
    eng = JaxEngine(cfg)

    async def run(rid):
        req = PreprocessedRequest(
            token_ids=list(range(5, 17)), request_id=rid,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
        )
        toks = []
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
        return toks

    first = await run("m1")
    assert len(first) == 6
    second = await run("m2")
    assert second == first
    assert eng.metrics["cache_hit_tokens"] > 0  # prefix cache engaged
    await eng.close()


def test_moe_preset_registered():
    assert PRESETS["tiny-moe"].n_experts == 4
    assert PRESETS["mixtral-8x7b"].n_experts == 8


def test_moe_batched_prefill_per_row_capacity():
    """prefill_batched must give each sequence its OWN expert-capacity pool
    (capacity dispatch): co-scheduled requests must not capacity-drop each
    other's tokens, so batched logits equal per-sequence prefill logits."""
    from dynamo_tpu.models.llama import init_params, prefill, prefill_batched

    # tight capacity so cross-row pooling WOULD drop tokens if shared
    cfg = moe_cfg(n_layers=2, moe_dispatch="capacity",
                  moe_capacity_factor=1.0)
    params = init_params(cfg, jax.random.PRNGKey(4))
    bs, nb, mb, T = 4, 64, 8, 16
    shape = (cfg.n_layers, cfg.n_kv_heads, nb, cfg.head_dim, bs)
    kv_a = (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))
    kv_b = (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))

    rng = np.random.default_rng(8)
    prompts = [rng.integers(1, cfg.vocab_size, T).astype(np.int32)
               for _ in range(2)]
    tables = np.zeros((2, mb), np.int32)
    for i in range(2):
        tables[i, : T // bs] = 1 + i * mb + np.arange(T // bs)

    solo = []
    for i in range(2):
        lg, kv_a = prefill(
            params, cfg, kv_a, jnp.asarray(prompts[i]),
            jnp.arange(T, dtype=jnp.int32), jnp.asarray(tables[i]),
            jnp.int32(0), jnp.int32(T),
        )
        solo.append(np.asarray(lg))

    blg, kv_b = prefill_batched(
        params, cfg, kv_b, jnp.asarray(np.stack(prompts)),
        jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (2, T)),
        jnp.asarray(tables), jnp.zeros(2, jnp.int32),
        jnp.full((2,), T, jnp.int32),
    )
    for i in range(2):
        np.testing.assert_allclose(np.asarray(blg[i]), solo[i],
                                   rtol=2e-5, atol=2e-5)
