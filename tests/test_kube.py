"""KubeDiscovery + KubernetesConnector against an in-process fake of the
Kubernetes API server (Lease objects + Deployment scale subresource).

Ref shape: lib/runtime/src/discovery/kube.rs (API-server discovery the
operator selects with DYN_DISCOVERY_BACKEND=kubernetes) and
components/src/dynamo/planner/connectors/kubernetes.py (planner EXECUTE
patches replica counts)."""

import asyncio
import contextlib
import uuid

from dynamo_tpu.planner.connectors import KubernetesConnector
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig
from dynamo_tpu.runtime.kube import KubeDiscovery

from fake_kube import FakeKubeApiServer


@contextlib.asynccontextmanager
async def fake_kube():
    srv = await FakeKubeApiServer().start()
    try:
        yield srv
    finally:
        await srv.close()


def kd(fake, **kw):
    kw.setdefault("ttl_s", 5.0)
    return KubeDiscovery(api_url=fake.endpoint, namespace="dyn",
                         cluster_id="test", **kw)


async def test_put_get_delete_roundtrip():
    async with fake_kube() as fake:
        d = kd(fake)
        await d.start()
        await d.put("v1/instances/ns/w/e/42", {"instance_id": 42})
        await d.put("v1/mdc/ns/model", {"name": "m"}, lease=False)
        snap = await d.get_prefix("v1/instances/")
        assert snap == {"v1/instances/ns/w/e/42": {"instance_id": 42}}
        assert await d.get_prefix("v1/") == {
            "v1/instances/ns/w/e/42": {"instance_id": 42},
            "v1/mdc/ns/model": {"name": "m"},
        }
        # replace in place (put of an existing key patches the object)
        await d.put("v1/instances/ns/w/e/42", {"instance_id": 42, "v": 2})
        assert (await d.get_prefix("v1/instances/"))[
            "v1/instances/ns/w/e/42"]["v"] == 2
        await d.delete("v1/instances/ns/w/e/42")
        assert await d.get_prefix("v1/instances/") == {}
        await d.close()


async def test_watch_snapshot_then_live_events():
    async with fake_kube() as fake:
        d1 = kd(fake)
        d2 = kd(fake)
        await d1.put("v1/instances/ns/w/e/1", {"instance_id": 1})

        events = []
        cancel = asyncio.Event()

        async def watch():
            async for ev in d2.watch("v1/instances/", cancel=cancel):
                events.append(ev)
                if len(events) >= 3:
                    cancel.set()

        task = asyncio.create_task(watch())
        await asyncio.sleep(0.3)
        await d1.put("v1/instances/ns/w/e/2", {"instance_id": 2})
        await d1.delete("v1/instances/ns/w/e/1")
        await asyncio.wait_for(task, timeout=5)
        assert [(e.type, e.key) for e in events] == [
            ("put", "v1/instances/ns/w/e/1"),
            ("put", "v1/instances/ns/w/e/2"),
            ("delete", "v1/instances/ns/w/e/1"),
        ]
        assert events[1].value == {"instance_id": 2}
        await d1.close()
        await d2.close()


async def test_stale_renew_time_surfaces_as_delete():
    """Crash (no renew, no revoke): the API server keeps the Lease
    object, but readers must treat a stale renewTime as gone — the
    K8s-native equivalent of etcd lease expiry."""
    async with fake_kube() as fake:
        d1 = kd(fake, ttl_s=1.0)
        await d1.put("v1/instances/ns/w/e/7", {"instance_id": 7})

        d2 = kd(fake, ttl_s=1.0)
        events = []
        cancel = asyncio.Event()

        async def watch():
            async for ev in d2.watch("v1/instances/", cancel=cancel):
                events.append(ev)
                if ev.type == "delete":
                    cancel.set()

        task = asyncio.create_task(watch())
        await asyncio.sleep(0.2)
        # simulated crash: stop renewing without deleting
        d1._closed.set()
        if d1._ka_task:
            d1._ka_task.cancel()
        await asyncio.wait_for(task, timeout=6)
        assert events[-1].type == "delete"
        assert events[-1].key == "v1/instances/ns/w/e/7"
        assert await d2.get_prefix("v1/instances/") == {}
        if d1._session is not None and not d1._session.closed:
            await d1._session.close()
        await d2.close()


async def test_keepalive_holds_lease_past_ttl():
    async with fake_kube() as fake:
        d = kd(fake, ttl_s=1.0)
        await d.put("v1/instances/ns/w/e/9", {"instance_id": 9})
        probe = kd(fake)
        await asyncio.sleep(2.5)  # > 2 TTLs; renew loop must hold it
        assert await probe.get_prefix("v1/instances/") == {
            "v1/instances/ns/w/e/9": {"instance_id": 9}}
        await d.close()
        # clean close deletes owned objects: keys disappear immediately
        assert await probe.get_prefix("v1/instances/") == {}
        await probe.close()


async def test_deleted_lease_object_reregisters():
    """An administratively deleted Lease (kubectl delete / GC) must be
    re-created by the owner's keepalive so a healthy worker does not
    stay invisible."""
    async with fake_kube() as fake:
        d = kd(fake, ttl_s=1.0)
        await d.put("v1/instances/ns/w/e/5", {"instance_id": 5})
        fake.leases.clear()  # admin wipe
        assert await d.get_prefix("v1/instances/") == {}
        for _ in range(40):
            await asyncio.sleep(0.1)
            if await d.get_prefix("v1/instances/"):
                break
        assert await d.get_prefix("v1/instances/") == {
            "v1/instances/ns/w/e/5": {"instance_id": 5}}
        await d.close()


async def test_withdraw_restore_cycle():
    """Health-check integration: withdraw pulls leased keys out (durable
    keys stay), restore puts them back."""
    async with fake_kube() as fake:
        d = kd(fake)
        await d.put("v1/instances/ns/w/e/3", {"instance_id": 3})
        await d.put("v1/mdc/ns/m", {"name": "m"}, lease=False)
        await d.withdraw_lease()
        assert await d.get_prefix("v1/instances/") == {}
        assert await d.get_prefix("v1/mdc/") == {
            "v1/mdc/ns/m": {"name": "m"}}
        await d.restore_lease()
        assert await d.get_prefix("v1/instances/") == {
            "v1/instances/ns/w/e/3": {"instance_id": 3}}
        await d.close()


async def test_runtime_serves_over_kube_discovery():
    """A full runtime (worker endpoint + client) over the kubernetes
    backend: the discovery contract end to end."""
    async with fake_kube() as fake:
        def rt():
            return DistributedRuntime(
                config=RuntimeConfig(event_plane="inproc"),
                cluster_id=uuid.uuid4().hex,
                discovery=kd(fake))

        server = await rt().start()
        client_rt = await rt().start()

        async def handler(payload, ctx):
            yield {"echo": payload["x"]}

        served = await (server.namespace("n").component("c")
                        .endpoint("e").serve_endpoint(handler))
        client = await (client_rt.namespace("n").component("c")
                        .endpoint("e").client()).start()
        await client.wait_for_instances()
        out = [item async for item in client.generate({"x": 5})]
        assert out == [{"echo": 5}]
        await served.shutdown()
        await client.close()
        await server.shutdown()
        await client_rt.shutdown()


async def test_kubernetes_connector_scales_deployment():
    """Planner EXECUTE: the connector patches the Deployment scale
    subresource and reads the applied count back."""
    async with fake_kube() as fake:
        conn = KubernetesConnector("decode-workers", namespace="dyn",
                                   api_url=fake.endpoint)
        assert await conn.current_replicas() == 1
        assert await conn.scale(4) == 4
        assert await conn.current_replicas() == 4
        assert await conn.scale(2) == 2
        assert fake.scale_calls == [("decode-workers", 4),
                                    ("decode-workers", 2)]
        await conn.close()


async def test_planner_drives_kubernetes_connector():
    """The planner's scaling decision lands as a Deployment patch (the
    reference's planner->K8s EXECUTE path, kubernetes.py:63)."""
    import sys

    sys.path.insert(0, "tests") if "tests" not in sys.path[0] else None
    from test_planner import _bare_planner

    from dynamo_tpu.planner.metrics import AggregateLoad
    from dynamo_tpu.planner.planner import PlannerConfig

    async with fake_kube() as fake:
        conn = KubernetesConnector("workers", namespace="dyn",
                                   api_url=fake.endpoint)
        cfg = PlannerConfig(min_replicas=1, max_replicas=8,
                            target_active_per_replica=4.0, cooldown_s=0.0)
        p = _bare_planner(cfg, conn)
        # load far above one replica's capacity -> PROPOSE scales up,
        # EXECUTE patches the Deployment's scale subresource
        p.observer.load = AggregateLoad(workers=1, active_seqs=16,
                                        mean_kv_usage=0.5)
        n = await p.tick()
        assert n is not None and n >= 2
        assert fake.deployments["workers"]["spec"]["replicas"] == n
        assert fake.scale_calls[-1] == ("workers", n)
        await conn.close()
