"""Anthropic Messages API + KServe v2 gRPC over mocker workers
(ref: lib/llm/src/http/service/anthropic.rs, grpc/service/kserve.rs)."""

import asyncio
import json

import pytest
import uuid

import aiohttp

from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

MODEL = "proto-model"


async def start_stack():
    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem", event_plane="inproc"),
        cluster_id=uuid.uuid4().hex).start()
    args = MockEngineArgs(model_name=MODEL, block_size=4,
                          base_step_s=0.0005, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0)
    worker = await MockerWorker(rt, args).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get(MODEL):
            break
        await asyncio.sleep(0.02)
    return rt, worker, watcher, service, manager, port


async def stop_stack(rt, worker, watcher, service):
    await service.close()
    await watcher.close()
    await worker.close()
    await rt.shutdown()


# ----------------------------- Anthropic ------------------------------------


async def test_anthropic_messages_unary():
    rt, worker, watcher, service, manager, port = await start_stack()
    try:
        body = {"model": MODEL, "max_tokens": 6,
                "system": "be brief",
                "messages": [{"role": "user", "content": [
                    {"type": "text", "text": "hello"}]}],
                "ignore_eos": True}
        async with aiohttp.ClientSession() as s:
            async with s.post(f"http://127.0.0.1:{port}/v1/messages",
                              json=body) as r:
                assert r.status == 200
                out = await r.json()
        assert out["type"] == "message" and out["role"] == "assistant"
        assert out["id"].startswith("msg_")
        assert out["content"][0]["type"] == "text"
        assert out["content"][0]["text"]
        assert out["stop_reason"] == "max_tokens"
        assert out["usage"]["output_tokens"] == 6
        assert out["usage"]["input_tokens"] > 0
    finally:
        await stop_stack(rt, worker, watcher, service)


async def test_anthropic_messages_stream_framing():
    rt, worker, watcher, service, manager, port = await start_stack()
    try:
        body = {"model": MODEL, "max_tokens": 4, "stream": True,
                "messages": [{"role": "user", "content": "hi"}],
                "ignore_eos": True}
        events = []
        async with aiohttp.ClientSession() as s:
            async with s.post(f"http://127.0.0.1:{port}/v1/messages",
                              json=body) as r:
                assert r.status == 200
                raw = (await r.read()).decode()
        for block in raw.strip().split("\n\n"):
            lines = dict(ln.split(": ", 1) for ln in block.splitlines()
                         if ": " in ln)
            if "event" in lines:
                events.append((lines["event"], json.loads(lines["data"])))
        names = [e[0] for e in events]
        assert names[0] == "message_start"
        assert names[1] == "content_block_start"
        assert "content_block_delta" in names
        assert names[-3:] == ["content_block_stop", "message_delta",
                              "message_stop"]
        start = events[0][1]
        assert start["message"]["usage"]["input_tokens"] > 0
        md = next(d for n, d in events if n == "message_delta")
        assert md["delta"]["stop_reason"] == "max_tokens"
        assert md["usage"]["output_tokens"] == 4
        text = "".join(d["delta"]["text"] for n, d in events
                       if n == "content_block_delta")
        assert text
    finally:
        await stop_stack(rt, worker, watcher, service)


async def test_anthropic_count_tokens_and_errors():
    rt, worker, watcher, service, manager, port = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                    f"http://127.0.0.1:{port}/v1/messages/count_tokens",
                    json={"model": MODEL,
                          "messages": [{"role": "user",
                                        "content": "hello world"}]}) as r:
                assert r.status == 200
                assert (await r.json())["input_tokens"] > 0
            # max_tokens required
            async with s.post(f"http://127.0.0.1:{port}/v1/messages",
                              json={"model": MODEL,
                                    "messages": []}) as r:
                assert r.status == 400
                err = await r.json()
                assert err["type"] == "error"
            # unknown model -> anthropic-shaped 404
            async with s.post(f"http://127.0.0.1:{port}/v1/messages",
                              json={"model": "nope", "max_tokens": 4,
                                    "messages": []}) as r:
                assert r.status == 404
                assert (await r.json())["error"]["type"] == \
                    "not_found_error"
    finally:
        await stop_stack(rt, worker, watcher, service)


# ----------------------------- KServe gRPC ----------------------------------


def _infer_request(pb, prompt: str, stream=False, max_tokens=5):
    req = pb.ModelInferRequest(model_name=MODEL, id="req-1")
    t = req.inputs.add()
    t.name, t.datatype = "text_input", "BYTES"
    t.shape.append(1)
    t.contents.bytes_contents.append(prompt.encode())
    req.parameters["max_tokens"].int64_param = max_tokens
    req.parameters["ignore_eos"].bool_param = True
    return req


# grpc.aio channel/server setup + proto import run sync in the test
# body; under suite load they cross the 200ms loop gate (harness
# cost, not a serving path)
@pytest.mark.allow_slow_callbacks
async def test_kserve_grpc_end_to_end():
    import grpc

    from dynamo_tpu.frontend import kserve_pb2 as pb
    from dynamo_tpu.frontend.kserve import SERVICE, KserveGrpcService

    rt, worker, watcher, service, manager, port = await start_stack()
    ks = await KserveGrpcService(rt, manager, host="127.0.0.1",
                                 port=0).start()
    try:
        async with grpc.aio.insecure_channel(
                f"127.0.0.1:{ks.bound_port}") as ch:
            live = ch.unary_unary(
                f"/{SERVICE}/ServerLive",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.ServerLiveResponse.FromString)
            assert (await live(pb.ServerLiveRequest())).live

            ready = ch.unary_unary(
                f"/{SERVICE}/ModelReady",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.ModelReadyResponse.FromString)
            assert (await ready(pb.ModelReadyRequest(name=MODEL))).ready
            assert not (await ready(pb.ModelReadyRequest(name="nope"))).ready

            meta = ch.unary_unary(
                f"/{SERVICE}/ModelMetadata",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.ModelMetadataResponse.FromString)
            md = await meta(pb.ModelMetadataRequest(name=MODEL))
            assert md.platform == "dynamo_tpu"
            assert md.inputs[0].name == "text_input"
            assert md.outputs[0].name == "text_output"

            infer = ch.unary_unary(
                f"/{SERVICE}/ModelInfer",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.ModelInferResponse.FromString)
            resp = await infer(_infer_request(pb, "hello grpc"))
            assert resp.model_name == MODEL and resp.id == "req-1"
            out = resp.outputs[0]
            assert out.name == "text_output"
            text = out.contents.bytes_contents[0].decode()
            assert text.strip()
            assert resp.parameters["finish_reason"].string_param == "length"

            stream = ch.stream_stream(
                f"/{SERVICE}/ModelStreamInfer",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=(
                    pb.ModelStreamInferResponse.FromString))
            call = stream()
            await call.write(_infer_request(pb, "stream me",
                                            max_tokens=4))
            await call.done_writing()
            chunks = []
            final = 0
            async for item in call:
                assert not item.error_message
                ir = item.infer_response
                chunks.append(
                    ir.outputs[0].contents.bytes_contents[0].decode())
                if ir.parameters["triton_final_response"].bool_param:
                    final += 1
            assert final == 1 and len(chunks) >= 2
            assert "".join(chunks).strip()
    finally:
        await ks.close()
        await stop_stack(rt, worker, watcher, service)


async def test_kserve_unknown_model_aborts():
    import grpc

    from dynamo_tpu.frontend import kserve_pb2 as pb
    from dynamo_tpu.frontend.kserve import SERVICE, KserveGrpcService

    rt, worker, watcher, service, manager, port = await start_stack()
    ks = await KserveGrpcService(rt, manager, host="127.0.0.1",
                                 port=0).start()
    try:
        async with grpc.aio.insecure_channel(
                f"127.0.0.1:{ks.bound_port}") as ch:
            infer = ch.unary_unary(
                f"/{SERVICE}/ModelInfer",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.ModelInferResponse.FromString)
            req = _infer_request(pb, "x")
            req.model_name = "missing"
            try:
                await infer(req)
                raise AssertionError("expected NOT_FOUND")
            except grpc.aio.AioRpcError as e:
                assert e.code() == grpc.StatusCode.NOT_FOUND
    finally:
        await ks.close()
        await stop_stack(rt, worker, watcher, service)


def test_stop_reason_mapping():
    from dynamo_tpu.frontend.anthropic import _stop_reason
    from dynamo_tpu.frontend.pipeline import ModelPipeline

    assert _stop_reason("length", None) == ("max_tokens", None)
    assert _stop_reason("stop", "###") == ("stop_sequence", "###")
    # EOS also reports finish "stop" but with no matched trigger
    assert _stop_reason("stop", None) == ("end_turn", None)
    cut, which = ModelPipeline._find_stop("abc###def", ["def", "###"])
    assert (cut, which) == (3, "###")
    assert ModelPipeline._find_stop("abc", ["x"]) == (None, None)


def test_anthropic_block_conversion():
    import pytest as _pytest

    from dynamo_tpu.frontend.anthropic import _convert_blocks, _to_chat_body

    parts = _convert_blocks([
        {"type": "text", "text": "hi"},
        {"type": "image", "source": {"type": "base64",
                                     "media_type": "image/png",
                                     "data": "QUJD"}}])
    assert parts[0] == {"type": "text", "text": "hi"}
    assert parts[1]["image_url"]["url"].startswith("data:image/png;base64,")
    with _pytest.raises(ValueError):
        _convert_blocks([{"type": "tool_result"}])
    chat, stops = _to_chat_body({
        "model": "m", "max_tokens": 5, "stop_sequences": ["##"],
        "system": [{"type": "text", "text": "sys"}],
        "messages": [{"role": "user", "content": "q"}],
        "tools": [{"name": "f", "description": "d",
                   "input_schema": {"type": "object"}}]})
    assert chat["messages"][0] == {"role": "system", "content": "sys"}
    assert chat["tools"][0]["function"]["name"] == "f"
    assert stops == ["##"]
