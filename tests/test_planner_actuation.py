"""Self-healing planner actuation (ISSUE 15): drain-gated scale-down
proven token-identical under chaos, phase-attributed burn-rate scale-up,
straggler quarantine with readmission, and the crashloop-proof EXECUTE
(spawn backoff + circuit breaker).

Every e2e scenario drives greedy requests through the real migration
path (ModelPipeline.migration → Client → request plane → mocker worker)
and asserts the actuated run's output is TOKEN-IDENTICAL to a fault-free
run — the mocker's position-addressed token stream makes token-replay
migration exact, same property greedy decoding has on the real engine."""

import asyncio
import time
import uuid
from collections import deque

import pytest

from dynamo_tpu import chaos
from dynamo_tpu.frontend import ModelManager, ModelWatcher
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.planner import (
    CallbackConnector,
    Planner,
    PlannerConfig,
    SpawnGovernor,
    StragglerQuarantine,
    make_predictor,
)
from dynamo_tpu.protocols import (PreprocessedRequest, SamplingOptions,
                                  StopConditions)
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

pytestmark = pytest.mark.chaos

MODEL = "planner-model"


def fresh_runtime() -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


def greedy_req(rid: str, max_tokens: int = 12,
               seed: int = 1234) -> PreprocessedRequest:
    return PreprocessedRequest(
        token_ids=[5, 6, 7, 8], request_id=rid,
        sampling=SamplingOptions(temperature=0.0, seed=seed),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )


async def collect(pipeline, req) -> list:
    tokens = []
    async for out in pipeline.migration.generate(req):
        assert out.finish_reason != "error", out.error
        tokens.extend(out.token_ids)
    return tokens


def make_connector(rt, args, drain_deadline_s=2.0, margin=0.3,
                   component="mocker"):
    """The bench/production shape: spawn/stop/drain of real mocker
    workers, drain-gated scale-down with bounded escalation."""
    return CallbackConnector(
        spawn=lambda: MockerWorker(rt, args, component=component,
                                   migration_limit=3).start(),
        stop=lambda w: w.close(),
        drain=lambda w, d: w.drain(deadline_s=d),
        drain_deadline_s=drain_deadline_s,
        drain_escalate_margin_s=margin)


async def fleet_pipeline(rt, conn, n):
    await conn.scale(n)
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    for _ in range(300):
        if manager.get(MODEL):
            break
        await asyncio.sleep(0.01)
    pipeline = manager.get(MODEL)
    assert pipeline is not None
    await pipeline.client.wait_for_instances()
    for _ in range(300):
        if len(pipeline.client.instances) == n:
            break
        await asyncio.sleep(0.01)
    assert len(pipeline.client.instances) == n
    return watcher, pipeline


def engine_args(**kw):
    base = dict(model_name=MODEL, block_size=4, base_step_s=0.0005,
                prefill_s_per_token=0.0, decode_s_per_seq=0.0)
    base.update(kw)
    return MockEngineArgs(**base)


def metric_value(rt, name, **labels):
    """One sample's value off the runtime's own registry, matched by
    sample name + label subset (the scrape-contract idiom)."""
    from prometheus_client.parser import text_string_to_metric_families

    for fam in text_string_to_metric_families(rt.metrics.render().decode()):
        for s in fam.samples:
            if s.name == name and all(s.labels.get(k) == v
                                      for k, v in labels.items()):
                return s.value
    return None


# --------------------------- spawn governor ------------------------------


def test_spawn_governor_backoff_and_breaker():
    g = SpawnGovernor(backoff_base_s=1.0, backoff_max_s=8.0,
                      breaker_threshold=3, breaker_reset_s=10.0)
    t = 100.0
    assert g.allow(t)
    assert g.record_failure(t) is False
    # exponential backoff: blocked now, allowed after base
    assert g.why_blocked(t) == "backoff"
    assert g.allow(t + 1.1)
    assert g.record_failure(t + 1.1) is False   # backoff now 2s
    assert g.why_blocked(t + 2.0) == "backoff"
    assert g.allow(t + 3.2)
    # third consecutive failure trips the breaker — exactly one OPEN
    # transition reported
    assert g.record_failure(t + 3.2) is True
    assert g.why_blocked(t + 4.0) == "breaker_open"
    assert g.breaker_opens_total == 1
    # still open through the cool-off, half-open after
    assert g.why_blocked(t + 13.0) == "breaker_open"
    assert g.allow(t + 13.3)
    # a failed half-open probe re-opens (a new transition)
    assert g.record_failure(t + 13.3) is True
    assert g.breaker_opens_total == 2
    # success closes everything
    g.record_success()
    assert g.allow(t + 13.4) and g.failures == 0
    st = g.state()
    assert st["failures_total"] == 4 and st["successes_total"] == 1
    assert st["breaker_open"] is False


# ------------------------ burn-rate actuation ----------------------------


class _FakeConnector:
    def __init__(self, replicas=1):
        self.replicas = replicas
        self.calls = []

    async def current_replicas(self):
        return self.replicas

    async def scale(self, n):
        self.calls.append(("scale", n))
        self.replicas = n
        return n

    async def drain(self, n):
        self.calls.append(("drain", n))
        self.replicas = n
        return n


class _FakeObserver:
    def __init__(self, load=None):
        self.load = load

    def aggregate(self):
        return self.load


class _FakeSlo:
    def __init__(self, agg):
        self.agg = agg

    def aggregate(self):
        return self.agg


def _bare_planner(cfg, conn, slo=None):
    p = Planner.__new__(Planner)
    p.config = cfg
    p.connector = conn
    p.observer = _FakeObserver()
    p.predictor = make_predictor("constant")
    p._task = None
    p._last_action_t = 0.0
    p._low_ticks = 0
    p.decisions = deque()
    if slo is not None:
        p.slo = slo
    return p


async def test_burn_actuation_scales_up_by_phase():
    """A fast TTFT burn forces +1 on a prefill-phase (and whole-fleet)
    planner ahead of the predictor; a decode-phase planner ignores it —
    the split that controls the disagg P/D ratio."""
    from dynamo_tpu.planner.metrics import AggregateLoad

    slo = _FakeSlo({"goodput": 0.4, "max_burn": 30.0,
                    "burn_by_phase": {"ttft": 30.0}})
    load = AggregateLoad(workers=1, active_seqs=2, mean_kv_usage=0.1)

    def planner(phase):
        cfg = PlannerConfig(min_replicas=1, max_replicas=4, cooldown_s=0.0,
                            target_active_per_replica=4.0,
                            burn_up_threshold=2.0, phase=phase)
        p = _bare_planner(cfg, _FakeConnector(replicas=1), slo=slo)
        p.observer.load = load
        return p

    # prefill pool: TTFT burn actuates — predictor alone proposed 1
    p = planner("prefill")
    assert await p.tick() == 2
    assert p.last_diag["burn_actuation"]["phase"] == "prefill"
    assert p.last_diag["slo_burn_by_phase"] == {"ttft": 30.0}
    # decode pool: a TTFT burn is NOT its signal
    p = planner("decode")
    assert await p.tick() is None
    assert "burn_actuation" not in p.last_diag
    # whole-fleet pool: any burn (max_burn) actuates
    p = planner("")
    assert await p.tick() == 2
    assert p.last_diag["burn_actuation"]["phase"] == "any"
    # below the threshold: no forcing
    quiet = _FakeSlo({"goodput": 0.995, "max_burn": 0.5,
                      "burn_by_phase": {"ttft": 0.5}})
    p = planner("prefill")
    p.slo = quiet
    assert await p.tick() is None


async def test_burn_actuation_respects_max_replicas():
    from dynamo_tpu.planner.metrics import AggregateLoad

    cfg = PlannerConfig(min_replicas=1, max_replicas=2, cooldown_s=0.0,
                        burn_up_threshold=2.0)
    p = _bare_planner(cfg, _FakeConnector(replicas=2),
                      slo=_FakeSlo({"goodput": 0.0, "max_burn": 99.0,
                                    "burn_by_phase": {"itl": 99.0}}))
    p.observer.load = AggregateLoad(workers=2, active_seqs=2,
                                    mean_kv_usage=0.1)
    assert await p.tick() is None  # already at max: burn cannot exceed it


def test_slo_plane_burn_by_phase_attribution():
    """obs/slo.py: breach reasons carry through the rolling window into
    per-phase burn — TTFT breaches attribute to 'ttft', ITL to 'itl',
    and the published summary carries the split end to end."""
    from dynamo_tpu.obs.slo import SloConfig, SloPlane
    from dynamo_tpu.runtime.metrics import MetricsHierarchy

    class _T:
        model = "m"

    plane = SloPlane(MetricsHierarchy().scoped(component="frontend"),
                     SloConfig(ttft_ms=100.0, itl_ms=50.0,
                               objective=0.9, windows_s=(60.0, 300.0)))
    rec = lambda ttft, itl: {"request": {
        "outcome": "ok", "total_time_ms": 500.0, "ttft_ms": ttft,
        "avg_itl_ms": itl}}
    for _ in range(6):
        plane.observe_finish(_T(), rec(20.0, 10.0))    # good
    for _ in range(3):
        plane.observe_finish(_T(), rec(500.0, 10.0))   # ttft breach
    plane.observe_finish(_T(), rec(20.0, 200.0))       # itl breach
    phases = plane.burn_by_phase()
    # 3/10 ttft-bad over a 0.1 budget = burn 3.0; 1/10 itl-bad = 1.0
    assert phases["ttft"] == pytest.approx(3.0)
    assert phases["itl"] == pytest.approx(1.0)
    s = plane.summary()
    assert s["burn_by_phase"]["ttft"] == pytest.approx(3.0)
    # total burn covers both: 4/10 over 0.1 budget
    assert max(s["burn"].values()) == pytest.approx(4.0)


async def test_slo_observer_aggregates_burn_by_phase():
    """SloObserver (planner side) folds each frontend's burn_by_phase
    into the per-phase max the tick's actuation reads."""
    from dynamo_tpu.planner.metrics import SloObserver

    rt = await fresh_runtime().start()
    try:
        obs_ = await SloObserver(rt, "dynamo").start()
        for _ in range(200):
            # re-publish until the subscription has ingested both
            # frontends (subscribe setup races the first publish)
            for fid, phases in ((1, {"ttft": 5.0}),
                                (2, {"ttft": 2.0, "itl": 7.0})):
                await rt.event_plane.publish("slo_metrics.dynamo", {
                    "frontend_id": fid, "goodput": 0.5,
                    "burn": {"60s": max(phases.values())},
                    "burn_by_phase": phases, "requests": 10})
            await asyncio.sleep(0.01)
            if len(obs_.samples) == 2:
                break
        agg = obs_.aggregate()
        assert agg["burn_by_phase"] == {"ttft": 5.0, "itl": 7.0}
        await obs_.close()
    finally:
        await rt.shutdown()


# ---------------------- drain-gated scale-down ---------------------------


async def test_drain_gated_scale_down_token_identical():
    """Planner RECONCILE scales 2→1 during live traffic through
    connector.drain(): the victim's routing identity is withdrawn, its
    in-flight streams finish or migrate via token replay, and every
    stream is TOKEN-IDENTICAL to the fault-free run.  The actuation
    lands in dynamo_planner_actuations_total{kind=scale_down}."""
    rt = await fresh_runtime().start()
    try:
        args = engine_args(decode_s_per_seq=0.01)  # slow: streams in flight
        conn = make_connector(rt, args, drain_deadline_s=2.0)
        watcher, pipeline = await fleet_pipeline(rt, conn, 2)
        baseline = {}
        for i in range(4):
            baseline[i] = await collect(
                pipeline, greedy_req(f"ff-{i}", 12, seed=300 + i))

        planner = Planner(
            rt, "dynamo", "mocker", conn,
            config=PlannerConfig(min_replicas=1, max_replicas=2,
                                 cooldown_s=0.0, down_stable_ticks=1,
                                 target_active_per_replica=8.0,
                                 predictor="constant"))
        await planner.observer.start()  # manual ticks

        tasks = [asyncio.create_task(collect(
            pipeline, greedy_req(f"ch-{i}", 12, seed=300 + i)))
            for i in range(4)]
        for _ in range(300):
            if any(e.num_active_seqs for w in conn.handles
                   for e in w.engines):
                break
            await asyncio.sleep(0.01)
        # wait until the load observer sees the fleet (otherwise the
        # telemetry-loss guard holds)
        for _ in range(300):
            await asyncio.sleep(0.01)
            if planner.observer.aggregate().workers == 2:
                break
        victim = conn.handles[-1]  # newest is drained first
        victim_key = victim.served.instance.key()
        applied = await planner.tick()
        assert applied == 1, planner.last_diag
        results = await asyncio.gather(*tasks)
        for i, tokens in enumerate(results):
            assert tokens == baseline[i], f"request {i} diverged"
        # the victim's routing identity is gone; no escalation needed
        assert victim_key not in await rt.discovery.get_prefix(
            "v1/instances")
        assert conn.drain_escalations == 0
        assert len(conn.handles) == 1
        assert metric_value(rt, "dynamo_planner_actuations_total",
                            kind="scale_down") == 1.0

        await planner.close()
        await watcher.close()
        await conn.close()
    finally:
        await rt.shutdown()


async def test_scale_down_escalates_past_drain_ignoring_worker():
    """Chaos worker.drain wedge: the victim IGNORES drain.  The
    connector's bounded wait escalates to the hard stop, the orphaned
    streams migrate via token replay, and the output stays
    token-identical — scale-down can never hang on a sick worker."""
    rt = await fresh_runtime().start()
    try:
        args = engine_args(decode_s_per_seq=0.01)
        conn = make_connector(rt, args, drain_deadline_s=0.15, margin=0.2)
        watcher, pipeline = await fleet_pipeline(rt, conn, 2)
        baseline = {}
        for i in range(3):
            baseline[i] = await collect(
                pipeline, greedy_req(f"ff2-{i}", 12, seed=400 + i))

        plane = chaos.ChaosPlane(seed=7).rule("worker.drain", "wedge",
                                              times=1)
        with plane:
            tasks = [asyncio.create_task(collect(
                pipeline, greedy_req(f"ch2-{i}", 12, seed=400 + i)))
                for i in range(3)]
            for _ in range(300):
                if any(e.num_active_seqs for w in conn.handles
                       for e in w.engines):
                    break
                await asyncio.sleep(0.01)
            applied = await conn.drain(1)
            assert applied == 1
            results = await asyncio.gather(*tasks)
        assert plane.fired("worker.drain") == 1
        assert conn.drain_escalations == 1
        for i, tokens in enumerate(results):
            assert tokens == baseline[i], f"request {i} diverged"

        await watcher.close()
        await conn.close()
    finally:
        await rt.shutdown()


# ------------------------ straggler quarantine ---------------------------


async def test_quarantine_withdraw_hold_probe_readmit():
    """Unit-ish: a straggler's discovery keys are withdrawn (instance +
    MDC), held for the delay rule, canary re-probed through the real
    in-process handler, and restored; a re-quarantine doubles the hold
    (flap hysteresis); a 1-worker fleet is never quarantined."""
    rt = await fresh_runtime().start()
    try:
        w1 = await MockerWorker(rt, engine_args()).start()
        w2 = await MockerWorker(rt, engine_args()).start()
        iid = w1.served.instance_id
        q = StragglerQuarantine(rt.discovery, namespace="dynamo",
                                component="mocker", hold_s=0.3,
                                flap_factor=2.0, probe=True, runtime=rt)
        actions = await q.reconcile({"live": 2, "stragglers": [iid]})
        assert [a["kind"] for a in actions] == ["quarantine"]
        assert iid in q.held and len(q.held[iid].keys) >= 2  # inst + MDC
        # routing identity gone, but the quarantine breadcrumb marks it
        for prefix in ("v1/instances", "v1/mdc"):
            snap = await rt.discovery.get_prefix(prefix)
            assert not any(k.endswith(f"/{iid}") for k in snap)
        marker = await rt.discovery.get_prefix("v1/quarantine")
        assert [v["instance_id"] for v in marker.values()] == [iid]
        # held: a second tick does nothing new before the hold expires
        assert await q.reconcile({"live": 1, "stragglers": []}) == []
        await asyncio.sleep(0.35)
        # delay rule expired → canary re-probe (real generate handler)
        # passes → readmitted, keys restored
        actions = await q.reconcile({"live": 1, "stragglers": []})
        assert [a["kind"] for a in actions] == ["readmit"]
        snap = await rt.discovery.get_prefix("v1")
        assert any(k.endswith(f"/{iid}") for k in snap)
        assert not await rt.discovery.get_prefix("v1/quarantine")
        # flap: the repeat offender's hold starts doubled
        actions = await q.reconcile({"live": 2, "stragglers": [iid]})
        assert actions[0]["kind"] == "quarantine"
        assert actions[0]["hold_s"] == pytest.approx(0.6)
        await q.release_all()  # cleanup restores the fleet
        # cap: the last in-rotation worker is never quarantined
        q2 = StragglerQuarantine(rt.discovery, namespace="dynamo",
                                 component="mocker", hold_s=0.3,
                                 runtime=rt)
        assert await q2.reconcile(
            {"live": 1, "stragglers": [w2.served.instance_id]}) == []
        await w1.close()
        await w2.close()
    finally:
        await rt.shutdown()


async def test_chaos_delayed_straggler_quarantined_and_readmitted():
    """Acceptance e2e: ONE worker of three gets chaos-delayed
    engine.step ticks → its decode ITL p95 becomes a fleet outlier →
    the planner tick quarantines it (lease-withdrawal mark: routers
    drop it, the process keeps running) → after the delay rule expires
    the canary re-probe passes and the planner readmits it — all
    visible in dynamo_planner_* metrics."""
    from dynamo_tpu.obs.fleet import summarize_states

    rt = await fresh_runtime().start()
    try:
        args = engine_args(base_step_s=0.002)
        conn = make_connector(rt, args)
        watcher, pipeline = await fleet_pipeline(rt, conn, 3)
        workers = list(conn.handles)
        straggler = workers[0]
        s_iid = straggler.served.instance_id

        class _Fleet:
            """The obs.fleet adapter: summarize the IN-ROTATION workers
            (a quarantined worker's discovery keys are gone, so the
            real aggregator would not see it either)."""

            def summary(self):
                held = (planner.quarantine.held
                        if planner.quarantine else {})
                states = [w.debug_state() for w in workers
                          if w.served.instance_id not in held]
                return summarize_states(states)

        planner = Planner(
            rt, "dynamo", "mocker", conn, fleet=_Fleet(),
            config=PlannerConfig(min_replicas=3, max_replicas=3,
                                 quarantine_hold_s=0.5,
                                 predictor="constant"))
        await planner.observer.start()

        # chaos-delay ONLY the straggler's steps (key carries the
        # worker id); times bounds it so the delay rule expires
        plane = chaos.ChaosPlane(seed=3).rule(
            "engine.step", "delay", delay_s=0.05, match=f":{s_iid}",
            times=200)
        with plane:
            jobs = [asyncio.create_task(collect(
                pipeline, greedy_req(f"load-{i}", 24, seed=500 + i)))
                for i in range(9)]
            # wait for decode FPM windows to show the outlier
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                await asyncio.sleep(0.1)
                s = summarize_states([w.debug_state() for w in workers])
                if s_iid in s["stragglers"]:
                    break
            assert s_iid in s["stragglers"], s
            await planner.tick()
            assert s_iid in planner.quarantine.held, planner.last_diag
            assert planner.last_diag["quarantined"] == [s_iid]
            # routers dropped it: only 2 instances remain visible
            for _ in range(300):
                await asyncio.sleep(0.01)
                if len(pipeline.client.instances) == 2:
                    break
            assert len(pipeline.client.instances) == 2
            await asyncio.gather(*jobs)  # in-flight work still completes
        # the worker process is alive (mark, not kill)
        assert not straggler.engines[0].dead
        # delay rule expired + hold elapsed → readmission
        await asyncio.sleep(0.55)
        await planner.tick()
        assert s_iid not in planner.quarantine.held, planner.last_diag
        for _ in range(300):
            await asyncio.sleep(0.01)
            if len(pipeline.client.instances) == 3:
                break
        assert len(pipeline.client.instances) == 3
        assert metric_value(rt, "dynamo_planner_actuations_total",
                            kind="quarantine") == 1.0
        assert metric_value(rt, "dynamo_planner_actuations_total",
                            kind="readmit") == 1.0

        await planner.close()
        await watcher.close()
        await conn.close()
    finally:
        await rt.shutdown()


async def test_burn_up_counted_only_when_action_lands():
    """The burn_up counter records landed actuations, not proposals: a
    burn that persists under cooldown must not inflate the counter every
    tick."""
    from dynamo_tpu.planner.metrics import AggregateLoad

    slo = _FakeSlo({"goodput": 0.4, "max_burn": 30.0,
                    "burn_by_phase": {"ttft": 30.0}})
    cfg = PlannerConfig(min_replicas=1, max_replicas=4, cooldown_s=3600.0,
                        burn_up_threshold=2.0)
    p = _bare_planner(cfg, _FakeConnector(replicas=1), slo=slo)
    p.observer.load = AggregateLoad(workers=1, active_seqs=2,
                                    mean_kv_usage=0.1)
    counted = []
    p._count = counted.append
    p._last_action_t = time.monotonic()  # cooldown holds the action
    for _ in range(3):
        assert await p.tick() is None
        assert p.last_diag["burn_actuation"]  # still diagnosed per tick
    assert counted == []  # nothing landed, nothing counted
    p.config = PlannerConfig(min_replicas=1, max_replicas=4,
                             cooldown_s=0.0, burn_up_threshold=2.0)
    assert await p.tick() == 2
    assert counted == ["scale_up", "burn_up"]


async def test_governor_blocked_execute_is_not_an_actuation():
    """EXECUTE that moves nothing (spawn governor blocking) must not
    count an actuation, consume the cooldown, or record a decision —
    the next tick retries the moment the governor allows."""
    from dynamo_tpu.planner.metrics import AggregateLoad

    class _Blocked(_FakeConnector):
        async def scale(self, n):
            self.calls.append(("scale", n))
            return self.replicas  # governor refused: nothing moved

    cfg = PlannerConfig(min_replicas=1, max_replicas=4, cooldown_s=30.0,
                        target_active_per_replica=2.0)
    conn = _Blocked(replicas=1)
    p = _bare_planner(cfg, conn)
    p.observer.load = AggregateLoad(workers=1, active_seqs=8,
                                    mean_kv_usage=0.1)
    counted = []
    p._count = counted.append
    assert await p.tick() is None
    assert counted == [] and list(p.decisions) == []
    assert p._last_action_t == 0.0  # cooldown NOT consumed
    # the moment the connector can move again, the same tick shape acts
    conn.scale = _FakeConnector.scale.__get__(conn)
    assert await p.tick() == 3
    assert counted == ["scale_up"]


async def test_scale_down_held_while_quarantine_holds_a_worker():
    """A held worker keeps publishing near-idle load; acting on that dip
    would drain a HEALTHY worker while the fleet is degraded — scale-down
    waits for the quarantine to resolve."""
    from types import SimpleNamespace

    from dynamo_tpu.planner.metrics import AggregateLoad

    cfg = PlannerConfig(min_replicas=1, max_replicas=4, cooldown_s=0.0,
                        down_stable_ticks=1)
    conn = _FakeConnector(replicas=3)
    p = _bare_planner(cfg, conn)
    p.observer.load = AggregateLoad(workers=3, active_seqs=0,
                                    mean_kv_usage=0.0)
    p.quarantine = SimpleNamespace(held={7: object()})
    assert await p.tick() is None
    assert p.last_diag["scale_down_held_by_quarantine"] == 1
    assert conn.calls == []
    # hold resolved: the same dip now scales down normally
    p.quarantine = None
    assert await p.tick() == 1
    assert conn.calls == [("drain", 1)]


async def test_restore_lease_defers_quarantine_held_keys():
    """A quarantined worker's own canary fail→recover cycle
    (withdraw_lease/restore_lease on ITS backend instance) must not
    resurrect the routing keys the planner withdrew mid-hold."""
    from dynamo_tpu.runtime.discovery import make_discovery

    cluster = uuid.uuid4().hex
    worker_d = make_discovery("mem", cluster_id=cluster)
    planner_d = make_discovery("mem", cluster_id=cluster)
    await worker_d.start()
    await planner_d.start()
    key = "v1/instances/dynamo/mocker/generate/77"
    await worker_d.put(key, {"namespace": "dynamo", "component": "mocker",
                             "endpoint": "generate", "instance_id": 77,
                             "address": "h:1", "metadata": {}})
    # planner quarantines: keys withdrawn + marker published
    q = StragglerQuarantine(planner_d, namespace="dynamo",
                            component="mocker", hold_s=60.0, probe=False)
    await q.reconcile({"live": 2, "stragglers": [77]})
    assert key not in await planner_d.get_prefix("v1/instances")
    # the worker's canary fails then recovers: restore must DEFER
    await worker_d.withdraw_lease()
    await worker_d.restore_lease()
    assert key not in await worker_d.get_prefix("v1/instances")
    assert key in worker_d._withdrawn_values  # stash kept, not lost
    # readmission restores the identity; the worker's next recovery
    # cycle re-owns the key now the marker is gone
    await q.release_all()
    assert key in await worker_d.get_prefix("v1/instances")
    await worker_d.restore_lease()
    assert key in await worker_d.get_prefix("v1/instances")
    await worker_d.close()
    await planner_d.close()


async def test_file_heartbeat_reclaims_after_holder_crash(tmp_path):
    """FileDiscovery: a quarantine hold is exactly as alive as the
    holder's leased marker.  While the marker is fresh the worker's
    heartbeat leaves its withdrawn identity down; a holder that CRASHES
    without readmitting lets the marker expire, and the worker
    re-registers itself at the next beat — and a readmitted identity is
    unleased on the restorer's side, so the restorer's clean exit never
    revokes it."""
    from dynamo_tpu.runtime.discovery import (INSTANCE_PREFIX,
                                              FileDiscovery,
                                              mark_quarantined,
                                              restore_instance,
                                              withdraw_instance)

    key = INSTANCE_PREFIX + "/dynamo/mocker/generate/99"
    val = {"namespace": "dynamo", "component": "mocker",
           "endpoint": "generate", "instance_id": 99, "address": "h:1",
           "metadata": {}}
    worker = FileDiscovery(str(tmp_path), ttl_s=0.6)
    planner = FileDiscovery(str(tmp_path), ttl_s=0.6)
    try:
        await worker.put(key, val)
        stash = await withdraw_instance(planner, 99)
        await mark_quarantined(planner, 99, stash)
        # marker fresh (holder heartbeating): the worker's beats must
        # NOT resurrect the withdrawn identity
        await asyncio.sleep(0.8)
        assert key not in await worker.get_prefix(INSTANCE_PREFIX)
        # holder crashes: heartbeat stops, no readmission ran.  The
        # marker ages past TTL, the worker's reclaim reaps it and
        # restores its own identity.
        planner._closed.set()
        for _ in range(40):
            await asyncio.sleep(0.1)
            if key in await worker.get_prefix(INSTANCE_PREFIX):
                break
        assert key in await worker.get_prefix(INSTANCE_PREFIX)
        # clean-path lease ownership: readmission re-puts UNLEASED, so
        # the restorer's close() cannot revoke the worker's identity
        restorer = FileDiscovery(str(tmp_path), ttl_s=0.6)
        stash = await withdraw_instance(restorer, 99)
        await restore_instance(restorer, stash)
        await restorer.close()
        assert key in await worker.get_prefix(INSTANCE_PREFIX)
    finally:
        await worker.close()
        await planner.close()


async def test_quarantined_worker_stays_on_fleet_board():
    """A held worker's routing keys are gone, but the quarantine marker
    keeps it in obs.fleet snapshots as state='quarantined' — the fleet
    must not appear to shrink while the planner holds a worker."""
    from dynamo_tpu.obs import fleet as obs_fleet
    from dynamo_tpu.runtime.metrics import MetricsHierarchy

    rt = await fresh_runtime().start()
    try:
        w1 = await MockerWorker(rt, engine_args()).start()
        w2 = await MockerWorker(rt, engine_args()).start()
        iid = w1.served.instance_id
        q = StragglerQuarantine(rt.discovery, namespace="dynamo",
                                component="mocker", hold_s=30.0,
                                probe=False, runtime=rt)
        await q.reconcile({"live": 2, "stragglers": [iid]})
        snap = await obs_fleet.snapshot(rt.discovery)
        held = [w for w in snap.workers if w.state == "quarantined"]
        assert [w.worker_id for w in held] == [iid]
        assert snap.summary["quarantined"] == 1
        # counts stay disjoint and the fleet size holds at 2
        assert snap.summary["workers"] == 2
        assert iid not in snap.summary["stragglers"]
        # the state label exports on the worker-count gauge family
        from prometheus_client.parser import \
            text_string_to_metric_families

        m = MetricsHierarchy().scoped(component="fleet")
        obs_fleet.export_fleet_gauges(m, snap)
        held_gauge = [
            s.value for fam in
            text_string_to_metric_families(m.render().decode())
            for s in fam.samples
            if s.name == "dynamo_fleet_workers"
            and s.labels.get("state") == "quarantined"]
        assert held_gauge == [1.0]
        # readmission clears the marker: the board shows 2 in rotation
        await q.release_all()
        snap = await obs_fleet.snapshot(rt.discovery)
        assert snap.summary["quarantined"] == 0
        assert snap.summary["workers"] == 2
        await w1.close()
        await w2.close()
    finally:
        await rt.shutdown()


def test_report_actuation_section(tmp_path):
    """obs.report reduces a /debug/state dump carrying a planner source
    into the actuation section: scale directions, burn actuations,
    quarantine events, spawn/breaker totals, drain escalations."""
    import json

    from dynamo_tpu.obs.report import report_paths

    doc = {"sources": {"planner:mocker": {
        "kind": "planner", "namespace": "dynamo", "component": "mocker",
        "mode": "load", "phase": "",
        "last_diag": {},
        "decisions": [
            {"current": 1, "applied": 2,
             "burn_actuation": {"burn": 5.0}},
            {"current": 2, "applied": 3},
            {"current": 3, "applied": 1},
        ],
        "quarantine": {
            "held": {"42": {"hold_s": 30.0}},
            "strikes": {"42": 2},
            "events": [{"kind": "quarantine"}, {"kind": "requarantine"},
                       {"kind": "readmit"}, {"kind": "quarantine"}],
        },
        "spawn": {"failures_total": 4, "breaker_opens_total": 1,
                  "breaker_open": True},
        "drain_escalations": 1,
    }}}
    path = tmp_path / "planner_state.json"
    path.write_text(json.dumps(doc))
    act = report_paths([str(path)])["actuation"]
    assert act["scale_ups"] == 2 and act["scale_downs"] == 1
    assert act["burn_actuations"] == 1
    assert act["quarantine"] == {
        "held": 1, "strikes": 2,
        "events": {"quarantine": 2, "requarantine": 1, "readmit": 1}}
    assert act["spawn"] == {"failures_total": 4, "breaker_opens_total": 1,
                            "breaker_open": True}
    assert act["drain_escalations"] == 1
    assert act["planners"] == [{"component": "mocker", "mode": "load",
                                "phase": "any", "decisions": 3}]


# ----------------------- crashloop circuit breaker -----------------------


async def test_boot_crash_trips_backoff_and_breaker():
    """A spawn that always fails (chaos connector.spawn) must NOT be
    retried every tick: the governor backs off exponentially, the
    breaker opens after the streak, and both are visible in
    dynamo_planner_* metrics + the tick diag."""
    rt = await fresh_runtime().start()
    try:
        async def bad_spawn():
            raise AssertionError("unreachable: chaos fails first")

        async def stop(w):
            pass

        conn = CallbackConnector(
            bad_spawn, stop,
            governor=SpawnGovernor(backoff_base_s=0.05, backoff_max_s=0.2,
                                   breaker_threshold=3,
                                   breaker_reset_s=30.0))
        planner = Planner(
            rt, "dynamo", "mocker", conn,
            config=PlannerConfig(min_replicas=2, max_replicas=4,
                                 cooldown_s=0.0, quarantine=False))
        await planner.observer.start()
        plane = chaos.ChaosPlane(seed=1).rule("connector.spawn", "fail")
        with plane:
            for _ in range(12):
                await planner.tick()
                await asyncio.sleep(0.03)
        # without the governor this would be ≥12 spawn attempts (one per
        # tick, forever); the backoff + breaker cap the streak
        assert plane.fired("connector.spawn") == 3, plane.injections
        assert conn.governor.breaker_open
        assert planner.last_diag["spawn"]["breaker_open"] is True
        assert metric_value(rt, "dynamo_planner_actuations_total",
                            kind="breaker_open") == 1.0
        assert metric_value(rt,
                            "dynamo_planner_spawn_breaker_open") == 1.0
        # debug surface carries the control-plane state
        dbg = planner.debug_state()
        assert dbg["spawn"]["breaker_open"] is True
        await planner.close()
        await conn.close()
    finally:
        await rt.shutdown()


async def test_planner_scale_seam_fault_is_survivable():
    """chaos planner.scale fail: the tick raises (no actuation), the
    next tick retries and succeeds — the loop never wedges on a failed
    EXECUTE."""
    from dynamo_tpu.planner.metrics import AggregateLoad

    cfg = PlannerConfig(min_replicas=1, max_replicas=4, cooldown_s=0.0,
                        target_active_per_replica=2.0)
    conn = _FakeConnector(replicas=1)
    p = _bare_planner(cfg, conn)
    p.observer.load = AggregateLoad(workers=1, active_seqs=8,
                                    mean_kv_usage=0.1)
    plane = chaos.ChaosPlane(seed=5).rule("planner.scale", "fail",
                                          times=1)
    with plane:
        with pytest.raises(chaos.ChaosError):
            await p.tick()
        assert conn.calls == []          # EXECUTE never ran
        assert await p.tick() == 3       # retried clean next tick
    assert conn.calls == [("scale", 3)]


async def test_drain_on_scale_down_disabled_uses_hard_stop():
    """drain_on_scale_down=False restores the reference hard-stop path
    (and the base Connector.drain default delegates to scale)."""
    from dynamo_tpu.planner.metrics import AggregateLoad

    cfg = PlannerConfig(min_replicas=1, max_replicas=4, cooldown_s=0.0,
                        down_stable_ticks=1, drain_on_scale_down=False)
    conn = _FakeConnector(replicas=3)
    p = _bare_planner(cfg, conn)
    p.observer.load = AggregateLoad(workers=3, active_seqs=0,
                                    mean_kv_usage=0.0)
    assert await p.tick() == 1
    assert conn.calls == [("scale", 1)]
    # with the default, the same scale-down goes through drain()
    cfg2 = PlannerConfig(min_replicas=1, max_replicas=4, cooldown_s=0.0,
                         down_stable_ticks=1)
    conn2 = _FakeConnector(replicas=3)
    p2 = _bare_planner(cfg2, conn2)
    p2.observer.load = AggregateLoad(workers=3, active_seqs=0,
                                     mean_kv_usage=0.0)
    assert await p2.tick() == 1
    assert conn2.calls == [("drain", 1)]
