"""OPT-IN integration tests against a REAL etcd (the fake in
tests/fake_etcd.py cannot prove lease-keepalive jitter, watch-revision
compaction, or reconnect behavior — exactly what fakes get wrong).

Run with a real etcd v3 (needs its grpc-gateway JSON interface, on by
default) and:

    DYN_ETCD_ENDPOINT=http://127.0.0.1:2379 pytest tests/test_etcd_real.py

Skipped entirely when DYN_ETCD_ENDPOINT is unset (CI has no etcd).
Ref behavior: lib/runtime/src/discovery/kv_store.rs (primary lease,
keys bound to it, prefix watch -> delete on expiry)."""

import asyncio
import json
import os
import uuid

import pytest

ENDPOINT = os.environ.get("DYN_ETCD_ENDPOINT", "")

pytestmark = pytest.mark.skipif(
    not ENDPOINT, reason="set DYN_ETCD_ENDPOINT to run real-etcd tests")


def kd(ttl=2.0):
    from dynamo_tpu.runtime.etcd import EtcdDiscovery

    return EtcdDiscovery(ENDPOINT, ttl_s=ttl)


def prefix():
    return f"it/{uuid.uuid4().hex[:8]}/"


async def test_real_lease_expiry_notifies_watchers():
    """Crash (stop keepalive without revoking): the REAL etcd must
    expire the lease and watchers must see the deletes."""
    pre = prefix()
    d1 = kd(ttl=1.0)
    await d1.put(pre + "w/1", {"instance_id": 1})

    d2 = kd(ttl=5.0)
    events = []
    cancel = asyncio.Event()

    async def watch():
        async for ev in d2.watch(pre, cancel=cancel):
            events.append(ev)
            if ev.type == "delete":
                cancel.set()

    task = asyncio.create_task(watch())
    await asyncio.sleep(0.3)
    # simulated crash
    d1._closed.set()
    if d1._ka_task:
        d1._ka_task.cancel()
    await asyncio.wait_for(task, timeout=15)
    assert events[-1].type == "delete"
    assert events[-1].key == pre + "w/1"
    if d1._session is not None and not d1._session.closed:
        await d1._session.close()
    await d2.close()


async def test_real_keepalive_survives_many_ttls():
    """The keepalive cadence (ttl/3) must hold a SHORT lease against a
    real server's expiry clock for many TTLs (fakes cannot prove the
    jitter margins)."""
    pre = prefix()
    d = kd(ttl=1.0)
    await d.put(pre + "w/9", {"instance_id": 9})
    probe = kd(ttl=5.0)
    for _ in range(8):  # 8 x 0.5s = 4s > 4 TTLs
        await asyncio.sleep(0.5)
        assert await probe.get_prefix(pre) == {
            pre + "w/9": {"instance_id": 9}}, "lease lost under keepalive"
    await d.close()
    assert await probe.get_prefix(pre) == {}
    await probe.close()


async def test_real_watch_reconnect_after_compaction():
    """Kill the watch stream, compact the revision it would resume from,
    then mutate: the reconnect path must re-snapshot + diff (not resume
    from a compacted revision and die), emitting the missed delete."""
    pre = prefix()
    d1 = kd(ttl=5.0)
    d2 = kd(ttl=5.0)
    await d1.put(pre + "a", {"v": 1})

    events = []
    cancel = asyncio.Event()

    async def watch():
        async for ev in d2.watch(pre, cancel=cancel):
            events.append(ev)

    task = asyncio.create_task(watch())
    await asyncio.sleep(0.5)
    assert [e.type for e in events] == ["put"]

    # sever the live stream under the watcher (session close simulates a
    # network drop; the generator's retry path must re-snapshot)
    await d2._session.close()

    # mutate while disconnected, then compact everything so the old
    # revision cannot be resumed
    await d1.delete(pre + "a")
    await d1.put(pre + "b", {"v": 2})
    out = await d1._call("/v3/maintenance/status", {})
    head = int(json.loads(json.dumps(out)).get("header", {})
               .get("revision", 0))
    if head:
        try:
            await d1._call("/v3/kv/compaction",
                           {"revision": head, "physical": True})
        except Exception:
            pass  # older gateways name it differently; reconnect still runs

    def keys():
        return {e.key for e in events if e.type == "put"}

    for _ in range(100):
        await asyncio.sleep(0.1)
        if any(e.type == "delete" and e.key == pre + "a"
               for e in events) and pre + "b" in keys():
            break
    cancel.set()
    await asyncio.wait_for(task, timeout=5)
    assert any(e.type == "delete" and e.key == pre + "a" for e in events), \
        "missed delete across reconnect+compaction"
    assert pre + "b" in keys()
    await d1.close()
    await d2.close()
