"""Pallas kernel unification suite (ROADMAP item 1 / PR 12).

Two kernels under test, both interpret-mode on CPU (the same code path
compiles on TPU):

  * the packed-prefill tile-skip kernel
    (ops/pallas_packed_prefill.py) vs the XLA masked reference
    (ops/packed_prefill.py) across segment layouts — uneven lengths,
    prefix-cache committed KV, spec_verify-shaped k+1 rows, int8
    caches, tp sharding;
  * the paged-attention decode kernel's in-kernel int8 dequant
    (ops/pallas_paged_attention.py) vs the jnp gather path.

Plus the engine-level contracts: greedy byte-identity at
impl=pallas_interpret with kv_cache_dtype=int8 (overlap scheduling
ON), the zero-recompile steady state with the kernels in the watched
families, and the --attn-impl config/CLI plumbing.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks

from dynamo_tpu.ops.packed_prefill import (
    packed_prefill_attention,
    write_packed_kv,
)
from dynamo_tpu.ops.paged_attention import (
    paged_attention_decode_jnp,
    write_prompt_kv,
)
from dynamo_tpu.ops.pallas_packed_prefill import (
    packed_prefill_attention_pallas,
)
from dynamo_tpu.ops.pallas_paged_attention import (
    paged_attention_decode_pallas,
)


def _packed_case(rng, lens, *, nkv=2, group=2, hd=16, bs=4, mb=8, L=2,
                 bucket=None, ctx0=None, dtype=jnp.float32, int8=False):
    """Build one packed-stream case: per-segment chunk lengths `lens`
    (0 = unused row), optional committed prefix lengths `ctx0` already
    in cache before the chunk, KV written through the real write ops so
    int8 cases round-trip the quantizer exactly like serving."""
    S = len(lens)
    nh = nkv * group
    num_blocks = 1 + S * mb
    ctx0 = ctx0 or [0] * S
    T = sum(lens)
    bucket = bucket or T
    pad = bucket - T
    seg_ids = np.concatenate(
        [np.full(n, i, np.int32) for i, n in enumerate(lens)]
        + [np.zeros(pad, np.int32)])
    positions = np.concatenate(
        [c + np.arange(n, dtype=np.int32) for c, n in zip(ctx0, lens)]
        + [np.zeros(pad, np.int32)])
    valid = np.concatenate([np.ones(T, bool), np.zeros(pad, bool)])
    tables = np.zeros((S, mb), np.int32)
    perm = rng.permutation(num_blocks - 1) + 1
    for s in range(S):
        tables[s] = perm[s * mb:(s + 1) * mb]
    tables = jnp.asarray(tables)
    seg_ids = jnp.asarray(seg_ids)
    positions = jnp.asarray(positions)
    valid = jnp.asarray(valid)

    cache_shape = (L, nkv, num_blocks, hd, bs)
    if int8:
        kc = jnp.zeros(cache_shape, jnp.int8)
        vc = jnp.zeros(cache_shape, jnp.int8)
        ks = jnp.zeros((L, nkv, num_blocks, bs), jnp.float32)
        vs = jnp.zeros((L, nkv, num_blocks, bs), jnp.float32)
    else:
        kc = jnp.asarray(rng.standard_normal(cache_shape), dtype)
        vc = jnp.asarray(rng.standard_normal(cache_shape), dtype)
        ks = vs = None

    # committed prefixes first (prefix-cache hits): written through the
    # prompt write op, exactly as a previous chunk would have
    for li in range(L):
        for s, c in enumerate(ctx0):
            if c == 0:
                continue
            kp = jnp.asarray(rng.standard_normal((c, nkv, hd)), dtype)
            vp = jnp.asarray(rng.standard_normal((c, nkv, hd)), dtype)
            out = write_prompt_kv(kc, vc, li, kp, vp, tables[s],
                                  jnp.int32(0), jnp.int32(c),
                                  k_scale=ks, v_scale=vs)
            kc, vc, ks, vs = out if len(out) == 4 else (*out, None, None)
        kch = jnp.asarray(rng.standard_normal((bucket, nkv, hd)), dtype)
        vch = jnp.asarray(rng.standard_normal((bucket, nkv, hd)), dtype)
        out = write_packed_kv(kc, vc, li, kch, vch, tables, seg_ids,
                              positions, valid, k_scale=ks, v_scale=vs)
        kc, vc, ks, vs = out if len(out) == 4 else (*out, None, None)
    q = jnp.asarray(rng.standard_normal((bucket, nh, hd)), dtype)
    return q, kc, vc, ks, vs, tables, seg_ids, positions, valid


def _assert_packed_parity(case, L=2, **pallas_kw):
    q, kc, vc, ks, vs, tables, seg_ids, positions, valid = case
    # parity on the LAST layer only: the layer index selects a cache
    # slice (the kernel body is layer-independent), and every extra
    # layer is a second interpret-mode trace+compile of tier-1 wall
    # clock; li=L-1 keeps the non-zero-offset slicing under test
    for li in (L - 1,):
        ref = packed_prefill_attention(
            q, kc, vc, li, tables, seg_ids, positions, valid,
            impl="xla", k_scale=ks, v_scale=vs)
        out = packed_prefill_attention_pallas(
            q, kc, vc, li, tables, seg_ids, positions, valid,
            interpret=True, k_scale=ks, v_scale=vs, **pallas_kw)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("lens,bucket", [
    ([7, 1, 12, 4], 32),       # uneven lengths + padded tail
    ([16, 16], 32),            # balanced, no tail
    ([3], 8),                  # single segment
    ([5, 0, 9, 0], 16),        # unused segment rows (waterfill leftovers)
])
def test_packed_pallas_matches_xla_segment_layouts(lens, bucket):
    """Tile-skip kernel vs the masked XLA reference across the segment
    layouts the packing planner actually produces."""
    rng = np.random.default_rng(0)
    case = _packed_case(rng, lens, bucket=bucket)
    _assert_packed_parity(case)


def test_packed_pallas_multi_tile_and_chunking():
    """Small token_block + chunk_cols force the tile grid and the
    double-buffered context chunk loop through many iterations, with a
    segment boundary landing mid-tile."""
    rng = np.random.default_rng(1)
    case = _packed_case(rng, [11, 9, 6], bucket=32)
    _assert_packed_parity(case, token_block=8, chunk_cols=2)


def test_packed_pallas_committed_prefix():
    """Prefix-cache hits: chunk tokens at positions ctx0.. attend to the
    committed KV written by earlier chunks through the block table."""
    rng = np.random.default_rng(2)
    case = _packed_case(rng, [6, 10], ctx0=[5, 13], mb=8, bucket=16)
    _assert_packed_parity(case, token_block=8, chunk_cols=2)


def test_packed_pallas_spec_verify_rows():
    """spec_verify's layout: S rows of k+1 tokens each at large committed
    positions (the draft window riding a long context)."""
    rng = np.random.default_rng(3)
    k = 4
    case = _packed_case(rng, [k + 1] * 3, ctx0=[17, 9, 26], mb=8,
                        bucket=16)
    _assert_packed_parity(case)


def test_packed_pallas_int8_dequant():
    """Int8 cache: the kernel's fused in-VMEM dequant must match the
    XLA reference's gather-side dequant on the same quantized cache
    (both read the identical int8+scale planes)."""
    rng = np.random.default_rng(4)
    case = _packed_case(rng, [7, 1, 12, 4], bucket=32, int8=True,
                        ctx0=[3, 0, 0, 5])
    _assert_packed_parity(case, token_block=8, chunk_cols=2)


def test_packed_pallas_bf16_tolerance():
    rng = np.random.default_rng(5)
    case = _packed_case(rng, [9, 7], bucket=16, dtype=jnp.bfloat16)
    q, kc, vc, ks, vs, tables, seg_ids, positions, valid = case
    ref = packed_prefill_attention(
        q, kc, vc, 0, tables, seg_ids, positions, valid, impl="xla")
    out = packed_prefill_attention_pallas(
        q, kc, vc, 0, tables, seg_ids, positions, valid, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=0.05, atol=0.05)


def test_packed_pallas_tp_sharded_matches_xla():
    """The packed kernel under shard_map over a tp>1 mesh (each shard
    owning its kv-head slice) must match the unsharded XLA reference —
    the path multi-chip packed prefill takes at impl=pallas."""
    from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

    rng = np.random.default_rng(6)
    case = _packed_case(rng, [7, 9], nkv=4, group=2, bucket=16)
    q, kc, vc, ks, vs, tables, seg_ids, positions, valid = case
    ref = packed_prefill_attention(
        q, kc, vc, 0, tables, seg_ids, positions, valid, impl="xla")
    mesh = make_mesh(MeshConfig(dp=2, tp=4))  # 8 virtual CPU devices
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = NamedSharding(mesh, P(None, "tp", None, None, None))
    with mesh:
        kc_s = jax.device_put(kc, spec)
        vc_s = jax.device_put(vc, spec)
        out = jax.jit(
            lambda q_, kc_, vc_, t_, s_, p_, v_: packed_prefill_attention(
                q_, kc_, vc_, 0, t_, s_, p_, v_,
                impl="pallas_interpret", mesh=mesh)
        )(q, kc_s, vc_s, tables, seg_ids, positions, valid)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# decode kernel: in-kernel int8 dequant
# ---------------------------------------------------------------------------


def _int8_decode_case(rng, kv_lens, *, nkv=2, group=2, hd=16, bs=4,
                      mb=6, L=2):
    B = len(kv_lens)
    nh = nkv * group
    num_blocks = 1 + B * mb
    kc = jnp.zeros((L, nkv, num_blocks, hd, bs), jnp.int8)
    vc = jnp.zeros((L, nkv, num_blocks, hd, bs), jnp.int8)
    ks = jnp.zeros((L, nkv, num_blocks, bs), jnp.float32)
    vs = jnp.zeros((L, nkv, num_blocks, bs), jnp.float32)
    tables = np.zeros((B, mb), np.int32)
    perm = rng.permutation(num_blocks - 1) + 1
    for b in range(B):
        tables[b] = perm[b * mb:(b + 1) * mb]
    tables = jnp.asarray(tables)
    for b in range(B):
        n = int(kv_lens[b])
        kt = jnp.asarray(rng.standard_normal((n, nkv, hd)), jnp.float32)
        vt = jnp.asarray(rng.standard_normal((n, nkv, hd)), jnp.float32)
        for li in range(L):
            kc, vc, ks, vs = write_prompt_kv(
                kc, vc, li, kt, vt, tables[b], jnp.int32(0),
                jnp.int32(n), k_scale=ks, v_scale=vs)
    q = jnp.asarray(rng.standard_normal((B, nh, hd)), jnp.float32)
    return q, kc, vc, ks, vs, tables, jnp.asarray(
        np.asarray(kv_lens, np.int32))


def test_int8_decode_pallas_matches_jnp():
    """In-kernel dequant vs the jnp gather path's dequant-on-gather, on
    the same quantized cache — uneven lengths incl. partial blocks, and
    blocks_per_chunk forced small so the double-buffered scale DMA loop
    runs several iterations."""
    rng = np.random.default_rng(7)
    q, kc, vc, ks, vs, tables, kv_lens = _int8_decode_case(
        rng, [17, 24, 5])
    # layer 1 only — same one-interpret-trace rationale as
    # _assert_packed_parity, non-zero layer offset kept under test
    for li in (1,):
        ref = paged_attention_decode_jnp(q, kc, vc, li, tables, kv_lens,
                                         k_scale=ks, v_scale=vs)
        out = paged_attention_decode_pallas(
            q, kc, vc, li, tables, kv_lens, interpret=True,
            k_scale=ks, v_scale=vs, blocks_per_chunk=2)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_int8_decode_pallas_tp_sharded_matches_jnp():
    """The int8 kernel under shard_map over tp>1: each shard DMAs and
    dequantizes its own cache+scale slab (kv_scale_spec sharding)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamo_tpu.ops.paged_attention import paged_attention_decode
    from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

    rng = np.random.default_rng(8)
    q, kc, vc, ks, vs, tables, kv_lens = _int8_decode_case(
        rng, [13, 7, 21], nkv=4)
    ref = paged_attention_decode_jnp(q, kc, vc, 1, tables, kv_lens,
                                     k_scale=ks, v_scale=vs)
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    cspec = NamedSharding(mesh, P(None, "tp", None, None, None))
    sspec = NamedSharding(mesh, P(None, "tp", None, None))
    with mesh:
        kc_s, vc_s = jax.device_put(kc, cspec), jax.device_put(vc, cspec)
        ks_s, vs_s = jax.device_put(ks, sspec), jax.device_put(vs, sspec)
        out = jax.jit(
            lambda q_, kc_, vc_, ks_, vs_, t_, l_: paged_attention_decode(
                q_, kc_, vc_, 1, t_, l_, impl="pallas_interpret",
                mesh=mesh, k_scale=ks_, v_scale=vs_)
        )(q, kc_s, vc_s, ks_s, vs_s, tables, kv_lens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_int8_no_longer_reroutes_pallas_to_jnp():
    """The PR 3 caveat is dead: impl="pallas_interpret" with scales must
    run the KERNEL, not silently fall back to the gather path.  The
    kernel's online softmax reassociates differently from the one-shot
    softmax, so bit-identical output to the jnp path would itself be
    suspicious; instead pin the dispatch by breaking the kernel's
    input contract and seeing the kernel's own failure mode."""
    from dynamo_tpu.ops.paged_attention import paged_attention_decode

    rng = np.random.default_rng(9)
    q, kc, vc, ks, vs, tables, kv_lens = _int8_decode_case(rng, [9, 12])
    out = paged_attention_decode(q, kc, vc, 0, tables, kv_lens,
                                 impl="pallas_interpret",
                                 k_scale=ks, v_scale=vs)
    ref = paged_attention_decode_jnp(q, kc, vc, 0, tables, kv_lens,
                                     k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    # and the result is NOT the bf16-operand fallback the old reroute
    # produced (jnp_bf16 quantizes operands to bf16; the kernel keeps
    # the query dtype fp32 here, so a max-abs-diff this small vs the
    # fp32 reference is only reachable through the kernel)
    bf16 = paged_attention_decode_jnp(q, kc, vc, 0, tables, kv_lens,
                                      native_dtype=True,
                                      k_scale=ks, v_scale=vs)
    assert float(jnp.max(jnp.abs(out - ref))) < \
        float(jnp.max(jnp.abs(bf16 - ref)))


# ---------------------------------------------------------------------------
# engine-level composition
# ---------------------------------------------------------------------------


def _engine_cfg(**kw):
    from test_engine import FP32 as _FP32

    from dynamo_tpu.engine import EngineConfig

    defaults = dict(model_config=_FP32, block_size=4, num_blocks=128,
                    max_blocks_per_seq=16, max_num_seqs=2,
                    prefill_buckets=(8, 16), seed=7)
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _greedy(cfg, prompt, n, rid):
    from test_engine import collect, greedy_req

    from dynamo_tpu.engine import JaxEngine

    eng = JaxEngine(cfg)
    toks = await collect(eng, greedy_req(list(prompt), n, rid))
    await eng.close()
    return toks


async def test_engine_greedy_int8_pallas_byte_identity():
    """The acceptance gate: greedy byte-identity at impl=pallas_interpret
    for BOTH kernels with kv_cache_dtype=int8 and overlap scheduling ON
    — quantization composes with the fast path end to end."""
    prompt = [5, 9, 13, 2, 7, 11, 3, 1, 8, 20]
    # 5 decode steps cross a block boundary (block_size=4) so identity
    # covers intra- and inter-block paging.  decode_fused_steps=1 and
    # the smaller table keep tier-1 wall clock sane: every fusion-ladder
    # rung is its own interpret-mode compile (~12s each on CPU, and the
    # trace cost scales with max_blocks_per_seq); identical settings on
    # both engines keep the comparison exact.
    wall = dict(kv_cache_dtype="int8", overlap_scheduling=True,
                decode_fused_steps=1, num_blocks=64, max_blocks_per_seq=8)
    ref = await _greedy(_engine_cfg(**wall), prompt, 5, "i8-jnp")
    pal = await _greedy(
        _engine_cfg(attn_impl="pallas_interpret",
                    packed_attn_impl="pallas_interpret", **wall),
        prompt, 5, "i8-pal")
    assert len(ref) == 5  # a crashed engine's empty stream is vacuous
    assert pal == ref


async def test_zero_recompiles_with_pallas_kernels():
    """The new kernels ride the watched compile families (prefill_packed
    / decode): warmup + the first request compile each shape ONCE, two
    more same-shape requests compile NOTHING — the PR 11 pinned
    out_shardings invariant holds with pallas_call in the programs and
    the int8 4-tuple riding donation."""
    from dynamo_tpu.engine import JaxEngine

    # decode_fused_steps=1 keeps the warmup to the single-step decode
    # program (each interpret-mode pallas compile costs seconds on CPU;
    # the family-count contract is identical)
    eng = JaxEngine(_engine_cfg(
        kv_cache_dtype="int8", attn_impl="pallas_interpret",
        packed_attn_impl="pallas_interpret", decode_fused_steps=1,
        num_blocks=64, max_blocks_per_seq=8))
    try:
        await asyncio.to_thread(eng.warmup_decode)
        from test_engine import collect, greedy_req

        # 4 tokens/request: the compile-family counts under judgment are
        # identical at any length ≥1, and every interpret-mode decode
        # step is seconds of tier-1 wall clock
        await collect(eng, greedy_req([5, 9, 13, 2, 7, 11, 3, 1, 8, 20],
                                      4, "pk-r0"))
        counts = dict(eng.compile_watch.counts)
        assert counts.get("prefill_packed", 0) == 1
        assert counts.get("decode", 0) >= 1
        await collect(eng, greedy_req([6, 10, 14, 3, 8, 12, 4, 2, 9, 21],
                                      4, "pk-r1"))
        await collect(eng, greedy_req([9, 13, 17, 6, 11, 15, 7, 5, 12, 24],
                                      4, "pk-r2"))
        assert dict(eng.compile_watch.counts) == counts, \
            "steady-state serving recompiled a pallas-kernel program"
    finally:
        await eng.close()


def test_engine_config_attn_impl_override_and_validation():
    """EngineConfig.attn_impl/packed_attn_impl replace the resolved
    model config's fields; junk values fail fast at engine init."""
    from dynamo_tpu.engine import JaxEngine

    eng = JaxEngine(_engine_cfg(attn_impl="jnp_bf16",
                                packed_attn_impl="xla"))
    assert eng.model_cfg.attn_impl == "jnp_bf16"
    assert eng.model_cfg.packed_attn_impl == "xla"
    with pytest.raises(ValueError, match="attn_impl"):
        JaxEngine(_engine_cfg(attn_impl="triton"))
    with pytest.raises(ValueError, match="packed_attn_impl"):
        JaxEngine(_engine_cfg(packed_attn_impl="cuda"))


def test_engine_cli_parses_attn_impl_flags():
    from dynamo_tpu.engine.__main__ import build_args

    a = build_args().parse_args(
        ["--attn-impl", "pallas", "--packed-attn-impl", "pallas"])
    assert a.attn_impl == "pallas"
    assert a.packed_attn_impl == "pallas"
    # default keeps the model family's choice
    d = build_args().parse_args([])
    assert d.attn_impl == "" and d.packed_attn_impl == ""
    with pytest.raises(SystemExit):
        build_args().parse_args(["--attn-impl", "triton"])


def test_mla_rejects_attn_impl_overrides():
    """MLA consults neither knob: its absorbed-latent decode never
    dispatches paged_attention_decode (SUPPORTED_ATTN_IMPLS = jnp) and
    it has no packed path — asking its worker for a kernel must be a
    config error, not a silent no-op the MDC then mis-advertises."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine

    def mla_cfg(**kw):
        return EngineConfig(model="tiny-mla", block_size=4,
                            num_blocks=32, max_blocks_per_seq=8, **kw)

    with pytest.raises(ValueError, match="packed_attn_impl"):
        JaxEngine(mla_cfg(packed_attn_impl="pallas_interpret"))
    with pytest.raises(ValueError, match="attn_impl"):
        JaxEngine(mla_cfg(attn_impl="pallas"))
    # the one value MLA actually runs passes through
    eng = JaxEngine(mla_cfg(attn_impl="jnp"))
    assert eng.model_cfg.attn_impl == "jnp"
