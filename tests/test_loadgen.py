"""Loadgen: trace schema round-trip, prefix-sharing materialization, and
open-loop replay against a mocker worker with TTFT/ITL/goodput capture."""

import asyncio
import json
import uuid

from dynamo_tpu.loadgen import (
    TraceRow,
    load_trace,
    materialize_tokens,
    replay,
    save_trace,
    synthesize,
)
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig


def test_trace_roundtrip_and_aliases(tmp_path):
    rows = synthesize(10, rate_rps=100.0, input_len=64, output_len=8,
                      prefix_groups=2, prefix_blocks=3, seed=1)
    p = tmp_path / "t.jsonl"
    save_trace(str(p), rows)
    back = load_trace(str(p))
    assert [r.request_id for r in back] == [r.request_id for r in rows]
    assert [r.hash_ids for r in back] == [r.hash_ids for r in rows]
    # upstream mooncake aliases load into the canonical fields
    alias = tmp_path / "alias.jsonl"
    alias.write_text(json.dumps({
        "input_tokens": 32, "output_tokens": 4, "created_time": 1500.0,
    }) + "\n")
    [r] = load_trace(str(alias))
    assert (r.input_length, r.output_length, r.timestamp) == (32, 4, 1500.0)


def test_materialize_prefix_sharing():
    a = TraceRow(request_id="a", input_length=40, hash_ids=[1, 2])
    b = TraceRow(request_id="b", input_length=40, hash_ids=[1, 2])
    c = TraceRow(request_id="c", input_length=40, hash_ids=[9, 2])
    ta, tb, tc = (materialize_tokens(r, block_size=16) for r in (a, b, c))
    assert len(ta) == 40
    assert ta[:32] == tb[:32]          # shared hash_ids -> shared blocks
    assert ta[:16] != tc[:16]          # different first block
    assert ta[16:32] == tc[16:32]      # same second block
    assert ta[32:] != tb[32:]          # per-request tail is unique


async def test_replay_against_mocker_reports_latencies():
    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem", event_plane="inproc"),
        cluster_id=uuid.uuid4().hex,
    ).start()
    worker = await MockerWorker(
        rt, MockEngineArgs(model_name="m", block_size=16, num_blocks=1024,
                           speedup_ratio=50.0),
        component="backend",
    ).start()
    client = await (rt.namespace("dynamo").component("backend")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()

    rows = synthesize(12, rate_rps=200.0, input_len=48, output_len=6,
                      prefix_groups=2, prefix_blocks=2, seed=3)
    report = await replay(client.generate, rows, block_size=16,
                          speedup=2.0)
    s = report.summary(slo_ttft_s=30.0, slo_itl_s=30.0)
    assert s["completed"] == 12 and s["errors"] == 0
    assert s["output_tokens_per_s"] > 0
    assert s["ttft_s"]["p50"] > 0 and s["ttft_s"]["p99"] >= s["ttft_s"]["p50"]
    assert s["itl_s"]["p50"] > 0
    # generous SLOs: everything is good -> goodput == completion rate
    assert s["goodput"]["good_requests"] == 12

    # session turns serialize: the follow-up fires only after turn 1
    sess = [TraceRow(request_id="s0", session_id="S", input_length=32,
                     output_length=4, timestamp=0.0),
            TraceRow(request_id="s1", session_id="S", input_length=16,
                     output_length=4, delay=10.0)]
    rep2 = await replay(client.generate, sess, block_size=16)
    r0 = next(r for r in rep2.results if r.request_id == "s0")
    r1 = next(r for r in rep2.results if r.request_id == "s1")
    assert r1.start_t >= r0.end_t

    await client.close()
    await worker.close()
    await rt.shutdown()
