"""Engine-level fused sampling epilogue contracts (PR 17).

EngineConfig.sampling_epilogue="fused" swaps the decode/decode_multi
programs onto the hidden-state surface (models/llama.py decode_hidden /
decode_multi_hidden) + the streaming epilogue (ops/fused_sampling.py).
The contracts pinned here:

  * greedy streams are byte-identical epilogue on vs off, with overlap
    scheduling ON and an int8 KV cache (the serving composition);
  * seeded sampled streams are draw-identical (same keys, same window);
  * the epilogue rides the SAME program families as the reference path
    (it is a static init-time choice baked into the partials, not a
    dispatch key): warmup + first request compile each shape once and
    steady-state serving recompiles nothing;
  * config validation fails fast on junk values, MLA families (no
    hidden-state decode surface) fall back to "off", and the worker
    CLI parses the flag.
"""

import asyncio

import pytest

# real-JAX-engine tests: XLA compiles and device work run inside the
# async test bodies (see test_engine.py's rationale)
pytestmark = pytest.mark.allow_slow_callbacks

from test_engine import collect, greedy_req

from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.protocols import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)


def _cfg(**kw):
    from test_engine import FP32

    defaults = dict(model_config=FP32, block_size=4, num_blocks=128,
                    max_blocks_per_seq=16, max_num_seqs=2,
                    prefill_buckets=(8, 16), seed=7)
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _run(cfg, req):
    eng = JaxEngine(cfg)
    toks = await collect(eng, req)
    await eng.close()
    return toks


def _sampled_req(tokens, n, rid, *, temperature, top_k=0, top_p=1.0,
                 seed=123):
    return PreprocessedRequest(
        token_ids=tokens, request_id=rid,
        sampling=SamplingOptions(temperature=temperature, top_k=top_k,
                                 top_p=top_p, seed=seed),
        stop=StopConditions(max_tokens=n, ignore_eos=True))


PROMPT = [5, 9, 13, 2, 7, 11, 3, 1, 8, 20]


async def test_greedy_byte_identity_overlap_int8():
    """The acceptance gate: epilogue ON vs OFF greedy streams are
    byte-identical with overlap scheduling ON and kv_cache_dtype=int8 —
    the fused path composes with the whole fast stack."""
    ref = await _run(
        _cfg(kv_cache_dtype="int8", overlap_scheduling=True,
             sampling_epilogue="off"),
        greedy_req(list(PROMPT), 10, "ep-off"))
    fused = await _run(
        _cfg(kv_cache_dtype="int8", overlap_scheduling=True,
             sampling_epilogue="fused"),
        greedy_req(list(PROMPT), 10, "ep-on"))
    assert len(ref) == 10  # a crashed engine's empty stream is vacuous
    assert fused == ref


async def test_sampled_draw_identity():
    """Seeded temperature/top-k/top-p request: the streamed window must
    make every per-step categorical draw the token the reference path
    draws (distribution-identity realized as draw-identity at a fixed
    key stream)."""
    req = _sampled_req(list(PROMPT), 12, "ep-s", temperature=0.8,
                       top_k=20, top_p=0.9)
    ref = await _run(_cfg(sampling_epilogue="off"), req)
    req2 = _sampled_req(list(PROMPT), 12, "ep-s2", temperature=0.8,
                        top_k=20, top_p=0.9)
    fused = await _run(_cfg(sampling_epilogue="fused"), req2)
    assert len(ref) == 12
    assert fused == ref


async def test_zero_recompiles_with_epilogue():
    """The epilogue is baked into the decode partials (no new program
    family, no new dispatch key): after warmup + the first request,
    same-shape serving compiles NOTHING — the pinned out_shardings
    zero-recompile invariant covers the fused programs too."""
    eng = JaxEngine(_cfg(sampling_epilogue="fused",
                         kv_cache_dtype="int8", decode_fused_steps=2))
    try:
        await asyncio.to_thread(eng.warmup_decode)
        await collect(eng, greedy_req(list(PROMPT), 12, "ep-r0"))
        counts = dict(eng.compile_watch.counts)
        assert counts.get("prefill_packed", 0) == 1
        assert counts.get("decode", 0) >= 1
        await collect(eng, greedy_req(
            [6, 10, 14, 3, 8, 12, 4, 2, 9, 21], 12, "ep-r1"))
        await collect(eng, _sampled_req(
            [9, 13, 17, 6, 11, 15, 7, 5, 12, 24], 12, "ep-r2",
            temperature=0.7, top_k=8))
        assert dict(eng.compile_watch.counts) == counts, \
            "steady-state serving recompiled an epilogue program"
    finally:
        await eng.close()


async def test_warmup_serializes_with_steps():
    """warmup_decode holds _step_lock for its dispatch+restore section.

    The worker serves its generate endpoint (and arms the health-check
    canary) before warmup runs, so a canary probe can start the
    scheduler loop while warmup is still compiling; an unlocked
    _sched_step then reads self.kv between two warmup dispatches that
    already donated it ("Array has been deleted" in _prefill_packed, a
    permanently dead engine loop).  Pin the serialization contract: a
    held step lock blocks warmup, and serving after a contended warmup
    still streams."""
    import threading
    import time

    eng = JaxEngine(_cfg(decode_fused_steps=1))
    try:
        # first warmup pays the compiles so the contended one below
        # measures lock behavior, not XLA
        await asyncio.to_thread(eng.warmup_decode)
        eng._step_lock.acquire()
        t = threading.Thread(target=eng.warmup_decode, daemon=True)
        t.start()
        t.join(timeout=0.5)
        try:
            assert t.is_alive(), \
                "warmup_decode ran without taking the step lock"
        finally:
            eng._step_lock.release()
        deadline = time.monotonic() + 30.0
        while t.is_alive() and time.monotonic() < deadline:
            t.join(timeout=0.2)
        assert not t.is_alive()
        toks = await collect(eng, greedy_req(list(PROMPT), 10, "ep-w"))
        assert len(toks) == 10
    finally:
        await eng.close()


def test_config_validation_and_mode():
    eng = JaxEngine(_cfg(sampling_epilogue="fused"))
    assert eng.sampling_epilogue == "fused"
    eng2 = JaxEngine(_cfg())
    assert eng2.sampling_epilogue == "off"
    with pytest.raises(ValueError, match="sampling_epilogue"):
        JaxEngine(_cfg(sampling_epilogue="pallas"))


def test_cli_parses_sampling_epilogue():
    from dynamo_tpu.engine.__main__ import build_args

    a = build_args().parse_args(["--sampling-epilogue", "fused"])
    assert a.sampling_epilogue == "fused"
    assert build_args().parse_args([]).sampling_epilogue == "off"
    with pytest.raises(SystemExit):
        build_args().parse_args(["--sampling-epilogue", "pallas"])


def test_worker_mdc_advertises_epilogue():
    """The MDC runtime_config must carry the EFFECTIVE epilogue mode so
    routers/planners can tell fused workers from reference ones."""
    from dynamo_tpu.engine.worker import JaxEngineWorker

    w = JaxEngineWorker(None, _cfg(sampling_epilogue="fused"))
    assert w.card.runtime_config["sampling_epilogue"] == "fused"
    # MLA's absorbed-latent decode has no hidden-state surface
    # (decode_hidden/unembed_weight): the engine degrades fused -> off
    # (same precedent as kv_cache_dtype), and the card must carry the
    # engine's RESOLVED mode, not the requested one
    mla_cfg = EngineConfig(model="tiny-mla", block_size=4, num_blocks=32,
                           max_blocks_per_seq=8,
                           sampling_epilogue="fused")
    w2 = JaxEngineWorker(None, mla_cfg)
    w2.engine = JaxEngine(mla_cfg)
    assert w2.engine.sampling_epilogue == "off"
    assert w2.card.runtime_config["sampling_epilogue"] == "off"
