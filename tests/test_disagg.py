"""Disaggregated prefill/decode tests.

- conditional-disagg policy unit tests
- mocker-level disagg e2e (frontend orchestration, CPU-fast)
- JAX engine-to-engine KV transfer roundtrip: prefill on engine A, pull
  blocks over the request plane, inject into engine B, and check the decode
  continuation equals aggregated serving on a single engine (the strongest
  correctness property of the transfer path).
"""

import asyncio
import uuid

import jax.numpy as jnp

from dynamo_tpu.disagg.prefill_router import (
    ConditionalDisaggConfig,
    PrefillOrchestrator,
)
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.protocols import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

FP32 = LlamaConfig(name="tiny32", vocab_size=256, d_model=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
                   dtype=jnp.float32)


def fresh_runtime():
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


def greedy_req(tokens, n, rid):
    return PreprocessedRequest(
        token_ids=tokens, request_id=rid,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )


def test_conditional_disagg_policy():
    orch = PrefillOrchestrator.__new__(PrefillOrchestrator)
    orch.config = ConditionalDisaggConfig(min_effective_isl=100,
                                          min_effective_ratio=0.7)
    req = greedy_req(list(range(200)), 5, "r")
    assert orch.should_disagg(req, overlap_tokens=0)          # long, cold
    assert not orch.should_disagg(req, overlap_tokens=150)    # mostly cached
    short = greedy_req(list(range(50)), 5, "r2")
    assert not orch.should_disagg(short, overlap_tokens=0)    # too short
    orch.config = ConditionalDisaggConfig(always_remote=True)
    assert orch.should_disagg(short, overlap_tokens=50)


async def test_mocker_disagg_e2e():
    """Prefill mocker + decode mocker behind the frontend orchestration."""
    from dynamo_tpu.frontend import ModelManager, ModelWatcher
    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker

    rt = await fresh_runtime().start()
    common = dict(model_name="m", block_size=4, base_step_s=0.0005,
                  prefill_s_per_token=0.0, decode_s_per_seq=0.0)
    decode_w = await MockerWorker(
        rt, MockEngineArgs(role="decode", **common), component="backend"
    ).start()
    prefill_w = await MockerWorker(
        rt, MockEngineArgs(role="prefill", **common), component="prefill"
    ).start()

    manager = ModelManager()
    watcher = await ModelWatcher(
        rt, manager,
        disagg_config=ConditionalDisaggConfig(min_effective_isl=8,
                                              min_effective_ratio=0.0),
    ).start()
    for _ in range(100):
        p = manager.get("m")
        if p is not None and p.prefill is not None:
            break
        await asyncio.sleep(0.02)
    pipeline = manager.get("m")
    assert pipeline is not None and pipeline.prefill is not None

    req = greedy_req(list(range(40)), 5, "d1")
    deltas = [d async for d in pipeline.generate_deltas(req)]
    assert deltas[-1].finish_reason is not None
    assert sum(d.token_count for d in deltas) == 5
    # the prefill mocker actually served a hop (its engine saw the request)
    assert prefill_w.engine.metrics["prefill_tokens"] >= 40
    # decode mocker skipped prefill compute (remote_prefilled path)
    assert decode_w.engine.metrics["prefill_tokens"] == 0

    # short request bypasses remote prefill (conditional disagg)
    watcher2_cfg = pipeline.prefill.config
    watcher2_cfg.min_effective_isl = 1000
    p_before = prefill_w.engine.metrics["prefill_tokens"]
    req2 = greedy_req(list(range(12)), 3, "d2")
    deltas = [d async for d in pipeline.generate_deltas(req2)]
    assert sum(d.token_count for d in deltas) == 3
    assert prefill_w.engine.metrics["prefill_tokens"] == p_before

    await watcher.close()
    await prefill_w.close()
    await decode_w.close()
    await rt.shutdown()


def test_chunked_transfer_protocol_roundtrip():
    """Header + bounded slabs reassemble to the exact payload; incomplete
    streams and incompatible layouts fail loudly."""
    import numpy as np
    import pytest

    from dynamo_tpu.disagg.transfer import (
        ChunkAssembler, KvLayout, iter_chunks, make_header,
    )

    rng = np.random.default_rng(3)
    k = rng.normal(size=(2, 6, 4, 2, 8)).astype(np.float32)
    v = rng.normal(size=(2, 6, 4, 2, 8)).astype(np.float32)
    block_bytes = k[0, :1].nbytes
    frames = list(iter_chunks(k, v, max_bytes=2 * 2 * block_bytes))
    # 6 blocks / 2-per-slab * 2 layers = 6 frames, each within the bound
    assert len(frames) == 6
    assert all(len(f["k"]) + len(f["v"]) <= 4 * block_bytes for f in frames)

    layout = KvLayout.of(k, tp=1)
    asm = ChunkAssembler(make_header(24, layout))
    for f in frames:
        asm.add(f)
    out = asm.finish()
    np.testing.assert_array_equal(out.k, k)
    np.testing.assert_array_equal(out.v, v)
    assert asm.prompt_len == 24

    # a dropped slab is an error, not silent zeros
    asm2 = ChunkAssembler(make_header(24, layout))
    for f in frames[:-1]:
        asm2.add(f)
    with pytest.raises(ValueError, match="incomplete"):
        asm2.finish()

    # logical-geometry mismatch rejected at the header; tp may differ
    other = KvLayout.of(k, tp=4)
    other.kv_heads = 8
    with pytest.raises(ValueError, match="kv_heads"):
        ChunkAssembler(make_header(24, layout), expect=other)
    ok = KvLayout.of(k, tp=4)  # same geometry, different parallelism
    ChunkAssembler(make_header(24, layout), expect=ok)

    # a corrupt header must not size the receiver's allocation unbounded
    huge = KvLayout.of(k)
    huge.num_blocks = 2**30
    with pytest.raises(ValueError, match="exceeds"):
        ChunkAssembler(make_header(24, huge), max_blocks=64)


async def test_disagg_resharding_prefill_tp1_decode_tp2():
    """The headline transfer property: KV prefilled on a tp=1 engine must
    continue identically on a tp=2 decode engine (logical payload, GSPMD
    reshard on inject) — with the payload forced across many wire frames."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.engine.worker import JaxEngineWorker

    rt = await fresh_runtime().start()
    ecfg = dict(model_config=FP32, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, max_num_seqs=2,
                prefill_buckets=(8, 16, 32), seed=7)
    prefill_worker = await JaxEngineWorker(
        rt, EngineConfig(role="prefill", tp=1, transfer_chunk_bytes=2048,
                         **ecfg),
        component="prefill",
    ).start()
    decode_worker = await JaxEngineWorker(
        rt, EngineConfig(role="decode", tp=2, **ecfg), component="backend",
    ).start()
    agg = JaxEngine(EngineConfig(**ecfg))  # tp=1 reference

    prompt = list(range(30, 52))
    expect = []
    async for out in agg.generate(greedy_req(prompt, 6, "agg")):
        expect.extend(out.token_ids)

    pclient = await (rt.namespace("dynamo").component("prefill")
                     .endpoint("generate").client()).start()
    dclient = await (rt.namespace("dynamo").component("backend")
                     .endpoint("generate").client()).start()
    orch = PrefillOrchestrator(
        pclient, ConditionalDisaggConfig(always_remote=True))
    routed = await orch.maybe_prefill(greedy_req(prompt, 6, "reshard1"))
    assert routed.disaggregated_params is not None

    from dynamo_tpu.protocols import LLMEngineOutput

    tokens = []
    async for item in dclient.generate(routed.to_dict()):
        tokens.extend(LLMEngineOutput.from_dict(item).token_ids)
    assert tokens == expect, "tp-resharded continuation diverged"
    assert decode_worker.engine.metrics["prefill_tokens"] == 0

    await orch.close()
    await dclient.close()
    await agg.close()
    await prefill_worker.close()
    await decode_worker.close()
    await rt.shutdown()


async def test_jax_engine_disagg_transfer_roundtrip():
    """KV computed on engine A must continue identically on engine B."""
    await _engine_disagg_roundtrip(FP32)


async def test_mla_engine_disagg_transfer_roundtrip():
    """Same contract for the MLA family: the asymmetric latent/rope-key
    cache pair (different head dims) rides the same transfer protocol
    (KvLayout.head_dim_v)."""
    from dynamo_tpu.models.deepseek import DeepseekConfig

    mla = DeepseekConfig(
        name="mla-disagg", vocab_size=256, d_model=64, n_layers=2,
        n_heads=4, q_lora_rank=24, kv_lora_rank=32, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, ffn_dim=128,
        dtype=jnp.float32,
    )
    await _engine_disagg_roundtrip(mla)


async def _engine_disagg_roundtrip(model_config):
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.engine.worker import JaxEngineWorker

    rt = await fresh_runtime().start()
    ecfg = dict(model_config=model_config, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, max_num_seqs=2,
                prefill_buckets=(8, 16, 32), seed=7)
    prefill_worker = await JaxEngineWorker(
        rt, EngineConfig(role="prefill", **ecfg), component="prefill",
    ).start()
    decode_worker = await JaxEngineWorker(
        rt, EngineConfig(role="decode", **ecfg), component="backend",
    ).start()
    # reference: the same params on a single aggregated engine
    agg = JaxEngine(EngineConfig(**ecfg))

    prompt = list(range(30, 52))  # 22 tokens
    expect = []
    async for out in agg.generate(greedy_req(prompt, 6, "agg")):
        expect.extend(out.token_ids)

    # frontend-style orchestration against the two workers
    pclient = await (rt.namespace("dynamo").component("prefill")
                     .endpoint("generate").client()).start()
    dclient = await (rt.namespace("dynamo").component("backend")
                     .endpoint("generate").client()).start()
    orch = PrefillOrchestrator(
        pclient, ConditionalDisaggConfig(always_remote=True))
    req = greedy_req(prompt, 6, "disagg1")
    routed = await orch.maybe_prefill(req)
    assert routed.disaggregated_params is not None
    assert routed.disaggregated_params["first_token"] == expect[0]
    assert routed.disaggregated_params["prompt_len"] == len(prompt)

    tokens = []
    async for item in dclient.generate(routed.to_dict()):
        from dynamo_tpu.protocols import LLMEngineOutput

        out = LLMEngineOutput.from_dict(item)
        tokens.extend(out.token_ids)
    assert tokens == expect, "disagg continuation diverged from aggregated"
    # decode engine did zero prefill compute (transfer + 0 recompute)
    assert decode_worker.engine.metrics["prefill_tokens"] == 0
    # parked KV was released after the pull
    for _ in range(100):
        if not prefill_worker.engine._parked:
            break
        await asyncio.sleep(0.02)
    assert not prefill_worker.engine._parked

    await orch.close()
    await dclient.close()
    await agg.close()
    await prefill_worker.close()
    await decode_worker.close()
    await rt.shutdown()
