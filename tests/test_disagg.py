"""Disaggregated prefill/decode tests.

- conditional-disagg policy unit tests
- mocker-level disagg e2e (frontend orchestration, CPU-fast)
- JAX engine-to-engine KV transfer roundtrip: prefill on engine A, pull
  blocks over the request plane, inject into engine B, and check the decode
  continuation equals aggregated serving on a single engine (the strongest
  correctness property of the transfer path).
"""


import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks

import asyncio
import uuid

import jax.numpy as jnp

from dynamo_tpu.disagg.prefill_router import (
    ConditionalDisaggConfig,
    PrefillOrchestrator,
)
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.protocols import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

FP32 = LlamaConfig(name="tiny32", vocab_size=256, d_model=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
                   dtype=jnp.float32)


def fresh_runtime():
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


def greedy_req(tokens, n, rid):
    return PreprocessedRequest(
        token_ids=tokens, request_id=rid,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )


def test_conditional_disagg_policy():
    orch = PrefillOrchestrator.__new__(PrefillOrchestrator)
    orch.config = ConditionalDisaggConfig(min_effective_isl=100,
                                          min_effective_ratio=0.7)
    req = greedy_req(list(range(200)), 5, "r")
    assert orch.should_disagg(req, overlap_tokens=0)          # long, cold
    assert not orch.should_disagg(req, overlap_tokens=150)    # mostly cached
    short = greedy_req(list(range(50)), 5, "r2")
    assert not orch.should_disagg(short, overlap_tokens=0)    # too short
    orch.config = ConditionalDisaggConfig(always_remote=True)
    assert orch.should_disagg(short, overlap_tokens=50)


async def test_mocker_disagg_e2e():
    """Prefill mocker + decode mocker behind the frontend orchestration."""
    from dynamo_tpu.frontend import ModelManager, ModelWatcher
    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker

    rt = await fresh_runtime().start()
    common = dict(model_name="m", block_size=4, base_step_s=0.0005,
                  prefill_s_per_token=0.0, decode_s_per_seq=0.0)
    decode_w = await MockerWorker(
        rt, MockEngineArgs(role="decode", **common), component="backend"
    ).start()
    prefill_w = await MockerWorker(
        rt, MockEngineArgs(role="prefill", **common), component="prefill"
    ).start()

    manager = ModelManager()
    watcher = await ModelWatcher(
        rt, manager,
        disagg_config=ConditionalDisaggConfig(min_effective_isl=8,
                                              min_effective_ratio=0.0),
    ).start()
    for _ in range(100):
        p = manager.get("m")
        if p is not None and p.prefill is not None:
            break
        await asyncio.sleep(0.02)
    pipeline = manager.get("m")
    assert pipeline is not None and pipeline.prefill is not None

    req = greedy_req(list(range(40)), 5, "d1")
    deltas = [d async for d in pipeline.generate_deltas(req)]
    assert deltas[-1].finish_reason is not None
    assert sum(d.token_count for d in deltas) == 5
    # the prefill mocker actually served a hop (its engine saw the request)
    assert prefill_w.engine.metrics["prefill_tokens"] >= 40
    # decode mocker skipped prefill compute (remote_prefilled path)
    assert decode_w.engine.metrics["prefill_tokens"] == 0

    # short request bypasses remote prefill (conditional disagg)
    watcher2_cfg = pipeline.prefill.config
    watcher2_cfg.min_effective_isl = 1000
    p_before = prefill_w.engine.metrics["prefill_tokens"]
    req2 = greedy_req(list(range(12)), 3, "d2")
    deltas = [d async for d in pipeline.generate_deltas(req2)]
    assert sum(d.token_count for d in deltas) == 3
    assert prefill_w.engine.metrics["prefill_tokens"] == p_before

    await watcher.close()
    await prefill_w.close()
    await decode_w.close()
    await rt.shutdown()


def test_chunk_frame_protocol():
    """Chunk frames round-trip exactly; corrupt frames and incompatible
    layouts fail loudly; chunk sizing respects the byte bound."""
    import numpy as np
    import pytest

    from dynamo_tpu.disagg.transfer import (
        KvLayout, decode_chunk_frame, encode_chunk_frame, make_header,
    )

    rng = np.random.default_rng(3)
    k = rng.normal(size=(2, 6, 4, 2, 8)).astype(np.float32)
    v = rng.normal(size=(2, 6, 4, 2, 8)).astype(np.float32)
    layout = KvLayout.of(k, tp=1)

    # whole-payload roundtrip through bounded chunks
    per = layout.blocks_per_chunk(2 * layout.block_bytes())
    assert per == 2
    out_k = np.zeros_like(k)
    out_v = np.zeros_like(v)
    for b0 in range(0, 6, per):
        n = min(per, 6 - b0)
        frame = encode_chunk_frame(b0, k[:, b0:b0 + n], v[:, b0:b0 + n])
        fb0, fn, kb, vb = decode_chunk_frame(frame, layout)
        assert (fb0, fn) == (b0, n)
        out_k[:, fb0:fb0 + fn] = kb
        out_v[:, fb0:fb0 + fn] = vb
    np.testing.assert_array_equal(out_k, k)
    np.testing.assert_array_equal(out_v, v)

    # a single block never chunks to zero even under a tiny bound
    assert layout.blocks_per_chunk(1) == 1

    # out-of-bounds frames rejected (a corrupt sender must not scatter
    # outside the expected payload)
    bad = encode_chunk_frame(5, k[:, 5:6], v[:, 5:6])
    bad["block_count"] = 4
    with pytest.raises(ValueError, match="out of bounds"):
        decode_chunk_frame(bad, layout)

    # logical-geometry mismatch rejected; tp may differ freely
    other = KvLayout.of(k, tp=4)
    other.kv_heads = 8
    with pytest.raises(ValueError, match="kv_heads"):
        layout.check_compatible(other)
    layout.check_compatible(KvLayout.of(k, tp=4))

    # header carries the tier-2 capability advertisement
    assert "transfer_addr" not in make_header(8, layout)
    assert make_header(8, layout, "host:1")["transfer_addr"] == "host:1"


async def test_disagg_resharding_prefill_tp1_decode_tp2():
    """The headline transfer property: KV prefilled on a tp=1 engine must
    continue identically on a tp=2 decode engine (logical payload, GSPMD
    reshard on inject) — with the payload forced across many wire frames."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.engine.worker import JaxEngineWorker

    rt = await fresh_runtime().start()
    ecfg = dict(model_config=FP32, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, max_num_seqs=2,
                prefill_buckets=(8, 16, 32), seed=7)
    prefill_worker = await JaxEngineWorker(
        rt, EngineConfig(role="prefill", tp=1, transfer_chunk_bytes=2048,
                         **ecfg),
        component="prefill",
    ).start()
    decode_worker = await JaxEngineWorker(
        rt, EngineConfig(role="decode", tp=2, **ecfg), component="backend",
    ).start()
    agg = JaxEngine(EngineConfig(**ecfg))  # tp=1 reference

    prompt = list(range(30, 52))
    expect = []
    async for out in agg.generate(greedy_req(prompt, 6, "agg")):
        expect.extend(out.token_ids)

    pclient = await (rt.namespace("dynamo").component("prefill")
                     .endpoint("generate").client()).start()
    dclient = await (rt.namespace("dynamo").component("backend")
                     .endpoint("generate").client()).start()
    orch = PrefillOrchestrator(
        pclient, ConditionalDisaggConfig(always_remote=True))
    routed = await orch.maybe_prefill(greedy_req(prompt, 6, "reshard1"))
    assert routed.disaggregated_params is not None

    from dynamo_tpu.protocols import LLMEngineOutput

    tokens = []
    async for item in dclient.generate(routed.to_dict()):
        tokens.extend(LLMEngineOutput.from_dict(item).token_ids)
    assert tokens == expect, "tp-resharded continuation diverged"
    assert decode_worker.engine.metrics["prefill_tokens"] == 0

    await orch.close()
    await dclient.close()
    await agg.close()
    await prefill_worker.close()
    await decode_worker.close()
    await rt.shutdown()


async def test_jax_engine_disagg_transfer_roundtrip():
    """KV computed on engine A must continue identically on engine B."""
    await _engine_disagg_roundtrip(FP32)


async def test_mla_engine_disagg_transfer_roundtrip():
    """Same contract for the MLA family: the asymmetric latent/rope-key
    cache pair (different head dims) rides the same transfer protocol
    (KvLayout.head_dim_v)."""
    from dynamo_tpu.models.deepseek import DeepseekConfig

    mla = DeepseekConfig(
        name="mla-disagg", vocab_size=256, d_model=64, n_layers=2,
        n_heads=4, q_lora_rank=24, kv_lora_rank=32, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, ffn_dim=128,
        dtype=jnp.float32,
    )
    await _engine_disagg_roundtrip(mla)


async def _engine_disagg_roundtrip(model_config):
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.engine.worker import JaxEngineWorker

    rt = await fresh_runtime().start()
    ecfg = dict(model_config=model_config, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, max_num_seqs=2,
                prefill_buckets=(8, 16, 32), seed=7)
    prefill_worker = await JaxEngineWorker(
        rt, EngineConfig(role="prefill", **ecfg), component="prefill",
    ).start()
    decode_worker = await JaxEngineWorker(
        rt, EngineConfig(role="decode", **ecfg), component="backend",
    ).start()
    # reference: the same params on a single aggregated engine
    agg = JaxEngine(EngineConfig(**ecfg))

    prompt = list(range(30, 52))  # 22 tokens
    expect = []
    async for out in agg.generate(greedy_req(prompt, 6, "agg")):
        expect.extend(out.token_ids)

    # frontend-style orchestration against the two workers
    pclient = await (rt.namespace("dynamo").component("prefill")
                     .endpoint("generate").client()).start()
    dclient = await (rt.namespace("dynamo").component("backend")
                     .endpoint("generate").client()).start()
    orch = PrefillOrchestrator(
        pclient, ConditionalDisaggConfig(always_remote=True))
    req = greedy_req(prompt, 6, "disagg1")
    routed = await orch.maybe_prefill(req)
    assert routed.disaggregated_params is not None
    assert routed.disaggregated_params["first_token"] == expect[0]
    assert routed.disaggregated_params["prompt_len"] == len(prompt)

    tokens = []
    async for item in dclient.generate(routed.to_dict()):
        from dynamo_tpu.protocols import LLMEngineOutput

        out = LLMEngineOutput.from_dict(item)
        tokens.extend(out.token_ids)
    assert tokens == expect, "disagg continuation diverged from aggregated"
    # decode engine did zero prefill compute (transfer + 0 recompute)
    assert decode_worker.engine.metrics["prefill_tokens"] == 0
    # parked KV was released after the pull
    for _ in range(100):
        if not prefill_worker.engine._parked:
            break
        await asyncio.sleep(0.02)
    assert not prefill_worker.engine._parked

    await orch.close()
    await dclient.close()
    await agg.close()
    await prefill_worker.close()
    await decode_worker.close()
    await rt.shutdown()


# ------------------- transfer tiers + streaming behavior -------------------


async def _forced_tier_roundtrip(patch):
    """Run the engine-to-engine roundtrip with the broker (tier 1)
    disabled so the pull takes the patched-in network tier."""
    from dynamo_tpu.disagg import broker, device_transfer

    orig_lookup = broker.lookup_engine
    broker.lookup_engine = lambda _id: None
    try:
        with patch:
            await _engine_disagg_roundtrip(FP32)
    finally:
        broker.lookup_engine = orig_lookup


class _NoTransferServer:
    """Context: force get_transfer_server() to 'unavailable'."""

    def __enter__(self):
        from dynamo_tpu.disagg import device_transfer

        self._orig = device_transfer.get_transfer_server
        device_transfer.get_transfer_server = lambda: None

    def __exit__(self, *exc):
        from dynamo_tpu.disagg import device_transfer

        device_transfer.get_transfer_server = self._orig


class _Nop:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


async def test_disagg_roundtrip_host_staged_tier():
    """Tier 3 forced: no broker, no transfer server — byte frames over
    the request plane must still reproduce the aggregated continuation."""
    await _forced_tier_roundtrip(_NoTransferServer())


async def test_disagg_roundtrip_transfer_server_tier():
    """Tier 2: payload through the jax transfer server (device-to-device
    across processes; loopback here).  Skips where the backend lacks
    transfer-server support."""
    import os

    import pytest

    from dynamo_tpu.disagg import device_transfer

    os.environ["DYN_KV_TRANSFER_SERVER"] = "1"  # opt-in (see get_transfer_server)
    try:
        if device_transfer.get_transfer_server() is None:
            pytest.skip("jax transfer server unavailable on this backend")
        await _forced_tier_roundtrip(_Nop())
    finally:
        os.environ.pop("DYN_KV_TRANSFER_SERVER", None)


async def test_streaming_pull_overlaps_decode_and_bounds_host_memory():
    """The round-3 review findings: a pull must not stall decode for the
    whole prompt, and must never stage the whole payload in host RAM.
    A deliberately slow multi-chunk pull streams into engine B while B
    decodes another request; B keeps emitting tokens DURING the pull,
    and the recorded peak host chunk stays one chunk, not the payload."""
    import time as _time

    import numpy as np

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.protocols.llm import DISAGG_ANNOTATION

    ecfg = dict(model_config=FP32, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, max_num_seqs=2,
                prefill_buckets=(8, 16, 32), seed=7)
    src = JaxEngine(EngineConfig(role="prefill", **ecfg))
    dst = JaxEngine(EngineConfig(**ecfg))
    agg = JaxEngine(EngineConfig(**ecfg))

    prompt = list(range(30, 52))  # 22 tokens -> 6 blocks
    expect = []
    async for out in agg.generate(greedy_req(prompt, 4, "agg")):
        expect.extend(out.token_ids)

    # park a prefill on src
    pref = greedy_req(prompt, 4, "d1")
    pref.annotations = [DISAGG_ANNOTATION]
    park_out = None
    async for out in src.generate(pref):
        park_out = out
    params = park_out.kv_transfer_params
    assert params is not None and params["first_token"] == expect[0]

    # slow host-staged source: one block per chunk, 30ms apart
    class SlowHostSource:
        def __init__(self, engine, rid):
            self.engine, self.rid = engine, rid

        async def open(self):
            from dynamo_tpu.disagg.transfer import make_header

            n_blocks, plen = await self.engine.parked_info(self.rid)
            return make_header(plen, self.engine.kv_wire_layout(n_blocks))

        async def chunk(self, b0, n):
            await asyncio.sleep(0.03)
            return await self.engine.extract_parked_chunk(self.rid, b0, n)

        async def close(self):
            await self.engine.release_parked(self.rid)

    async def pull_fn(dp):
        return SlowHostSource(src, dp["request_id"])

    dst.kv_pull_fn = pull_fn
    # one block per chunk
    dst.config.transfer_chunk_bytes = 1

    # background decode on dst, tokens timestamped
    bg_times = []

    async def run_bg():
        async for out in dst.generate(
                greedy_req(list(range(8)), 60, "bg")):
            bg_times.append(_time.monotonic())

    bg = asyncio.create_task(run_bg())
    while not bg_times:  # bg is decoding before the pull starts
        await asyncio.sleep(0.005)

    t_start = _time.monotonic()
    dis = greedy_req(prompt, 4, "d1")
    dis.disaggregated_params = params
    tokens = []
    t_first = None
    async for out in dst.generate(dis):
        if t_first is None and out.token_ids:
            t_first = _time.monotonic()
        tokens.extend(out.token_ids)
    await bg

    assert tokens == expect, "streamed-pull continuation diverged"
    # decode engine never prefilled the disagg prompt
    assert dst.metrics["prefill_tokens"] <= 8  # only bg's own prompt
    # ITL overlap: bg emitted tokens while the pull was in flight
    during = [t for t in bg_times if t_start < t < t_first]
    assert len(during) >= 3, (
        f"decode stalled during pull: {len(during)} tokens in "
        f"{t_first - t_start:.3f}s pull window")
    # host memory bound: peak staged chunk = one block, not the payload
    lo = dst.kv_wire_layout(0)
    assert dst.metrics["pull_host_chunk_bytes_max"] <= lo.block_bytes()
    assert dst.metrics["pull_blocks"] == 6

    await src.close()
    await dst.close()
    await agg.close()


async def test_stream_pull_external_cancel_propagates(caplog):
    """ADVICE r5 regression: an external cancellation of the pull task
    (the generate teardown's pull_task.cancel()) delivered while
    _stream_pull awaits its in-flight prefetch must PROPAGATE — the
    cleanup suppresses only the prefetch future's own cancellation.
    The old `except (CancelledError, Exception): pass` let the
    metrics/fallback tail keep running after cancel, racing teardown:
    observable as the local-prefill-fallback path firing for a request
    the client already abandoned."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.protocols.llm import DISAGG_ANNOTATION

    ecfg = dict(model_config=FP32, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, max_num_seqs=2,
                prefill_buckets=(8, 16, 32), seed=7)
    src = JaxEngine(EngineConfig(role="prefill", **ecfg))
    dst = JaxEngine(EngineConfig(**ecfg))

    prompt = list(range(30, 52))  # 22 tokens -> 6 blocks
    pref = greedy_req(prompt, 4, "c1")
    pref.annotations = [DISAGG_ANNOTATION]
    park_out = None
    async for out in src.generate(pref):
        park_out = out
    params = park_out.kv_transfer_params

    chunk_started = asyncio.Event()

    class HangingSource:
        async def open(self):
            from dynamo_tpu.disagg.transfer import make_header

            n_blocks, plen = await src.parked_info("c1")
            return make_header(plen, src.kv_wire_layout(n_blocks))

        async def chunk(self, b0, n):
            chunk_started.set()
            await asyncio.Event().wait()  # hangs until cancelled

        async def close(self):
            pass

    async def pull_fn(dp):
        return HangingSource()

    dst.kv_pull_fn = pull_fn
    dst.config.transfer_chunk_bytes = 1  # multi-chunk spans

    async def consume():
        dis = greedy_req(prompt, 4, "c1")
        dis.disaggregated_params = params
        async for _ in dst.generate(dis):
            pass

    consumer = asyncio.create_task(consume())
    await asyncio.wait_for(chunk_started.wait(), 20.0)
    await asyncio.sleep(0.05)  # the pull parks on the hanging prefetch
    consumer.cancel()
    with pytest.raises(asyncio.CancelledError):
        await consumer
    # let the scheduler reap the cancelled slot and settle
    for _ in range(100):
        if dst.allocator.num_free == dst.config.num_blocks - 1:
            break
        await asyncio.sleep(0.02)
    # the cancelled pull never ran its failure/fallback tail
    assert "local prefill fallback" not in caplog.text
    assert "pull_blocks" not in dst.metrics
    # every block the cancelled request held was released — and the
    # ledger's auditor agrees the books reconcile
    assert dst.allocator.num_free == dst.config.num_blocks - 1
    if dst.kv_ledger is not None:
        report = await dst.audit_kv()
        assert report["clean"], report

    await src.close()
    await dst.close()
