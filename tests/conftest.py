"""Test config: force JAX onto a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): everything below the
hardware layer is testable with no accelerator.  Multi-chip sharding tests run
against 8 virtual CPU devices; real-TPU paths are exercised by bench.py and
the driver's dryrun instead.

Must run before jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# tests write throwaway checkpoints under tmp paths; populating the global
# tmpfs weight cache for them would grow /dev/shm forever (explicit cache
# tests point DYN_WEIGHT_CACHE_DIR at a tmp dir instead)
os.environ.setdefault("DYN_WEIGHT_CACHE", "0")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# this image's axon TPU plugin prepends itself to jax_platforms regardless of
# JAX_PLATFORMS; force the CPU backend explicitly for tests
import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# build the native library from source if absent (it is not committed);
# make_indexer falls back to pure Python when the toolchain is unavailable
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_native_so = os.path.join(_repo_root, "native", "libdynamo_native.so")
if not os.path.exists(_native_so):
    import subprocess

    try:
        subprocess.run(["make", "-C", os.path.join(_repo_root, "native")],
                       capture_output=True)
    except OSError:
        pass  # no toolchain: tests run on the pure-Python indexer

import asyncio
import inspect

import pytest


def pytest_pyfunc_call(pyfuncitem):
    """Minimal async-test support (pytest-asyncio is not in the image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=120))
        return True
    return None
