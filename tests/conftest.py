"""Test config: force JAX onto a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): everything below the
hardware layer is testable with no accelerator.  Multi-chip sharding tests run
against 8 virtual CPU devices; real-TPU paths are exercised by bench.py and
the driver's dryrun instead.

Must run before jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# tests write throwaway checkpoints under tmp paths; populating the global
# tmpfs weight cache for them would grow /dev/shm forever (explicit cache
# tests point DYN_WEIGHT_CACHE_DIR at a tmp dir instead)
os.environ.setdefault("DYN_WEIGHT_CACHE", "0")
# NOTE: do NOT enable JAX's persistent compilation cache here.  On this
# image (jaxlib 0.4.36 CPU, 8 virtual devices, donated-buffer engine
# programs) deserializing cached executables corrupts the heap: a warm
# cache makes the suite fail nondeterministically — wrong KV bytes in the
# multihost bit-identity tests on a good day, a segfault inside gc on a
# bad one.  Reproducer: run tests/test_engine.py tests/test_kvbm.py
# tests/test_multihost.py twice with JAX_COMPILATION_CACHE_DIR pointed at
# the same dir — cold passes, warm crashes.  The suite's wall clock is
# kept inside its envelope by compiling at -O0 instead (below).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
# tier-1 runs tiny models where XLA optimization buys nothing but compile
# time (~1/3 of suite wall clock); correctness assertions (greedy token
# equality, leader/follower bit-identity) compare within-run outputs, so
# the pass-pipeline level does not affect them
if "xla_backend_optimization_level" not in flags:
    flags = (flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = flags

# this image's axon TPU plugin prepends itself to jax_platforms regardless of
# JAX_PLATFORMS; force the CPU backend explicitly for tests
import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# build the native library from source if absent (it is not committed):
# the native indexer is the promoted DEFAULT when built, so tier-1 must
# exercise it whenever a toolchain exists.  No toolchain degrades
# gracefully to the pure-Python indexer (tests/test_native_build.py
# skips its native half); a PRESENT toolchain whose build fails is
# surfaced loudly instead of silently testing the fallback forever.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_native_so = os.path.join(_repo_root, "native", "libdynamo_native.so")
if not os.path.exists(_native_so):
    import shutil
    import subprocess

    if shutil.which("make") and (shutil.which("c++") or
                                 shutil.which("g++") or
                                 shutil.which("clang++")):
        try:
            _build = subprocess.run(
                ["make", "-C", os.path.join(_repo_root, "native")],
                capture_output=True, text=True, timeout=120)
            if _build.returncode != 0:
                sys.stderr.write(
                    "conftest: native indexer build FAILED (tests fall "
                    "back to the pure-Python indexer):\n"
                    + _build.stdout[-1000:] + _build.stderr[-1000:]
                    + "\n")
        except (OSError, subprocess.TimeoutExpired) as e:
            sys.stderr.write(f"conftest: native indexer build errored: "
                             f"{e}\n")
    # else: no toolchain — pure-Python indexer serves tier-1

import asyncio
import gc
import inspect
import logging
import warnings

import pytest

# Runtime twin of the DYN004 lint (dynamo_tpu/lint): asyncio debug mode
# times every callback, and any callback holding the event loop longer
# than this fails the test with the offending callback named (the lint
# catches time.sleep/open()/.result() lexically; this catches the
# blocking work static analysis can't see — a jit compile or device
# fetch that snuck onto the loop instead of asyncio.to_thread).  Debug
# mode's expensive half is the source-traceback capture on every
# Task/Handle creation — stubbed to empty below so the suite keeps its
# wall-clock envelope while the slow-callback timer stays armed.
# The design bound is 200ms; tier-1 arms at 500ms because this box has
# ONE shared CPU core — under full-suite load, innocent 0.25-0.45s
# scheduler-noise slices cross 200ms nondeterministically (measured:
# different tests each run), while the bug class this exists for (sync
# sleeps, mid-serving compiles, device fetches on the loop) blocks for
# ≥0.5s when real.  Tune with DYN_TEST_SLOW_CB_S.
SLOW_CALLBACK_S = float(os.environ.get("DYN_TEST_SLOW_CB_S", "0.5"))
asyncio.format_helpers.extract_stack = lambda *a, **k: []  # type: ignore


class _SlowCallbackCapture(logging.Handler):
    """Collects asyncio's 'Executing <Handle ...> took N seconds'
    warnings for the duration of one test — but only when the named
    culprit is THIS repo's code holding the loop.  A warning whose
    running-at frame is stdlib (e.g. selector_events.py accepting a
    connection) is a major-GC pause or scheduler stall attributed to
    whatever callback it interrupted: real to the wall clock, but not
    actionable by the test under judgment (observed: a 1.1s gen-2
    collection of the JAX heap billed to _accept_connection2)."""

    def __init__(self):
        super().__init__(level=logging.WARNING)
        self.slow: list = []
        self._repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))

    def emit(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        if "took" in msg and "Executing" in msg and self._repo in msg:
            self.slow.append(msg)


@pytest.fixture(autouse=True, scope="module")
def _freeze_longlived_heap():
    """Move each module's surviving heap out of the cyclic collector.

    The suite's long-lived object graph (jit caches, compiled
    executables, module state) grows to millions of objects; a gen-2
    collection over it takes 1-2s on this box and lands wherever the
    allocator happens to trip threshold2 — including mid-event-loop,
    where the slow-callback gate above bills the pause to whichever
    innocent repo-code callback it interrupted (the PR 10-documented
    once-per-full-run flake: a different async test each time).  At
    every module boundary we collect once OUTSIDE any event loop (the
    previous module's cyclic garbage goes here, where a pause judges
    nothing) and FREEZE the survivors into the permanent generation, so
    later collections scan only the current module's young objects —
    mid-test gen-2 pauses stay small, and each boundary collect stays
    cheap because everything older is already frozen.  Refcounting
    still frees frozen objects; only cycle detection skips them, and
    anything cyclic-dead was collected the moment before its freeze.

    Caveat: a cycle formed LATER through a frozen object (a frozen
    registry mutated by a subsequent module's test) is never
    collectable for the rest of the run — acceptable because tests
    build their own fixtures rather than mutating other modules'
    state, and full-suite RSS held steady across the validation runs;
    if suite RSS ever creeps, add a periodic gc.unfreeze()+collect
    here instead of removing the fixture."""
    gc.collect()
    gc.freeze()
    yield


def pytest_pyfunc_call(pyfuncitem):
    """Minimal async-test support (pytest-asyncio is not in the image),
    plus tier-1-wide leak detection: a test that exits with pending
    asyncio tasks (something it started and never cancelled/awaited) or
    that leaves never-awaited coroutines behind FAILS.  Leaked tasks are
    how wedged-worker bugs hide — a canary loop or pull task that
    outlives its test would be silently destroyed with the loop.

    Tasks the test's own teardown already cancelled are given a few loop
    cycles to retire before the check, so `task.cancel()` without an
    await (the common close() idiom) does not false-positive.  A test
    that legitimately abandons tasks can opt out with
    `@pytest.mark.allow_task_leaks`."""
    fn = pyfuncitem.obj
    if not inspect.iscoroutinefunction(fn):
        return None
    kwargs = {
        name: pyfuncitem.funcargs[name]
        for name in pyfuncitem._fixtureinfo.argnames
    }
    leaked: list = []
    slow_capture = _SlowCallbackCapture()
    # the opt-out disables debug mode itself, not just the verdict:
    # debug's per-callback timing is real overhead, and the tests that
    # opt out (real-JAX-engine bodies, timing-sensitive SLO assertions)
    # are exactly the ones that overhead distorts
    gate_on = pyfuncitem.get_closest_marker("allow_slow_callbacks") is None

    async def runner():
        me = asyncio.current_task()
        loop = asyncio.get_running_loop()
        if gate_on:
            # arm the slow-callback watchdog: debug mode is what makes
            # the event loop time its callbacks at all (extract_stack
            # stubbed above keeps it cheap)
            loop.set_debug(True)
            loop.slow_callback_duration = SLOW_CALLBACK_S
            logging.getLogger("asyncio").addHandler(slow_capture)
        try:
            await asyncio.wait_for(fn(**kwargs), timeout=120)
        finally:
            # let tasks cancelled-but-not-reaped by the test's teardown
            # retire before judging what is genuinely leaked; a short
            # real-time grace covers teardown paths that need wall clock
            # (aiohttp connection handlers after server cleanup, nested
            # cancellation chains)
            import time as _time

            deadline = _time.monotonic() + 0.75
            while _time.monotonic() < deadline:
                await asyncio.sleep(0)
                if all(t.done() for t in asyncio.all_tasks()
                       if t is not me):
                    break
                await asyncio.sleep(0.02)
            pending = [t for t in asyncio.all_tasks()
                       if t is not me and not t.done()]
            leaked.extend(pending)
            for t in pending:
                t.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
            logging.getLogger("asyncio").removeHandler(slow_capture)

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        asyncio.run(runner())
        # never-awaited coroutines surface their RuntimeWarning when the
        # object dies: refcounting catches the common case the moment the
        # test's frames unwind, a young-generation pass catches the
        # cycle-trapped rest.  (A FULL gc.collect() here would walk the
        # whole JAX heap after every async test — tens of ms each, minutes
        # across the suite.)
        gc.collect(1)
    if leaked and not pyfuncitem.get_closest_marker("allow_task_leaks"):
        pytest.fail(
            "test leaked pending asyncio tasks (start it, own it): "
            + ", ".join(repr(t) for t in leaked[:8]), pytrace=False)
    never_awaited = [w for w in caught
                     if "was never awaited" in str(w.message)]
    if never_awaited:
        pytest.fail(
            "test left never-awaited coroutines: "
            + ", ".join(str(w.message) for w in never_awaited[:8]),
            pytrace=False)
    if slow_capture.slow and gate_on:
        pytest.fail(
            f"test blocked the event loop > {SLOW_CALLBACK_S:.1f}s "
            "(every concurrent stream stalls behind a blocking "
            "callback; move the work to asyncio.to_thread, or opt out "
            "with @pytest.mark.allow_slow_callbacks): "
            + "; ".join(slow_capture.slow[:4]), pytrace=False)
    return True
