"""Multi-host SPMD serving: leadership gating + step-stream replay.

Two single-host engines stand in for the two host-shards of one slice:
the protocol layer (ordering, gating, replay fidelity) is what is testable
without multi-host hardware, and the assertion is strong — after serving a
request on the leader, the follower's KV cache must be bit-identical,
because it replayed the exact jit sequence on identical state."""

import asyncio
import uuid

import jax.numpy as jnp
import numpy as np
import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks


from dynamo_tpu.engine import EngineConfig
from dynamo_tpu.engine.worker import JaxEngineWorker
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.parallel.multihost import (
    MultihostContext,
    StepBroadcaster,
    StepFollower,
    StepGapError,
)
from dynamo_tpu.protocols import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

FP32 = LlamaConfig(name="tiny32", vocab_size=256, d_model=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
                   dtype=jnp.float32)


def fresh_runtime():
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


def test_context_detect_env(monkeypatch):
    monkeypatch.setenv("DYN_MH_RANK", "2")
    monkeypatch.setenv("DYN_MH_WORLD", "4")
    ctx = MultihostContext.detect()
    assert ctx.rank == 2 and ctx.world == 4 and not ctx.is_leader
    monkeypatch.setenv("DYN_MH_RANK", "0")
    assert MultihostContext.detect().is_leader


async def test_step_stream_ordered_and_gap_fatal():
    rt = await fresh_runtime().start()
    bc = await StepBroadcaster(rt, "ns", "c", 0).start()
    fo = StepFollower(rt, "ns", "c", 0)

    got = []

    async def consume():
        try:
            async for kind, arrays, meta in fo.steps():
                got.append((kind, arrays, meta))
        except StepGapError:
            got.append("GAP")

    task = asyncio.create_task(consume())
    await asyncio.sleep(0.05)
    bc.publish_step("a", {"x": np.arange(4, dtype=np.int32)}, {"n": 1})
    bc.publish_step("b", {"y": np.ones((2, 2), np.float32)})
    for _ in range(100):
        await asyncio.sleep(0.01)
        if len(got) >= 2:
            break
    assert [g[0] for g in got] == ["a", "b"]
    np.testing.assert_array_equal(got[0][1]["x"],
                                  np.arange(4, dtype=np.int32))
    assert got[0][2] == {"n": 1}
    assert got[1][1]["y"].dtype == np.float32

    # a gap (simulated lost frame) must be fatal, not silently skipped
    bc._seq += 1  # drop one sequence number
    bc.publish_step("c", {})
    for _ in range(100):
        await asyncio.sleep(0.01)
        if "GAP" in got:
            break
    assert got[-1] == "GAP"
    fo.stop()
    task.cancel()
    await bc.close()
    await rt.shutdown()


async def test_follower_kv_matches_leader_after_serving():
    """Leader serves a request; the follower replays the broadcast step
    stream and ends with a bit-identical KV cache."""
    rt = await fresh_runtime().start()
    ecfg = dict(model_config=FP32, block_size=4, num_blocks=32,
                max_blocks_per_seq=8, max_num_seqs=2,
                prefill_buckets=(8, 16), seed=5)

    follower = await JaxEngineWorker(
        rt, EngineConfig(**ecfg), mh=MultihostContext(rank=1, world=2),
    ).start()
    leader = await JaxEngineWorker(
        rt, EngineConfig(**ecfg), mh=MultihostContext(rank=0, world=2),
    ).start()
    # follower exposes no routing identity; leader does
    assert follower.served is None
    assert leader.served is not None

    req = PreprocessedRequest(
        token_ids=list(range(3, 17)), request_id="mh1",
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=6, ignore_eos=True),
    )
    toks = []
    async for out in leader.engine.generate(req):
        toks.extend(out.token_ids)
    assert len(toks) == 6

    # wait for the follower to drain the stream, then compare caches
    for _ in range(200):
        await asyncio.sleep(0.02)
        if np.array_equal(np.asarray(leader.engine.kv[0]),
                          np.asarray(follower.engine.kv[0])):
            break
    np.testing.assert_array_equal(np.asarray(leader.engine.kv[0]),
                                  np.asarray(follower.engine.kv[0]))
    np.testing.assert_array_equal(np.asarray(leader.engine.kv[1]),
                                  np.asarray(follower.engine.kv[1]))

    await leader.close()
    await follower.close()
    await rt.shutdown()


from test_engine import collect, greedy_req  # noqa: E402 (shared helpers)


async def _wait_kv_equal(leader, follower, rounds=300):
    for _ in range(rounds):
        await asyncio.sleep(0.02)
        if np.array_equal(np.asarray(leader.engine.kv[0]),
                          np.asarray(follower.engine.kv[0])):
            break
    np.testing.assert_array_equal(np.asarray(leader.engine.kv[0]),
                                  np.asarray(follower.engine.kv[0]))
    np.testing.assert_array_equal(np.asarray(leader.engine.kv[1]),
                                  np.asarray(follower.engine.kv[1]))


async def test_follower_replays_kvbm_offload_onboard():
    """KVBM tiers compose with multi-host: gathers (offload) and injects
    (onboard) ride the step stream, so a follower's KV stays bit-identical
    through an offload → evict → onboard cycle on the leader."""
    rt = await fresh_runtime().start()
    ecfg = dict(model_config=FP32, block_size=4, num_blocks=16,
                max_blocks_per_seq=8, max_num_seqs=2,
                prefill_buckets=(8, 16, 32), seed=5,
                host_cache_blocks=64, offload_watermark_blocks=16)

    follower = await JaxEngineWorker(
        rt, EngineConfig(**ecfg), mh=MultihostContext(rank=1, world=2),
    ).start()
    leader = await JaxEngineWorker(
        rt, EngineConfig(**ecfg), mh=MultihostContext(rank=0, world=2),
    ).start()
    assert follower.engine.kvbm is None  # tiers live on the leader only
    assert leader.engine.kvbm is not None

    prompt_a = list(range(1, 13))  # 3 full blocks
    out1 = await collect(leader.engine, greedy_req(prompt_a, 4, "a1"))
    # churn HBM so A's blocks offload to G2 and get evicted
    for i in range(6):
        p = [50 + 7 * i + j for j in range(12)]
        await collect(leader.engine, greedy_req(p, 2, f"churn{i}"))
    assert leader.engine.kvbm.stats["offloaded"] > 0
    out2 = await collect(leader.engine, greedy_req(prompt_a, 4, "a2"))
    assert out2 == out1
    assert leader.engine.metrics.get("onboarded_tokens", 0) > 0, \
        "workload failed to exercise the onboard (inject) path"

    await _wait_kv_equal(leader, follower)
    await leader.close()
    await follower.close()
    await rt.shutdown()


async def test_multihost_disagg_north_star():
    """The north-star composition (round-2 verdict missing #1): a prefill
    slice and a decode slice, each world=2, KVBM enabled on the decode
    leader — request flows prefill leader → parked KV → decode leader pull
    → inject broadcast, and BOTH followers end bit-identical to their
    leaders with tokens equal to an aggregated reference."""
    from dynamo_tpu.disagg.prefill_router import (
        ConditionalDisaggConfig,
        PrefillOrchestrator,
    )
    from dynamo_tpu.engine.core import JaxEngine
    from dynamo_tpu.protocols import LLMEngineOutput

    rt = await fresh_runtime().start()
    ecfg = dict(model_config=FP32, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, max_num_seqs=2,
                prefill_buckets=(8, 16, 32), seed=7)

    p_follower = await JaxEngineWorker(
        rt, EngineConfig(role="prefill", **ecfg), component="prefill",
        mh=MultihostContext(rank=1, world=2),
    ).start()
    p_leader = await JaxEngineWorker(
        rt, EngineConfig(role="prefill", **ecfg), component="prefill",
        mh=MultihostContext(rank=0, world=2),
    ).start()
    d_follower = await JaxEngineWorker(
        rt, EngineConfig(role="decode", host_cache_blocks=32, **ecfg),
        component="backend", mh=MultihostContext(rank=1, world=2),
    ).start()
    d_leader = await JaxEngineWorker(
        rt, EngineConfig(role="decode", host_cache_blocks=32, **ecfg),
        component="backend", mh=MultihostContext(rank=0, world=2),
    ).start()

    agg = JaxEngine(EngineConfig(**ecfg))  # aggregated reference
    prompt = list(range(30, 52))
    expect = await collect(agg, greedy_req(prompt, 6, "agg"))

    pclient = await (rt.namespace("dynamo").component("prefill")
                     .endpoint("generate").client()).start()
    dclient = await (rt.namespace("dynamo").component("backend")
                     .endpoint("generate").client()).start()
    orch = PrefillOrchestrator(
        pclient, ConditionalDisaggConfig(always_remote=True))
    routed = await orch.maybe_prefill(greedy_req(prompt, 6, "ns1"))
    assert routed.disaggregated_params is not None

    tokens = []
    async for item in dclient.generate(routed.to_dict()):
        tokens.extend(LLMEngineOutput.from_dict(item).token_ids)
    assert tokens == expect, "multihost disagg continuation diverged"
    assert d_leader.engine.metrics["prefill_tokens"] == 0

    await _wait_kv_equal(p_leader, p_follower)
    await _wait_kv_equal(d_leader, d_follower)

    await orch.close()
    await dclient.close()
    await agg.close()
    for w in (p_leader, p_follower, d_leader, d_follower):
        await w.close()
    await rt.shutdown()


async def test_multihost_lora_and_embed_compose(tmp_path):
    """Round-3 composition holes closed: an adapter request's bank write
    and an embed dispatch both ride the step stream, so a world-2 slice
    serves them with the follower's adapter bank AND KV bit-identical to
    the leader's (a one-sided bank would compile a different program and
    desynchronize the collective schedule)."""
    from test_lora import write_peft_adapter

    rt = await fresh_runtime().start()
    write_peft_adapter(str(tmp_path), "style-a", FP32, rank=2, alpha=2,
                       seed=11)
    ecfg = dict(model_config=FP32, block_size=4, num_blocks=32,
                max_blocks_per_seq=8, max_num_seqs=2,
                prefill_buckets=(8, 16), seed=5,
                lora_max_adapters=2, lora_rank=4, lora_dir=str(tmp_path))

    follower = await JaxEngineWorker(
        rt, EngineConfig(**ecfg), mh=MultihostContext(rank=1, world=2),
    ).start()
    leader = await JaxEngineWorker(
        rt, EngineConfig(**ecfg), mh=MultihostContext(rank=0, world=2),
    ).start()

    # adapter request: triggers a lazy bank load on the leader, whose
    # write must reach the follower before its prefill replay needs it
    req = PreprocessedRequest(
        token_ids=list(range(3, 17)), request_id="mh-lora",
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=5, ignore_eos=True),
        lora_name="style-a",
    )
    toks = []
    async for out in leader.engine.generate(req):
        toks.extend(out.token_ids)
    assert len(toks) == 5

    await _wait_kv_equal(leader, follower)
    for key in leader.engine.lora_bank:
        np.testing.assert_array_equal(
            np.asarray(leader.engine.lora_bank[key]),
            np.asarray(follower.engine.lora_bank[key]),
            err_msg=f"adapter bank diverged at {key}")

    # embed dispatch broadcasts (the follower executes the same program;
    # a leader-only dispatch would hang a real collective slice) and the
    # leader's pooled vector equals a single-engine oracle's
    vec = await leader.engine.embed(list(range(5, 15)))
    from dynamo_tpu.engine import JaxEngine

    oracle = JaxEngine(EngineConfig(**{k: v for k, v in ecfg.items()
                                       if not k.startswith("lora")}))
    ovec = await oracle.embed(list(range(5, 15)))
    await oracle.close()
    np.testing.assert_allclose(vec, ovec, rtol=1e-5, atol=1e-5)

    await leader.close()
    await follower.close()
    await rt.shutdown()
