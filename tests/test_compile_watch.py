"""Compile watchdog + XLA cost-analysis roofline (obs/compile_watch.py):
per-family compile observations on the real JAX engine, cost-analysis
MFU agreement with the hand-counted estimate, mid-serving flight dumps,
worker gauge export, mocker parity, and the planner's recompile-storm
diag."""

import asyncio
import os
import time
import uuid

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks


from dynamo_tpu import obs
from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.obs.compile_watch import (
    COMPILE_KIND,
    CompileWatch,
    observe_compile_records,
)
from dynamo_tpu.planner.metrics import FpmWindow
from dynamo_tpu.protocols import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

TINY = LlamaConfig(name="tiny32", vocab_size=256, d_model=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
                   dtype=jnp.float32)


def make_engine(**kw):
    defaults = dict(model_config=TINY, block_size=4, num_blocks=256,
                    max_blocks_per_seq=32, max_num_seqs=4,
                    peak_tflops=100.0, peak_hbm_gbps=100.0,
                    prefill_buckets=(8, 16, 32, 64), seed=7)
    defaults.update(kw)
    return JaxEngine(EngineConfig(**defaults))


async def serve_one(eng, i, n_prompt=32, max_tokens=4):
    req = PreprocessedRequest(
        token_ids=[(i * 37 + j) % 200 + 3 for j in range(n_prompt)],
        request_id=f"r{i}",
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True))
    toks = []
    async for out in eng.generate(req):
        toks.extend(out.token_ids)
    return toks


# --------------------- WatchedProgram unit ---------------------------------


def test_watched_program_counts_and_costs_shapes():
    watch = CompileWatch()
    wp = watch.wrap(jax.jit(lambda x: jnp.tanh(x) @ x.T), "toy",
                    tokens_of=lambda a: a[0].shape[0])
    wp(np.ones((8, 8), np.float32))
    assert watch.counts == {"toy": 1}
    assert wp.cost(8) is not None and wp.cost(8)["flops"] > 0
    wp(np.ones((8, 8), np.float32))  # steady state: no new compile
    assert watch.counts == {"toy": 1}
    wp(np.ones((16, 16), np.float32))  # new shape: a second executable
    assert watch.counts == {"toy": 2}
    assert wp.cost(16) is not None
    assert wp.cost(16)["flops"] > wp.cost(8)["flops"]
    # None passes through untouched (config-gated program families)
    assert watch.wrap(None, "absent") is None


def test_watch_sink_and_serving_flag():
    recs = []
    serving = {"on": False}
    watch = CompileWatch(sink=recs.append, serving=lambda: serving["on"])
    wp = watch.wrap(jax.jit(lambda x: x * 2), "toy")
    wp(np.ones((4,), np.float32))
    serving["on"] = True
    wp(np.ones((8,), np.float32))
    assert [r["serving"] for r in recs] == [False, True]
    assert all(r["kind"] == COMPILE_KIND and r["seconds"] >= 0.0
               for r in recs)
    assert watch.serving_compiles == 1


# --------------------- JAX engine end-to-end --------------------------------


async def test_engine_compile_observation_per_program_family(tmp_path):
    """Serving one request must leave >=1 compile observation for every
    program family it dispatched (packed prefill + fused decode), each
    carrying cost-analysis flops/bytes, a compile span on the engine
    track, and — having landed mid-serving with no warmup — a flight
    dump."""
    tr = obs.Tracer(out_path=str(tmp_path / "t.json")).install()
    try:
        eng = make_engine()
        toks = await serve_one(eng, 0)
        assert len(toks) == 4
        counts = eng.compile_watch.counts
        assert counts.get("prefill_packed", 0) >= 1, counts
        assert (counts.get("decode_multi", 0) >= 1
                or counts.get("decode", 0) >= 1), counts
        comp = [r for r in eng.fpm if r.get("kind") == COMPILE_KIND]
        families = {r["family"] for r in comp}
        assert {"prefill_packed"} <= families
        for r in comp:
            if r["seconds"] > 0.01:  # a real XLA compile, not a cache fork
                assert r.get("flops", 0) > 0 and r.get("bytes", 0) > 0
        # compile spans landed on the engine's logical track
        spans = [s for s in tr.spans if s[0] == COMPILE_KIND]
        assert spans and all(s[3].startswith("sched:") for s in spans)
        # mid-serving (no warmup, request in flight) => flight recorder
        assert any("compile-" in p for p in tr.flight_dumps)
        await eng.close()
    finally:
        tr.uninstall()


async def test_warmup_compiles_are_not_serving(tmp_path):
    """warmup_decode's compiles happen with no active sequences: they
    must be counted but NOT flagged mid-serving (no flight dump)."""
    tr = obs.Tracer(out_path=str(tmp_path / "t.json")).install()
    try:
        eng = make_engine()
        eng.warmup_decode()
        comp = [r for r in eng.fpm if r.get("kind") == COMPILE_KIND]
        assert comp, "warmup compiled nothing?"
        assert all(not r["serving"] for r in comp)
        assert not any("compile-" in p for p in tr.flight_dumps)
        await eng.close()
    finally:
        tr.uninstall()


async def test_prefill_cost_analysis_agrees_with_hand_count(tmp_path):
    """The acceptance bar: cost-analysis MFU for packed prefill agrees
    with the existing hand-counted FPM path within 20% on the same run
    (full-bucket prompts, so padding doesn't separate the two), both on
    the raw records and in obs.report's per-phase roofline table."""
    tr = obs.Tracer(out_path=str(tmp_path / "roof.json")).install()
    try:
        eng = make_engine()
        for i in range(4):
            await serve_one(eng, i)  # 32-token prompts == bucket 32
        recs = list(eng.fpm)
        await eng.close()
        path = tr.dump()
    finally:
        tr.uninstall()
    pre = [r for r in recs if r.get("kind") == "prefill"]
    costed = [r for r in pre if "xla_flops" in r and r["flops"]]
    assert costed, "no prefill record carried cost analysis"
    for r in costed:
        ratio = r["xla_flops"] / r["flops"]
        assert 0.8 <= ratio <= 1.2, (
            f"cost-analysis flops diverged {ratio:.2f}x from the hand "
            f"count: {r}")
    # both MFUs present on gap-valid records and in agreement
    mfus = [r for r in pre if "mfu" in r and "est_mfu" in r]
    assert mfus, "no prefill record carried mfu (no plausible gap?)"
    for r in mfus:
        assert r["mfu"] == pytest.approx(r["est_mfu"], rel=0.2)
    # the FpmWindow headline gauge path consumes the same records: the
    # cost-analysis phase rate must agree with the hand count under the
    # SAME aggregation (ratio of sums over the same gated records)
    fw = FpmWindow()
    for r in recs:
        fw.add(1, r)
    xla_mfu = fw.phase_mfu("prefill", peak_tflops=100.0)
    assert xla_mfu > 0.0
    gated = [r for r in pre
             if "xla_flops" in r and r["synced"]
             and 0.0 < r["gap_s"] < 1.0]
    hand_rate = (sum(r["flops"] for r in gated)
                 / sum(r["gap_s"] for r in gated))
    assert xla_mfu == pytest.approx(hand_rate / (100.0 * 1e12), rel=0.25)
    assert fw.prefill_mfu() > 0.0  # the headline gauge still reads
    # ...and obs.report prints the same numbers in its roofline table
    from dynamo_tpu.obs.report import report_paths

    roof = report_paths([path], peak_tflops=100.0,
                        peak_hbm_gbps=100.0)["roofline"]
    assert "prefill_packed" in roof["compiles"]
    prefill = roof["phases"]["prefill"]
    assert prefill["costed_dispatches"] >= 1
    assert prefill["mfu"] == pytest.approx(prefill["est_mfu"], rel=0.25)
    assert prefill["xla_bytes_per_s"] > 0
    assert "decode" in roof["phases"]


async def test_decode_and_spec_records_carry_costs():
    """Decode (and spec-verify when enabled) FPM records carry the
    compiled program's flops/bytes — the inputs decode MFU/MBU gauges
    aggregate; FpmWindow.phase_mbu turns them into a utilization."""
    eng = make_engine(spec_decode="ngram", spec_k=2)
    # a repetitive prompt so the n-gram proposer engages
    req = PreprocessedRequest(
        token_ids=[5, 6, 7, 8] * 8, request_id="rep",
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=16, ignore_eos=True))
    async for _ in eng.generate(req):
        pass
    for i in range(2):
        await serve_one(eng, i + 10)
    recs = list(eng.fpm)
    await eng.close()
    dec = [r for r in recs if r.get("kind") == "decode"]
    assert dec and all("xla_flops" in r and "xla_bytes" in r for r in dec)
    spec = [r for r in recs if r.get("kind") == "spec_verify"]
    assert spec, "speculation never engaged"
    assert any("xla_flops" in r for r in spec)
    fw = FpmWindow()
    for r in recs:
        fw.add(1, r)
    assert fw.phase_mbu("decode", peak_hbm_gbps=100.0) > 0.0
    assert fw.phase_mfu("decode", peak_tflops=100.0) > 0.0


async def test_guided_topk_compile_is_watched():
    """The guided top-M program's 8-14s mid-serving fork is the compile
    the watchdog exists for: _guided_step's lazy init must go through
    the wrapped _topk_jit, not a raw jax.jit that escapes observation."""
    eng = make_engine(max_num_seqs=2)
    schema = {"type": "object",
              "properties": {"unit": {"enum": ["c", "f"]}}}
    req = PreprocessedRequest(
        token_ids=list(range(7, 19)), request_id="g1",
        sampling=SamplingOptions(temperature=0.0, guided_json=schema),
        stop=StopConditions(max_tokens=24))
    async for _ in eng.generate(req):
        pass
    assert eng.compile_watch.counts.get("decode_topk", 0) >= 1, \
        eng.compile_watch.counts
    await eng.close()


# --------------------- engine KV occupancy ----------------------------------


async def test_engine_kv_occupancy_tiers():
    eng = make_engine(host_cache_blocks=8)
    occ0 = eng.kv_occupancy()
    assert occ0["g1"]["capacity"] == 255  # block 0 is the garbage block
    assert occ0["g1"]["used"] == 0
    assert "g2" in occ0 and occ0["g2"]["capacity"] == 8
    await serve_one(eng, 0)
    occ = eng.kv_occupancy()
    assert occ["g1"]["used"] > 0
    assert occ["g1"]["used"] + occ["g1"]["free"] == occ["g1"]["capacity"]
    await eng.close()


# --------------------- mocker parity ----------------------------------------


async def test_mock_engine_emits_compile_and_roofline_records():
    from dynamo_tpu.mocker import MockEngine, MockEngineArgs

    eng = MockEngine(MockEngineArgs(
        model_name="m", block_size=4, base_step_s=0.0005,
        peak_tflops=50.0, peak_hbm_gbps=100.0))
    # two sequential requests: the second's prefill dispatch has a
    # plausible (>0) gap, which is what gates the mfu field
    for i in (1, 2):
        req = PreprocessedRequest(
            token_ids=list(range(3, 40)), request_id=f"r{i}",
            stop=StopConditions(max_tokens=24, ignore_eos=True))
        async for _ in eng.generate(req):
            pass
    await eng.close()
    recs = list(eng.fpm)
    comp = [r for r in recs if r.get("kind") == COMPILE_KIND]
    assert {r["family"] for r in comp} == {"prefill", "decode"}
    assert all(not r["serving"] for r in comp)  # first-dispatch = warmup
    assert [r["family"] for r in comp].count("prefill") == 1  # once each
    dec = [r for r in recs if r.get("kind") == "decode"]
    pre = [r for r in recs if r.get("kind") == "prefill"]
    assert dec and pre
    assert all("xla_flops" in r for r in dec + pre)
    fw = FpmWindow()
    for r in recs:
        fw.add(1, r)
    assert fw.phase_mfu("decode", 50.0) > 0.0
    assert fw.phase_mbu("decode", 100.0) > 0.0
    assert fw.prefill_mfu() > 0.0  # sim prefill records carry mfu


async def test_mock_engine_recompile_storm_records():
    from dynamo_tpu.mocker import MockEngine, MockEngineArgs

    eng = MockEngine(MockEngineArgs(
        model_name="m", block_size=4, base_step_s=0.0,
        sim_recompile_every=5))
    req = PreprocessedRequest(
        token_ids=list(range(3, 20)), request_id="r1",
        stop=StopConditions(max_tokens=30, ignore_eos=True))
    async for _ in eng.generate(req):
        pass
    await eng.close()
    storm = [r for r in eng.fpm
             if r.get("kind") == COMPILE_KIND and r.get("serving")]
    assert storm, "sim_recompile_every emitted no mid-serving compiles"


# --------------------- worker /metrics export -------------------------------


async def test_mocker_worker_exports_compile_and_occupancy_gauges():
    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc"),
        cluster_id=uuid.uuid4().hex).start()
    worker = await MockerWorker(rt, MockEngineArgs(
        model_name="roof-model", block_size=4, base_step_s=0.0005,
        peak_tflops=50.0, peak_hbm_gbps=100.0)).start()
    client = await (rt.namespace("dynamo").component("mocker")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    req = PreprocessedRequest(
        token_ids=list(range(3, 40)), request_id="r1",
        stop=StopConditions(max_tokens=24, ignore_eos=True))
    async for _ in client.generate(req.to_dict()):
        pass
    text = ""
    for _ in range(40):  # wait out a load-loop tick
        await asyncio.sleep(0.1)
        text = rt.metrics.render().decode()
        if "dynamo_engine_compile_seconds" in text \
                and "dynamo_engine_mfu" in text:
            break
    assert 'dynamo_engine_compile_seconds_count{' in text
    assert 'family="prefill"' in text and 'family="decode"' in text
    assert "dynamo_engine_compiles_total" in text
    assert 'dynamo_engine_mfu{' in text and 'phase="decode"' in text
    assert 'dynamo_engine_mbu{' in text
    assert 'dynamo_engine_kv_blocks_used{' in text
    assert 'tier="g1"' in text
    assert "dynamo_engine_kv_blocks_capacity" in text
    await client.close()
    await worker.close()
    await rt.shutdown()


def test_observe_compile_records_histogram_math():
    from dynamo_tpu.runtime.metrics import MetricsHierarchy

    m = MetricsHierarchy(component="backend")
    observe_compile_records(m, [
        {"kind": COMPILE_KIND, "family": "decode", "seconds": 12.0,
         "serving": True},
        {"kind": COMPILE_KIND, "family": "decode", "seconds": 0.5},
        {"kind": "decode", "gap_s": 0.01},  # non-compile: ignored
    ])
    text = m.render().decode()
    # 12s must land in a real bucket, not only +Inf (buckets reach 60s)
    assert 'dynamo_engine_compile_seconds_bucket{' in text
    assert 'le="20.0"' in text
    for line in text.splitlines():
        if line.startswith("dynamo_engine_compiles_total{"):
            assert float(line.rsplit(" ", 1)[1]) == 2.0
        if line.startswith("dynamo_engine_serving_compiles_total{"):
            assert float(line.rsplit(" ", 1)[1]) == 1.0


# --------------------- planner storm diag -----------------------------------


def test_fpm_window_compile_stats_and_planner_storm_diag():
    fw = FpmWindow()
    fw.add(1, {"kind": COMPILE_KIND, "family": "decode", "seconds": 9.0,
               "serving": True})
    fw.add(1, {"kind": COMPILE_KIND, "family": "prefill_packed",
               "seconds": 2.0, "serving": False})
    stats = fw.compile_stats()
    assert stats["total"] == 2 and stats["serving"] == 1
    assert stats["families"]["decode"]["seconds"] == 9.0

    # the SLA tick diag surfaces the storm (planner/_propose_sla)
    import test_sla_planner as tsp
    from dynamo_tpu.planner.metrics import AggregateLoad
    from dynamo_tpu.planner.perf_model import PerfModel
    from dynamo_tpu.planner.planner import PlannerConfig

    p = tsp._sla_planner(
        PlannerConfig(mode="sla", itl_target_s=0.01),
        tsp._FakeConnector(), PerfModel(tsp.synthetic_profile()))
    p.fpm = fw
    diag = {}
    p._propose_sla(AggregateLoad(workers=1, active_seqs=4,
                                 mean_kv_usage=0.1, mean_isl=128),
                   4.0, diag)
    assert diag["compiles"]["decode"]["count"] == 1
    assert diag["recompile_storm"]["serving_compiles"] == 1
    assert "decode" in diag["recompile_storm"]["families"]


# --------------------- KVBM manager occupancy -------------------------------


def test_kvbm_manager_occupancy(tmp_path):
    from dynamo_tpu.kvbm.manager import TieredKvManager

    mgr = TieredKvManager(host_blocks=2, disk_dir=str(tmp_path),
                          disk_blocks=4)
    blk = (np.ones((2, 4), np.float16), np.ones((2, 4), np.float16))
    for h in (11, 22, 33):  # 3 blocks into a 2-block G2: one demotes
        mgr.offload(h, *blk)
    occ = mgr.occupancy()
    assert occ["g2"]["used"] == 2 and occ["g2"]["capacity"] == 2
    assert occ["g2"]["free"] == 0
    assert occ["g3"]["used"] == 1 and occ["g3"]["capacity"] == 4
    mgr.close()
