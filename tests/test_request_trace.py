"""Request tracing: per-request JSONL records, x-request-id echo,
traceparent propagation (ref: lib/llm/src/request_trace/)."""

import asyncio
import json
import uuid

import aiohttp

from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.frontend.request_trace import (
    RequestTracker,
    TraceConfig,
    TraceSink,
    parse_traceparent,
)
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.runtime import DistributedRuntime, RouterMode, RuntimeConfig


def fresh_runtime() -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


# --------------------------- unit: traceparent ------------------------------


def test_parse_traceparent():
    tid, span = parse_traceparent(
        "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01")
    assert tid == "0af7651916cd43dd8448eb211c80319c"
    assert span == "b7ad6b7169203331"
    assert parse_traceparent(None) == (None, None)
    assert parse_traceparent("junk") == (None, None)
    # all-zero ids are invalid per W3C
    assert parse_traceparent(
        "00-00000000000000000000000000000000-b7ad6b7169203331-01"
    ) == (None, None)


def test_tracker_record_shape(tmp_path):
    sink = TraceSink(TraceConfig(enabled=True,
                                 file_path=str(tmp_path / "t.jsonl")))
    tr = RequestTracker(request_id="r1", model="m", sink=sink,
                        input_tokens=10, session_id="sess",
                        trace_id="a" * 32, parent_span_id="b" * 16)
    tr.on_dispatch(101)
    tr.on_tokens(1)
    tr.on_tokens(3)
    tr.cached_tokens = 5
    rec = tr.finish(finish_reason="stop")
    sink.close()
    assert rec["schema"] == "dynamo.request.trace.v1"
    assert rec["event_type"] == "request_end"
    r = rec["request"]
    assert r["input_tokens"] == 10 and r["output_tokens"] == 4
    assert r["worker"]["decode_worker_id"] == 101
    assert r["kv_hit_rate"] == 0.5
    assert r["finish_reason_metadata"]["finish_reason"] == "stop"
    assert rec["trace"]["trace_id"] == "a" * 32
    assert rec["trace"]["parent_span_id"] == "b" * 16
    assert rec["agent_context"]["session_id"] == "sess"
    assert "ttft_ms" in r and "avg_itl_ms" in r and "total_time_ms" in r
    # written to the file sink
    lines = (tmp_path / "t.jsonl").read_text().strip().splitlines()
    assert len(lines) == 1 and json.loads(lines[0]) == rec


def test_tracker_migration_counting():
    tr = RequestTracker(request_id="r", model="m")
    tr.on_dispatch(1)
    tr.on_dispatch(2)  # migrated
    rec = tr.finish(error="worker died twice")
    assert rec["request"]["migrations"] == 1
    assert rec["request"]["worker"]["decode_worker_id"] == 2
    assert rec["request"]["error"] == "worker died twice"


def test_disabled_sink_emits_nothing(tmp_path):
    path = tmp_path / "none.jsonl"
    sink = TraceSink(TraceConfig(enabled=False, file_path=str(path)))
    RequestTracker(request_id="r", model="m", sink=sink).finish()
    sink.close()
    assert not path.exists()


# --------------------------- HTTP e2e ---------------------------------------


async def test_http_trace_end_to_end(tmp_path, monkeypatch):
    trace_file = tmp_path / "trace.jsonl"
    monkeypatch.setenv("DYN_REQUEST_TRACE", "1")
    monkeypatch.setenv("DYN_REQUEST_TRACE_FILE_PATH", str(trace_file))

    rt = await fresh_runtime().start()
    model = "trace-model"
    args = MockEngineArgs(model_name=model, block_size=4,
                          base_step_s=0.0005, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0)
    worker = await MockerWorker(rt, args).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get(model):
            break
        await asyncio.sleep(0.02)
    try:
        body = {"model": model,
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 6, "ignore_eos": True}
        headers = {
            "x-request-id": "client-chose-this",
            "traceparent":
                "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01",
            "x-session-id": "agent-7",
        }
        async with aiohttp.ClientSession() as s:
            # unary
            async with s.post(f"http://127.0.0.1:{port}/v1/chat/completions",
                              json=body, headers=headers) as r:
                assert r.status == 200
                assert r.headers["X-Request-Id"] == "client-chose-this"
            # streaming
            async with s.post(f"http://127.0.0.1:{port}/v1/chat/completions",
                              json={**body, "stream": True},
                              headers=headers) as r:
                assert r.status == 200
                assert r.headers["X-Request-Id"] == "client-chose-this"
                await r.read()
        recs = [json.loads(x) for x in
                trace_file.read_text().strip().splitlines()]
        assert len(recs) == 2
        for rec in recs:
            assert rec["schema"] == "dynamo.request.trace.v1"
            r = rec["request"]
            assert r["x_request_id"] == "client-chose-this"
            assert r["model"] == model
            assert r["output_tokens"] == 6
            assert r["worker"]["decode_worker_id"] == \
                worker.served.instance_id
            assert rec["trace"]["trace_id"] == \
                "0af7651916cd43dd8448eb211c80319c"
            assert rec["agent_context"]["session_id"] == "agent-7"
            assert r["finish_reason_metadata"]["finish_reason"] == "length"
            assert r["ttft_ms"] >= 0.0 and r["total_time_ms"] > 0.0
    finally:
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()
