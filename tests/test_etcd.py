"""EtcdDiscovery against an in-process fake of the etcd v3 JSON gateway:
kv roundtrip, prefix watch with snapshot + live events, lease expiry as
the failure-detection primitive, and a full runtime serving over it.

Ref shape: lib/runtime/src/discovery/kv_store.rs (primary lease, keys
bound to it, prefix watch -> delete on expiry)."""

import asyncio
import contextlib
import uuid

from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig
from dynamo_tpu.runtime.etcd import EtcdDiscovery, prefix_range_end

from fake_etcd import FakeEtcd


def test_prefix_range_end():
    assert prefix_range_end(b"v1/") == b"v10"
    assert prefix_range_end(b"a\xff") == b"b"
    assert prefix_range_end(b"\xff\xff") == b"\0"  # whole keyspace


@contextlib.asynccontextmanager
async def fake_etcd():
    # async-contextmanager, not a fixture: the repo's minimal async-test
    # hook (conftest.pytest_pyfunc_call) does not support async fixtures
    srv = await FakeEtcd().start()
    try:
        yield srv
    finally:
        await srv.close()


async def test_put_get_delete_roundtrip():
    async with fake_etcd() as fake:
        d = EtcdDiscovery(fake.endpoint, ttl_s=5.0)
        await d.start()
        await d.put("v1/instances/ns/w/e/42", {"instance_id": 42})
        await d.put("v1/mdc/ns/model", {"name": "m"}, lease=False)
        snap = await d.get_prefix("v1/instances/")
        assert snap == {"v1/instances/ns/w/e/42": {"instance_id": 42}}
        assert await d.get_prefix("v1/") == {
            "v1/instances/ns/w/e/42": {"instance_id": 42},
            "v1/mdc/ns/model": {"name": "m"},
        }
        await d.delete("v1/instances/ns/w/e/42")
        assert await d.get_prefix("v1/instances/") == {}
        await d.close()


async def test_watch_snapshot_then_live_events():
    async with fake_etcd() as fake:
        d1 = EtcdDiscovery(fake.endpoint, ttl_s=5.0)
        d2 = EtcdDiscovery(fake.endpoint, ttl_s=5.0)
        await d1.put("v1/instances/ns/w/e/1", {"instance_id": 1})

        events = []
        cancel = asyncio.Event()

        async def watch():
            async for ev in d2.watch("v1/instances/", cancel=cancel):
                events.append(ev)
                if len(events) >= 3:
                    cancel.set()

        task = asyncio.create_task(watch())
        await asyncio.sleep(0.3)  # let the snapshot + stream establish
        await d1.put("v1/instances/ns/w/e/2", {"instance_id": 2})
        await d1.delete("v1/instances/ns/w/e/1")
        await asyncio.wait_for(task, timeout=5)
        assert [(e.type, e.key) for e in events] == [
            ("put", "v1/instances/ns/w/e/1"),
            ("put", "v1/instances/ns/w/e/2"),
            ("delete", "v1/instances/ns/w/e/1"),
        ]
        assert events[1].value == {"instance_id": 2}
        await d1.close()
        await d2.close()


async def test_lease_expiry_deletes_keys_and_notifies():
    """Crash (no keepalive, no revoke) -> etcd expires the lease ->
    watchers see deletes.  The failure-detection primitive."""
    async with fake_etcd() as fake:
        d1 = EtcdDiscovery(fake.endpoint, ttl_s=1.0)
        await d1.put("v1/instances/ns/w/e/7", {"instance_id": 7})

        d2 = EtcdDiscovery(fake.endpoint, ttl_s=5.0)
        events = []
        cancel = asyncio.Event()

        async def watch():
            async for ev in d2.watch("v1/instances/", cancel=cancel):
                events.append(ev)
                if ev.type == "delete":
                    cancel.set()

        task = asyncio.create_task(watch())
        await asyncio.sleep(0.2)
        # simulated crash: stop keepalive without revoking
        d1._closed.set()
        if d1._ka_task:
            d1._ka_task.cancel()
        await asyncio.wait_for(task, timeout=6)
        assert events[-1].type == "delete"
        assert events[-1].key == "v1/instances/ns/w/e/7"
        assert await d2.get_prefix("v1/instances/") == {}
        if d1._session is not None and not d1._session.closed:
            await d1._session.close()
        await d2.close()


async def test_keepalive_holds_lease_past_ttl():
    async with fake_etcd() as fake:
        d = EtcdDiscovery(fake.endpoint, ttl_s=1.0)
        await d.put("v1/instances/ns/w/e/9", {"instance_id": 9})
        probe = EtcdDiscovery(fake.endpoint, ttl_s=5.0)
        await asyncio.sleep(2.5)  # > 2 TTLs; keepalive must hold it
        assert await probe.get_prefix("v1/instances/") == {
            "v1/instances/ns/w/e/9": {"instance_id": 9}}
        await d.close()
        # clean close revokes the lease: keys disappear immediately
        assert await probe.get_prefix("v1/instances/") == {}
        await probe.close()


async def test_expired_lease_reregisters_owned_keys():
    """Partition longer than the TTL: etcd expires the lease and deletes
    the keys; the next keepalive sees TTL=0 and must re-grant + re-put so
    a healthy worker does not stay invisible forever."""
    async with fake_etcd() as fake:
        d = EtcdDiscovery(fake.endpoint, ttl_s=1.0)
        await d.put("v1/instances/ns/w/e/5", {"instance_id": 5})
        old_lease = d.lease_id
        # force-expire server side (as if keepalives were partitioned away)
        fake._drop_lease(old_lease)
        assert await d.get_prefix("v1/instances/") == {}
        for _ in range(40):  # keepalive interval is ttl/3
            await asyncio.sleep(0.1)
            if await d.get_prefix("v1/instances/"):
                break
        assert await d.get_prefix("v1/instances/") == {
            "v1/instances/ns/w/e/5": {"instance_id": 5}}
        assert d.lease_id != old_lease
        await d.close()


async def test_runtime_serves_over_etcd():
    """Full endpoint round-trip with etcd as the discovery plane."""
    async with fake_etcd() as fake:
        def rt_with_etcd():
            cfg = RuntimeConfig(discovery_backend="etcd",
                                etcd_endpoint=fake.endpoint,
                                event_plane="inproc")
            return DistributedRuntime(config=cfg,
                                      cluster_id=uuid.uuid4().hex)

        async def echo(payload, ctx):
            for tok in payload["items"]:
                yield {"echo": tok}

        async with rt_with_etcd() as rt1, rt_with_etcd() as rt2:
            ep = rt1.namespace("ns").component("worker").endpoint("generate")
            await ep.serve_endpoint(echo)
            client = await (rt2.namespace("ns").component("worker")
                            .endpoint("generate").client()).start()
            await client.wait_for_instances()
            out = [item["echo"] async for item in
                   client.generate({"items": [1, 2, 3]})]
            assert out == [1, 2, 3]
            await client.close()


async def test_make_discovery_selects_etcd():
    from dynamo_tpu.runtime.discovery import make_discovery

    async with fake_etcd() as fake:
        d = make_discovery("etcd", etcd_endpoint=fake.endpoint, ttl_s=2.0)
        assert isinstance(d, EtcdDiscovery)
        await d.put("v1/x", {"a": 1})
        assert await d.get_prefix("v1/") == {"v1/x": {"a": 1}}
        await d.close()
