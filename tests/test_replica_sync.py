"""RouterReplicaSync: ordering, convergence, snapshot-on-subscribe,
TTL stale-reap, and malformed-frame resilience.

These are the slot-view guarantees the scaled-out frontend tier
(global_router/) leans on: N replicas sharing one pool must converge to
the same per-worker load picture, a late-started replica must inherit
the in-flight picture within one tick, and a crashed replica's phantom
load must decay instead of pinning workers busy forever.
"""

import asyncio
import uuid

from dynamo_tpu import chaos
from dynamo_tpu.router.replica_sync import RouterReplicaSync
from dynamo_tpu.router.sequences import ActiveSequences
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig


async def make_sync(cluster: str, router_id=None, ttl=5.0):
    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc"),
        cluster_id=cluster).start()
    seqs = ActiveSequences()
    sync = await RouterReplicaSync(rt, "ns", "comp", seqs,
                                   router_id=router_id,
                                   peer_ttl_s=ttl).start()
    return rt, seqs, sync


async def teardown(*stacks):
    for rt, _seqs, sync in stacks:
        await sync.close()
        await rt.shutdown()


async def poll(cond, timeout_s=3.0, interval=0.02):
    for _ in range(int(timeout_s / interval)):
        if cond():
            return True
        await asyncio.sleep(interval)
    return cond()


async def test_free_before_add_never_leaves_phantom_load():
    """The _outbox single-writer guarantee: add and free enqueued
    back-to-back (before the send loop even wakes) must arrive in
    order, so the peer ends with ZERO load — not a phantom entry."""
    cluster = uuid.uuid4().hex
    a = await make_sync(cluster, "ra")
    b = await make_sync(cluster, "rb")
    try:
        # enqueue add+free synchronously, no await in between: a
        # fire-and-forget implementation could publish these out of
        # order (the first publish sets up the subscription socket)
        a[2].publish_add("r1", worker_id=7, blocks=10, overlap_blocks=0)
        a[2].publish_free("r1")
        # a second request stays open so we can tell "converged" from
        # "nothing arrived yet"
        a[2].publish_add("r2", worker_id=7, blocks=4, overlap_blocks=0)
        assert await poll(lambda: "r2@ra" in b[1]._reqs)
        assert "r1@ra" not in b[1]._reqs
        # only r2's load remains: 4 decode blocks + 2.0 * 4 prefill
        assert b[1].active_blocks(7) == 12.0
    finally:
        await teardown(a, b)


async def test_n_router_views_converge_under_concurrent_adds():
    cluster = uuid.uuid4().hex
    stacks = [await make_sync(cluster, f"r{i}") for i in range(4)]
    try:

        async def burst(i):
            for k in range(5):
                stacks[i][2].publish_add(f"q{i}-{k}", worker_id=k % 3,
                                         blocks=k + 1, overlap_blocks=0)
                await asyncio.sleep(0)  # interleave the four publishers

        await asyncio.gather(*(burst(i) for i in range(4)))
        # every router must fold in all 15 peer entries (its own 5 are
        # applied by its KvRouter, not by sync — not simulated here)
        assert await poll(lambda: all(
            len(seqs._reqs) == 15 for _rt, seqs, _s in stacks))
        # every burst has the same (worker, blocks) shape, so all four
        # views — each the sum of the OTHER three bursts — must agree
        # exactly on per-worker load
        for w in range(3):
            views = {round(seqs.active_blocks(w), 3)
                     for _rt, seqs, _s in stacks}
            assert len(views) == 1, (w, views)
    finally:
        await teardown(*stacks)


async def test_malformed_frame_drop_keeps_loop_alive():
    cluster = uuid.uuid4().hex
    a = await make_sync(cluster, "ra")
    b = await make_sync(cluster, "rb")
    try:
        # three shapes of garbage: not a dict field set, missing fields,
        # wrong types — each must be dropped without killing the loop
        for frame in (
            {"op": "add", "router_id": "evil"},                # no fields
            {"op": "add", "router_id": "evil", "request_id": "x",
             "worker_id": "NaN", "blocks": "many"},            # bad types
            {"router_id": "evil", "entries": None, "op": "snapshot",
             "to": "rb"},                                      # bad body
        ):
            await a[0].event_plane.publish(a[2].subject, frame)
        a[2].publish_add("ok", worker_id=1, blocks=2, overlap_blocks=0)
        assert await poll(lambda: "ok@ra" in b[1]._reqs), (
            "recv loop died on a malformed frame")
    finally:
        await teardown(a, b)


async def test_ttl_reap_decays_crashed_peer_load():
    cluster = uuid.uuid4().hex
    a = await make_sync(cluster, "ra", ttl=0.25)
    b = await make_sync(cluster, "rb", ttl=0.25)
    try:
        b[2].publish_add("z1", worker_id=2, blocks=8, overlap_blocks=0)
        b[2].publish_add("z2", worker_id=2, blocks=8, overlap_blocks=0)
        assert await poll(lambda: len(a[1]._reqs) == 2)
        assert a[1].active_blocks(2) > 0
        # crash rb: no free, no heartbeats — just silence
        await b[2].close()
        assert await poll(lambda: len(a[1]._reqs) == 0, timeout_s=5.0), (
            "phantom load never reaped after peer went silent")
        assert a[1].active_blocks(2) == 0.0
        assert "rb" not in a[2].stats()["peer_inflight"]
    finally:
        await a[2].close()
        await a[0].shutdown()
        await b[0].shutdown()


async def test_live_peer_with_idle_traffic_is_not_reaped():
    """Heartbeats keep an idle-but-alive peer's entries resident past
    the TTL — reap is for crashed peers, not quiet ones."""
    cluster = uuid.uuid4().hex
    a = await make_sync(cluster, "ra", ttl=0.3)
    b = await make_sync(cluster, "rb", ttl=0.3)
    try:
        b[2].publish_add("idle", worker_id=1, blocks=3, overlap_blocks=0)
        assert await poll(lambda: "idle@rb" in a[1]._reqs)
        await asyncio.sleep(1.0)  # > 3x TTL, heartbeats flowing
        assert "idle@rb" in a[1]._reqs
    finally:
        await teardown(a, b)


async def test_snapshot_on_subscribe_late_joiner_converges():
    """PR 14's late-joiner contract applied to slot state: a replica
    started AFTER its peers took load inherits their in-flight adds —
    including prefill_done transitions — within one sync tick."""
    cluster = uuid.uuid4().hex
    a = await make_sync(cluster, "ra")
    b = await make_sync(cluster, "rb")
    try:
        a[2].publish_add("p1", worker_id=0, blocks=6, overlap_blocks=2)
        a[2].publish_add("p2", worker_id=1, blocks=4, overlap_blocks=0)
        a[2].publish_prefill_done("p2")
        b[2].publish_add("p3", worker_id=0, blocks=5, overlap_blocks=0)
        assert await poll(lambda: len(b[1]._reqs) == 2)  # a's two adds
        # late joiner: no replayed live frames, only the snapshot
        c = await make_sync(cluster, "rc")
        try:
            assert await poll(lambda: len(c[1]._reqs) == 3), (
                c[1]._reqs.keys())
            assert c[1]._reqs["p1@ra"].blocks == 6
            assert c[1]._reqs["p1@ra"].overlap_blocks == 2
            assert c[1]._reqs["p2@ra"].prefill_done is True
            assert c[1]._reqs["p3@rb"].blocks == 5
            # and load math matches a fully-synced peer's view of the
            # same entries
            assert c[1].active_blocks(0) == 6 + 2 * 4 + 5 + 2 * 5
            assert c[2].stats()["snapshots_applied"] >= 1
        finally:
            await teardown(c)
        # freed entries must never resurrect via a later snapshot
        a[2].publish_free("p1")
        d = await make_sync(cluster, "rd")
        try:
            assert await poll(lambda: "p2@ra" in d[1]._reqs)
            await asyncio.sleep(0.1)
            assert "p1@ra" not in d[1]._reqs
        finally:
            await teardown(d)
    finally:
        await teardown(a, b)


async def test_snapshot_chaos_fault_is_survived_and_retried():
    """A chaos fault in the snapshot answer (seam router_sync.snapshot)
    must drop that one frame, keep the peer's recv loop alive, and the
    joiner's subscribe retry still converges."""
    cluster = uuid.uuid4().hex
    a = await make_sync(cluster, "ra")
    plane = chaos.ChaosPlane(seed=3)
    plane.rule("router_sync.snapshot", "fail", times=1)
    try:
        a[2].publish_add("s1", worker_id=0, blocks=2, overlap_blocks=0)
        with plane:
            b = await make_sync(cluster, "rb")
            try:
                # first snapshot answer fails; the hello loop's retry
                # gets the second one through
                assert await poll(lambda: "s1@ra" in b[1]._reqs), (
                    "joiner never converged after snapshot fault")
                assert plane.injections
                # peer's loop is alive: live traffic still applies
                a[2].publish_add("s2", worker_id=0, blocks=2,
                                 overlap_blocks=0)
                assert await poll(lambda: "s2@ra" in b[1]._reqs)
            finally:
                await teardown(b)
    finally:
        await teardown(a)
