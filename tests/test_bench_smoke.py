"""Importability + argparse smoke for every benchmarks/bench_*.py.

The benchmarks run only on real TPU hardware, so nothing in CI executed
them and import-time drift (renamed ops, moved modules, jax API skew)
went unnoticed until someone sat down at a chip.  `--help` forces the
full module import plus argument parsing and must exit 0 in a few
seconds on CPU — cheap enough for tier-1, and it catches exactly the
drift class that cost round 5 a bench session."""

import glob
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCHES = sorted(glob.glob(os.path.join(REPO, "benchmarks",
                                        "bench_*.py")))


def test_benchmarks_discovered():
    # the glob must see the suite; an empty list would vacuously pass
    assert len(BENCHES) >= 7, BENCHES
    names = {os.path.basename(p) for p in BENCHES}
    assert "bench_kv_quant.py" in names


def test_lint_cli_help_exits_zero():
    """The dynlint CLI rides the same drift gate as the benches: --help
    forces the full module import and argparse wiring (the --json
    contract itself is covered in tests/test_lint.py)."""
    r = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.lint", "--help"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])
    assert "--json" in r.stdout and "--baseline" in r.stdout


@pytest.mark.parametrize(
    "path", BENCHES, ids=[os.path.basename(p) for p in BENCHES])
def test_bench_help_exits_zero(path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, path, "--help"],
        capture_output=True, text=True, env=env, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])
    assert "usage" in r.stdout.lower()
    if os.path.basename(path) == "bench_prefill_phases.py":
        # the attention-impl A/B mode (Pallas tile-skip kernel vs the
        # masked XLA reference, one JSON line with both variants' MFU)
        assert "--impl" in r.stdout
    if os.path.basename(path) == "bench_serving.py":
        # the timeline-tracing hook (obs/): --trace-out records the run
        # and prints the gap-attribution line
        assert "--trace-out" in r.stdout
        # SLO plane flags (obs/slo.py vocabulary, ms like the frontend)
        assert "--slo-ttft-ms" in r.stdout
        assert "--slo-itl-ms" in r.stdout
        # forensics plane A/B hook (obs/forensics.py)
        assert "--forensics" in r.stdout
        # KV-accounting plane A/B hook (obs/kv_ledger.py)
        assert "--kv-ledger" in r.stdout


def test_bench_serving_json_carries_slo_and_roofline_blocks():
    """The bench JSON schema's `slo` + `roofline` blocks must actually
    serialize from a (tiny, sped-up) run: the scoreboard the rounds are
    diffed on, not just flags in --help."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "bench_serving.py"),
         "--requests", "8", "--rate", "40", "--speedup", "20",
         "--workers", "2", "--slo-ttft-ms", "2000", "--slo-itl-ms", "25"],
        capture_output=True, text=True, env=env, timeout=300, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])
    reps = [json.loads(line) for line in r.stdout.splitlines()
            if line.startswith("{")]
    configs = {rep["config"] for rep in reps}
    assert any(c.startswith("agg-") for c in configs), configs
    assert any(c.startswith("disagg-") for c in configs), configs
    for rep in reps:
        if rep["config"] == "trace":
            continue
        # ms flags override the seconds-based defaults
        assert rep["slo"]["ttft_s"] == 2.0
        assert rep["slo"]["itl_s"] == 0.025
        assert 0.0 <= rep["slo"]["goodput"] <= 1.0
        roof = rep["roofline"]
        # the mocker sim compiled prefill+decode and the gauges lit up
        assert roof["compiles"].get("prefill", 0) >= 1
        assert roof["compiles"].get("decode", 0) >= 1
        assert "decode" in roof["mfu"] and "decode" in roof["mbu"]
        # fleet block (obs/fleet.py): peak imbalance / straggler count /
        # min KV headroom scraped back off the run's own registry
        fleet = rep["fleet"]
        assert fleet["imbalance"] >= 1.0
        assert fleet["stragglers"] >= 0
        assert 0.0 <= fleet["kv_headroom_min"] <= 1.0
        # tail-forensics block (obs/forensics.py, plane on by default):
        # worst retained exemplar's EXACT phase partition + the
        # realized-overlap rate read off the run's own registry
        tail = rep["tail"]
        assert tail["exemplars"] >= 1
        part = tail["p99_partition"]
        assert set(part) == {"queue", "route", "prefill", "transfer",
                             "decode", "stall"}
        # the pre-first-token phases sum to the exemplar's TTFT (the
        # partition's exactness property, visible in the bench block)
        pre = (part["queue"] + part["route"] + part["prefill"]
               + part["transfer"])
        assert abs(pre - tail["p99_ttft_ms"]) <= 0.02 * pre + 0.02


def test_bench_serving_kv_ledger_ab_streams_identical_and_clean():
    """--kv-ledger ab: the always-on accounting plane must be pure
    observation — byte-identical token streams with it on vs off (hard
    assert inside the bench) AND a post-run audit that reconciles
    exactly (0 violations, also a hard assert inside the bench).  The
    <1% overhead target is a bench-scale number; at smoke scale under
    suite-parallel CPU contention the rate comparison carries timing
    noise, so the gate here is a generous sanity bound on top of the
    identity + reconciliation asserts."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "bench_serving.py"),
         "--requests", "12", "--rate", "50", "--input-len", "64",
         "--output-len", "8", "--speedup", "4", "--kv-ledger", "ab"],
        capture_output=True, text=True, env=env, timeout=300, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])
    (rep,) = [json.loads(line) for line in r.stdout.splitlines()
              if line.startswith("{")]
    assert rep["config"] == "kv_ledger_ab"
    assert rep["streams_identical"] is True
    assert rep["violations_total"] == 0
    assert rep["overhead_target_frac"] == 0.01
    assert rep["overhead_frac"] < 0.5, rep
    assert rep["kv_ledger"]["occupancy"]["g1"]["prefix_cached"] >= 0


def test_bench_serving_forensics_ab_streams_identical():
    """--forensics ab: the always-on plane must be pure observation —
    byte-identical token streams with it on vs off (hard assert inside
    the bench), and a measured throughput overhead.  The <1% overhead
    target is a bench-scale number; at smoke scale under suite-parallel
    CPU contention the rate comparison carries timing noise, so the
    gate here is a generous sanity bound on top of the identity
    assert."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "bench_serving.py"),
         "--requests", "12", "--rate", "50", "--input-len", "64",
         "--output-len", "8", "--speedup", "4", "--forensics", "ab"],
        capture_output=True, text=True, env=env, timeout=300, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])
    (rep,) = [json.loads(line) for line in r.stdout.splitlines()
              if line.startswith("{")]
    assert rep["config"] == "forensics_ab"
    assert rep["streams_identical"] is True
    assert rep["overhead_target_frac"] == 0.01
    assert rep["overhead_frac"] < 0.5, rep
    assert rep["tail"]["exemplars"] >= 1


def test_bench_global_router_smoke_closed_loop():
    """The PR 18 mega-fleet closed loop at smoke scale runs IN tier-1
    (seconds on CPU): 2 pools x 3 replica-sync'd frontends x mocker
    workers, with the correctness gates — byte-identity vs the
    single-frontend baseline and both pool classes routed — enforced
    even in smoke mode (the bench exits 1 on failure), and the
    latency/staleness measurement surfaces present per JSON line."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "bench_global_router.py"),
         "--mode", "smoke"],
        capture_output=True, text=True, env=env, timeout=300, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    (rep,) = [json.loads(line) for line in r.stdout.splitlines()
              if line.startswith("{")]
    status = {g["name"]: g["status"] for g in rep["gates"]}
    assert status["grouter_byte_identity"] == "pass"
    assert status["grouter_pools_routed"] == "pass"
    res = rep["result"]
    assert res["byte_identical"] is True and res["empty_streams"] == 0
    assert res["route_latency"]["count"] == res["streams"]
    # per-replica staleness + decision counts reported for every pool's
    # frontend tier (the replica-sync health surfaces)
    for pool in res["staleness"].values():
        assert len(pool["replicas"]) >= 3
        assert sum(r_["decisions"]
                   for r_ in pool["replicas"].values()) > 0


def test_bench_prefix_fleet_smoke_closed_loop():
    """The ISSUE-19 fleet-prefix-cache A/B at smoke scale runs IN
    tier-1 (seconds on CPU): warm fleet -> junk churn demotes prefixes
    into the shared G4 store -> a cold worker in a fresh namespace
    onboards them.  The mechanism gates — byte identity across arms,
    store populated, cold onboarding from G4, router-visible G4
    blocks, clean ledger audits — are enforced even in smoke mode (the
    bench exits 1 on failure); only the TTFT-penalty chip bars are
    skipped."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "bench_prefix_fleet.py"),
         "--mode", "smoke"],
        capture_output=True, text=True, env=env, timeout=300, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    (rep,) = [json.loads(line) for line in r.stdout.splitlines()
              if line.startswith("{")]
    status = {g["name"]: g["status"] for g in rep["gates"]}
    assert status["prefix_fleet_byte_identity"] == "pass"
    assert status["prefix_fleet_store_populated"] == "pass"
    assert status["prefix_fleet_cold_onboard_g4"] == "pass"
    assert status["prefix_fleet_router_g4_visible"] == "pass"
    assert status["prefix_fleet_ledger_audit"] == "pass"
    assert status["prefix_fleet_cold_start_penalty"] == "skipped_smoke"
    res = rep["result"]
    g4, ctl = res["g4"], res["control"]
    # the cold worker really onboarded from the shared store, and the
    # control arm really had no tier ladder to lean on
    assert g4["cold_onboards"]["g4"] > 0 and g4["store_blobs"] > 0
    assert ctl["cold_onboards"]["g4"] == 0 and ctl["store_blobs"] == 0
    # G4 residency verdicts surface on the cold worker's /debug/kv
    assert sum(g4["cold_g4_residency"]["residency"].values()) > 0
    # even unenforced, the smoke-scale penalty must point the right
    # way: onboarding strictly cheaper than the control's recompute
    assert g4["cold_start_penalty"] < ctl["cold_start_penalty"]


def test_bench_chaos_cache_smoke_closed_loop():
    """The ISSUE-20 KV-integrity A/B at smoke scale runs IN tier-1
    (seconds on CPU): warm fleet -> junk churn spills prefixes into the
    shared G4 store -> the measure wave re-onboards them, once healthy
    and once under injected corruption + stalls.  The mechanism gates —
    byte identity across arms, store populated, real G4 onboarding,
    stall/breaker observation, 1:1 corrupt attribution, clean ledger
    audits — are enforced even in smoke mode (the bench exits 1 on
    failure); only the p90-TTFT-ratio chip bar is skipped."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "bench_chaos_cache.py"),
         "--mode", "smoke"],
        capture_output=True, text=True, env=env, timeout=300, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    (rep,) = [json.loads(line) for line in r.stdout.splitlines()
              if line.startswith("{")]
    status = {g["name"]: g["status"] for g in rep["gates"]}
    assert status["chaos_cache_byte_identity"] == "pass"
    assert status["chaos_cache_store_populated"] == "pass"
    assert status["chaos_cache_control_onboard_g4"] == "pass"
    assert status["chaos_cache_stall_observed"] == "pass"
    assert status["chaos_cache_corrupt_attributed"] == "pass"
    assert status["chaos_cache_ledger_audit"] == "pass"
    assert status["chaos_cache_p90_ttft_ratio"] == "skipped_smoke"
    res = rep["result"]
    cha, ctl = res["chaos"], res["control"]
    # every materialized corruption quarantined AND attributed; the
    # healthy arm saw none of either
    hi = cha["integrity"]
    assert hi["quarantined"] > 0
    assert hi["ledger_corrupt_g4"] == hi["quarantined"]
    assert hi["breaker_trips"] > 0 and hi["timeouts"] > 0
    ci = ctl["integrity"]
    assert ci["quarantined"] == 0 and ci["breaker_trips"] == 0


def test_run_round_help_exits_zero():
    """benchmarks/run_round.py is not matched by the bench_*.py glob
    above, so it gets its own drift gate: --help must import the driver
    and exit 0, with the round's mode/subset knobs wired."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "run_round.py"), "--help"],
        capture_output=True, text=True, env=env, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])
    assert "--mode" in r.stdout and "--only" in r.stdout


@pytest.mark.slow
def test_run_round_smoke_emits_gated_json_per_bench():
    """The round driver end to end at smoke scale: one JSON line per
    bench, every line labeled mode=smoke, and every TPU acceptance gate
    PRESENT but skipped (interpret/mocker numbers must never satisfy a
    chip bar).  This is the r07 cash-in path minus the chip."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "run_round.py"), "--mode", "smoke"],
        capture_output=True, text=True, env=env, timeout=900, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    lines = [json.loads(line) for line in r.stdout.splitlines()
             if line.startswith("{")]
    by_bench = {rep["bench"]: rep for rep in lines}
    assert set(by_bench) == {"prefill", "kv_quant", "serving",
                             "indexer", "global_router",
                             "prefix_fleet", "chaos_cache"}
    gate_names = set()
    for rep in by_bench.values():
        assert rep["round"] == "r07"
        assert rep["mode"] == "smoke"
        assert rep["gates"], rep
        for g in rep["gates"]:
            # chip bars are skipped at smoke scale; correctness bars
            # (indexer parity, grouter byte-identity/pool coverage)
            # are enforced in EVERY mode and must pass
            assert g["status"] in ("skipped_smoke", "pass"), g
            gate_names.add(g["name"])
        assert "result" in rep
    assert gate_names >= {"prefill_pallas_mfu", "int8_pallas_ge_bf16",
                          "zero_mid_serving_compiles",
                          "indexer_events_per_s", "indexer_query_p99_us",
                          "grouter_byte_identity",
                          "grouter_pools_routed",
                          "grouter_route_p99_ms",
                          "grouter_staleness_spread",
                          "prefix_fleet_byte_identity",
                          "prefix_fleet_cold_onboard_g4",
                          "prefix_fleet_cold_start_penalty",
                          "chaos_cache_byte_identity",
                          "chaos_cache_corrupt_attributed",
                          "chaos_cache_p90_ttft_ratio"}
    # the correctness bars really ran
    assert {g["name"]: g["status"]
            for g in by_bench["global_router"]["gates"]
            }["grouter_byte_identity"] == "pass"
    # the per-bench results carry the round's measurement surfaces
    assert "pallas_interpret" in by_bench["prefill"]["result"]["impls"]
    rows = by_bench["kv_quant"]["result"]["decode"]["rows"]
    assert {(r_["kv_dtype"], r_["attn_impl"]) for r_ in rows} >= {
        ("bf16", "pallas_interpret"), ("int8", "pallas_interpret")}
    assert by_bench["serving"]["result"]["impls"]["engine"] == "mocker"


def test_run_round_only_subset_and_impl_flag_vocab():
    """--only serving keeps the driver to one bench, and the serving
    bench's impl-stamp flag vocabulary (kept as literals so the mocker
    bench stays jax-free) must still cover the canonical impl tuples —
    the parity the bench's comment promises."""
    from dynamo_tpu.ops.fused_sampling import EPILOGUE_MODES
    from dynamo_tpu.ops.packed_prefill import PACKED_IMPLS
    from dynamo_tpu.ops.paged_attention import DECODE_IMPLS

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "bench_serving.py"), "--help"],
        capture_output=True, text=True, env=env, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0
    for impl in (*PACKED_IMPLS, *DECODE_IMPLS, *EPILOGUE_MODES):
        assert impl in r.stdout, f"--help missing impl choice {impl!r}"
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "run_round.py"), "--mode", "smoke",
         "--only", "serving"],
        capture_output=True, text=True, env=env, timeout=300, cwd=REPO,
    )
    assert r2.returncode == 0, (r2.stdout[-2000:], r2.stderr[-2000:])
    lines = [json.loads(line) for line in r2.stdout.splitlines()
             if line.startswith("{")]
    assert [rep["bench"] for rep in lines] == ["serving"]


def test_bench_planner_loop_ab_closed_beats_static():
    """bench_planner_loop --policy ab at smoke scale: the closed loop
    must hold the latency targets with FEWER worker-seconds than static
    max-provisioning and zero errors — the bench itself exits 1 when
    the verdict fails, so the returncode is the acceptance gate.  The
    swing is shortened (10s) but keeps the 10× trough→peak ratio; the
    latency targets are generous because CI CPUs carry suite-parallel
    contention."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "bench_planner_loop.py"),
         "--policy", "ab", "--duration-s", "10", "--rate-low", "0.4",
         "--rate-high", "4.0", "--max-replicas", "3",
         "--slo-ttft-ms", "2000", "--slo-itl-ms", "500"],
        capture_output=True, text=True, env=env, timeout=300, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    lines = [json.loads(line) for line in r.stdout.splitlines()
             if line.startswith("{")]
    by_cfg = {}
    for rep in lines:
        by_cfg.setdefault(rep["config"], []).append(rep)
    (v,) = by_cfg["planner_loop_ab"]
    assert v["ok"] is True, v
    assert v["closed_worker_seconds"] < v["static_worker_seconds"]
    closed = next(r for r in by_cfg["planner_loop"]
                  if r["policy"] == "closed")
    assert closed["errors"] == 0
    # the loop actually moved: at least one scale action happened
    assert sum(closed.get("actions", {}).values()) >= 1, closed
