"""MLA / DeepSeek family: paged latent-cache attention vs a dense
non-absorbed oracle, chunked-prefill equivalence, fused decode, MoE with
shared experts, and the engine serving the family end-to-end.

Mirrors tests/test_engine.py's shape: an independent full-attention
reference implementation is ground truth for the paged + weight-absorbed
serving path."""


import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.models import get_family
from dynamo_tpu.models.deepseek import (
    DeepseekConfig,
    _ds_ffn,
    _kv_latent,
    _q_proj,
    decode,
    decode_multi,
    init_params,
    kv_cache_shapes,
    prefill,
    prefill_batched,
)
from dynamo_tpu.models.llama import rms_norm
from dynamo_tpu.protocols import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

MLA32 = DeepseekConfig(
    name="mla32", vocab_size=256, d_model=64, n_layers=2, n_heads=4,
    q_lora_rank=24, kv_lora_rank=32, qk_nope_head_dim=16,
    qk_rope_head_dim=8, v_head_dim=16, ffn_dim=128, dtype=jnp.float32,
)
MLA32_MOE = DeepseekConfig(
    name="mla32-moe", vocab_size=256, d_model=64, n_layers=3, n_heads=4,
    kv_lora_rank=32, qk_nope_head_dim=16, qk_rope_head_dim=8,
    v_head_dim=16, ffn_dim=128, moe_ffn_dim=64, n_experts=4,
    experts_per_token=2, n_shared_experts=1, first_k_dense=1,
    routed_scaling_factor=1.5, dtype=jnp.float32,
)


def dense_mla_logits(params, cfg, token_ids):
    """Independent oracle: full-sequence MLA attention with per-head K/V
    MATERIALIZED (non-absorbed, no paging).  Shares only the projection
    helpers with the implementation under test."""
    T = len(token_ids)
    x = params["embedding"][jnp.asarray(token_ids)].astype(cfg.dtype)
    positions = jnp.arange(T)
    scale = 1.0 / np.sqrt(cfg.qk_head_dim)
    for layer in params["layers"]:
        h = rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
        q_nope, q_rope = _q_proj(layer, cfg, h, positions)  # [T,nh,*]
        c, kr = _kv_latent(layer, cfg, h, positions)        # [T,R],[T,dr]
        k_nope = jnp.einsum("tr,hrd->thd", c.astype(jnp.float32),
                            layer["w_uk"].astype(jnp.float32))
        v = jnp.einsum("tr,hrd->thd", c.astype(jnp.float32),
                       layer["w_uv"].astype(jnp.float32))
        q = jnp.concatenate(
            [q_nope.astype(jnp.float32), q_rope.astype(jnp.float32)], -1)
        k = jnp.concatenate(
            [k_nope,
             jnp.broadcast_to(kr.astype(jnp.float32)[:, None, :],
                              (T, cfg.n_heads, cfg.qk_rope_head_dim))], -1)
        s = jnp.einsum("ihd,jhd->hij", q, k) * scale
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("hij,jhd->ihd", p, v)
        x = x + o.reshape(T, -1).astype(cfg.dtype) @ layer["wo"]
        h = rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
        x = x + _ds_ffn(layer, cfg, h)
    x = rms_norm(x, params["final_norm"]["norm"], cfg.rms_eps)
    return (x @ params["lm_head"]).astype(jnp.float32)


def fresh_cache(cfg, num_blocks=32, block_size=4):
    ks, vs = kv_cache_shapes(cfg, num_blocks, block_size)
    return jnp.zeros(ks, cfg.dtype), jnp.zeros(vs, cfg.dtype)


def rollout_paged(params, cfg, prompt, n_steps, chunks=None,
                  block_size=4):
    """Greedy autoregressive rollout through the paged prefill+decode path
    (optionally chunked prefill).  Returns generated tokens."""
    kv = fresh_cache(cfg, block_size=block_size)
    table = jnp.arange(1, 17, dtype=jnp.int32)[None]  # blocks 1..16
    chunks = chunks or [len(prompt)]
    pos = 0
    toks = []
    for ch in chunks:
        chunk = prompt[pos:pos + ch]
        logits, kv = prefill(
            params, cfg, kv, jnp.asarray(chunk, jnp.int32),
            jnp.arange(pos, pos + ch, dtype=jnp.int32), table[0],
            jnp.int32(pos), jnp.int32(ch),
        )
        pos += ch
    last = int(jnp.argmax(logits))
    toks.append(last)
    for _ in range(n_steps - 1):
        logits, kv = decode(
            params, cfg, kv, jnp.asarray([last], jnp.int32),
            jnp.asarray([pos], jnp.int32), table,
            jnp.asarray([pos], jnp.int32),
        )
        last = int(jnp.argmax(logits[0]))
        toks.append(last)
        pos += 1
    return toks


def oracle_rollout(params, cfg, prompt, n_steps):
    seq = list(prompt)
    out = []
    for _ in range(n_steps):
        logits = dense_mla_logits(params, cfg, seq)
        t = int(jnp.argmax(logits[-1]))
        out.append(t)
        seq.append(t)
    return out


def test_mla_paged_matches_dense_oracle():
    params = init_params(MLA32, jax.random.PRNGKey(3))
    prompt = [5, 9, 13, 2, 7, 11, 3, 1, 8, 20]  # crosses block boundary
    # 3 steps: every oracle step is a fresh dense shape — a fresh XLA
    # compile — so each extra step costs seconds of tier-1 wall clock
    got = rollout_paged(params, MLA32, prompt, 3)
    want = oracle_rollout(params, MLA32, prompt, 3)
    assert got == want


def test_mla_chunked_prefill_equivalence():
    """Prefill in 3 chunks (prefix-cache / chunked path, ctx_len>0) must
    generate identically to one-shot prefill."""
    params = init_params(MLA32, jax.random.PRNGKey(4))
    prompt = list(range(40, 52))  # 12 tokens
    one = rollout_paged(params, MLA32, prompt, 5)
    chunked = rollout_paged(params, MLA32, prompt, 5, chunks=[4, 4, 4])
    assert one == chunked


def test_mla_moe_paged_matches_dense_oracle():
    """DeepSeekMoE layers (shared + routed, scaled) through the paged
    path vs the oracle."""
    params = init_params(MLA32_MOE, jax.random.PRNGKey(5))
    prompt = [3, 17, 44, 9, 100, 55, 8]
    # 3 steps, same per-step oracle-compile rationale as above
    got = rollout_paged(params, MLA32_MOE, prompt, 3)
    want = oracle_rollout(params, MLA32_MOE, prompt, 3)
    assert got == want


def test_mla_decode_multi_matches_single_steps():
    params = init_params(MLA32, jax.random.PRNGKey(6))
    prompt = [10, 20, 30, 40, 50]
    kv = fresh_cache(MLA32)
    table = jnp.arange(1, 17, dtype=jnp.int32)[None]
    logits, kv = prefill(
        params, MLA32, kv, jnp.asarray(prompt, jnp.int32),
        jnp.arange(len(prompt), dtype=jnp.int32), table[0],
        jnp.int32(0), jnp.int32(len(prompt)),
    )
    first = jnp.argmax(logits)[None].astype(jnp.int32)
    pos = len(prompt)
    burst, _ = decode_multi(
        params, MLA32, kv, first, jnp.asarray([pos], jnp.int32),
        table, jnp.asarray([pos], jnp.int32), 4,
    )
    single = rollout_paged(params, MLA32, prompt, 5)
    assert [int(first[0])] + [int(t) for t in burst[:, 0]] == single


def test_mla_prefill_batched_matches_single():
    params = init_params(MLA32, jax.random.PRNGKey(7))
    kv = fresh_cache(MLA32)
    prompts = [[4, 8, 15, 16], [23, 42, 7, 99, 3, 12]]
    T = 8
    toks = jnp.zeros((2, T), jnp.int32)
    tables = jnp.stack([jnp.arange(1, 17, dtype=jnp.int32),
                        jnp.arange(17, 33, dtype=jnp.int32)])
    for i, p in enumerate(prompts):
        toks = toks.at[i, :len(p)].set(jnp.asarray(p, jnp.int32))
    logits_b, _ = prefill_batched(
        params, MLA32, kv,
        toks, jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (2, T)),
        tables, jnp.zeros((2,), jnp.int32),
        jnp.asarray([len(p) for p in prompts], jnp.int32),
    )
    for i, p in enumerate(prompts):
        kv1 = fresh_cache(MLA32)
        logits_1, _ = prefill(
            params, MLA32, kv1, jnp.asarray(p, jnp.int32),
            jnp.arange(len(p), dtype=jnp.int32), tables[i],
            jnp.int32(0), jnp.int32(len(p)),
        )
        np.testing.assert_allclose(np.asarray(logits_b[i]),
                                   np.asarray(logits_1),
                                   rtol=2e-4, atol=2e-4)


async def test_engine_serves_mla_family():
    """JaxEngine end-to-end on the MLA family via get_family dispatch:
    greedy generations equal the oracle's teacher-forced argmax."""
    eng = JaxEngine(EngineConfig(
        model_config=MLA32, block_size=4, num_blocks=128,
        max_blocks_per_seq=16, max_num_seqs=4,
        prefill_buckets=(8, 16, 32, 64), seed=7,
    ))
    assert get_family(eng.model_cfg).__name__.endswith("deepseek")
    prompt = [5, 9, 13, 2, 7, 11, 3, 1, 8, 20]
    req = PreprocessedRequest(
        token_ids=prompt, request_id="mla0",
        sampling=SamplingOptions(temperature=0.0, seed=0),
        stop=StopConditions(max_tokens=6, ignore_eos=True),
    )
    toks = []
    async for out in eng.generate(req):
        toks.extend(out.token_ids)
    assert len(toks) == 6
    seq = list(prompt)
    for t in toks:
        logits = dense_mla_logits(eng.params, MLA32, seq)
        assert int(jnp.argmax(logits[-1])) == t, \
            f"divergence at position {len(seq)}"
        seq.append(t)
    await eng.close()


def test_deepseek_presets_resolve():
    cfg = EngineConfig(model="tiny-mla").resolve_model()
    assert isinstance(cfg, DeepseekConfig)
    r1 = EngineConfig(model="deepseek-r1").resolve_model()
    assert r1.n_experts == 256 and r1.kv_lora_rank == 512
