"""Pallas paged-attention decode kernel vs the jnp reference path.

The two implementations are interchangeable (ops/paged_attention.py
dispatch); these tests pin that equivalence on randomized shapes, including
GQA grouping, partial blocks, garbage-block padding, and multi-chunk
contexts (forcing the double-buffered DMA loop through >1 iteration).
Runs the kernel under the Pallas interpreter so CPU CI covers it; the same
code path compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks


from dynamo_tpu.ops.paged_attention import paged_attention_decode_jnp
from dynamo_tpu.ops.pallas_paged_attention import paged_attention_decode_pallas


def _mk_case(rng, *, B, nkv, group, hd, bs, max_blocks, L=2, dtype=jnp.float32):
    num_blocks = 1 + B * max_blocks  # block 0 is garbage
    shape = (L, nkv, num_blocks, hd, bs)  # transposed block layout
    k_cache = jnp.asarray(rng.standard_normal(shape), dtype)
    v_cache = jnp.asarray(rng.standard_normal(shape), dtype)
    q = jnp.asarray(rng.standard_normal((B, nkv * group, hd)), dtype)
    # each sequence owns a disjoint set of physical blocks, shuffled so
    # gathers are genuinely scattered
    tables = np.zeros((B, max_blocks), np.int32)
    perm = rng.permutation(num_blocks - 1) + 1
    for b in range(B):
        tables[b] = perm[b * max_blocks:(b + 1) * max_blocks]
    kv_lens = rng.integers(1, max_blocks * bs + 1, B).astype(np.int32)
    # zero-out table entries beyond each sequence's context (garbage block)
    for b in range(B):
        used = -(-int(kv_lens[b]) // bs)
        tables[b, used:] = 0
    return q, k_cache, v_cache, jnp.asarray(tables), jnp.asarray(kv_lens)


@pytest.mark.parametrize("case", [
    dict(B=2, nkv=2, group=1, hd=16, bs=4, max_blocks=4),    # MHA-ish
    dict(B=3, nkv=2, group=4, hd=32, bs=8, max_blocks=6),    # GQA
    dict(B=1, nkv=1, group=8, hd=64, bs=16, max_blocks=9),   # MQA, odd blocks
])
def test_pallas_matches_jnp(case):
    rng = np.random.default_rng(42)
    q, kc, vc, tables, kv_lens = _mk_case(rng, **case)
    for layer in range(2):
        ref = paged_attention_decode_jnp(q, kc, vc, layer, tables, kv_lens)
        out = paged_attention_decode_pallas(
            q, kc, vc, layer, tables, kv_lens, interpret=True
        )
        # 1e-4: the kernel's online softmax accumulates per chunk (not
        # per whole context), so f32 sums reassociate
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )


def test_pallas_matches_jnp_multichunk():
    """Context long enough that the kernel's chunk loop runs > 1 iteration
    (blocks_per_chunk forced small), exercising double-buffer slot reuse."""
    rng = np.random.default_rng(7)
    q, kc, vc, tables, kv_lens = _mk_case(
        rng, B=2, nkv=2, group=2, hd=16, bs=4, max_blocks=8
    )
    kv_lens = jnp.asarray([29, 32], jnp.int32)  # partial + full final block
    ref = paged_attention_decode_jnp(q, kc, vc, 0, tables, kv_lens)
    out = paged_attention_decode_pallas(
        q, kc, vc, 0, tables, kv_lens, blocks_per_chunk=2, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
    )


def test_pallas_bf16_tolerance():
    rng = np.random.default_rng(3)
    q, kc, vc, tables, kv_lens = _mk_case(
        rng, B=2, nkv=2, group=2, hd=32, bs=8, max_blocks=4,
        dtype=jnp.bfloat16,
    )
    ref = paged_attention_decode_jnp(q, kc, vc, 1, tables, kv_lens)
    out = paged_attention_decode_pallas(
        q, kc, vc, 1, tables, kv_lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=0.05, atol=0.05,
    )


def test_pallas_tp_sharded_matches_jnp():
    """The kernel under shard_map over a tp>1 mesh (each shard owning its
    kv-head slice) must match the unsharded jnp oracle — the path multi-chip
    decode takes so tp>1 keeps the fast path (round-2 verdict weak #1)."""
    from jax.sharding import PartitionSpec as P

    from dynamo_tpu.ops.paged_attention import paged_attention_decode
    from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

    rng = np.random.default_rng(11)
    q, kc, vc, tables, kv_lens = _mk_case(
        rng, B=3, nkv=4, group=2, hd=16, bs=4, max_blocks=5
    )
    mesh = make_mesh(MeshConfig(dp=2, tp=4))  # 8 virtual CPU devices
    ref = paged_attention_decode_jnp(q, kc, vc, 1, tables, kv_lens)
    spec = jax.sharding.NamedSharding(
        mesh, P(None, "tp", None, None, None))
    with mesh:
        # place the cache tp-sharded as the engine does, q replicated (the
        # shard_map in_specs reshard q to its head slice per device)
        kc_s = jax.device_put(kc, spec)
        vc_s = jax.device_put(vc, spec)
        out = jax.jit(
            lambda q_, kc_, vc_, t_, l_: paged_attention_decode(
                q_, kc_, vc_, 1, t_, l_, impl="pallas_interpret", mesh=mesh)
        )(q, kc_s, vc_s, tables, kv_lens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def _tpu_devices():
    try:
        return jax.devices("tpu")
    except RuntimeError:
        return []


@pytest.mark.skipif(not _tpu_devices(),
                    reason="needs a TPU (compiled-kernel cross-check)")
def test_pallas_kernel_compiled_matches_jnp_uneven_kv_lens():
    """COMPILED (non-interpret) kernel vs the jnp reference on real TPU
    hardware, with uneven kv_lens across a multi-sequence batch.  The
    interpreter tests above cannot catch Mosaic-level regressions, and
    impl="auto" no longer routes serving traffic through the kernel (it
    selects the jnp path) — without this gate the compiled kernel could
    silently rot."""
    rng = np.random.default_rng(0)
    B, nkv, group, hd, bs, max_blocks = 4, 2, 4, 128, 128, 4
    num_blocks = 1 + B * max_blocks
    shape = (2, nkv, num_blocks, hd, bs)
    kc = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    vc = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((B, nkv * group, hd)),
                    jnp.bfloat16)
    # uneven contexts incl. partial blocks and a single-block sequence
    kv_lens = np.asarray([500, 512, 37, 129], np.int32)
    tables = np.zeros((B, max_blocks), np.int32)
    perm = rng.permutation(num_blocks - 1) + 1
    for b in range(B):
        used = -(-int(kv_lens[b]) // bs)
        tables[b, :used] = perm[b * max_blocks:b * max_blocks + used]
    tables = jnp.asarray(tables)
    kv_lens = jnp.asarray(kv_lens)
    for layer in range(2):
        ref = paged_attention_decode_jnp(q, kc, vc, layer, tables,
                                         kv_lens)
        out = paged_attention_decode_pallas(q, kc, vc, layer, tables,
                                            kv_lens, interpret=False)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=0.05, atol=0.05,
        )


async def test_engine_greedy_with_pallas_attention():
    """End-to-end: the engine produces identical greedy tokens with the
    Pallas decode path (interpret mode) and the jnp path."""
    from dataclasses import replace

    from test_engine import FP32, collect, greedy_req

    from dynamo_tpu.engine import EngineConfig, JaxEngine

    prompt = [5, 9, 13, 2, 7, 11, 3, 1, 8, 20]

    async def run(impl):
        cfg = EngineConfig(
            model_config=replace(FP32, attn_impl=impl), block_size=4,
            num_blocks=64, max_blocks_per_seq=8, max_num_seqs=2,
            prefill_buckets=(8, 16), seed=7, decode_fused_steps=1,
        )
        eng = JaxEngine(cfg)
        # 4 tokens crosses a block boundary (block_size=4); fused_steps=1
        # keeps the ladder to one interpret-mode compile (~7s/rung on CPU)
        toks = await collect(eng, greedy_req(list(prompt), 4, f"pl-{impl}"))
        await eng.close()
        return toks

    pallas_toks = await run("pallas_interpret")
    jnp_toks = await run("jnp")
    # a crashed engine yields an empty stream — equality alone is vacuous
    assert len(jnp_toks) == 4  # max_tokens generated (first + 3 decode)
    assert pallas_toks == jnp_toks


async def test_engine_tp2_keeps_pallas_fast_path():
    """Under tp>1 the engine must NOT silently fall back to jnp (round-2
    verdict weak #1): the Pallas kernel runs via shard_map and produces the
    same greedy tokens as the unsharded jnp engine."""
    from dataclasses import replace

    from test_engine import FP32, collect, greedy_req

    from dynamo_tpu.engine import EngineConfig, JaxEngine

    prompt = [5, 9, 13, 2, 7, 11, 3, 1, 8, 20]

    async def run(impl, tp):
        cfg = EngineConfig(
            model_config=replace(FP32, attn_impl=impl), block_size=4,
            num_blocks=64, max_blocks_per_seq=8, max_num_seqs=2,
            prefill_buckets=(8, 16), seed=7, tp=tp, decode_fused_steps=1,
        )
        eng = JaxEngine(cfg)
        assert eng.model_cfg.attn_impl == impl  # no silent downgrade
        toks = await collect(eng, greedy_req(list(prompt), 4, f"tp-{impl}"))
        await eng.close()
        return toks

    sharded = await run("pallas_interpret", tp=2)
    ref = await run("jnp", tp=1)
    assert len(ref) == 4
    assert sharded == ref
