"""Fleet introspection plane: the per-process /debug/state + /debug/profile
admin surface (DYN_ADMIN_TOKEN-gated, both worker types + frontend), the
discovery-driven fleet aggregator (obs/fleet.py) with its stale/unreachable
degradation, the dynamo_fleet_* scrape contract, and the planner's
fleet-signal diag."""

import asyncio
import json
import os
import select
import signal
import socket
import subprocess
import sys
import time
import uuid

import aiohttp
import pytest

from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.obs import fleet as obs_fleet
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig
from dynamo_tpu.runtime.metrics import MetricsHierarchy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOKEN = "fleet-test-token"


def fresh_runtime(**cfg_kw) -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc",
                        **cfg_kw)
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def admin_get(url: str, token=TOKEN):
    headers = {"X-Dyn-Admin-Token": token} if token else {}
    async with aiohttp.ClientSession() as s:
        async with s.get(url, headers=headers) as r:
            body = await r.read()
            try:
                return r.status, json.loads(body)
            except json.JSONDecodeError:
                return r.status, body


# --------------------- per-process debug surface -----------------------------


async def test_debug_state_token_gated_and_dumps_mocker_state():
    """/debug/state: 401 without/with-wrong token, full dump with the
    right one — scheduler seqs, KV occupancy, drain status, effective
    config, compile stats — for the mocker worker type."""
    rt = await fresh_runtime(system_port=-1, admin_token=TOKEN).start()
    assert rt.system_address, "ephemeral system port must be advertised"
    worker = await MockerWorker(
        rt, MockEngineArgs(model_name="m", block_size=4,
                           base_step_s=0.0005)).start()
    url = f"http://{rt.system_address}/debug/state"
    try:
        status, _ = await admin_get(url, token=None)
        assert status == 401
        status, _ = await admin_get(url, token="wrong")
        assert status == 401
        status, state = await admin_get(url)
        assert status == 200
        assert state["worker_id"] == rt.worker_id
        assert state["config"]["admin_token"] == "***"  # never leaked
        src = state["sources"][f"worker:{worker.served.instance_id}"]
        assert src["kind"] == "mocker"
        assert src["instance_id"] == worker.served.instance_id
        assert src["draining"] is False
        assert src["kv"]["g1"]["capacity"] > 0
        assert "slots" in src and "waiting" in src
        assert "compile" in src and "config" in src
        # drain status flows through live
        worker.engine.draining = True
        _, state2 = await admin_get(url)
        assert state2["sources"][
            f"worker:{worker.served.instance_id}"]["draining"] is True
        # flight-recorder tail: off by default, spans when tracing is on
        assert state2["flight"]["enabled"] is False
        from dynamo_tpu import obs

        tr = obs.Tracer().install()
        try:
            t0 = obs.begin()
            obs.end("step", t0, track="sched:test")
            _, state3 = await admin_get(url + "?spans=8")
            assert state3["flight"]["enabled"] is True
            kinds = [s["kind"] for s in state3["flight"]["spans"]]
            assert "step" in kinds
        finally:
            tr.uninstall()
    finally:
        await worker.close()
        await rt.shutdown()
    # close() must unregister the debug source
    assert not rt.debug_sources


async def test_debug_state_without_admin_token_is_403():
    """Fail closed: no DYN_ADMIN_TOKEN on the process means the admin
    surface stays off (403 explains why), while /health /metrics serve."""
    rt = await fresh_runtime(system_port=-1).start()
    try:
        base = f"http://{rt.system_address}"
        status, body = await admin_get(f"{base}/debug/state", token="x")
        assert status == 403 and "DYN_ADMIN_TOKEN" in body["error"]
        status, _ = await admin_get(f"{base}/debug/profile", token="x")
        assert status == 403
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/health") as r:
                assert r.status == 200
            async with s.get(f"{base}/metrics") as r:
                assert r.status == 200
    finally:
        await rt.shutdown()


async def test_debug_profile_captures_trace_and_memory(tmp_path,
                                                       monkeypatch):
    """/debug/profile: a time-bounded jax.profiler capture + device
    memory snapshot land under DYN_PROFILE_DIR; CPU-safe."""
    monkeypatch.setenv("DYN_PROFILE_DIR", str(tmp_path))
    rt = await fresh_runtime(system_port=-1, admin_token=TOKEN).start()
    try:
        url = f"http://{rt.system_address}/debug/profile?duration_s=0.1"
        status, prof = await admin_get(url)
        assert status == 200
        assert prof["status"] == "ok", prof
        assert prof["backend"] == "cpu"
        assert os.path.isdir(prof["trace_dir"])
        if "memory_profile" in prof:
            assert os.path.exists(prof["memory_profile"])
        # bad duration is a 400, not a crash
        status, _ = await admin_get(
            f"http://{rt.system_address}/debug/profile?duration_s=nan2",
            token=TOKEN)
        assert status == 400
    finally:
        await rt.shutdown()


# real JAX engine in an async body: -O0 compiles dwarf the slow-callback
# gate (see conftest)
@pytest.mark.allow_slow_callbacks
async def test_debug_state_jax_worker():
    """The JAX engine worker serves the same /debug/state contract:
    engine kind, per-tier KV occupancy, slots, compile stats."""
    import jax.numpy as jnp

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.worker import JaxEngineWorker
    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    tiny = LlamaConfig(name="tiny32", vocab_size=256, d_model=64,
                       n_layers=2, n_heads=4, n_kv_heads=2, head_dim=16,
                       ffn_dim=128, dtype=jnp.float32)
    rt = await fresh_runtime(system_port=-1, admin_token=TOKEN).start()
    worker = await JaxEngineWorker(rt, EngineConfig(
        model_config=tiny, block_size=4, num_blocks=64,
        max_blocks_per_seq=16, max_num_seqs=2,
        prefill_buckets=(8, 16, 32), seed=7)).start()
    client = await (rt.namespace("dynamo").component("backend")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    try:
        req = PreprocessedRequest(
            token_ids=list(range(3, 20)), request_id="r1",
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=4, ignore_eos=True))
        async for _ in client.generate(req.to_dict()):
            pass
        status, state = await admin_get(
            f"http://{rt.system_address}/debug/state")
        assert status == 200
        src = state["sources"][f"worker:{worker.served.instance_id}"]
        assert src["kind"] == "engine"
        assert src["kv"]["g1"]["capacity"] == 63  # block 0 is garbage
        assert src["kv"]["g1"]["used"] + src["kv"]["g1"]["free"] == 63
        assert src["engine_metrics"]["requests"] == 1
        assert src["config"]["total_kv_blocks"] == 64
        assert isinstance(src["slots"], list)
        assert src["compile"]["total"] >= 0
    finally:
        await client.close()
        await worker.close()
        await rt.shutdown()


# --------------------- aggregator: reduction + gauges ------------------------


def _mk_state(iid, toks=0, active=0, itl_p95=0.0, free=90, cap=100,
              draining=False, serving_compiles=0):
    return {
        "kind": "mocker", "instance_id": iid, "active_seqs": active,
        "tokens_in_flight": toks, "itl_p95_s": itl_p95,
        "kv": {"g1": {"used": cap - free, "free": free, "capacity": cap}},
        "kv_usage": (cap - free) / cap, "draining": draining,
        "compile": {"total": serving_compiles,
                    "serving": serving_compiles,
                    "families": ({"decode": {"count": serving_compiles,
                                             "seconds": 0.1,
                                             "serving": serving_compiles}}
                                 if serving_compiles else {})},
    }


def test_summarize_states_imbalance_straggler_headroom():
    states = [
        _mk_state(1, toks=300, active=6, itl_p95=0.010, free=10, cap=100),
        _mk_state(2, toks=100, active=2, itl_p95=0.050, free=80, cap=100,
                  serving_compiles=3),
        _mk_state(3, toks=200, active=4, itl_p95=0.012, free=50, cap=100,
                  draining=True),
    ]
    s = obs_fleet.summarize_states(states, stale=1, unreachable=2)
    assert s["workers"] == 6 and s["live"] == 3
    assert s["stale"] == 1 and s["unreachable"] == 2
    assert s["imbalance"] == pytest.approx(300 / 200)
    # median itl_p95 = 0.012; worker 2 at 0.050 > 2x median
    assert s["stragglers"] == [2] and s["straggler_count"] == 1
    assert s["kv_headroom_min"] == pytest.approx(0.10)
    assert s["serving_compile_hotspots"] == {"decode": 3}
    assert s["draining"] == 1
    assert s["tokens_in_flight"]["max"] == 300
    # goodput spread across frontends
    s2 = obs_fleet.summarize_states(states, frontend_states=[
        {"slo": {"goodput": 0.9}}, {"slo": {"goodput": 0.5}}])
    assert s2["goodput"]["spread"] == pytest.approx(0.4)
    # a partially-scraped worker folds its data into the reduction but
    # counts under stale, not live — worker counts stay disjoint
    s3 = obs_fleet.summarize_states(
        states[:2], stale=1, stale_states=[states[2]])
    assert s3["workers"] == 3 and s3["live"] == 2 and s3["stale"] == 1
    assert s3["draining"] == 1          # the stale worker's drain flag
    assert s3["tokens_in_flight"]["total"] == 600  # its load counted


def test_fleet_gauges_scrape_contract():
    """Every dynamo_fleet_* family parses with the prometheus parser,
    is dynamo_-prefixed, and per-instance families carry a `worker`
    label; labels of departed workers are removed on re-export."""
    from prometheus_client.parser import text_string_to_metric_families

    def view(iid, state="live", dbg=True):
        return obs_fleet.WorkerView(
            worker_id=iid, kind="mocker", namespace="dynamo",
            component="backend", endpoint="generate", address="h:1",
            system_addr="h:2", state=state,
            debug=_mk_state(iid, toks=10 * iid, active=iid,
                            itl_p95=0.01) if dbg else None)

    snap = obs_fleet.FleetSnapshot(
        ts_unix=0.0,
        workers=[view(1), view(2), view(3, "unreachable", dbg=False)],
        frontends=[],
        summary=obs_fleet.summarize_states(
            [_mk_state(1, toks=10), _mk_state(2, toks=20)],
            unreachable=1))
    m = MetricsHierarchy(namespace="dynamo", component="fleet")
    prev = obs_fleet.export_fleet_gauges(m, snap)
    assert prev == {"1", "2", "3"}
    text = m.render().decode()
    families = list(text_string_to_metric_families(text))
    assert families
    bad = [f.name for f in families if not f.name.startswith("dynamo_")]
    assert not bad, bad
    fleet_fams = {f.name: f for f in families
                  if f.name.startswith("dynamo_fleet_")}
    assert set(obs_fleet.PER_WORKER_FAMILIES) <= set(fleet_fams)
    for name in obs_fleet.PER_WORKER_FAMILIES:
        for sample in fleet_fams[name].samples:
            assert "worker" in sample.labels, (name, sample)
    # the unreachable worker exports up=0 and nothing else
    ups = {s.labels["worker"]: s.value
           for s in fleet_fams["dynamo_fleet_up"].samples}
    assert ups == {"1": 1.0, "2": 1.0, "3": 0.0}
    assert {s.labels["state"]: s.value
            for s in fleet_fams["dynamo_fleet_workers"].samples} == {
        "live": 2.0, "stale": 0.0, "unreachable": 1.0, "draining": 0.0,
        "quarantined": 0.0}
    # worker 3 leaves the fleet: its labels must not freeze in place
    snap2 = obs_fleet.FleetSnapshot(
        ts_unix=1.0, workers=[view(1), view(2)], frontends=[],
        summary=obs_fleet.summarize_states(
            [_mk_state(1, toks=10), _mk_state(2, toks=20)],
            frontend_states=[{"slo": {"goodput": 0.8}},
                             {"slo": {"goodput": 0.6}}]))
    obs_fleet.export_fleet_gauges(m, snap2, prev)
    text2 = m.render().decode()
    assert 'worker="3"' not in text2
    assert 'worker="1"' in text2
    assert "dynamo_fleet_goodput_spread" in text2
    # all frontends gone: the goodput gauges must not freeze their last
    # value into future scrapes
    snap3 = obs_fleet.FleetSnapshot(
        ts_unix=2.0, workers=[view(1), view(2)], frontends=[],
        summary=obs_fleet.summarize_states(
            [_mk_state(1, toks=10), _mk_state(2, toks=20)]))
    obs_fleet.export_fleet_gauges(m, snap3, {"1", "2"})
    # the HELP/TYPE declarations survive; the SAMPLES must not
    text3 = m.render().decode()
    assert not [ln for ln in text3.splitlines()
                if ln.startswith(("dynamo_fleet_goodput_spread{",
                                  "dynamo_fleet_goodput_min{"))]


async def test_scrape_4xx_fails_fast_without_retry():
    """A 401/403 scrape (wrong admin token) is deterministic: it must
    fail the surface on the FIRST attempt, not re-hit every worker
    under the retry policy on every snapshot."""
    from aiohttp import ClientSession, web

    hits = {"n": 0}

    async def unauthorized(request):
        hits["n"] += 1
        return web.json_response({"error": "unauthorized"}, status=401)

    app = web.Application()
    app.router.add_get("/debug/state", unauthorized)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    try:
        async with ClientSession() as session:
            with pytest.raises(obs_fleet.PermanentScrapeError):
                await obs_fleet._fetch(
                    session, f"http://127.0.0.1:{port}/debug/state", {},
                    timeout_s=2.0)
        assert hits["n"] == 1
    finally:
        await runner.cleanup()


# --------------------- planner diag ------------------------------------------


class _StaticConnector:
    def __init__(self, n):
        self.n = n

    async def current_replicas(self):
        return self.n

    async def scale(self, n):
        self.n = n
        return n


async def test_planner_diag_carries_fleet_signals_after_skewed_burst():
    """Two mocker workers on one runtime; a skewed burst parks load on
    worker A only.  The FleetObserver's merged scrape shows the
    imbalance, and the planner tick folds it into diag — the inputs
    ROADMAP item 4's controller and item 2's cost function read."""
    from dynamo_tpu.planner import Planner, PlannerConfig
    from dynamo_tpu.protocols import PreprocessedRequest, StopConditions

    rt = await fresh_runtime(system_port=-1, admin_token=TOKEN).start()
    args = MockEngineArgs(model_name="m", block_size=4, base_step_s=0.002,
                          decode_s_per_seq=0.0005)
    w1 = await MockerWorker(rt, args).start()
    w2 = await MockerWorker(rt, args).start()
    fleet = obs_fleet.FleetObserver(runtime=rt, token=TOKEN,
                                    interval_s=60.0)  # manual refresh
    planner = Planner(rt, "dynamo", "mocker",
                      _StaticConnector(2),
                      PlannerConfig(target_active_per_replica=100.0),
                      fleet=fleet)
    await planner.observer.start()

    async def consume(gen):
        async for _ in gen:
            pass

    burst = []
    try:
        # skewed burst: all streams pinned to worker A's engine
        for i in range(4):
            req = PreprocessedRequest(
                token_ids=list(range(16)), request_id=f"r{i}",
                stop=StopConditions(max_tokens=200, ignore_eos=True))
            burst.append(asyncio.create_task(
                consume(w1.engine.generate(req))))
        # wait until A is visibly loaded and B idle, and the load
        # observer has samples (tick holds without them)
        for _ in range(100):
            await asyncio.sleep(0.05)
            if (w1.engine.num_active_seqs >= 3
                    and len(planner.observer.samples) >= 2):
                break
        snap = await fleet.refresh()
        assert snap.summary["live"] == 2
        assert snap.summary["imbalance"] > 1.5, snap.summary
        await planner.tick()
        assert planner.last_diag["fleet_imbalance"] > 1.5
        assert planner.last_diag["fleet_straggler"] >= 0
        assert 0.0 <= planner.last_diag["fleet_kv_headroom"] <= 1.0
        # the fleet gauges rode the runtime registry too
        text = rt.metrics.render().decode()
        assert "dynamo_fleet_load_imbalance" in text
    finally:
        for t in burst:
            t.cancel()
        await asyncio.gather(*burst, return_exceptions=True)
        await planner.close()
        await fleet.close()
        await w1.close()
        await w2.close()
        await rt.shutdown()


async def test_read_only_file_discovery_never_reaps(tmp_path):
    """Live-drive regression: the fleet CLI launched with a mismatched
    (shorter) DYN_LEASE_TTL used to REAP the fleet's live lease files —
    heartbeats only utime existing paths, so a reaped key never came
    back.  A read_only observer may hide entries past its own TTL but
    must never unlink them."""
    from dynamo_tpu.runtime.discovery import INSTANCE_PREFIX, FileDiscovery

    key = INSTANCE_PREFIX + "/ns/c/e/1"
    owner = FileDiscovery(str(tmp_path), ttl_s=60.0)
    observer = FileDiscovery(str(tmp_path), ttl_s=0.01, read_only=True)
    try:
        await owner.put(key, {"x": 1})
        await asyncio.sleep(0.05)  # older than the observer's TTL
        assert await observer.get_prefix(INSTANCE_PREFIX) == {}
        # ...hidden from the observer, but NOT deleted for the owner
        assert key in await owner.get_prefix(INSTANCE_PREFIX)
    finally:
        await observer.close()
        await owner.close()


# --------------------- e2e: 2-process fleet over file discovery --------------


def _wait_line(proc, needle: str, deadline_s: float) -> str:
    """Read stdout lines until `needle` appears (select-paced so a dead
    process can't block the suite)."""
    t_end = time.monotonic() + deadline_s
    buf = ""
    while time.monotonic() < t_end:
        if proc.poll() is not None:
            break
        r, _, _ = select.select([proc.stdout], [], [], 0.25)
        if not r:
            continue
        line = proc.stdout.readline()
        buf += line
        if needle in line:
            return line
    raise AssertionError(
        f"{needle!r} not seen (rc={proc.poll()}):\n{buf}\n"
        f"stderr: {proc.stderr.read() if proc.poll() is not None else ''}")


def test_fleet_e2e_two_process_mockers_and_frontend(tmp_path):
    """Acceptance path: a real 2-process mocker fleet + frontend over
    file discovery.  `python -m dynamo_tpu.obs.fleet --json` returns one
    merged snapshot with per-worker KV occupancy, load, and health;
    /debug/state enforces DYN_ADMIN_TOKEN on a real worker process; a
    SIGSTOP'd worker degrades to `unreachable` without failing the
    snapshot."""
    disco_root = str(tmp_path / "disco")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
        DYN_DISCOVERY_BACKEND="file", DYN_DISCOVERY_PATH=disco_root,
        DYN_ADMIN_TOKEN=TOKEN,
        # long lease TTL: a SIGSTOP'd worker must stay IN discovery
        # (scrape-unreachable), not expire out of the snapshot
        DYN_LEASE_TTL="120",
    )
    sys_ports = [free_port(), free_port(), free_port()]
    procs = []
    try:
        for port in sys_ports[:2]:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "dynamo_tpu.mocker",
                 "--component", "backend", "--block-size", "4"],
                env=dict(env, DYN_SYSTEM_PORT=str(port)),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, cwd=REPO))
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.frontend",
             "--host", "127.0.0.1", "--port", str(free_port())],
            env=dict(env, DYN_SYSTEM_PORT=str(sys_ports[2])),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO))
        for proc in procs:
            _wait_line(proc, "ready", 90.0)

        # -- the CLI the acceptance criterion names -----------------------
        r = subprocess.run(
            [sys.executable, "-m", "dynamo_tpu.obs.fleet", "--json"],
            env=env, capture_output=True, text=True, timeout=120,
            cwd=REPO)
        assert r.returncode == 0, r.stderr[-2000:]
        snap = json.loads(r.stdout)
        workers = snap["workers"]
        assert len(workers) == 2, workers
        assert all(w["state"] == "live" for w in workers), workers
        for w in workers:
            assert w["debug"]["kv"]["g1"]["capacity"] > 0  # KV occupancy
            assert "active_seqs" in w["debug"]              # load
            assert w["debug"]["draining"] is False          # health
        assert snap["summary"]["live"] == 2
        assert len(snap["frontends"]) == 1
        assert snap["frontends"][0]["debug"]["kind"] == "frontend"

        # -- token enforcement against a real worker process --------------
        async def check_auth():
            base = f"http://127.0.0.1:{sys_ports[0]}"
            st, _ = await admin_get(f"{base}/debug/state", token=None)
            assert st == 401
            st, state = await admin_get(f"{base}/debug/state")
            assert st == 200
            assert any(s.get("kind") == "mocker"
                       for s in state["sources"].values())
            st, prof = await admin_get(
                f"{base}/debug/profile?duration_s=0.1")
            assert st == 200 and prof["status"] in ("ok", "unavailable")

        asyncio.run(check_auth())

        # -- SIGSTOP degradation ------------------------------------------
        procs[0].send_signal(signal.SIGSTOP)
        time.sleep(0.2)

        async def stopped_snapshot():
            from dynamo_tpu.runtime.discovery import FileDiscovery

            disco = FileDiscovery(disco_root, ttl_s=120.0)
            try:
                return await obs_fleet.snapshot(disco, token=TOKEN,
                                                timeout_s=0.5)
            finally:
                await disco.close()

        snap2 = asyncio.run(stopped_snapshot())
        states = sorted(w.state for w in snap2.workers)
        assert states == ["live", "unreachable"], states
        assert snap2.summary["unreachable"] == 1
        assert snap2.summary["live"] == 1
        procs[0].send_signal(signal.SIGCONT)
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGCONT)
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
