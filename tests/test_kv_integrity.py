"""KV integrity & degraded-mode serving: checksummed cache fabric,
poisoned-source quarantine, tier circuit breakers.

Every tier-crossing consume of a persisted/transferred KV block must
verify the crc32 footer, and every verification failure must degrade to
a MISS with attribution (quarantined blob, ledger `corrupt` violation,
suspect peer) — never raise into the scheduler, never serve wrong
bytes.  The breaker suite proves a failing tier prices recompute
instead of wedging admission, and re-probes its way back.
"""

import os
import time

import numpy as np
import pytest

from dynamo_tpu import chaos
from dynamo_tpu.kvbm import object_store as obj_mod
from dynamo_tpu.kvbm.breaker import NUMERIC, TierBreaker
from dynamo_tpu.kvbm.manager import TieredKvManager
from dynamo_tpu.kvbm.object_store import ObjectStorePool
from dynamo_tpu.kvbm.pools import (
    BlockIntegrityError,
    DiskBlockPool,
    _save_block,
    block_crc,
    read_block_file,
    verify_block,
)
from dynamo_tpu.obs.kv_ledger import KvLedger


def blk(seed):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(2, 4, 2, 8)).astype(np.float32),
            rng.normal(size=(2, 4, 2, 8)).astype(np.float32))


def _write_tampered(path, arrays, mutate):
    """Persist `arrays` claiming their TRUE crc, then let `mutate`
    corrupt the payload dict before it hits disk — a valid npz whose
    footer no longer matches its bytes (bit rot / version skew), which
    only the checksum (not the npz layer) can catch."""
    payload = {}
    for name, arr in zip(("k", "v"), arrays):
        payload[name] = np.ascontiguousarray(arr).view(np.uint8).copy()
        payload[name + "d"] = str(arr.dtype)
    payload["crc"] = np.uint32(block_crc(arrays))
    mutate(payload)
    np.savez(path, **payload)


def _flip_bit(payload):
    payload["k"].reshape(-1)[0] ^= 0xFF


def _skew_dtype(payload):
    # version-skewed reader metadata: same bytes, re-viewed at a
    # different width — the crc commits to dtype, so this must fail
    payload["kd"] = np.str_("float16")


# --------------------------- canonical checksum -------------------------


def test_block_crc_commits_to_bytes_dtype_and_shape():
    k, v = blk(1)
    base = block_crc((k, v))
    assert base == block_crc((k.copy(), v.copy()))  # deterministic
    flipped = k.copy()
    flipped.view(np.uint8).reshape(-1)[0] ^= 0x01
    assert block_crc((flipped, v)) != base
    assert block_crc((k.view(np.uint8), v)) != base      # dtype committed
    assert block_crc((k.reshape(2, 4, 16), v)) != base   # shape committed
    assert block_crc((k,)) != base                       # member count


def test_save_load_round_trip_and_verify(tmp_path):
    k, v = blk(2)
    path = str(tmp_path / "b.npz")
    _save_block(path, (k, v))
    got, crc = read_block_file(path)
    assert crc is not None
    verify_block(got, crc)  # clean blob passes
    np.testing.assert_array_equal(got[0], k)
    bad = (got[0].copy(),) + got[1:]
    bad[0].view(np.uint8).reshape(-1)[0] ^= 0xFF
    with pytest.raises(BlockIntegrityError):
        verify_block(bad, crc)
    verify_block(bad, None)  # legacy blob (no footer): caller re-stamps


# --------------------------- G3 consume sites ---------------------------


@pytest.mark.parametrize("mutate", [_flip_bit, _skew_dtype],
                         ids=["bitflip", "dtype_skew"])
def test_g3_corrupt_read_quarantines_with_attribution(tmp_path, mutate):
    """A checksum-failed G3 read must degrade to a miss: entry dropped,
    file unlinked, on_corruption fired — no exception reaches the
    caller (the engine scheduler)."""
    pool = DiskBlockPool(str(tmp_path), capacity_blocks=4)
    try:
        seen = []
        pool.on_corruption = lambda h: seen.append(h)
        pool.put(7, *blk(3))
        _write_tampered(pool._path(7), blk(3), mutate)
        assert 7 in pool
        assert pool.get(7) is None
        assert seen == [7]
        assert 7 not in pool
        assert not os.path.exists(pool._path(7))  # quarantined on disk too
    finally:
        pool.close()


def test_g3_truncated_file_is_a_miss_not_a_raise(tmp_path):
    """A torn write (not valid npz at all) is an unreadable-file drop —
    the pre-checksum degradation path, distinct from corruption."""
    pool = DiskBlockPool(str(tmp_path), capacity_blocks=4)
    try:
        seen = []
        pool.on_corruption = lambda h: seen.append(h)
        pool.put(9, *blk(4))
        with open(pool._path(9), "wb") as f:
            f.write(b"PK\x03\x04 torn")
        assert pool.get(9) is None
        assert 9 not in pool
        assert seen == []  # unreadable != checksum-failed
    finally:
        pool.close()


# --------------------------- G4 consume sites ---------------------------


def test_g4_chaos_corrupt_is_caught_by_the_checksum(tmp_path):
    """The kvbm.object_io "corrupt" action tampers the payload AFTER the
    file is read — the crc verification (not the injector) must catch
    it, delete the blob fleet-wide, and raise BlockIntegrityError for
    the caller to attribute."""
    pool = ObjectStorePool(str(tmp_path))
    k, v = blk(5)
    assert pool.put(0xABC, k, v)
    plane = chaos.ChaosPlane(seed=1)
    plane.rule("kvbm.object_io", "corrupt", times=1, match="get:")
    with plane:
        with pytest.raises(BlockIntegrityError, match="quarantined"):
            pool.get(0xABC)
    assert 0xABC not in pool  # blob deleted at the source
    assert pool.get(0xABC) is None  # now a plain miss, fleet-wide


def test_g4_legacy_blob_read_once_and_restamped(tmp_path):
    """A pre-checksum blob is served once and re-stamped with the
    footer in place — the shared namespace converges to all-checksummed
    without a migration."""
    pool = ObjectStorePool(str(tmp_path))
    k, v = blk(6)
    p = pool._path(0xDEF)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    # blob paths carry no .npz suffix: write through the file handle
    # (np.savez on a bare path would append one)
    with open(p, "wb") as f:
        np.savez(f, k=np.ascontiguousarray(k).view(np.uint8),
                 kd=str(k.dtype),
                 v=np.ascontiguousarray(v).view(np.uint8),
                 vd=str(v.dtype))
    _, crc = read_block_file(p)
    assert crc is None  # really legacy
    got = pool.get(0xDEF)
    np.testing.assert_array_equal(got[0], k)
    _, crc2 = read_block_file(p)
    assert crc2 == block_crc((k, v))  # footer landed


def test_g4_legacy_blob_reaped_when_restamp_cannot_land(
        tmp_path, monkeypatch):
    """A legacy blob whose re-stamp fails must not sit unverifiable in
    the shared namespace forever: serve the one read, then reap it."""
    pool = ObjectStorePool(str(tmp_path))
    k, v = blk(7)
    p = pool._path(0x123)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    with open(p, "wb") as f:
        np.savez(f, k=np.ascontiguousarray(k).view(np.uint8),
                 kd=str(k.dtype),
                 v=np.ascontiguousarray(v).view(np.uint8),
                 vd=str(v.dtype))

    def refuse_write(*a, **kw):
        raise OSError("disk full")

    monkeypatch.setattr(obj_mod, "_save_block", refuse_write)
    got = pool.get(0x123)
    assert got is not None  # the read itself was served
    assert 0x123 not in pool  # un-restampable blob reaped
    assert not any(".tmp" in n for _, _, ns in os.walk(str(tmp_path))
                   for n in ns)


def test_g4_put_reaps_tmp_on_any_failure(tmp_path):
    """Satellite: a put that dies for ANY reason (not just OSError) must
    not orphan its tmp blob on the shared volume."""
    pool = ObjectStorePool(str(tmp_path))
    bad = np.array([object()])  # .view(np.uint8) raises TypeError
    with pytest.raises(TypeError):
        pool.put(0x777, bad)
    assert not any(".tmp" in n for _, _, ns in os.walk(str(tmp_path))
                   for n in ns)
    assert 0x777 not in pool


def test_g4_sweep_reaps_stale_tmp_but_not_live_put(tmp_path):
    """Satellite: an abandoned mid-put tmp blob (crashed writer) ages
    out after the TTL; a fresh tmp (a put in flight right now)
    survives."""
    pool = ObjectStorePool(str(tmp_path), ttl_s=5.0)
    sub = tmp_path / "ab"
    sub.mkdir()
    stale = sub / (f"{0xAB0:032x}" + ".tmpdeadbeef")
    stale.write_bytes(b"orphan")
    old = time.time() - 100.0
    os.utime(str(stale), (old, old))
    fresh = sub / (f"{0xAB1:032x}" + ".tmpcafebabe")
    fresh.write_bytes(b"in flight")
    pool.sweep()
    assert not stale.exists()
    assert fresh.exists()
    # without a pool TTL the orphan grace defaults to _TMP_TTL_S
    pool2 = ObjectStorePool(str(tmp_path))
    stale.write_bytes(b"orphan again")
    os.utime(str(stale), (old, old))
    pool2.sweep(now=time.time() + obj_mod._TMP_TTL_S)
    assert not stale.exists()


def test_g4_sweep_and_keys_survive_listing_failure(
        tmp_path, monkeypatch):
    """Satellite: a fanout dir that vanishes (concurrent GC) or an
    unmounted volume yields a PARTIAL sweep/manifest, never an exception
    out of every caller."""
    pool = ObjectStorePool(str(tmp_path), ttl_s=0.0)
    # top byte of the 128-bit PLH is the fanout dir: force two of them
    h_broken = (0xAA << 120) | 0x1
    h_healthy = (0xBB << 120) | 0x2
    pool.put(h_broken, *blk(8))
    pool.put(h_healthy, *blk(9))
    real_listdir = os.listdir

    def flaky(d):
        if os.path.basename(d) == "aa":
            raise OSError("stale NFS handle")
        return real_listdir(d)

    monkeypatch.setattr(os, "listdir", flaky)
    assert list(pool.keys()) == [h_healthy]  # partial manifest
    reaped = pool.sweep(now=time.time() + 10.0)  # partial sweep, no raise
    assert reaped == [h_healthy]
    monkeypatch.setattr(os, "listdir", real_listdir)
    assert h_broken in pool  # unreachable subtree untouched


# --------------------------- manager consume sites ----------------------


def test_manager_g3_corruption_publishes_removal_and_attributes(tmp_path):
    """fetch() of a corrupted G3 block: miss + removed(g3) event (the
    router must see it gone) + on_corruption attribution + stats."""
    mgr = TieredKvManager(host_blocks=1, disk_dir=str(tmp_path / "g3"),
                          disk_blocks=4)
    try:
        seen = []
        mgr.on_corruption = lambda tier, h: seen.append((tier, h))
        mgr.offload(1, *blk(10))
        mgr.offload(2, *blk(11))  # g2 cap 1: block 1 demotes to g3
        assert 1 in mgr.g3
        _write_tampered(mgr.g3._path(1), blk(10), _flip_bit)
        got, events, src = mgr.fetch(1)
        assert got is None and src is None
        assert ([], [1], "g3") in events
        assert seen == [("g3", 1)]
        assert mgr.stats.get("g3_quarantined") == 1
        assert mgr.tier_states().get("g3") == "closed"  # data fault only
    finally:
        mgr.close()


def test_manager_g4_corrupt_fetch_degrades_with_attribution(tmp_path):
    """The full serving-path wiring: chaos-corrupted G4 blob → ObjectIO
    status "corrupt" → quarantine already done in the pool → manager
    publishes removed(g4), attributes, and recomputes (miss).  The
    breaker records OK: a data fault is not a tier fault."""
    mgr = TieredKvManager(host_blocks=2,
                          object_dir=str(tmp_path / "g4"))
    try:
        seen = []
        mgr.on_corruption = lambda tier, h: seen.append((tier, h))
        k, v = blk(12)
        mgr.g4.put(0xBEEF, k, v)
        plane = chaos.ChaosPlane(seed=2)
        plane.rule("kvbm.object_io", "corrupt", times=1, match="get:")
        with plane:
            got, events, src = mgr.fetch(0xBEEF)
        assert got is None and src is None
        assert ([], [0xBEEF], "g4") in events
        assert seen == [("g4", 0xBEEF)]
        assert mgr.stats.get("g4_quarantined") == 1
        assert mgr.tier_states()["g4"] == "closed"
        assert 0xBEEF not in mgr.g4
        # a clean re-spill heals the namespace: next fetch onboards
        mgr.g4.put(0xBEEF, k, v)
        got2, _, src2 = mgr.fetch(0xBEEF)
        assert src2 == "g4"
        np.testing.assert_array_equal(got2[0], k)
    finally:
        mgr.close()


def test_manager_g4_stalls_trip_breaker_then_reprobe_heals(
        tmp_path, monkeypatch):
    """Deadline-bounded I/O + breaker: a hung shared mount turns into
    bounded timeouts; `threshold` consecutive ones trip the breaker
    (match_run stops promising G4 blocks), and after the cooldown one
    probe re-closes it."""
    monkeypatch.setattr(obj_mod, "_STALL_S", 0.05)
    mgr = TieredKvManager(host_blocks=2,
                          object_dir=str(tmp_path / "g4"),
                          io_deadline_s=0.01, breaker_threshold=3,
                          breaker_cooldown_s=0.3)
    try:
        k, v = blk(13)
        mgr.g4.put(0xFEED, k, v)
        plane = chaos.ChaosPlane(seed=3)
        plane.rule("kvbm.object_io", "stall", times=3, match="get:")
        with plane:
            for _ in range(3):
                got, _, _ = mgr.fetch(0xFEED)
                assert got is None  # bounded give-up, not a wedge
        assert mgr.tier_states()["g4"] == "open"
        assert mgr.breaker.trips("g4") == 1
        assert mgr.io_failure_counters()[("g4", "timeout")] == 3
        assert NUMERIC[mgr.tier_states()["g4"]] == 2
        # open tier advertises nothing: admission prices recompute
        assert mgr.match_run([0xFEED]) == 0
        time.sleep(0.35)  # cooldown + let the wedged I/O thread drain
        assert mgr.tier_states()["g4"] == "half_open"
        got, _, src = mgr.fetch(0xFEED)  # the single probe
        assert src == "g4"
        np.testing.assert_array_equal(got[0], k)
        assert mgr.tier_states()["g4"] == "closed"
    finally:
        mgr.close()


# --------------------------- breaker unit -------------------------------


def test_tier_breaker_trip_probe_and_reclose():
    clk = [0.0]
    br = TierBreaker(("g4",), threshold=2, cooldown_s=10.0,
                     clock=lambda: clk[0])
    assert br.allow("g4")
    br.record_failure("g4")
    assert br.state("g4") == "closed"  # one failure is not a trip
    br.record_failure("g4")
    assert br.state("g4") == "open" and br.trips("g4") == 1
    assert not br.allow("g4")
    clk[0] = 10.0
    assert br.state("g4") == "half_open"
    assert br.allow("g4")       # consumes the single probe slot
    assert not br.allow("g4")   # second concurrent probe refused
    br.record_failure("g4")     # probe failed: straight back to open
    assert br.state("g4") == "open" and br.trips("g4") == 2
    clk[0] = 20.0
    assert br.allow("g4")
    br.record_ok("g4")          # probe succeeded
    assert br.state("g4") == "closed"
    assert br.allow("g4") and br.allow("g4")  # closed admits freely
    assert br.state("untracked") == "closed" and br.allow("untracked")


def test_success_resets_the_consecutive_failure_count():
    br = TierBreaker(("g4",), threshold=3, cooldown_s=10.0)
    br.record_failure("g4")
    br.record_failure("g4")
    br.record_ok("g4")  # CONSECUTIVE failures trip, interleaved ok resets
    br.record_failure("g4")
    br.record_failure("g4")
    assert br.state("g4") == "closed" and br.trips("g4") == 0


def test_degraded_tier_costs_prices_open_tiers_at_recompute():
    from dynamo_tpu.router.tiered_index import degraded_tier_costs

    costs = {"g2": 0.05, "g3": 0.2, "g4": 0.5}
    assert degraded_tier_costs(costs, {"g4": "closed"}) == costs
    assert degraded_tier_costs(costs, None) == costs
    out = degraded_tier_costs(costs, {"g4": "open", "g3": "closed"})
    assert out["g4"] == 1.0 and out["g3"] == 0.2 and out["g2"] == 0.05
    # half_open is still degraded: one probe is not a tier
    assert degraded_tier_costs(costs, {"g4": "half_open"})["g4"] == 1.0
    # publishing beats omitting: no costs + a broken tier still prices it
    assert degraded_tier_costs(None, {"g4": "open"})["g4"] == 1.0


# --------------------------- remote pulls -------------------------------


def test_remote_frame_round_trip_and_tamper_detection():
    from dynamo_tpu.kvbm.remote import (
        _tamper_frame, decode_block, encode_block,
    )

    k, v = blk(14)
    ks = np.ones((2, 4, 2), np.float32)
    vs = np.ones((2, 4, 2), np.float32) * 2
    frame = encode_block(0x42, k.astype(np.int8), v.astype(np.int8),
                         ks, vs)
    h, *arrays = decode_block(frame)
    assert h == 0x42 and len(arrays) == 4  # scales ride verbatim
    np.testing.assert_array_equal(arrays[2], ks)
    with pytest.raises(BlockIntegrityError):
        decode_block(_tamper_frame(frame))
    # an unupgraded peer's frame (no crc) still decodes: mixed-version
    # fleets keep pulling
    legacy = dict(frame)
    del legacy["crc"]
    assert decode_block(legacy)[0] == 0x42


def test_remote_index_suspect_marking_drops_the_peer():
    from dynamo_tpu.kvbm.remote import RemoteBlockIndex

    idx = RemoteBlockIndex(None, "ns", "comp", self_worker_id=0)
    for h in (1, 2, 3):
        idx.holders.setdefault(h, {}).setdefault(7, set()).add("g2")
    idx.holders.setdefault(2, {}).setdefault(8, set()).add("g2")
    assert idx.best_run([1, 2, 3]) == (7, 3)
    idx.mark_suspect(7)  # one corrupt frame: stop advertising it NOW
    assert idx.best_run([1, 2, 3]) == (None, 0)
    assert idx.best_run([2]) == (8, 1)  # other peers unaffected
    assert idx.suspects[7] == 1
    # a future stored event re-admits the peer (not exiled forever)
    idx.holders.setdefault(1, {}).setdefault(7, set()).add("g2")
    assert idx.best_run([1]) == (7, 1)


async def test_remote_pull_corrupt_frame_marks_suspect_and_attributes():
    """A chaos-corrupted pull frame: the wire crc (not the injector)
    catches it, the source is marked suspect BEFORE retry policy runs,
    and the corruption is attributed with tier="remote"."""
    from dynamo_tpu.kvbm.remote import (
        RemoteBlockIndex, RemoteKvbmPuller, encode_block,
    )

    k, v = blk(15)

    class FakeClient:
        async def generate(self, payload, instance_id=None):
            for h in payload["hashes"]:
                yield encode_block(h, k, v)

    idx = RemoteBlockIndex(None, "ns", "comp", self_worker_id=0)
    for h in (10, 11):
        idx.holders.setdefault(h, {}).setdefault(5, set()).add("g2")
    puller = RemoteKvbmPuller(idx, FakeClient(), timeout_s=2.0)
    seen = []
    puller.on_corruption = lambda tier, h: seen.append((tier, h))
    plane = chaos.ChaosPlane(seed=4)
    # every frame from peer 5 decodes corrupt (retries included)
    plane.rule("kvbm.remote_pull", "corrupt", match="5:")
    with plane:
        out = await puller.fetch_run([10, 11])
    assert out == []  # nothing corrupt was staged
    assert idx.suspects.get(5, 0) >= 1
    assert 5 not in idx.holders.get(10, {})  # advertisements dropped
    assert seen and seen[0] == ("remote", 10)
    # with the plane gone and the peer re-advertised, pulls verify clean
    for h in (10, 11):
        idx.holders.setdefault(h, {}).setdefault(5, set()).add("g2")
    out2 = await puller.fetch_run([10, 11])
    assert [b[0] for b in out2] == [10, 11]
    np.testing.assert_array_equal(out2[0][1], k)


# --------------------------- disagg transfer ----------------------------


def test_disagg_chunk_frame_crc_catches_tamper_and_splice():
    from dynamo_tpu.disagg.transfer import (
        KvLayout, decode_chunk_frame, encode_chunk_frame,
    )

    rng = np.random.default_rng(6)
    k = rng.normal(size=(2, 4, 4, 2, 8)).astype(np.float32)
    v = rng.normal(size=(2, 4, 4, 2, 8)).astype(np.float32)
    layout = KvLayout.of(k, tp=1)
    frame = encode_chunk_frame(1, k[:, 1:3], v[:, 1:3])
    decode_chunk_frame(frame, layout)  # clean frame passes

    flipped = dict(frame)
    b = bytearray(flipped["k"])
    b[0] ^= 0xFF
    flipped["k"] = bytes(b)
    with pytest.raises(ValueError, match="crc32"):
        decode_chunk_frame(flipped, layout)

    # the crc seeds with (block_start, block_count): a frame spliced
    # onto the wrong range fails even with intact payload bytes
    spliced = dict(frame)
    spliced["block_start"] = 2
    with pytest.raises(ValueError, match="crc32"):
        decode_chunk_frame(spliced, layout)

    legacy = dict(frame)
    del legacy["crc"]  # unupgraded sender: passes
    decode_chunk_frame(legacy, layout)


# --------------------------- ledger attribution -------------------------


def test_ledger_corruption_counts_without_dirtying_audits(tmp_path):
    """corruption() is recorded at the consume site, not derived by an
    audit sweep — the violation counter moves, a quarantine tape entry
    lands, the first per tier snapshots the flight recorder, and a
    subsequent reconciliation audit stays clean."""
    from dynamo_tpu import obs

    led = KvLedger()
    tr = obs.Tracer(out_path=str(tmp_path / "trace.json"))
    tr.install()
    try:
        led.corruption("g4", 0xABC)
        led.corruption("g4", 0xDEF)
        led.corruption("remote", 0x123)
    finally:
        tr.uninstall()
    vk = led.violations_by_kind()
    assert vk["corrupt"]["g4"] == 2
    assert vk["corrupt"]["remote"] == 1
    # first corruption per tier dumps the flight recorder (2 tiers)
    assert len(tr.flight_dumps) == 2
    report = led.finish_audit([], where="test")
    assert report["clean"]  # corrupt never comes from the sweep
    # the /debug/kv payload carries the totals + the quarantine tape ops
    snap = led.dump()
    assert snap["violations_total"]["corrupt"]["g4"] == 2
    assert any(e["op"] == "quarantine" for e in snap["events_tail"])


# --------------------------- mocker parity ------------------------------


def test_sim_g4_corrupt_quarantines_and_attributes_like_the_manager():
    from dynamo_tpu.mocker.kv_cache_sim import KvCacheSim, SimObjectStore

    led = KvLedger()
    store = SimObjectStore()
    seen = []
    sim = KvCacheSim(num_blocks=8, ledger=led, object_store=store,
                     breaker=TierBreaker(("g4",), threshold=3),
                     g4_deadline_s=0.05,
                     on_corruption=lambda t, h: seen.append((t, h)))
    store.put(101)
    plane = chaos.ChaosPlane(seed=7)
    plane.rule("kvbm.object_io", "corrupt", times=1, match="get:")
    with plane:
        out = sim.allocate("s1", [101], 1)
    assert out is not None
    assert out.onboarded == {}  # corrupt lookup never onboards
    assert ([], [101], "g4") in out.tier_events  # removed(g4) published
    assert 101 not in store  # quarantined fleet-wide
    assert seen == [("g4", 101)]
    assert led.violations_by_kind()["corrupt"]["g4"] == 1
    assert sim.breaker.state("g4") == "closed"  # data fault, mount fine
    # the block was recomputed into G1: same-tenant reuse proceeds
    sim.free("s1")
    out2 = sim.allocate("s2", [101], 1)
    assert out2.cached_blocks == 1


def test_sim_g4_stall_charges_deadline_and_trips_breaker():
    from dynamo_tpu.mocker.kv_cache_sim import KvCacheSim, SimObjectStore

    clk = [0.0]
    br = TierBreaker(("g4",), threshold=3, cooldown_s=5.0,
                     clock=lambda: clk[0])
    store = SimObjectStore()
    sim = KvCacheSim(num_blocks=16, object_store=store, breaker=br,
                     g4_deadline_s=0.05)
    for h in (201, 202, 203, 204):
        store.put(h)
    plane = chaos.ChaosPlane(seed=8)
    plane.rule("kvbm.object_io", "stall", times=3, match="get:")
    with plane:
        for i, h in enumerate((201, 202, 203)):
            sim.allocate(f"s{i}", [h], 1)
    # each stall charged one deadline of SIMULATED time (no real sleep)
    assert sim.io_penalty_s == pytest.approx(3 * 0.05)
    assert sim.io_failures == {"timeout": 3}
    assert br.state("g4") == "open" and br.trips("g4") == 1
    # open breaker: the store is not even consulted
    out = sim.allocate("s4", [204], 1)
    assert out.onboarded == {}
    clk[0] = 5.0  # cooldown elapsed: half-open probe onboards + recloses
    sim.free("s4")
    sim.clear_cached()
    out2 = sim.allocate("s5", [204], 1)
    assert out2.onboarded == {"g4": 1}
    assert br.state("g4") == "closed"
