"""Chaos plane: seeded fault injection proves the fault-tolerance
mechanisms COMPOSE (ISSUE 5 acceptance).

Every scenario drives greedy (seeded) requests through the real
frontend-style path — ModelPipeline.migration → Client → request plane →
worker — first fault-free, then with injections, and asserts the faulted
run's output is TOKEN-IDENTICAL to the fault-free one (or fails with a
typed, migratable-classified error).  The mocker's token stream is
position-addressed (mocker/engine.py _next_token), so token-replay
migration is exact: same property greedy decoding has on the real engine.
"""

import asyncio
import os
import signal
import uuid

import pytest

from dynamo_tpu import chaos
from dynamo_tpu.frontend import ModelManager, ModelWatcher
from dynamo_tpu.frontend.pipeline import is_migratable
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.protocols import (LLMEngineOutput, PreprocessedRequest,
                                  SamplingOptions, StopConditions)
from dynamo_tpu.runtime import DistributedRuntime, EngineError, RuntimeConfig

pytestmark = pytest.mark.chaos


def fresh_runtime(**cfg_kw) -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc",
                        **cfg_kw)
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


def greedy_req(rid: str, max_tokens: int = 8, seed: int = 1234,
               prompt=None) -> PreprocessedRequest:
    return PreprocessedRequest(
        token_ids=list(prompt or [5, 6, 7, 8]), request_id=rid,
        sampling=SamplingOptions(temperature=0.0, seed=seed),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )


async def start_fleet(rt, n_workers=2, model_name="chaos-model",
                      migration_limit=3, worker_args=None, **engine_kw):
    """n mocker workers + watcher/manager; returns (workers, pipeline)."""
    kw = dict(model_name=model_name, block_size=4, base_step_s=0.0005,
              prefill_s_per_token=0.0, decode_s_per_seq=0.0)
    kw.update(engine_kw)
    args = MockEngineArgs(**kw)
    workers = []
    for i in range(n_workers):
        wa = args if worker_args is None else worker_args[i]
        workers.append(await MockerWorker(
            rt, wa, migration_limit=migration_limit).start())
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    for _ in range(200):
        if manager.get(model_name):
            break
        await asyncio.sleep(0.02)
    pipeline = manager.get(model_name)
    assert pipeline is not None
    await pipeline.client.wait_for_instances()
    for _ in range(200):
        if len(pipeline.client.instances) == n_workers:
            break
        await asyncio.sleep(0.02)
    assert len(pipeline.client.instances) == n_workers
    return workers, watcher, pipeline


async def collect(pipeline, req) -> list:
    tokens = []
    async for out in pipeline.migration.generate(req):
        assert out.finish_reason != "error", out.error
        tokens.extend(out.token_ids)
    return tokens


# ------------------------------ unit tests ------------------------------


def test_seams_are_noops_when_uninstalled():
    assert chaos.active() is None
    assert chaos.hit("engine.step", key="x") is None


async def test_async_seam_noop_when_uninstalled():
    assert await chaos.ahit("request_plane.frame", key="y") is None


def test_rules_fire_deterministically_from_seed():
    def drive(plane):
        fired = []
        with plane:
            for i in range(50):
                try:
                    a = chaos.hit("engine.step", key=f"k{i % 3}")
                    fired.append((i, a))
                except chaos.ChaosError:
                    fired.append((i, "fail"))
        return fired

    mk = lambda: (chaos.ChaosPlane(seed=42)
                  .rule("engine.step", "fail", p=0.3)
                  .rule("engine.step", "drop", p=0.5, match="k1"))
    a, b = drive(mk()), drive(mk())
    assert a == b  # bit-identical decisions from the same seed
    assert any(x == "fail" for _, x in a)
    c = drive(chaos.ChaosPlane(seed=43)
              .rule("engine.step", "fail", p=0.3)
              .rule("engine.step", "drop", p=0.5, match="k1"))
    assert c != a  # a different seed is a different run


def test_after_times_and_match_semantics():
    plane = chaos.ChaosPlane(seed=0).rule(
        "engine.step", "fail", after=2, times=2, match="good")
    with plane:
        outcomes = []
        for key in ["bad", "good", "good", "good", "good", "good"]:
            try:
                chaos.hit("engine.step", key=key)
                outcomes.append("ok")
            except chaos.ChaosError:
                outcomes.append("fail")
    # "bad" never matches; first 2 matching hits skipped; next 2 fire;
    # then the times budget is spent
    assert outcomes == ["ok", "ok", "ok", "fail", "fail", "ok"]
    assert plane.fired() == 2
    assert [i.n for i in plane.injections] == [1, 2]


def test_injected_errors_classify_as_migratable():
    plane = chaos.ChaosPlane(seed=0).rule(
        "request_plane.frame", "truncate", times=1)
    with plane:
        with pytest.raises(chaos.ChaosError) as ei:
            chaos.hit("request_plane.frame", key="p:1")
    assert is_migratable(ei.value)
    # and the engine-crash flavor too
    assert is_migratable(RuntimeError("worker engine error: loop crashed"))
    # dynlint: disable=DYN007 deliberately a NON-canonical marker-prefixed text: the test proves substring classification
    assert is_migratable(EngineError("worker draining: migrating"))
    assert is_migratable(RuntimeError("worker stalled: no stream frame"))
    assert not is_migratable(RuntimeError("schema validation failed"))


def test_install_is_scoped():
    plane = chaos.ChaosPlane(seed=1).rule("engine.step", "fail")
    with plane:
        assert chaos.active() is plane
    assert chaos.active() is None
    chaos.hit("engine.step")  # uninstalled again: no raise


def test_rule_rejects_unregistered_seam():
    """A typo'd seam name used to be a rule that silently never fired;
    the SEAMS registry makes it a construction-time error."""
    with pytest.raises(ValueError, match="unknown chaos seam"):
        # dynlint: disable=DYN006 the typo is the point: negative test for the registry validation
        chaos.ChaosPlane(seed=0).rule("engine.stpe", "fail")
    assert "engine.step" in chaos.SEAMS


# --------------------------- scenario: frames ---------------------------


async def test_worker_killed_mid_decode_token_identical():
    """Acceptance scenario 1: a stream truncated mid-decode (what a
    worker death looks like from the client) migrates via token replay
    and the final output is token-identical to the fault-free run."""
    rt = await fresh_runtime().start()
    try:
        workers, watcher, pipeline = await start_fleet(rt)
        baseline = await collect(pipeline, greedy_req("ff-1", 10))
        assert len(baseline) == 10

        plane = chaos.ChaosPlane(seed=7).rule(
            "request_plane.frame", "truncate", after=3, times=1,
            match="generate")
        with plane:
            faulted = await collect(pipeline, greedy_req("ch-1", 10))
        assert plane.fired() == 1, plane.injections
        assert faulted == baseline
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


async def test_dropped_and_delayed_frames_still_exact():
    """Frame drops lose tokens on the wire (client sees a gap -> the
    stream just has fewer items; dropped DATA frames mean lost tokens, so
    the total differs) — drops are only safe when a retry re-sends.  Here
    we assert the milder contract: delay injections never corrupt
    content, and the request still completes exactly."""
    rt = await fresh_runtime().start()
    try:
        workers, watcher, pipeline = await start_fleet(rt)
        baseline = await collect(pipeline, greedy_req("ff-2", 8))
        plane = chaos.ChaosPlane(seed=3).rule(
            "request_plane.frame", "delay", delay_s=0.05, after=2, times=2,
            match="generate")
        with plane:
            faulted = await collect(pipeline, greedy_req("ch-2", 8))
        assert plane.fired() == 2
        assert faulted == baseline
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


async def test_dispatch_failure_migrates_exactly():
    """Injected dispatch failure (instance picked, stream never opens —
    the pick-vs-death race) replays with zero emitted tokens."""
    rt = await fresh_runtime().start()
    try:
        workers, watcher, pipeline = await start_fleet(rt)
        baseline = await collect(pipeline, greedy_req("ff-3", 8))
        plane = chaos.ChaosPlane(seed=11).rule(
            "request_plane.dispatch", "fail", times=1,
            error="connection lost (chaos: dispatch)")
        with plane:
            faulted = await collect(pipeline, greedy_req("ch-3", 8))
        assert plane.fired() == 1
        assert faulted == baseline
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


async def test_exhausted_migration_budget_fails_typed():
    """When injections outlast migration_limit the request must fail
    with a typed, migratable-classified error — never hang, never
    silently truncate."""
    rt = await fresh_runtime().start()
    try:
        workers, watcher, pipeline = await start_fleet(rt, migration_limit=1)
        plane = chaos.ChaosPlane(seed=5).rule(
            "request_plane.dispatch", "fail",
            error="connection lost (chaos: dispatch)")  # unlimited
        with plane:
            with pytest.raises((EngineError, RuntimeError)) as ei:
                await collect(pipeline, greedy_req("ch-4", 8))
        assert is_migratable(ei.value)
        assert plane.fired() == 2  # initial try + 1 migration
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


# ----------------------- scenario: engine crash -------------------------


async def test_engine_step_crash_migrates_token_identical():
    """Chaos "fail" on the scheduler step: the loop dies, every stream
    errors with the migratable worker-engine-error marker, and the
    request replays to completion on the surviving worker."""
    rt = await fresh_runtime().start()
    try:
        workers, watcher, pipeline = await start_fleet(rt)
        # 40 tokens: the overlapped scheduler's fused bursts emit up to
        # 8 tokens per step, so a shorter request would finish before
        # the rule's step-5 crash ever fires
        baseline = await collect(pipeline, greedy_req("ff-5", 40))
        plane = chaos.ChaosPlane(seed=13).rule(
            "engine.step", "fail", after=4, times=1,
            error="worker engine error: chaos crash on step N")
        with plane:
            faulted = await collect(pipeline, greedy_req("ch-5", 40))
        assert plane.fired() == 1
        assert faulted == baseline
        # the crashed engine fails fast (migratable) instead of hanging
        dead = [w for w in workers
                if w.engine._task is not None and w.engine._task.done()]
        assert len(dead) == 1
        outs = [o async for o in dead[0].engine.generate(
            greedy_req("post-crash", 2))]
        assert outs[0].finish_reason == "error"
        assert is_migratable(RuntimeError(outs[0].error))
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


# -------------------- scenario: mocker fault modes ----------------------


async def test_mocker_fail_after_tokens_death_token_identical():
    """--fail-after-tokens: simulated worker death mid-decode.  The
    faulty worker is preferred by the route hook; after it dies the
    avoid set moves the replay to the healthy worker; output is exact."""
    rt = await fresh_runtime().start()
    try:
        base = dict(model_name="chaos-model", block_size=4,
                    base_step_s=0.0005, prefill_s_per_token=0.0,
                    decode_s_per_seq=0.0)
        faulty = MockEngineArgs(fail_after_tokens=3, **base)
        healthy = MockEngineArgs(**base)
        workers, watcher, pipeline = await start_fleet(
            rt, worker_args=[faulty, healthy])
        faulty_id = workers[0].served.instance_id
        healthy_id = workers[1].served.instance_id

        # baseline on the healthy worker only — it must not consume the
        # faulty worker's fail_after_tokens budget
        async def route_healthy(req, avoid=()):
            return healthy_id

        pipeline.migration.route = route_healthy
        baseline = await collect(pipeline, greedy_req("ff-6", 10))

        picks = []

        async def route(req, avoid=()):
            iid = faulty_id if faulty_id not in avoid else healthy_id
            picks.append(iid)
            return iid

        pipeline.migration.route = route
        faulted = await collect(pipeline, greedy_req("ch-6", 10))
        assert faulted == baseline
        assert picks[0] == faulty_id and picks[-1] == healthy_id
        assert workers[0].engine.dead
        pipeline.migration.route = None
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


async def test_mocker_flaky_streams_all_complete_exactly():
    """--flaky: every request either completes token-identically (after
    any number of migrations) or fails migratable-classified.  With the
    budget high enough, all complete."""
    rt = await fresh_runtime().start()
    try:
        base = dict(model_name="chaos-model", block_size=4,
                    base_step_s=0.0005, prefill_s_per_token=0.0,
                    decode_s_per_seq=0.0)
        # sequential requests + seeded fault RNGs = a fully deterministic
        # faulted run (the drop schedule depends only on per-engine draw
        # order, which sequential single-stream traffic fixes)
        workers, watcher, pipeline = await start_fleet(
            rt, migration_limit=30,
            worker_args=[MockEngineArgs(flaky=0.2, fault_seed=99, **base),
                         MockEngineArgs(flaky=0.2, fault_seed=77, **base)])
        # fault-free baselines on a separate pristine fleet
        rt2 = await fresh_runtime().start()
        w2, watcher2, pipe2 = await start_fleet(rt2)
        baselines = {}
        for i in range(4):
            baselines[i] = await collect(
                pipe2, greedy_req(f"ff-7-{i}", 6, seed=100 + i))
        drops_before = sum(w.engines[0].metrics["requests"]
                           for w in workers)
        for i in range(4):
            tokens = await collect(
                pipeline, greedy_req(f"ch-7-{i}", 6, seed=100 + i))
            assert tokens == baselines[i], f"request {i} diverged"
        # migrations actually happened (serving attempts > client sends;
        # deterministic given the seeds above)
        attempts = sum(w.engines[0].metrics["requests"]
                       for w in workers) - drops_before
        assert attempts > 4, "no flaky drop ever fired; raise flaky/seed"
        await watcher2.close()
        for w in w2:
            await w.close()
        await rt2.shutdown()
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


async def test_mocker_wedged_worker_rescued_by_idle_bound():
    """--wedge-after: an alive-but-stuck engine produces no error on its
    own; the frontend's stream-idle bound fails the in-flight stream
    with the migratable "worker stalled" marker and the replay lands on
    the healthy worker — token-identical."""
    rt = await fresh_runtime().start()
    try:
        base = dict(model_name="chaos-model", block_size=4,
                    base_step_s=0.0005, prefill_s_per_token=0.0,
                    decode_s_per_seq=0.0)
        wedgy = MockEngineArgs(wedge_after=4, **base)
        healthy = MockEngineArgs(**base)
        workers, watcher, pipeline = await start_fleet(
            rt, worker_args=[wedgy, healthy])
        wedgy_id = workers[0].served.instance_id
        healthy_id = workers[1].served.instance_id

        # baseline on the healthy worker — it must not burn the wedgy
        # worker's step budget
        async def route_healthy(req, avoid=()):
            return healthy_id

        pipeline.migration.route = route_healthy
        baseline = await collect(pipeline, greedy_req("ff-8", 10))
        pipeline.migration.stream_idle_s = 0.4

        async def route(req, avoid=()):
            return wedgy_id if wedgy_id not in avoid else healthy_id

        pipeline.migration.route = route
        faulted = await collect(pipeline, greedy_req("ch-8", 10))
        assert faulted == baseline
        pipeline.migration.route = None
        pipeline.migration.stream_idle_s = None
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


# ---------------------- scenario: discovery outage ----------------------


async def test_file_discovery_watch_survives_transient_outage(tmp_path):
    """A transient discovery outage (injected get_prefix failures) must
    not kill a poll-based watch — the watcher keeps its last view and
    converges once the backend recovers."""
    from dynamo_tpu.runtime.discovery import FileDiscovery

    disco = FileDiscovery(str(tmp_path), ttl_s=5.0, poll_s=0.05)
    await disco.start()
    try:
        await disco.put("v1/instances/a", {"v": 1})
        seen = {}
        cancel = asyncio.Event()

        async def follow():
            async for ev in disco.watch("v1/instances/", cancel=cancel):
                if ev.type == "put":
                    seen[ev.key] = ev.value

        task = asyncio.create_task(follow())
        for _ in range(100):
            if "v1/instances/a" in seen:
                break
            await asyncio.sleep(0.02)
        assert "v1/instances/a" in seen

        plane = chaos.ChaosPlane(seed=21).rule(
            "discovery.op", "fail", match="get:v1/instances/", times=3,
            error="injected discovery outage")
        with plane:
            await disco.put("v1/instances/b", {"v": 2})
            for _ in range(200):
                if "v1/instances/b" in seen:
                    break
                await asyncio.sleep(0.02)
        assert plane.fired() == 3
        assert seen.get("v1/instances/b") == {"v": 2}, \
            "watch died during the outage instead of retrying"
        cancel.set()
        await asyncio.wait_for(task, timeout=5)
    finally:
        await disco.close()


async def test_requests_flow_through_discovery_outage():
    """End-to-end: with the fleet already discovered, a window of
    injected discovery failures must not affect in-flight or new
    requests (the request plane does not touch discovery per request)."""
    rt = await fresh_runtime().start()
    try:
        workers, watcher, pipeline = await start_fleet(rt)
        baseline = await collect(pipeline, greedy_req("ff-9", 8))
        plane = chaos.ChaosPlane(seed=23).rule(
            "discovery.op", "fail", error="injected discovery outage")
        with plane:
            faulted = await collect(pipeline, greedy_req("ch-9", 8))
        assert faulted == baseline
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


# -------------------------- scenario: drain -----------------------------


async def test_drain_migrates_inflight_zero_client_errors():
    """Acceptance scenario 4: draining a serving worker completes every
    in-flight request on the surviving worker with zero client-visible
    errors, token-identical to the fault-free run; the drained worker
    leaves discovery."""
    rt = await fresh_runtime().start()
    try:
        workers, watcher, pipeline = await start_fleet(
            rt, decode_s_per_seq=0.01)  # slow decode: streams in flight
        baseline = {}
        for i in range(4):
            baseline[i] = await collect(
                pipeline, greedy_req(f"ff-10-{i}", 12, seed=200 + i))

        tasks = [asyncio.create_task(collect(
            pipeline, greedy_req(f"ch-10-{i}", 12, seed=200 + i)))
            for i in range(4)]
        # wait until both workers actually hold in-flight sequences
        for _ in range(200):
            if any(e.num_active_seqs for w in workers
                   for e in w.engines):
                break
            await asyncio.sleep(0.01)
        drained = workers[0]
        key = drained.served.instance.key()
        await drained.drain(deadline_s=0.05)
        results = await asyncio.gather(*tasks)
        for i, tokens in enumerate(results):
            assert tokens == baseline[i], f"request {i} diverged"
        assert key not in await rt.discovery.get_prefix("v1/instances")
        # drained engine rejects new work with the migratable marker
        outs = [o async for o in drained.engines[0].generate(
            greedy_req("post-drain", 2))]
        assert outs[0].finish_reason == "error"
        assert "draining" in outs[0].error
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


async def test_sigterm_triggers_graceful_drain():
    """SIGTERM → install_drain_handler → worker.drain(): the acceptance
    path `kill -TERM <worker>` with in-flight work completing on the
    survivor."""
    from dynamo_tpu.runtime.aio import install_drain_handler

    rt = await fresh_runtime().start()
    try:
        workers, watcher, pipeline = await start_fleet(
            rt, decode_s_per_seq=0.01)
        baseline = await collect(pipeline, greedy_req("ff-11", 12))

        drained = asyncio.Event()

        async def drain_all():
            await workers[0].drain(deadline_s=0.05)
            drained.set()

        install_drain_handler(drain_all, signals=(signal.SIGTERM,))
        task = asyncio.create_task(collect(
            pipeline, greedy_req("ch-11", 12)))
        for _ in range(200):
            if any(e.num_active_seqs for w in workers for e in w.engines):
                break
            await asyncio.sleep(0.01)
        os.kill(os.getpid(), signal.SIGTERM)
        await asyncio.wait_for(drained.wait(), timeout=10)
        tokens = await asyncio.wait_for(task, timeout=10)
        assert tokens == baseline
        asyncio.get_running_loop().remove_signal_handler(signal.SIGTERM)
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


# -------------------- scenario: failed KV pull (JAX) --------------------


async def _disagg_pair(rt):
    """Prefill + decode JAX workers (tiny fp32 model, CPU) and an
    aggregated reference engine for token-identity baselines."""
    import jax.numpy as jnp

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.engine.worker import JaxEngineWorker
    from dynamo_tpu.models.llama import LlamaConfig

    tiny = LlamaConfig(name="tiny32", vocab_size=256, d_model=64,
                       n_layers=2, n_heads=4, n_kv_heads=2, head_dim=16,
                       ffn_dim=128, dtype=jnp.float32)
    ecfg = dict(model_config=tiny, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, max_num_seqs=2,
                prefill_buckets=(8, 16, 32), seed=7)
    prefill_w = await JaxEngineWorker(
        rt, EngineConfig(role="prefill", **ecfg), component="prefill",
    ).start()
    decode_w = await JaxEngineWorker(
        rt, EngineConfig(role="decode", **ecfg), component="backend",
    ).start()
    agg = JaxEngine(EngineConfig(**ecfg))
    return prefill_w, decode_w, agg


async def _disagg_pull_run(rt, decode_w, prefill_w, agg, rid):
    """Route one request through prefill -> KV transfer -> decode;
    returns (tokens, expected-from-aggregated-engine)."""
    from dynamo_tpu.disagg.prefill_router import (ConditionalDisaggConfig,
                                                  PrefillOrchestrator)

    prompt = list(range(30, 52))
    expect = []
    async for out in agg.generate(greedy_req(
            f"agg-{rid}", 6, prompt=prompt)):
        expect.extend(out.token_ids)
    pclient = await (rt.namespace("dynamo").component("prefill")
                     .endpoint("generate").client()).start()
    dclient = await (rt.namespace("dynamo").component("backend")
                     .endpoint("generate").client()).start()
    orch = PrefillOrchestrator(
        pclient, ConditionalDisaggConfig(always_remote=True))
    req = greedy_req(rid, 6, prompt=prompt)
    routed = await orch.maybe_prefill(req)
    assert routed.disaggregated_params is not None
    tokens = []
    async for item in dclient.generate(routed.to_dict()):
        out = LLMEngineOutput.from_dict(item)
        assert out.finish_reason != "error", out.error
        tokens.extend(out.token_ids)
    await orch.close()
    await pclient.close()
    await dclient.close()
    return tokens, expect


# real JAX engine in an async body: -O0 compiles dwarf the 200ms
# loop gate (see conftest); mocker-based tests here stay gated
@pytest.mark.allow_slow_callbacks
async def test_kv_pull_chunk_failure_retry_then_fallback():
    """Acceptance scenario 2: mid-sequence KV pull failures on the real
    JAX disagg path (one fleet, two sub-scenarios — the engines are the
    expensive part).

    2a. A pull failing partway through the sequence (one chunk op) is
        absorbed by the unified retry policy: the transfer completes,
        decode does ZERO local prefill, output token-identical.
    2b. A pull that keeps failing past the retry budget falls back to
        local prefill — the request STILL completes token-identical
        (correctness never depends on the transfer)."""
    rt = await fresh_runtime().start()
    prefill_w = decode_w = agg = None
    try:
        prefill_w, decode_w, agg = await _disagg_pair(rt)

        # -- 2a: transient, absorbed -----------------------------------
        plane = chaos.ChaosPlane(seed=17).rule(
            "disagg.pull.chunk", "fail", times=1,
            error="injected pull chunk failure")
        with plane:
            tokens, expect = await _disagg_pull_run(
                rt, decode_w, prefill_w, agg, "chaos-pull-1")
        assert plane.fired() == 1
        assert tokens == expect
        assert decode_w.engine.metrics["prefill_tokens"] == 0, \
            "retry should have absorbed the fault without local prefill"

        # -- 2b: persistent, local-prefill fallback --------------------
        plane = chaos.ChaosPlane(seed=19).rule(
            "disagg.pull.chunk", "fail",
            error="injected pull chunk failure")  # unlimited
        with plane:
            tokens, expect = await _disagg_pull_run(
                rt, decode_w, prefill_w, agg, "chaos-pull-2")
        assert plane.fired() >= 3  # the whole retry budget was consumed
        assert tokens == expect
        assert decode_w.engine.metrics["prefill_tokens"] > 0, \
            "fallback should have recomputed prefill locally"

        # -- graceful drain on the real engine worker ------------------
        key = decode_w.served.instance.key()
        await decode_w.drain(deadline_s=0.01)
        assert key not in await rt.discovery.get_prefix("v1/instances")
        outs = [o async for o in decode_w.engine.generate(
            greedy_req("post-drain-jax", 2))]
        assert outs[0].finish_reason == "error"
        assert "draining" in outs[0].error
    finally:
        if agg is not None:
            await agg.close()
        if prefill_w is not None:
            await prefill_w.close()
        if decode_w is not None:
            await decode_w.close()
        await rt.shutdown()


# ---------------------- migration operator hardening --------------------


async def test_avoid_set_relaxes_when_it_excludes_every_live_instance():
    """Fleet-wide blip: after every live instance lands on the avoid
    list, the set is cleared so recovered workers are re-admitted instead
    of permanently exhausting routing candidates."""
    rt = await fresh_runtime().start()
    try:
        workers, watcher, pipeline = await start_fleet(
            rt, n_workers=2, migration_limit=6)
        ids = sorted(pipeline.client.instance_ids)
        seen_avoids = []

        async def route(req, avoid=()):
            seen_avoids.append(set(avoid))
            for iid in ids:
                if iid not in avoid:
                    return iid
            raise AssertionError("avoid excluded everyone and was not "
                                 "relaxed")

        pipeline.migration.route = route
        # fail the first 2 dispatches (one per worker) then recover
        plane = chaos.ChaosPlane(seed=31).rule(
            "request_plane.dispatch", "fail", times=2,
            error="connection lost (chaos: blip)")
        with plane:
            tokens = await collect(pipeline, greedy_req("ch-12", 8))
        assert len(tokens) == 8
        # the 3rd routing attempt saw a RELAXED (empty) avoid set
        assert len(seen_avoids) == 3
        assert len(seen_avoids[1]) == 1
        assert seen_avoids[2] == set(), \
            f"avoid set was not relaxed: {seen_avoids}"
        pipeline.migration.route = None
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


async def test_migration_backoff_is_jittered_not_flat():
    """The operator paces replays through Backoff (not a flat sleep):
    exhausting the budget with unlimited failures takes at least the
    deterministic minimum of zero (full jitter) but respects the policy's
    attempt pacing — assert the Backoff object advanced."""
    from dynamo_tpu.runtime.retry import RetryPolicy

    rt = await fresh_runtime().start()
    try:
        workers, watcher, pipeline = await start_fleet(rt, migration_limit=2)
        pipeline.migration.retry_policy = RetryPolicy(
            max_attempts=1 << 10, base_s=0.001, cap_s=0.002)
        plane = chaos.ChaosPlane(seed=37).rule(
            "request_plane.dispatch", "fail",
            error="connection lost (chaos)")
        with plane:
            with pytest.raises((EngineError, RuntimeError)):
                await collect(pipeline, greedy_req("ch-13", 4))
        assert plane.fired() == 3  # initial + 2 migrations
        await watcher.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()
