"""Operator reconcile loop vs the fake API server.

Ref behavior model: the reference's DynamoGraphDeployment controller
(deploy/operator/internal/controller/dynamographdeployment_controller.go)
— apply a graph spec, get the component Deployment set; edit the spec,
the set converges; planner scale writes survive spec-unrelated passes.
"""

import asyncio
import copy

import pytest

from dynamo_tpu.operator import GraphSpec, GraphOperator, render_deployments
from dynamo_tpu.operator.spec import HASH_ANN, REPLICAS_ANN

from fake_kube import FakeKubeApiServer

SPEC = {
    "name": "llama-fleet",
    "image": "reg/dynamo-tpu:v1",
    "model": {"name": "llama-3b", "path": "/models/llama-3b"},
    "components": {
        "frontend": {"kind": "frontend", "replicas": 2, "port": 8000},
        "decode": {"kind": "worker", "role": "decode", "replicas": 3,
                   "tpu": 1},
        "prefill": {"kind": "worker", "role": "prefill", "replicas": 2,
                    "tpu": 1},
        "planner": {"kind": "planner", "replicas": 1,
                    "args": ["--mode", "sla"]},
    },
}


def test_spec_parse_and_render():
    spec = GraphSpec.parse(SPEC)
    deps = render_deployments(spec)
    assert set(deps) == {"llama-fleet-frontend", "llama-fleet-decode",
                         "llama-fleet-prefill", "llama-fleet-planner"}
    fe = deps["llama-fleet-frontend"]
    assert fe["spec"]["replicas"] == 2
    cont = fe["spec"]["template"]["spec"]["containers"][0]
    assert cont["command"][:3] == ["python", "-m", "dynamo_tpu.frontend"]
    assert {"name": "JAX_PLATFORMS", "value": "cpu"} in cont["env"]
    dec = deps["llama-fleet-decode"]
    dcont = dec["spec"]["template"]["spec"]["containers"][0]
    assert "--role" in dcont["command"] and "decode" in dcont["command"]
    assert dcont["resources"]["limits"]["google.com/tpu"] == "1"
    assert not any(e["name"] == "JAX_PLATFORMS" for e in dcont["env"])
    # rolling updates never drop to zero
    assert dec["spec"]["strategy"]["rollingUpdate"]["maxUnavailable"] == 0
    # annotations carry the drift-detection state
    ann = dec["metadata"]["annotations"]
    assert ann[REPLICAS_ANN] == "3" and ann[HASH_ANN]


def test_spec_validation():
    with pytest.raises(ValueError):
        GraphSpec.parse({"image": "x", "components": {"a": {}}})
    with pytest.raises(ValueError):
        GraphSpec.parse({"name": "g", "image": "x",
                         "components": {"a": {"kind": "nope"}}})
    with pytest.raises(ValueError):
        GraphSpec.parse({"name": "g", "image": "x", "components": {}})


@pytest.mark.asyncio
async def test_reconcile_create_update_delete():
    fake = await FakeKubeApiServer().start()
    op = GraphOperator(api_url=fake.endpoint, namespace="ns",
                       interval_s=0.05)
    try:
        fake.set_graph_spec("llama-fleet", SPEC)
        await op.reconcile_once()
        assert set(fake.deployments) == {
            "llama-fleet-frontend", "llama-fleet-decode",
            "llama-fleet-prefill", "llama-fleet-planner"}
        assert fake.deployments["llama-fleet-decode"]["spec"][
            "replicas"] == 3
        assert op.stats["created"] == 4

        # no-op pass: converged, nothing patched
        await op.reconcile_once()
        assert op.stats["patched"] == 0

        # spec edit: image change rolls every component; replica change
        # on decode scales it; prefill removed -> deleted
        spec2 = copy.deepcopy(SPEC)
        spec2["image"] = "reg/dynamo-tpu:v2"
        spec2["components"]["decode"]["replicas"] = 5
        del spec2["components"]["prefill"]
        fake.set_graph_spec("llama-fleet", spec2)
        await op.reconcile_once()
        assert "llama-fleet-prefill" not in fake.deployments
        dec = fake.deployments["llama-fleet-decode"]
        assert dec["spec"]["replicas"] == 5
        assert dec["spec"]["template"]["spec"]["containers"][0][
            "image"] == "reg/dynamo-tpu:v2"
        assert op.stats["deleted"] == 1
    finally:
        await op.close()
        await fake.close()


@pytest.mark.asyncio
async def test_planner_scale_survives_reconcile():
    """The planner patches the scale subresource; a spec-unrelated
    reconcile pass must NOT fight it (replicas only corrected when the
    SPEC's replica count changes)."""
    fake = await FakeKubeApiServer().start()
    op = GraphOperator(api_url=fake.endpoint, namespace="ns")
    try:
        fake.set_graph_spec("llama-fleet", SPEC)
        await op.reconcile_once()

        # planner scales decode 3 -> 7 out of band
        fake.deployments["llama-fleet-decode"]["spec"]["replicas"] = 7
        await op.reconcile_once()
        assert fake.deployments["llama-fleet-decode"]["spec"][
            "replicas"] == 7  # left alone

        # but a SPEC replica edit wins over the planner's value
        spec2 = copy.deepcopy(SPEC)
        spec2["components"]["decode"]["replicas"] = 4
        fake.set_graph_spec("llama-fleet", spec2)
        await op.reconcile_once()
        assert fake.deployments["llama-fleet-decode"]["spec"][
            "replicas"] == 4
    finally:
        await op.close()
        await fake.close()


@pytest.mark.asyncio
async def test_broken_spec_never_reaps_running_fleet():
    """A config typo in a live graph's spec must NOT take down its
    running Deployments: the graph is quarantined (parseable-JSON case)
    or all stray deletion freezes (unparseable-JSON case) until the spec
    parses again."""
    fake = await FakeKubeApiServer().start()
    op = GraphOperator(api_url=fake.endpoint, namespace="ns")
    try:
        fake.set_graph_spec("llama-fleet", SPEC)
        await op.reconcile_once()
        assert len(fake.deployments) == 4

        # JSON parses but spec is invalid (image dropped): quarantine
        bad = copy.deepcopy(SPEC)
        del bad["image"]
        fake.set_graph_spec("llama-fleet", bad)
        await op.reconcile_once()
        assert len(fake.deployments) == 4 and op.stats["deleted"] == 0

        # JSON itself is garbage: graph name unknowable, deletes freeze
        fake.configmaps["llama-fleet"]["data"]["spec"] = "{nope"
        await op.reconcile_once()
        assert len(fake.deployments) == 4 and op.stats["deleted"] == 0

        # spec restored: converges again, still nothing reaped
        fake.set_graph_spec("llama-fleet", SPEC)
        await op.reconcile_once()
        assert len(fake.deployments) == 4 and op.stats["deleted"] == 0
    finally:
        await op.close()
        await fake.close()


@pytest.mark.asyncio
async def test_bad_spec_skipped_and_loop_runs():
    """One malformed graph must not stall the others; the run() loop
    reconciles on its own."""
    fake = await FakeKubeApiServer().start()
    op = GraphOperator(api_url=fake.endpoint, namespace="ns",
                       interval_s=0.02)
    try:
        fake.set_graph_spec("bad", {"name": "bad"})  # no image/components
        fake.set_graph_spec("llama-fleet", SPEC)
        task = asyncio.create_task(op.run())
        for _ in range(100):
            if len(fake.deployments) == 4:
                break
            await asyncio.sleep(0.02)
        assert len(fake.deployments) == 4
        assert op.stats["errors"] >= 1
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
    finally:
        await op.close()
        await fake.close()
