"""Core runtime tests: endpoints, streaming, cancellation, discovery, events.

Model: the reference's in-process runtime tests
(lib/runtime distributed_test_utils::create_test_drt_async, SURVEY.md §4) —
no external infra, mem discovery, real TCP sockets on loopback.
"""

import asyncio
import os
import uuid

import pytest

from dynamo_tpu.runtime import (
    CancellationToken,
    DistributedRuntime,
    EngineError,
    RouterMode,
    RuntimeConfig,
)


def fresh_runtime(**kw) -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    for k, v in kw.items():
        setattr(cfg, k, v)
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


async def echo_handler(payload, ctx):
    for tok in payload["items"]:
        yield {"echo": tok}


async def test_serve_and_stream():
    async with fresh_runtime() as rt:
        ep = rt.namespace("ns").component("worker").endpoint("generate")
        await ep.serve_endpoint(echo_handler)
        client = await ep.client().start()
        out = []
        async for item in client.generate({"items": [1, 2, 3]}):
            out.append(item["echo"])
        assert out == [1, 2, 3]
        await client.close()


async def test_remote_error_propagates():
    async def bad_handler(payload, ctx):
        yield {"ok": 1}
        raise ValueError("engine exploded")

    async with fresh_runtime() as rt:
        ep = rt.namespace("ns").component("worker").endpoint("generate")
        await ep.serve_endpoint(bad_handler)
        client = await ep.client().start()
        got = []
        with pytest.raises(EngineError, match="engine exploded"):
            async for item in client.generate({}):
                got.append(item)
        assert got == [{"ok": 1}]
        await client.close()


async def test_round_robin_across_instances():
    async def make_handler(name):
        async def h(payload, ctx):
            yield {"worker": name}

        return h

    async with fresh_runtime() as rt:
        ep = rt.namespace("ns").component("worker").endpoint("generate")
        # two instances on the same process share one TCP server but have
        # distinct instance ids -> register under different endpoint names
        await ep.serve_endpoint(await make_handler("a"), instance_id=1)
        # second runtime in the same cluster = separate "process"
        rt2 = DistributedRuntime(config=rt.config, cluster_id=rt.cluster_id)
        rt2.discovery = rt.discovery.__class__(cluster_id=rt.cluster_id)
        await rt2.start()
        ep2 = rt2.namespace("ns").component("worker").endpoint("generate")
        await ep2.serve_endpoint(await make_handler("b"), instance_id=2)

        client = await ep.client(RouterMode.ROUND_ROBIN).start()
        await client.wait_for_instances()
        # wait until both instances are visible
        for _ in range(50):
            if len(client.instances) == 2:
                break
            await asyncio.sleep(0.02)
        assert len(client.instances) == 2

        seen = set()
        for _ in range(4):
            async for item in client.generate({}):
                seen.add(item["worker"])
        assert seen == {"a", "b"}
        await client.close()
        await rt2.shutdown()


async def test_direct_routing():
    async def h(payload, ctx):
        yield {"iid": "one"}

    async with fresh_runtime() as rt:
        ep = rt.namespace("ns").component("w").endpoint("e")
        served = await ep.serve_endpoint(h)
        client = await ep.client().start()
        async for item in client.direct({}, served.instance_id):
            assert item["iid"] == "one"
        with pytest.raises(RuntimeError, match="not found"):
            await client.wait_for_instances()
            async for _ in client.generate({}, instance_id=999):
                pass
        await client.close()


async def test_two_instances_one_process_direct_dispatch():
    """Two instances of one endpoint in one process must dispatch by
    instance id, not whoever registered last."""
    async def ha(payload, ctx):
        yield {"who": "a"}

    async def hb(payload, ctx):
        yield {"who": "b"}

    async with fresh_runtime() as rt:
        ep = rt.namespace("ns").component("w").endpoint("generate")
        await ep.serve_endpoint(ha, instance_id=1)
        await ep.serve_endpoint(hb, instance_id=2)
        client = await ep.client().start()
        await client.wait_for_instances()
        for _ in range(50):
            if len(client.instances) == 2:
                break
            await asyncio.sleep(0.02)
        got_a = [i async for i in client.direct({}, 1)]
        got_b = [i async for i in client.direct({}, 2)]
        assert got_a == [{"who": "a"}]
        assert got_b == [{"who": "b"}]
        await client.close()


async def test_cancellation_stops_stream():
    started = asyncio.Event()

    async def slow_handler(payload, ctx):
        started.set()
        for i in range(1000):
            if ctx.is_stopped():
                return
            yield {"i": i}
            await asyncio.sleep(0.01)

    async with fresh_runtime() as rt:
        ep = rt.namespace("ns").component("w").endpoint("e")
        await ep.serve_endpoint(slow_handler)
        client = await ep.client().start()
        token = CancellationToken()
        got = []

        async def consume():
            async for item in client.generate({}, token=token):
                got.append(item)

        task = asyncio.create_task(consume())
        await started.wait()
        await asyncio.sleep(0.05)
        token.stop()
        await asyncio.wait_for(task, timeout=5)
        assert len(got) < 1000
        await client.close()


async def test_instance_removal_on_shutdown():
    async def h(payload, ctx):
        yield {}

    async with fresh_runtime() as rt:
        ep = rt.namespace("ns").component("w").endpoint("e")
        served = await ep.serve_endpoint(h)
        client = await ep.client().start()
        await client.wait_for_instances()
        assert len(client.instances) == 1
        await served.shutdown()
        for _ in range(50):
            if not client.instances:
                break
            await asyncio.sleep(0.02)
        assert client.instances == []
        await client.close()


async def test_file_discovery_roundtrip(tmp_path):
    from dynamo_tpu.runtime import FileDiscovery

    d1 = FileDiscovery(str(tmp_path), ttl_s=1.0, poll_s=0.05)
    d2 = FileDiscovery(str(tmp_path), ttl_s=1.0, poll_s=0.05)
    await d1.start()
    await d1.put("v1/instances/ns/w/e/42", {"instance_id": 42})
    snap = await d2.get_prefix("v1/instances/")
    assert snap == {"v1/instances/ns/w/e/42": {"instance_id": 42}}

    events = []
    cancel = asyncio.Event()

    async def watch():
        async for ev in d2.watch("v1/instances/", cancel=cancel):
            events.append(ev)
            if len(events) >= 2:
                cancel.set()

    task = asyncio.create_task(watch())
    await asyncio.sleep(0.15)
    await d1.delete("v1/instances/ns/w/e/42")
    await asyncio.wait_for(task, timeout=5)
    assert events[0].type == "put"
    assert events[1].type == "delete"
    await d1.close()
    await d2.close()


async def test_file_discovery_lease_expiry(tmp_path):
    from dynamo_tpu.runtime import FileDiscovery

    d1 = FileDiscovery(str(tmp_path), ttl_s=0.3, poll_s=0.05)
    await d1.put("v1/instances/ns/w/e/1", {"instance_id": 1})
    # kill the heartbeat without clean delete (simulated crash)
    d1._closed.set()
    if d1._hb_task:
        d1._hb_task.cancel()

    d2 = FileDiscovery(str(tmp_path), ttl_s=0.3, poll_s=0.05)
    await asyncio.sleep(0.5)
    snap = await d2.get_prefix("v1/instances/")
    assert snap == {}
    await d2.close()


async def test_event_plane_pubsub():
    async with fresh_runtime() as rt:
        got = []
        cancel = asyncio.Event()

        async def sub():
            async for subj, payload in rt.event_plane.subscribe(
                "kv_events.", cancel=cancel
            ):
                got.append((subj, payload))
                if len(got) >= 2:
                    cancel.set()

        task = asyncio.create_task(sub())
        await asyncio.sleep(0.02)
        await rt.event_plane.publish("kv_events.ns.w", {"seq": 1})
        await rt.event_plane.publish("other.subject", {"seq": -1})
        await rt.event_plane.publish("kv_events.ns.w", {"seq": 2})
        await asyncio.wait_for(task, timeout=5)
        assert [p["seq"] for _, p in got] == [1, 2]


async def test_zmq_event_plane(tmp_path):
    from dynamo_tpu.runtime import FileDiscovery
    from dynamo_tpu.runtime.event_plane import ZmqEventPlane

    disco = FileDiscovery(str(tmp_path), ttl_s=2.0, poll_s=0.05)
    pub = ZmqEventPlane(disco)
    sub_plane = ZmqEventPlane(disco)
    got = []
    cancel = asyncio.Event()

    async def sub():
        async for subj, payload in sub_plane.subscribe("kv.", cancel=cancel):
            got.append(payload)
            cancel.set()

    task = asyncio.create_task(sub())
    await asyncio.sleep(0.1)
    # publisher announces itself on first publish; subscriber connects via
    # discovery watch; retry until the SUB join completes
    for _ in range(40):
        await pub.publish("kv.test", {"x": 1})
        if got:
            break
        await asyncio.sleep(0.05)
    await asyncio.wait_for(task, timeout=5)
    assert got[0] == {"x": 1}
    await pub.close()
    await sub_plane.close()
    await disco.close()
