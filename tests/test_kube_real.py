"""OPT-IN integration tests against a REAL Kubernetes API server (kind /
k3s / minikube).  The fake in tests/fake_kube.py cannot prove renew-PATCH
latency under apiserver load, watch bookmark/reconnect semantics, or RBAC
shapes — the exact gaps the real-etcd suite (tests/test_etcd_real.py)
closes for the etcd backend.

Run with a reachable API server and a token allowed to manage Leases,
Deployments and ConfigMaps in the target namespace:

    DYN_K8S_TEST_API=https://127.0.0.1:6443 \
    DYN_K8S_TEST_TOKEN=$(kubectl create token dynamo-tpu) \
    DYN_K8S_TEST_NAMESPACE=default \
    pytest tests/test_kube_real.py

Skipped entirely when DYN_K8S_TEST_API is unset (CI has no cluster).
Ref behavior: lib/runtime/src/discovery/kube.rs (API-server discovery,
staleness via renewTime).
"""

import asyncio
import os
import uuid

import pytest

API = os.environ.get("DYN_K8S_TEST_API", "")
TOKEN = os.environ.get("DYN_K8S_TEST_TOKEN", "")
NS = os.environ.get("DYN_K8S_TEST_NAMESPACE", "default")

pytestmark = pytest.mark.skipif(
    not API, reason="set DYN_K8S_TEST_API to run real-cluster kube tests")


def kd(ttl=2.0, cluster=None):
    from dynamo_tpu.runtime.kube import KubeDiscovery

    return KubeDiscovery(api_url=API, namespace=NS,
                         cluster_id=cluster or f"it-{uuid.uuid4().hex[:8]}",
                         ttl_s=ttl, token=TOKEN)


async def test_real_lease_roundtrip_and_revoke():
    """put/get/delete against real Lease objects, incl. the annotation
    encoding surviving the API server's own field management."""
    d = kd(ttl=2.0)
    probe = kd(ttl=2.0, cluster=d.cluster_id)
    try:
        await d.put("w/1", {"instance_id": 1, "nested": {"x": [1, 2]}})
        await d.put("cards/m", {"model": "llama"}, lease=False)
        snap = await probe.get_prefix("")
        assert snap == {"w/1": {"instance_id": 1, "nested": {"x": [1, 2]}},
                        "cards/m": {"model": "llama"}}
        await d.delete("cards/m")
        await d.revoke_lease()
        assert await probe.get_prefix("") == {}
    finally:
        await d.close()
        await probe.close()


async def test_real_stale_holder_surfaces_as_delete():
    """A holder that stops renewing (simulated crash: keepalive cancelled,
    no revoke) must surface to a live watcher as a delete within ~one
    ttl + sweep — driven by the WATCHER's wall-clock sweep, since the
    real API server emits no event for staleness."""
    d1 = kd(ttl=1.0)
    d2 = kd(ttl=1.0, cluster=d1.cluster_id)
    events = []
    cancel = asyncio.Event()
    try:
        await d1.put("w/9", {"instance_id": 9})

        async def watch():
            async for ev in d2.watch("", cancel=cancel):
                events.append(ev)
                if ev.type == "delete":
                    cancel.set()

        task = asyncio.create_task(watch())
        for _ in range(50):
            await asyncio.sleep(0.1)
            if any(e.type == "put" for e in events):
                break
        assert any(e.type == "put" and e.key == "w/9" for e in events)

        # crash: stop renewing without deleting the Lease object
        d1._closed.set()
        if d1._ka_task:
            d1._ka_task.cancel()
        await asyncio.wait_for(task, timeout=10)
        assert events[-1].type == "delete" and events[-1].key == "w/9"
    finally:
        cancel.set()
        if d1._session is not None and not d1._session.closed:
            await d1._session.close()
        await d2.close()


async def test_real_watch_survives_stream_drop():
    """Sever the watch HTTP connection under the watcher; the reconnect
    re-snapshot must surface mutations made while disconnected."""
    d1 = kd(ttl=5.0)
    d2 = kd(ttl=5.0, cluster=d1.cluster_id)
    events = []
    cancel = asyncio.Event()
    try:
        await d1.put("a", {"v": 1})

        async def watch():
            async for ev in d2.watch("", cancel=cancel):
                events.append(ev)

        task = asyncio.create_task(watch())
        for _ in range(50):
            await asyncio.sleep(0.1)
            if events:
                break
        assert [e.type for e in events] == ["put"]

        await d2._session.close()  # network drop
        await d1.delete("a")
        await d1.put("b", {"v": 2})

        for _ in range(100):
            await asyncio.sleep(0.1)
            if any(e.type == "delete" and e.key == "a" for e in events) \
                    and any(e.type == "put" and e.key == "b"
                            for e in events):
                break
        assert any(e.type == "delete" and e.key == "a" for e in events), \
            "missed delete across watch reconnect"
        assert any(e.type == "put" and e.key == "b" for e in events)
    finally:
        cancel.set()
        await asyncio.sleep(0)
        await asyncio.wait_for(task, timeout=5)
        await d1.close()
        await d2.close()


async def test_real_keepalive_holds_short_ttl():
    """ttl/3 renews must hold a 1s-TTL Lease live across many TTLs of
    real apiserver round-trips."""
    d = kd(ttl=1.0)
    probe = kd(ttl=1.0, cluster=d.cluster_id)
    try:
        await d.put("w/keep", {"instance_id": 5})
        for _ in range(8):
            await asyncio.sleep(0.5)
            snap = await probe.get_prefix("")
            assert snap.get("w/keep") == {"instance_id": 5}, \
                "lease went stale under keepalive"
        await d.close()
        assert await probe.get_prefix("") == {}
    finally:
        await probe.close()


async def test_real_connector_scale_roundtrip():
    """Planner connector against a real Deployment: create via the
    operator's renderer, scale through the scale subresource, read back,
    delete."""
    import aiohttp

    from dynamo_tpu.operator import GraphSpec, render_deployments
    from dynamo_tpu.planner.connectors import KubernetesConnector

    name = f"it-{uuid.uuid4().hex[:8]}"
    spec = GraphSpec.parse({
        "name": name, "image": "busybox:stable",
        "components": {"w": {"kind": "mocker", "replicas": 1,
                             "args": ["--help"]}},
    })
    manifest = list(render_deployments(spec).values())[0]
    dname = manifest["metadata"]["name"]
    headers = {"Authorization": f"Bearer {TOKEN}"} if TOKEN else {}
    from dynamo_tpu.runtime.kube import resolve_k8s_credentials

    api, ns, _tok, ssl_ctx = resolve_k8s_credentials(API, NS, TOKEN)
    url = f"{api}/apis/apps/v1/namespaces/{ns}/deployments"
    conn = aiohttp.TCPConnector(ssl=ssl_ctx) if ssl_ctx else None
    async with aiohttp.ClientSession(headers=headers,
                                     connector=conn) as s:
        async with s.post(url, json=manifest) as resp:
            assert resp.status in (200, 201), await resp.text()
        try:
            c = KubernetesConnector(dname, namespace=ns, api_url=API,
                                    token=TOKEN)
            assert await c.current_replicas() == 1
            assert await c.scale(3) == 3
            assert await c.current_replicas() == 3
            await c.close()
        finally:
            async with s.delete(f"{url}/{dname}") as resp:
                assert resp.status in (200, 202)
