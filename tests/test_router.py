"""KV router tests: indexer semantics (Python + C++ cross-check), selector,
slot manager, and KV-routing e2e against mocker workers."""

import asyncio
import os
import random
import uuid

import pytest

from dynamo_tpu.router.indexer import PyKvIndexer
from dynamo_tpu.router.selector import (
    DefaultWorkerSelector,
    KvRouterConfig,
    WorkerState,
)
from dynamo_tpu.router.sequences import ActiveSequences


def H(i: int) -> int:
    return (i << 70) | (i * 2654435761 + 17)


def make_indexers():
    out = [PyKvIndexer()]
    try:
        from dynamo_tpu.router.native_indexer import NativeKvIndexer

        out.append(NativeKvIndexer())
    except ImportError:
        pass
    return out


def test_native_indexer_available():
    """The C++ indexer must be built in this repo (make -C native)."""
    from dynamo_tpu.router.native_indexer import NativeKvIndexer  # noqa: F401


def test_indexer_semantics_match():
    """Python and C++ indexers agree on randomized event sequences."""
    indexers = make_indexers()
    assert len(indexers) == 2, "native indexer missing"
    rng = random.Random(42)
    workers = [11, 22, 33, 44]
    universe = [H(i) for i in range(200)]
    for step in range(300):
        op = rng.random()
        w = rng.choice(workers)
        if op < 0.6:
            start = rng.randrange(0, 150)
            chunk = universe[start : start + rng.randrange(1, 20)]
            for ix in indexers:
                ix.apply_stored(w, chunk)
        elif op < 0.9:
            start = rng.randrange(0, 180)
            chunk = universe[start : start + rng.randrange(1, 10)]
            for ix in indexers:
                ix.apply_removed(w, chunk)
        else:
            for ix in indexers:
                ix.remove_worker(w)
        if step % 10 == 0:
            q_start = rng.randrange(0, 100)
            q = universe[q_start : q_start + rng.randrange(1, 40)]
            results = [ix.find_matches(q) for ix in indexers]
            assert results[0] == results[1], f"divergence at step {step}"
    assert indexers[0].num_blocks == indexers[1].num_blocks


def test_indexer_prefix_walk():
    ix = PyKvIndexer()
    hs = [H(i) for i in range(8)]
    ix.apply_stored(1, hs[:6])
    ix.apply_stored(2, hs[:3])
    ix.apply_stored(3, hs[2:5])  # no prefix from 0 -> no overlap
    m = ix.find_matches(hs)
    assert m == {1: 6, 2: 3}
    # a hole stops everyone
    ix.apply_removed(1, [hs[1]])
    m = ix.find_matches(hs)
    assert m == {1: 1, 2: 3}


def test_selector_prefers_overlap_and_load():
    sel = DefaultWorkerSelector(KvRouterConfig(temperature=0.0))
    states = {1: WorkerState(active_blocks=0), 2: WorkerState(active_blocks=0)}
    # worker 2 has 8 of 10 blocks cached -> cheaper
    assert sel.select([1, 2], 10, {2: 8}, states) == 2
    # ...unless it's heavily loaded
    states[2].active_blocks = 100
    assert sel.select([1, 2], 10, {2: 8}, states) == 1
    # avoid set wins over cost
    assert sel.select([1, 2], 10, {2: 8}, states, avoid={1}) == 2
    # busy-KV threshold pushes a worker to last resort
    states[2].active_blocks = 0
    states[2].kv_usage = 0.99
    assert sel.select([1, 2], 10, {2: 8}, states) == 1


def test_active_sequences_accounting():
    from dynamo_tpu.router.sequences import PREFILL_WEIGHT as W

    seqs = ActiveSequences()
    seqs.add_request("r1", 1, blocks=10, overlap_blocks=4)
    seqs.add_request("r2", 1, blocks=5, overlap_blocks=0)
    seqs.add_request("r3", 2, blocks=7, overlap_blocks=7)
    # worker 1: decode 15, pending prefill 6+5; worker 2: full overlap
    assert seqs.active_blocks(1) == 15 + W * 11
    assert seqs.active_blocks(2) == 7
    assert seqs.active_requests(1) == 2
    # prefill completion drops the prefill charge, keeps the KV charge
    seqs.mark_prefill_completed("r1")
    assert seqs.active_blocks(1) == 15 + W * 5
    seqs.mark_prefill_completed("r1")  # idempotent
    assert seqs.active_blocks(1) == 15 + W * 5
    seqs.free("r1")
    assert seqs.active_blocks(1) == 5 + W * 5
    seqs.free("r2")  # freed before prefill done: both charges released
    assert seqs.active_blocks(1) == 0
    seqs.remove_worker(2)
    assert seqs.active_blocks(2) == 0
    assert seqs.active_requests() == 0


# ---------------------------------------------------------------------------
# e2e: KV-aware routing across mocker workers
# ---------------------------------------------------------------------------


async def test_kv_routing_e2e_prefers_warm_worker():
    """Warm a prefix on one worker; KV-routed repeats must go there."""
    from dynamo_tpu.frontend import ModelManager, ModelWatcher
    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
    from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
    from dynamo_tpu.router.kv_router import make_kv_route_factory
    from dynamo_tpu.runtime import (
        DistributedRuntime,
        RouterMode,
        RuntimeConfig,
    )

    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    rt = await DistributedRuntime(
        config=cfg, cluster_id=uuid.uuid4().hex
    ).start()
    args = MockEngineArgs(model_name="m", block_size=4, base_step_s=0.0005,
                          prefill_s_per_token=0.0, decode_s_per_seq=0.0)
    w1 = await MockerWorker(rt, args).start()
    w2 = await MockerWorker(rt, args).start()

    manager = ModelManager()
    watcher = await ModelWatcher(
        rt, manager, router_mode=RouterMode.KV,
        make_route=make_kv_route_factory(rt),
    ).start()
    for _ in range(100):
        if manager.get("m"):
            break
        await asyncio.sleep(0.02)
    pipeline = manager.get("m")
    await pipeline.client.wait_for_instances()
    for _ in range(100):
        if len(pipeline.client.instances) == 2:
            break
        await asyncio.sleep(0.02)

    prompt = list(range(40))  # 10 blocks

    def req(rid):
        return PreprocessedRequest(
            token_ids=prompt, request_id=rid,
            stop=StopConditions(max_tokens=2, ignore_eos=True),
        )

    # warm worker 1 directly
    async for _ in pipeline.client.generate(
        req("warm").to_dict(), instance_id=w1.served.instance_id
    ):
        pass
    # let the KV events land in the router's indexer
    router = pipeline.migration.route
    for _ in range(100):
        if router.indexer.worker_block_count(w1.served.instance_id) >= 10:
            break
        await asyncio.sleep(0.02)
    assert router.indexer.worker_block_count(w1.served.instance_id) >= 10

    # KV-routed requests with the same prefix must pick the warm worker
    for i in range(4):
        picked = await router.pick(req(f"route{i}"))
        router.complete(f"route{i}")
        assert picked == w1.served.instance_id

    # a totally different prompt should balance by load, not stick to w1
    cold = PreprocessedRequest(
        token_ids=list(range(500, 540)), request_id="cold",
        stop=StopConditions(max_tokens=2, ignore_eos=True),
    )
    # load w1 with fake in-flight requests
    for i in range(4):
        router.sequences.add_request(f"fake{i}", w1.served.instance_id, 20, 0)
    picked = await router.pick(cold)
    assert picked == w2.served.instance_id

    await watcher.close()
    await w1.close()
    await w2.close()
    await rt.shutdown()


async def test_kv_router_event_gap_recovery():
    """Drop an event on the floor; the router recovers via replay endpoint."""
    from dynamo_tpu.protocols import PreprocessedRequest
    from dynamo_tpu.router.events import KvEventPublisher
    from dynamo_tpu.router.kv_router import KvRouter
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    rt = await DistributedRuntime(
        config=cfg, cluster_id=uuid.uuid4().hex
    ).start()
    comp = rt.namespace("ns").component("w")
    pub = KvEventPublisher(rt, "ns", "w", worker_id=7)
    await comp.endpoint("kv_events_replay").serve_endpoint(
        pub.replay_handler, instance_id=7
    )
    gen_client = await comp.endpoint("generate").client().start()
    router = await KvRouter(rt, "ns", "w", gen_client, block_size=4).start()
    await asyncio.sleep(0.05)

    hs = [H(i) for i in range(10)]
    await pub.stored(hs[:3])          # event 0: delivered
    ev1 = pub._mk("stored", hs[3:6], None, "g1")  # event 1: NOT published
    await pub.stored(hs[6:10])        # event 2: delivered -> gap detected
    for _ in range(100):
        if router.indexer.worker_block_count(7) >= 10:
            break
        await asyncio.sleep(0.02)
    assert router.indexer.worker_block_count(7) == 10
    m = router.indexer.find_matches(hs)
    assert m == {7: 10}

    await router.close()
    await gen_client.close()
    await rt.shutdown()


async def test_kv_router_late_join_full_replay():
    """A router that subscribes AFTER a worker has been publishing must
    replay events 0..N-1 on its first observed event, or blocks stored
    before subscription stay invisible to routing (ADVICE r1, medium)."""
    from dynamo_tpu.router.events import KvEventPublisher
    from dynamo_tpu.router.kv_router import KvRouter
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    rt = await DistributedRuntime(
        config=cfg, cluster_id=uuid.uuid4().hex
    ).start()
    comp = rt.namespace("ns").component("w")
    pub = KvEventPublisher(rt, "ns", "w", worker_id=9)
    await comp.endpoint("kv_events_replay").serve_endpoint(
        pub.replay_handler, instance_id=9
    )
    hs = [H(i) for i in range(8)]
    # events 0 and 1 happen before any router exists
    await pub.stored(hs[:3])
    await pub.stored(hs[3:5])
    await asyncio.sleep(0.05)

    gen_client = await comp.endpoint("generate").client().start()
    router = await KvRouter(rt, "ns", "w", gen_client, block_size=4).start()
    await asyncio.sleep(0.05)
    # first event the late router sees has event_id=2 -> full replay from 0
    await pub.stored(hs[5:8])
    for _ in range(100):
        if router.indexer.worker_block_count(9) >= 8:
            break
        await asyncio.sleep(0.02)
    assert router.indexer.worker_block_count(9) == 8
    assert router.indexer.find_matches(hs) == {9: 8}

    await router.close()
    await gen_client.close()
    await rt.shutdown()


async def test_router_replica_sync_converges():
    """Two router replicas over one fleet: each router's slot manager must
    reflect the OTHER router's in-flight picks (add / prefill_done / free),
    or multi-frontend deployments dogpile workers."""
    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
    from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
    from dynamo_tpu.router.kv_router import KvRouter
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    rt = await DistributedRuntime(
        config=cfg, cluster_id=uuid.uuid4().hex
    ).start()
    args = MockEngineArgs(model_name="m", block_size=4, base_step_s=0.0005,
                          prefill_s_per_token=0.0, decode_s_per_seq=0.0)
    w1 = await MockerWorker(rt, args).start()
    wid = w1.served.instance_id
    comp = rt.namespace("dynamo").component("mocker")
    cA = await comp.endpoint("generate").client().start()
    cB = await comp.endpoint("generate").client().start()
    rA = await KvRouter(rt, "dynamo", "mocker", cA, block_size=4).start()
    rB = await KvRouter(rt, "dynamo", "mocker", cB, block_size=4).start()
    await cA.wait_for_instances()
    await cB.wait_for_instances()

    req = PreprocessedRequest(
        token_ids=list(range(40)), request_id="r1",
        stop=StopConditions(max_tokens=8, ignore_eos=True),
    )
    picked = await rA.pick(req)
    assert picked == wid
    # B must learn about A's in-flight request via replica sync
    for _ in range(100):
        if rB.sequences.active_blocks(wid) > 0:
            break
        await asyncio.sleep(0.02)
    assert rB.sequences.active_blocks(wid) == rA.sequences.active_blocks(wid)
    assert rB.sequences.active_requests(wid) == 1

    rA.mark_prefill_completed("r1")
    for _ in range(100):
        if rB.sequences.active_blocks(wid) == rA.sequences.active_blocks(wid) \
                and rB.sequences._reqs.get(f"r1@{rA.sync.router_id}") is not None \
                and rB.sequences._reqs[f"r1@{rA.sync.router_id}"].prefill_done:
            break
        await asyncio.sleep(0.02)
    assert rB.sequences._reqs[f"r1@{rA.sync.router_id}"].prefill_done

    rA.complete("r1")
    for _ in range(100):
        if rB.sequences.active_requests(wid) == 0:
            break
        await asyncio.sleep(0.02)
    assert rB.sequences.active_blocks(wid) == 0.0

    await rA.close()
    await rB.close()
    await cA.close()
    await cB.close()
    await w1.close()
    await rt.shutdown()


def test_selector_tiebreak_not_herded():
    """Independent selector replicas must not break cost ties identically
    (shared constant seed == thundering herd across frontends)."""
    workers = list(range(8))
    seqs = []
    for _ in range(2):
        sel = DefaultWorkerSelector(KvRouterConfig())
        seqs.append([
            sel.select(workers, 4, {}, {}) for _ in range(64)
        ])
    assert seqs[0] != seqs[1], "replicas picked identical tie-break sequences"


async def test_dp_ranks_are_distinct_routing_targets():
    """One worker with dp_size=2: the router must treat each rank as its
    own target — a warmed prefix routes repeats to the SAME rank (overlap
    credit is per rank, the caches are disjoint), and a cold request under
    load lands on the other rank (ref WorkerWithDpRank, selector.rs:33)."""
    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
    from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
    from dynamo_tpu.router.kv_router import KvRouter
    from dynamo_tpu.router.targets import target_id
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    rt = await DistributedRuntime(
        config=cfg, cluster_id=uuid.uuid4().hex
    ).start()
    args = MockEngineArgs(model_name="m", block_size=4, dp_size=2,
                          base_step_s=0.0005, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0)
    w = await MockerWorker(rt, args).start()
    wid = w.served.instance_id
    comp = rt.namespace("dynamo").component("mocker")
    client = await comp.endpoint("generate").client().start()
    # seeded tie-break: cold requests are exact cost TIES between the
    # two ranks, and the default OS-entropy seed made "all 6 land on
    # one rank" a ~3% full-run flake — a fixed seed keeps the spread
    # assertion deterministic (KvRouterConfig documents test seeding)
    router = await KvRouter(rt, "dynamo", "mocker", client,
                            block_size=4,
                            config=KvRouterConfig(seed=7)).start()
    await client.wait_for_instances()
    # both ranks visible as targets (load metrics carry per-rank state)
    for _ in range(200):
        if len(router.targets.targets_of(wid)) == 2:
            break
        await asyncio.sleep(0.02)
    assert len(router.targets.targets_of(wid)) == 2

    async def serve(req):
        picked = await router.pick(req)
        assert picked == wid
        async for item in client.generate(req.to_dict(),
                                          instance_id=picked):
            pass
        router.complete(req.request_id)
        return req.dp_rank

    # warm a prefix: whatever rank it lands on must attract the repeat
    prompt = list(range(64))
    r1 = await serve(PreprocessedRequest(
        token_ids=prompt, request_id="a1",
        stop=StopConditions(max_tokens=4, ignore_eos=True)))
    # wait for the stored events of that rank's engine to index
    tid = target_id(wid, r1)
    for _ in range(200):
        if router.indexer.find_matches(
                __import__("dynamo_tpu.tokens", fromlist=["x"])
                .compute_block_hashes_for_request(prompt, 4)).get(tid):
            break
        await asyncio.sleep(0.02)
    r2 = await serve(PreprocessedRequest(
        token_ids=prompt, request_id="a2",
        stop=StopConditions(max_tokens=4, ignore_eos=True)))
    assert r2 == r1, "repeat did not follow its rank's warm prefix"

    # distinct prompts spread across ranks (load balancing over targets)
    ranks = set()
    for i in range(6):
        ranks.add(await serve(PreprocessedRequest(
            token_ids=list(range(100 + 40 * i, 140 + 40 * i)),
            request_id=f"b{i}",
            stop=StopConditions(max_tokens=4, ignore_eos=True))))
    assert ranks == {0, 1}, f"cold requests never spread: {ranks}"

    # each rank's engine actually served requests (the worker dispatched
    # by request.dp_rank)
    served = [e.metrics["requests"] for e in w.engines]
    assert all(n > 0 for n in served), served

    await router.close()
    await client.close()
    await w.close()
    await rt.shutdown()


# ----------------------- fleet prefix cache: tiered index -----------------------


def make_tiered_indexers():
    from dynamo_tpu.router.tiered_index import TieredKvIndexer

    return [TieredKvIndexer(base) for base in make_indexers()]


def test_tiered_indexer_parity_on_tier_ingestion():
    """Python- and C++-backed tiered indexers agree on randomized
    PER-TIER event streams: the union view (base membership is derived
    from local-tier residency) and the tiered overlap query both match,
    so the py/native parity the classic tests pin carries over to the
    fleet-prefix-cache ingestion path."""
    idx = make_tiered_indexers()
    assert len(idx) == 2, "native indexer missing"
    rng = random.Random(7)
    workers = [11, 22, 33]
    universe = [H(i) for i in range(160)]
    tiers = ["g1", "g1", "g2", "g3", "g4"]
    for step in range(400):
        op = rng.random()
        w = rng.choice(workers)
        tier = rng.choice(tiers)
        if op < 0.55:
            start = rng.randrange(0, 120)
            chunk = universe[start:start + rng.randrange(1, 16)]
            for ix in idx:
                ix.apply_stored(w, chunk, tier=tier)
        elif op < 0.85:
            start = rng.randrange(0, 150)
            chunk = universe[start:start + rng.randrange(1, 8)]
            for ix in idx:
                ix.apply_removed(w, chunk, tier=tier)
        elif op < 0.95:
            for ix in idx:
                ix.remove_worker(w)
        else:
            for ix in idx:
                ix.clear_worker(w)
        if step % 10 == 0:
            start = rng.randrange(0, 100)
            q = universe[start:start + rng.randrange(1, 40)]
            assert idx[0].find_matches(q) == idx[1].find_matches(q), \
                f"union divergence at step {step}"
            assert (idx[0].find_matches_tiered(q, workers)
                    == idx[1].find_matches_tiered(q, workers)), \
                f"tiered divergence at step {step}"
    assert idx[0].g4_blocks == idx[1].g4_blocks


def test_tiered_index_g4_scores_for_every_candidate():
    """G4 ownership is fleet-wide: the shared store's blobs extend ANY
    candidate's leading run, the sweeper need not be the spiller to
    remove one, and blobs outlive their spiller (remove_worker) but not
    a resync clear of the worker they are attributed to."""
    from dynamo_tpu.router.tiered_index import TieredKvIndexer

    ix = TieredKvIndexer(PyKvIndexer())
    hs = [H(i) for i in range(5)]
    ix.apply_stored(1, hs[:2], tier="g1")
    ix.apply_stored(1, hs[:4], tier="g4")  # spilled copies of the head
    m = ix.find_matches_tiered(hs, [1, 2, 3])
    assert m[1] == {"g1": 2, "g4": 2}  # own g1 is the cheaper source
    assert m[2] == {"g4": 4} and m[3] == {"g4": 4}
    # the union view stays local-tiers-only: only the spiller appears
    assert ix.find_matches(hs) == {1: 2}
    # a sweeper that never stored the blob removes it fleet-wide
    ix.apply_removed(99, [hs[2]], tier="g4")
    assert ix.find_matches_tiered(hs, [2])[2] == {"g4": 2}
    # the spiller dying keeps its G4 blobs onboardable...
    ix.remove_worker(1)
    assert ix.find_matches_tiered(hs, [2])[2] == {"g4": 2}
    # ...but a resync clear drops the worker's attributed blobs
    ix2 = TieredKvIndexer(PyKvIndexer())
    ix2.apply_stored(1, hs[:4], tier="g4")
    ix2.clear_worker(1)
    assert ix2.g4_blocks == 0
    assert ix2.find_matches_tiered(hs, [2]) == {}


def test_spilled_block_no_longer_free_g1_hit():
    """Regression for the tier-blind overlap inflation: a block the
    worker offloaded out of HBM used to keep scoring as a FREE G1 hit
    for its spiller (the union index never saw the demotion, so routing
    chased overlap that would be re-onboarded at real cost).  With
    per-tier events it must downgrade to a priced g4 hit, and the
    selector must prefer genuine HBM residency on another worker."""
    from dynamo_tpu.router.tiered_index import TieredKvIndexer

    ix = TieredKvIndexer(PyKvIndexer())
    hs = [H(i) for i in range(8)]
    # worker 1 computed the prefix, then demoted all of it down to G4
    ix.apply_stored(1, hs, tier="g1")
    ix.apply_stored(1, hs, tier="g4")
    ix.apply_removed(1, hs, tier="g1")
    # worker 2 holds the same prefix hot in HBM
    ix.apply_stored(2, hs, tier="g1")
    tiers = ix.find_matches_tiered(hs, [1, 2])
    assert tiers[1] == {"g4": 8}, "spilled run still counted as g1"
    assert tiers[2] == {"g1": 8}
    sel = DefaultWorkerSelector(KvRouterConfig(temperature=0.0, seed=0))
    states = {1: WorkerState(), 2: WorkerState()}
    overlaps = {w: sum(c.values()) for w, c in tiers.items()}
    assert sel.select([1, 2], 8, overlaps, states,
                      tier_overlaps=tiers) == 2


def test_selector_tier_pricing():
    import pytest as _pytest

    sel = DefaultWorkerSelector(KvRouterConfig(temperature=0.0, seed=0))
    tiers = {1: {"g4": 8}, 2: {"g1": 8}}
    states = {1: WorkerState(), 2: WorkerState()}
    choice, logits = sel.select_verbose([1, 2], 10, {}, states,
                                        tier_overlaps=tiers)
    assert choice == 2
    assert logits[2] == _pytest.approx(2.0)  # pure-g1 = classic formula
    assert logits[1] == _pytest.approx(2 + 8 * 0.7)  # default g4 cost
    # measured tier costs from load_metrics override the defaults
    states[1].tier_costs = {"g4": 0.05}
    _, logits = sel.select_verbose([1, 2], 10, {}, states,
                                   tier_overlaps=tiers)
    assert logits[1] == _pytest.approx(2 + 8 * 0.05)
    # cheap-enough onboarding beats a busier g1 holder
    states[2].active_blocks = 10
    assert sel.select([1, 2], 10, {}, states, tier_overlaps=tiers) == 1
    # onboarding is never priced above recompute (cap at 1.0)
    states[1].tier_costs = {"g4": 9.0}
    _, logits = sel.select_verbose([1, 2], 10, {}, states,
                                   tier_overlaps=tiers)
    assert logits[1] == _pytest.approx(2 + 8 * 1.0)


def test_compute_tier_costs_roofline():
    import pytest as _pytest

    from dynamo_tpu.router.tiered_index import (
        DEFAULT_TIER_COSTS,
        compute_tier_costs,
    )

    # recompute_s = 16 tok * 2e9 flop/tok / 1e12 flop/s = 32 ms/block;
    # a 32 MB block over a 1 GB/s shared FS is ALSO 32 ms -> cost 1.0
    costs = compute_tier_costs(prefill_flops_per_s=1e12,
                               flops_per_token=2e9,
                               bytes_per_block=32e6, block_tokens=16,
                               tier_bw={"g4": 1e9})
    assert costs["g1"] == 0.0
    assert costs["g4"] == _pytest.approx(1.0, abs=0.01)
    # g2 at the default 8 GB/s staging rate: 4 ms onboard -> 0.125
    assert costs["g2"] == _pytest.approx(0.125, abs=0.01)
    # unmeasured chip rate falls back to the static defaults
    assert compute_tier_costs(None, 2e9, 32e6, 16) == DEFAULT_TIER_COSTS
    assert compute_tier_costs(0.0, 2e9, 32e6, 16) == DEFAULT_TIER_COSTS
