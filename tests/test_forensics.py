"""Tail-latency forensics plane (obs/forensics.py + the RequestTracker
hop timeline + router decision attribution):

- exact phase partition: queue/route/prefill/transfer/decode/stall sum
  to the e2e (synthetic hop sets + a live tracker)
- tail-exemplar reservoir: slowest-K retention/eviction order, window
  rotation, breach retention with pinned flight-recorder spans
- timeline coherence: mid-stream migration and drain-abort keep TWO
  dispatched hops on ONE record; disagg brackets prefill_open/done and
  first_token partitions as transfer
- predicted-vs-realized overlap: a 2-worker mocker fleet with shared
  prefixes converges the router's staleness ratio toward 0
- the token-gated /debug/requests surface on a live fleet, with a
  forced SLO breach pinned (timeline + span snapshot), folded into the
  fleet snapshot
"""

import asyncio
import json
import time
import types
import uuid

import aiohttp
import pytest

from dynamo_tpu import obs
from dynamo_tpu.frontend.pipeline import MigrationOperator
from dynamo_tpu.frontend.request_trace import RequestTracker
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.obs.forensics import (
    HOP_KINDS,
    PHASES,
    ForensicsPlane,
    phase_partition,
)
from dynamo_tpu.obs.slo import SloConfig, breach_reason
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

TOKEN = "forensics-test-token"


def fresh_runtime(**cfg_kw) -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc",
                        **cfg_kw)
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


def mock_args(**kw):
    base = dict(model_name="m", block_size=4, base_step_s=0.0005,
                prefill_s_per_token=0.0, decode_s_per_seq=0.0)
    base.update(kw)
    return MockEngineArgs(**base)


def greedy_request(tokens, n, rid):
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    return PreprocessedRequest(
        token_ids=list(tokens), request_id=rid,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )


# --------------------------- partition ----------------------------------


def test_partition_exact_synthetic():
    """The six phases sum to the e2e EXACTLY (telescoping), on local,
    disagg, stalled, and died-early hop layouts."""
    cases = [
        # local: route/queue/prefill/decode
        ([{"hop": "routed", "t_ms": 2.0},
          {"hop": "dispatched", "t_ms": 3.5},
          {"hop": "first_token", "t_ms": 53.0}], 100.0, 0.0),
        # disagg: prefill hop then decode dispatch -> transfer phase
        ([{"hop": "prefill_open", "t_ms": 1.0},
          {"hop": "prefill_done", "t_ms": 41.0},
          {"hop": "routed", "t_ms": 42.0},
          {"hop": "dispatched", "t_ms": 43.0},
          {"hop": "first_token", "t_ms": 60.0}], 90.0, 0.0),
        # stalled decode: stall carved out of the decode interval
        ([{"hop": "dispatched", "t_ms": 1.0},
          {"hop": "first_token", "t_ms": 10.0}], 200.0, 75.0),
        # died before any token
        ([{"hop": "routed", "t_ms": 2.0},
          {"hop": "dispatched", "t_ms": 3.0}], 50.0, 0.0),
        # no hops at all (preprocess failure): everything is queue
        ([], 30.0, 0.0),
    ]
    for hops, total, stall in cases:
        part = phase_partition(hops, total, stall)
        assert set(part) == set(PHASES)
        assert all(v >= 0.0 for v in part.values()), part
        assert abs(sum(part.values()) - total) < 1e-9, (hops, part)
    # stall really lands in stall, not decode
    part = phase_partition(cases[2][0], 200.0, 75.0)
    assert part["stall"] == 75.0 and part["decode"] == 115.0


def test_partition_exact_from_live_tracker():
    """Partition exactness as recorded by a real tracker (the tested
    acceptance property: phases sum to e2e within 1%), with a forced
    decode stall producing a decode_stall hop AND exact stall_ms."""
    tr = RequestTracker(request_id="r1", model="m",
                        stall_threshold_s=0.02)
    tr.on_routed(7, {"predicted_overlap_blocks": 3, "regret": 0.0})
    tr.on_dispatch(7)
    tr.on_tokens(1)
    time.sleep(0.05)          # > stall threshold: one stall
    tr.on_tokens(1)
    tr.on_tokens(2)
    rec = tr.finish(finish_reason="stop")
    t = rec["timeline"]
    kinds = [h["hop"] for h in t["hops"]]
    assert kinds[0] == "received" and kinds[-1] == "finish"
    assert "routed" in kinds and "dispatched" in kinds
    assert "first_token" in kinds and "decode_stall" in kinds
    assert all(k in HOP_KINDS for k in kinds)
    assert t["stall_ms"] >= 50.0 * 0.9
    total = rec["request"]["total_time_ms"]
    part = t["partition"]
    assert abs(sum(part.values()) - total) <= 0.01 * total
    assert part["stall"] > 0.0
    # the routed hop carries the decision attribution
    routed = next(h for h in t["hops"] if h["hop"] == "routed")
    assert routed["predicted_overlap_blocks"] == 3
    assert routed["worker"] == 7


def test_worker_stamp_replaces_predicted_cached_tokens():
    tr = RequestTracker(request_id="r", model="m", input_tokens=20)
    tr.on_dispatch(1)
    tr.cached_tokens = 12    # frontend's router-predicted guess
    tr.on_tokens(1)
    tr.on_worker_stamp({"cached_tokens": 8, "queue_pos": 2,
                        "prefill_chunks": 1, "generated": 1})
    rec = tr.finish(finish_reason="stop")
    # realized reuse wins as the record's truth
    assert rec["request"]["cached_tokens"] == 8
    assert rec["request"]["kv_hit_rate"] == 0.4
    assert rec["timeline"]["worker"]["queue_pos"] == 2
    stamp = next(h for h in rec["timeline"]["hops"]
                 if h["hop"] == "worker_stamp")
    assert stamp["cached_tokens"] == 8 and stamp["attempt"] == 1


def test_unregistered_hop_kind_raises():
    tr = RequestTracker(request_id="r", model="m")
    with pytest.raises(ValueError):
        tr.hop("dispatchd")  # dynlint: disable=DYN012 the negative test


def test_timeline_off_records_nothing():
    tr = RequestTracker(request_id="r", model="m", timeline_on=False)
    tr.on_dispatch(1)
    tr.on_tokens(3)
    rec = tr.finish(finish_reason="stop")
    assert tr.hops == [] and "timeline" not in rec


# --------------------------- reservoir ----------------------------------


def mk_record(rid, ttft=10.0, itl=None, e2e=100.0, outcome="ok",
              model="m"):
    req = {"request_id": rid, "model": model, "outcome": outcome,
           "total_time_ms": e2e, "input_tokens": 10}
    if ttft is not None:
        req["ttft_ms"] = ttft
    if itl is not None:
        req["avg_itl_ms"] = itl
    return {
        "schema": "dynamo.request.trace.v1",
        "request": req,
        "timeline": {
            "hops": [{"hop": "received", "t_ms": 0.0},
                     {"hop": "dispatched", "t_ms": 1.0},
                     {"hop": "first_token", "t_ms": ttft or 1.0}],
            "stall_ms": 0.0,
        },
    }


STUB = types.SimpleNamespace(trace_id=None)


def test_reservoir_keeps_slowest_k_evicts_fastest():
    plane = ForensicsPlane(k=3, window_s=600.0)
    for rid, ttft in (("a", 10.0), ("b", 30.0), ("c", 20.0),
                      ("d", 40.0), ("e", 5.0)):
        plane.observe_finish(STUB, mk_record(rid, ttft=ttft, itl=ttft / 10))
    (w,) = plane._windows.values()
    ranked = w["m"]["ttft"]
    # descending by TTFT, fastest exemplars evicted first
    assert [e.request_id for e in ranked] == ["d", "b", "c"]
    assert [e.request_id for e in w["m"]["itl"]] == ["d", "b", "c"]
    # a new slow request displaces exactly the CURRENT fastest
    plane.observe_finish(STUB, mk_record("f", ttft=25.0))
    assert [e.request_id for e in w["m"]["ttft"]] == ["d", "b", "f"]
    # counts dedupe across the ranked lists (d/b sit in BOTH): distinct
    # retained requests are {d, b, c, f} — the same dedupe the tail
    # autopsy applies, so the two surfaces agree
    assert plane.counts() == {"exemplars": 4, "breaches": 0}
    assert plane.dump()["exemplars"] == 4
    # dump carries the partition for every exemplar
    dump = plane.dump()
    assert dump["schema"] == "dynamo.forensics.v1"
    ex = dump["models"]["m"][0]["ttft"][0]
    assert ex["request_id"] == "d"
    assert abs(sum(ex["partition"].values()) - ex["e2e_ms"]) \
        <= 0.01 * ex["e2e_ms"]


def test_reservoir_window_rotation_evicts_oldest():
    plane = ForensicsPlane(k=2, window_s=0.05, max_windows=2)
    plane.observe_finish(STUB, mk_record("w0", ttft=10.0))
    first_widx = next(iter(plane._windows))
    time.sleep(0.06)
    plane.observe_finish(STUB, mk_record("w1", ttft=10.0))
    time.sleep(0.06)
    plane.observe_finish(STUB, mk_record("w2", ttft=10.0))
    assert len(plane._windows) == 2
    assert first_widx not in plane._windows  # oldest window went first


def test_breach_retained_and_pins_flight_spans():
    tid = "ab" * 16
    cfg = SloConfig(ttft_ms=1.0)  # everything breaches
    plane = ForensicsPlane(slo_config=cfg, k=2)
    tracker = types.SimpleNamespace(trace_id=tid)
    with obs.Tracer(ring=256):
        t0 = obs.begin()
        obs.end("worker_request", t0, trace_id=tid, request_id="b1")
        t0 = obs.begin()
        obs.end("request", t0, trace_id="ff" * 16)  # other request
        plane.observe_finish(tracker, mk_record("b1", ttft=500.0))
    (w,) = plane._windows.values()
    breaches = list(w["m"]["breach"])
    assert len(breaches) == 1 and breaches[0].breach == "ttft"
    # the pinned snapshot holds ONLY this trace's spans, and survives
    # the tracer being uninstalled (the ring is gone, the pin is not)
    kinds = [s["kind"] for s in breaches[0].spans]
    assert kinds == ["worker_request"]
    # non-ok outcomes breach even without latency targets
    plane2 = ForensicsPlane()
    plane2.observe_finish(STUB, mk_record("e1", ttft=None,
                                          outcome="no_first_token"))
    (w2,) = plane2._windows.values()
    assert [e.breach for e in w2["m"]["breach"]] == ["no_first_token"]


def test_breach_reason_is_the_shared_predicate():
    cfg = SloConfig(ttft_ms=100.0, itl_ms=10.0)
    assert breach_reason(cfg, mk_record("r", ttft=50.0, itl=5.0)) is None
    assert breach_reason(cfg, mk_record("r", ttft=500.0)) == "ttft"
    assert breach_reason(cfg, mk_record("r", ttft=50.0, itl=50.0)) == "itl"
    assert breach_reason(cfg, mk_record("r", outcome="error")) == "error"
    assert breach_reason(None, mk_record("r", outcome="error")) == "error"
    assert breach_reason(None, mk_record("r")) is None
    no_targets = SloConfig()
    assert breach_reason(no_targets, mk_record("r", ttft=1e9)) is None


def test_tail_autopsy_report_section(tmp_path):
    plane = ForensicsPlane(k=4, slo_config=SloConfig(ttft_ms=15.0))
    for rid, ttft in (("a", 10.0), ("b", 99.0), ("c", 20.0)):
        plane.observe_finish(STUB, mk_record(rid, ttft=ttft, itl=ttft / 7))
    from dynamo_tpu.obs.report import report_paths, tail_autopsy

    tail = tail_autopsy([plane.dump()])
    assert tail["partition_err_max"] <= 0.01
    m = tail["models"]["m"]
    assert m["worst_ttft"]["request_id"] == "b"
    assert m["breaches"] == 2 and m["breach_reasons"] == {"ttft": 2}
    assert abs(sum(m["phase_mix"].values()) - 1.0) < 0.02
    # the CLI path: a /debug/requests-shaped file mixes with trace dumps
    p = tmp_path / "requests.json"
    p.write_text(json.dumps({"worker_id": 1,
                             "sources": {"frontend:1": plane.dump()}}))
    rep = report_paths([str(p)])
    assert rep["tail"]["models"]["m"]["exemplars"] == 3


# --------------------------- worker stamps ------------------------------


async def test_mocker_stamps_first_and_finish_frames():
    """The mocker's forensic stamps (realized overlap from the capacity
    sim, queue position, step counts) ride exactly the first-token and
    finish frames — the JAX engine's contract."""
    from dynamo_tpu.mocker.engine import MockEngine

    eng = MockEngine(mock_args(enable_prefix_caching=True))
    prompt = list(range(1, 17))  # 4 full blocks at block_size=4

    async def run(rid):
        outs = []
        async for out in eng.generate(greedy_request(prompt, 5, rid)):
            outs.append(out)
        return outs

    cold = await run("cold")
    warm = await run("warm")
    await eng.close()
    for outs in (cold, warm):
        stamped = [o for o in outs
                   if o.metrics and "forensic" in o.metrics]
        assert len(stamped) == 2          # first token + finish
        assert stamped[0] is outs[0] and stamped[1] is outs[-1]
        assert stamped[1].metrics["forensic"]["generated"] == 5
        assert stamped[1].metrics["forensic"]["queue_pos"] == 0
    # the cold request computed its prefill (≥1 chunk); the warm one
    # skipped it entirely off the cache (0 chunks is the right answer)
    assert cold[-1].metrics["forensic"]["prefill_chunks"] >= 1
    assert warm[-1].metrics["forensic"]["prefill_chunks"] == 0
    assert cold[0].metrics["forensic"]["cached_tokens"] == 0
    # warm request REALIZED the shared prefix from the capacity sim
    assert warm[0].metrics["forensic"]["cached_tokens"] == 16


# --------------------------- timeline coherence -------------------------


async def test_migration_two_dispatch_hops_one_record():
    """A worker death mid-stream replays on the survivor: the ONE
    record carries both dispatched hops (attempt 1 and 2), one finish,
    and the worker ids of both attempts."""
    rt = await fresh_runtime().start()
    dying = await MockerWorker(rt, mock_args(fail_after_tokens=3),
                               component="backend").start()
    healthy = await MockerWorker(rt, mock_args(),
                                 component="backend").start()
    client = await (rt.namespace("dynamo").component("backend")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    op = MigrationOperator(client, migration_limit=2)
    try:
        migrated = None
        for i in range(8):
            tr = RequestTracker(request_id=f"mig-{i}", model="m")
            req = greedy_request(list(range(8)), 10, f"mig-{i}")
            toks = []
            async for out in op.generate(req, tracker=tr):
                toks.extend(out.token_ids)
            rec = tr.finish(finish_reason="stop")
            assert len(toks) == 10  # migration is client-invisible
            if rec["request"].get("migrations"):
                migrated = (tr, rec)
                break
        assert migrated is not None, "no request hit the dying worker"
        tr, rec = migrated
        dispatched = [h for h in rec["timeline"]["hops"]
                      if h["hop"] == "dispatched"]
        assert [h["attempt"] for h in dispatched] == [1, 2]
        assert dispatched[0]["worker"] == dying.served.instance_id
        assert dispatched[1]["worker"] == healthy.served.instance_id
        assert sum(h["hop"] == "finish"
                   for h in rec["timeline"]["hops"]) == 1
        assert rec["request"]["outcome"] == "ok"
        total = rec["request"]["total_time_ms"]
        assert abs(sum(rec["timeline"]["partition"].values())
                   - total) <= 0.01 * total
    finally:
        await client.close()
        await dying.close()
        await healthy.close()
        await rt.shutdown()


async def test_drain_abort_one_coherent_record():
    """Graceful drain mid-stream: the aborted attempt and its replay
    stay ONE record — two dispatched hops, full-length stream, ok."""
    rt = await fresh_runtime().start()
    # sync lockstep decode (no fused bursts): the stream must still be
    # mid-flight when the drain deadline expires
    w1 = await MockerWorker(rt, mock_args(base_step_s=0.005,
                                          overlap_scheduling=False),
                            component="backend").start()
    w2 = await MockerWorker(rt, mock_args(base_step_s=0.005,
                                          overlap_scheduling=False),
                            component="backend").start()
    client = await (rt.namespace("dynamo").component("backend")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    op = MigrationOperator(client, migration_limit=2)
    by_id = {w.served.instance_id: w for w in (w1, w2)}
    try:
        tr = RequestTracker(request_id="drain-1", model="m")
        req = greedy_request(list(range(8)), 40, "drain-1")
        toks = []
        drained = False
        async for out in op.generate(req, tracker=tr):
            toks.extend(out.token_ids)
            if not drained and len(toks) >= 2:
                drained = True
                await by_id[tr.decode_worker_id].drain(0.02)
        rec = tr.finish(finish_reason="stop")
        assert len(toks) == 40
        dispatched = [h for h in rec["timeline"]["hops"]
                      if h["hop"] == "dispatched"]
        assert len(dispatched) == 2
        assert rec["request"]["migrations"] == 1
        assert rec["request"]["outcome"] == "ok"
    finally:
        await client.close()
        await w1.close()
        await w2.close()
        await rt.shutdown()


async def test_disagg_timeline_brackets_prefill_and_transfer():
    """Disagg path through the real frontend pipeline: prefill_open /
    prefill_done bracket the remote hop, the prefill worker id lands on
    the hop, and the partition's prefill phase is nonzero."""
    from dynamo_tpu.disagg.prefill_router import ConditionalDisaggConfig
    from dynamo_tpu.frontend import ModelManager, ModelWatcher

    rt = await fresh_runtime().start()
    decode_w = await MockerWorker(rt, mock_args(role="decode"),
                                  component="backend").start()
    prefill_w = await MockerWorker(rt, mock_args(role="prefill"),
                                   component="prefill").start()
    manager = ModelManager()
    watcher = await ModelWatcher(
        rt, manager,
        disagg_config=ConditionalDisaggConfig(min_effective_isl=8,
                                              min_effective_ratio=0.0),
    ).start()
    try:
        for _ in range(100):
            p = manager.get("m")
            if p is not None and p.prefill is not None:
                break
            await asyncio.sleep(0.02)
        pipeline = manager.get("m")
        assert pipeline is not None and pipeline.prefill is not None
        tr = RequestTracker(request_id="d1", model="m", input_tokens=40)
        req = greedy_request(list(range(40)), 5, "d1")
        deltas = [d async for d in
                  pipeline.generate_deltas(req, tracker=tr)]
        assert sum(d.token_count for d in deltas) == 5
        rec = tr.finish(finish_reason="stop")
        kinds = [h["hop"] for h in rec["timeline"]["hops"]]
        assert "prefill_open" in kinds and "prefill_done" in kinds
        assert kinds.index("prefill_done") < kinds.index("dispatched")
        # (mock transfer params carry no instance_id; the JAX disagg
        # path stamps the prefill worker on the hop)
        # the PREFILL worker's own forensic stamp rides the
        # prefill_done hop (prefill_router.py popped it off the
        # transfer params), not the decode worker's stream
        done = next(h for h in rec["timeline"]["hops"]
                    if h["hop"] == "prefill_done")
        # generated==0: the prefill hop decodes nothing (its first
        # token rides the transfer params) — same on both engines
        assert done["generated"] == 0 and done["prefill_chunks"] >= 1
        assert "cached_tokens" in done
        part = rec["timeline"]["partition"]
        assert part["prefill"] > 0.0
        total = rec["request"]["total_time_ms"]
        assert abs(sum(part.values()) - total) <= 0.01 * total
        # queue_ms still ends at the prefill hop (the PR 7 semantics)
        assert rec["request"]["queue_ms"] <= part["queue"] + 0.01
    finally:
        await watcher.close()
        await prefill_w.close()
        await decode_w.close()
        await rt.shutdown()


# ----------------- predicted vs realized (router feedback) --------------


async def test_predicted_vs_realized_overlap_converges():
    """2-worker mocker fleet, shared-prefix traffic through the KV
    router: after the cache warms, the router's predicted overlap is
    REALIZED by the workers (staleness ratio near 0), the realized
    histogramed blocks match, and the decision attribution (scores,
    best rejected, regret) rides the routed hop."""
    from dynamo_tpu.router.kv_router import KvRouter

    rt = await fresh_runtime().start()
    workers = [
        await MockerWorker(rt, mock_args(), component="mocker").start()
        for _ in range(2)
    ]
    client = await (rt.namespace("dynamo").component("mocker")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    router = await KvRouter(rt, "dynamo", "mocker", client,
                            block_size=4, replica_sync=False).start()
    op = MigrationOperator(client, migration_limit=0, route=router)
    prompt = list(range(100, 132))  # 8 full blocks, shared by everyone
    trackers = []
    try:
        for i in range(6):
            tr = RequestTracker(request_id=f"warm-{i}", model="m")
            req = greedy_request(prompt, 4, f"warm-{i}")
            async for _out in op.generate(req, tracker=tr):
                pass
            tr.finish(finish_reason="stop")
            trackers.append(tr)
            await asyncio.sleep(0.15)  # let KV events reach the indexer
        stats = router.overlap_stats()
        assert stats["decisions"] == 6
        # warm requests predicted AND realized the shared prefix
        assert stats["predicted_blocks"] >= 8
        assert stats["realized_blocks"] >= 8
        assert stats["staleness_ratio"] is not None
        assert stats["staleness_ratio"] <= 0.2, stats
        last = trackers[-1]
        routed = next(h for h in last.hops if h["hop"] == "routed")
        assert routed["predicted_overlap_blocks"] == 8
        assert "scores" in routed and "best_rejected" in routed
        assert routed["regret"] >= 0.0
        stamp = next(h for h in last.hops if h["hop"] == "worker_stamp")
        # realized == predicted on the warm path: the index is accurate
        assert stamp["cached_tokens"] == 32
        # the new router gauges render on the process registry (what a
        # fleet scrape picks up via _parse_headline_metrics)
        scrape = rt.metrics.render()
        assert b"dynamo_router_overlap_staleness_ratio" in scrape
        assert b"dynamo_router_overlap_realized_blocks" in scrape
        assert b"dynamo_router_overlap_best_rejected_blocks" in scrape
        assert b"dynamo_router_decision_regret_blocks" in scrape
    finally:
        await router.close()
        await client.close()
        for w in workers:
            await w.close()
        await rt.shutdown()


# --------------------------- /debug/requests e2e ------------------------


async def test_debug_requests_breach_pinned_on_live_fleet():
    """Acceptance e2e: a live mocker fleet with an impossible TTFT
    target — /debug/requests is token-gated, returns the breach's
    pinned timeline + span snapshot, and the fleet snapshot folds the
    tail + router block in."""
    from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
    from dynamo_tpu.obs import fleet as obs_fleet

    rt = await fresh_runtime(system_port=-1, admin_token=TOKEN).start()
    worker = await MockerWorker(rt, mock_args(base_step_s=0.002),
                                component="backend").start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    tracer = obs.Tracer(ring=4096).install()
    service = await HttpService(
        rt, manager, host="127.0.0.1", port=0,
        slo=SloConfig(ttft_ms=0.01),   # impossible: every request breaches
    ).start()
    port = service._runner.addresses[0][1]
    try:
        for _ in range(100):
            if manager.get("m"):
                break
            await asyncio.sleep(0.02)
        body = {"model": "m",
                "messages": [{"role": "user", "content": "hello there"}],
                "max_tokens": 6, "ignore_eos": True}
        base = f"http://127.0.0.1:{port}"
        dbg = f"http://{rt.system_address}/debug/requests"
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{base}/v1/chat/completions",
                              json=body) as r:
                assert r.status == 200
            # token gate: 401 without, payload with
            async with s.get(dbg) as r:
                assert r.status == 401
            async with s.get(
                    dbg, headers={"X-Dyn-Admin-Token": TOKEN}) as r:
                assert r.status == 200
                dump = await r.json()
        src = dump["sources"][f"frontend:{service._fleet_instance_id}"]
        assert src["schema"] == "dynamo.forensics.v1"
        assert src["breaches"] >= 1
        breach = src["models"]["m"][0]["breach"][0]
        assert breach["breach"] == "ttft"
        hops = [h["hop"] for h in breach["record"]["timeline"]["hops"]]
        assert "dispatched" in hops and "first_token" in hops
        part = breach["partition"]
        assert abs(sum(part.values()) - breach["e2e_ms"]) \
            <= 0.01 * breach["e2e_ms"]
        # the breach pinned its span snapshot by trace_id (tracing on:
        # the frontend minted a trace_id and the worker's spans joined)
        assert breach.get("spans"), breach.get("spans")
        assert any(s["kind"] == "worker_request" for s in breach["spans"])
        # worker stamps flowed back through the live stream
        assert breach["record"]["timeline"]["worker"]["generated"] == 6
        # fleet snapshot folds the forensics + tail summary in
        snap = await obs_fleet.snapshot(rt.discovery, token=TOKEN)
        fe = next(f for f in snap.frontends
                  if f.worker_id == service._fleet_instance_id)
        assert fe.tail is not None and fe.tail["breaches"] >= 1
        assert snap.summary["tail"]["breaches"] >= 1
    finally:
        tracer.uninstall()
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()
    assert not rt.forensics_sources  # close() unregistered the source
