"""KVBM multi-tier KV management: pools, consolidation, offload/onboard.

Mirrors the reference's kvbm test discipline (lib/kvbm-engine testing
features): pool/consolidator units first, then engine e2e where evicted
blocks round-trip HBM→host→HBM instead of being recomputed."""

import asyncio
import os

import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.kvbm import (
    DiskBlockPool,
    HostBlockPool,
    KvEventConsolidator,
    TieredKvManager,
)
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.protocols import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

FP32 = LlamaConfig(name="tiny32", vocab_size=256, d_model=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
                   dtype=jnp.float32)


def blk(seed):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(2, 4, 2, 8)).astype(np.float32),
            rng.normal(size=(2, 4, 2, 8)).astype(np.float32))


def greedy_req(tokens, n, rid):
    return PreprocessedRequest(
        token_ids=tokens, request_id=rid,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )


async def collect(eng, req):
    toks = []
    async for out in eng.generate(req):
        toks.extend(out.token_ids)
    return toks


# ----------------------------- pools -----------------------------------


def test_host_pool_lru_eviction():
    pool = HostBlockPool(capacity_blocks=2)
    k1, v1 = blk(1)
    assert pool.put(1, k1, v1) == []
    assert pool.put(2, *blk(2)) == []
    pool.get(1)  # touch: 2 becomes LRU victim
    evicted = pool.put(3, *blk(3))
    assert [h for h, _ in evicted] == [2]
    assert 1 in pool and 3 in pool and 2 not in pool
    got = pool.get(1)
    np.testing.assert_array_equal(got[0], k1)


def test_disk_pool_round_trip(tmp_path):
    pool = DiskBlockPool(str(tmp_path), capacity_blocks=2)
    k, v = blk(7)
    assert pool.put(10, k, v) == []
    assert pool.put(11, *blk(8)) == []
    assert pool.put(12, *blk(9)) == [10]  # capacity eviction, oldest first
    got = pool.get(11)
    assert got is not None
    assert pool.get(10) is None
    pool.clear()
    assert len(pool) == 0 and pool.get(11) is None


def test_disk_pool_round_trips_bfloat16(tmp_path):
    """bfloat16 is the default KV dtype; a plain np.savez round-trips it as
    raw void ('|V2'), which crashes jnp.asarray at onboard time.  The pool
    must hand back the original dtype."""
    import ml_dtypes

    pool = DiskBlockPool(str(tmp_path), capacity_blocks=2)
    k = np.arange(2 * 4 * 2 * 8, dtype=np.float32).reshape(2, 4, 2, 8)
    kb = k.astype(ml_dtypes.bfloat16)
    pool.put(1, kb, (k + 1).astype(ml_dtypes.bfloat16))
    got_k, got_v = pool.get(1)
    assert got_k.dtype == kb.dtype and got_v.dtype == kb.dtype
    np.testing.assert_array_equal(got_k, kb)
    jnp.asarray(got_k)  # must be a valid JAX input


def test_disk_pool_rejects_shared_directory(tmp_path):
    """Two engines pointed at the same disk_cache_dir would wipe and evict
    each other's live G3 blocks — the second pool must refuse to start."""
    import pytest

    pool = DiskBlockPool(str(tmp_path), capacity_blocks=2)
    with pytest.raises(RuntimeError, match="owned by another engine"):
        DiskBlockPool(str(tmp_path), capacity_blocks=2)
    pool.close()  # ownership released: a successor may now take over
    pool2 = DiskBlockPool(str(tmp_path), capacity_blocks=2)
    pool2.close()


def test_disk_pool_wipes_stale_files_but_not_foreign_ones(tmp_path):
    stale = tmp_path / ("0" * 31 + "a.npz")  # pool's own 32-hex name form
    stale.write_bytes(b"junk")
    foreign = tmp_path / "user_data.npz"  # NOT ours: must survive
    foreign.write_bytes(b"precious")
    pool = DiskBlockPool(str(tmp_path), capacity_blocks=2)
    assert not stale.exists()
    assert foreign.exists()
    pool.put(1, *blk(1))
    assert pool.get(1) is not None


def test_manager_offload_cooldown_prevents_pingpong(tmp_path):
    """A capacity-dropped hash must be excluded from immediate re-offload
    (via offload_skip), or an undersized G2 regathers the same cold blocks
    every scheduler step."""
    mgr = TieredKvManager(host_blocks=1)
    mgr.offload(1, *blk(1))
    mgr.offload(2, *blk(2))  # drops 1 (no G3)
    assert 1 in mgr.offload_skip  # recently dropped: don't re-offload
    assert 2 in mgr.offload_skip  # resident: don't re-offload
    assert 3 not in mgr.offload_skip
    # an explicit re-offload (block turned hot again) still works and
    # clears the cooldown
    mgr.offload(1, *blk(1))
    assert mgr.match_run([1]) == 1


def test_manager_demotes_g2_to_g3_and_promotes_back(tmp_path):
    mgr = TieredKvManager(host_blocks=1, disk_dir=str(tmp_path),
                          disk_blocks=4)
    ev1 = mgr.offload(1, *blk(1))
    assert ev1 == [([1], [], "g2")]
    ev2 = mgr.offload(2, *blk(2))  # demotes 1 to disk
    assert ([1], [], "g3") in ev2 and ([], [1], "g2") in ev2
    assert mgr.match_run([1, 2]) == 2
    # fetching the disk-resident block promotes it back into G2
    (k, v), ev3, src = mgr.fetch(1)
    np.testing.assert_array_equal(k, blk(1)[0])
    assert ([1], [], "g2") in ev3
    assert src == "g3"
    assert mgr.stats["disk_hits"] == 1


def test_manager_fetch_emits_removal_for_vanished_disk_block(tmp_path):
    """An externally corrupted/deleted G3 file must surface a g3 removal so
    the router stops expecting an onboard that can never happen."""
    import os

    mgr = TieredKvManager(host_blocks=1, disk_dir=str(tmp_path),
                          disk_blocks=4)
    mgr.offload(1, *blk(1))
    mgr.offload(2, *blk(2))  # demotes 1 to disk
    for f in os.listdir(tmp_path):
        os.unlink(os.path.join(tmp_path, f))
    blk_out, events, src = mgr.fetch(1)
    assert blk_out is None and src is None
    assert ([], [1], "g3") in events


# -------------------------- consolidator --------------------------------


def test_consolidator_nets_events_per_tier():
    """Per-tier netting: each tier's membership nets independently, so
    an offload IS wire-visible as stored(g2) — the tier-aware router
    needs to know which tier holds the copy (pricing + the tier-blind
    inflation fix), unlike the old union netting that swallowed it."""
    c = KvEventConsolidator()
    assert c.apply([1, 2], [], "g1") == ([1, 2], [], "g1")
    # offload copies into g2: visible — the hash ENTERS g2
    assert c.apply([1], [], "g2") == ([1], [], "g2")
    # re-offload of a g2-resident hash: netted (no membership change)
    assert c.apply([1], [], "g2") == ([], [], "g2")
    # g1 eviction while g2 holds: visible — the hash LEAVES g1 (the
    # router downgrades it from a free g1 hit to a priced g2 onboard)
    assert c.apply([], [1], "g1") == ([], [1], "g1")
    # double-remove from g1: netted (not a g1 member anymore)
    assert c.apply([], [1], "g1") == ([], [], "g1")
    # g2 drop: the last copy goes
    assert c.apply([], [1], "g2") == ([], [1], "g2")
    assert c.resident_tiers(1) == set()
    # hash 2 only ever in g1
    assert c.apply([], [2], "g1") == ([], [2], "g1")


def test_consolidator_g4_removal_passes_through():
    """removed(g4) forwards even when this worker never stored the blob:
    the shared store's sweeper may not be the spiller, and the removal
    must still reach the fleet's routers."""
    c = KvEventConsolidator()
    assert c.apply([], [7], "g4") == ([], [7], "g4")
    # but a LOCAL g4 spill still nets on re-apply
    assert c.apply([8], [], "g4") == ([8], [], "g4")
    assert c.apply([8], [], "g4") == ([], [], "g4")


def test_consolidator_evict_reregister_same_mutation():
    c = KvEventConsolidator()
    c.apply([5], [], "g1")
    # one mutation: evict 5, re-register 5 (allocator can do this)
    stored, removed, _ = c.apply([5], [5], "g1")
    assert stored == [5] and removed == [5]  # removed precedes stored on wire


# ------------------------- engine e2e ------------------------------------


def eng_kwargs(**kw):
    d = dict(model_config=FP32, block_size=4, num_blocks=16,
             max_blocks_per_seq=8, max_num_seqs=2,
             prefill_buckets=(8, 16, 32), seed=7)
    d.update(kw)
    return d


# real JAX engine in an async body: -O0 compiles dwarf the 200ms
# loop gate (see conftest); mocker-based tests here stay gated
@pytest.mark.allow_slow_callbacks
async def test_offload_onboard_instead_of_recompute():
    """Fill the small HBM cache, force prompt A's blocks out, then resubmit
    A: its prefix must come back from the host tier (onboarded) rather than
    recomputed, with identical greedy output."""
    events = []

    def sink(stored, removed, tier="g1"):
        events.append((list(stored), list(removed), tier))

    cfg = EngineConfig(**eng_kwargs(host_cache_blocks=64,
                                    offload_watermark_blocks=16))
    eng = JaxEngine(cfg, kv_event_sink=sink)
    prompt_a = list(range(1, 13))  # 3 full blocks
    out1 = await collect(eng, greedy_req(prompt_a, 4, "a1"))

    # churn: distinct prompts that force A's cached blocks to be evicted
    # (watermark == num_blocks, so every step offloads before evicting)
    for i in range(6):
        p = [50 + 7 * i + j for j in range(12)]
        await collect(eng, greedy_req(p, 2, f"churn{i}"))

    assert eng.kvbm.stats["offloaded"] > 0
    pre_prefill = eng.metrics["prefill_tokens"]
    out2 = await collect(eng, greedy_req(prompt_a, 4, "a2"))
    assert out2 == out1
    assert eng.metrics.get("onboarded_tokens", 0) >= 8, \
        "prefix should onboard from the host tier"
    # onboarded blocks skip prefill compute (only the tail recomputes)
    assert eng.metrics["prefill_tokens"] - pre_prefill <= 8
    await eng.close()

    # router-visible consistency: every net-removed hash was stored before,
    # and a hash the worker still holds in ANY tier was never net-removed
    seen = set()
    for stored, removed, _tier in events:
        for h in removed:
            assert h in seen, f"removed-before-stored leaked for {h}"
            seen.discard(h)
        seen.update(stored)


# real JAX engine in an async body: -O0 compiles dwarf the 200ms
# loop gate (see conftest); mocker-based tests here stay gated
@pytest.mark.allow_slow_callbacks
async def test_concurrent_same_prefix_not_corrupted_by_deferred_commit():
    """Two identical prompts admitted near-simultaneously with chunked
    prefill: the second must not prefix-match blocks whose KV is still being
    prefilled by the first (registration is deferred to materialization).
    Greedy outputs must match a serial run."""
    cfg = EngineConfig(**eng_kwargs(num_blocks=64, max_blocks_per_seq=16,
                                    prefill_buckets=(8,),
                                    max_batch_tokens=8))
    eng = JaxEngine(cfg)
    prompt = list(range(1, 49))  # 12 blocks, 6 prefill chunks
    serial = await collect(eng, greedy_req(prompt, 6, "s0"))
    await eng.clear_kv_blocks()

    r1, r2 = await asyncio.gather(
        collect(eng, greedy_req(prompt, 6, "c1")),
        collect(eng, greedy_req(prompt, 6, "c2")),
    )
    assert r1 == serial
    assert r2 == serial
    await eng.close()


# real JAX engine in an async body: -O0 compiles dwarf the 200ms
# loop gate (see conftest); mocker-based tests here stay gated
@pytest.mark.allow_slow_callbacks
async def test_disk_tier_survives_host_pressure(tmp_path):
    """With a 2-block G2 and a disk G3, offloaded blocks demoted to disk are
    still onboardable."""
    cfg = EngineConfig(**eng_kwargs(
        host_cache_blocks=2, offload_watermark_blocks=16,
        disk_cache_dir=str(tmp_path), disk_cache_blocks=32,
    ))
    eng = JaxEngine(cfg)
    prompt_a = list(range(1, 13))
    out1 = await collect(eng, greedy_req(prompt_a, 4, "a1"))
    for i in range(6):
        p = [60 + 5 * i + j for j in range(12)]
        await collect(eng, greedy_req(p, 2, f"churn{i}"))
    assert eng.kvbm.stats["demoted"] > 0
    out2 = await collect(eng, greedy_req(prompt_a, 4, "a2"))
    assert out2 == out1
    assert eng.kvbm.stats["disk_hits"] + eng.metrics.get(
        "onboarded_tokens", 0) > 0
    await eng.close()


def test_object_store_keys_full_128_bits(tmp_path):
    # G4 blob names must commit to the full 128-bit PLH: two hashes that
    # collide in their low 64 bits must land in distinct blobs
    import numpy as np
    from dynamo_tpu.kvbm.object_store import ObjectStorePool

    pool = ObjectStorePool(str(tmp_path))
    low = 0xDEADBEEF_CAFEF00D
    h1 = (1 << 64) | low
    h2 = (2 << 64) | low
    k1 = np.full((2, 2), 1, dtype=np.float32)
    k2 = np.full((2, 2), 2, dtype=np.float32)
    assert pool.put(h1, k1, k1)
    assert pool.put(h2, k2, k2)
    g1, g2 = pool.get(h1), pool.get(h2)
    assert g1 is not None and g2 is not None
    assert float(g1[0].view(np.float32).ravel()[0]) == 1.0
    assert float(g2[0].view(np.float32).ravel()[0]) == 2.0
    assert sorted(pool.keys()) == sorted([h1, h2])


# ------------------- G4 object store: concurrency + residency -------------------


def test_object_store_atomic_put_racing_writers(tmp_path):
    """Uncoordinated same-hash writers (two engines demoting the same
    shared prefix at once): the tmp+rename put stays atomic — the blob
    is whole and readable afterwards and no tmp litter survives."""
    import threading

    import numpy as np
    from dynamo_tpu.kvbm.object_store import ObjectStorePool

    pool = ObjectStorePool(str(tmp_path))
    h = (7 << 64) | 0x1234
    arr = np.arange(64, dtype=np.float32).reshape(8, 8)
    wins = []
    barrier = threading.Barrier(8)

    def writer():
        barrier.wait()
        if pool.put(h, arr, arr):
            wins.append(1)

    threads = [threading.Thread(target=writer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert wins and h in pool
    got = pool.get(h)
    assert got is not None
    assert np.array_equal(got[0].view(np.float32).reshape(8, 8), arr)
    litter = [n for _, _, files in os.walk(str(tmp_path))
              for n in files if ".tmp" in n]
    assert litter == []
    # content-addressed dedup: a later put is a no-op, not a rewrite
    assert pool.put(h, arr, arr) is False


def test_object_store_read_during_gc(tmp_path):
    """Readers racing an aggressive TTL sweep see either the blob or a
    clean miss, never an exception — the engine's onboard path treats
    None as a broken run and recomputes from there."""
    import threading
    import time as _time

    import numpy as np
    from dynamo_tpu.kvbm.object_store import ObjectStorePool

    pool = ObjectStorePool(str(tmp_path), ttl_s=0.0)
    arr = np.ones((4, 4), dtype=np.float32)
    hashes = [(i << 64) | 0xABC for i in range(1, 33)]
    stop = threading.Event()
    errors = []

    def reader():
        while not stop.is_set():
            for h in hashes:
                try:
                    pool.get(h)
                except Exception as e:  # noqa: BLE001 - the contract
                    errors.append(e)
                    return

    t = threading.Thread(target=reader)
    t.start()
    try:
        for _ in range(10):
            for h in hashes:
                pool.put(h, arr, arr)
            pool.sweep(now=_time.time() + 1.0)
    finally:
        stop.set()
        t.join()
    assert errors == []


def test_object_store_multi_client_uncoordinated_gc(tmp_path):
    """Two mounted clients sweep the same directory concurrently: every
    expired blob is reaped EXACTLY once across both (the unlink race is
    benign and losers do not report), so fleet-wide removed(g4) events
    never double-fire for one blob."""
    import threading
    import time as _time

    import numpy as np
    from dynamo_tpu.kvbm.object_store import ObjectStorePool

    a = ObjectStorePool(str(tmp_path), ttl_s=5.0)
    b = ObjectStorePool(str(tmp_path), ttl_s=5.0)
    arr = np.ones((2, 2), dtype=np.float32)
    hashes = [(i << 64) | 0xF00D for i in range(1, 65)]
    for h in hashes:
        assert a.put(h, arr, arr)
    assert sorted(b.keys()) == sorted(hashes)  # shared view, no handoff
    out = {}
    barrier = threading.Barrier(2)
    future = _time.time() + 10.0

    def sweep(name, pool):
        barrier.wait()
        out[name] = pool.sweep(now=future)

    ta = threading.Thread(target=sweep, args=("a", a))
    tb = threading.Thread(target=sweep, args=("b", b))
    ta.start()
    tb.start()
    ta.join()
    tb.join()
    assert sorted(out["a"] + out["b"]) == sorted(hashes)
    assert list(a.keys()) == []


def test_object_store_residency_verdicts_drive_sweep(tmp_path):
    """The lineage policy upgrades the blind TTL: hot renews past its
    deadline, dead reaps ahead of it, None leaves the clock in charge."""
    import time as _time

    import numpy as np
    from dynamo_tpu.kvbm.object_store import ObjectStorePool

    pool = ObjectStorePool(str(tmp_path), ttl_s=5.0)
    arr = np.ones((2, 2), dtype=np.float32)
    hot, dead, young, old = [(i << 64) | i for i in range(1, 5)]
    for h in (hot, dead, young, old):
        pool.put(h, arr, arr)
    # age the hot and old blobs past the TTL
    stale = _time.time() - 6.0
    os.utime(pool._path(hot), (stale, stale))
    os.utime(pool._path(old), (stale, stale))
    reaped = pool.sweep(residency={hot: "hot", dead: "dead"}.get)
    # dead dies early, old dies by TTL; hot was renewed despite its age
    assert set(reaped) == {dead, old}
    assert hot in pool and young in pool
    # the renewal restarted hot's TTL clock: a blind sweep keeps it
    assert pool.sweep() == []


def test_lineage_residency_from_ledger():
    """LineageResidency verdicts straight from the ledger's books:
    touched-recently => hot; parent gone from the books AND the shared
    store => dead; roots, live parents, and unknown hashes => TTL."""
    import time as _time

    from dynamo_tpu.kvbm.residency import LineageResidency
    from dynamo_tpu.obs.kv_ledger import KvLedger

    led = KvLedger()
    root, child, orphan = 101, 102, 103
    led.alloc(1, "s", h=root)
    led.commit(1, root, parent=None, seq="s")
    led.alloc(2, "s", h=child)
    led.commit(2, child, parent=root, seq="s")
    led.alloc(3, "s", h=orphan)
    led.commit(3, orphan, parent=999, seq="s")
    # freshly committed: everything is hot (commit touches the hash)
    res = LineageResidency(led)
    assert res(child) == "hot" and res(orphan) == "hot"
    # past the hot window the lineage verdicts take over
    later = _time.monotonic() + 1000.0
    res = LineageResidency(led, now=later)
    assert res(root) is None        # lineage root: reachable by definition
    assert res(child) is None       # parent resident in the books
    assert res(orphan) == "dead"    # parent gone everywhere

    class Store:  # parent alive only in the shared store itself
        def __contains__(self, h):
            return h == 999

    assert LineageResidency(led, pool=Store(), now=later)(orphan) is None
    # commit record never ran here: the policy must not guess
    known, _ = led.lineage_parent(555)
    assert not known
    assert LineageResidency(led, now=later)(555) is None
    assert LineageResidency(led, now=later).verdicts(
        [root, child, orphan]) == {"hot": 0, "dead": 1, "ttl": 2}
