"""Session affinity: sticky agent-session routing (ref:
lib/llm/src/session_affinity/ + protocols/agents.rs)."""

import asyncio
import uuid

import aiohttp
import pytest

from dynamo_tpu.frontend.affinity import (
    AffinityCoordinator,
    SessionAffinityRouter,
    session_affinity_from_headers,
)
from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime import DistributedRuntime, RouterMode, RuntimeConfig


def fresh_runtime() -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


# --------------------------- header extraction ------------------------------


def test_header_priority_and_agent_mappings():
    assert session_affinity_from_headers({}) == (None, False)
    # dynamo-native header wins over agent headers
    sid, final = session_affinity_from_headers({
        "x-dynamo-session-id": "d1",
        "x-claude-code-session-id": "c1",
    })
    assert (sid, final) == ("d1", False)
    # agent child id preferred over root session
    sid, _ = session_affinity_from_headers({
        "x-claude-code-session-id": "root",
        "x-claude-code-agent-id": "sub",
    })
    assert sid == "sub"
    sid, _ = session_affinity_from_headers({"session-id": "codex"})
    assert sid == "codex"
    sid, _ = session_affinity_from_headers({"x-session-id": "oc"})
    assert sid == "oc"
    _, final = session_affinity_from_headers({
        "x-session-id": "oc", "x-dynamo-session-final": "true"})
    assert final is True
    # blank values ignored
    assert session_affinity_from_headers({"x-session-id": "  "}) == (
        None, False)


# --------------------------- coordinator ------------------------------------


async def test_bind_release_ttl_expiry():
    coord = AffinityCoordinator(ttl_s=1.0).start()
    e = await coord.acquire("s1")
    assert e is not None and not e.bound
    coord.bind("s1", e, 42)
    # a second acquire sees the binding
    e2 = await coord.acquire("s1")
    assert e2.bound and e2.worker_id == 42
    coord.release("s1", e2)
    coord.release("s1", e)
    # not expired yet
    e3 = await coord.acquire("s1")
    assert e3.worker_id == 42
    coord.release("s1", e3)
    # force expiry
    e3.idle_deadline = 0.0
    e4 = await coord.acquire("s1")
    assert not e4.bound  # fresh initializing entry
    coord.abort("s1", e4)
    await coord.close()


async def test_concurrent_first_requests_converge():
    """The initializing barrier: concurrent first requests on one session
    wait for the winner's bind instead of racing to different workers."""
    coord = AffinityCoordinator(ttl_s=5.0).start()

    e1 = await coord.acquire("s")
    got = []

    async def second():
        e = await coord.acquire("s")
        got.append(e.worker_id)

    t = asyncio.create_task(second())
    await asyncio.sleep(0.05)
    assert not got  # blocked on the initializing entry
    coord.bind("s", e1, 7)
    await asyncio.wait_for(t, 2.0)
    assert got == [7]
    await coord.close()


async def test_abort_unblocks_waiters():
    coord = AffinityCoordinator(ttl_s=5.0).start()
    e1 = await coord.acquire("s")

    async def second():
        return await coord.acquire("s")

    t = asyncio.create_task(second())
    await asyncio.sleep(0.02)
    coord.abort("s", e1)  # routing failed
    e2 = await asyncio.wait_for(t, 2.0)
    assert e2 is not None and not e2.bound  # waiter takes over as binder
    coord.abort("s", e2)
    await coord.close()


async def test_capacity_cap_skips_affinity():
    coord = AffinityCoordinator(ttl_s=60.0, max_entries=2).start()
    for i in range(2):
        e = await coord.acquire(f"s{i}")
        coord.bind(f"s{i}", e, i)
        coord.release(f"s{i}", e)
    assert await coord.acquire("s-over") is None  # full, nothing expired
    assert await coord.acquire("x" * 300) is None  # oversized id
    await coord.close()


async def test_replica_sync_converges():
    rt = await fresh_runtime().start()
    try:
        a = AffinityCoordinator(ttl_s=30.0).start()
        b = AffinityCoordinator(ttl_s=30.0).start()
        await a.enable_replica_sync(rt, "ns", "comp")
        await b.enable_replica_sync(rt, "ns", "comp")
        e = await a.acquire("shared")
        a.bind("shared", e, 99)
        a.release("shared", e)
        for _ in range(100):
            be = b.entries.get("shared")
            if be is not None and be.bound:
                break
            await asyncio.sleep(0.02)
        be = await b.acquire("shared")
        assert be.bound and be.worker_id == 99
        b.release("shared", be)
        await a.close()
        await b.close()
    finally:
        await rt.shutdown()


# --------------------------- router wrapper ---------------------------------


async def _mock_fleet(rt, n=2, model="aff-model"):
    args = MockEngineArgs(model_name=model, block_size=4,
                          base_step_s=0.0005, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0)
    workers = [await MockerWorker(rt, args).start() for _ in range(n)]
    client = await (rt.namespace("dynamo").component("mocker")
                    .endpoint("generate")
                    .client(RouterMode.ROUND_ROBIN)).start()
    await client.wait_for_instances()
    while len(client.instances) < n:
        await asyncio.sleep(0.02)
    return workers, client


def _req(rid: str, sid=None, final=False) -> PreprocessedRequest:
    return PreprocessedRequest(token_ids=list(range(8)), request_id=rid,
                               stop=StopConditions(max_tokens=2),
                               session_id=sid, session_final=final)


async def test_sticky_routing_and_failover():
    rt = await fresh_runtime().start()
    try:
        workers, client = await _mock_fleet(rt)
        coord = AffinityCoordinator(ttl_s=30.0).start()
        router = SessionAffinityRouter(coord, client)

        first = await router(_req("r1", sid="sess"))
        assert first in client.instance_ids
        router.complete("r1")
        # round-robin inner would alternate; affinity pins
        for i in range(4):
            rid = f"r{i + 2}"
            assert await router(_req(rid, sid="sess")) == first
            router.complete(rid)
        # no session id -> no pin; the client's own push router picks
        assert await router(_req("n0")) is None

        # bound worker dies -> rebind to the survivor
        dead = next(w for w in workers if w.served.instance_id == first)
        await dead.close()
        while first in client.instance_ids:
            await asyncio.sleep(0.02)
        second = await router(_req("rf", sid="sess"))
        assert second != first and second in client.instance_ids
        router.complete("rf")
        await router.close()
        await client.close()
        for w in workers:
            if w is not dead:
                await w.close()
    finally:
        await rt.shutdown()


async def test_avoid_set_overrides_binding():
    """Migration's avoid-set must beat stickiness (the pinned worker just
    failed this very request)."""
    rt = await fresh_runtime().start()
    try:
        workers, client = await _mock_fleet(rt)
        coord = AffinityCoordinator(ttl_s=30.0).start()
        router = SessionAffinityRouter(coord, client)
        first = await router(_req("r1", sid="s"))
        second = await router(_req("r1", sid="s"), avoid={first})
        assert second != first
        router.complete("r1")
        # rebound: later requests follow the new worker
        nxt = await router(_req("r2", sid="s"))
        assert nxt == second
        router.complete("r2")
        await router.close()
        await client.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


async def test_session_final_evicts_binding():
    rt = await fresh_runtime().start()
    try:
        workers, client = await _mock_fleet(rt)
        coord = AffinityCoordinator(ttl_s=30.0).start()
        router = SessionAffinityRouter(coord, client)
        await router(_req("r1", sid="s", final=True))
        router.complete("r1")
        assert "s" not in coord.entries
        await router.close()
        await client.close()
        for w in workers:
            await w.close()
    finally:
        await rt.shutdown()


# --------------------------- HTTP e2e ---------------------------------------


async def test_http_session_header_pins_worker():
    """Full stack: chat requests carrying an agent session header all land
    on one worker; unpinned requests round-robin."""
    rt = await fresh_runtime().start()
    model = "aff-http"
    args = MockEngineArgs(model_name=model, block_size=4,
                          base_step_s=0.0005, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0)
    workers = [await MockerWorker(rt, args).start() for _ in range(2)]
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager,
                                 session_affinity_ttl=30.0).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get(model):
            break
        await asyncio.sleep(0.02)
    try:
        body = {"model": model,
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 2, "ignore_eos": True}
        async with aiohttp.ClientSession() as s:
            for _ in range(4):
                async with s.post(
                        f"http://127.0.0.1:{port}/v1/chat/completions",
                        json=body,
                        headers={"x-claude-code-session-id": "cc"}) as r:
                    assert r.status == 200
        route = manager.get(model).migration.route
        assert isinstance(route, SessionAffinityRouter)
        entry = route.coordinator.entries.get("cc")
        assert entry is not None and entry.bound
        served = [w.engine.metrics["requests"] for w in workers]
        assert sorted(served) == [0, 4]  # all four on the pinned worker
    finally:
        await service.close()
        await watcher.close()
        for w in workers:
            await w.close()
        await rt.shutdown()
