"""Observability batch: structured JSONL logging, frontend TTFT/ITL/
queue-depth metrics, and per-worker routing counters (ref: the
reference's metrics.rs hierarchy + structured logging surface)."""

import asyncio
import json
import logging
import uuid

import aiohttp

from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig
from dynamo_tpu.runtime.logging import JsonFormatter


def fresh_runtime() -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


# ------------------------------ logging ------------------------------------


def test_json_formatter_structured_fields():
    fmt = JsonFormatter()
    rec = logging.LogRecord("dynamo_tpu.router", logging.INFO, "f.py", 10,
                            "routed %s", ("r1",), None)
    rec.worker_id = 42
    rec.overlap_blocks = 7
    out = json.loads(fmt.format(rec))
    assert out["level"] == "INFO"
    assert out["logger"] == "dynamo_tpu.router"
    assert out["msg"] == "routed r1"
    assert out["worker_id"] == 42 and out["overlap_blocks"] == 7
    assert isinstance(out["ts"], float)


def test_json_formatter_handles_unserializable_extra():
    fmt = JsonFormatter()
    rec = logging.LogRecord("x", logging.WARNING, "f.py", 1, "m", (), None)
    rec.weird = object()
    out = json.loads(fmt.format(rec))
    assert out["weird"].startswith("<object object")


def test_json_formatter_exception():
    fmt = JsonFormatter()
    try:
        raise ValueError("boom")
    except ValueError:
        import sys

        rec = logging.LogRecord("x", logging.ERROR, "f.py", 1, "failed",
                                (), sys.exc_info())
    out = json.loads(fmt.format(rec))
    assert "ValueError: boom" in out["exc"]


# ------------------------------ metrics ------------------------------------


async def test_frontend_latency_metrics_exported():
    """A served chat request must leave TTFT/ITL samples, the inflight
    gauge, and output-token counters on /metrics."""
    rt = await fresh_runtime().start()
    args = MockEngineArgs(model_name="obs-model", block_size=4,
                          base_step_s=0.0005, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0)
    worker = await MockerWorker(rt, args).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get("obs-model"):
            break
        await asyncio.sleep(0.02)
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "obs-model",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 8, "ignore_eos": True}
            async with s.post(f"http://127.0.0.1:{port}/v1/chat/completions",
                              json=body) as r:
                assert r.status == 200
            async with s.get(f"http://127.0.0.1:{port}/metrics") as r:
                text = await r.text()
        assert 'dynamo_frontend_ttft_seconds_count{' in text
        assert 'dynamo_frontend_itl_seconds_count{' in text
        assert 'model="obs-model"' in text
        assert "dynamo_frontend_inflight" in text
        # 8 generated tokens counted
        for line in text.splitlines():
            if line.startswith("dynamo_frontend_output_tokens_total{"):
                assert float(line.rsplit(" ", 1)[1]) == 8.0
                break
        else:
            raise AssertionError("output_tokens_total not exported")
    finally:
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()


async def test_router_pick_counters():
    """KV-routed requests appear in per-worker routing counters."""
    from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
    from dynamo_tpu.router import KvRouter

    rt = await fresh_runtime().start()
    args = MockEngineArgs(model_name="m", block_size=4, base_step_s=0.0005)
    w = await MockerWorker(rt, args).start()
    client = await (rt.namespace("dynamo").component("mocker")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    router = await KvRouter(rt, "dynamo", "mocker", client,
                            block_size=4).start()
    req = PreprocessedRequest(token_ids=list(range(12)), request_id="r1",
                              stop=StopConditions(max_tokens=4))
    choice = await router.pick(req)
    assert choice == w.served.instance_id
    text = rt.metrics.render().decode()
    assert "dynamo_router_routed_requests_total" in text
    assert f'worker="{choice}"' in text
    assert "dynamo_router_overlap_blocks_count" in text
    await router.close()
    await client.close()
    await w.close()
    await rt.shutdown()
