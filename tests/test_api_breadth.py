"""API breadth: reasoning/tool-call stream parsing, SSE usage chunks,
and /v1/embeddings (ref: the reference's http route families +
preprocessor.rs stream parsers)."""

import pytest
import asyncio
import json
import uuid

import aiohttp
import numpy as np

from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.frontend.parsers import (
    OutputParser,
    ReasoningParser,
    ToolCallParser,
)
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig


def fresh_runtime() -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


# ------------------------------ parsers ------------------------------------


def test_reasoning_parser_split_across_chunks():
    p = ReasoningParser()
    # the tags arrive split across arbitrary chunk boundaries
    chunks = ["<th", "ink>let me ", "think</thi", "nk>the answer"]
    content, reasoning = "", ""
    for c in chunks:
        co, re = p.push(c)
        content += co
        reasoning += re
    co, re = p.flush()
    content += co
    reasoning += re
    assert content == "the answer"
    assert reasoning == "let me think"


def test_reasoning_parser_unclosed_span_stays_reasoning():
    p = ReasoningParser()
    c1, r1 = p.push("<think>truncated stream")
    c2, r2 = p.flush()
    assert c1 + c2 == ""
    assert r1 + r2 == "truncated stream"


def test_reasoning_parser_r1_implicit_open():
    """R1-style templates end the prompt with <think>: the model emits
    only the close tag, so the parser must start inside the span."""
    p = ReasoningParser(start_in_reasoning=True)
    content, reasoning = "", ""
    for c in ("chain of ", "thought</th", "ink>answer"):
        co, re = p.push(c)
        content += co
        reasoning += re
    co, re = p.flush()
    assert content + co == "answer"
    assert reasoning + re == "chain of thought"
    # a model that repeats the open tag anyway is also handled
    p2 = ReasoningParser(start_in_reasoning=True)
    co1, re1 = p2.push("<think>x</think>y")
    co2, re2 = p2.flush()
    assert co1 + co2 == "y" and re1 + re2 == "x"


def test_tool_call_parser_extracts_openai_shape():
    p = ToolCallParser()
    text = ('before <tool_call>{"name": "get_weather", "arguments": '
            '{"city": "SF"}}</tool_call> after')
    content, calls = "", []
    for i in range(0, len(text), 7):  # arbitrary chunking
        c, cs = p.push(text[i:i + 7])
        content += c
        calls += cs
    content += p.flush()
    assert content == "before  after"
    assert len(calls) == 1
    call = calls[0]
    assert call["type"] == "function"
    assert call["function"]["name"] == "get_weather"
    assert json.loads(call["function"]["arguments"]) == {"city": "SF"}


def test_tool_call_parser_malformed_json_falls_back_to_content():
    p = ToolCallParser()
    content, calls = p.push("<tool_call>not json</tool_call>done")
    content += p.flush()
    assert calls == []
    assert "not json" in content and "done" in content


def test_tool_call_parser_unterminated_flushes_verbatim():
    p = ToolCallParser()
    content, calls = p.push('x <tool_call>{"name": "f"')
    assert content == "x " and calls == []
    assert p.flush() == '<tool_call>{"name": "f"'


def test_output_parser_composes_reasoning_then_tools():
    p = OutputParser(reasoning=True, tools=True)
    text = ('<think>plan the call</think>ok '
            '<tool_call>{"name": "f", "arguments": {"a": 1}}</tool_call>')
    content, reasoning, calls = "", "", []
    for i in range(0, len(text), 5):
        out = p.push(text[i:i + 5])
        content += out.content
        reasoning += out.reasoning
        calls += out.tool_calls
    out = p.flush()
    content += out.content
    assert reasoning == "plan the call"
    assert content.strip() == "ok"
    assert len(calls) == 1 and p.saw_tool_call


# ----------------------------- service e2e ---------------------------------


CANNED = ('<think>I should call f</think>hello '
          '<tool_call>{"name": "f", "arguments": {"x": 2}}</tool_call>')


async def start_stack(model_name="api-model", canned="", reasoning="",
                      **kw):
    rt = await fresh_runtime().start()
    args = MockEngineArgs(model_name=model_name, block_size=4,
                          base_step_s=0.0002, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0, canned_text=canned, **kw)
    worker = await MockerWorker(rt, args,
                                reasoning_parser=reasoning).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get(model_name):
            break
        await asyncio.sleep(0.02)
    assert manager.get(model_name)
    return rt, worker, watcher, service, f"http://127.0.0.1:{port}"


async def stop_stack(rt, worker, watcher, service):
    await service.close()
    await watcher.close()
    await worker.close()
    await rt.shutdown()


async def test_chat_tools_and_reasoning_unary():
    stack = await start_stack(canned=CANNED, reasoning="deepseek_r1")
    rt, worker, watcher, service, url = stack
    try:
        body = {
            "model": "api-model",
            "messages": [{"role": "user", "content": "weather?"}],
            "max_tokens": 300,
            "tools": [{"type": "function",
                       "function": {"name": "f", "parameters": {}}}],
        }
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{url}/v1/chat/completions", json=body) as r:
                assert r.status == 200, await r.text()
                data = await r.json()
        msg = data["choices"][0]["message"]
        assert msg["reasoning_content"] == "I should call f"
        assert msg["content"].strip() == "hello"
        assert msg["tool_calls"][0]["function"]["name"] == "f"
        assert json.loads(
            msg["tool_calls"][0]["function"]["arguments"]) == {"x": 2}
        assert data["choices"][0]["finish_reason"] == "tool_calls"
    finally:
        await stop_stack(*stack[:4])


async def test_chat_stream_parsers_and_usage_chunk():
    stack = await start_stack(canned=CANNED, reasoning="deepseek_r1")
    rt, worker, watcher, service, url = stack
    try:
        body = {
            "model": "api-model",
            "messages": [{"role": "user", "content": "go"}],
            "max_tokens": 300,
            "stream": True,
            "stream_options": {"include_usage": True},
            "tools": [{"type": "function",
                       "function": {"name": "f", "parameters": {}}}],
        }
        reasoning, content, calls, usage = "", "", [], None
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{url}/v1/chat/completions", json=body) as r:
                assert r.status == 200
                async for line in r.content:
                    line = line.decode().strip()
                    if not line.startswith("data: ") or line.endswith(
                            "[DONE]"):
                        continue
                    obj = json.loads(line[6:])
                    if obj.get("usage") is not None:
                        usage = obj["usage"]
                    for ch in obj.get("choices", []):
                        d = ch.get("delta", {})
                        reasoning += d.get("reasoning_content", "")
                        content += d.get("content", "")
                        calls += d.get("tool_calls") or []
        assert reasoning == "I should call f"
        assert content.strip() == "hello"
        assert len(calls) == 1 and calls[0]["function"]["name"] == "f"
        assert usage is not None and usage["completion_tokens"] > 0
    finally:
        await stop_stack(*stack[:4])


async def test_embeddings_route_with_mocker():
    stack = await start_stack()
    rt, worker, watcher, service, url = stack
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "api-model",
                    "input": ["hello world", "other text"]}
            async with s.post(f"{url}/v1/embeddings", json=body) as r:
                assert r.status == 200, await r.text()
                data = await r.json()
            assert data["object"] == "list" and len(data["data"]) == 2
            v0 = np.asarray(data["data"][0]["embedding"])
            v1 = np.asarray(data["data"][1]["embedding"])
            assert abs(np.linalg.norm(v0) - 1.0) < 1e-6
            assert not np.allclose(v0, v1)
            assert data["usage"]["prompt_tokens"] > 0
            # determinism: same input -> same vector
            async with s.post(f"{url}/v1/embeddings", json={
                "model": "api-model", "input": "hello world"}) as r2:
                d2 = await r2.json()
            np.testing.assert_allclose(
                v0, np.asarray(d2["data"][0]["embedding"]))
            # token-array input form
            async with s.post(f"{url}/v1/embeddings", json={
                "model": "api-model", "input": [5, 6, 7]}) as r3:
                assert r3.status == 200
                d3 = await r3.json()
                assert len(d3["data"]) == 1
    finally:
        await stop_stack(*stack[:4])


# real JAX engine in an async body: -O0 compiles dwarf the 200ms
# loop gate (see conftest); mocker-based tests here stay gated
@pytest.mark.allow_slow_callbacks
async def test_jax_engine_embed_pooled_unit_vector():
    from dynamo_tpu.engine import EngineConfig, JaxEngine

    import jax.numpy as jnp
    from dynamo_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(name="t32", vocab_size=128, d_model=32, n_layers=2,
                      n_heads=4, n_kv_heads=2, head_dim=8, ffn_dim=64,
                      dtype=jnp.float32)
    eng = JaxEngine(EngineConfig(model_config=cfg, block_size=4,
                                 num_blocks=16, max_blocks_per_seq=8,
                                 max_num_seqs=2, prefill_buckets=(8, 16)))
    try:
        v1 = await eng.embed([5, 9, 13])
        v2 = await eng.embed([5, 9, 13])
        v3 = await eng.embed([7, 7, 7, 7])
        assert v1.shape == (32,)
        assert abs(float(np.linalg.norm(v1)) - 1.0) < 1e-5
        np.testing.assert_allclose(v1, v2)
        assert not np.allclose(v1, v3)
        # bucketing: a length crossing into the next bucket still works
        v4 = await eng.embed(list(range(3, 15)))
        assert v4.shape == (32,)
    finally:
        await eng.close()


# --------------------- anthropic /v1/messages parsers ----------------------


async def test_anthropic_messages_tools_unary():
    stack = await start_stack(canned=CANNED, reasoning="deepseek_r1")
    rt, worker, watcher, service, url = stack
    try:
        body = {
            "model": "api-model",
            "messages": [{"role": "user", "content": "weather?"}],
            "max_tokens": 300,
            "tools": [{"name": "f", "description": "",
                       "input_schema": {"type": "object"}}],
        }
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{url}/v1/messages", json=body) as r:
                assert r.status == 200, await r.text()
                data = await r.json()
        kinds = [b["type"] for b in data["content"]]
        assert kinds == ["thinking", "text", "tool_use"]
        assert data["content"][0]["thinking"] == "I should call f"
        assert "signature" in data["content"][0]
        assert data["content"][1]["text"].strip() == "hello"
        tu = data["content"][2]
        assert tu["name"] == "f" and tu["input"] == {"x": 2}
        assert tu["id"].startswith("toolu_")
        assert data["stop_reason"] == "tool_use"
        # no raw tags anywhere in the text block
        assert "<tool_call>" not in data["content"][1]["text"]
        assert "<think>" not in data["content"][1]["text"]
    finally:
        await stop_stack(*stack[:4])


async def test_anthropic_messages_tools_stream():
    stack = await start_stack(canned=CANNED, reasoning="deepseek_r1")
    rt, worker, watcher, service, url = stack
    try:
        body = {
            "model": "api-model",
            "messages": [{"role": "user", "content": "go"}],
            "max_tokens": 300,
            "stream": True,
            "tools": [{"name": "f", "description": "",
                       "input_schema": {"type": "object"}}],
        }
        events = []
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{url}/v1/messages", json=body) as r:
                assert r.status == 200
                raw = (await r.read()).decode()
        for block in raw.strip().split("\n\n"):
            lines = dict(ln.split(": ", 1) for ln in block.splitlines()
                         if ": " in ln)
            if "event" in lines:
                events.append((lines["event"], json.loads(lines["data"])))
        starts = [d for n, d in events if n == "content_block_start"]
        stops = [d for n, d in events if n == "content_block_stop"]
        kinds = [d["content_block"]["type"] for d in starts]
        assert kinds == ["thinking", "text", "tool_use"]
        # indices strictly increase and every start has a stop
        assert [d["index"] for d in starts] == [0, 1, 2]
        assert sorted(d["index"] for d in stops) == [0, 1, 2]
        thinking = "".join(
            d["delta"]["thinking"] for n, d in events
            if n == "content_block_delta"
            and d["delta"]["type"] == "thinking_delta")
        text = "".join(
            d["delta"]["text"] for n, d in events
            if n == "content_block_delta"
            and d["delta"]["type"] == "text_delta")
        tool_json = "".join(
            d["delta"]["partial_json"] for n, d in events
            if n == "content_block_delta"
            and d["delta"]["type"] == "input_json_delta")
        assert thinking == "I should call f"
        # thinking block closes with a signature_delta (SDK schema)
        assert any(n == "content_block_delta"
                   and d["delta"]["type"] == "signature_delta"
                   for n, d in events)
        assert text.strip() == "hello" and "<tool_call>" not in text
        assert json.loads(tool_json) == {"x": 2}
        tu = next(d["content_block"] for d in starts
                  if d["content_block"]["type"] == "tool_use")
        assert tu["name"] == "f"
        md = next(d for n, d in events if n == "message_delta")
        assert md["delta"]["stop_reason"] == "tool_use"
    finally:
        await stop_stack(*stack[:4])


def test_anthropic_tool_round_trip_messages():
    from dynamo_tpu.frontend.anthropic import _to_chat_body

    body = {
        "model": "m", "max_tokens": 5,
        "messages": [
            {"role": "user", "content": "weather?"},
            {"role": "assistant", "content": [
                {"type": "thinking", "thinking": "hmm"},
                {"type": "text", "text": "checking"},
                {"type": "tool_use", "id": "toolu_1", "name": "f",
                 "input": {"x": 2}}]},
            {"role": "user", "content": [
                {"type": "tool_result", "tool_use_id": "toolu_1",
                 "content": [{"type": "text", "text": "sunny"}]}]},
        ],
    }
    chat, _ = _to_chat_body(body)
    msgs = chat["messages"]
    roles = [m["role"] for m in msgs]
    assert roles == ["user", "assistant", "tool"]
    # assistant turn re-renders the call as the hermes span the model
    # originally emitted; prior thinking is dropped from context
    atext = "".join(p["text"] for p in msgs[1]["content"])
    assert '<tool_call>{"name": "f", "arguments": {"x": 2}}</tool_call>' \
        in atext
    assert "hmm" not in atext
    assert msgs[2]["tool_call_id"] == "toolu_1"
    assert msgs[2]["content"] == "sunny"


def test_anthropic_tool_result_precedes_trailing_text():
    # Anthropic requires tool_result blocks to lead a user message; the
    # peeled role-"tool" message must stay adjacent to the assistant
    # tool-call turn, with the user's follow-up text AFTER it
    from dynamo_tpu.frontend.anthropic import _split_tool_blocks

    msgs = _split_tool_blocks({
        "role": "user",
        "content": [
            {"type": "tool_result", "tool_use_id": "toolu_1",
             "content": "sunny"},
            {"type": "text", "text": "now summarize"}]})
    assert [m["role"] for m in msgs] == ["tool", "user"]
    assert msgs[0]["content"] == "sunny"

    # non-text blocks inside tool_result raise (never silently dropped)
    import pytest
    with pytest.raises(ValueError):
        _split_tool_blocks({
            "role": "user",
            "content": [{"type": "tool_result", "tool_use_id": "t",
                         "content": [{"type": "image",
                                      "source": {"type": "base64",
                                                 "data": ""}}]}]})
