"""KV ledger plane (obs/kv_ledger.py): block-lifecycle accounting,
the invariant auditor (leak / double-free / orphan / refcount-drift —
each chaos-provable), per-tier attribution on /debug/kv, fleet folding,
and the kv_events snapshot-on-subscribe replay (ROADMAP item 2's
ingestion contract)."""

import asyncio
import json
import uuid

import pytest

from dynamo_tpu import chaos
from dynamo_tpu.engine.block_allocator import BlockAllocator
from dynamo_tpu.obs.kv_ledger import (
    KvLedger,
    LEDGER_OPS,
    ledger_enabled,
)

H = lambda i: (0xABC0000 + i) << 64  # 128-bit-ish PLH stand-ins # noqa: E731


def kinds_of(violations):
    return sorted({v["kind"] for v in violations})


# ---------------------- allocator-level accounting -----------------------


def test_allocator_lifecycle_mirrors_and_reconciles():
    """The full G1 lifecycle — allocate (miss + prefix hit), commit,
    append, free-to-cache, trim, clear — keeps the ledger's books in
    exact agreement with the allocator at every stage (0 violations),
    with the attribution states tracking the transitions."""
    led = KvLedger()
    a = BlockAllocator(16, ledger=led)

    res = a.allocate("s1", [H(1), H(2)], 3)
    assert res is not None and res.cached_blocks == 0
    a.commit_block("s1", 0, H(1))
    a.commit_block("s1", 1, H(2))
    assert led.audit_allocator(a, live_seqs=["s1"]) == []
    assert led.attribution()["g1"]["active"] == 3

    grow = a.append_block("s1")
    assert grow.block_id is not None
    assert led.audit_allocator(a, live_seqs=["s1"]) == []
    assert led.attribution()["g1"]["active"] == 4

    # spec-rollback trim releases the grown block
    a.trim_blocks("s1", 3)
    assert led.audit_allocator(a, live_seqs=["s1"]) == []

    a.free("s1")
    assert led.audit_allocator(a, live_seqs=[]) == []
    att = led.attribution()["g1"]
    # the two committed blocks stay prefix-cached; the partial freed
    assert att["active"] == 0 and att["prefix_cached"] == 2

    # prefix reuse pins the cached blocks for a second sequence
    res2 = a.allocate("s2", [H(1), H(2)], 4)
    assert res2.cached_blocks == 2
    assert led.audit_allocator(a, live_seqs=["s2"]) == []
    assert led.attribution()["g1"]["active"] == 4
    a.free("s2")

    removed = a.clear_cached()
    assert len(removed) == 2
    assert led.audit_allocator(a, live_seqs=[]) == []
    assert led.attribution()["g1"]["tracked"] == 0

    # the event tape recorded every op class it should have
    ops = {e[1] for e in led.events}
    assert {"alloc", "pin", "unpin", "cache", "commit", "evict",
            "release"} <= ops
    assert ops <= LEDGER_OPS
    # and op counts are exported for /debug/kv
    assert led.dump()["counts"]["alloc"] >= 5


def test_capacity_rollback_keeps_books_clean():
    """An allocate() that fails capacity after pinning its prefix hits
    must roll the ledger back too (the unpin path) — the books stay
    clean and the hits return to prefix-cached."""
    led = KvLedger()
    a = BlockAllocator(5, ledger=led)  # 4 usable
    a.allocate("s1", [H(1)], 2)
    a.commit_block("s1", 0, H(1))
    a.free("s1")  # H(1) cached
    # needs 5 blocks against 4 usable: must fail and roll back the pin
    assert a.allocate("s2", [H(1)], 5) is None
    assert led.audit_allocator(a, live_seqs=[]) == []
    assert led.attribution()["g1"]["prefix_cached"] == 1


@pytest.mark.parametrize("kind", ["leak", "double_free", "orphan",
                                  "refcount_drift"])
def test_auditor_catches_chaos_seeded_violations(kind, tmp_path):
    """Each accounting-fault class seeded through the engine.kv_account
    chaos seam is caught by the reconciliation sweep, attributed to
    tier + block (+ seq where one exists), counted into the violation
    totals, and snapshots the flight recorder on first occurrence."""
    from dynamo_tpu import obs

    reported = kind.replace("_", "-").replace("refcount-drift",
                                              "refcount-drift")
    expect_kind = {"leak": "leak", "double_free": "double-free",
                   "orphan": "orphan",
                   "refcount_drift": "refcount-drift"}[kind]
    led = KvLedger()
    a = BlockAllocator(16, ledger=led)
    plane = chaos.ChaosPlane(seed=3)
    plane.rule("engine.kv_account", "drop", match=f"{kind}:", times=1)
    tr = obs.Tracer(out_path=str(tmp_path / "trace.json"))
    tr.install()
    try:
        with plane:
            a.allocate("victim", [], 3)
            a.free("victim")
        assert plane.fired("engine.kv_account") == 1
        viol = led.audit_allocator(a, live_seqs=[])
        report = led.finish_audit(viol, where="test")
    finally:
        tr.uninstall()
    assert not report["clean"]
    assert expect_kind in kinds_of(viol), (reported, viol)
    first = next(v for v in viol if v["kind"] == expect_kind)
    assert first["tier"] == "g1"
    assert "block" in first
    if expect_kind in ("leak", "orphan"):
        assert first.get("seq_id") == "victim"
    # violation totals are monotonic and keyed (kind, tier)
    assert led.violations_by_kind()[expect_kind]["g1"] >= 1
    # first occurrence of the class dumped the flight recorder
    assert tr.flight_dumps, "expected a kv_ledger flight-recorder dump"


def test_auditor_catches_direct_mutation_orphan_and_drift():
    """The DYN013 bug class at runtime: rogue code mutating the
    allocator's books behind the ledger's back is exactly what the
    auditor reports."""
    led = KvLedger()
    a = BlockAllocator(16, ledger=led)
    a.allocate("s1", [], 2)
    bid = a.seq_block_ids("s1")[0]
    # dynlint: disable=DYN013 deliberately corrupting the books to prove the auditor catches it
    a._block_ref[bid] += 1
    viol = led.audit_allocator(a, live_seqs=["s1"])
    assert "refcount-drift" in kinds_of(viol)

    led2 = KvLedger()
    b = BlockAllocator(16, ledger=led2)
    b.allocate("s2", [], 2)
    bid2 = b.seq_block_ids("s2")[-1]
    # release behind the ledger's back (the books now point at a ghost)
    # dynlint: disable=DYN013 deliberately corrupting the books to prove the auditor catches it
    b._block_ref.pop(bid2)
    # dynlint: disable=DYN013 deliberately corrupting the books to prove the auditor catches it
    b._free.append(bid2)
    viol = led2.audit_allocator(b, live_seqs=["s2"])
    assert "orphan" in kinds_of(viol)


def test_fragmentation_counts_dead_cached_tails():
    """Lineage fragmentation: a cached block whose parent was evicted
    can never be prefix-hit again (matching walks leading runs) — the
    attribution reports it as dead capacity."""
    led = KvLedger()
    a = BlockAllocator(4, ledger=led)  # 3 usable
    a.allocate("s1", [H(1), H(2)], 3)
    a.commit_block("s1", 0, H(1))
    a.commit_block("s1", 1, H(2))  # parent = H(1)
    a.free("s1")  # H(1), H(2) cached (LRU order: 1 then 2), partial freed
    frag = led.attribution()["g1"]["fragmentation"]
    assert frag["dead_cached"] == 0
    # two fresh blocks evict H(1) — the LRU-coldest — leaving H(2)'s
    # parent gone
    a.allocate("s2", [], 2)
    assert led.audit_allocator(a, live_seqs=["s2"]) == []
    frag = led.attribution()["g1"]["fragmentation"]
    assert frag["dead_cached"] == 1 and frag["dead_frac"] == 1.0


def test_kvbm_manifest_reconciliation(tmp_path):
    """Tier books: stage/evict events keep the ledger's tier sets equal
    to the pool manifests; a pool mutation the ledger never saw is a
    leak (pool-only) or orphan (ledger-only), attributed to the tier."""
    import numpy as np

    from dynamo_tpu.kvbm.manager import TieredKvManager

    led = KvLedger()
    mgr = TieredKvManager(host_blocks=4)
    k = np.zeros((1, 2, 1, 4), np.float32)

    def feed(batches):
        for stored, removed, tier in batches:
            led.tier_batch(stored, removed, tier)

    for i in range(4):
        feed(mgr.offload(H(i), k, k))
    assert led.audit_kvbm(mgr) == []
    # a fifth offload LRU-evicts H(0) (no g3: dropped) — still clean
    feed(mgr.offload(H(5), k, k))
    assert led.audit_kvbm(mgr) == []
    # pool mutation behind the ledger's back
    mgr.g2.drop(H(1))
    viol = led.audit_kvbm(mgr)
    assert kinds_of(viol) == ["orphan"] and viol[0]["tier"] == "g2"
    led.tier_batch([], [H(1)], "g2")  # reconcile
    mgr.g2.put(H(9), k, k)
    viol = led.audit_kvbm(mgr)
    assert kinds_of(viol) == ["leak"] and viol[0]["tier"] == "g2"
    mgr.close()


def test_ledger_enabled_gate(monkeypatch):
    monkeypatch.delenv("DYN_KV_LEDGER", raising=False)
    assert ledger_enabled(None) is True
    monkeypatch.setenv("DYN_KV_LEDGER", "0")
    assert ledger_enabled(None) is False
    assert ledger_enabled(True) is True  # explicit config wins
    monkeypatch.setenv("DYN_KV_LEDGER", "1")
    assert ledger_enabled(False) is False


# ---------------------- engine integration -------------------------------


@pytest.mark.allow_slow_callbacks
async def test_engine_e2e_clean_audit_with_kvbm_and_cadence():
    """A real tiny JAX engine serving shared-prefix requests with KVBM
    offload enabled: the finish-cadence audit runs on its own, the
    on-demand /debug/kv audit reconciles exactly (0 violations), and
    the attribution carries prefix-cached blocks + tier occupancy."""
    from test_engine import FP32, collect, greedy_req

    from dynamo_tpu.engine import EngineConfig, JaxEngine

    eng = JaxEngine(EngineConfig(
        model_config=FP32, block_size=4, num_blocks=32,
        max_blocks_per_seq=8, max_num_seqs=2,
        prefill_buckets=(8, 16, 32), seed=7,
        host_cache_blocks=8, offload_watermark_blocks=30,
    ))
    assert eng.kv_ledger is not None
    prefix = list(range(40, 52))
    await collect(eng, greedy_req(prefix + [1, 2], 4, "r1"))
    await collect(eng, greedy_req(prefix + [7, 8], 4, "r2"))
    # the request-finish cadence audited without being asked
    for _ in range(100):
        if eng.kv_ledger.last_audit is not None:
            break
        await asyncio.sleep(0.02)
    assert eng.kv_ledger.last_audit is not None
    # on-demand audit (the /debug/kv path): clean books
    report = await eng.audit_kv()
    assert report["clean"], report
    att = eng.kv_ledger.attribution()
    assert att["g1"]["prefix_cached"] > 0
    assert att["g1"]["active"] == 0
    # offload staged blocks into g2 and the tier books agree
    assert att.get("g2", {}).get("blocks", 0) > 0
    dump = eng.kv_ledger.dump()
    assert dump["schema"] == "dynamo.kv_ledger.v1"
    assert dump["violations_total"] == {}
    await eng.close()


@pytest.mark.allow_slow_callbacks
async def test_engine_ledger_disabled_is_none():
    """kv_ledger=False (or DYN_KV_LEDGER=0) keeps the whole plane off:
    no ledger object, allocator hooks are one pointer compare, serving
    is unaffected."""
    from test_engine import FP32, collect, greedy_req

    from dynamo_tpu.engine import EngineConfig, JaxEngine

    eng = JaxEngine(EngineConfig(
        model_config=FP32, block_size=4, num_blocks=32,
        max_blocks_per_seq=8, max_num_seqs=2,
        prefill_buckets=(8, 16), seed=7, kv_ledger=False,
    ))
    assert eng.kv_ledger is None and eng.allocator.ledger is None
    toks = await collect(eng, greedy_req(list(range(10)), 4, "r1"))
    assert len(toks) == 4
    assert await eng.audit_kv() == {}
    await eng.close()


@pytest.mark.allow_slow_callbacks
async def test_engine_parked_blocks_attributed_pinned_by_transfer():
    """Disagg handoff accounting: a parked prefill's blocks attribute
    as pinned-by-transfer (not active, not leaked) and reconcile clean;
    releasing the parked KV returns them to the prefix cache."""
    from test_engine import FP32, greedy_req

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.protocols.llm import DISAGG_ANNOTATION

    eng = JaxEngine(EngineConfig(
        model_config=FP32, block_size=4, num_blocks=32,
        max_blocks_per_seq=8, max_num_seqs=2,
        prefill_buckets=(8, 16, 32), seed=7, role="prefill"))
    req = greedy_req(list(range(30, 44)), 4, "park1")
    req.annotations = [DISAGG_ANNOTATION]
    async for _ in eng.generate(req):
        pass
    att = eng.kv_ledger.attribution()["g1"]
    assert att["pinned_by_transfer"] > 0, att
    report = await eng.audit_kv()
    assert report["clean"], report
    await eng.release_parked("park1")
    att = eng.kv_ledger.attribution()["g1"]
    assert att["pinned_by_transfer"] == 0
    assert att["prefix_cached"] > 0
    report = await eng.audit_kv()
    assert report["clean"], report
    await eng.close()


# ---------------------- mocker parity ------------------------------------


async def test_mocker_ledger_parity_clean_audit():
    """The capacity sim feeds the same ledger (hash-keyed): a mocker
    serving shared-prefix streams reconciles exactly, with attribution
    matching the sim's own used-block count."""
    from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        StopConditions,
    )

    eng = MockEngine(MockEngineArgs(
        model_name="m", block_size=4, num_blocks=64,
        base_step_s=0.0001, prefill_s_per_token=0.0,
        decode_s_per_seq=0.0))
    assert eng.kv_ledger is not None

    async def run(rid, toks):
        req = PreprocessedRequest(
            token_ids=toks, request_id=rid,
            stop=StopConditions(max_tokens=6, ignore_eos=True))
        async for _ in eng.generate(req):
            pass

    prefix = list(range(16))
    await asyncio.gather(run("a", prefix + [1]), run("b", prefix + [2]))
    report = eng.audit_kv()
    assert report["clean"], report
    att = eng.kv_ledger.attribution()["g1"]
    assert att["prefix_cached"] > 0 and att["active"] == 0
    # ledger tracked == sim used (cached blocks hold no partials now)
    assert att["tracked"] == eng.cache.used_blocks
    # the sim's finish cadence audited on its own too
    assert eng.kv_ledger.last_audit is not None
    await eng.close()


async def test_mocker_auditor_catches_sim_corruption():
    """Direct sim-book mutation (the DYN013 class, mocker side) is
    classified: a dropped refcount is drift, a vanished entry an
    orphan, an unledgered one a leak."""
    from dynamo_tpu.mocker.kv_cache_sim import KvCacheSim

    led = KvLedger()
    sim = KvCacheSim(16, ledger=led)
    sim.allocate("s1", [H(1), H(2)], 3)
    # dynlint: disable=DYN013 deliberately corrupting the sim books to prove the auditor catches it
    sim._ref[H(1)] += 1
    viol = led.audit_sim(sim, live_seqs=["s1"])
    assert "refcount-drift" in kinds_of(viol)
    # dynlint: disable=DYN013 deliberately corrupting the sim books to prove the auditor catches it
    sim._ref.pop(H(2))
    viol = led.audit_sim(sim, live_seqs=["s1"])
    assert "orphan" in kinds_of(viol)
    # dynlint: disable=DYN013 deliberately corrupting the sim books to prove the auditor catches it
    sim._ref[H(7)] = 1
    viol = led.audit_sim(sim, live_seqs=["s1"])
    assert "leak" in kinds_of(viol)


# ---------------------- /debug/kv + fleet --------------------------------


async def test_debug_kv_token_gated_and_fleet_folds():
    """/debug/kv: 401 without the admin token, a schema'd dump with a
    FRESH audit with it; the fleet snapshot attaches the per-worker
    ledger view (strict instance match) and the summary carries the
    per-tier attributed occupancy + violation rollup, with the
    dynamo_fleet_kv_violations gauge exported."""
    import aiohttp

    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
    from dynamo_tpu.obs import fleet as obs_fleet
    from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig
    from dynamo_tpu.runtime.metrics import MetricsHierarchy

    token = "kv-test-token"
    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem", event_plane="inproc",
                             system_port=-1, admin_token=token),
        cluster_id=uuid.uuid4().hex).start()
    worker = await MockerWorker(
        rt, MockEngineArgs(model_name="m", block_size=4,
                           base_step_s=0.0001)).start()
    req = PreprocessedRequest(
        token_ids=list(range(12)), request_id="warm",
        stop=StopConditions(max_tokens=4, ignore_eos=True))
    async for _ in worker.engine.generate(req):
        pass
    url = f"http://{rt.system_address}/debug/kv"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(url) as r:
                assert r.status == 401
            async with s.get(
                    url, headers={"X-Dyn-Admin-Token": token}) as r:
                assert r.status == 200
                state = json.loads(await r.read())
        src = state["sources"][f"kv:{worker.served.instance_id}"]
        assert src["schema"] == "dynamo.kv_ledger.v1"
        assert src["audit"]["clean"] is True
        assert src["attribution"]["g1"]["prefix_cached"] > 0
        # fleet snapshot: per-worker kv_ledger view + summary rollup
        snap = await obs_fleet.snapshot(rt.discovery, token=token)
        view = next(w for w in snap.workers
                    if w.worker_id == worker.served.instance_id)
        assert view.kv_ledger is not None
        assert view.kv_ledger["schema"] == "dynamo.kv_ledger.v1"
        kvl = snap.summary["kv_ledger"]
        assert kvl["violations_total"] == 0
        assert kvl["occupancy"]["g1"]["prefix_cached"] > 0
        m = MetricsHierarchy(namespace="t")
        obs_fleet.export_fleet_gauges(m, snap)
        rendered = m.render().decode()
        assert "dynamo_fleet_kv_violations" in rendered \
            and "} 0.0" in rendered.split(
                "dynamo_fleet_kv_violations{", 1)[1].splitlines()[0]
        # obs.report renders the KV-accounting section from the dump
        from dynamo_tpu.obs.report import kv_accounting, kv_ledger_docs

        docs = kv_ledger_docs(state)
        assert docs, "report must find the ledger dump in /debug/kv"
        acct = kv_accounting(docs)
        assert acct["reconciled_clean"] is True
        assert acct["violations_total"] == 0
        assert acct["occupancy"]["g1"]["prefix_cached"] > 0
    finally:
        await worker.close()
        await rt.shutdown()
    assert not rt.kv_sources  # close() unregisters


async def test_kv_ledger_violation_gauge_exported():
    """A seeded violation reaches /metrics through the shared worker
    gauge surface (export_engine_gauges) as
    dynamo_kv_ledger_violations_total{kind,tier}."""
    from dynamo_tpu.planner.metrics import FpmWindow, export_engine_gauges
    from dynamo_tpu.runtime.metrics import MetricsHierarchy

    led = KvLedger()
    a = BlockAllocator(8, ledger=led)
    plane = chaos.ChaosPlane(seed=5)
    plane.rule("engine.kv_account", "drop", match="leak:", times=1)
    with plane:
        a.allocate("s", [], 2)
        a.free("s")
    led.finish_audit(led.audit_allocator(a, live_seqs=[]), where="test")
    m = MetricsHierarchy(namespace="t")
    export_engine_gauges(m, FpmWindow(), kv_ledger=led)
    rendered = m.render().decode()
    line = next(ln for ln in rendered.splitlines()
                if ln.startswith("dynamo_kv_ledger_violations_total{"))
    assert 'kind="leak"' in line and 'tier="g1"' in line
    assert line.endswith(" 1.0")
    assert "dynamo_kv_ledger_blocks{" in rendered


# ---------------------- snapshot-on-subscribe ----------------------------


async def test_publisher_snapshot_events():
    """The publisher's resident mirror follows the netted stream, and a
    snapshot replay carries the CURRENT resident set per tier stamped
    with the latest event id."""
    from dynamo_tpu.router.events import KvCacheEvent, KvEventPublisher
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc"),
        cluster_id=uuid.uuid4().hex).start()
    try:
        pub = KvEventPublisher(rt, "ns", "w", worker_id=7)
        pub.enqueue_batch(stored=[H(1), H(2)])
        pub.enqueue_batch(stored=[H(3)], tier="g2")
        pub.enqueue_batch(removed=[H(2)])
        evs = [KvCacheEvent.from_wire(w) for w in pub.snapshot_events()]
        by_tier = {e.tier: sorted(e.block_hashes) for e in evs}
        assert by_tier == {"g1": [H(1)], "g2": [H(3)]}
        assert all(e.event_id == pub._next_id - 1 for e in evs)
        assert all(e.op == "stored" for e in evs)
        # the replay endpoint answers snapshot requests with the same
        got = []
        async for w in pub.replay_handler({"snapshot": True}, None):
            got.append(KvCacheEvent.from_wire(w))
        assert {e.tier: sorted(e.block_hashes) for e in got} == by_tier
        # cleared() empties the mirror
        await pub.cleared()
        assert pub.snapshot_events() == []
    finally:
        await rt.shutdown()


async def test_router_snapshot_on_subscribe_sees_warm_cache():
    """THE PR 13 staleness fix, e2e: a router started AFTER a worker
    warmed its cache — with no further KV events ever firing — still
    indexes the worker's resident blocks via the snapshot replay, so
    its overlap predictions are nonzero against the warm fleet."""
    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
    from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
    from dynamo_tpu.router.kv_router import KvRouter
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig
    from dynamo_tpu.tokens import compute_block_hashes_for_request

    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc"),
        cluster_id=uuid.uuid4().hex).start()
    worker = await MockerWorker(
        rt, MockEngineArgs(model_name="m", block_size=4,
                           base_step_s=0.0001)).start()
    prompt = list(range(24))  # 6 blocks, 5 full ones hashed
    req = PreprocessedRequest(
        token_ids=prompt, request_id="warm",
        stop=StopConditions(max_tokens=2, ignore_eos=True))
    async for _ in worker.engine.generate(req):
        pass
    await asyncio.sleep(0.1)  # the warm events drain to nobody
    client = await (rt.namespace("dynamo").component("mocker")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    # the LATE subscriber: no events will ever fire again (pure cache
    # hits don't), so only the snapshot sync can warm its index
    router = await KvRouter(rt, "dynamo", "mocker", client,
                            block_size=4).start()
    hashes = compute_block_hashes_for_request(prompt, 4)
    try:
        deadline = 100
        overlap = {}
        for _ in range(deadline):
            overlap = router.indexer.find_matches(hashes)
            if overlap:
                break
            await asyncio.sleep(0.05)
        assert overlap, "late router never saw the warm resident set"
        assert max(overlap.values()) >= len(hashes)
    finally:
        await router.close()
        await client.close()
        await worker.close()
        await rt.shutdown()
