"""Cross-tile/segment DMA chain parity (PR 17).

The packed-prefill and decode kernels no longer re-prime their
double-buffered chunk DMA chain at each (tile, segment) / row boundary:
a global phase over the prefetched nchunks plane
(pallas_paged_attention.make_chunk_chain) keeps the chain saturated
across boundaries.  These layouts are chosen so the HANDOFF itself is
what's exercised — the globally-first active pair not being (0, 0),
empty rows interleaved between active ones, boundaries landing mid-tile,
fully-padded tail tiles after the last chunk, single segments spanning
many tiles, committed prefix KV, and int8 scale lanes riding the same
chain.  All interpret-mode vs the XLA references; the existing
test_packed_pallas.py layouts stay untouched as the base contract.
Interpret-mode calls cost seconds each on CPU, so the stress variants
of already-covered handoffs carry the `slow` marker — tier-1 keeps one
layout per distinct mechanism (mid-tile boundaries, empty-row skip,
int8 scale lanes, uneven decode rows).
"""

import numpy as np
import pytest

# sibling-module reuse (the tests/ conftest puts tests/ on sys.path),
# same pattern test_packed_pallas.py uses for test_engine helpers
from test_packed_pallas import (
    _assert_packed_parity,
    _int8_decode_case,
    _packed_case,
)

from dynamo_tpu.ops.paged_attention import paged_attention_decode_jnp
from dynamo_tpu.ops.pallas_paged_attention import (
    paged_attention_decode_pallas,
)

pytestmark = pytest.mark.allow_slow_callbacks


@pytest.mark.parametrize("lens,bucket,kw", [
    # chunk_cols=1 maximizes chain length: every block is its own
    # chunk, every segment boundary is a chain handoff, and token_block
    # 8 puts several of those boundaries mid-tile
    ([5, 11, 3, 13], 32, dict(token_block=8, chunk_cols=1)),
    # leading + interleaved EMPTY rows: the prime must skip to the
    # first pair with work, and each handoff must skip the zero-chunk
    # rows (the next_seg suffix-scan), not stall on them
    ([0, 7, 0, 9, 0], 16, dict(token_block=8, chunk_cols=2)),
    # many tiny segments: a handoff at (nearly) every loop iteration
    # (slow: stress variant of the first layout; interpret-mode calls
    # cost seconds each on CPU and tier-1 has a wall-clock budget)
    pytest.param([2, 2, 2, 2, 2, 2, 2, 2], 16,
                 dict(token_block=4, chunk_cols=1),
                 marks=pytest.mark.slow),
    # one long segment over 4 token tiles: the chain crosses TILE
    # boundaries (same segment re-walked per tile) without draining
    pytest.param([29], 32, dict(token_block=8, chunk_cols=2),
                 marks=pytest.mark.slow),
    # short stream + fully padded tail tiles: the global chain must end
    # exactly at the last real chunk (no prefetch past the plane)
    pytest.param([3], 32, dict(token_block=8, chunk_cols=2),
                 marks=pytest.mark.slow),
])
def test_packed_chain_boundary_layouts(lens, bucket, kw):
    rng = np.random.default_rng(21)
    case = _packed_case(rng, lens, bucket=bucket)
    _assert_packed_parity(case, **kw)


@pytest.mark.slow
def test_packed_chain_committed_prefix_mid_tile():
    """Prefix-cache hits give segments different chunk counts for the
    same chunk length (ctx0 extends the context walk), so the chain's
    per-pair bases are uneven while segment boundaries land mid-tile."""
    rng = np.random.default_rng(22)
    case = _packed_case(rng, [6, 4, 6], ctx0=[13, 0, 5], mb=8,
                        bucket=16)
    _assert_packed_parity(case, token_block=8, chunk_cols=1)


def test_packed_chain_int8_scale_lanes():
    """Int8 cache: the k/v scale rows ride the SAME chained descriptors
    as the quantized blocks — a slot-phase bug would pair a block with
    the wrong scale row and the dequant would show it."""
    rng = np.random.default_rng(23)
    case = _packed_case(rng, [5, 0, 11, 7], bucket=32, int8=True,
                        ctx0=[2, 0, 0, 9])
    _assert_packed_parity(case, token_block=8, chunk_cols=1)


@pytest.mark.parametrize("kv_lens,bpc", [
    # uneven rows: the cross-row handoff happens at every row edge,
    # with chain phases that differ per row
    ([1, 24, 3], 2),
    # single-chunk rows between long ones: prime-once, immediate
    # handoff (slow: the uneven-rows layouts above/below already cross
    # every row edge; tier-1 wall-clock budget)
    pytest.param([24, 4, 24, 4], 2, marks=pytest.mark.slow),
    # chunk bigger than some rows' contexts: rows with n_chunks == 1
    # next to rows with several (slow: tier-1 wall-clock budget)
    pytest.param([17, 24, 5, 9], 3, marks=pytest.mark.slow),
])
def test_decode_chain_uneven_rows(kv_lens, bpc):
    """Decode kernel: the batch-dim chunk chain hands off row b -> b+1
    without draining; uneven kv_lens give each row a different chunk
    count (incl. partial last blocks)."""
    rng = np.random.default_rng(24)
    q, kc, vc, ks, vs, tables, lens = _int8_decode_case(rng, kv_lens)
    # layer 1 only — the layer index picks a cache slice, and a second
    # layer is a second interpret-mode trace (tier-1 wall-clock budget)
    for li in (1,):
        ref = paged_attention_decode_jnp(q, kc, vc, li, tables, lens,
                                         k_scale=ks, v_scale=vs)
        out = paged_attention_decode_pallas(
            q, kc, vc, li, tables, lens, interpret=True,
            k_scale=ks, v_scale=vs, blocks_per_chunk=bpc)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)
