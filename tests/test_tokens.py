"""Tests for the PLH hashing contract (ref test model: lib/tokens tests)."""

from dynamo_tpu.tokens import (
    TokenBlockSequence,
    compute_block_hashes,
    compute_block_hashes_for_request,
    local_block_hash,
)
from dynamo_tpu.tokens.hashing import prefix_overlap_blocks


def test_full_blocks_only():
    toks = list(range(130))
    hs = compute_block_hashes(toks, block_size=64)
    assert len(hs) == 2  # 130 // 64


def test_determinism_and_uniqueness():
    a = compute_block_hashes(list(range(128)), 64)
    b = compute_block_hashes(list(range(128)), 64)
    assert a == b
    c = compute_block_hashes([1] + list(range(1, 128)), 64)
    assert a[0] != c[0]
    # lineage: same second-block content, different first block -> different PLH
    assert a[1] != c[1]


def test_lineage_chains():
    toks = list(range(256))
    full = compute_block_hashes(toks, 64)
    head = compute_block_hashes(toks[:128], 64)
    tail = compute_block_hashes(toks[128:], 64, parent=head[-1])
    assert full == head + tail


def test_positional_dependence():
    # identical content at different positions hashes differently (PLH)...
    toks = [7] * 128
    hs = compute_block_hashes(toks, 64)
    assert hs[0] != hs[1]
    # ...but local (content) hash is identical
    assert local_block_hash(toks[:64]) == local_block_hash(toks[64:])


def test_lora_salt_namespaces():
    toks = list(range(64))
    a = compute_block_hashes_for_request(toks, 64)
    b = compute_block_hashes_for_request(toks, 64, lora_name="adapter1")
    assert a != b


def test_incremental_sequence_matches_batch():
    toks = list(range(300))
    seq = TokenBlockSequence(block_size=64)
    completed = seq.extend(toks)
    assert seq.block_hashes == compute_block_hashes(toks, 64)
    assert completed == seq.block_hashes
    assert seq.num_full_blocks == 4
    assert seq.partial_len() == 300 - 256
    assert seq.num_blocks == 5


def test_prefix_overlap():
    toks = list(range(256))
    hs = compute_block_hashes(toks, 64)
    assert prefix_overlap_blocks(hs, set(hs)) == 4
    assert prefix_overlap_blocks(hs, set(hs[:2])) == 2
    # hole in the middle stops the walk
    assert prefix_overlap_blocks(hs, {hs[0], hs[2], hs[3]}) == 1
    assert prefix_overlap_blocks(hs, set()) == 0


def test_request_salt_injective():
    # adapter "a|b" must not alias adapter "a" + media "b" (delimiter
    # injection), and media ordering must matter
    from dynamo_tpu.tokens.hashing import request_salt

    assert request_salt("a|b") != request_salt("a", ["b"])
    assert request_salt("a", ["b|c"]) != request_salt("a", ["b", "c"])
    assert request_salt("ab") != request_salt("a", ["b"])
    assert request_salt() == b""
    assert request_salt("x") == request_salt("x")
