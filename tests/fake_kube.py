"""In-process fake of the Kubernetes API server's Lease + scale subset.

Serves just enough of the JSON API for KubeDiscovery (coordination.k8s.io
Leases: create/patch/delete/list/watch) and the planner's
KubernetesConnector (apps/v1 Deployment scale subresource) — the same
role tests/fake_etcd.py plays for the etcd backend.
"""

from __future__ import annotations

import asyncio
import copy
import json
from typing import Any, Dict, List

from aiohttp import web

LEASE_PATH = "/apis/coordination.k8s.io/v1/namespaces/{ns}/leases"


def _merge_patch(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
    """RFC 7386 JSON merge-patch: null deletes, dicts recurse."""
    for k, v in src.items():
        if v is None:
            dst.pop(k, None)
        elif isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge_patch(dst[k], v)
        else:
            dst[k] = copy.deepcopy(v)


def _match_selector(obj: Dict[str, Any], sel: str) -> bool:
    """k=v and bare-key ("k") selector terms, comma-joined."""
    labels = (obj.get("metadata") or {}).get("labels") or {}
    for term in filter(None, (t.strip() for t in sel.split(","))):
        if "=" in term:
            k, v = term.split("=", 1)
            if labels.get(k) != v:
                return False
        elif term not in labels:
            return False
    return True


class FakeKubeApiServer:
    def __init__(self):
        self.leases: Dict[str, Dict[str, Any]] = {}  # name -> object
        # name -> full apps/v1 Deployment object (scale-only callers get a
        # minimal synthesized object)
        self.deployments: Dict[str, Dict[str, Any]] = {}
        self.configmaps: Dict[str, Dict[str, Any]] = {}  # name -> object
        self.rv = 0
        self._watchers: List[asyncio.Queue] = []
        self._runner = None
        self.endpoint = ""
        # test hooks
        self.scale_calls: List[tuple] = []

    def set_graph_spec(self, name: str, spec: Dict[str, Any]) -> None:
        """Store a graph ConfigMap the way the operator expects it."""
        self.configmaps[name] = {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name,
                         "labels": {"dynamo.dev/graph": "1"}},
            "data": {"spec": json.dumps(spec)},
        }

    def _bump(self) -> str:
        self.rv += 1
        return str(self.rv)

    def _notify(self, etype: str, obj: Dict[str, Any]) -> None:
        ev = {"type": etype, "object": copy.deepcopy(obj)}
        for q in list(self._watchers):
            q.put_nowait(ev)

    # -- lease handlers ---------------------------------------------------

    async def h_list_or_watch(self, request: web.Request):
        if request.query.get("watch") == "true":
            return await self._h_watch(request)
        sel = request.query.get("labelSelector", "")
        items = []
        for obj in self.leases.values():
            if sel and "=" in sel:
                k, v = sel.split("=", 1)
                if (obj["metadata"].get("labels") or {}).get(k) != v:
                    continue
            items.append(copy.deepcopy(obj))
        return web.json_response({
            "kind": "LeaseList", "items": items,
            "metadata": {"resourceVersion": str(self.rv)},
        })

    async def _h_watch(self, request: web.Request):
        resp = web.StreamResponse()
        resp.content_type = "application/json"
        await resp.prepare(request)
        q: asyncio.Queue = asyncio.Queue()
        self._watchers.append(q)
        try:
            while True:
                ev = await q.get()
                await resp.write(json.dumps(ev).encode() + b"\n")
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        finally:
            self._watchers.remove(q)
        return resp

    async def h_create(self, request: web.Request):
        body = await request.json()
        name = body["metadata"]["name"]
        if name in self.leases:
            return web.json_response(
                {"kind": "Status", "code": 409, "reason": "AlreadyExists"},
                status=409)
        body["metadata"]["resourceVersion"] = self._bump()
        self.leases[name] = body
        self._notify("ADDED", body)
        return web.json_response(body, status=201)

    async def h_patch(self, request: web.Request):
        name = request.match_info["name"]
        obj = self.leases.get(name)
        if obj is None:
            return web.json_response({"kind": "Status", "code": 404},
                                     status=404)
        _merge_patch(obj, await request.json())
        obj["metadata"]["resourceVersion"] = self._bump()
        self._notify("MODIFIED", obj)
        return web.json_response(obj)

    async def h_delete(self, request: web.Request):
        name = request.match_info["name"]
        obj = self.leases.pop(name, None)
        if obj is None:
            return web.json_response({"kind": "Status", "code": 404},
                                     status=404)
        self._bump()
        self._notify("DELETED", obj)
        return web.json_response({"kind": "Status", "status": "Success"})

    # -- deployments (operator + planner connector) -----------------------

    def _dep(self, name: str) -> Dict[str, Any]:
        return self.deployments.setdefault(name, {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": name}, "spec": {"replicas": 1},
        })

    async def h_get_scale(self, request: web.Request):
        name = request.match_info["name"]
        dep = self._dep(name)
        n = dep["spec"].get("replicas", 1)
        return web.json_response({
            "kind": "Scale",
            "metadata": {"name": name,
                         "namespace": request.match_info["ns"]},
            "spec": {"replicas": n},
            "status": {"replicas": n},
        })

    async def h_patch_scale(self, request: web.Request):
        name = request.match_info["name"]
        body = await request.json()
        n = int(body.get("spec", {}).get("replicas", 0))
        self._dep(name)["spec"]["replicas"] = n
        self.scale_calls.append((name, n))
        return web.json_response({
            "kind": "Scale", "metadata": {"name": name},
            "spec": {"replicas": n}, "status": {"replicas": n},
        })

    async def h_dep_list(self, request: web.Request):
        sel = request.query.get("labelSelector", "")
        items = [copy.deepcopy(o) for o in self.deployments.values()
                 if _match_selector(o, sel)]
        return web.json_response({
            "kind": "DeploymentList", "items": items,
            "metadata": {"resourceVersion": str(self.rv)},
        })

    async def h_dep_create(self, request: web.Request):
        body = await request.json()
        name = body["metadata"]["name"]
        if name in self.deployments:
            return web.json_response(
                {"kind": "Status", "code": 409, "reason": "AlreadyExists"},
                status=409)
        body["metadata"]["resourceVersion"] = self._bump()
        self.deployments[name] = body
        return web.json_response(body, status=201)

    async def h_dep_get(self, request: web.Request):
        obj = self.deployments.get(request.match_info["name"])
        if obj is None:
            return web.json_response({"kind": "Status", "code": 404},
                                     status=404)
        return web.json_response(obj)

    async def h_dep_patch(self, request: web.Request):
        name = request.match_info["name"]
        obj = self.deployments.get(name)
        if obj is None:
            return web.json_response({"kind": "Status", "code": 404},
                                     status=404)
        _merge_patch(obj, await request.json())
        obj["metadata"]["resourceVersion"] = self._bump()
        return web.json_response(obj)

    async def h_dep_delete(self, request: web.Request):
        obj = self.deployments.pop(request.match_info["name"], None)
        if obj is None:
            return web.json_response({"kind": "Status", "code": 404},
                                     status=404)
        self._bump()
        return web.json_response({"kind": "Status", "status": "Success"})

    # -- configmaps (graph specs) -----------------------------------------

    async def h_cm_list(self, request: web.Request):
        sel = request.query.get("labelSelector", "")
        items = [copy.deepcopy(o) for o in self.configmaps.values()
                 if _match_selector(o, sel)]
        return web.json_response({
            "kind": "ConfigMapList", "items": items,
            "metadata": {"resourceVersion": str(self.rv)},
        })

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> "FakeKubeApiServer":
        app = web.Application()
        base = "/apis/coordination.k8s.io/v1/namespaces/{ns}/leases"
        app.router.add_get(base, self.h_list_or_watch)
        app.router.add_post(base, self.h_create)
        app.router.add_patch(base + "/{name}", self.h_patch)
        app.router.add_delete(base + "/{name}", self.h_delete)
        deps = "/apis/apps/v1/namespaces/{ns}/deployments"
        app.router.add_get(deps + "/{name}/scale", self.h_get_scale)
        app.router.add_patch(deps + "/{name}/scale", self.h_patch_scale)
        app.router.add_get(deps, self.h_dep_list)
        app.router.add_post(deps, self.h_dep_create)
        app.router.add_get(deps + "/{name}", self.h_dep_get)
        app.router.add_patch(deps + "/{name}", self.h_dep_patch)
        app.router.add_delete(deps + "/{name}", self.h_dep_delete)
        app.router.add_get("/api/v1/namespaces/{ns}/configmaps",
                           self.h_cm_list)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = self._runner.addresses[0][1]
        self.endpoint = f"http://127.0.0.1:{port}"
        return self

    async def close(self) -> None:
        for q in list(self._watchers):
            q.put_nowait({"type": "BOOKMARK", "object": {}})
        if self._runner is not None:
            await self._runner.cleanup()
