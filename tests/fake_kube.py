"""In-process fake of the Kubernetes API server's Lease + scale subset.

Serves just enough of the JSON API for KubeDiscovery (coordination.k8s.io
Leases: create/patch/delete/list/watch) and the planner's
KubernetesConnector (apps/v1 Deployment scale subresource) — the same
role tests/fake_etcd.py plays for the etcd backend.
"""

from __future__ import annotations

import asyncio
import copy
import json
from typing import Any, Dict, List

from aiohttp import web

LEASE_PATH = "/apis/coordination.k8s.io/v1/namespaces/{ns}/leases"


class FakeKubeApiServer:
    def __init__(self):
        self.leases: Dict[str, Dict[str, Any]] = {}  # name -> object
        self.deployments: Dict[str, Dict[str, Any]] = {}  # name -> {replicas}
        self.rv = 0
        self._watchers: List[asyncio.Queue] = []
        self._runner = None
        self.endpoint = ""
        # test hooks
        self.scale_calls: List[tuple] = []

    def _bump(self) -> str:
        self.rv += 1
        return str(self.rv)

    def _notify(self, etype: str, obj: Dict[str, Any]) -> None:
        ev = {"type": etype, "object": copy.deepcopy(obj)}
        for q in list(self._watchers):
            q.put_nowait(ev)

    # -- lease handlers ---------------------------------------------------

    async def h_list_or_watch(self, request: web.Request):
        if request.query.get("watch") == "true":
            return await self._h_watch(request)
        sel = request.query.get("labelSelector", "")
        items = []
        for obj in self.leases.values():
            if sel and "=" in sel:
                k, v = sel.split("=", 1)
                if (obj["metadata"].get("labels") or {}).get(k) != v:
                    continue
            items.append(copy.deepcopy(obj))
        return web.json_response({
            "kind": "LeaseList", "items": items,
            "metadata": {"resourceVersion": str(self.rv)},
        })

    async def _h_watch(self, request: web.Request):
        resp = web.StreamResponse()
        resp.content_type = "application/json"
        await resp.prepare(request)
        q: asyncio.Queue = asyncio.Queue()
        self._watchers.append(q)
        try:
            while True:
                ev = await q.get()
                await resp.write(json.dumps(ev).encode() + b"\n")
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        finally:
            self._watchers.remove(q)
        return resp

    async def h_create(self, request: web.Request):
        body = await request.json()
        name = body["metadata"]["name"]
        if name in self.leases:
            return web.json_response(
                {"kind": "Status", "code": 409, "reason": "AlreadyExists"},
                status=409)
        body["metadata"]["resourceVersion"] = self._bump()
        self.leases[name] = body
        self._notify("ADDED", body)
        return web.json_response(body, status=201)

    async def h_patch(self, request: web.Request):
        name = request.match_info["name"]
        obj = self.leases.get(name)
        if obj is None:
            return web.json_response({"kind": "Status", "code": 404},
                                     status=404)
        patch = await request.json()

        def merge(dst, src):
            for k, v in src.items():
                if isinstance(v, dict) and isinstance(dst.get(k), dict):
                    merge(dst[k], v)
                else:
                    dst[k] = v

        merge(obj, patch)
        obj["metadata"]["resourceVersion"] = self._bump()
        self._notify("MODIFIED", obj)
        return web.json_response(obj)

    async def h_delete(self, request: web.Request):
        name = request.match_info["name"]
        obj = self.leases.pop(name, None)
        if obj is None:
            return web.json_response({"kind": "Status", "code": 404},
                                     status=404)
        self._bump()
        self._notify("DELETED", obj)
        return web.json_response({"kind": "Status", "status": "Success"})

    # -- deployment scale (planner connector) -----------------------------

    async def h_get_scale(self, request: web.Request):
        name = request.match_info["name"]
        dep = self.deployments.setdefault(name, {"replicas": 1})
        return web.json_response({
            "kind": "Scale",
            "metadata": {"name": name,
                         "namespace": request.match_info["ns"]},
            "spec": {"replicas": dep["replicas"]},
            "status": {"replicas": dep["replicas"]},
        })

    async def h_patch_scale(self, request: web.Request):
        name = request.match_info["name"]
        body = await request.json()
        n = int(body.get("spec", {}).get("replicas", 0))
        dep = self.deployments.setdefault(name, {"replicas": 1})
        dep["replicas"] = n
        self.scale_calls.append((name, n))
        return web.json_response({
            "kind": "Scale", "metadata": {"name": name},
            "spec": {"replicas": n}, "status": {"replicas": n},
        })

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> "FakeKubeApiServer":
        app = web.Application()
        base = "/apis/coordination.k8s.io/v1/namespaces/{ns}/leases"
        app.router.add_get(base, self.h_list_or_watch)
        app.router.add_post(base, self.h_create)
        app.router.add_patch(base + "/{name}", self.h_patch)
        app.router.add_delete(base + "/{name}", self.h_delete)
        dep = "/apis/apps/v1/namespaces/{ns}/deployments/{name}/scale"
        app.router.add_get(dep, self.h_get_scale)
        app.router.add_patch(dep, self.h_patch_scale)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = self._runner.addresses[0][1]
        self.endpoint = f"http://127.0.0.1:{port}"
        return self

    async def close(self) -> None:
        for q in list(self._watchers):
            q.put_nowait({"type": "BOOKMARK", "object": {}})
        if self._runner is not None:
            await self._runner.cleanup()
