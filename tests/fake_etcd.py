"""In-process fake of the etcd v3 JSON gateway, for EtcdDiscovery tests.

Implements the subset the backend speaks — kv put/range/deleterange,
lease grant/keepalive/revoke with real TTL expiry, and streaming watch —
with etcd's wire conventions (base64 keys/values, revision counter,
DELETE/PUT event types, lease expiry deleting bound keys and notifying
watchers).  Runs on an ephemeral localhost port via aiohttp.
"""

from __future__ import annotations

import asyncio
import base64
import json
import time
from typing import Dict, List, Optional, Tuple

from aiohttp import web

# queue sentinel: close() wakes parked watch handlers with this
_WATCH_CLOSED = object()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


class FakeEtcd:
    def __init__(self, expiry_poll_s: float = 0.1):
        # key -> (value_bytes, lease_id or None)
        self.kv: Dict[bytes, Tuple[bytes, Optional[int]]] = {}
        # lease_id -> (ttl_s, deadline)
        self.leases: Dict[int, Tuple[float, float]] = {}
        self.revision = 1
        self._next_lease = 1000
        self.watchers: List[Tuple[bytes, bytes, asyncio.Queue]] = []
        self.expiry_poll_s = expiry_poll_s
        self._runner = None
        self.port: Optional[int] = None
        self._expiry_task: Optional[asyncio.Task] = None

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> "FakeEtcd":
        app = web.Application()
        app.router.add_post("/v3/lease/grant", self._lease_grant)
        app.router.add_post("/v3/lease/keepalive", self._lease_keepalive)
        app.router.add_post("/v3/lease/revoke", self._lease_revoke)
        app.router.add_post("/v3/kv/put", self._kv_put)
        app.router.add_post("/v3/kv/range", self._kv_range)
        app.router.add_post("/v3/kv/deleterange", self._kv_deleterange)
        app.router.add_post("/v3/watch", self._watch)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        self._expiry_task = asyncio.create_task(self._expiry_loop())
        return self

    async def close(self) -> None:
        if self._expiry_task is not None:
            self._expiry_task.cancel()
            self._expiry_task = None
        # wake every long-poll watch handler: a client that abandoned its
        # watch leaves the handler parked on q.get() forever, and
        # AppRunner.cleanup() does not cancel in-flight handlers — the
        # conftest pending-task check would flag the leak
        for _s, _e, q in list(self.watchers):
            q.put_nowait(_WATCH_CLOSED)
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- internals --------------------------------------------------------

    def _notify(self, ev_type: str, key: bytes,
                value: bytes = b"") -> None:
        self.revision += 1
        ev = {"kv": {"key": _b64(key),
                     "mod_revision": str(self.revision)}}
        if ev_type == "DELETE":
            ev["type"] = "DELETE"
        else:
            ev["kv"]["value"] = _b64(value)
        for start, end, q in list(self.watchers):
            if start <= key < end:
                q.put_nowait(ev)

    async def _expiry_loop(self) -> None:
        while True:
            await asyncio.sleep(self.expiry_poll_s)
            now = time.monotonic()
            for lid, (_ttl, deadline) in list(self.leases.items()):
                if now > deadline:
                    self._drop_lease(lid)

    def _drop_lease(self, lid: int) -> None:
        self.leases.pop(lid, None)
        for key, (_v, key_lid) in list(self.kv.items()):
            if key_lid == lid:
                del self.kv[key]
                self._notify("DELETE", key)

    # -- handlers ---------------------------------------------------------

    async def _lease_grant(self, req: web.Request) -> web.Response:
        body = await req.json()
        ttl = float(body.get("TTL", 5))
        self._next_lease += 1
        lid = self._next_lease
        self.leases[lid] = (ttl, time.monotonic() + ttl)
        return web.json_response({"ID": str(lid), "TTL": str(int(ttl))})

    async def _lease_keepalive(self, req: web.Request) -> web.Response:
        body = await req.json()
        lid = int(body.get("ID", 0))
        if lid in self.leases:
            ttl = self.leases[lid][0]
            self.leases[lid] = (ttl, time.monotonic() + ttl)
            out = {"result": {"ID": str(lid), "TTL": str(int(ttl))}}
        else:
            out = {"result": {"ID": str(lid), "TTL": "0"}}  # expired
        return web.json_response(out)

    async def _lease_revoke(self, req: web.Request) -> web.Response:
        body = await req.json()
        self._drop_lease(int(body.get("ID", 0)))
        return web.json_response({})

    async def _kv_put(self, req: web.Request) -> web.Response:
        body = await req.json()
        key = _unb64(body["key"])
        value = _unb64(body.get("value", ""))
        lease = int(body["lease"]) if body.get("lease") else None
        if lease is not None and lease not in self.leases:
            return web.json_response(
                {"error": "lease not found", "code": 5}, status=400)
        self.kv[key] = (value, lease)
        self._notify("PUT", key, value)
        return web.json_response(
            {"header": {"revision": str(self.revision)}})

    def _select(self, body: dict) -> List[bytes]:
        key = _unb64(body["key"])
        if body.get("range_end"):
            end = _unb64(body["range_end"])
            return [k for k in self.kv if key <= k < end]
        return [k for k in self.kv if k == key]

    async def _kv_range(self, req: web.Request) -> web.Response:
        body = await req.json()
        keys = sorted(self._select(body))
        return web.json_response({
            "header": {"revision": str(self.revision)},
            "kvs": [{"key": _b64(k), "value": _b64(self.kv[k][0])}
                    for k in keys],
            "count": str(len(keys)),
        })

    async def _kv_deleterange(self, req: web.Request) -> web.Response:
        body = await req.json()
        keys = self._select(body)
        for k in keys:
            del self.kv[k]
            self._notify("DELETE", k)
        return web.json_response({
            "header": {"revision": str(self.revision)},
            "deleted": str(len(keys)),
        })

    async def _watch(self, req: web.Request) -> web.StreamResponse:
        body = await req.json()
        cr = body.get("create_request", {})
        start = _unb64(cr["key"])
        end = _unb64(cr["range_end"]) if cr.get("range_end") \
            else start + b"\0"
        q: asyncio.Queue = asyncio.Queue()
        ent = (start, end, q)
        self.watchers.append(ent)
        resp = web.StreamResponse()
        resp.enable_chunked_encoding()
        await resp.prepare(req)
        try:
            # the gateway acks watch creation first
            await resp.write(json.dumps(
                {"result": {"created": True}}).encode() + b"\n")
            while True:
                ev = await q.get()
                if ev is _WATCH_CLOSED:
                    break
                await resp.write(json.dumps(
                    {"result": {"events": [ev]}}).encode() + b"\n")
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            try:
                self.watchers.remove(ent)
            except ValueError:
                pass
        return resp
