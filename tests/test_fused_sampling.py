"""Fused sampling/top-k epilogue unit parity (ops/fused_sampling.py).

The epilogue streams the final projection in vocab tiles and reduces on
the fly; its contract against engine/sampler.py is byte-identity at
greedy and draw-identity at seeded sampled settings (same key, same
candidate window, same nucleus mask -> the categorical picks the same
index).  Every test here compares against the materialize-then-sample
reference on the SAME (hidden, unembedding) operands, across tile
widths that exercise the clamped-overlap last tile, single-tile, and
tile-larger-than-vocab plans.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import sampler
from dynamo_tpu.ops import fused_sampling as fs

# tile widths: non-divisor (overlapped last tile), divisor, single
# tile, tile > vocab (clamped to V)
TILES = (64, 100, 256, 1000, 4096)
B, D, V = 5, 32, 1000


def _case(seed=0, dtype=jnp.float32, vocab=V):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.standard_normal((B, D)), dtype)
    w = jnp.asarray(rng.standard_normal((D, vocab)), dtype)
    logits = (h @ w).astype(jnp.float32)  # the reference _logits matmul
    return h, w, logits


def _sampling_batch():
    """Mixed per-slot settings: greedy slot, plain temperature, top-k,
    top-p, and all three — the heterogeneous batch one compiled
    program serves."""
    seeds = jnp.asarray([7, 11, 13, 17, 23], jnp.int32)
    steps = jnp.asarray([0, 3, 9, 1, 42], jnp.int32)
    temps = jnp.asarray([0.0, 0.7, 1.3, 0.9, 0.8], jnp.float32)
    top_ks = jnp.asarray([0, 0, 20, 0, 5], jnp.int32)
    top_ps = jnp.asarray([1.0, 1.0, 1.0, 0.9, 0.85], jnp.float32)
    return seeds, steps, temps, top_ks, top_ps


def test_cap_matches_sampler():
    """The window replay is only valid if both sides cap at the same
    candidate count."""
    assert fs.CAP == sampler.CAP


@pytest.mark.parametrize("tile", TILES)
def test_fused_greedy_byte_identity(tile):
    h, w, logits = _case(0)
    ref = sampler.greedy_tokens(logits)
    out = fs.fused_greedy_tokens(h, w, tile=tile)
    assert out.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.parametrize("tile", TILES)
def test_fused_sample_draw_identity(tile):
    """Same seeds/steps/settings -> the streamed window must make the
    categorical draw the exact token the full-vocab reference draws."""
    h, w, logits = _case(1)
    batch = _sampling_batch()
    ref = sampler.sample_tokens(logits, *batch)
    out = fs.fused_sample_tokens(h, w, *batch, tile=tile)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_fused_greedy_tie_break_first_max():
    """jnp.argmax returns the FIRST maximum; the streaming strict-`>`
    update must too, including when the duplicate maxima land in
    different tiles."""
    h = jnp.ones((1, 2), jnp.float32)
    # columns 3 and 257 get identical (maximal) logits, tiles of 128
    # put them in tile 0 and tile 2
    w = np.zeros((2, 512), np.float32)
    w[:, 3] = 2.0
    w[:, 257] = 2.0
    w = jnp.asarray(w)
    logits = (h @ w).astype(jnp.float32)
    assert int(jnp.argmax(logits[0])) == 3
    out = fs.fused_greedy_tokens(h, w, tile=128)
    assert int(out[0]) == 3


def test_fused_sample_tie_break_matches_reference():
    """Duplicate logit values across tiles: the merge order (running
    window before tile candidates) must reproduce lax.top_k's stable
    lower-index preference, so the masked categorical sees the same
    (vals, idx) the reference sees."""
    h = jnp.ones((1, 2), jnp.float32)
    w = np.zeros((2, 300), np.float32)
    w[:, 10] = 1.5
    w[:, 190] = 1.5  # same value, later tile at tile=128
    w[:, 20] = 1.0
    w = jnp.asarray(w)
    logits = (h @ w).astype(jnp.float32)
    batch = tuple(jnp.asarray(a) for a in (
        [3], [5], [1.0], [2], [1.0]))
    batch = (batch[0].astype(jnp.int32), batch[1].astype(jnp.int32),
             batch[2].astype(jnp.float32), batch[3].astype(jnp.int32),
             batch[4].astype(jnp.float32))
    ref = sampler.sample_tokens(logits, *batch)
    out = fs.fused_sample_tokens(h, w, *batch, tile=128)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_fused_bf16_operands_match_reference():
    """bf16 hidden/unembedding (the serving dtype): per-tile matmul
    columns are the same dots as the full matmul's columns, so greedy
    stays byte-identical and sampled draws stay identical."""
    h, w, logits = _case(2, dtype=jnp.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(fs.fused_greedy_tokens(h, w, tile=192)),
        np.asarray(sampler.greedy_tokens(logits)))
    batch = _sampling_batch()
    np.testing.assert_array_equal(
        np.asarray(fs.fused_sample_tokens(h, w, *batch, tile=192)),
        np.asarray(sampler.sample_tokens(logits, *batch)))


def test_fused_small_vocab_tile_plan():
    """vocab barely above CAP: the plan clamps tile to V and the whole
    stream is one tile — the degenerate path must still match."""
    h, w, logits = _case(3, vocab=sampler.CAP + 7)
    batch = _sampling_batch()
    np.testing.assert_array_equal(
        np.asarray(fs.fused_sample_tokens(h, w, *batch, tile=4096)),
        np.asarray(sampler.sample_tokens(logits, *batch)))
    np.testing.assert_array_equal(
        np.asarray(fs.fused_greedy_tokens(h, w, tile=7)),
        np.asarray(sampler.greedy_tokens(logits)))


def test_fused_inside_jit_under_vmapped_settings():
    """The epilogue runs inside the jitted decode program; jit must not
    change the draws (pure functions of the same key/window)."""
    h, w, logits = _case(4)
    batch = _sampling_batch()
    ref = sampler.sample_tokens(logits, *batch)
    out = jax.jit(
        lambda *a: fs.fused_sample_tokens(*a, tile=256))(h, w, *batch)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
