"""dynlint tests: per-rule fixtures (a minimal bad snippet that must be
flagged + a good/suppressed snippet that must pass), the PR 7 raw-jit
guided-topk regression fixture verbatim, suppression-reason enforcement,
baseline semantics, the repo-wide tier-1 gate, and the CLI --json smoke.

Note on fixtures containing suppression comments: the suppression parser
is line-based (comments don't survive ast), so a reasonless
``dynlint: disable`` written literally inside a fixture string would be
parsed out of THIS file too and fail the repo gate — those fixtures are
built by concatenation instead.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from dynamo_tpu import lint
from dynamo_tpu.lint.core import canon_path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALL_RULES = tuple(f"DYN{i:03d}" for i in range(1, 15))


def run(src, path="dynamo_tpu/engine/snippet.py", rules=None):
    return lint.run_source(textwrap.dedent(src), path, rules=rules)


def rule_ids(findings):
    return sorted({f.rule for f in findings})


def test_registry_has_all_rules():
    assert set(ALL_RULES) <= set(lint.RULES)
    for r in lint.RULES.values():
        assert r.title and r.bug  # README table sources


def test_canon_path_is_invocation_invariant():
    assert canon_path("/root/repo/dynamo_tpu/engine/core.py") \
        == "dynamo_tpu/engine/core.py"
    assert canon_path("./tests/test_lint.py") == "tests/test_lint.py"
    assert canon_path("dynamo_tpu/lint/core.py") == "dynamo_tpu/lint/core.py"


# --------------------------- DYN001: raw jit ----------------------------

# the PR 7 headline blind spot, verbatim: _guided_step's duplicate lazy
# top-k init went through a raw jax.jit that bypassed the watchdog — the
# measured 8-14s guided-fork compile would have landed mid-serving with
# zero telemetry.  Re-introducing this exact code must be DYN001.
PR7_GUIDED_TOPK_BYPASS = """
import jax
from functools import partial

class JaxEngine:
    def _guided_step(self, e):
        if getattr(self, "_jit_decode_topk", None) is None:
            self._jit_decode_topk = jax.jit(
                partial(self._decode_topk_impl, self.family,
                        self.model_cfg, self.mesh, self.GUIDED_TOPM),
                donate_argnums=(1,),
            )
        return self._jit_decode_topk
"""


def test_dyn001_flags_pr7_guided_topk_bypass():
    findings = run(PR7_GUIDED_TOPK_BYPASS, path="dynamo_tpu/engine/core.py")
    assert rule_ids(findings) == ["DYN001"]
    assert len(findings) == 1
    assert findings[0].line == 8


def test_dyn001_wrapped_form_passes():
    findings = run("""
        import jax
        from functools import partial

        class JaxEngine:
            def _topk_jit(self):
                if getattr(self, "_jit_decode_topk", None) is None:
                    self._jit_decode_topk = self.compile_watch.wrap(jax.jit(
                        partial(self._decode_topk_impl, self.family,
                                self.model_cfg, self.mesh, self.GUIDED_TOPM),
                        donate_argnums=(1,),
                    ), "decode_topk")
                return self._jit_decode_topk
        """, path="dynamo_tpu/engine/core.py")
    assert findings == []


def test_dyn001_bare_jit_import_and_decorator_partial():
    findings = run("""
        from functools import partial
        from jax import jit

        @partial(jit, static_argnames=("n",))
        def f(x, n):
            return x * n
        """, path="dynamo_tpu/ops/snippet.py")
    assert rule_ids(findings) == ["DYN001"]
    # a LOCAL helper called jit is not jax's
    assert run("""
        def jit(f):
            return f

        g = jit(lambda x: x)
        """, path="dynamo_tpu/ops/snippet.py") == []


def test_dyn001_scope():
    src = "import jax\nf = jax.jit(lambda x: x)\n"
    # the watchdog module itself is the allowlist
    assert run(src, path="dynamo_tpu/obs/compile_watch.py") == []
    # tests/benchmarks are out of scope for this rule
    assert run(src, path="tests/test_x.py") == []


# --------------------------- DYN002: hash() -----------------------------

def test_dyn002_hash_for_identity():
    bad = run("seed = hash(request_id)\n",
              path="dynamo_tpu/mocker/engine.py")
    assert rule_ids(bad) == ["DYN002"]
    good = run("""
        import zlib
        seed = zlib.crc32(request_id.encode())
        """, path="dynamo_tpu/mocker/engine.py")
    assert good == []
    # method .hash() is not the builtin
    assert run("h = obj.hash()\n", path="dynamo_tpu/mocker/engine.py") == []


# --------------------------- DYN003: metric prefix ----------------------

def test_dyn003_unprefixed_metric_family():
    bad = run('m.inc("requests_total", 1.0)\n',
              path="dynamo_tpu/frontend/service.py")
    assert rule_ids(bad) == ["DYN003"]
    bad2 = run("""
        from prometheus_client import Counter
        c = Counter("frontend_requests", "doc")
        """, path="dynamo_tpu/frontend/service.py")
    assert rule_ids(bad2) == ["DYN003"]
    good = run('m.inc("dynamo_frontend_requests_total", 1.0)\n',
               path="dynamo_tpu/frontend/service.py")
    assert good == []
    # .observe() on non-metric objects (non-name strings, numbers) pass
    assert run('hist.labels(family="x").observe(1.0)\n',
               path="dynamo_tpu/obs/slo.py") == []
    assert run('tid = self.targets.observe(w, 0)\n',
               path="dynamo_tpu/router/kv_router.py") == []


# --------------------------- DYN004: blocking in async ------------------

def test_dyn004_blocking_calls_in_async_def():
    bad = run("""
        import time

        async def handler(req):
            time.sleep(0.5)
            with open("/tmp/x") as f:
                data = f.read()
            return fut.result()
        """, path="dynamo_tpu/frontend/service.py")
    assert rule_ids(bad) == ["DYN004"]
    assert len(bad) == 3
    good = run("""
        import asyncio, time

        async def handler(req):
            await asyncio.sleep(0.5)
            data = await asyncio.to_thread(read_file, "/tmp/x")
            return await fut

        def sync_helper():
            time.sleep(0.5)  # runs in a thread, not on the loop

        async def offload():
            def work():
                with open("/tmp/x") as f:
                    return f.read()
            return await asyncio.to_thread(work)
        """, path="dynamo_tpu/frontend/service.py")
    assert good == []


# --------------------------- DYN005: discarded task ---------------------

def test_dyn005_discarded_task():
    bad = run("""
        import asyncio

        async def go():
            asyncio.create_task(pump())
            asyncio.ensure_future(drain())
        """, path="dynamo_tpu/router/kv_router.py")
    assert rule_ids(bad) == ["DYN005"]
    assert len(bad) == 2
    good = run("""
        import asyncio

        async def go(self):
            t = asyncio.create_task(pump())
            self._tasks.add(t)
            t.add_done_callback(self._tasks.discard)
            await asyncio.ensure_future(drain())
        """, path="dynamo_tpu/router/kv_router.py")
    assert good == []


# --------------------------- DYN006: registries -------------------------

def test_dyn006_seam_and_span_literals():
    bad = run("""
        from dynamo_tpu import chaos, obs

        async def step(self):
            await chaos.ahit("engine.stpe", key="x")
            chaos.hit("engine.step2")
            with obs.span("decode_dispatcher"):
                pass
            obs.end("sched_", 0.0)
        """, path="dynamo_tpu/engine/core.py")
    assert rule_ids(bad) == ["DYN006"]
    assert len(bad) == 4
    good = run("""
        from dynamo_tpu import chaos, obs

        async def step(self):
            await chaos.ahit("engine.step", key="x")
            with obs.span("decode_dispatch"):
                pass
            obs.end("sched", 0.0)
        """, path="dynamo_tpu/engine/core.py")
    assert good == []


def test_dyn006_rule_scenario_literals():
    bad = run("""
        plane = chaos.ChaosPlane(seed=1).rule("request_plane.framez",
                                              "truncate", times=1)
        """, path="tests/test_chaos.py")
    assert rule_ids(bad) == ["DYN006"]
    good = run("""
        plane = chaos.ChaosPlane(seed=1).rule("request_plane.frame",
                                              "truncate", times=1)
        other.rule("not-a-seam", "whatever")  # not a chaos action: not ours
        """, path="tests/test_chaos.py")
    assert good == []


def test_registries_are_canonical():
    from dynamo_tpu import chaos, obs
    from dynamo_tpu.obs.compile_watch import COMPILE_KIND

    assert set(obs.STEP_PHASES) <= obs.SPAN_KINDS
    assert COMPILE_KIND in obs.SPAN_KINDS
    assert "engine.step" in chaos.SEAMS
    # forensics hop taxonomy (obs/forensics.py, DYN012's registry)
    from dynamo_tpu.obs.forensics import PHASES

    assert {"received", "routed", "dispatched", "prefill_open",
            "prefill_done", "worker_stamp", "first_token",
            "decode_stall", "finish"} == set(obs.HOP_KINDS)
    assert set(PHASES) == {"queue", "route", "prefill", "transfer",
                           "decode", "stall"}


# --------------------------- DYN007: inline markers ---------------------

def test_dyn007_inline_drain_marker():
    from dynamo_tpu.protocols import DRAIN_REJECT

    bad = run(f"""
        async def generate(self, req):
            yield Output(error={DRAIN_REJECT!r})
        """, path="dynamo_tpu/mocker/engine.py")
    assert rule_ids(bad) == ["DYN007"]
    good = run("""
        from ..protocols import DRAIN_REJECT

        async def generate(self, req):
            yield Output(error=DRAIN_REJECT)
        """, path="dynamo_tpu/mocker/engine.py")
    assert good == []
    # the defining module is the allowlist
    assert run(f"DRAIN_REJECT = {DRAIN_REJECT!r}\n",
               path="dynamo_tpu/protocols/llm.py") == []


# --------------------------- DYN008: swallowed cancellation -------------

def test_dyn008_bare_except_in_async():
    bad = run("""
        async def pump(self):
            try:
                await self.once()
            except BaseException:
                log.warning("oops")
        """, path="dynamo_tpu/runtime/component.py")
    assert rule_ids(bad) == ["DYN008"]
    bad2 = run("""
        async def pump(self):
            try:
                await self.once()
            except:
                pass
        """, path="dynamo_tpu/runtime/component.py")
    assert rule_ids(bad2) == ["DYN008"]
    good = run("""
        async def pump(self):
            try:
                await self.once()
            except BaseException:
                self.cleanup()
                raise
            try:
                await self.twice()
            except Exception:
                log.warning("oops")  # CancelledError passes through
        """, path="dynamo_tpu/runtime/component.py")
    assert good == []


# --------------------------- DYN009: kv arity ---------------------------

def test_dyn009_fixed_arity_kv_destructure():
    bad = run("""
        def write(kv_cache, blk):
            k, v = kv_cache
            return k, v
        """, path="dynamo_tpu/models/llama.py")
    assert rule_ids(bad) == ["DYN009"]
    good = run("""
        def write(kv_cache, blk):
            if len(kv_cache) == 4:
                k, v, ks, vs = kv_cache
            else:
                k, v = kv_cache
            return k, v
        """, path="dynamo_tpu/models/llama.py")
    assert good == []
    # out-of-scope modules (runtime kv pairs, not KV caches) pass
    assert run("k, v = kv\n", path="dynamo_tpu/runtime/kube.py") == []


# --------------------------- DYN010: print ------------------------------

def test_dyn010_print_in_library():
    bad = run('print("served")\n', path="dynamo_tpu/router/kv_router.py")
    assert rule_ids(bad) == ["DYN010"]
    assert run('print("usage: ...")\n',
               path="dynamo_tpu/engine/__main__.py") == []
    assert run('print("report")\n', path="dynamo_tpu/obs/report.py") == []


# ------------------- DYN011: blocking sync in hot path ------------------

def test_dyn011_unattributed_asarray_in_hot_path():
    bad = run("""
        import numpy as np

        class JaxEngine:
            def _process_oldest_burst(self):
                e = self._inflight.popleft()
                arr = np.asarray(e["burst"])
                return arr
        """, path="dynamo_tpu/engine/core.py")
    assert rule_ids(bad) == ["DYN011"]
    assert len(bad) == 1


def test_dyn011_device_wait_span_idiom_passes():
    good = run("""
        import numpy as np
        from dynamo_tpu import obs

        class JaxEngine:
            def _process_oldest_burst(self):
                e = self._inflight.popleft()
                t_obs = obs.begin()
                arr = np.asarray(e["burst"])
                obs.end("device_wait", t_obs, track=self._obs_track,
                        what="burst_fetch")
                return arr
        """, path="dynamo_tpu/engine/core.py")
    assert good == []


def test_dyn011_item_and_block_until_ready_flagged():
    bad = run("""
        class JaxEngine:
            def _sched_step(self, tok, kv):
                a = tok.item()
                tok.block_until_ready()
                return a
        """, path="dynamo_tpu/engine/core.py")
    assert rule_ids(bad) == ["DYN011"]
    assert len(bad) == 2


def test_dyn011_scope_and_exemptions():
    # pre-serving warmup and the follower's lockstep replay are exempt
    assert run("""
        import numpy as np
        import jax

        class JaxEngine:
            def warmup_decode(self):
                jax.block_until_ready(self.kv)

            def apply_step(self, kind, a):
                return np.asarray(a["toks"])
        """, path="dynamo_tpu/engine/core.py") == []
    # only the engine core is the hot path; other modules are governed
    # by their own rules (DYN004 covers the event loop)
    assert run("import numpy as np\nx = np.asarray(y)\n",
               path="dynamo_tpu/kvbm/pools.py") == []


def test_dyn011_suppression_with_reason():
    src = ("import numpy as np\n"
           "def _dispatch_decode(a):\n"
           "    # dynlint: disable=DYN011 host-side numpy descriptor\n"
           "    return np.asarray(a['temps'])\n")
    assert lint.run_source(src, "dynamo_tpu/engine/core.py") == []


# ------------------- DYN012: forensics hop registry ---------------------

def test_dyn012_hop_literals():
    bad = run("""
        def on_dispatch(self, iid):
            self.hop("dispatchd", worker=iid)
            tracker.hop("prefil_open")
        """, path="dynamo_tpu/frontend/request_trace.py")
    assert rule_ids(bad) == ["DYN012"]
    assert len(bad) == 2
    good = run("""
        def on_dispatch(self, iid):
            self.hop("dispatched", worker=iid)
            tracker.hop("prefill_open", at=t0)
            tracker.hop(kind_variable)  # non-literal: not judged
        """, path="dynamo_tpu/frontend/request_trace.py")
    assert good == []


def test_dyn012_applies_in_tests_and_suppresses():
    bad = run("""
        tr.hop("first_tokn")
        """, path="tests/test_forensics.py")
    assert rule_ids(bad) == ["DYN012"]
    src = ('tr.hop("first_tokn")  '
           "# dynlint: disable=DYN012 the negative-test literal\n")
    assert lint.run_source(src, "tests/test_forensics.py") == []


# ------------------- DYN013: allocator/pool book mutation ---------------

def test_dyn013_flags_book_mutations_outside_defining_module():
    bad = run("""
        def steal(allocator, sim, pool, bid, h):
            allocator._free.append(bid)          # free-list mutation
            allocator._block_ref[bid] = 2        # subscript store
            allocator._block_ref[bid] += 1       # augassign
            del allocator._seq_blocks["s"]       # del
            allocator._lru.pop(h, None)          # mutating method
            sim._ref.update({h: 1})              # sim books
            pool._order.clear()                  # pool manifest
        """, path="dynamo_tpu/engine/core.py")
    assert rule_ids(bad) == ["DYN013"]
    assert len(bad) == 7


def test_dyn013_reads_pass_and_defining_modules_exempt():
    good = run("""
        def audit(allocator):
            free_list = list(allocator._free)    # read-only copy
            rc = dict(allocator._block_ref)
            n = len(allocator._seq_blocks)
            return free_list, rc, n
        """, path="dynamo_tpu/obs/kv_ledger.py")
    assert good == []
    # the defining modules mutate their own books by definition
    owner = run("""
        def free(self, bid):
            self._block_ref.pop(bid, None)
            self._free.append(bid)
        """, path="dynamo_tpu/engine/block_allocator.py")
    assert owner == []


def test_dyn013_applies_in_tests_and_suppresses():
    bad = run("""
        def test_corrupt(a):
            a._free.append(3)
        """, path="tests/test_something.py")
    assert rule_ids(bad) == ["DYN013"]
    src = ("a._free.append(3)  "
           "# dynlint: disable=DYN013 seeding the fault the auditor must catch\n")
    assert lint.run_source(src, "tests/test_something.py") == []


# ------------------- DYN014: raw npz of block payloads -------------------

def test_dyn014_flags_raw_npz_outside_sanctioned_helpers():
    bad = run("""
        import numpy as np

        def restore(path, arrays):
            np.savez(path, **arrays)             # skips the crc stamp
            blob = np.load(path)                 # skips the verify
            np.savez_compressed(path, **arrays)
            return blob
        """, path="dynamo_tpu/engine/core.py")
    assert rule_ids(bad) == ["DYN014"]
    assert len(bad) == 3


def test_dyn014_sanctioned_modules_and_tests_exempt():
    src = """
        import numpy as np

        def _load_block(path):
            return np.load(path)
        """
    # kvbm/pools.py IS the checksummed helper layer
    assert run(src, path="dynamo_tpu/kvbm/pools.py") == []
    # multimodal decodes media tensors, not KV block payloads
    assert run(src, path="dynamo_tpu/multimodal/encoder.py") == []
    # tests craft corrupt/legacy blobs on purpose — out of scope
    assert run(src, path="tests/test_kv_integrity.py") == []


def test_dyn014_suppresses_with_reason():
    src = ("blob = np.load(path)  "
           "# dynlint: disable=DYN014 reading a non-block npz artifact\n")
    assert lint.run_source(src, "dynamo_tpu/engine/core.py") == []


# --------------------------- suppressions -------------------------------

def test_suppression_with_reason_is_honored():
    findings = run("""
        seed = hash(rid)  # dynlint: disable=DYN002 single-process dict key, never crosses a boundary
        """, path="dynamo_tpu/mocker/engine.py")
    assert findings == []


def test_suppression_standalone_line_covers_next_line():
    findings = run("""
        # dynlint: disable=DYN002 single-process dict key, never crosses a boundary
        seed = hash(rid)
        """, path="dynamo_tpu/mocker/engine.py")
    assert findings == []


def test_suppression_reason_is_mandatory():
    # built by concatenation so THIS file's line-based suppression scan
    # does not see a reasonless disable (see module docstring)
    src = "seed = hash(rid)  # dynlint: " + "disable=DYN002\n"
    findings = lint.run_source(src, "dynamo_tpu/mocker/engine.py")
    ids = rule_ids(findings)
    assert "DYN000" in ids    # the reasonless suppression is a finding
    assert "DYN002" in ids    # and it does NOT suppress


def test_dyn008_tuple_except_clause():
    """`except (OSError, BaseException)` swallows CancelledError just
    like the bare form."""
    bad = run("""
        async def pump(self):
            try:
                await self.once()
            except (OSError, BaseException):
                pass
        """, path="dynamo_tpu/runtime/component.py")
    assert rule_ids(bad) == ["DYN008"]
    good = run("""
        async def pump(self):
            try:
                await self.once()
            except (OSError, ValueError):
                pass
        """, path="dynamo_tpu/runtime/component.py")
    assert good == []


def test_stacked_standalone_suppressions_anchor_on_code_line():
    """Two standalone disables above one flagged line both target the
    code, not each other."""
    findings = run("""
        import jax
        # dynlint: disable=DYN002 fixture: first of a stack
        # dynlint: disable=DYN001 fixture: second of a stack
        x = jax.jit(hash(f))
        """, path="dynamo_tpu/engine/core.py")
    assert findings == []


def test_trailing_suppression_on_continuation_line():
    """A suppression on any physical line of a multiline statement
    covers findings anywhere on that statement."""
    findings = run("""
        import jax
        y = jax.jit(
            fn)  # dynlint: disable=DYN001 fixture: comment on the continuation line
        """, path="dynamo_tpu/engine/core.py")
    assert findings == []


def test_suppression_only_covers_named_rule():
    findings = run("""
        import time

        async def f():
            time.sleep(hash("x"))  # dynlint: disable=DYN002 fixture: only DYN002 is waived
        """, path="dynamo_tpu/engine/core.py")
    assert rule_ids(findings) == ["DYN004"]


def test_unused_suppression_is_flagged():
    """Dead disables must not accumulate: a suppression whose target
    line no longer produces the named finding is itself DYN000 (the
    suppression analogue of the baseline stale-entry rule)."""
    src = ("import zlib\n"
           "seed = zlib.crc32(rid)  # dynlint: " +
           "disable=DYN002 fixed long ago, comment left behind\n")
    findings = lint.run_source(src, "dynamo_tpu/mocker/engine.py")
    assert rule_ids(findings) == ["DYN000"]
    assert "unused" in findings[0].message
    # rule-restricted runs skip the check: suppressions for unselected
    # rules are not "unused", they are out of scope
    assert lint.run_source(src, "dynamo_tpu/mocker/engine.py",
                           rules=["DYN004"]) == []


def test_suppression_inside_string_literal_is_not_parsed():
    """The parser reads real COMMENT tokens, so suppression-shaped text
    in a string (fixtures, docs) neither suppresses nor counts as an
    unused disable."""
    src = ('FIXTURE = """\n'
           'seed = hash(rid)  # dynlint: disable=DYN002 inside a string\n'
           '"""\n'
           "seed = hash(rid)\n")
    findings = lint.run_source(src, "dynamo_tpu/mocker/engine.py")
    assert rule_ids(findings) == ["DYN002"]  # real call flagged, no DYN000


# --------------------------- baseline -----------------------------------

def test_baseline_grandfathers_and_goes_stale(tmp_path):
    pkg = tmp_path / "dynamo_tpu" / "mocker"
    pkg.mkdir(parents=True)
    mod = pkg / "engine.py"
    mod.write_text("seed = hash(rid)\n")

    res = lint.run_paths([str(tmp_path)])
    assert rule_ids(res.findings) == ["DYN002"]

    base = tmp_path / "dynlint.baseline"
    base.write_text(lint.render_baseline(res.findings))
    res2 = lint.run_paths([str(tmp_path)], baseline_path=str(base))
    assert res2.ok and res2.findings == [] and len(res2.baselined) == 1

    # fixing the finding strands the baseline entry -> the gate fails
    # until the stale line is deleted (the baseline only shrinks)
    mod.write_text("import zlib\nseed = zlib.crc32(rid)\n")
    res3 = lint.run_paths([str(tmp_path)], baseline_path=str(base))
    assert res3.findings == [] and len(res3.stale_baseline) == 1
    assert not res3.ok


def test_restricted_runs_do_not_false_stale(tmp_path):
    """A --rule or path-subset run cannot re-produce unrelated baseline
    entries; reporting them stale would tell the developer to delete
    still-valid lines."""
    pkg = tmp_path / "dynamo_tpu" / "mocker"
    pkg.mkdir(parents=True)
    (pkg / "engine.py").write_text("seed = hash(rid)\n")
    other = tmp_path / "dynamo_tpu" / "router"
    other.mkdir()
    (other / "r.py").write_text('print("x")\n')

    res = lint.run_paths([str(tmp_path)])
    base = tmp_path / "dynlint.baseline"
    base.write_text(lint.render_baseline(res.findings))

    # rule-restricted: the DYN010 entry is out of scope, not stale
    r1 = lint.run_paths([str(tmp_path)], baseline_path=str(base),
                        rules=["DYN002"])
    assert r1.ok and r1.stale_baseline == []
    # path-subset: the un-linted router/ entry is out of scope too
    r2 = lint.run_paths([str(pkg)], baseline_path=str(base))
    assert r2.ok and r2.stale_baseline == []


def test_baseline_never_launders_suppression_hygiene(tmp_path):
    """DYN000 (reasonless/dead disables) is neither written by
    --write-baseline nor honored if hand-added: the reason-mandatory
    contract cannot be grandfathered away."""
    pkg = tmp_path / "dynamo_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        "import time\ntime.sleep(1)  # dynlint: " + "disable=DYN004\n")
    res = lint.run_paths([str(tmp_path)])
    assert "DYN000" in rule_ids(res.findings)
    rendered = lint.render_baseline(res.findings)
    assert "DYN000" not in rendered          # never written
    base = tmp_path / "b.txt"
    base.write_text(rendered + "".join(
        f.key + "\n" for f in res.findings if f.rule == "DYN000"))
    res2 = lint.run_paths([str(tmp_path)], baseline_path=str(base))
    assert "DYN000" in rule_ids(res2.findings)  # hand-added key ignored


def test_missing_path_is_an_error_not_a_green_gate(tmp_path):
    res = lint.run_paths([str(tmp_path / "no_such_dir")])
    assert not res.ok and res.files == 0
    assert "no Python files" in res.errors[0]


def test_deleted_file_baseline_entry_goes_stale(tmp_path):
    """An entry for a file that no longer exists under the linted roots
    must go stale — a lingering key would grandfather a later
    identically-keyed regression in a re-created file."""
    pkg = tmp_path / "dynamo_tpu" / "mocker"
    pkg.mkdir(parents=True)
    mod = pkg / "engine.py"
    mod.write_text("seed = hash(rid)\n")
    keeper = tmp_path / "dynamo_tpu" / "ok.py"
    keeper.write_text("x = 1\n")
    root = str(tmp_path / "dynamo_tpu")

    res = lint.run_paths([root])
    base = tmp_path / "dynlint.baseline"
    base.write_text(lint.render_baseline(res.findings))
    mod.unlink()
    res2 = lint.run_paths([root], baseline_path=str(base))
    assert res2.stale_baseline and not res2.ok


def test_overlapping_path_args_lint_each_file_once(tmp_path):
    """`dynlint dynamo_tpu dynamo_tpu/mocker` must not lint a file
    twice: the duplicate finding would escape the baseline's multiset
    matching and turn a green gate red."""
    pkg = tmp_path / "dynamo_tpu" / "mocker"
    pkg.mkdir(parents=True)
    (pkg / "engine.py").write_text("seed = hash(rid)\n")
    root = str(tmp_path / "dynamo_tpu")

    res = lint.run_paths([root, str(pkg)])
    assert res.files == 1 and len(res.findings) == 1
    base = tmp_path / "b.txt"
    base.write_text(lint.render_baseline(res.findings))
    res2 = lint.run_paths([root, str(pkg)], baseline_path=str(base))
    assert res2.ok, [f.render() for f in res2.findings]


def test_stale_verdict_is_invocation_spelling_invariant(tmp_path):
    """`dynlint <root>` and `dynlint <root>/dynamo_tpu` must agree that
    a deleted file's entry is stale: an unmarked enclosing root covers
    every namespace its walk produced files in."""
    pkg = tmp_path / "dynamo_tpu"
    pkg.mkdir()
    (pkg / "ok.py").write_text("x = 1\n")
    base = tmp_path / "b.txt"
    base.write_text(lint.render_baseline([lint.Finding(
        rule="DYN002", path="dynamo_tpu/deleted.py", line=1,
        message="m", snippet="seed = hash(x)")]))
    # enclosing unmarked root (the `dynlint .` spelling)
    r1 = lint.run_paths([str(tmp_path)], baseline_path=str(base))
    # marker root (the `dynlint dynamo_tpu` spelling)
    r2 = lint.run_paths([str(pkg)], baseline_path=str(base))
    assert r1.stale_baseline == r2.stale_baseline != []


def test_write_baseline_path_subset_preserves_other_entries(tmp_path):
    """--write-baseline over a path subset regenerates only that
    subtree's entries; out-of-scope ones survive verbatim."""
    pkg = tmp_path / "dynamo_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text("seed = hash(rid)\n")
    tdir = tmp_path / "tests"
    tdir.mkdir()
    (tdir / "test_x.py").write_text("import asyncio\n\n\nasync def f():\n"
                                    "    asyncio.create_task(g())\n")
    base = tmp_path / "dynlint.baseline"
    full = lint.run_paths([str(pkg), str(tdir)])
    base.write_text(lint.render_baseline(full.findings))

    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.lint", str(pkg),
         "--write-baseline", "--baseline", str(base)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "kept 1 out-of-scope" in out.stdout
    content = base.read_text()
    assert "DYN005|tests/test_x.py" in content  # preserved
    res = lint.run_paths([str(pkg), str(tdir)], baseline_path=str(base))
    assert res.ok, [f.render() for f in res.findings]


def test_write_baseline_refuses_rule_subset(tmp_path):
    """Regenerating the baseline from a rule subset would silently drop
    every other rule's grandfathered entries."""
    pkg = tmp_path / "dynamo_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text("seed = hash(rid)\n")
    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.lint", str(pkg),
         "--rule", "DYN002", "--write-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2
    assert "--write-baseline cannot be combined" in out.stderr


# --------------------------- the tier-1 gate ----------------------------

def test_repo_is_lint_clean():
    """THE gate: the full rule set over dynamo_tpu/ + tests/ must report
    zero new findings (suppressed-with-reason and baselined are clean),
    zero stale baseline entries, zero parse failures.  A PR that
    introduces any PR-1..7 bug-class regression fails here."""
    res = lint.run_paths(
        [os.path.join(REPO, "dynamo_tpu"), os.path.join(REPO, "tests")],
        baseline_path=os.path.join(REPO, "dynlint.baseline"))
    assert res.files > 150
    assert not res.errors, res.errors
    assert not res.findings, "new dynlint findings:\n" + "\n".join(
        f.render() for f in res.findings)
    assert not res.stale_baseline, (
        "stale dynlint baseline entries (fixed findings must leave "
        "dynlint.baseline):\n" + "\n".join(res.stale_baseline))


def test_every_suppression_in_repo_names_a_reason():
    """Reason enforcement over the real tree, not just fixtures: DYN000
    would surface in the gate above, but assert it directly so the
    failure message is unambiguous."""
    res = lint.run_paths(
        [os.path.join(REPO, "dynamo_tpu"), os.path.join(REPO, "tests")])
    assert not [f for f in res.findings if f.rule == "DYN000"]


# --------------------------- runtime twin (conftest gate) ---------------

def test_slow_callback_gate_fails_blocking_async_test():
    """DYN004's runtime twin end-to-end: a test that blocks the event
    loop past the armed threshold must FAIL with the offending callback
    named.  Runs a throwaway test file under the real tests/ conftest in
    a subprocess (the gate lives there), so this exercises the exact
    mechanism — armed at the 200ms design bound via DYN_TEST_SLOW_CB_S
    to stay well clear of the blocking sleep."""
    path = os.path.join(REPO, "tests", f"test_tmp_slowgate_{os.getpid()}.py")
    with open(path, "w") as f:
        f.write(textwrap.dedent("""
            import time

            async def test_blocks_the_loop():
                time.sleep(0.8)  # lint-exempt: tests/ are out of DYN004 scope; the GATE must catch it
        """))
    try:
        out = subprocess.run(
            [sys.executable, "-m", "pytest", path, "-q",
             "-p", "no:cacheprovider"],
            cwd=REPO, capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     DYN_TEST_SLOW_CB_S="0.2"))
        assert out.returncode == 1, out.stdout[-2000:]
        assert "blocked the event loop" in out.stdout
        assert "test_blocks_the_loop" in out.stdout  # culprit named
    finally:
        os.unlink(path)


# --------------------------- CLI ----------------------------------------

def test_cli_json_smoke():
    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.lint", "dynamo_tpu/lint",
         "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    data = json.loads(out.stdout)
    assert data["ok"] is True
    assert data["files"] >= 5
    assert isinstance(data["findings"], list)
    assert "stale_baseline" in data


def test_cli_flags_finding_with_exit_1(tmp_path):
    pkg = tmp_path / "dynamo_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text("seed = hash(rid)\n")
    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.lint", str(pkg), "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    data = json.loads(out.stdout)
    assert [f["rule"] for f in data["findings"]] == ["DYN002"]


def test_cli_list_rules():
    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.lint", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    for rid in ALL_RULES:
        assert rid in out.stdout
