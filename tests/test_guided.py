"""Guided decoding: JSON-schema prefix validation, canonical completion,
and the engine's constrained sampling path (schema-valid output under
temperature).  Ref: the reference's guided_json / structural outputs
(preprocessor.rs structural_tag)."""

import json

import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks


from dynamo_tpu.guided import JsonSchemaGuide

WEATHER = {
    "type": "object",
    "properties": {
        "city": {"type": "string"},
        "unit": {"enum": ["c", "f"]},
        "days": {"type": "integer"},
    },
}


def test_prefix_acceptance_walk():
    g = JsonSchemaGuide(WEATHER)
    doc = '{"city": "Paris", "unit": "c", "days": 3}'
    for cut in range(len(doc) + 1):
        assert g.ok(doc[:cut]), f"rejected valid prefix {doc[:cut]!r}"
    assert g.done(doc)
    # wrong key order / wrong types / garbage rejected at first bad byte
    assert not g.ok('{"unit"')
    assert not g.ok('{"city": 3')
    assert not g.ok('{"city": "x", "unit": "k"')
    assert not g.ok(doc + "x")
    assert not g.ok("[")


def test_canonical_completion_closes_any_prefix():
    g = JsonSchemaGuide(WEATHER)
    doc = '{"city": "Par"'
    closed = doc + g.complete(doc)
    assert g.done(closed)
    parsed = json.loads(closed)
    assert parsed["city"] == "Par" and parsed["unit"] in ("c", "f")
    # every truncation point of a valid doc completes to a valid doc
    full = '{"city": "Paris", "unit": "f", "days": 12}'
    for cut in range(len(full)):
        prefix = full[:cut]
        whole = prefix + g.complete(prefix)
        assert g.done(whole), f"completion failed at {cut}: {whole!r}"
        json.loads(whole)
    with pytest.raises(ValueError):
        g.complete('{"nope"')


def test_nested_and_arrays_and_escapes():
    schema = {
        "type": "object",
        "properties": {
            "tags": {"type": "array", "items": {"type": "string"}},
            "loc": {"type": "object", "properties": {
                "lat": {"type": "number"}, "lon": {"type": "number"}}},
            "ok": {"type": "boolean"},
        },
    }
    g = JsonSchemaGuide(schema)
    doc = ('{"tags": ["a\\n", "b\\u00e9"], '
           '"loc": {"lat": -1.5e2, "lon": 0.25}, "ok": true}')
    for cut in range(len(doc) + 1):
        assert g.ok(doc[:cut]), doc[:cut]
    assert g.done(doc)
    json.loads(doc)
    # completion mid-escape and mid-number
    for prefix in ('{"tags": ["x\\', '{"tags": [], "loc": {"lat": -',
                   '{"tags": ["a", '):
        whole = prefix + g.complete(prefix)
        assert g.done(whole), whole
        json.loads(whole)


def test_untyped_schema_accepts_any_json():
    g = JsonSchemaGuide({})
    assert g.ok('{"anything": [1, {"x": null}, "s"]}')
    assert g.done('{"a": 1}')
    assert not g.ok("nope")
    whole = '{"a": [1,' + g.complete('{"a": [1,')
    json.loads(whole)


# ------------------------------ engine path --------------------------------


async def test_engine_guided_json_schema_valid_under_temperature():
    """The engine's constrained path must produce schema-valid JSON even
    at high temperature from a RANDOM tiny model (which would otherwise
    emit noise), for several seeds — validity is guaranteed by
    construction (candidate filtering + canonical close)."""
    import jax.numpy as jnp

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    FP32 = LlamaConfig(name="tiny32", vocab_size=300, d_model=64,
                       n_layers=2, n_heads=4, n_kv_heads=2, head_dim=16,
                       ffn_dim=128, dtype=jnp.float32)
    schema = {
        "type": "object",
        "properties": {
            "city": {"type": "string"},
            "unit": {"enum": ["c", "f"]},
            "days": {"type": "integer"},
        },
    }
    eng = JaxEngine(EngineConfig(
        model_config=FP32, block_size=4, num_blocks=128,
        max_blocks_per_seq=32, max_num_seqs=2,
        prefill_buckets=(8, 16), seed=3))
    from dynamo_tpu.frontend.tokenizer import MockTokenizer

    codec = MockTokenizer(FP32.vocab_size)
    try:
        for seed in (1, 2, 3):
            req = PreprocessedRequest(
                token_ids=list(range(7, 19)), request_id=f"g{seed}",
                sampling=SamplingOptions(temperature=1.2, seed=seed,
                                         guided_json=schema),
                stop=StopConditions(max_tokens=48),
            )
            ids = []
            async for out in eng.generate(req):
                ids.extend(out.token_ids)
            text = codec.decode([t for t in ids])
            obj = json.loads(text)  # parses at all
            g = JsonSchemaGuide(schema)
            assert g.done(text.strip()), f"not schema-valid: {text!r}"
            assert set(obj) == {"city", "unit", "days"}
            assert obj["unit"] in ("c", "f")
            assert isinstance(obj["days"], int)
    finally:
        await eng.close()


async def test_engine_guided_deterministic_by_seed_and_unguided_unchanged():
    import jax.numpy as jnp

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    FP32 = LlamaConfig(name="tiny32", vocab_size=300, d_model=64,
                       n_layers=2, n_heads=4, n_kv_heads=2, head_dim=16,
                       ffn_dim=128, dtype=jnp.float32)
    schema = {"type": "object",
              "properties": {"ok": {"type": "boolean"}}}
    eng = JaxEngine(EngineConfig(
        model_config=FP32, block_size=4, num_blocks=128,
        max_blocks_per_seq=32, max_num_seqs=2,
        prefill_buckets=(8, 16), seed=3))

    async def run(rid, guided, seed=5):
        req = PreprocessedRequest(
            token_ids=list(range(7, 19)), request_id=rid,
            sampling=SamplingOptions(
                temperature=0.8, seed=seed,
                guided_json=schema if guided else None),
            stop=StopConditions(max_tokens=24, ignore_eos=not guided),
        )
        ids = []
        async for out in eng.generate(req):
            ids.extend(out.token_ids)
        return ids

    try:
        a = await run("a", True)
        b = await run("b", True)
        assert a == b, "guided sampling not deterministic by seed"
        # an unguided request on the same engine still serves normally
        u = await run("u", False)
        assert len(u) == 24
    finally:
        await eng.close()


# ------------------------- frontend integration ----------------------------


async def test_frontend_response_format_and_tool_choice():
    """OpenAI surface: response_format json_schema constrains the output;
    tool_choice with a named function returns tool_calls built from the
    guided envelope (no <tool_call> tags involved)."""
    import asyncio
    import uuid

    import aiohttp

    from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem", event_plane="inproc"),
        cluster_id=uuid.uuid4().hex).start()
    worker = await MockerWorker(rt, MockEngineArgs(
        model_name="gm", block_size=4, base_step_s=0.0002,
        prefill_s_per_token=0.0, decode_s_per_seq=0.0)).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get("gm"):
            break
        await asyncio.sleep(0.02)
    url = f"http://127.0.0.1:{port}/v1/chat/completions"
    schema = {"type": "object",
              "properties": {"city": {"type": "string"},
                             "unit": {"enum": ["c", "f"]}}}
    try:
        async with aiohttp.ClientSession() as s:
            # response_format: schema-valid content
            body = {"model": "gm", "max_tokens": 64,
                    "messages": [{"role": "user", "content": "weather"}],
                    "response_format": {
                        "type": "json_schema",
                        "json_schema": {"schema": schema}}}
            async with s.post(url, json=body) as r:
                assert r.status == 200, await r.text()
                data = await r.json()
            content = data["choices"][0]["message"]["content"]
            obj = json.loads(content)
            assert set(obj) == {"city", "unit"} and obj["unit"] in ("c", "f")

            # tool_choice named function -> tool_calls from the envelope
            body = {"model": "gm", "max_tokens": 64,
                    "messages": [{"role": "user", "content": "weather"}],
                    "tools": [{"type": "function", "function": {
                        "name": "get_weather",
                        "parameters": {
                            "type": "object",
                            "properties": {
                                "city": {"type": "string"}}}}}],
                    "tool_choice": {"type": "function",
                                    "function": {"name": "get_weather"}}}
            async with s.post(url, json=body) as r:
                assert r.status == 200, await r.text()
                data = await r.json()
            msg = data["choices"][0]["message"]
            assert data["choices"][0]["finish_reason"] == "tool_calls"
            call = msg["tool_calls"][0]
            assert call["function"]["name"] == "get_weather"
            json.loads(call["function"]["arguments"])

            # tool_choice naming an unknown tool is a 400
            body["tool_choice"] = {"type": "function",
                                   "function": {"name": "nope"}}
            async with s.post(url, json=body) as r:
                assert r.status == 400
    finally:
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()


async def test_frontend_streaming_forced_tool_choice():
    """stream:true + tool_choice: the raw envelope never leaks as
    content; one tool_calls delta arrives, finish_reason 'tool_calls'."""
    import asyncio
    import uuid

    import aiohttp

    from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem", event_plane="inproc"),
        cluster_id=uuid.uuid4().hex).start()
    worker = await MockerWorker(rt, MockEngineArgs(
        model_name="gs", block_size=4, base_step_s=0.0002,
        prefill_s_per_token=0.0, decode_s_per_seq=0.0)).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get("gs"):
            break
        await asyncio.sleep(0.02)
    try:
        body = {"model": "gs", "max_tokens": 64, "stream": True,
                "messages": [{"role": "user", "content": "weather"}],
                "tools": [{"type": "function", "function": {
                    "name": "f", "parameters": {
                        "type": "object",
                        "properties": {"x": {"type": "integer"}}}}}],
                "tool_choice": "required"}
        content, calls, finishes = "", [], []
        async with aiohttp.ClientSession() as s:
            async with s.post(
                    f"http://127.0.0.1:{port}/v1/chat/completions",
                    json=body) as r:
                assert r.status == 200
                async for line in r.content:
                    line = line.decode().strip()
                    if not line.startswith("data: ") or \
                            line.endswith("[DONE]"):
                        continue
                    obj = json.loads(line[6:])
                    for ch in obj.get("choices", []):
                        d = ch.get("delta", {})
                        content += d.get("content", "") or ""
                        calls += d.get("tool_calls") or []
                        if ch.get("finish_reason"):
                            finishes.append(ch["finish_reason"])
        assert content == "", f"envelope leaked as content: {content!r}"
        assert len(calls) == 1 and calls[0]["function"]["name"] == "f"
        json.loads(calls[0]["function"]["arguments"])
        assert finishes[-1] == "tool_calls"
    finally:
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()
