"""Speculative decoding (spec/): proposers, packed multi-token
verification, distribution preservation, KV rollback, adaptivity, the
guided-decoding guard, multihost replay, and the mocker simulation."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks


from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.engine.sampler import CAP, spec_accept_tokens, \
    spec_window_weights
from dynamo_tpu.models.llama import LlamaConfig, init_params
from dynamo_tpu.protocols import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.spec import NgramProposer

FP32 = LlamaConfig(name="tiny32", vocab_size=256, d_model=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
                   dtype=jnp.float32)

# repetition-friendly prompt: greedy streams on the tiny model cycle, so
# the n-gram proposer's history matches get accepted
REPEAT_PROMPT = [5, 9, 13, 2] * 6


def engine(**kw):
    defaults = dict(model_config=FP32, block_size=4, num_blocks=256,
                    max_blocks_per_seq=64, max_num_seqs=4,
                    prefill_buckets=(8, 16, 32, 64), seed=7)
    defaults.update(kw)
    return JaxEngine(EngineConfig(**defaults))


def req(tokens, n, rid, temp=0.0, seed=0, top_k=0, top_p=1.0,
        guided_json=None):
    return PreprocessedRequest(
        token_ids=tokens, request_id=rid,
        sampling=SamplingOptions(temperature=temp, seed=seed, top_k=top_k,
                                 top_p=top_p, guided_json=guided_json),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )


async def collect(eng, r):
    toks = []
    async for out in eng.generate(r):
        toks.extend(out.token_ids)
    return toks


# -- proposers -------------------------------------------------------------


def test_ngram_proposer_matches_history():
    p = NgramProposer(max_ngram=3, min_ngram=1)
    # suffix [7, 8] recurred earlier; the continuation there was [9, 10]
    assert p.propose([1, 7, 8, 9, 10, 5, 7, 8], 2) == [9, 10]
    # longest n-gram wins over a more recent shorter match
    toks = [1, 2, 3, 40, 9, 2, 3, 50, 1, 2, 3]
    assert p.propose(toks, 1) == [40]
    # draft truncated to k and to available continuation
    assert p.propose([4, 4, 4], 5) == [4, 4]  # only 2 tokens follow
    # a recurrence immediately adjacent to the suffix (onset of
    # token-level repetition) is a legitimate candidate
    assert p.propose([1, 2, 2], 4) == [2]
    # no recurrence -> no proposal
    assert p.propose([1, 2, 3, 4, 5], 4) == []
    # min_ngram=2 refuses single-token evidence
    assert NgramProposer(max_ngram=3, min_ngram=2).propose(
        [9, 1, 2, 9], 2) == []


def test_spec_verify_packed_matches_prefill_packed():
    """The verify program is prefill_packed minus the last-token gather:
    its last-position logits per segment must match prefill_packed's, and
    the KV it writes must be identical."""
    from dynamo_tpu.models.llama import prefill_packed, spec_verify_packed

    cfg = FP32
    params = init_params(cfg, jax.random.PRNGKey(1))
    bs, nb, mb = 4, 64, 8
    shape = (cfg.n_layers, cfg.n_kv_heads, nb, cfg.head_dim, bs)
    kv_a = (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))
    kv_b = (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))

    rng = np.random.default_rng(3)
    lens = [9, 6]
    prompts = [rng.integers(1, cfg.vocab_size, n).astype(np.int32)
               for n in lens]
    tables = np.zeros((2, mb), np.int32)
    for i, n in enumerate(lens):
        used = -(-n // bs)
        tables[i, :used] = 1 + i * mb + np.arange(used)

    T = 16
    toks = np.zeros(T, np.int32)
    pos = np.zeros(T, np.int32)
    seg = np.zeros(T, np.int32)
    val = np.zeros(T, bool)
    last = np.zeros(2, np.int32)
    off = 0
    for i, p in enumerate(prompts):
        n = len(p)
        toks[off:off + n] = p
        pos[off:off + n] = np.arange(n)
        seg[off:off + n] = i
        val[off:off + n] = True
        last[i] = off + n - 1
        off += n

    lg_a, kv_a = prefill_packed(
        params, cfg, kv_a, jnp.asarray(toks), jnp.asarray(pos),
        jnp.asarray(seg), jnp.asarray(tables), jnp.asarray(last),
        jnp.asarray(val))
    lg_b, kv_b = spec_verify_packed(
        params, cfg, kv_b, jnp.asarray(toks), jnp.asarray(pos),
        jnp.asarray(seg), jnp.asarray(tables), jnp.asarray(val))
    for i in range(2):
        np.testing.assert_allclose(
            np.asarray(lg_b[last[i]]), np.asarray(lg_a[i]),
            rtol=1e-5, atol=1e-5)
    for ca, cb in zip(kv_a, kv_b):
        np.testing.assert_array_equal(np.asarray(ca), np.asarray(cb))


# -- greedy: token-identical to plain decode -------------------------------


async def test_ngram_greedy_token_identical_and_engages():
    base = engine()
    expect = await collect(base, req(REPEAT_PROMPT, 96, "b"))
    await base.close()

    spec = engine(spec_decode="ngram", spec_k=4)
    got = await collect(spec, req(REPEAT_PROMPT, 96, "s"))
    m = dict(spec.metrics)
    recs = [r for r in spec.fpm if r.get("kind") == "spec_verify"]
    await spec.close()
    assert got == expect, "speculative greedy output diverged"
    assert m.get("spec_accepted", 0) > 0, "speculation never accepted"
    assert recs, "no spec_verify FPM records emitted"
    for r in recs:
        assert {"proposed", "accepted", "lanes", "gap_s"} <= set(r)


async def test_draft_model_greedy_token_identical():
    """Draft == target (same config, same seed => identical params):
    greedy drafts are the target's own argmax chain, so acceptance is
    high and output stays token-identical."""
    base = engine()
    expect = await collect(base, req(REPEAT_PROMPT, 48, "b"))
    await base.close()

    spec = engine(spec_decode="draft", spec_draft_config=FP32, spec_k=4)
    got = await collect(spec, req(REPEAT_PROMPT, 48, "s"))
    m = dict(spec.metrics)
    await spec.close()
    assert got == expect
    assert m.get("spec_proposed", 0) > 0
    # identical draft/target disagree only on float near-ties between
    # the decode and packed-verify program shapes
    assert m["spec_accepted"] >= m["spec_proposed"] // 2


async def test_random_workload_stays_token_identical():
    """Adversarial (non-crafted) workloads: whatever the proposer does,
    greedy output is token-identical to plain decode."""
    rng = np.random.default_rng(11)
    prompt = list(map(int, rng.integers(1, 250, 24)))
    base = engine()
    expect = await collect(base, req(prompt, 64, "b"))
    await base.close()

    spec = engine(spec_decode="ngram", spec_k=4)
    got = await collect(spec, req(prompt, 64, "s"))
    await spec.close()
    assert got == expect


async def test_adaptive_k_collapses_under_persistent_rejection():
    """Near-zero acceptance must fall back to plain decode: with a
    proposer that only ever drafts garbage, the acceptance EMA collapses
    k to 0 after a few rounds and exponentially backed-off probes bound
    further verify dispatches — the zero-regression criterion's
    mechanics (benchmarks/bench_speculative.py measures the throughput
    half).  Output stays token-identical throughout."""
    rng = np.random.default_rng(11)
    prompt = list(map(int, rng.integers(1, 250, 24)))
    base = engine()
    expect = await collect(base, req(prompt, 64, "b"))
    await base.close()

    spec = engine(spec_decode="ngram", spec_k=4, spec_probe_interval=64)

    class HostileProposer:
        def propose(self, tokens, k, **kw):
            return [251] * k  # 251 never appears in any greedy stream

    spec.proposer = HostileProposer()
    got = await collect(spec, req(prompt, 64, "s"))
    m = dict(spec.metrics)
    await spec.close()
    assert got == expect
    # 251 could coincide with a rare argmax; near-zero, not exactly zero
    assert m.get("spec_accepted", 0) <= 2
    # EMA (0.5 prior, alpha 0.3, min 0.15) collapses after ~4 rejected
    # rounds; afterwards probes at 8/16/32/64-token backoff add only a
    # handful more dispatches across a 64-token stream
    assert m.get("spec_steps", 0) <= 12, \
        f"adaptive k failed to collapse: {m.get('spec_steps')} dispatches"


async def test_spec_then_plain_decode_does_not_chain_stale_tokens():
    """Regression: after a slot speculates, the device token chain no
    longer feeds its lane — a later decode burst whose descriptor
    happens to line up as a 'continuation' must re-upload the true
    (spec-emitted) last token instead of chaining the stale device one.
    Mirrors the bench shape that caught it: concurrent sequences,
    fused bursts, intermittent speculation, long greedy streams."""
    rng = np.random.default_rng(17)
    prompts = [list(map(int, rng.integers(1, 250, 32))) for _ in range(2)]

    async def run(spec):
        eng = engine(max_num_seqs=2, decode_fused_steps=8,
                     block_size=16, num_blocks=64, max_blocks_per_seq=16,
                     prefill_buckets=(16, 32),
                     **({"spec_decode": "ngram", "spec_k": 4} if spec
                        else {}))
        outs = await asyncio.gather(*[
            collect(eng, req(p, 96, f"ch{spec}-{i}"))
            for i, p in enumerate(prompts)])
        m = dict(eng.metrics)
        await eng.close()
        return list(outs), m

    expect, _ = await run(False)
    got, m = await run(True)
    assert got == expect, "post-speculation decode chained a stale token"


# -- distribution preservation ---------------------------------------------


def _fake_rows(rng, n, peaked=2.0):
    """Synthetic verify outputs: [n, CAP] sorted scaled logits with ids,
    plus the exact full-vocab logsumexp (vocab == CAP here, so the
    window holds the whole distribution)."""
    logits = rng.normal(0.0, peaked, size=(n, CAP))
    order = np.argsort(-logits, axis=1)
    vals = np.take_along_axis(logits, order, axis=1)
    lse = np.log(np.exp(logits).sum(axis=1))
    return order.astype(np.int64), vals, lse


def test_rejection_sampling_preserves_target_distribution():
    """Point-mass rejection sampling must emit position-1 tokens with
    EXACTLY the target's window distribution, whatever the draft was:
    empirical TV distance over many trials stays small for both a
    high-probability and a low-probability draft."""
    rng = np.random.default_rng(0)
    ids, vals, lse = _fake_rows(rng, 2)
    target = spec_window_weights(vals[0], lse[0], top_k=0, top_p=1.0)
    for draft in (int(ids[0, 0]), int(ids[0, CAP - 1])):
        counts = np.zeros(CAP)
        trials = 20000
        sampler_rng = np.random.default_rng(123)
        for _ in range(trials):
            _, emitted = spec_accept_tokens(
                ids, vals, lse, [draft], greedy=False, top_k=0,
                top_p=1.0, rng=sampler_rng)
            counts[np.nonzero(ids[0] == emitted[0])[0][0]] += 1
        tv = 0.5 * np.abs(counts / trials - target).sum()
        assert tv < 0.02, f"TV {tv:.4f} for draft {draft}"


def test_rejection_sampling_respects_top_k_top_p():
    """Acceptance decisions must use the SAME masked window the decode
    sampler draws from: a draft outside top-k is never accepted, and the
    emitted token always lies inside the mask."""
    rng = np.random.default_rng(4)
    ids, vals, lse = _fake_rows(rng, 2)
    w = spec_window_weights(vals[0], lse[0], top_k=4, top_p=1.0)
    assert np.count_nonzero(w) <= 4
    outside = int(ids[0, 10])  # rank 10 > top_k=4
    sampler_rng = np.random.default_rng(9)
    for _ in range(200):
        accepted, emitted = spec_accept_tokens(
            ids, vals, lse, [outside], greedy=False, top_k=4, top_p=1.0,
            rng=sampler_rng)
        assert accepted == 0
        assert emitted[0] in set(int(t) for t in ids[0, :4])


async def test_sampled_spec_deterministic_by_seed():
    e1 = engine(spec_decode="ngram")
    a = await collect(e1, req(REPEAT_PROMPT, 24, "t1", temp=0.8, seed=42))
    await e1.close()
    e2 = engine(spec_decode="ngram")
    b = await collect(e2, req(REPEAT_PROMPT, 24, "t2", temp=0.8, seed=42))
    await e2.close()
    e3 = engine(spec_decode="ngram")
    c = await collect(e3, req(REPEAT_PROMPT, 24, "t3", temp=0.8, seed=9))
    await e3.close()
    assert a == b
    assert a != c


# -- KV rollback -----------------------------------------------------------


def test_allocator_trim_blocks_rollback():
    from dynamo_tpu.engine.block_allocator import BlockAllocator

    alloc = BlockAllocator(num_blocks=16)
    res = alloc.allocate("s", [], 2)
    free0 = alloc.num_free
    for _ in range(3):  # speculative growth for a k=3 verify
        g = alloc.append_block("s")
        assert g.block_id is not None
    assert alloc.num_free == free0 - 3
    alloc.trim_blocks("s", 2)  # everything rejected: back to 2 blocks
    assert alloc.num_free == free0
    assert alloc.seq_block_ids("s") == res.block_ids
    # freeing after a trim releases exactly the retained blocks
    alloc.free("s")
    assert alloc.num_free == 15  # all but the garbage block


async def test_kv_rollback_accounting_matches_plain_decode():
    """After serving the same workload, the allocator's free/evictable
    accounting with speculation (including its rejected-draft block
    growth) must equal plain decode's — rollback leaks nothing and frees
    nothing it shouldn't."""
    plain = engine()
    await collect(plain, req(REPEAT_PROMPT, 96, "p"))
    spec = engine(spec_decode="ngram", spec_k=4)
    await collect(spec, req(REPEAT_PROMPT, 96, "s"))
    assert spec.metrics.get("spec_proposed", 0) \
        > spec.metrics.get("spec_accepted", 0), \
        "workload produced no rejections; rollback not exercised"
    # the finish frame is enqueued to the consumer BEFORE the scheduler
    # thread frees the slot's blocks, so read-after-finish races the
    # teardown by design — wait (bounded) for the accounting to settle
    # instead of asserting mid-free (the historical 1-in-a-few flake)
    for _ in range(200):
        if (spec.allocator.num_free == plain.allocator.num_free
                and spec.allocator.num_evictable
                == plain.allocator.num_evictable):
            break
        await asyncio.sleep(0.02)
    assert spec.allocator.num_free == plain.allocator.num_free
    assert spec.allocator.num_evictable == plain.allocator.num_evictable
    await plain.close()
    await spec.close()


# -- guided decoding guard -------------------------------------------------


async def test_guided_requests_bypass_speculation():
    """Constrained (guided_json) requests must force plain decode even
    with speculation globally enabled: byte-identical output, and no
    speculative token ever enters the constrained stream."""
    schema = {"type": "object", "properties": {
        "city": {"type": "string"}, "days": {"type": "integer"}}}
    base = engine()
    expect = await collect(
        base, req(REPEAT_PROMPT, 64, "g1", guided_json=schema))
    await base.close()

    spec = engine(spec_decode="ngram", spec_k=4)
    got = await collect(
        spec, req(REPEAT_PROMPT, 64, "g2", guided_json=schema))
    m = dict(spec.metrics)
    await spec.close()
    assert got == expect, "guided output changed under speculation"
    assert m.get("spec_steps", 0) == 0, \
        "a guided request entered the speculative path"


# -- multihost replay ------------------------------------------------------


async def test_spec_verify_rides_step_stream_and_replays():
    """The leader's spec_verify dispatches ride the step stream like
    prefill/decode; a follower replaying the captured stream must end
    with a bit-identical KV cache."""
    steps = []
    # lockstep leader: this test's subject is step-stream REPLAY, and it
    # needs a deterministic schedule that produces spec_verify steps —
    # the overlapped scheduler's pipelined bursts coarsen the collapsed-
    # slot probe cadence (probes land wherever a drain puts `generated`),
    # so whether an n-gram probe matches this tiny model's pseudo-random
    # tail becomes schedule luck.  Replay mechanics are mode-independent.
    kw = dict(model_config=FP32, block_size=4, num_blocks=128,
              max_blocks_per_seq=32, max_num_seqs=2,
              prefill_buckets=(8, 16, 32), seed=5,
              spec_decode="ngram", spec_k=4, overlap_scheduling=False)
    leader = JaxEngine(EngineConfig(**kw),
                       step_sink=lambda kind, a: steps.append((kind, a)))
    toks = await collect(leader, req(REPEAT_PROMPT, 64, "mh"))
    assert len(toks) == 64
    kinds = {k for k, _ in steps}
    assert "spec_verify" in kinds, f"no spec_verify step published: {kinds}"

    follower = JaxEngine(EngineConfig(**kw))
    for kind, a in steps:
        follower.apply_step(kind, a)
    for lc, fc in zip(leader.kv, follower.kv):
        np.testing.assert_array_equal(np.asarray(lc), np.asarray(fc))
    await leader.close()
    await follower.close()


async def test_draft_proposer_rejected_on_multihost():
    with pytest.raises(ValueError, match="single-host"):
        JaxEngine(
            EngineConfig(model_config=FP32, block_size=4, num_blocks=64,
                         max_blocks_per_seq=16, max_num_seqs=2,
                         spec_decode="draft", spec_draft_config=FP32),
            step_sink=lambda kind, a: None,
        )


# -- MLA / config fallbacks ------------------------------------------------


async def test_mla_family_falls_back_to_plain_decode():
    """DeepSeek (MLA) has no packed verify path in v1: the engine must
    serve plain decode instead of failing."""
    from dynamo_tpu.models.deepseek import PRESETS as DS_PRESETS

    eng = JaxEngine(EngineConfig(
        model_config=DS_PRESETS["tiny-mla"], block_size=4, num_blocks=64,
        max_blocks_per_seq=16, max_num_seqs=2, prefill_buckets=(8, 16),
        seed=3, spec_decode="ngram"))
    # the worker gates its MDC `speculative` advertisement on this
    assert not eng.spec_enabled
    toks = await collect(eng, req(list(range(1, 11)), 6, "mla"))
    assert len(toks) == 6
    assert eng.metrics.get("spec_steps", 0) == 0
    await eng.close()


def test_unknown_spec_decode_rejected():
    with pytest.raises(ValueError, match="spec_decode"):
        JaxEngine(EngineConfig(model_config=FP32, num_blocks=16,
                               spec_decode="medusa"))


# -- mocker + FPM plumbing -------------------------------------------------


async def test_mocker_simulated_acceptance_and_fpm():
    from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs

    args = MockEngineArgs(block_size=4, num_blocks=256, speedup_ratio=100,
                          speculative={"k": 4, "acceptance": 1.0})
    eng = MockEngine(args)
    r = req(list(range(1, 9)), 40, "m1")
    toks = await collect(eng, r)
    assert len(toks) == 40
    m = eng.metrics
    assert m["spec_proposed"] > 0
    # acceptance 1.0: every draft accepted
    assert m["spec_accepted"] == m["spec_proposed"]
    recs = [rec for rec in eng.fpm if rec["kind"] == "spec_verify"]
    assert recs and all(
        {"proposed", "accepted", "lanes"} <= set(rec) for rec in recs)
    # 5 tokens per engine step (1 + 4 accepted): far fewer steps than
    # tokens proves multi-token emission actually happened
    assert m["steps"] < len(toks)
    await eng.close()


async def test_mocker_zero_acceptance_is_plain_decode():
    from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs

    eng = MockEngine(MockEngineArgs(
        block_size=4, num_blocks=256, speedup_ratio=100,
        speculative={"k": 4, "acceptance": 0.0}))
    toks = await collect(eng, req(list(range(1, 9)), 20, "m0"))
    assert len(toks) == 20
    assert eng.metrics["spec_accepted"] == 0
    await eng.close()


def test_fpm_observer_spec_acceptance():
    from collections import deque

    from dynamo_tpu.planner.metrics import FpmObserver

    obs = FpmObserver(runtime=None, namespace="ns", component="c")
    now = __import__("time").monotonic()
    obs._steps[1] = deque([
        (now, {"kind": "spec_verify", "proposed": 8, "accepted": 6}),
        (now, {"kind": "decode", "k": 8, "gap_s": 0.01}),
        (now, {"kind": "spec_verify", "proposed": 4, "accepted": 3}),
    ])
    assert obs.spec_acceptance() == pytest.approx(9 / 12)
    # None = idle; a real 0.0 (total rejection) must stay distinguishable
    assert FpmObserver(None, "ns", "c").spec_acceptance() is None
    obs._steps[1] = deque([
        (now, {"kind": "spec_verify", "proposed": 8, "accepted": 0})])
    assert obs.spec_acceptance() == 0.0


async def test_mocker_worker_advertises_and_publishes_acceptance():
    """End-to-end satellite: a mocker worker with `speculative` set
    advertises the knobs in its MDC and its FPM records aggregate to the
    configured acceptance through FpmObserver — the planner-visible
    path, no real model involved."""
    import uuid

    from dynamo_tpu.mocker.engine import MockEngineArgs
    from dynamo_tpu.mocker.worker import MockerWorker
    from dynamo_tpu.planner.metrics import FpmObserver
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem", event_plane="inproc"),
        cluster_id=uuid.uuid4().hex).start()
    worker = await MockerWorker(
        rt, MockEngineArgs(block_size=4, num_blocks=256, speedup_ratio=100,
                           speculative={"k": 4, "acceptance": 1.0}),
        namespace="dynamo", component="mocker").start()
    assert worker.card.runtime_config["speculative"] == {
        "k": 4, "acceptance": 1.0}
    obs = await FpmObserver(rt, "dynamo", "mocker").start()
    toks = []
    async for out in worker.engine.generate(req(list(range(1, 9)), 40,
                                                "w1")):
        toks.extend(out.token_ids)
    assert len(toks) == 40
    for _ in range(100):
        await asyncio.sleep(0.05)
        if obs.spec_acceptance() is not None:
            break
    assert obs.spec_acceptance() == pytest.approx(1.0)
    await obs.close()
    await worker.close()
    await rt.shutdown()
