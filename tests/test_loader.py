"""Weight loading: HF safetensors checkpoint -> params pytree -> engine.

Ground truth is the transformers CPU forward pass on the SAME randomly
initialized tiny checkpoint: if our prefill logits match HF's logits
position-by-position, the name mapping, transposes, norms, rope, and GQA
wiring are all correct — the strongest parity signal available without
network access (ref: reference backends load real weights before serving,
components/src/dynamo/vllm/main.py:114).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks


torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


TINY_HF = dict(
    architectures=["LlamaForCausalLM"],
    hidden_size=64,
    intermediate_size=128,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    num_hidden_layers=2,
    vocab_size=256,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
    max_position_embeddings=512,
    tie_word_embeddings=False,
    torch_dtype="float32",
)


@pytest.fixture(scope="module")
def tiny_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("tiny-llama-hf")
    cfg = transformers.LlamaConfig(**{
        k: v for k, v in TINY_HF.items() if k != "architectures"
    })
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_hf_config_mapping(tiny_checkpoint):
    from dynamo_tpu.models.loader import load_hf_config

    path, _ = tiny_checkpoint
    cfg = load_hf_config(path, dtype=jnp.float32)
    assert cfg.d_model == 64
    assert cfg.n_heads == 4
    assert cfg.n_kv_heads == 2
    assert cfg.head_dim == 16
    assert cfg.n_layers == 2
    assert cfg.vocab_size == 256
    assert not cfg.qk_norm


def test_loaded_prefill_matches_hf_logits(tiny_checkpoint):
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.loader import load_hf_config, load_params

    path, hf_model = tiny_checkpoint
    cfg = load_hf_config(path, dtype=jnp.float32)
    params = load_params(path, cfg)

    token_ids = [5, 9, 13, 2, 7, 11, 3, 1, 8, 20, 100, 255]
    T = len(token_ids)
    with torch.no_grad():
        ref = hf_model(torch.tensor([token_ids])).logits[0].numpy()

    # drive our prefill through the paged cache, one block at a time
    bs, nblocks = 4, 8
    kv = tuple(
        jnp.zeros((cfg.n_layers, cfg.n_kv_heads, nblocks, cfg.head_dim, bs),
                  cfg.dtype)
        for _ in range(2)
    )
    table = jnp.asarray(np.arange(1, nblocks + 1, dtype=np.int32) % nblocks)
    # prefill the full prompt; compare last-position logits
    logits, kv = llama.prefill(
        params, cfg, kv,
        jnp.asarray(np.asarray(token_ids, np.int32)),
        jnp.arange(T, dtype=jnp.int32), table,
        jnp.int32(0), jnp.int32(T),
    )
    np.testing.assert_allclose(
        np.asarray(logits), ref[-1], rtol=2e-4, atol=2e-4
    )


async def test_engine_serves_real_checkpoint_greedy_matches_hf(
    tiny_checkpoint,
):
    """End-to-end: the engine loads the checkpoint from disk and its greedy
    continuation equals HF's greedy decoding."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.protocols import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )

    path, hf_model = tiny_checkpoint
    prompt = [5, 9, 13, 2, 7, 11, 3, 1]
    n_gen = 6
    with torch.no_grad():
        out = hf_model.generate(
            torch.tensor([prompt]), max_new_tokens=n_gen, do_sample=False,
            num_beams=1, pad_token_id=0,
        )[0][len(prompt):].tolist()

    from dynamo_tpu.models.loader import load_hf_config

    cfg = EngineConfig(
        model_path=path,
        model_config=None,
        block_size=4, num_blocks=64, max_blocks_per_seq=16,
        max_num_seqs=2, prefill_buckets=(8, 16), seed=3,
    )
    # force fp32 to match the fp32 HF reference exactly
    from dataclasses import replace
    cfg.model_config = replace(
        load_hf_config(path, dtype=jnp.float32), attn_impl="jnp")
    eng = JaxEngine(cfg)
    req = PreprocessedRequest(
        token_ids=list(prompt), request_id="hf1",
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n_gen, ignore_eos=True),
    )
    toks = []
    async for o in eng.generate(req):
        toks.extend(o.token_ids)
    await eng.close()
    assert toks == out


TINY_MIXTRAL = dict(
    hidden_size=64,
    intermediate_size=96,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    num_hidden_layers=2,
    vocab_size=256,
    num_local_experts=4,
    num_experts_per_tok=2,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
    max_position_embeddings=512,
    tie_word_embeddings=False,
    torch_dtype="float32",
)


@pytest.fixture(scope="module")
def tiny_mixtral_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("tiny-mixtral-hf")
    cfg = transformers.MixtralConfig(**TINY_MIXTRAL)
    torch.manual_seed(0)
    model = transformers.MixtralForCausalLM(cfg)
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


TINY_DEEPSEEK = dict(
    hidden_size=64,
    intermediate_size=128,
    moe_intermediate_size=64,
    num_attention_heads=4,
    num_key_value_heads=4,
    num_hidden_layers=3,
    vocab_size=256,
    q_lora_rank=24,
    kv_lora_rank=32,
    qk_nope_head_dim=16,
    qk_rope_head_dim=8,
    v_head_dim=16,
    n_routed_experts=4,
    num_experts_per_tok=2,
    n_shared_experts=1,
    first_k_dense_replace=1,
    n_group=2,
    topk_group=1,
    norm_topk_prob=True,
    routed_scaling_factor=1.5,
    rms_norm_eps=1e-6,
    rope_theta=10000.0,
    max_position_embeddings=512,
    tie_word_embeddings=False,
    torch_dtype="float32",
)


@pytest.fixture(scope="module")
def tiny_deepseek_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("tiny-deepseek-hf")
    cfg = transformers.DeepseekV3Config(**TINY_DEEPSEEK)
    torch.manual_seed(0)
    model = transformers.DeepseekV3ForCausalLM(cfg)
    # non-zero choice bias so the sigmoid+bias routing path is exercised
    # (checkpoints ship trained biases; zeros would mask a mapping bug)
    with torch.no_grad():
        for layer in model.model.layers[1:]:
            layer.mlp.gate.e_score_correction_bias.copy_(
                torch.randn(TINY_DEEPSEEK["n_routed_experts"]) * 0.5)
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_deepseek_config_mapping(tiny_deepseek_checkpoint):
    from dynamo_tpu.models.deepseek import DeepseekConfig
    from dynamo_tpu.models.loader import load_hf_config

    path, _ = tiny_deepseek_checkpoint
    cfg = load_hf_config(path, dtype=jnp.float32)
    assert isinstance(cfg, DeepseekConfig)
    assert cfg.q_lora_rank == 24 and cfg.kv_lora_rank == 32
    assert cfg.qk_rope_head_dim == 8 and cfg.v_head_dim == 16
    assert cfg.n_experts == 4 and cfg.n_shared_experts == 1
    assert cfg.first_k_dense == 1 and cfg.moe_scoring == "sigmoid"
    assert cfg.n_group == 2 and cfg.norm_topk_prob


def test_deepseek_prefill_matches_hf_logits(tiny_deepseek_checkpoint):
    """MLA loader parity against HF DeepseekV3: rope de-interleave,
    kv_b split into w_uk/w_uv, sigmoid+bias group-limited routing, shared
    experts — all verified in one logits comparison."""
    from dynamo_tpu.models import deepseek
    from dynamo_tpu.models.loader import load_hf_config, load_params

    path, hf_model = tiny_deepseek_checkpoint
    cfg = load_hf_config(path, dtype=jnp.float32)
    params = load_params(path, cfg)

    token_ids = [5, 9, 13, 2, 7, 11, 3, 1, 8, 20, 100, 255]
    T = len(token_ids)
    with torch.no_grad():
        ref = hf_model(torch.tensor([token_ids])).logits[0].numpy()

    bs, nblocks = 4, 8
    ks, vs = deepseek.kv_cache_shapes(cfg, nblocks, bs)
    kv = (jnp.zeros(ks, cfg.dtype), jnp.zeros(vs, cfg.dtype))
    table = jnp.asarray(np.arange(1, nblocks + 1, dtype=np.int32) % nblocks)
    logits, kv = deepseek.prefill(
        params, cfg, kv,
        jnp.asarray(np.asarray(token_ids, np.int32)),
        jnp.arange(T, dtype=jnp.int32), table,
        jnp.int32(0), jnp.int32(T),
    )
    np.testing.assert_allclose(
        np.asarray(logits), ref[-1], rtol=3e-4, atol=3e-4
    )


async def test_engine_serves_deepseek_checkpoint_greedy_matches_hf(
    tiny_deepseek_checkpoint,
):
    """End-to-end: the engine loads a DeepSeek checkpoint from disk and
    its greedy continuation equals HF's greedy decoding."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.loader import load_hf_config
    from dynamo_tpu.protocols import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )

    path, hf_model = tiny_deepseek_checkpoint
    prompt = [5, 9, 13, 2, 7, 11, 3, 1]
    n_gen = 6
    with torch.no_grad():
        out = hf_model.generate(
            torch.tensor([prompt]), max_new_tokens=n_gen, do_sample=False,
            num_beams=1, pad_token_id=0,
        )[0][len(prompt):].tolist()

    cfg = EngineConfig(
        model_path=path,
        model_config=load_hf_config(path, dtype=jnp.float32),
        block_size=4, num_blocks=64, max_blocks_per_seq=16,
        max_num_seqs=2, prefill_buckets=(8, 16), seed=3,
    )
    eng = JaxEngine(cfg)
    req = PreprocessedRequest(
        token_ids=list(prompt), request_id="ds1",
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n_gen, ignore_eos=True),
    )
    toks = []
    async for o in eng.generate(req):
        toks.extend(o.token_ids)
    await eng.close()
    assert toks == out


def test_mixtral_prefill_matches_hf_logits(tiny_mixtral_checkpoint):
    """MoE loader + routing parity against HF Mixtral: our topk-then-softmax
    equals HF's softmax-topk-renormalize, and the default dense dispatch is
    dropless like HF, so prefill logits must match exactly."""
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.loader import load_hf_config, load_params

    path, hf_model = tiny_mixtral_checkpoint
    cfg = load_hf_config(path, dtype=jnp.float32)
    assert cfg.n_experts == 4 and cfg.experts_per_token == 2
    params = load_params(path, cfg)

    token_ids = [5, 9, 13, 2, 7, 11, 3, 1, 8, 20, 100, 255]
    T = len(token_ids)
    with torch.no_grad():
        ref = hf_model(torch.tensor([token_ids])).logits[0].numpy()

    bs, nblocks = 4, 8
    kv = tuple(
        jnp.zeros((cfg.n_layers, cfg.n_kv_heads, nblocks, cfg.head_dim, bs),
                  cfg.dtype)
        for _ in range(2)
    )
    table = jnp.asarray(np.arange(1, nblocks + 1, dtype=np.int32) % nblocks)
    logits, kv = llama.prefill(
        params, cfg, kv,
        jnp.asarray(np.asarray(token_ids, np.int32)),
        jnp.arange(T, dtype=jnp.int32), table,
        jnp.int32(0), jnp.int32(T),
    )
    np.testing.assert_allclose(
        np.asarray(logits), ref[-1], rtol=3e-4, atol=3e-4
    )


# --------------------------- host weight cache -----------------------------


def test_weight_cache_restores_without_checkpoint(tiny_checkpoint,
                                                  tmp_path, monkeypatch):
    """Fast restart: the first load populates the tmpfs cache; a second
    load must rebuild the identical pytree FROM the cache alone — proven
    by deleting the safetensors before the reload (the reference covers
    this role with GMS/ModelExpress, README.md:79)."""
    import shutil

    from dynamo_tpu.models.loader import load_hf_config, load_params
    from dynamo_tpu.models.weight_cache import clear_cache

    src, _ = tiny_checkpoint
    path = str(tmp_path / "ckpt")
    shutil.copytree(src, path)
    cache = str(tmp_path / "wcache")
    monkeypatch.setenv("DYN_WEIGHT_CACHE_DIR", cache)
    monkeypatch.delenv("DYN_WEIGHT_CACHE", raising=False)

    p1 = load_params(path)
    assert os.path.isdir(cache)

    # remove the weights; keep the fingerprint inputs (names/sizes/mtimes
    # are recorded at write time, so the check must pass without re-stat
    # of the .safetensors? -> fingerprint includes them; keep file stats
    # by moving content away but restoring the entry is cheating — the
    # honest simulation is a reload in a NEW process with the checkpoint
    # intact; here we prove no safetensors BYTES are read by truncating
    # the tensor file after stashing its stat
    st_file = next(f for f in os.listdir(path)
                   if f.endswith(".safetensors"))
    full = os.path.join(path, st_file)
    st = os.stat(full)
    with open(full, "r+b") as f:  # corrupt the payload, keep the size
        f.seek(8)
        f.write(b"\xff" * 8)
    os.utime(full, (st.st_atime, st.st_mtime))

    p2 = load_params(path)
    flat1 = jax.tree_util.tree_leaves(p1)
    flat2 = jax.tree_util.tree_leaves(p2)
    assert len(flat1) == len(flat2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # a changed checkpoint (new mtime) must MISS and reload from disk
    os.utime(full, (st.st_atime, st.st_mtime + 60))
    from dynamo_tpu.models.weight_cache import read_cache

    assert read_cache(cache, path) is None  # stale fingerprint

    clear_cache(cache)
    assert not os.path.isdir(cache)


def test_weight_cache_read_resharpens_to_mesh(tiny_checkpoint, tmp_path,
                                              monkeypatch):
    """A restarted worker may come back with a different tp: cached
    tensors re-derive their NamedSharding from the same rules the loader
    uses."""
    from jax.sharding import Mesh

    from dynamo_tpu.models.loader import load_params
    from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

    src, _ = tiny_checkpoint
    cache = str(tmp_path / "wcache2")
    monkeypatch.setenv("DYN_WEIGHT_CACHE_DIR", cache)
    monkeypatch.delenv("DYN_WEIGHT_CACHE", raising=False)

    p1 = load_params(src)  # writes cache (no mesh)
    mesh = make_mesh(MeshConfig(dp=1, tp=2), devices=jax.devices()[:2])
    p2 = load_params(src, mesh=mesh)  # cache hit, sharded read
    wq = p2["layers"][0]["wq"]
    assert len(wq.sharding.device_set) == 2
    np.testing.assert_array_equal(np.asarray(p1["layers"][0]["wq"]),
                                  np.asarray(wq))
