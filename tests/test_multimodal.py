"""Multimodal encoder disagg: ViT tower, embedding cache, encode worker
endpoint, frontend hop with placeholder splicing, media-hash KV salting,
and the full chat e2e against a mocker fleet (BASELINE config 5 skeleton).

Ref shape: encode_worker_handler.py (encode fleet + embedding cache) and
encoder_router.rs (media-hash cache affinity)."""

import asyncio
import base64
import io
import uuid

import aiohttp
import numpy as np

from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.multimodal import (
    EmbeddingCache,
    EncoderWorker,
    MockVisionEncoder,
    VisionConfig,
    VitEncoder,
    media_hash,
)
from dynamo_tpu.multimodal.hop import rendezvous_pick
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig
from dynamo_tpu.tokens import compute_block_hashes_for_request


def fresh_runtime() -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


def npy_data_uri(arr: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, arr)
    b64 = base64.b64encode(buf.getvalue()).decode()
    return f"data:application/x-npy;base64,{b64}"


# ----------------------------- encoder ------------------------------------


def test_vit_encoder_shapes_and_determinism():
    cfg = VisionConfig(image_size=32, patch_size=16, d_model=32,
                       n_layers=1, n_heads=2, out_dim=48)
    enc = VitEncoder(cfg, seed=1)
    assert enc.n_tokens == 4  # (32/16)^2
    rng = np.random.default_rng(0)
    px = rng.random((2, 32, 32, 3)).astype(np.float32)
    out = enc.encode(px)
    assert out.shape == (2, 4, 48)
    np.testing.assert_array_equal(out, enc.encode(px))  # deterministic
    assert not np.allclose(out[0], out[1])  # inputs matter


def test_embedding_cache_lru():
    c = EmbeddingCache(capacity=2)
    a, b, d = (np.ones((2, 4)) * i for i in (1, 2, 3))
    c.put("a", a)
    c.put("b", b)
    assert c.get("a") is not None  # refresh a
    c.put("d", d)                  # evicts b (LRU)
    assert c.get("b") is None
    assert c.get("a") is not None and c.get("d") is not None
    assert c.hits == 3 and c.misses == 1


def test_rendezvous_pick_stability():
    ids = [11, 22, 33]
    key = "media-x"
    first = rendezvous_pick(ids, key)
    assert all(rendezvous_pick(ids, key) == first for _ in range(5))
    # removing an unrelated instance keeps the mapping when possible
    remaining = [i for i in ids if i != first]
    moved = rendezvous_pick(remaining, key)
    assert moved in remaining
    assert rendezvous_pick([42], key) == 42


# --------------------------- media KV salt ---------------------------------


def test_media_hashes_salt_block_hashes():
    toks = list(range(32))
    plain = compute_block_hashes_for_request(toks, 16)
    img_a = compute_block_hashes_for_request(toks, 16,
                                             media_hashes=["aaa"])
    img_b = compute_block_hashes_for_request(toks, 16,
                                             media_hashes=["bbb"])
    assert plain != img_a
    assert img_a != img_b
    # same media -> same lineage (prefix cache works across requests)
    assert img_a == compute_block_hashes_for_request(
        toks, 16, media_hashes=["aaa"])


# --------------------------- preprocessor ----------------------------------


def test_preprocessor_extracts_images_with_positions():
    from dynamo_tpu.frontend.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.protocols import ModelDeploymentCard

    pre = OpenAIPreprocessor(ModelDeploymentCard(name="m"))
    uri = npy_data_uri(np.zeros((4, 4, 3), np.float32))
    body = {
        "model": "m",
        "messages": [{
            "role": "user",
            "content": [
                {"type": "text", "text": "describe "},
                {"type": "image_url", "image_url": {"url": uri}},
                {"type": "text", "text": " briefly"},
            ],
        }],
        "max_tokens": 4,
    }
    req = pre.preprocess_chat(body)
    assert req.multimodal is not None and len(req.multimodal) == 1
    item = req.multimodal[0]
    assert item["media_hash"] == media_hash(uri.partition(",")[2].encode())
    assert 0 < item["insert_pos"] <= len(req.token_ids)
    # marker characters never leak into the prompt tokens
    text = pre.tokenizer.decode(req.token_ids)
    assert "dyn_image" not in text and "\x00" not in text


def test_preprocessor_strips_forged_marker():
    from dynamo_tpu.frontend.preprocessor import (
        _IMAGE_MARKER,
        OpenAIPreprocessor,
    )
    from dynamo_tpu.protocols import ModelDeploymentCard

    pre = OpenAIPreprocessor(ModelDeploymentCard(name="m"))
    uri = npy_data_uri(np.zeros((2, 2, 3), np.float32))
    body = {
        "model": "m",
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": f"evil {_IMAGE_MARKER} text "},
            {"type": "image_url", "image_url": {"url": uri}},
        ]}],
        "max_tokens": 4,
    }
    req = pre.preprocess_chat(body)  # must not raise marker/media divergence
    assert len(req.multimodal) == 1


async def test_hop_preserves_adjacent_image_order():
    """Two images with no text between them share an insert_pos; the
    splice must keep the user's order (a back-to-front splice reverses
    them)."""
    from dynamo_tpu.multimodal.hop import EncoderHop
    from dynamo_tpu.protocols import PreprocessedRequest

    class FakeClient:
        instance_ids = [1]

        async def generate(self, payload, instance_id=None, token=None):
            for it in payload["items"]:
                # n_tokens differs per image so order is observable
                n = 2 if it["media_hash"] == "A" else 3
                yield {"media_hash": it["media_hash"], "n_tokens": n,
                       "shape": [n, 4], "dtype": "float32",
                       "embedding": b"\0" * (n * 16)}

    req = PreprocessedRequest(
        token_ids=[10, 11], request_id="r",
        multimodal=[{"media_hash": "A", "data_uri": "data:x,", "insert_pos": 1},
                    {"media_hash": "B", "data_uri": "data:x,", "insert_pos": 1}],
    )
    out = await EncoderHop(FakeClient(), image_token_id=99
                           ).encode_and_attach(req)
    # [10][A: 2 tokens][B: 3 tokens][11]
    assert out.token_ids == [10, 99, 99, 99, 99, 99, 11]
    assert [m["media_hash"] for m in out.multimodal] == ["A", "B"]
    assert [m["n_tokens"] for m in out.multimodal] == [2, 3]


# ------------------------- worker + hop e2e --------------------------------


async def test_encoder_worker_endpoint_and_cache():
    rt = await fresh_runtime().start()
    w = await EncoderWorker(rt, "mm-model",
                            encoder=MockVisionEncoder(n_tokens=3,
                                                      out_dim=8)).start()
    client = await (rt.namespace("dynamo").component("encoder")
                    .endpoint("encode").client()).start()
    await client.wait_for_instances()
    uri = npy_data_uri(np.ones((4, 4, 3), np.float32))
    h = media_hash(uri.partition(",")[2].encode())

    async def encode_once():
        frames = []
        async for f in client.generate(
            {"request_id": "r1",
             "items": [{"media_hash": h, "data_uri": uri}]}
        ):
            frames.append(f)
        return frames

    first = (await encode_once())[0]
    assert first["media_hash"] == h and first["n_tokens"] == 3
    assert not first["cached"]
    emb = np.frombuffer(first["embedding"],
                        dtype=first["dtype"]).reshape(first["shape"])
    assert emb.shape == (3, 8)
    second = (await encode_once())[0]
    assert second["cached"]
    np.testing.assert_array_equal(
        emb, np.frombuffer(second["embedding"],
                           dtype=second["dtype"]).reshape(second["shape"]))
    await client.close()
    await w.close()
    await rt.shutdown()


async def test_multimodal_chat_e2e_with_mocker():
    """Full path: OpenAI chat with an image part -> preprocessor
    descriptors -> EncoderHop (placeholder splice) -> mocker generation.
    The encoder fleet attaches via its role=encoder MDC."""
    rt = await fresh_runtime().start()
    args = MockEngineArgs(model_name="mm-model", block_size=4,
                          base_step_s=0.0005, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0)
    worker = await MockerWorker(rt, args).start()
    enc = await EncoderWorker(
        rt, "mm-model",
        encoder=MockVisionEncoder(n_tokens=5, out_dim=8)).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        p = manager.get("mm-model")
        if p is not None and p.encoder is not None:
            break
        await asyncio.sleep(0.02)
    p = manager.get("mm-model")
    assert p is not None and p.encoder is not None

    uri = npy_data_uri(np.full((4, 4, 3), 0.25, np.float32))
    body = {
        "model": "mm-model",
        "messages": [{
            "role": "user",
            "content": [
                {"type": "text", "text": "what is in "},
                {"type": "image_url", "image_url": {"url": uri}},
            ],
        }],
        "max_tokens": 6,
        "ignore_eos": True,
    }
    async with aiohttp.ClientSession() as s:
        async with s.post(f"http://127.0.0.1:{port}/v1/chat/completions",
                          json=body) as r:
            assert r.status == 200, await r.text()
            data = await r.json()
            assert data["usage"]["completion_tokens"] == 6
            # the 5 image placeholder tokens count as prompt tokens
            text_only = dict(body)
            text_only["messages"] = [
                {"role": "user", "content": "what is in "}]
            async with s.post(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                json=text_only,
            ) as r2:
                base = (await r2.json())["usage"]["prompt_tokens"]
            assert data["usage"]["prompt_tokens"] == base + 5
    assert enc.metrics["items"] == 1

    await service.close()
    await watcher.close()
    await enc.close()
    await worker.close()
    await rt.shutdown()


async def test_multimodal_without_encoder_fleet_fails_fast():
    rt = await fresh_runtime().start()
    args = MockEngineArgs(model_name="mm-x", block_size=4,
                          base_step_s=0.0005)
    worker = await MockerWorker(rt, args).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get("mm-x"):
            break
        await asyncio.sleep(0.02)
    uri = npy_data_uri(np.zeros((2, 2, 3), np.float32))
    body = {
        "model": "mm-x",
        "messages": [{"role": "user", "content": [
            {"type": "image_url", "image_url": {"url": uri}}]}],
        "max_tokens": 4,
    }
    async with aiohttp.ClientSession() as s:
        async with s.post(f"http://127.0.0.1:{port}/v1/chat/completions",
                          json=body) as r:
            assert r.status == 500
            assert "encoder" in (await r.text())
    await service.close()
    await watcher.close()
    await worker.close()
    await rt.shutdown()
