"""make_indexer selection policy + native/Python parity.

The C++ indexer (native/indexer.cc) is the promoted DEFAULT when its
shared library is built — conftest.py builds it at session start
whenever a toolchain exists, so on a toolchain'd box these tests
exercise the real promotion path; without one the native half skips and
the env-pinning contract is still covered.
"""

import random

import pytest

from dynamo_tpu.router.indexer import (PyKvIndexer, indexer_impl,
                                       make_indexer)


def native_built() -> bool:
    try:
        from dynamo_tpu.router.native_indexer import NativeKvIndexer  # noqa
        return True
    except (ImportError, OSError):
        return False


def test_env_pin_py_forces_reference_impl():
    ix = make_indexer("py")
    assert isinstance(ix, PyKvIndexer)
    assert indexer_impl(ix) == "py"


def test_invalid_impl_rejected_loudly():
    with pytest.raises(ValueError, match="expected auto|py|native"):
        make_indexer("bogus")


def test_default_promotes_native_when_built():
    ix = make_indexer()
    if native_built():
        assert indexer_impl(ix) == "native", (
            "library is built but auto still degraded to Python")
    else:
        assert indexer_impl(ix) == "py"


def test_native_pin_raises_when_absent_else_returns_native():
    if native_built():
        assert indexer_impl(make_indexer("native")) == "native"
    else:
        with pytest.raises((ImportError, OSError)):
            make_indexer("native")


def test_py_native_parity_randomized():
    """Interleaved stores/removes/worker-drops on both impls, comparing
    find_matches + num_blocks at every query — the same contract the
    bench parity gate enforces (benchmarks/bench_indexer.py)."""
    if not native_built():
        pytest.skip("native library not built (no toolchain)")
    py, nat = make_indexer("py"), make_indexer("native")
    rng = random.Random(23)
    universe = [rng.getrandbits(63) for _ in range(512)]
    workers = list(range(6))
    live = []
    for _ in range(1500):
        op = rng.random()
        if op < 0.55:
            w = rng.choice(workers)
            hashes = rng.sample(universe, rng.randint(1, 12))
            py.apply_stored(w, hashes)
            nat.apply_stored(w, hashes)
            live.append((w, hashes))
        elif op < 0.75 and live:
            w, hashes = live.pop(rng.randrange(len(live)))
            py.apply_removed(w, hashes)
            nat.apply_removed(w, hashes)
        elif op < 0.8:
            w = rng.choice(workers)
            py.remove_worker(w)
            nat.remove_worker(w)
            live = [(lw, h) for lw, h in live if lw != w]
        else:
            # query: a prefix-ish slice biased toward stored runs
            if live and rng.random() < 0.7:
                _, base = rng.choice(live)
                q = base + rng.sample(universe, rng.randint(0, 4))
            else:
                q = rng.sample(universe, rng.randint(1, 16))
            assert py.find_matches(q) == nat.find_matches(q)
            assert py.num_blocks == nat.num_blocks
    assert py.num_blocks == nat.num_blocks
    assert sorted(py.workers) == sorted(nat.workers)
