"""Mock engine tests: scheduling, prefix caching, KV events, cancellation."""

import asyncio
import uuid

from dynamo_tpu.mocker import MockEngine, MockEngineArgs
from dynamo_tpu.mocker.kv_cache_sim import KvCacheSim
from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime import CancellationToken
from dynamo_tpu.tokens import compute_block_hashes


def make_args(**kw):
    defaults = dict(block_size=4, num_blocks=64, base_step_s=0.0005,
                    prefill_s_per_token=0.0, decode_s_per_seq=0.0)
    defaults.update(kw)
    return MockEngineArgs(**defaults)


def req(tokens, max_tokens=8, rid=None, seed=0, ignore_eos=True):
    return PreprocessedRequest(
        token_ids=tokens,
        request_id=rid or uuid.uuid4().hex,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=ignore_eos),
    )


# --------------------------- KvCacheSim unit ---------------------------


def test_cache_prefix_hit_and_eviction():
    sim = KvCacheSim(num_blocks=8)
    hs = compute_block_hashes(list(range(16)), 4)  # 4 full blocks
    res = sim.allocate("a", hs, total_blocks=4)
    assert res is not None and len(res.stored) == 4 and res.cached_blocks == 0
    # same prefix again: full hit
    res2 = sim.allocate("b", hs, total_blocks=4)
    assert res2 is not None and res2.cached_blocks == 4 and not res2.stored
    sim.free("a")
    sim.free("b")
    # blocks remain cached for reuse
    assert sim.lookup(hs) == 4
    # fill the cache with new sequences; old blocks get evicted (LRU)
    hs2 = compute_block_hashes(list(range(100, 132)), 4)  # 8 blocks
    res3 = sim.allocate("c", hs2, total_blocks=8)
    assert res3 is not None
    assert len(res3.removed) == 4  # evicted the old cached blocks
    assert sim.lookup(hs) == 0


def test_cache_capacity_refusal():
    sim = KvCacheSim(num_blocks=4)
    hs = compute_block_hashes(list(range(32)), 4)  # 8 blocks > capacity
    assert sim.allocate("a", hs, total_blocks=8) is None


# --------------------------- engine behavior ---------------------------


async def test_engine_generates_and_finishes():
    eng = MockEngine(make_args())
    outs = []
    async for out in eng.generate(req(list(range(10)), max_tokens=5)):
        outs.append(out)
    assert len(outs) == 5
    assert all(len(o.token_ids) == 1 for o in outs)
    assert outs[-1].finish_reason == "length"
    assert outs[-1].metrics is not None
    await eng.close()


async def test_engine_prefix_cache_hits_across_requests():
    eng = MockEngine(make_args())
    prompt = list(range(40))  # 10 blocks of 4
    async for _ in eng.generate(req(prompt, max_tokens=2, seed=1)):
        pass
    hit0 = eng.metrics["cache_hit_blocks"]
    async for _ in eng.generate(req(prompt, max_tokens=2, seed=2)):
        pass
    assert eng.metrics["cache_hit_blocks"] >= hit0 + 10
    await eng.close()


async def test_engine_concurrent_requests():
    eng = MockEngine(make_args(max_num_seqs=8))
    async def run_one(i):
        n = 0
        async for out in eng.generate(req(list(range(i * 7, i * 7 + 12)),
                                          max_tokens=6)):
            n += len(out.token_ids)
        return n
    counts = await asyncio.gather(*[run_one(i) for i in range(6)])
    assert all(c == 6 for c in counts)
    await eng.close()


async def test_engine_cancellation():
    eng = MockEngine(make_args(decode_s_per_seq=0.01))
    token = CancellationToken()
    got = []

    async def consume():
        async for out in eng.generate(req(list(range(8)), max_tokens=10_000),
                                      token=token):
            got.append(out)

    task = asyncio.create_task(consume())
    await asyncio.sleep(0.3)
    token.stop()
    await asyncio.wait_for(task, timeout=5)
    assert got and got[-1].finish_reason == "cancelled"
    assert eng.running == [] and eng.waiting == []
    await eng.close()


async def test_engine_deterministic_with_seed():
    eng = MockEngine(make_args())
    async def run(seed):
        r = req(list(range(8)), max_tokens=6)
        r.sampling.seed = seed
        return [o.token_ids[0] async for o in eng.generate(r)
                if o.token_ids]
    a = await run(42)
    b = await run(42)
    c = await run(43)
    assert a == b
    assert a != c
    await eng.close()


# ----------------------- tier sim (fleet prefix cache) -----------------------


def test_cache_sim_demotion_chain_and_g4_onboard():
    """G1 evictions walk the G2 host LRU into the shared store, emitting
    the same per-tier event batches the real engine publishes; a later
    admission onboards the whole run back instead of recomputing."""
    from dynamo_tpu.mocker.kv_cache_sim import SimObjectStore
    from dynamo_tpu.obs.kv_ledger import KvLedger

    store = SimObjectStore()
    led = KvLedger()
    sim = KvCacheSim(num_blocks=8, ledger=led, host_blocks=2,
                     object_store=store)
    prefix = [1001, 1002, 1003, 1004]
    res = sim.allocate("a", prefix, 4)
    assert res.cached_blocks == 0 and res.onboarded == {}
    sim.free("a")
    # junk floods G1: the prefix demotes into the 2-slot host LRU, whose
    # own overflow spills on into the shared store
    res2 = sim.allocate("j", [2000 + i for i in range(8)], 8)
    g2_stored = [h for st, _, t in res2.tier_events for h in st
                 if t == "g2"]
    g4_stored = [h for st, _, t in res2.tier_events for h in st
                 if t == "g4"]
    assert set(res2.removed) == set(prefix)
    assert set(g2_stored) == set(prefix)  # every demotion hops through g2
    assert g4_stored == [1001, 1002]      # LRU overflow spilled the oldest
    assert sim.g2_blocks == 2 and 1001 in store
    sim.free("j")
    # the prefix comes back: onboarded (g2/g4 mix), not recomputed
    res3 = sim.allocate("b", prefix, 4)
    assert sum(res3.onboarded.values()) == 4
    assert res3.cached_blocks == 4
    assert led.onboard_counts() == dict(res3.onboarded)
    # g4 blobs STAY in the shared store (fleet copy) after onboarding
    assert 1001 in store and 1002 in store


def test_cache_sim_g2_onboard_moves_host_copy():
    from dynamo_tpu.mocker.kv_cache_sim import KvCacheSim

    sim = KvCacheSim(num_blocks=4, host_blocks=4)
    prefix = [11, 12]
    sim.allocate("a", prefix, 2)
    sim.free("a")
    sim.allocate("j", [21, 22, 23, 24], 4)  # evicts the prefix into g2
    assert sim.g2_blocks == 2
    sim.free("j")
    res = sim.allocate("b", prefix, 2)
    assert res.onboarded == {"g2": 2}
    g2_removed = [h for _, rm, t in res.tier_events for h in rm
                  if t == "g2"]
    # the host copy MOVES into G1 (slot freed), unlike the shared g4 blob
    assert set(g2_removed) >= set(prefix)


def test_cache_sim_onboard_run_breaks_at_miss():
    """Prefix KV is position-addressed: a missing middle block ends the
    onboardable run — later store-resident blocks must not count."""
    from dynamo_tpu.mocker.kv_cache_sim import KvCacheSim, SimObjectStore

    store = SimObjectStore()
    store.put(31)
    store.put(33)  # 32 missing: the run must break there
    sim = KvCacheSim(num_blocks=8, object_store=store)
    res = sim.allocate("a", [31, 32, 33], 3)
    assert res.onboarded == {"g4": 1}
    assert res.cached_blocks == 1


def test_sim_object_store_sweep_verdicts():
    """Same verdict ladder as ObjectStorePool.sweep: hot renews, dead
    reaps early, None falls back to the TTL clock."""
    import time

    from dynamo_tpu.mocker.kv_cache_sim import SimObjectStore

    store = SimObjectStore(ttl_s=10.0)
    for h in (1, 2, 3):
        store.put(h)
    now = time.monotonic() + 20.0
    reaped = store.sweep(now=now, residency={1: "hot", 2: "dead"}.get)
    assert set(reaped) == {2, 3}  # dead early + TTL-expired
    assert 1 in store and len(store) == 1
    # the hot renewal restarted the clock...
    assert store.sweep(now=now + 5.0) == []
    # ...but without fresh traffic the TTL eventually wins
    assert store.sweep(now=now + 50.0) == [1]


async def test_engine_g4_onboarding_across_engines():
    """Two simulated engines share one SimObjectStore (the shared-FS
    mount analogue): engine A computes a prefix and churns it down to
    G4; a COLD engine B serves the same prefix by onboarding — counted
    in kv_onboard_g4, marked in its ledger, books still clean."""
    from dynamo_tpu.mocker.kv_cache_sim import SimObjectStore

    store = SimObjectStore()
    a = MockEngine(make_args(num_blocks=8, host_blocks=2,
                             object_store=store, kv_ledger=True))
    prompt = list(range(16))  # 4 blocks of 4
    async for _ in a.generate(req(prompt, max_tokens=2, seed=1)):
        pass
    for i in range(4):
        junk = list(range(100 + 16 * i, 116 + 16 * i))
        async for _ in a.generate(req(junk, max_tokens=2)):
            pass
    assert len(store) >= 4, "churn never reached the shared store"
    b = MockEngine(make_args(num_blocks=16, host_blocks=2,
                             object_store=store, kv_ledger=True))
    async for _ in b.generate(req(prompt, max_tokens=2, seed=1)):
        pass
    assert b.metrics.get("kv_onboard_g4", 0) >= 4
    assert b.kv_ledger.onboard_counts().get("g4", 0) >= 4
    assert b.audit_kv()["clean"] and a.audit_kv()["clean"]
    await a.close()
    await b.close()
