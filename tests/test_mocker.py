"""Mock engine tests: scheduling, prefix caching, KV events, cancellation."""

import asyncio
import uuid

from dynamo_tpu.mocker import MockEngine, MockEngineArgs
from dynamo_tpu.mocker.kv_cache_sim import KvCacheSim
from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime import CancellationToken
from dynamo_tpu.tokens import compute_block_hashes


def make_args(**kw):
    defaults = dict(block_size=4, num_blocks=64, base_step_s=0.0005,
                    prefill_s_per_token=0.0, decode_s_per_seq=0.0)
    defaults.update(kw)
    return MockEngineArgs(**defaults)


def req(tokens, max_tokens=8, rid=None, seed=0, ignore_eos=True):
    return PreprocessedRequest(
        token_ids=tokens,
        request_id=rid or uuid.uuid4().hex,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=ignore_eos),
    )


# --------------------------- KvCacheSim unit ---------------------------


def test_cache_prefix_hit_and_eviction():
    sim = KvCacheSim(num_blocks=8)
    hs = compute_block_hashes(list(range(16)), 4)  # 4 full blocks
    res = sim.allocate("a", hs, total_blocks=4)
    assert res is not None and len(res.stored) == 4 and res.cached_blocks == 0
    # same prefix again: full hit
    res2 = sim.allocate("b", hs, total_blocks=4)
    assert res2 is not None and res2.cached_blocks == 4 and not res2.stored
    sim.free("a")
    sim.free("b")
    # blocks remain cached for reuse
    assert sim.lookup(hs) == 4
    # fill the cache with new sequences; old blocks get evicted (LRU)
    hs2 = compute_block_hashes(list(range(100, 132)), 4)  # 8 blocks
    res3 = sim.allocate("c", hs2, total_blocks=8)
    assert res3 is not None
    assert len(res3.removed) == 4  # evicted the old cached blocks
    assert sim.lookup(hs) == 0


def test_cache_capacity_refusal():
    sim = KvCacheSim(num_blocks=4)
    hs = compute_block_hashes(list(range(32)), 4)  # 8 blocks > capacity
    assert sim.allocate("a", hs, total_blocks=8) is None


# --------------------------- engine behavior ---------------------------


async def test_engine_generates_and_finishes():
    eng = MockEngine(make_args())
    outs = []
    async for out in eng.generate(req(list(range(10)), max_tokens=5)):
        outs.append(out)
    assert len(outs) == 5
    assert all(len(o.token_ids) == 1 for o in outs)
    assert outs[-1].finish_reason == "length"
    assert outs[-1].metrics is not None
    await eng.close()


async def test_engine_prefix_cache_hits_across_requests():
    eng = MockEngine(make_args())
    prompt = list(range(40))  # 10 blocks of 4
    async for _ in eng.generate(req(prompt, max_tokens=2, seed=1)):
        pass
    hit0 = eng.metrics["cache_hit_blocks"]
    async for _ in eng.generate(req(prompt, max_tokens=2, seed=2)):
        pass
    assert eng.metrics["cache_hit_blocks"] >= hit0 + 10
    await eng.close()


async def test_engine_concurrent_requests():
    eng = MockEngine(make_args(max_num_seqs=8))
    async def run_one(i):
        n = 0
        async for out in eng.generate(req(list(range(i * 7, i * 7 + 12)),
                                          max_tokens=6)):
            n += len(out.token_ids)
        return n
    counts = await asyncio.gather(*[run_one(i) for i in range(6)])
    assert all(c == 6 for c in counts)
    await eng.close()


async def test_engine_cancellation():
    eng = MockEngine(make_args(decode_s_per_seq=0.01))
    token = CancellationToken()
    got = []

    async def consume():
        async for out in eng.generate(req(list(range(8)), max_tokens=10_000),
                                      token=token):
            got.append(out)

    task = asyncio.create_task(consume())
    await asyncio.sleep(0.3)
    token.stop()
    await asyncio.wait_for(task, timeout=5)
    assert got and got[-1].finish_reason == "cancelled"
    assert eng.running == [] and eng.waiting == []
    await eng.close()


async def test_engine_deterministic_with_seed():
    eng = MockEngine(make_args())
    async def run(seed):
        r = req(list(range(8)), max_tokens=6)
        r.sampling.seed = seed
        return [o.token_ids[0] async for o in eng.generate(r)
                if o.token_ids]
    a = await run(42)
    b = await run(42)
    c = await run(43)
    assert a == b
    assert a != c
    await eng.close()
