"""JAX engine correctness: paged attention vs dense reference, prefix cache,
batching invariance, tensor-parallel invariance, cancellation."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks


from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.models.llama import LlamaConfig, init_params, rms_norm, rope
from dynamo_tpu.protocols import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

FP32 = LlamaConfig(name="tiny32", vocab_size=256, d_model=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
                   dtype=jnp.float32)


def dense_reference_logits(params, cfg, token_ids):
    """Independent full-attention forward (no paging): logits for every
    position.  Used as ground truth for the paged implementation."""
    T = len(token_ids)
    x = params["embedding"][jnp.asarray(token_ids)].astype(cfg.dtype)
    positions = jnp.arange(T)
    for layer in params["layers"]:
        h = rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
        q = (h @ layer["wq"]).reshape(T, cfg.n_heads, cfg.head_dim)
        k = (h @ layer["wk"]).reshape(T, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ layer["wv"]).reshape(T, cfg.n_kv_heads, cfg.head_dim)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        group = cfg.n_heads // cfg.n_kv_heads
        kr = jnp.repeat(k, group, axis=1)  # [T, nh, hd]
        vr = jnp.repeat(v, group, axis=1)
        s = jnp.einsum("ihd,jhd->hij", q.astype(jnp.float32),
                       kr.astype(jnp.float32)) / np.sqrt(cfg.head_dim)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("hij,jhd->ihd", p, vr.astype(jnp.float32))
        x = x + o.reshape(T, -1).astype(cfg.dtype) @ layer["wo"]
        h = rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
        x = x + (jax.nn.silu(h @ layer["w_gate"]) * (h @ layer["w_up"])) @ layer["w_down"]
    x = rms_norm(x, params["final_norm"]["norm"], cfg.rms_eps)
    return (x @ params["lm_head"]).astype(jnp.float32)


def engine(tp=1, **kw):
    defaults = dict(model_config=FP32, block_size=4, num_blocks=128,
                    max_blocks_per_seq=16, max_num_seqs=4, tp=tp,
                    prefill_buckets=(8, 16, 32, 64), seed=7)
    defaults.update(kw)
    return JaxEngine(EngineConfig(**defaults))


def greedy_req(tokens, n, rid, seed=0):
    return PreprocessedRequest(
        token_ids=tokens, request_id=rid,
        sampling=SamplingOptions(temperature=0.0, seed=seed),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )


async def collect(eng, req, token=None):
    toks = []
    async for out in eng.generate(req, token=token):
        toks.extend(out.token_ids)
    return toks


async def test_greedy_matches_dense_reference():
    """The paged engine's greedy generations must equal teacher-forced argmax
    under an independent dense implementation."""
    eng = engine()
    prompt = [5, 9, 13, 2, 7, 11, 3, 1, 8, 20]  # 10 tokens (crosses blocks)
    toks = await collect(eng, greedy_req(prompt, 6, "r0"))
    assert len(toks) == 6

    seq = list(prompt)
    for t in toks:
        logits = dense_reference_logits(eng.params, FP32, seq)
        expect = int(jnp.argmax(logits[-1]))
        assert expect == t, f"divergence at position {len(seq)}"
        seq.append(t)
    await eng.close()


async def test_prefix_cache_reuse_preserves_output():
    eng = engine()
    prompt = list(range(30, 50))  # 20 tokens = 5 full blocks
    a = await collect(eng, greedy_req(prompt, 5, "a"))
    hit0 = eng.metrics["cache_hit_tokens"]
    b = await collect(eng, greedy_req(prompt, 5, "b"))
    assert eng.metrics["cache_hit_tokens"] > hit0  # reused prefix blocks
    assert a == b  # identical output despite skipped prefill
    await eng.close()


async def test_batching_invariance():
    """Concurrent requests must produce the same greedy outputs as solo runs."""
    eng = engine()
    prompts = [[3, 1, 4, 1, 5, 9], [2, 7, 1, 8, 2, 8, 1, 8], [14, 14, 2]]
    solo = []
    for i, p in enumerate(prompts):
        solo.append(await collect(eng, greedy_req(p, 4, f"solo{i}")))
        await eng.clear_kv_blocks()
    together = await asyncio.gather(*[
        collect(eng, greedy_req(p, 4, f"batch{i}"))
        for i, p in enumerate(prompts)
    ])
    assert list(together) == solo
    await eng.close()


async def test_tensor_parallel_invariance():
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    prompt = list(range(60, 75))
    e1 = engine(tp=1)
    t1 = await collect(e1, greedy_req(prompt, 5, "tp1"))
    await e1.close()
    e2 = engine(tp=2)
    t2 = await collect(e2, greedy_req(prompt, 5, "tp2"))
    await e2.close()
    assert t1 == t2


async def test_long_prompt_chunked_prefill():
    eng = engine(max_blocks_per_seq=64, num_blocks=256,
                 prefill_buckets=(8, 16))  # force chunking: prompt 40 > 16
    prompt = list(range(1, 41))
    toks = await collect(eng, greedy_req(prompt, 3, "long"))
    assert len(toks) == 3
    seq = list(prompt)
    for t in toks:
        logits = dense_reference_logits(eng.params, FP32, seq)
        assert int(jnp.argmax(logits[-1])) == t
        seq.append(t)
    await eng.close()


async def test_sampled_generation_deterministic_by_seed():
    eng = engine()
    def sreq(rid, seed):
        return PreprocessedRequest(
            token_ids=[4, 8, 15, 16, 23, 42], request_id=rid,
            sampling=SamplingOptions(temperature=0.8, top_k=20, seed=seed),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
        )
    a = await collect(eng, sreq("s1", 123))
    b = await collect(eng, sreq("s2", 123))
    c = await collect(eng, sreq("s3", 999))
    assert a == b
    assert a != c
    await eng.close()


async def test_cancellation_frees_blocks():
    from dynamo_tpu.runtime import CancellationToken

    eng = engine()
    token = CancellationToken()
    req = greedy_req(list(range(12)), 10_000, "cancelme")
    got = []

    async def consume():
        async for out in eng.generate(req, token=token):
            got.append(out)

    task = asyncio.create_task(consume())
    await asyncio.sleep(0.5)
    token.stop()
    await asyncio.wait_for(task, timeout=10)
    assert got[-1].finish_reason == "cancelled"
    # teardown happens on the next scheduler step, which may be stuck behind
    # a multi-second XLA compile on CPU — wait generously
    for _ in range(600):
        if all(s is None for s in eng._slots) and not eng.waiting:
            break
        await asyncio.sleep(0.05)
    assert all(s is None for s in eng._slots)
    assert not eng.waiting
    await eng.close()


async def test_kv_events_emitted():
    events = []

    async def sink(stored, removed):
        events.append((list(stored), list(removed)))

    cfg = EngineConfig(model_config=FP32, block_size=4, num_blocks=16,
                       max_blocks_per_seq=8, max_num_seqs=2,
                       prefill_buckets=(8, 16, 32), seed=7)
    eng = JaxEngine(cfg, kv_event_sink=sink)
    await collect(eng, greedy_req(list(range(12)), 6, "ev1"))
    await asyncio.sleep(0.05)
    stored = [h for st, _ in events for h in st]
    # 12-token prompt = 3 full blocks; some decode blocks may complete too
    assert len(stored) >= 3
    await eng.close()


async def test_trailing_block_not_registered_before_kv_materialized():
    """A request finishing exactly at a block boundary must NOT register the
    trailing block: the final sampled token's K/V is only written on the next
    decode step, which never runs.  Registering it would let a later prompt
    prefix-match a block whose last position holds zeros (ADVICE r1, high)."""
    events = []

    def sink(stored, removed):
        events.append((list(stored), list(removed)))

    cfg = EngineConfig(model_config=FP32, block_size=4, num_blocks=16,
                       max_blocks_per_seq=8, max_num_seqs=2,
                       prefill_buckets=(8, 16, 32), seed=7)
    eng = JaxEngine(cfg, kv_event_sink=sink)
    # 7-token prompt + 1 generated = 8 tokens = 2 exact blocks.  Block 0 is
    # fully materialized by prefill; block 1 is completed by the sampled
    # token whose K/V never lands in the cache.
    await collect(eng, greedy_req(list(range(1, 8)), 1, "bd1"))
    await asyncio.sleep(0.05)
    stored = [h for st, _ in events for h in st]
    assert len(stored) == 1, f"trailing block leaked into the cache: {stored}"
    await eng.close()


async def test_chunked_prefill_interleaves_with_decode():
    """A long multi-chunk prefill must not stall active decodes: with
    prefill buckets capped at 8 tokens, a 64-token prompt takes 8 chunks,
    and the already-decoding request should keep producing tokens between
    chunks (one per scheduler step) instead of stalling for the whole
    prefill (round-1 verdict weak #4)."""
    cfg = EngineConfig(model_config=FP32, block_size=4, num_blocks=128,
                       max_blocks_per_seq=32, max_num_seqs=2,
                       prefill_buckets=(8,), max_batch_tokens=8, seed=7)
    eng = JaxEngine(cfg)

    progress = []  # (who, engine prefill_tokens so far) per token

    async def run(req, tag):
        async for out in eng.generate(req):
            for _ in out.token_ids:
                progress.append((tag, eng.metrics["prefill_tokens"]))

    short = greedy_req(list(range(1, 9)), 40, "short")
    t_short = asyncio.create_task(run(short, "short"))
    # let the short request admit and start decoding
    for _ in range(600):
        if any(p[0] == "short" for p in progress):
            break
        await asyncio.sleep(0.05)
    long = greedy_req(list(range(1, 65)), 2, "long")
    t_long = asyncio.create_task(run(long, "long"))
    await asyncio.wait_for(asyncio.gather(t_short, t_long), 120)

    # tokens the short request produced while the long prefill was mid-way
    # (prefill counter strictly between its start and end values)
    pf_end = eng.metrics["prefill_tokens"]
    mid = [p for p in progress
           if p[0] == "short" and 8 < p[1] < pf_end]
    assert len(mid) >= 4, (
        f"decode stalled during chunked prefill: {progress}"
    )
    await eng.close()


async def test_sync_sink_removed_published_before_stored():
    """One allocator mutation can evict hash H and re-register it; the wire
    must carry removed before stored so routers don't drop live blocks."""
    from dynamo_tpu.router.events import KvEventPublisher

    published = []

    class FakePlane:
        async def publish(self, subject, payload):
            published.append(payload)

    class FakeRuntime:
        event_plane = FakePlane()

    pub = KvEventPublisher(FakeRuntime(), "ns", "comp", worker_id=1)
    pub.enqueue_batch(stored=[1 << 100], removed=[2 << 100])
    pub.enqueue_batch(stored=[3 << 100])
    await pub._flush()
    assert [p["op"] for p in published] == ["removed", "stored", "stored"]
    assert [p["event_id"] for p in published] == [0, 1, 2]


async def test_fused_decode_matches_single_step():
    """decode_fused_steps must not change outputs: greedy and sampled
    streams are token-identical to the single-step path (same seed
    folding), including mid-burst EOS/length finishes."""
    import jax.numpy as jnp

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    cfg32 = LlamaConfig(name="tiny32", vocab_size=256, d_model=64,
                        n_layers=2, n_heads=4, n_kv_heads=2, head_dim=16,
                        ffn_dim=128, dtype=jnp.float32)
    base = dict(model_config=cfg32, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, max_num_seqs=2,
                prefill_buckets=(8, 16), seed=11)

    async def run(fused, rid, temperature, n):
        eng = JaxEngine(EngineConfig(decode_fused_steps=fused, **base))
        req = PreprocessedRequest(
            token_ids=list(range(7, 20)), request_id=rid,
            sampling=SamplingOptions(temperature=temperature, seed=123),
            stop=StopConditions(max_tokens=n, ignore_eos=True),
        )
        toks = []
        async for out in eng.generate(req):
            toks.extend(out.token_ids)
        await eng.close()
        return toks

    # greedy, n not a multiple of the burst (mid-burst length finish)
    single = await run(1, "s", 0.0, 11)
    fused = await run(8, "f", 0.0, 11)
    assert fused == single and len(fused) == 11

    # sampled: per-token rng streams must line up across burst boundaries
    single = await run(1, "s2", 0.9, 10)
    fused = await run(4, "f2", 0.9, 10)
    assert fused == single


def test_prefill_batched_matches_sequential():
    """prefill_batched (multi-sequence, one program) must write the same KV
    and produce the same last-token logits as per-sequence prefill calls."""
    from dynamo_tpu.models.llama import prefill, prefill_batched

    cfg = FP32
    params = init_params(cfg, jax.random.PRNGKey(1))
    bs, nb, mb = 4, 64, 8
    shape = (cfg.n_layers, cfg.n_kv_heads, nb, cfg.head_dim, bs)
    kv_a = (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))
    kv_b = (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))

    rng = np.random.default_rng(3)
    T = 16
    lens = [16, 11, 7]  # full, partial, short
    prompts = [rng.integers(1, cfg.vocab_size, n).astype(np.int32)
               for n in lens]
    # disjoint block tables (ids >= 1)
    tables = np.zeros((3, mb), np.int32)
    for i, n in enumerate(lens):
        used = -(-n // bs)
        tables[i, :used] = 1 + i * mb + np.arange(used)

    # sequential oracle
    seq_logits = []
    for i, p in enumerate(prompts):
        toks = np.zeros(T, np.int32)
        toks[: lens[i]] = p
        lg, kv_a = prefill(
            params, cfg, kv_a, jnp.asarray(toks),
            jnp.arange(T, dtype=jnp.int32), jnp.asarray(tables[i]),
            jnp.int32(0), jnp.int32(lens[i]),
        )
        seq_logits.append(np.asarray(lg))

    # batched (pad to Bp=4 with an empty row)
    btoks = np.zeros((4, T), np.int32)
    for i, p in enumerate(prompts):
        btoks[i, : lens[i]] = p
    btables = np.zeros((4, mb), np.int32)
    btables[:3] = tables
    blogits, kv_b = prefill_batched(
        params, cfg, kv_b, jnp.asarray(btoks),
        jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (4, T)),
        jnp.asarray(btables), jnp.zeros(4, jnp.int32),
        jnp.asarray(np.array(lens + [0], np.int32)),
    )
    for i in range(3):
        np.testing.assert_allclose(
            np.asarray(blogits[i]), seq_logits[i], rtol=2e-5, atol=2e-5
        )
    # caches identical on every block the sequences own (block 0 is
    # garbage); tolerance covers batched-vs-single matmul reassociation
    np.testing.assert_allclose(
        np.asarray(kv_b[0][:, :, 1:]), np.asarray(kv_a[0][:, :, 1:]),
        rtol=1e-3, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(kv_b[1][:, :, 1:]), np.asarray(kv_a[1][:, :, 1:]),
        rtol=1e-3, atol=1e-5,
    )


def test_prefill_packed_matches_sequential():
    """prefill_packed (one padding-free stream with segment ids) must
    write the same KV and produce the same last-token logits as
    per-sequence prefill calls — including a prefix-cache-hit TAIL
    (packing starts at ctx > 0) and a chunk boundary (one prompt split
    across two packed dispatches)."""
    from dynamo_tpu.models.llama import prefill, prefill_packed

    cfg = FP32
    params = init_params(cfg, jax.random.PRNGKey(1))
    bs, nb, mb = 4, 64, 8
    shape = (cfg.n_layers, cfg.n_kv_heads, nb, cfg.head_dim, bs)
    kv_a = (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))
    kv_b = (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))

    rng = np.random.default_rng(3)
    lens = [16, 11, 7]
    prompts = [rng.integers(1, cfg.vocab_size, n).astype(np.int32)
               for n in lens]
    tables = np.zeros((3, mb), np.int32)
    for i, n in enumerate(lens):
        used = -(-n // bs)
        tables[i, :used] = 1 + i * mb + np.arange(used)

    # sequential oracle (whole prompts, one per call)
    T = 16
    seq_logits = []
    for i, p in enumerate(prompts):
        toks = np.zeros(T, np.int32)
        toks[: lens[i]] = p
        lg, kv_a = prefill(
            params, cfg, kv_a, jnp.asarray(toks),
            jnp.arange(T, dtype=jnp.int32), jnp.asarray(tables[i]),
            jnp.int32(0), jnp.int32(lens[i]),
        )
        seq_logits.append(np.asarray(lg))

    def packed_call(kv, parts, S=4, Tp=32):
        """parts: [(seg_row_tokens, start_pos, table_row), ...]"""
        toks = np.zeros(Tp, np.int32)
        pos = np.zeros(Tp, np.int32)
        seg = np.zeros(Tp, np.int32)
        val = np.zeros(Tp, bool)
        btables = np.zeros((S, mb), np.int32)
        last = np.zeros(S, np.int32)
        off = 0
        for i, (chunk, start, table) in enumerate(parts):
            n = len(chunk)
            toks[off:off + n] = chunk
            pos[off:off + n] = start + np.arange(n)
            seg[off:off + n] = i
            val[off:off + n] = True
            btables[i] = table
            last[i] = off + n - 1
            off += n
        return prefill_packed(
            params, cfg, kv, jnp.asarray(toks), jnp.asarray(pos),
            jnp.asarray(seg), jnp.asarray(btables), jnp.asarray(last),
            jnp.asarray(val),
        )

    # dispatch 1: prompt 0's FIRST chunk (10 tokens) + prompt 2 whole
    lg1, kv_b = packed_call(kv_b, [
        (prompts[0][:10], 0, tables[0]),
        (prompts[2], 0, tables[2]),
    ])
    np.testing.assert_allclose(np.asarray(lg1[1]), seq_logits[2],
                               rtol=2e-5, atol=2e-5)
    # dispatch 2: prompt 0's TAIL (chunk boundary: starts at ctx=10, the
    # prefix-hit shape) + prompt 1 whole
    lg2, kv_b = packed_call(kv_b, [
        (prompts[0][10:], 10, tables[0]),
        (prompts[1], 0, tables[1]),
    ])
    np.testing.assert_allclose(np.asarray(lg2[0]), seq_logits[0],
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lg2[1]), seq_logits[1],
                               rtol=2e-5, atol=2e-5)
    # caches identical on every owned block (block 0 is garbage);
    # tolerance covers packed-vs-single matmul reassociation
    for ca, cb in zip(kv_a, kv_b):
        np.testing.assert_allclose(
            np.asarray(cb[:, :, 1:]), np.asarray(ca[:, :, 1:]),
            rtol=1e-3, atol=1e-5,
        )


async def test_packed_prefill_engine_matches_legacy():
    """The packed chunked-prefill scheduler (the default) must produce
    the same greedy tokens as the legacy padded paths for concurrent
    arrivals, multi-chunk prompts, and a prefix-cache-hit second round —
    and its FPM records must carry the prefill-phase fields the SLA
    planner consumes."""
    rng = np.random.default_rng(5)
    prompts = [list(map(int, rng.integers(1, 200, n)))
               for n in (12, 7, 19, 26)]

    async def run(packed):
        eng = engine(max_num_seqs=4, prefill_packed=packed,
                     max_batch_tokens=32, max_prefill_seqs=4)
        outs = await asyncio.gather(*[
            collect(eng, greedy_req(p, 4, f"pk{packed}-{i}"))
            for i, p in enumerate(prompts)
        ])
        # prefix-cache hit: the same prompt again packs only its TAIL
        again = await collect(eng, greedy_req(prompts[0], 4,
                                              f"pk{packed}-again"))
        hits = eng.metrics["cache_hit_tokens"]
        recs = [r for r in eng.fpm if r.get("kind") == "prefill"]
        await eng.close()
        return list(outs), again, hits, recs

    p_outs, p_again, p_hits, p_recs = await run(True)
    l_outs, l_again, l_hits, _ = await run(False)
    assert p_outs == l_outs
    assert p_again == l_again
    assert p_hits > 0 and p_hits == l_hits
    assert any(r.get("packed") for r in p_recs), \
        "packed path never engaged"
    for r in p_recs:
        assert {"gap_s", "flops", "queue_depth"} <= set(r)


async def test_concurrent_prefill_batched_and_correct():
    """Concurrent arrivals must prefill together (round-2 verdict weak #3:
    one B=1 chunk per step serializes TTFT under queue depth) and produce
    the same tokens as each prompt served alone."""
    rng = np.random.default_rng(9)
    prompts = [list(map(int, rng.integers(1, 200, 12))) for _ in range(4)]

    # oracle: each prompt alone
    alone = []
    for i, p in enumerate(prompts):
        eng = engine(decode_fused_steps=1)
        alone.append(await collect(eng, greedy_req(p, 4, f"alone-{i}")))
        await eng.close()

    eng = engine(decode_fused_steps=1, max_batch_tokens=64,
                 max_prefill_seqs=4)
    outs = await asyncio.gather(*[
        collect(eng, greedy_req(p, 4, f"conc-{i}"))
        for i, p in enumerate(prompts)
    ])
    steps = eng.metrics["prefill_steps"]
    await eng.close()
    assert outs == alone
    # 4×12 prompt tokens fit one 64-token budget: batched prefill must not
    # take one step per sequence (allow slack for admission raciness)
    assert steps < 4, f"prefill serialized: {steps} steps for 4 arrivals"


async def test_continuation_bursts_engage_and_match_full_dispatch():
    """Steady-state decode takes the device-resident continuation path
    (zero per-burst uploads); its token streams must be identical to the
    always-full-dispatch path for greedy AND sampled requests, and the
    path must disengage cleanly around membership changes (a second
    request arriving mid-decode)."""

    async def run(force_full, rid_tag):
        # block_size > k * a few bursts, so tables don't grow every burst
        # (growth forces a full dispatch by design)
        eng = engine(decode_fused_steps=4, max_num_seqs=2, block_size=16,
                     prefill_buckets=(16, 32))
        if force_full:
            eng._is_continuation = lambda a, active, k: False
        r1 = PreprocessedRequest(
            token_ids=list(range(7, 20)), request_id=f"c1-{rid_tag}",
            sampling=SamplingOptions(temperature=0.9, seed=5),
            stop=StopConditions(max_tokens=24, ignore_eos=True),
        )
        r2 = greedy_req(list(range(40, 49)), 16, f"c2-{rid_tag}")

        async def delayed():
            await asyncio.sleep(0.25)  # arrive mid-decode of r1
            return await collect(eng, r2)

        t2 = asyncio.create_task(delayed())
        toks1 = await collect(eng, r1)
        toks2 = await t2
        bursts = eng.metrics.get("cont_bursts", 0)
        await eng.close()
        return toks1, toks2, bursts

    full1, full2, b_full = await run(True, "full")
    cont1, cont2, b_cont = await run(False, "cont")
    assert b_full == 0
    assert b_cont >= 2, "continuation path never engaged"
    assert cont1 == full1, "sampled stream diverged on continuation path"
    assert cont2 == full2, "greedy stream diverged on continuation path"


async def test_ring_attention_prefill_long_prompt_matches_chunked():
    """Long-context path: a prompt beyond the largest prefill bucket on
    an sp=2 mesh takes ONE sequence-parallel ring-attention program and
    must produce the same greedy continuation as the chunked path on an
    sp=1 engine (exactness of ops/ring_attention.py composed with the
    paged cache + sampler)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    base = dict(model_config=FP32, block_size=4, num_blocks=128,
                max_blocks_per_seq=32, max_num_seqs=2,
                prefill_buckets=(8, 16), seed=7)
    prompt = list(range(1, 41))  # 40 tokens > largest bucket (16)

    chunked = JaxEngine(EngineConfig(**base))
    expect = await collect(chunked, greedy_req(prompt, 5, "chunked"))
    await chunked.close()

    eng = JaxEngine(EngineConfig(sp=2, **base))
    toks = await collect(eng, greedy_req(prompt, 5, "ring"))
    assert eng.metrics.get("ring_prefills", 0) == 1, \
        "long prompt did not take the ring-attention path"
    assert toks == expect, "ring prefill continuation diverged"

    # short prompts stay on the (cheaper) chunked path
    toks2 = await collect(eng, greedy_req(list(range(50, 60)), 3, "short"))
    assert eng.metrics.get("ring_prefills", 0) == 1
    assert len(toks2) == 3
    await eng.close()
