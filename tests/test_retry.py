"""Unified retry policy (runtime/retry.py): backoff shape, deadlines,
cancellation-awareness — the single source of retry semantics adopted by
migration, disagg pulls, KVBM remote pulls, and etcd lease ops."""

import asyncio
import random
import time

import pytest

from dynamo_tpu.runtime.cancellation import CancellationToken
from dynamo_tpu.runtime.retry import Backoff, RetryPolicy, call_with_retry


def test_raw_delay_is_capped_exponential():
    p = RetryPolicy(base_s=0.1, cap_s=1.0, multiplier=2.0, jitter=False)
    assert p.raw_delay(1) == pytest.approx(0.1)
    assert p.raw_delay(2) == pytest.approx(0.2)
    assert p.raw_delay(3) == pytest.approx(0.4)
    assert p.raw_delay(5) == pytest.approx(1.0)  # capped
    assert p.raw_delay(50) == pytest.approx(1.0)


def test_full_jitter_draws_within_envelope_and_is_seeded():
    p = RetryPolicy(base_s=0.1, cap_s=1.0)
    rng = random.Random(7)
    draws = [p.delay(n, rng) for n in range(1, 6)]
    for n, d in enumerate(draws, start=1):
        assert 0.0 <= d <= p.raw_delay(n)
    # seeded rng -> reproducible schedule (chaos runs depend on this)
    rng2 = random.Random(7)
    assert draws == [p.delay(n, rng2) for n in range(1, 6)]


async def test_call_with_retry_recovers_after_transient_failures():
    calls = []

    async def fn():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient")
        return "ok"

    t0 = time.monotonic()
    out = await call_with_retry(
        fn, RetryPolicy(max_attempts=5, base_s=0.001, cap_s=0.002))
    assert out == "ok"
    assert len(calls) == 3
    assert time.monotonic() - t0 < 1.0


async def test_call_with_retry_exhausts_attempts():
    calls = []

    async def fn():
        calls.append(1)
        raise ValueError("always")

    with pytest.raises(ValueError):
        await call_with_retry(
            fn, RetryPolicy(max_attempts=3, base_s=0.001, cap_s=0.002))
    assert len(calls) == 3  # max_attempts counts the first try


async def test_call_with_retry_respects_retry_on_filter():
    calls = []

    async def fn():
        calls.append(1)
        raise KeyError("not retryable")

    with pytest.raises(KeyError):
        await call_with_retry(
            fn, RetryPolicy(max_attempts=5, base_s=0.001),
            retry_on=(ValueError,))
    assert len(calls) == 1


async def test_call_with_retry_never_retries_cancellation():
    calls = []

    async def fn():
        calls.append(1)
        raise asyncio.CancelledError()

    with pytest.raises(asyncio.CancelledError):
        await call_with_retry(
            fn, RetryPolicy(max_attempts=5, base_s=0.001))
    assert len(calls) == 1


async def test_backoff_deadline_bounds_wall_clock():
    p = RetryPolicy(max_attempts=1 << 20, base_s=0.01, cap_s=0.02,
                    deadline_s=0.1)
    bo = Backoff(p)
    t0 = time.monotonic()
    n = 0
    while await bo.sleep():
        n += 1
        assert n < 1000, "deadline never tripped"
    assert time.monotonic() - t0 < 1.0
    assert n >= 1


async def test_backoff_stopped_token_aborts_sleep_immediately():
    p = RetryPolicy(max_attempts=10, base_s=5.0, cap_s=5.0, jitter=False)
    bo = Backoff(p)
    token = CancellationToken()
    token.stop()
    t0 = time.monotonic()
    assert await bo.sleep(token=token) is False
    assert time.monotonic() - t0 < 1.0
    token.detach()


async def test_backoff_token_stop_mid_sleep_wakes_early():
    p = RetryPolicy(max_attempts=10, base_s=5.0, cap_s=5.0, jitter=False)
    bo = Backoff(p)
    token = CancellationToken()

    async def stopper():
        await asyncio.sleep(0.05)
        token.stop()

    task = asyncio.create_task(stopper())
    t0 = time.monotonic()
    assert await bo.sleep(token=token) is False
    assert time.monotonic() - t0 < 2.0  # not the 5s backoff
    await task
    token.detach()


async def test_on_retry_sees_attempt_and_error():
    seen = []

    async def fn():
        raise RuntimeError("x")

    with pytest.raises(RuntimeError):
        await call_with_retry(
            fn, RetryPolicy(max_attempts=3, base_s=0.001),
            on_retry=lambda n, e: seen.append((n, str(e))))
    assert [n for n, _ in seen] == [1, 2, 3]
    assert all(m == "x" for _, m in seen)
