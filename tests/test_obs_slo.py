"""SLO plane (obs/slo.py): per-request latency histograms fed from
RequestTracker.finish, terminal-outcome accounting, goodput + burn-rate
windows, the chaos-injected breach path, the planner's SloObserver feed,
the scrape contract, and log<->trace correlation."""

import asyncio
import json
import logging
import time
import uuid

import aiohttp
import jax.numpy as jnp
import pytest

from dynamo_tpu import chaos, obs
from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.frontend.request_trace import RequestTracker
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.obs.slo import SloConfig, SloPlane
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig
from dynamo_tpu.runtime.metrics import MetricsHierarchy


def fresh_runtime() -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


async def start_stack(rt, model="slo-model", slo=None, **engine_kw):
    args = MockEngineArgs(model_name=model, block_size=4,
                          base_step_s=0.0005, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0, **engine_kw)
    worker = await MockerWorker(rt, args).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1", port=0,
                                slo=slo).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get(model):
            break
        await asyncio.sleep(0.02)
    return worker, watcher, service, port


async def chat(port, model, max_tokens=4, stream=False):
    async with aiohttp.ClientSession() as s:
        body = {"model": model,
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": max_tokens, "ignore_eos": True,
                "stream": stream}
        async with s.post(f"http://127.0.0.1:{port}/v1/chat/completions",
                          json=body) as r:
            return r.status, await r.read()


async def scrape(port):
    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://127.0.0.1:{port}/metrics") as r:
            return await r.text()


def metric_value(text, prefix, **labels):
    """Sum of samples whose line starts with `prefix` and contains all
    label pairs."""
    total, seen = 0.0, False
    for line in text.splitlines():
        if not line.startswith(prefix + "{"):
            continue
        if all(f'{k}="{v}"' in line for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
            seen = True
    return total if seen else None


# --------------------- unit: goodput / burn / outcomes ----------------------


def test_slo_plane_goodput_burn_and_outcome_labels():
    m = MetricsHierarchy(component="frontend")
    plane = SloPlane(m, SloConfig(ttft_ms=50.0, objective=0.99,
                                  windows_s=(60.0, 300.0)))

    def run(ttft_sleep_s=None, error=None):
        t = RequestTracker(request_id=uuid.uuid4().hex, model="m",
                           slo=plane)
        t.on_dispatch(1)
        if error is None:
            if ttft_sleep_s:
                time.sleep(ttft_sleep_s)
            t.on_tokens(2)
            t.finish(finish_reason="stop")
        else:
            t.finish(error=error)
        return t

    run()                       # fast: good
    run(ttft_sleep_s=0.08)      # ok but TTFT 80ms > 50ms: breach (ttft)
    run(error="connection lost (worker died)")  # no token at all
    plane.refresh()  # per-finish refreshes are throttled; scrapes force
    text = m.render().decode()
    # TTFT histogram saw ONLY the two token-producing requests
    assert metric_value(text, "dynamo_frontend_ttft_seconds_count",
                        model="m") == 2.0
    # e2e + finished count ALL three, split by outcome
    assert metric_value(text, "dynamo_frontend_e2e_seconds_count",
                        outcome="ok") == 2.0
    assert metric_value(text, "dynamo_frontend_e2e_seconds_count",
                        outcome="no_first_token") == 1.0
    assert metric_value(text, "dynamo_frontend_requests_finished_total",
                        outcome="no_first_token") == 1.0
    assert metric_value(text, "dynamo_frontend_slo_breach_total",
                        reason="ttft") == 1.0
    assert metric_value(text, "dynamo_frontend_slo_breach_total",
                        reason="no_first_token") == 1.0
    # goodput 1/3; burn = (2/3) / (1 - 0.99)
    assert plane.goodput() == pytest.approx(1 / 3)
    burns = plane.burn_rates()
    assert burns[60.0] == pytest.approx((2 / 3) / 0.01, rel=1e-6)
    assert burns[300.0] == burns[60.0]  # same requests in both windows
    for line in text.splitlines():
        if line.startswith("dynamo_frontend_slo_goodput{"):
            assert float(line.rsplit(" ", 1)[1]) == pytest.approx(1 / 3)
    # queue time was recorded from the first dispatch
    assert metric_value(text, "dynamo_frontend_queue_seconds_count",
                        model="m") == 3.0


def test_slo_plane_without_targets_is_histogram_only():
    m = MetricsHierarchy(component="frontend")
    plane = SloPlane(m, SloConfig())
    t = RequestTracker(request_id="r", model="m", slo=plane)
    t.on_tokens(1)
    rec = t.finish(finish_reason="stop")
    assert rec["request"]["outcome"] == "ok"
    text = m.render().decode()
    assert "dynamo_frontend_e2e_seconds_count" in text
    assert "dynamo_frontend_slo_goodput" not in text
    assert plane.goodput() is None


def test_tracker_record_outcome_and_queue_fields():
    t = RequestTracker(request_id="r", model="m")
    t.on_dispatch(7)
    rec = t.finish(error="worker draining")
    assert rec["request"]["outcome"] == "no_first_token"
    assert rec["request"]["queue_ms"] >= 0.0
    t2 = RequestTracker(request_id="r2", model="m")
    t2.on_dispatch(7)
    t2.on_tokens(3)
    rec2 = t2.finish(error="connection lost mid-stream")
    assert rec2["request"]["outcome"] == "error"
    t3 = RequestTracker(request_id="r3", model="m")
    rec3 = t3.finish(error="preprocessing failed")
    assert rec3["request"]["outcome"] == "no_first_token"
    assert "queue_ms" not in rec3["request"]  # never dispatched


def test_queue_time_ends_at_prefill_hop_not_decode_dispatch():
    """Disagg: the prefill hop is the FIRST worker dispatch — the
    pipeline marks it before maybe_prefill, so queue_ms must not absorb
    a slow remote prefill as phantom admission wait."""
    t = RequestTracker(request_id="r", model="m")
    t.mark_dispatching()   # pipeline: request leaves for the prefill hop
    time.sleep(0.05)       # the remote prefill runs...
    t.on_dispatch(3)       # ...then the decode dispatch happens
    t.on_tokens(1)
    rec = t.finish(finish_reason="stop")
    assert rec["request"]["queue_ms"] < 25.0  # excludes the 50ms prefill


def test_burn_rate_windows_age_out():
    m = MetricsHierarchy(component="frontend")
    plane = SloPlane(m, SloConfig(ttft_ms=50.0,
                                  windows_s=(0.05, 10.0)))
    plane._finished.append((time.monotonic(), False))  # one bad request
    assert plane.burn_rates()[0.05] > 0.0
    plane.refresh()
    assert metric_value(m.render().decode(),
                        "dynamo_frontend_slo_goodput") == 0.0
    # past the short window AND the window-scan cache TTL (0.2s)
    time.sleep(0.25)
    burns = plane.burn_rates()
    # aged out of the short window, still burning in the long one
    assert 0.05 not in burns
    assert burns[10.0] > 0.0
    # a refresh after aging must ROLL the gauges past the breach: the
    # empty short window reads no-breach, not the frozen last value
    plane.refresh()
    text = m.render().decode()
    assert metric_value(text, "dynamo_frontend_slo_goodput") == 1.0
    assert metric_value(text, "dynamo_frontend_slo_burn_rate",
                        window="0s") == 0.0  # int(0.05) == 0
    assert metric_value(text, "dynamo_frontend_slo_burn_rate",
                        window="10s") > 0.0


# --------------------- e2e: histograms + injected breach --------------------


# timing-sensitive: asserts a real 80ms TTFT target holds on the fast
# path — the slow-callback gate's debug-mode overhead breaches it flakily
@pytest.mark.allow_slow_callbacks
async def test_frontend_exports_slo_surface_and_chaos_breach():
    """The acceptance path: a CPU-only mocker+frontend run exports the
    TTFT/e2e/queue histograms and a goodput gauge that RESPONDS to an
    injected breach — chaos-delayed frames push goodput below 1.0."""
    rt = await fresh_runtime().start()
    worker, watcher, service, port = await start_stack(
        rt, slo=SloConfig(ttft_ms=80.0, publish_interval_s=0.1))
    try:
        status, _ = await chat(port, "slo-model")  # fast: good
        assert status == 200
        text = await scrape(port)
        assert metric_value(text, "dynamo_frontend_slo_goodput") == 1.0

        # delay every response frame well past the TTFT target
        plane = chaos.ChaosPlane(seed=5).rule(
            "request_plane.frame", "delay", delay_s=0.15, times=2)
        with plane:
            status, _ = await chat(port, "slo-model")
            assert status == 200
        assert plane.fired() >= 1
        text = await scrape(port)
        assert metric_value(text, "dynamo_frontend_ttft_seconds_count",
                            model="slo-model") == 2.0
        assert metric_value(text, "dynamo_frontend_e2e_seconds_count",
                            outcome="ok") == 2.0
        assert metric_value(text, "dynamo_frontend_queue_seconds_count",
                            model="slo-model") == 2.0
        goodput = metric_value(text, "dynamo_frontend_slo_goodput")
        assert goodput == pytest.approx(0.5)
        assert metric_value(text, "dynamo_frontend_slo_burn_rate",
                            window="60s") == pytest.approx(0.5 / 0.01)
        assert metric_value(text, "dynamo_frontend_slo_breach_total",
                            reason="ttft") == 1.0

        # ...and the planner-facing feed carries the same breach
        from dynamo_tpu.planner.metrics import SloObserver

        slo_obs = await SloObserver(rt, "dynamo").start()
        agg = None
        for _ in range(40):
            await asyncio.sleep(0.05)
            agg = slo_obs.aggregate()
            if agg is not None:
                break
        assert agg is not None and agg["goodput"] == pytest.approx(0.5)
        assert agg["max_burn"] == pytest.approx(50.0, rel=0.01)
        await slo_obs.close()
    finally:
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()


async def test_dispatch_fail_counts_without_polluting_ttft(tmp_path,
                                                           monkeypatch):
    """The chaos dispatch-fail seam: a request that never produces a
    first token (migration budget 0) must land in the e2e/goodput
    denominators under outcome=no_first_token while the TTFT histogram
    stays empty — and its request_end record says why."""
    trace_file = tmp_path / "rt.jsonl"
    monkeypatch.setenv("DYN_REQUEST_TRACE", "1")
    monkeypatch.setenv("DYN_REQUEST_TRACE_FILE_PATH", str(trace_file))
    rt = await fresh_runtime().start()
    worker, watcher, service, port = await start_stack(
        rt, model="df-model", slo=SloConfig(ttft_ms=1000.0))
    try:
        plane = chaos.ChaosPlane(seed=9).rule(
            "request_plane.dispatch", "fail", times=1,
            error="connection lost (chaos: dispatch)")
        with plane:
            status, _ = await chat(port, "df-model")
        assert status == 500 and plane.fired() == 1
        text = await scrape(port)
        assert metric_value(text, "dynamo_frontend_ttft_seconds_count",
                            model="df-model") is None  # no sample at all
        assert metric_value(text, "dynamo_frontend_e2e_seconds_count",
                            outcome="no_first_token") == 1.0
        assert metric_value(text, "dynamo_frontend_slo_goodput") == 0.0
        rec = json.loads(trace_file.read_text().strip().splitlines()[-1])
        assert rec["request"]["outcome"] == "no_first_token"
        assert "connection lost" in rec["request"]["error"]
    finally:
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()


# --------------------- scrape contract --------------------------------------


def _assert_scrape_contract(text: str) -> int:
    """Every exported family parses and is dynamo_-prefixed — the
    lint-style gate that fails on any future unprefixed metric."""
    from prometheus_client.parser import text_string_to_metric_families

    families = list(text_string_to_metric_families(text))
    assert families, "empty scrape"
    bad = [f.name for f in families if not f.name.startswith("dynamo_")]
    assert not bad, f"unprefixed metric families exported: {bad}"
    return len(families)


async def test_scrape_contract_frontend_and_mocker():
    rt = await fresh_runtime().start()
    worker, watcher, service, port = await start_stack(
        rt, model="scrape-model", slo=SloConfig(ttft_ms=1000.0),
        peak_tflops=50.0, peak_hbm_gbps=100.0)
    try:
        await chat(port, "scrape-model")
        await asyncio.sleep(0.4)  # a mocker load-loop tick
        text = await scrape(port)
        n = _assert_scrape_contract(text)
        assert n > 10  # frontend + worker families on one registry
    finally:
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()


# real JAX engine in an async body: -O0 compiles dwarf the 200ms
# loop gate (see conftest); mocker-based tests here stay gated
@pytest.mark.allow_slow_callbacks
async def test_scrape_contract_jax_worker():
    """The JAX engine worker's /metrics surface (engine gauges, compile
    histogram, occupancy, FPM aggregates) honors the same contract."""
    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.engine.worker import JaxEngineWorker
    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    tiny = LlamaConfig(name="tiny32", vocab_size=256, d_model=64,
                       n_layers=2, n_heads=4, n_kv_heads=2, head_dim=16,
                       ffn_dim=128, dtype=jnp.float32)
    rt = await fresh_runtime().start()
    worker = await JaxEngineWorker(rt, EngineConfig(
        model_config=tiny, block_size=4, num_blocks=64,
        max_blocks_per_seq=16, max_num_seqs=2, peak_tflops=100.0,
        peak_hbm_gbps=100.0, prefill_buckets=(8, 16, 32), seed=7,
    )).start()
    client = await (rt.namespace("dynamo").component("backend")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    try:
        req = PreprocessedRequest(
            token_ids=list(range(3, 25)), request_id="r1",
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=4, ignore_eos=True))
        async for _ in client.generate(req.to_dict()):
            pass
        text = ""
        for _ in range(40):  # wait out a 0.5s load-loop tick
            await asyncio.sleep(0.1)
            text = rt.metrics.render().decode()
            if "dynamo_engine_compile_seconds" in text:
                break
        _assert_scrape_contract(text)
        # the new device-performance families are on the surface
        assert 'dynamo_engine_compile_seconds_count{' in text
        assert 'family="prefill_packed"' in text
        assert 'dynamo_engine_kv_blocks_used{' in text
        assert 'tier="g1"' in text
    finally:
        await client.close()
        await worker.close()
        await rt.shutdown()


# --------------------- log<->trace correlation ------------------------------


async def test_log_lines_join_spans_and_record_on_trace_id(tmp_path,
                                                           monkeypatch):
    """With tracing on, a request's frontend+worker log records carry
    the same trace_id as its spans and its request_end record — the
    three observability surfaces join on one key."""
    from dynamo_tpu.runtime.logging import TraceIdFilter

    trace_file = tmp_path / "rt.jsonl"
    monkeypatch.setenv("DYN_REQUEST_TRACE", "1")
    monkeypatch.setenv("DYN_REQUEST_TRACE_FILE_PATH", str(trace_file))

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    cap = Capture()
    cap.addFilter(TraceIdFilter())
    logging.getLogger().addHandler(cap)
    wlog = logging.getLogger("dynamo_tpu.mocker.worker")
    old_level = wlog.level
    wlog.setLevel(logging.INFO)  # pytest's root default is WARNING
    tr = obs.Tracer().install()
    rt = await fresh_runtime().start()
    worker, watcher, service, port = await start_stack(rt,
                                                       model="join-model")
    try:
        status, _ = await chat(port, "join-model")
        assert status == 200
        rec = json.loads(trace_file.read_text().strip().splitlines()[-1])
        tid = rec["trace"]["trace_id"]
        served = [r for r in records
                  if r.getMessage() == "request served"]
        assert served, "worker served-log line missing"
        assert getattr(served[-1], "trace_id", None) == tid
        # the worker span shares the id too (PR 6 contract still holds)
        wrk = next(s for s in tr.spans if s[0] == "worker_request")
        assert wrk[5] == tid
    finally:
        logging.getLogger().removeHandler(cap)
        wlog.setLevel(old_level)
        tr.uninstall()
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()


def test_trace_id_filter_respects_explicit_extra():
    from dynamo_tpu.runtime.logging import TraceIdFilter

    f = TraceIdFilter()
    rec = logging.LogRecord("x", logging.INFO, "f.py", 1, "m", (), None)
    tok = obs.bind_trace_id("a" * 32)
    try:
        assert f.filter(rec) and rec.trace_id == "a" * 32
        rec2 = logging.LogRecord("x", logging.INFO, "f.py", 1, "m", (),
                                 None)
        rec2.trace_id = "explicit"
        f.filter(rec2)
        assert rec2.trace_id == "explicit"  # extra= wins over context
    finally:
        obs.unbind_trace_id(tok)
    rec3 = logging.LogRecord("x", logging.INFO, "f.py", 1, "m", (), None)
    f.filter(rec3)
    assert not hasattr(rec3, "trace_id")  # nothing bound: no stamp


# --------------------- planner SloObserver ----------------------------------


async def test_slo_observer_aggregates_and_expires():
    from dynamo_tpu.planner.metrics import SloObserver

    rt = await fresh_runtime().start()
    slo_obs = await SloObserver(rt, "dynamo", stale_after_s=0.3).start()
    try:
        agg = None
        for _ in range(40):
            # republish until the subscription is attached and both
            # samples landed (subscribe() attaches asynchronously)
            await rt.event_plane.publish("slo_metrics.dynamo", {
                "frontend_id": 1, "goodput": 0.9,
                "burn": {"60s": 10.0, "300s": 2.0}, "requests": 30})
            await rt.event_plane.publish("slo_metrics.dynamo", {
                "frontend_id": 2, "goodput": 0.5,
                "burn": {"60s": 50.0}, "requests": 10})
            await asyncio.sleep(0.02)
            agg = slo_obs.aggregate()
            if agg is not None and agg["frontends"] == 2:
                break
        assert agg["frontends"] == 2 and agg["requests"] == 40
        # request-weighted: (0.9*30 + 0.5*10) / 40
        assert agg["goodput"] == pytest.approx(0.8)
        assert agg["max_burn"] == 50.0
        await asyncio.sleep(0.4)
        assert slo_obs.aggregate() is None  # stale frontends expire
    finally:
        await slo_obs.close()
        await rt.shutdown()
