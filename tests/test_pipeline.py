"""Pipeline parallelism: rotating-schedule correctness on the virtual mesh.

The property under test: pipeline_apply(stage_fn over S sharded stages)
produces exactly the sequential composition stage_{S-1} ∘ ... ∘ stage_0,
and per-stage state updated during bubbles is untouched."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from dynamo_tpu.parallel.pipeline import pipeline_apply


def pp_mesh(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(devs[:n]), axis_names=("pp",))


def mlp_stage(params, state, x, active):
    """Two-matmul stage; counts the tokens it actually processed (state
    writes masked during bubbles)."""
    y = jnp.tanh(x @ params["w1"]) @ params["w2"] + x
    count = state["count"] + jnp.where(active, x.shape[0], 0)
    return y, {"count": count}


def make_stages(key, S, d, hidden):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (S, d, hidden), jnp.float32) * 0.3,
        "w2": jax.random.normal(k2, (S, hidden, d), jnp.float32) * 0.3,
    }


def sequential(params, xs):
    S = params["w1"].shape[0]
    out = []
    for m in range(xs.shape[0]):
        x = xs[m]
        for s in range(S):
            sl = {"w1": params["w1"][s], "w2": params["w2"][s]}
            x, _ = mlp_stage(sl, {"count": jnp.int32(0)}, x, True)
        out.append(x)
    return jnp.stack(out)


@pytest.mark.parametrize("M", [4, 7, 2])  # M == S, M > S, M < S
def test_pipeline_matches_sequential(M):
    S, d, hidden, mb = 4, 16, 32, 3
    mesh = pp_mesh(S)
    params = make_stages(jax.random.PRNGKey(0), S, d, hidden)
    xs = jax.random.normal(jax.random.PRNGKey(1), (M, mb, d), jnp.float32)
    state = {"count": jnp.zeros((S,), jnp.int32)}

    ys, new_state = pipeline_apply(mlp_stage, params, state, xs, mesh)
    ref = sequential(params, xs)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # every stage processed exactly M microbatches of mb tokens — bubbles
    # must not have leaked into the state
    np.testing.assert_array_equal(np.asarray(new_state["count"]),
                                  np.full(S, M * mb))


def test_pipeline_under_jit():
    S, d, hidden = 4, 8, 16
    mesh = pp_mesh(S)
    params = make_stages(jax.random.PRNGKey(2), S, d, hidden)
    xs = jax.random.normal(jax.random.PRNGKey(3), (4, 2, d), jnp.float32)
    state = {"count": jnp.zeros((S,), jnp.int32)}
    fn = jax.jit(lambda p, s, x: pipeline_apply(mlp_stage, p, s, x, mesh))
    ys, _ = fn(params, state, xs)
    np.testing.assert_allclose(np.asarray(ys),
                               np.asarray(sequential(params, xs)),
                               rtol=2e-5, atol=2e-5)
