"""Planner: predictor units, control-loop reconcile logic, and the e2e
scale-up/scale-down cycle against a live mocker fleet.

Mirrors the reference's planner test shape (planner-design.md: the loop is
testable tick-by-tick; connectors absorb the execution substrate)."""

import asyncio
import uuid

from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.planner import (
    CallbackConnector,
    Planner,
    PlannerConfig,
    make_predictor,
)
from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig


def fresh_runtime():
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


# ----------------------------- predictors --------------------------------


def test_predictors():
    c = make_predictor("constant")
    for v in (1.0, 5.0, 3.0):
        c.observe(v)
    assert c.predict() == 3.0

    e = make_predictor("ema", window=3)
    for v in (0.0, 0.0, 8.0):
        e.observe(v)
    assert 0.0 < e.predict() < 8.0  # smoothed, lags the spike

    lin = make_predictor("linear", window=4)
    for v in (1.0, 2.0, 3.0, 4.0):
        lin.observe(v)
    assert lin.predict() > 4.0  # extrapolates the ramp
    lin2 = make_predictor("linear")
    lin2.observe(5.0)
    assert lin2.predict() == 5.0  # single sample: constant

    try:
        make_predictor("prophet")
        raise AssertionError("unknown predictor must raise")
    except ValueError:
        pass


# ----------------------------- reconcile ---------------------------------


class _FakeConnector:
    def __init__(self, replicas=1):
        self.replicas = replicas
        self.calls = []

    async def current_replicas(self):
        return self.replicas

    async def scale(self, n):
        self.calls.append(n)
        self.replicas = n
        return n


class _FakeObserver:
    def __init__(self):
        self.load = None

    async def start(self):
        return self

    async def close(self):
        pass

    def aggregate(self):
        return self.load


def _bare_planner(cfg, connector):
    p = Planner.__new__(Planner)
    p.config = cfg
    p.connector = connector
    p.observer = _FakeObserver()
    p.predictor = make_predictor("constant")
    p._task = None
    p._last_action_t = 0.0
    p._low_ticks = 0
    p.decisions = []
    return p


async def test_reconcile_bounds_cooldown_and_down_hysteresis():
    from dynamo_tpu.planner.metrics import AggregateLoad

    cfg = PlannerConfig(min_replicas=1, max_replicas=4,
                        target_active_per_replica=2.0, cooldown_s=0.0,
                        max_step=2, down_stable_ticks=2)
    conn = _FakeConnector(replicas=1)
    p = _bare_planner(cfg, conn)

    # spike to 12 active: proposed 6 -> clamped to max 4, step clamp 2/tick
    p.observer.load = AggregateLoad(workers=1, active_seqs=12,
                                    mean_kv_usage=0.2)
    assert await p.tick() == 3
    assert await p.tick() == 4
    assert await p.tick() is None  # at max, no action

    # load vanishes: down needs down_stable_ticks consecutive low ticks
    p.observer.load = AggregateLoad(workers=4, active_seqs=0,
                                    mean_kv_usage=0.0)
    p.predictor = make_predictor("constant")  # forget the spike
    assert await p.tick() is None   # low tick 1: hold
    assert await p.tick() == 2      # low tick 2: scale down (step clamp)
    assert await p.tick() is None   # hysteresis resets per action
    assert await p.tick() == 1
    assert conn.calls == [3, 4, 2, 1]


async def test_kv_pressure_forces_scale_up():
    from dynamo_tpu.planner.metrics import AggregateLoad

    cfg = PlannerConfig(min_replicas=1, max_replicas=4,
                        target_active_per_replica=4.0, cooldown_s=0.0,
                        kv_pressure_threshold=0.8)
    conn = _FakeConnector(replicas=1)
    p = _bare_planner(cfg, conn)
    # few actives but cache nearly full: parked sequences need room
    p.observer.load = AggregateLoad(workers=1, active_seqs=2,
                                    mean_kv_usage=0.92)
    assert await p.tick() == 2


async def test_telemetry_loss_holds_instead_of_scaling_down():
    """Zero samples with live replicas is lost telemetry, not zero load."""
    from dynamo_tpu.planner.metrics import AggregateLoad

    cfg = PlannerConfig(min_replicas=1, max_replicas=4, cooldown_s=0.0,
                        down_stable_ticks=1)
    conn = _FakeConnector(replicas=3)
    p = _bare_planner(cfg, conn)
    p.observer.load = AggregateLoad()  # no workers reporting
    for _ in range(5):
        assert await p.tick() is None
    assert conn.calls == []


async def test_scale_to_zero_allowed_when_configured():
    from dynamo_tpu.planner.metrics import AggregateLoad

    cfg = PlannerConfig(min_replicas=0, max_replicas=4, cooldown_s=0.0,
                        down_stable_ticks=1, max_step=4)
    conn = _FakeConnector(replicas=2)
    p = _bare_planner(cfg, conn)
    p.observer.load = AggregateLoad(workers=2, active_seqs=0,
                                    mean_kv_usage=0.0)
    assert await p.tick() == 0


async def test_observer_ignores_sibling_component_subjects():
    """Prefix-matched subscription must not leak backend2 into backend."""
    from dynamo_tpu.planner import LoadObserver

    rt = await fresh_runtime().start()
    obs = await LoadObserver(rt, "dynamo", "backend").start()
    for _ in range(100):
        await rt.event_plane.publish(
            "load_metrics.dynamo.backend2",
            {"worker_id": 99, "active_seqs": 50, "kv_usage": 0.5},
        )
        await rt.event_plane.publish(
            "load_metrics.dynamo.backend",
            {"worker_id": 1, "active_seqs": 2, "kv_usage": 0.1},
        )
        await asyncio.sleep(0.01)
        if obs.aggregate().workers:
            break
    agg = obs.aggregate()
    assert agg.workers == 1 and agg.active_seqs == 2
    await obs.close()
    await rt.shutdown()


# ------------------------------- e2e -------------------------------------


async def test_planner_scales_mocker_fleet_up_and_down():
    """Load spike on a live mocker fleet scales replicas up; drain scales
    them back down to min (the VirtualConnector e2e from the verdict)."""
    rt = await fresh_runtime().start()
    args = MockEngineArgs(model_name="m", block_size=4, base_step_s=0.02,
                          prefill_s_per_token=0.0, decode_s_per_seq=0.0)

    async def spawn():
        return await MockerWorker(rt, args).start()

    async def stop(w):
        await w.close()

    conn = CallbackConnector(spawn, stop)
    await conn.scale(1)
    planner = Planner(
        rt, "dynamo", "mocker", conn,
        PlannerConfig(min_replicas=1, max_replicas=3, cooldown_s=0.0,
                      target_active_per_replica=2.0, max_step=4,
                      down_stable_ticks=2, predictor="constant"),
    )
    await planner.observer.start()  # no background loop: manual ticks

    client = await (rt.namespace("dynamo").component("mocker")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()

    async def run_one(i):
        req = PreprocessedRequest(
            token_ids=list(range(i * 50, i * 50 + 16)),
            request_id=f"load{i}",
            stop=StopConditions(max_tokens=120, ignore_eos=True),
        )
        async for _ in client.generate(req.to_dict()):
            pass

    jobs = [asyncio.create_task(run_one(i)) for i in range(6)]
    # wait for the load signal (mocker publishes every 0.5s)
    for _ in range(100):
        await asyncio.sleep(0.05)
        if planner.observer.aggregate().active_seqs >= 5:
            break
    assert planner.observer.aggregate().active_seqs >= 5
    applied = await planner.tick()
    assert applied == 3, f"expected scale to max under load, got {applied}"

    await asyncio.gather(*jobs)
    # drain: metrics must observe idle workers before down-ticks count
    for _ in range(100):
        await asyncio.sleep(0.05)
        agg = planner.observer.aggregate()
        if agg.active_seqs == 0 and agg.workers >= 2:
            break
    planner.predictor = make_predictor("constant")
    assert await planner.tick() is None  # hysteresis tick 1
    assert await planner.tick() == 1     # back to min
    assert len(conn.handles) == 1

    await planner.close()
    await client.close()
    await conn.close()
    await rt.shutdown()
