"""Frontend e2e: OpenAI HTTP ↔ mocker workers over the real request plane.

Model: the reference's tests/router/test_router_e2e_with_mockers.py shape —
full pipeline, no accelerator.
"""

import asyncio
import json
import uuid

import aiohttp

from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.runtime import DistributedRuntime, RouterMode, RuntimeConfig


def fresh_runtime() -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


async def start_stack(n_workers=1, model_name="test-model", **engine_kw):
    rt = await fresh_runtime().start()
    args = MockEngineArgs(model_name=model_name, block_size=4,
                          base_step_s=0.0005, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0, **engine_kw)
    workers = []
    for _ in range(n_workers):
        workers.append(await MockerWorker(rt, args).start())
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1", port=0).start()
    port = service._runner.addresses[0][1]
    # wait for the watcher to pick the model up
    for _ in range(100):
        if manager.get(model_name):
            break
        await asyncio.sleep(0.02)
    assert manager.get(model_name) is not None
    return rt, workers, watcher, service, f"http://127.0.0.1:{port}"


async def stop_stack(rt, workers, watcher, service):
    await service.close()
    await watcher.close()
    for w in workers:
        await w.close()
    await rt.shutdown()


async def test_models_and_chat_completion():
    rt, workers, watcher, service, url = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{url}/v1/models") as r:
                data = await r.json()
                assert [m["id"] for m in data["data"]] == ["test-model"]

            body = {
                "model": "test-model",
                "messages": [{"role": "user", "content": "hello there"}],
                "max_tokens": 8,
                "ignore_eos": True,
            }
            async with s.post(f"{url}/v1/chat/completions", json=body) as r:
                assert r.status == 200
                data = await r.json()
                assert data["object"] == "chat.completion"
                assert data["usage"]["completion_tokens"] == 8
                assert data["choices"][0]["message"]["content"]
                assert data["choices"][0]["finish_reason"] == "length"
    finally:
        await stop_stack(rt, workers, watcher, service)


async def test_chat_streaming_sse():
    rt, workers, watcher, service, url = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": "test-model",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 5,
                "stream": True,
                "ignore_eos": True,
            }
            chunks = []
            async with s.post(f"{url}/v1/chat/completions", json=body) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/event-stream")
                async for line in r.content:
                    line = line.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    payload = line[len("data: "):]
                    if payload == "[DONE]":
                        chunks.append("DONE")
                        break
                    chunks.append(json.loads(payload))
            assert chunks[-1] == "DONE"
            deltas = [c for c in chunks if c != "DONE"]
            assert deltas[0]["choices"][0]["delta"].get("role") == "assistant"
            assert deltas[-1]["choices"][0]["finish_reason"] == "length"
            assert any(c["choices"][0]["delta"].get("content") for c in deltas)
    finally:
        await stop_stack(rt, workers, watcher, service)


async def test_completions_endpoint():
    rt, workers, watcher, service, url = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "test-model", "prompt": "once upon",
                    "max_tokens": 4, "ignore_eos": True}
            async with s.post(f"{url}/v1/completions", json=body) as r:
                data = await r.json()
                assert r.status == 200
                assert data["object"] == "text_completion"
                assert data["usage"]["completion_tokens"] == 4
    finally:
        await stop_stack(rt, workers, watcher, service)


async def test_error_paths():
    rt, workers, watcher, service, url = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{url}/v1/chat/completions",
                              json={"model": "nope", "messages": []}) as r:
                assert r.status == 404
            async with s.post(f"{url}/v1/chat/completions",
                              data=b"not json") as r:
                assert r.status == 400
            async with s.post(f"{url}/v1/chat/completions",
                              json={"model": "test-model",
                                    "messages": "bad"}) as r:
                assert r.status == 400
    finally:
        await stop_stack(rt, workers, watcher, service)


async def test_worker_error_surfaces_as_http_error_not_completion():
    """An engine-side failure (finish_reason='error') must NOT render as a
    successful OpenAI response: non-streaming gets a 5xx, streaming gets an
    SSE error event (round-1 verdict weak #6)."""
    rt = await fresh_runtime().start()
    comp = rt.namespace("dynamo").component("mocker")

    from dynamo_tpu.protocols import LLMEngineOutput, ModelDeploymentCard
    from dynamo_tpu.protocols.model_card import register_model

    async def broken_handler(payload, ctx):
        yield LLMEngineOutput(
            finish_reason="error",
            error="worker engine error: HBM OOM during prefill",
        ).to_dict()

    await comp.endpoint("generate").serve_endpoint(broken_handler,
                                                   instance_id=1)
    await register_model(rt, ModelDeploymentCard(
        name="broken", component="mocker", migration_limit=0))

    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1", port=0).start()
    port = service._runner.addresses[0][1]
    url = f"http://127.0.0.1:{port}"
    for _ in range(100):
        if manager.get("broken"):
            break
        await asyncio.sleep(0.02)
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "broken",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4}
            async with s.post(f"{url}/v1/chat/completions", json=body) as r:
                assert r.status == 500
                data = await r.json()
                assert data["error"]["type"] == "server_error"
                assert "HBM OOM" in data["error"]["message"]

            body["stream"] = True
            saw_error = saw_done = False
            async with s.post(f"{url}/v1/chat/completions", json=body) as r:
                async for line in r.content:
                    line = line.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    payload = line[len("data: "):]
                    if payload == "[DONE]":
                        saw_done = True
                        break
                    d = json.loads(payload)
                    if "error" in d:
                        saw_error = True
                    else:
                        assert d["choices"][0].get("finish_reason") != "error"
            assert saw_error, "stream must carry an SSE error event"
            # an errored stream terminates without [DONE] (OpenAI semantics:
            # the error event is terminal)
            assert not saw_done
    finally:
        await service.close()
        await watcher.close()
        await rt.shutdown()


async def test_migration_on_worker_failure():
    """A flaky worker dies mid-stream; migration replays onto a healthy one."""
    rt = await fresh_runtime().start()
    ns = rt.namespace("dynamo")
    comp = ns.component("mocker")

    from dynamo_tpu.protocols import (LLMEngineOutput, ModelDeploymentCard,
                                      PreprocessedRequest)
    from dynamo_tpu.protocols.model_card import register_model

    async def flaky_handler(payload, ctx):
        yield LLMEngineOutput(token_ids=[101]).to_dict()
        yield LLMEngineOutput(token_ids=[102]).to_dict()
        raise RuntimeError("connection lost (worker died)")

    async def healthy_handler(payload, ctx):
        req = PreprocessedRequest.from_dict(payload)
        # replayed prompt must include the two already-emitted tokens
        assert req.token_ids[-2:] == [101, 102]
        for t in range(req.stop.max_tokens - 1):
            yield LLMEngineOutput(token_ids=[200 + t]).to_dict()
        yield LLMEngineOutput(token_ids=[299],
                              finish_reason="length").to_dict()

    await comp.endpoint("generate").serve_endpoint(flaky_handler, instance_id=1)

    rt2 = DistributedRuntime(config=rt.config, cluster_id=rt.cluster_id)
    await rt2.start()
    await rt2.namespace("dynamo").component("mocker").endpoint(
        "generate").serve_endpoint(healthy_handler, instance_id=2)

    card = ModelDeploymentCard(name="m", component="mocker",
                               migration_limit=3)
    await register_model(rt, card)

    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    for _ in range(100):
        if manager.get("m"):
            break
        await asyncio.sleep(0.02)
    pipeline = manager.get("m")
    client = pipeline.client
    await client.wait_for_instances()
    for _ in range(100):
        if len(client.instances) == 2:
            break
        await asyncio.sleep(0.02)

    from dynamo_tpu.protocols import StopConditions

    req = PreprocessedRequest(token_ids=[1, 2, 3], request_id="mig-1",
                              stop=StopConditions(max_tokens=6,
                                                  ignore_eos=True))
    # force first attempt onto the flaky worker via a route hook
    attempts = []

    async def route(r, avoid=()):
        choice = 1 if 1 not in avoid else 2
        attempts.append(choice)
        return choice

    pipeline.migration.route = route
    tokens = []
    async for out in pipeline.migration.generate(req):
        tokens.extend(out.token_ids)
    # 2 tokens from flaky + 4 remaining from healthy (6 total budget)
    assert attempts == [1, 2]
    assert tokens[:2] == [101, 102]
    assert len(tokens) == 6

    await watcher.close()
    await rt2.shutdown()
    await rt.shutdown()
