"""Ring attention vs the single-device oracle on the 8-virtual-CPU mesh.

The property under test is EXACTNESS: sequence-parallel ring attention is
plain attention computed in a different order, so outputs must match the
global reference to accumulation tolerance — causal and full, MHA and GQA,
and composed with tp on a (tp, sp) mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.ops.ring_attention import attention_reference, ring_attention


def sp_mesh(n=8):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(devs[:n]), axis_names=("sp",))


def rand_qkv(key, b, t, hq, hkv, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, hq, d), dtype)
    k = jax.random.normal(kk, (b, t, hkv, d), dtype)
    v = jax.random.normal(kv, (b, t, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hq,hkv", [(4, 4), (8, 2)])  # MHA and GQA
def test_ring_matches_reference(causal, hq, hkv):
    mesh = sp_mesh()
    q, k, v = rand_qkv(jax.random.PRNGKey(0), 2, 64, hq, hkv, 16)
    out = ring_attention(q, k, v, mesh, axis_name="sp", causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_bf16_stable():
    mesh = sp_mesh()
    q, k, v = rand_qkv(jax.random.PRNGKey(1), 1, 32, 4, 4, 16,
                       dtype=jnp.bfloat16)
    out = ring_attention(q, k, v, mesh)
    ref = attention_reference(q, k, v)
    assert out.dtype == jnp.bfloat16
    assert not np.isnan(np.asarray(out, np.float32)).any()
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=0.05, atol=0.05)


def test_ring_under_jit_with_sharded_inputs():
    """jit(ring_attention) with inputs actually laid out on the sp axis —
    the long-context prefill usage pattern."""
    mesh = sp_mesh()
    q, k, v = rand_qkv(jax.random.PRNGKey(2), 1, 128, 4, 2, 16)
    shd = NamedSharding(mesh, P(None, "sp", None, None))
    q, k, v = (jax.device_put(x, shd) for x in (q, k, v))
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))
    out = fn(q, k, v)
    assert out.sharding.spec == P(None, "sp", None, None)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_composes_with_tp_mesh():
    """(tp=2, sp=4): heads sharded over tp, sequence over sp — the combined
    long-context layout."""
    from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    mesh = make_mesh(MeshConfig(dp=1, tp=2, sp=4))
    q, k, v = rand_qkv(jax.random.PRNGKey(3), 1, 64, 8, 4, 16)
    shd = NamedSharding(mesh, P(None, "sp", "tp", None))
    qs, ks, vs = (jax.device_put(x, shd) for x in (q, k, v))
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, axis_name="sp",
                                       head_axis="tp")
    )(qs, ks, vs)
    # heads stay tp-sharded (no all-gather + redundant per-head compute)
    assert out.sharding.spec == P(None, "sp", "tp", None)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
