"""LoRA serving: batched bank math vs dense-merge oracle, PEFT loading,
HRW routing (ref: lib/llm/src/lora/)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# real-JAX-engine tests: XLA compiles (seconds at tier-1's -O0) and
# device work run inside the async test bodies, so the conftest's 200ms
# event-loop slow-callback gate (DYN004's runtime twin) cannot hold
# here; mocker/frontend/router fleets keep it armed.
pytestmark = pytest.mark.allow_slow_callbacks


from dynamo_tpu.lora.bank import (
    bank_layer,
    clear_slot,
    empty_bank,
    lora_delta,
    write_adapter,
)
from dynamo_tpu.lora.routing import LoraReplicaSelector, rendezvous_ranking
from dynamo_tpu.lora.source import LocalLoraSource
from dynamo_tpu.models import llama
from dynamo_tpu.models.llama import LlamaConfig, init_params

CFG = LlamaConfig(name="tiny32", vocab_size=128, d_model=32, n_layers=2,
                  n_heads=4, n_kv_heads=2, head_dim=8, ffn_dim=64,
                  dtype=jnp.float32)
RANK = 4


def random_adapter_arrays(cfg, rank, seed):
    """Bank-layout tensors (A [L, d_in, r], B [L, r, d_out]) for all four
    attention targets."""
    rng = np.random.default_rng(seed)
    dims = {"q": (cfg.d_model, cfg.q_dim), "k": (cfg.d_model, cfg.kv_dim),
            "v": (cfg.d_model, cfg.kv_dim), "o": (cfg.q_dim, cfg.d_model)}
    out = {}
    for t, (d_in, d_out) in dims.items():
        out[f"A_{t}"] = rng.normal(
            0, 0.3, (cfg.n_layers, d_in, rank)).astype(np.float32)
        out[f"B_{t}"] = rng.normal(
            0, 0.3, (cfg.n_layers, rank, d_out)).astype(np.float32)
    return out


def merged_params(params, adapter):
    """Dense oracle: fold each layer's A@B into the base weights."""
    import copy

    p = copy.deepcopy(jax.tree.map(np.asarray, params))
    for li, layer in enumerate(p["layers"]):
        for t, w in (("q", "wq"), ("k", "wk"), ("v", "wv"), ("o", "wo")):
            layer[w] = layer[w] + adapter[f"A_{t}"][li] @ adapter[f"B_{t}"][li]
    return jax.tree.map(jnp.asarray, p)


def make_cache(cfg, num_blocks=16, block_size=4):
    k_shape, v_shape = llama.kv_cache_shapes(cfg, num_blocks, block_size)
    return (jnp.zeros(k_shape, cfg.dtype), jnp.zeros(v_shape, cfg.dtype))


# ------------------------- bank math vs oracle ------------------------------


def test_prefill_matches_dense_merge_oracle():
    params = init_params(CFG, jax.random.PRNGKey(0))
    adapter = random_adapter_arrays(CFG, RANK, seed=1)
    bank = empty_bank(CFG.n_layers, 3, RANK, CFG.d_model, CFG.q_dim,
                      CFG.kv_dim, dtype=jnp.float32)
    bank = write_adapter(bank, 1, adapter)

    toks = jnp.asarray(np.arange(8) % 50, jnp.int32)
    pos = jnp.arange(8, dtype=jnp.int32)
    table = jnp.arange(1, 3, dtype=jnp.int32)

    # adapter slot 1 == dense-merged weights
    logits_bank, _ = llama.prefill(
        params, CFG, make_cache(CFG), toks, pos, table,
        jnp.int32(0), jnp.int32(8), lora_bank=bank,
        adapter_idx=jnp.int32(1))
    logits_dense, _ = llama.prefill(
        merged_params(params, adapter), CFG, make_cache(CFG), toks, pos,
        table, jnp.int32(0), jnp.int32(8))
    np.testing.assert_allclose(np.asarray(logits_bank),
                               np.asarray(logits_dense), rtol=2e-4,
                               atol=2e-4)

    # adapter slot 0 (zeros) == base model
    logits_zero, _ = llama.prefill(
        params, CFG, make_cache(CFG), toks, pos, table,
        jnp.int32(0), jnp.int32(8), lora_bank=bank,
        adapter_idx=jnp.int32(0))
    logits_base, _ = llama.prefill(
        params, CFG, make_cache(CFG), toks, pos, table,
        jnp.int32(0), jnp.int32(8))
    np.testing.assert_allclose(np.asarray(logits_zero),
                               np.asarray(logits_base), rtol=1e-6)


def test_mixed_batch_decode_matches_per_adapter_runs():
    """One decode batch, three different adapters (incl. none): each lane
    must equal the same lane run alone with its adapter dense-merged."""
    params = init_params(CFG, jax.random.PRNGKey(0))
    ad1 = random_adapter_arrays(CFG, RANK, seed=1)
    ad2 = random_adapter_arrays(CFG, RANK, seed=2)
    bank = empty_bank(CFG.n_layers, 3, RANK, CFG.d_model, CFG.q_dim,
                      CFG.kv_dim, dtype=jnp.float32)
    bank = write_adapter(bank, 1, ad1)
    bank = write_adapter(bank, 2, ad2)

    B, bs = 3, 4
    toks = jnp.asarray([5, 9, 13], jnp.int32)
    positions = jnp.zeros(B, jnp.int32)
    tables = jnp.asarray([[1, 2], [3, 4], [5, 6]], jnp.int32)
    ctx = jnp.zeros(B, jnp.int32)
    idx = jnp.asarray([0, 1, 2], jnp.int32)

    logits_mix, _ = llama.decode(
        params, CFG, make_cache(CFG), toks, positions, tables, ctx,
        lora_bank=bank, adapter_idx=idx)

    for lane, adapter in ((0, None), (1, ad1), (2, ad2)):
        p = params if adapter is None else merged_params(params, adapter)
        lane_logits, _ = llama.decode(
            p, CFG, make_cache(CFG), toks[lane: lane + 1],
            positions[lane: lane + 1], tables[lane: lane + 1],
            ctx[lane: lane + 1])
        np.testing.assert_allclose(
            np.asarray(logits_mix[lane]), np.asarray(lane_logits[0]),
            rtol=2e-4, atol=2e-4)


def test_clear_slot_restores_base():
    params = init_params(CFG, jax.random.PRNGKey(0))
    adapter = random_adapter_arrays(CFG, RANK, seed=3)
    bank = empty_bank(CFG.n_layers, 2, RANK, CFG.d_model, CFG.q_dim,
                      CFG.kv_dim, dtype=jnp.float32)
    bank = clear_slot(write_adapter(bank, 1, adapter), 1)
    x = jnp.ones((2, CFG.d_model), jnp.float32)
    bl = bank_layer(bank, 0)
    d = lora_delta(x, bl["A_q"], bl["B_q"], jnp.asarray([1, 1], jnp.int32))
    assert float(jnp.abs(d).max()) == 0.0


# ------------------------- PEFT source loading ------------------------------


def write_peft_adapter(root, name, cfg, rank, alpha, seed, base="tiny32"):
    from safetensors.numpy import save_file

    rng = np.random.default_rng(seed)
    d = os.path.join(root, name)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "adapter_config.json"), "w") as f:
        json.dump({"r": rank, "lora_alpha": alpha,
                   "base_model_name_or_path": base,
                   "target_modules": ["q_proj", "k_proj", "v_proj",
                                      "o_proj"]}, f)
    tensors = {}
    dims = {"q": (cfg.d_model, cfg.q_dim), "k": (cfg.d_model, cfg.kv_dim),
            "v": (cfg.d_model, cfg.kv_dim), "o": (cfg.q_dim, cfg.d_model)}
    for li in range(cfg.n_layers):
        for t, (d_in, d_out) in dims.items():
            prefix = (f"base_model.model.model.layers.{li}."
                      f"self_attn.{t}_proj")
            tensors[f"{prefix}.lora_A.weight"] = rng.normal(
                0, 0.3, (rank, d_in)).astype(np.float32)
            tensors[f"{prefix}.lora_B.weight"] = rng.normal(
                0, 0.3, (d_out, rank)).astype(np.float32)
    save_file(tensors, os.path.join(d, "adapter_model.safetensors"))
    return tensors


def test_local_source_roundtrip(tmp_path):
    raw = write_peft_adapter(str(tmp_path), "my-adapter", CFG, rank=2,
                             alpha=4, seed=7)
    src = LocalLoraSource(str(tmp_path))
    assert src.list() == ["my-adapter"]
    ad = src.load("my-adapter", CFG.n_layers)
    assert ad.rank == 2 and ad.scaling == 2.0
    assert ad.base_model == "tiny32"
    # A transposed; B transposed with scaling folded
    a_key = "base_model.model.model.layers.0.self_attn.q_proj.lora_A.weight"
    b_key = "base_model.model.model.layers.0.self_attn.q_proj.lora_B.weight"
    np.testing.assert_allclose(ad.tensors["A_q"][0], raw[a_key].T)
    np.testing.assert_allclose(ad.tensors["B_q"][0], raw[b_key].T * 2.0,
                               rtol=1e-6)
    # rank padding
    padded = ad.padded_to(8)
    assert padded.tensors["A_q"].shape[-1] == 8
    np.testing.assert_allclose(padded.tensors["A_q"][..., :2],
                               ad.tensors["A_q"])
    assert float(np.abs(padded.tensors["A_q"][..., 2:]).max()) == 0.0
    with pytest.raises(ValueError):
        ad.padded_to(1)


# ------------------------- HRW routing --------------------------------------


def test_rendezvous_minimal_disruption():
    workers = [101, 202, 303, 404, 505]
    sel = LoraReplicaSelector(replica_factor=2)
    before = {f"ad{i}": sel.replica_set(f"ad{i}", workers)
              for i in range(40)}
    # deterministic
    assert before == {f"ad{i}": sel.replica_set(f"ad{i}", workers)
                      for i in range(40)}
    # removing one worker only remaps adapters that used it
    survivors = [w for w in workers if w != 303]
    moved = unchanged = 0
    for name, reps in before.items():
        after = sel.replica_set(name, survivors)
        if 303 in reps:
            assert 303 not in after
            moved += 1
        else:
            assert after == reps
            unchanged += 1
    assert moved > 0 and unchanged > 0


def test_filter_fallbacks():
    sel = LoraReplicaSelector(replica_factor=2)
    workers = [1, 2, 3, 4]
    # no lora -> whole fleet
    assert sel.filter(None, workers) == workers
    reps = sel.filter("ad", workers)
    assert len(reps) == 2 and set(reps) <= set(workers)
    # fleet smaller than replica factor -> everyone serves it
    assert sel.filter("ad", [7]) == [7]
    # entire replica set avoided -> fall back to the full fleet
    assert sel.filter("ad", workers, avoid=set(reps)) == workers
    # partial avoid -> surviving replica
    one = sel.filter("ad", workers, avoid={reps[0]})
    assert one == [reps[1]]


def test_ranking_is_total_order():
    r = rendezvous_ranking("a", [1, 2, 3])
    assert sorted(r) == [1, 2, 3]


# ------------------------- engine e2e ---------------------------------------


async def test_engine_serves_mixed_lora_batch(tmp_path):
    """Engine with a lazy-loading bank: base + two adapters concurrently,
    each stream matching a dedicated engine whose weights were
    dense-merged with that adapter."""
    import asyncio

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    write_peft_adapter(str(tmp_path), "ad1", CFG, rank=2, alpha=2, seed=11)
    write_peft_adapter(str(tmp_path), "ad2", CFG, rank=4, alpha=4, seed=22)
    params = init_params(CFG, jax.random.PRNGKey(3))

    def eng(p, **kw):
        return JaxEngine(EngineConfig(
            model_config=CFG, block_size=4, num_blocks=64,
            max_blocks_per_seq=16, max_num_seqs=4,
            prefill_buckets=(8, 16), decode_fused_steps=2,
            **kw), params=jax.tree.map(jnp.array, p))

    def req(rid, lora=None):
        return PreprocessedRequest(
            token_ids=[3, 14, 15, 9, 2, 6], request_id=rid,
            lora_name=lora,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=8, ignore_eos=True))

    async def collect(e, r):
        out = []
        async for item in e.generate(r):
            out.extend(item.token_ids)
        return out

    served = eng(params, lora_max_adapters=4, lora_rank=4,
                 lora_dir=str(tmp_path))
    try:
        base_t, ad1_t, ad2_t = await asyncio.gather(
            collect(served, req("r-base")),
            collect(served, req("r-ad1", "ad1")),
            collect(served, req("r-ad2", "ad2")))
        assert served._lora_slots.keys() == {"ad1", "ad2"}
    finally:
        await served.close()

    src = LocalLoraSource(str(tmp_path))
    for name, got in ((None, base_t), ("ad1", ad1_t), ("ad2", ad2_t)):
        if name is None:
            p = params
        else:
            ad = src.load(name, CFG.n_layers)
            full = {f"{k}": v for k, v in ad.tensors.items()}
            # source tensors may omit nothing here; merge directly
            p = merged_params(params, full)
        ref = eng(p)
        try:
            want = await collect(ref, req(f"ref-{name}"))
        finally:
            await ref.close()
        assert got == want, f"adapter {name}: {got} != {want}"


async def test_engine_rejects_unknown_adapter(tmp_path):
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.protocols import PreprocessedRequest, StopConditions

    e = JaxEngine(EngineConfig(
        model_config=CFG, block_size=4, num_blocks=32,
        max_blocks_per_seq=8, max_num_seqs=2, prefill_buckets=(8,),
        lora_max_adapters=2, lora_rank=4, lora_dir=str(tmp_path)))
    try:
        outs = []
        async for item in e.generate(PreprocessedRequest(
                token_ids=[1, 2, 3], request_id="r",
                lora_name="nope",
                stop=StopConditions(max_tokens=2))):
            outs.append(item)
        assert outs[-1].finish_reason == "error"
        assert "nope" in (outs[-1].error or "")
    finally:
        await e.close()


# ------------------------- frontend aliasing + router filter ----------------


async def test_frontend_adapter_alias_and_models_list(tmp_path, monkeypatch):
    """model=<adapter> resolves to the base pipeline with lora_name set;
    /v1/models lists adapters with their parent."""
    import asyncio
    import uuid

    import aiohttp

    from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    write_peft_adapter(str(tmp_path), "style-a", CFG, rank=2, alpha=2,
                       seed=5, base="alias-model")
    monkeypatch.setenv("DYN_LORA_PATH", str(tmp_path))

    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem", event_plane="inproc"),
        cluster_id=uuid.uuid4().hex).start()
    args = MockEngineArgs(model_name="alias-model", block_size=4,
                          base_step_s=0.0005, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0)
    worker = await MockerWorker(rt, args).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get("alias-model"):
            break
        await asyncio.sleep(0.02)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/v1/models") as r:
                ids = {m["id"]: m for m in (await r.json())["data"]}
            assert "alias-model" in ids and "style-a" in ids
            assert ids["style-a"]["parent"] == "alias-model"
            body = {"model": "style-a",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4, "ignore_eos": True}
            async with s.post(
                    f"http://127.0.0.1:{port}/v1/chat/completions",
                    json=body) as r:
                assert r.status == 200
                out = await r.json()
                assert out["model"] == "style-a"
    finally:
        await service.close()
        await watcher.close()
        await worker.close()
        await rt.shutdown()


async def test_kv_router_restricts_lora_to_replica_set():
    import asyncio
    import uuid

    from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
    from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
    from dynamo_tpu.router import KvRouter
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem", event_plane="inproc"),
        cluster_id=uuid.uuid4().hex).start()
    args = MockEngineArgs(model_name="m", block_size=4, base_step_s=0.0005)
    workers = [await MockerWorker(rt, args).start() for _ in range(4)]
    client = await (rt.namespace("dynamo").component("mocker")
                    .endpoint("generate").client()).start()
    await client.wait_for_instances()
    while len(client.instances) < 4:
        await asyncio.sleep(0.02)
    router = await KvRouter(rt, "dynamo", "mocker", client,
                            block_size=4).start()
    try:
        replicas = set(router.lora_selector.replica_set(
            "my-lora", client.instance_ids))
        assert len(replicas) == 2
        picks = set()
        for i in range(12):
            req = PreprocessedRequest(
                token_ids=list(range(8 + i)), request_id=f"r{i}",
                lora_name="my-lora", stop=StopConditions(max_tokens=4))
            choice = await router.pick(req)
            picks.add(choice)
            router.complete(req.request_id)
        assert picks <= replicas
    finally:
        await router.close()
        await client.close()
        for w in workers:
            await w.close()
        await rt.shutdown()
